"""Run the benchmark configs (BASELINE's six + framework extras); one JSON line each.

Usage: ``python benchmarks/run_all.py [config_numbers...]``
(no args = all). Runs on whatever backend jax selects (TPU when attached).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.configs import ALL_CONFIGS


def main():
    which = [int(a) for a in sys.argv[1:]] or sorted(ALL_CONFIGS)
    for i in which:
        try:
            res = ALL_CONFIGS[i]()
        except Exception as e:  # keep going; report the failure
            res = {"metric": f"config{i}", "error": f"{type(e).__name__}: {e}"}
        print(json.dumps(res))


if __name__ == "__main__":
    main()
