"""Decode throughput: KV-cached autoregressive generation tok/s.

The decode-as-first-class-workload row (the reference has no generation
at all — its models only score; SURVEY §5). One compiled scan per
config; the whole decode is a single dispatch, so link RTT amortizes
over every generated token.

    python benchmarks/decode_bench.py
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.configs import _sync


def _model(vocab=8192, d_model=512, n_heads=8, n_layers=8, max_len=512,
           n_kv_heads=None):
    from tensorframes_tpu.models import TransformerLM

    return TransformerLM.init(
        0, vocab, d_model=d_model, n_heads=n_heads, n_layers=n_layers,
        max_len=max_len, n_kv_heads=n_kv_heads,
    )


def bench_decode(mode="greedy", batch=8, prompt_len=32, new_tokens=256,
                 iters=3):
    """One decode mode's tok/s. Modes: greedy, sampled (temperature +
    top-k + nucleus), ragged (left-padded variable-length prompts)."""
    import jax

    from tensorframes_tpu.models import left_pad_prompts

    lm = _model(max_len=prompt_len + new_tokens + 1)
    rng = np.random.default_rng(0)
    kw = {}
    if mode == "ragged":
        seqs = [
            rng.integers(0, 8192, size=rng.integers(4, prompt_len + 1))
            .tolist()
            for _ in range(batch)
        ]
        prompt, lens = left_pad_prompts(seqs)
        kw["prompt_lengths"] = lens
    else:
        prompt = rng.integers(0, 8192, size=(batch, prompt_len)).astype(
            np.int32
        )
    if mode == "sampled":
        kw.update(temperature=0.8, seed=1, top_k=50, top_p=0.95)

    lm.generate(prompt, new_tokens, **kw)  # compile + weights upload
    t0 = time.perf_counter()
    for i in range(iters):
        if mode == "sampled":
            kw["seed"] = i  # traced arg: same program, no recompile
        out = lm.generate(prompt, new_tokens, **kw)
    dt = (time.perf_counter() - t0) / iters
    n_params = sum(
        int(np.prod(np.shape(v)))
        for v in jax.tree_util.tree_leaves(
            {k: v for k, v in lm.params.items() if k != "n_heads"}
        )
    )
    return {
        "metric": f"decode_{mode}_tok_per_sec",
        "value": round(batch * new_tokens / dt, 1),
        "unit": "tok/s",
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "params_m": round(n_params / 1e6, 1),
        "seconds_per_decode": round(dt, 4),
        "per_sequence_tok_per_sec": round(new_tokens / dt, 1),
        "note": "one compiled scan per decode (single dispatch; RTT "
        "amortizes over all generated tokens); compiled program reused "
        "across iters" + (
            " and across seeds (traced)" if mode == "sampled" else ""
        ),
    }


def bench_gqa(batch=16, prompt_len=32, new_tokens=1024, iters=3):
    """Long-context decode, MHA vs grouped-query (n_kv_heads=2): the KV
    cache — the decode memory ceiling and the per-step read — shrinks by
    the group factor (4x here), which is GQA's practical win."""
    import jax

    rows = []
    for label, kv in (("mha", None), ("gqa4", 2)):
        lm = _model(max_len=prompt_len + new_tokens + 1, n_kv_heads=kv)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 8192, size=(batch, prompt_len)).astype(
            np.int32
        )
        lm.generate(prompt, new_tokens)  # compile + upload
        t0 = time.perf_counter()
        for _ in range(iters):
            lm.generate(prompt, new_tokens)
        dt = (time.perf_counter() - t0) / iters
        # k cache + v cache, [layers, B, n_kv, plen+new, hd] f32 each —
        # geometry derived from the model, matching transformer_generate
        d_model = lm.params["embed"].shape[1]
        hd = d_model // lm.params["n_heads"]
        qkv_cols = lm.params["blocks"][0]["qkv"].shape[1]
        heads = ((qkv_cols - d_model) // 2) // hd
        cache_mb = (
            2 * len(lm.params["blocks"]) * batch * heads
            * (prompt_len + new_tokens) * hd * 4 / 1e6
        )
        rows.append({
            "metric": f"decode_longctx_{label}_tok_per_sec",
            "value": round(batch * new_tokens / dt, 1),
            "unit": "tok/s",
            "batch": batch,
            "new_tokens": new_tokens,
            "kv_heads": heads,
            "kv_cache_mb": round(cache_mb, 1),
            "seconds_per_decode": round(dt, 4),
        })
    return rows


def run_all():
    return [
        bench_decode("greedy"),
        bench_decode("sampled"),
        bench_decode("ragged"),
        *bench_gqa(),
    ]


if __name__ == "__main__":
    for row in run_all():
        print(json.dumps(row))
