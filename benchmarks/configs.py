"""The BASELINE.md benchmark configs, plus framework-specific extras (7+).

Each function runs one config and returns a result dict; ``run_all.py``
prints them as JSON lines. ``bench.py`` at the repo root runs config 3 (the
driver's headline metric). Hardware note: numbers depend on the attached
backend — real TPU via the default platform, or CPU when forced.

| # | config | reference provenance |
|---|--------|----------------------|
| 1 | README scalar add-3 map_blocks            | README.md:60-88 |
| 2 | README vector reduce_sum/min on [?,2]     | README.md:91-122 |
| 3 | MNIST LR scoring via map_blocks           | core.py:41-55 (frozen graphs) |
| 4 | image-embedding map_rows over binary rows | read_image.py:147-167 |
| 5 | distributed SGD: map_blocks(grad) + reduce_blocks(sum) | DebugRowOps.scala:290-526 |
"""

from __future__ import annotations

import os
import time
from typing import Dict

import numpy as np


def _sync(v):
    """A REAL device barrier: ``block_until_ready`` alone is advisory on
    relayed/tunneled PJRT devices (measured returning in ms for 200ms+ of
    queued work on the axon tunnel), so a 1-element host readback forces
    execution to actually finish inside the timing window."""
    if hasattr(v, "block_until_ready"):
        v.block_until_ready()
        np.asarray(v.ravel()[:1])
    return v


def _timeit(fn, iters=5, warmup=1):
    """Wall time per call; the returned value of ``fn`` is synchronized so
    async device dispatch cannot leak out of the timing window."""
    for _ in range(warmup):
        _sync(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        _sync(fn())
    return (time.perf_counter() - t0) / iters


def config1_add3(n_rows: int = 1_000_000) -> Dict:
    """Scalar add-3 map_blocks (README example 1, scaled up)."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.capture import functions as F

    df = tft.TensorFrame.from_columns(
        {"x": np.arange(n_rows, dtype=np.float64)}
    )
    with tft.graph():
        x = tft.block(df, "x")
        g = tft.build_graph((x + 3.0).named("z"))

    def run():
        return tft.map_blocks(g, df).cache().column_block("z")

    dt = _timeit(run)
    assert float(run()[0]) == 3.0
    return {
        "metric": "config1_add3_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def config2_vector_reduce(n_rows: int = 1_000_000) -> Dict:
    """Vector reduce_sum + reduce_min on [?, 2] doubles (README example 2)."""
    import tensorframes_tpu as tft

    y = np.stack(
        [np.arange(n_rows, dtype=np.float64), -np.arange(n_rows, dtype=np.float64)],
        axis=1,
    )
    df = tft.TensorFrame.from_columns({"y": y, "z": y.copy()}).analyze()

    # one function object across passes (capture/compile memoized on it)
    def reduce_fn(y_input, z_input):
        return {"y": y_input.sum(axis=0), "z": z_input.min(axis=0)}

    def run():
        return tft.reduce_blocks(reduce_fn, df)

    dt = _timeit(run)
    s, m = run()
    np.testing.assert_allclose(np.asarray(m)[1], -(n_rows - 1))
    return {
        "metric": "config2_vector_reduce_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def config3_mnist_scoring(n_rows: int = 200_000) -> Dict:
    """MNIST-LR scoring via map_blocks on a frozen model (bench.py metric)."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.models import MLPClassifier

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, 784)).astype(np.float32)
    clf = MLPClassifier.init(0, [784, 10])
    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    def run():
        return clf.score_frame(df, "features").cache().column_block("prediction")

    dt = _timeit(run)
    return {
        "metric": "config3_mnist_scoring_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def _publish_torch_cnn(path: str, embed_dim: int = 256):
    """The external publisher for config4: a torch VGG-style net saved
    the way model hubs publish checkpoints (the reference's downloaded
    VGG-16, ``read_image.py:29-44``, played by torch). Falls back to
    ``None`` where torch isn't installed."""
    try:
        import torch
    except ImportError:
        return False
    torch.manual_seed(0)
    layers = []
    c_in = 3
    for width in (32, 64, 128):
        for _ in range(2):
            layers += [
                torch.nn.Conv2d(c_in, width, 3, padding=1),
                torch.nn.ReLU(),
            ]
            c_in = width
        layers.append(torch.nn.MaxPool2d(2))
    layers += [
        torch.nn.Flatten(),
        torch.nn.Linear(128 * 4 * 4, embed_dim),
    ]
    model = torch.nn.Sequential(*layers).eval()
    np.savez(path, **{k: v.numpy() for k, v in model.state_dict().items()})
    return True


def config4_image_scoring(n_rows: int = 100_000) -> Dict:
    """Frozen multi-layer CNN embedding over binary image rows (the
    reference's VGG-over-binaryFiles workload, ``read_image.py:147-167``):
    host codec via ``decode_column``'s thread pool, then batched bf16 convs
    on device, one XLA program per partition block. 6 conv layers + dense
    head over 32x32x3 uint8 images — with REAL imported weights: a torch
    publisher model's checkpoint imported through
    ``CNNScorer.from_pretrained`` (the reference scored a downloaded
    pre-trained VGG-16; r05 closes that realism gap)."""
    import tempfile

    import tensorframes_tpu as tft
    from tensorframes_tpu.models import CNNScorer

    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "published.npz")
        if _publish_torch_cnn(ckpt):
            scorer = CNNScorer.from_pretrained(
                ckpt, input_hw=(32, 32), channels=3, convs_per_block=2,
                image_format="raw",  # rows below are raw packed pixels
            )
            model_name = "torch-published-cnn6-imported-embed256"
        else:  # no torch on this host: random-init fallback
            scorer = CNNScorer.init(
                0, input_hw=(32, 32), channels=3, embed_dim=256
            )
            model_name = "cnn6-bf16-32x32x3-embed256 (random init; no torch)"
    # one contiguous uint8 pool sliced into per-row byte cells: building
    # 100k bytes objects is frame-construction cost, not scoring cost
    pool = rng.integers(0, 256, size=(n_rows, 32 * 32 * 3), dtype=np.uint8)
    raws = [pool[i].tobytes() for i in range(n_rows)]
    df = tft.TensorFrame.from_columns({"image_data": raws}, num_partitions=16)

    # host codec stage, measured alone (chunked thread-pool decode with
    # dense chunk assembly — was 2.80s in round 2, per-cell futures)
    t0 = time.perf_counter()
    decoded = df.decode_column("image_data", scorer.decode).cache().analyze()
    dt_decode = time.perf_counter() - t0

    # chip scoring stage over the decoded frame: the first pass pays the
    # host->HBM transfer (memoized per column) + XLA compile, later passes
    # measure the conv pipeline itself — the reference analog is repeated
    # scoring of a resident dataset, and it isolates chip rate from tunnel
    # bandwidth
    def run():
        out = scorer.score_frame(decoded, "image_data")
        emb = out.cache().column_data("embedding").dense
        assert emb.shape == (n_rows, 256)
        return emb

    t0 = time.perf_counter()
    _sync(run())
    dt_first = time.perf_counter() - t0
    dt = _timeit(run, iters=2, warmup=0)

    # overlapped single-shot: decode runs on the pool several partitions
    # AHEAD of the chip (map_blocks decoders=), one end-to-end pass over
    # fresh binary rows. On this box the number is LINK-bound: each pass
    # moves the full decoded 307MB host->device through the ~70MB/s
    # tunnel; on a real TPU host (PCIe) the same path is compute-bound.
    def run_overlapped():
        out = scorer.score_frame(df, "image_data")
        return out.cache().column_data("embedding").dense

    t0 = time.perf_counter()
    _sync(run_overlapped())
    dt_overlap = time.perf_counter() - t0

    # per-pass cost of a resident dataset = chip pass; decode amortizes
    # once per dataset. rows_per_sec counts BOTH (decode + one chip pass),
    # matching how round 2's number was scored.
    return {
        "metric": "config4_image_scoring_rows_per_sec",
        "value": round(n_rows / (dt + dt_decode), 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
        "decode_seconds_per_pass": round(dt_decode, 4),
        # first execution = XLA compile + host->HBM transfer + run; the
        # components are not separable without a second compile, so this is
        # reported as one labeled number rather than a fake decomposition
        "first_pass_seconds_incl_compile_and_transfer": round(dt_first, 4),
        "overlapped_fresh_ingest_seconds_per_pass": round(dt_overlap, 4),
        "model": model_name,
    }


def config5_distributed_sgd(
    n_rows: int = 262_144, dim: int = 64, steps: int = 10
) -> Dict:
    """Distributed SGD composed from the dataframe ops: map_blocks computes
    per-block gradient partials, reduce_blocks sums them (the reference's
    composition, DebugRowOps.scala:290-526), parameters update on the host.
    Runs over the default mesh (all available devices)."""
    import tensorframes_tpu as tft
    import tensorframes_tpu.parallel as par

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=dim).astype(np.float32)
    x = rng.normal(size=(n_rows, dim)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=n_rows)).astype(np.float32)
    df = tft.TensorFrame.from_columns({"x": x, "y": y}).analyze()
    mesh = par.make_mesh()

    def grad_fn(x, y, w):
        err = x @ w - y
        return {"g": (x * err[:, None])[None].sum(axis=1)}

    w = np.zeros(dim, dtype=np.float32)
    lr = 0.1 / n_rows

    def sum_fn(g_input):
        return {"g": g_input.sum(axis=0)}

    def step(w):
        partials = par.map_blocks(
            grad_fn, df, mesh=mesh, trim=True, constants={"w": w}
        ).cache().analyze()
        g = par.reduce_blocks(sum_fn, partials, mesh=mesh)
        return w - lr * np.asarray(g)

    w = step(w)  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        w = step(w)
    dt = (time.perf_counter() - t0) / steps
    err = float(np.linalg.norm(w - w_true) / np.linalg.norm(w_true))

    # ORACLE: a numpy SGD running the IDENTICAL schedule (same init, lr,
    # step count, full-batch gradient). rel_param_error vs w_true only
    # measures convergence progress and cannot catch a wrong gradient;
    # the oracle delta can.
    w_oracle = np.zeros(dim, dtype=np.float32)
    for _ in range(steps + 1):  # +1: the warmup step also updated w
        err_vec = x @ w_oracle - y
        w_oracle = w_oracle - lr * (x * err_vec[:, None]).sum(axis=0)
    oracle_delta = float(
        np.linalg.norm(w - w_oracle) / (np.linalg.norm(w_oracle) + 1e-12)
    )
    # tolerance sized for backends whose default matmul precision is
    # bf16: a wrong gradient produces O(1) deltas, rounding drift stays
    # well under this (measured 1.3e-6 on the tunneled v5e)
    assert oracle_delta < 5e-2, (
        f"df-ops SGD diverged from the numpy oracle running the same "
        f"schedule: {oracle_delta}"
    )
    return {
        "metric": "config5_sgd_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_step": round(dt, 4),
        # distance to the NOISY problem's generating weights — bounded
        # below by the noise floor, NOT an optimizer error (correctness is
        # the oracle delta, ~1e-6); named so the artifact can't be misread
        # as a 31% optimizer error
        "rel_param_error_vs_ground_truth_under_noise": round(err, 4),
        "oracle_rel_delta": round(oracle_delta, 8),
    }


def config6_grouped_aggregate(
    n_rows: int = 10_000_000, n_groups: int = 1024
) -> Dict:
    """Keyed aggregation at scale: 10M rows summed into 1024 groups through
    the segmented-scan aggregate (device sort + scan), against a
    multithreaded numpy host oracle (argsort + reduceat) — the reference
    ran this entirely in the JVM shuffle (``TensorFlowUDAF``,
    ``DebugRowOps.scala:601-695``)."""
    import tensorframes_tpu as tft

    rng = np.random.default_rng(0)
    x = rng.normal(size=n_rows).astype(np.float32)
    key = rng.integers(0, n_groups, size=n_rows).astype(np.int32)
    df = tft.TensorFrame.from_columns({"x": x, "key": key}).analyze()
    grouped = df.group_by("key")

    # one function object across passes: graph capture and its compiled
    # scan programs are memoized per function identity
    def agg_fn(x_input):
        return {"x": x_input.sum(axis=0)}

    def run():
        return tft.aggregate(agg_fn, grouped).cache().column_block("x")

    dt = _timeit(run, iters=3)

    def host_oracle():
        order = np.argsort(key, kind="stable")
        ks = key[order]
        xs = x[order]
        starts = np.flatnonzero(np.r_[True, ks[1:] != ks[:-1]])
        return ks[starts], np.add.reduceat(xs, starts)

    t0 = time.perf_counter()
    ok, osum = host_oracle()
    dt_host = time.perf_counter() - t0

    res = tft.aggregate(agg_fn, grouped).cache()
    got = {
        int(k): float(v)
        for k, v in zip(
            np.asarray(res.column_block("key")), np.asarray(res.column_block("x"))
        )
    }
    want = dict(zip(ok.tolist(), osum.tolist()))
    assert set(got) == set(want)
    worst = max(abs(got[k] - want[k]) / (abs(want[k]) + 1e-6) for k in want)
    assert worst < 1e-2, f"group sums diverge: {worst}"
    return {
        "metric": "config6_grouped_aggregate_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
        "host_numpy_seconds": round(dt_host, 4),
        "vs_host_numpy": round(dt_host / dt, 3),
        "n_groups": n_groups,
    }


def config7_dense_map_rows(n_rows: int = 1_000_000) -> Dict:
    """1M-row dense ``map_rows`` vs the equivalent ``map_blocks``: the
    all-dense single-bucket fast path (device feeds, on-device chunk
    slicing/concat, no per-chunk host round-trips) should keep row-wise
    semantics within ~2x of block execution end to end (result pulled to
    host in both, so both pay one full transfer)."""
    import tensorframes_tpu as tft

    x = np.random.default_rng(0).normal(size=n_rows).astype(np.float32)
    df = tft.TensorFrame.from_columns({"x": x}).analyze()

    def row_fn(x):
        return {"y": x * 2.0 + 1.0}

    def blk_fn(x):
        return {"z": x * 2.0 + 1.0}

    def run_rows():
        return tft.map_rows(row_fn, df).cache().column_data("y").host()

    def run_blocks():
        return tft.map_blocks(blk_fn, df).cache().column_data("z").host()

    dt_rows = _timeit(run_rows, iters=3)
    dt_blocks = _timeit(run_blocks, iters=3)
    np.testing.assert_allclose(run_rows(), x * 2.0 + 1.0, rtol=1e-6)

    # CHIP-SIDE decomposition (chain-length differential, the kernel-row
    # methodology): the two paths' compiled programs — jit(vmap(fn)) for
    # rows, jit(fn) for blocks — chained so constant RTT/dispatch terms
    # cancel. This pins whether any end-to-end gap is chip work or link
    # round-trips: the row path's retry contract costs one extra sync
    # RTT per pass (eager materialization window), which is environment
    # latency, invisible chip-side.
    import jax

    from benchmarks.attention_bench import _diff_time

    xd = df.column_data("x").device()

    def rows_chain(n):
        def f(a):
            def body(_, acc):
                return jax.vmap(lambda v: v * 2.0 + 1.0)(acc)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    def blocks_chain(n):
        def f(a):
            def body(_, acc):
                return acc * 2.0 + 1.0

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    est = 2 * x.nbytes / 819e9  # HBM-bound elementwise op
    t_rows_chip, _ = _diff_time(rows_chain, (xd,), est)
    t_blocks_chip, _ = _diff_time(blocks_chain, (xd,), est)

    return {
        "metric": "config7_dense_map_rows_rows_per_sec",
        "value": round(n_rows / dt_rows, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt_rows, 4),
        "map_blocks_seconds_per_pass": round(dt_blocks, 4),
        "vs_map_blocks": round(dt_rows / dt_blocks, 3),
        "chip_side_row_program_us": round(t_rows_chip * 1e6, 1),
        "chip_side_block_program_us": round(t_blocks_chip * 1e6, 1),
        "vs_map_blocks_chip_side": round(t_rows_chip / t_blocks_chip, 3),
    }


def config8_string_key_aggregate(
    n_rows: int = 10_000_000, n_groups: int = 1024
) -> Dict:
    """10M-row aggregate grouped by a STRING key: key coding is vectorized
    (np.unique over a fixed-width byte array, first-appearance renumber) —
    the old per-row dict loop spent the whole pass in the interpreter.
    Reports coding time vs everything-else time."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.engine.ops import _group_sort_impl

    rng = np.random.default_rng(0)
    x = rng.normal(size=n_rows).astype(np.float32)
    gid = rng.integers(0, n_groups, size=n_rows)
    # one bytes pool sliced per row: building 10M bytes objects is frame
    # construction cost, not aggregation cost
    names = np.char.add("grp_", gid.astype("U8")).astype("S12")
    keys = [bytes(names[i]) for i in range(n_rows)]
    df = tft.TensorFrame.from_columns({"k": keys, "x": x}).analyze()
    grouped = df.group_by("k")

    def agg_fn(x_input):
        return {"x": x_input.sum(axis=0)}

    def run():
        return tft.aggregate(agg_fn, grouped).cache().column_data("x").host()

    dt = _timeit(run, iters=2)

    # key coding + device sort measured on a FRESH frame after everything
    # is warm (the sort permutation memoizes per frame, which is the
    # production behavior but would hide the per-dataset cost; a cold
    # frame before warmup would charge XLA compiles to coding)
    df2 = tft.TensorFrame.from_columns({"k": keys, "x": x}).analyze()
    t0 = time.perf_counter()
    _group_sort_impl(df2, ["k"], {})
    dt_coding = time.perf_counter() - t0
    got = run()
    assert got.shape[0] == n_groups
    np.testing.assert_allclose(float(got.sum()), float(x.sum()), rtol=1e-3)

    # decompose the fresh-frame cost: the host coding pass alone (the
    # native list-direct coder, r05) vs the remainder — the codes upload
    # (narrowed to the smallest dtype that fits the group ids, here
    # uint16) + device argsort + boundary readback, which scale with
    # LINK bandwidth, not host speed. Without the split, link weather
    # reads as a coding regression (r04's 4.36 s was ~75% upload).
    from tensorframes_tpu.data.packer import code_keys

    t0 = time.perf_counter()
    codes = code_keys(keys)
    dt_code_host = time.perf_counter() - t0
    code_bytes = None
    if codes is not None:
        mx = int(codes.max())
        width = 1 if mx < 256 else (2 if mx < 65536 else 4)
        code_bytes = n_rows * width
    # the sort permutation (and its coding pass) memoizes per frame, so
    # the timed passes above exclude coding; fresh data pays both, which
    # is what value reports
    return {
        "metric": "config8_string_key_aggregate_rows_per_sec",
        "value": round(n_rows / (dt + dt_coding), 1),
        "unit": "rows/s",
        "seconds_per_pass_memoized_sort": round(dt, 4),
        "key_coding_and_sort_seconds": round(dt_coding, 4),
        "key_coding_host_seconds": round(dt_code_host, 4)
        if codes is not None
        else None,
        "codes_upload_mb": round(code_bytes / 1e6, 1)
        if code_bytes
        else None,
        "upload_sort_readback_seconds": round(dt_coding - dt_code_host, 4)
        if codes is not None
        else None,
        "n_groups": n_groups,
    }


def config9_kmeans(
    n_rows: int = 1_000_000, dim: int = 16, k: int = 32, iters: int = 10
) -> Dict:
    """Lloyd k-means through the df ops (in-graph pre-aggregation +
    reduce merge, the reference demo's optimized pattern,
    ``kmeans_demo.py:101-171``), vs a numpy oracle running the IDENTICAL
    schedule (same seeded init, same update rule) — the oracle delta
    catches a wrong assignment/update, which a convergence curve cannot.
    Per iteration the host sees only the [k,d]+[k] partials (a few KB);
    the O(n*k*d) distance work stays on the MXU."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.models import kmeans

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, dim)).astype(np.float32)
    # well-separated planted clusters so the oracle path is stable
    x += rng.normal(size=(k, dim)).astype(np.float32)[
        rng.integers(0, k, size=n_rows)
    ] * 4.0
    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    kmeans(df, "features", k=k, num_iters=1, seed=1)  # warmup/compile
    t0 = time.perf_counter()
    cents, _ = kmeans(df, "features", k=k, num_iters=iters, seed=1)
    dt = (time.perf_counter() - t0) / iters

    # numpy oracle, identical schedule
    def numpy_lloyd():
        r = np.random.default_rng(1)
        c = x[r.choice(n_rows, size=k, replace=False)].astype(x.dtype)
        for _ in range(iters):
            d2 = ((x[:, None, :] - c[None, :, :]) ** 2).sum(axis=-1)
            closest = np.argmin(d2, axis=1)
            nc = c.copy()
            for j in range(k):
                m = closest == j
                if m.any():
                    nc[j] = x[m].mean(axis=0)
            if np.linalg.norm(nc - c) == 0.0:
                c = nc
                break
            c = nc
        return c

    t0 = time.perf_counter()
    c_oracle = numpy_lloyd()
    dt_numpy = (time.perf_counter() - t0) / iters
    oracle_delta = float(
        np.linalg.norm(cents - c_oracle) / np.linalg.norm(c_oracle)
    )
    # argmin assignments are exact (elementwise f32 distances); only the
    # mean update can pick up rounding, so the bound stays tight
    assert oracle_delta < 1e-3, (
        f"kmeans centroids diverged from the numpy oracle running the "
        f"same schedule: {oracle_delta}"
    )
    return {
        "metric": "config9_kmeans_rows_per_sec_per_iter",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_iter": round(dt, 4),
        "numpy_seconds_per_iter": round(dt_numpy, 4),
        "vs_numpy": round(dt_numpy / dt, 2),
        "oracle_rel_delta": round(oracle_delta, 8),
        "k": k,
        "dim": dim,
    }


def config10_streaming_map_blocks(n_rows: int = 200_000, d: int = 64) -> Dict:
    """Over-budget column: streaming ``map_blocks`` (host slices feed one
    partition at a time, HBM bounded at ~one block) vs the device-resident
    mode (column memoized in HBM, the engine default under the budget).

    The headline is ``overlap_efficiency`` = max(pure link, pure chip) /
    streaming pass — a perfectly pipelined stream takes ~max(link, chip)
    seconds, so 1.0 means transfers fully hide behind compute (or vice
    versa). Unlike a raw streaming time (or the previous (link+chip)/
    streaming ratio), this is normalized against the SAME RUN's measured
    link speed, so tunnel weather divides out to first order: halve the
    link rate and both the numerator's link term and the stream's
    link-bound part double. The link leg is measured before AND after the
    streaming pass; ``link_stability`` witnesses whether the weather held
    (ratios from runs with link_stability far from 1 are suspect). The
    chip and link seconds are also reported separately (config 2 pattern)
    so regressions are attributable. The reference gets this overlap
    shape from Spark's partition iterator (``DebugRowOps.scala:766-803``).
    ``vs_resident`` remains LINK-bound on a tunnel-attached chip — on a
    PCIe-attached host it is bounded by PCIe/HBM bandwidth instead."""
    import jax.numpy as jnp

    import tensorframes_tpu as tft
    from tensorframes_tpu.utils import get_config, set_config

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, d)).astype(np.float32)  # ~50MB
    w = jnp.asarray(rng.normal(size=(d, d)).astype(np.float32) * 0.1)
    df = tft.TensorFrame.from_columns(
        {"x": x}, num_partitions=8
    ).analyze()

    def fn(x):
        return {"y": jnp.tanh(x @ w) @ w}

    def run():
        out = tft.map_blocks(fn, df, trim=True).cache()
        # resident mode: stays in HBM (_sync reads 1 element); streaming
        # mode: already host rows (the streamed pull IS part of the pass)
        return out.column_data("y").dense

    old = get_config().device_cache_bytes
    try:
        # resident mode: column cached in HBM, passes read from HBM
        set_config(device_cache_bytes=4 << 30)
        dt_resident = _timeit(run, iters=2)

        # pure transfer round trip: a streamed pass must move every
        # partition up AND its result partition down; serialize both to
        # get the no-overlap baseline
        import jax

        bounds = df.partition_bounds()

        def transfer_round_trip():
            part = None
            for lo, hi in bounds:
                part = jax.device_put(x[lo:hi])
                np.asarray(part)
            return part

        dt_transfer_pre = _timeit(transfer_round_trip, iters=2)

        # streaming mode: budget below the column size -> host slices in,
        # result partitions pulled back as they land
        set_config(device_cache_bytes=8 << 20)
        df.unpersist_device()
        dt_streaming = _timeit(run, iters=2)

        # second link measurement AFTER the stream: witnesses whether the
        # link weather held across the measurement window
        dt_transfer_post = _timeit(transfer_round_trip, iters=2)
    finally:
        set_config(device_cache_bytes=old)

    dt_transfer = (dt_transfer_pre + dt_transfer_post) / 2.0
    efficiency = max(dt_transfer, dt_resident) / dt_streaming
    return {
        "metric": "config10_streaming_overlap_efficiency",
        "value": round(efficiency, 3),
        "unit": "x",
        "streaming_seconds_per_pass": round(dt_streaming, 4),
        "chip_seconds_per_pass": round(dt_resident, 4),
        "link_seconds_per_pass": round(dt_transfer, 4),
        "link_stability": round(dt_transfer_pre / dt_transfer_post, 3),
        "overlap_ratio_legacy": round(
            (dt_transfer + dt_resident) / dt_streaming, 3
        ),
        "vs_resident": round(dt_streaming / dt_resident, 2),
        "column_mb": round(x.nbytes / 1e6, 1),
        "link_mb_per_s_round_trip": round(
            2 * x.nbytes / 1e6 / dt_transfer, 1
        ),
        "note": "overlap_efficiency ~1 means the stream takes "
        "max(link, chip) — transfers fully pipeline against compute; "
        "weather-normalized against the same run's link measurements "
        "(floor: >= 0.6 on a stable link). vs_resident is "
        "link-bandwidth-bound on this tunnel (see docstring)",
    }


ALL_CONFIGS = {
    1: config1_add3,
    2: config2_vector_reduce,
    3: config3_mnist_scoring,
    4: config4_image_scoring,
    5: config5_distributed_sgd,
    6: config6_grouped_aggregate,
    7: config7_dense_map_rows,
    8: config8_string_key_aggregate,
    9: config9_kmeans,
    10: config10_streaming_map_blocks,
}
