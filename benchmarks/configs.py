"""The five BASELINE.md benchmark configs.

Each function runs one config and returns a result dict; ``run_all.py``
prints them as JSON lines. ``bench.py`` at the repo root runs config 3 (the
driver's headline metric). Hardware note: numbers depend on the attached
backend — real TPU via the default platform, or CPU when forced.

| # | config | reference provenance |
|---|--------|----------------------|
| 1 | README scalar add-3 map_blocks            | README.md:60-88 |
| 2 | README vector reduce_sum/min on [?,2]     | README.md:91-122 |
| 3 | MNIST LR scoring via map_blocks           | core.py:41-55 (frozen graphs) |
| 4 | image-embedding map_rows over binary rows | read_image.py:147-167 |
| 5 | distributed SGD: map_blocks(grad) + reduce_blocks(sum) | DebugRowOps.scala:290-526 |
"""

from __future__ import annotations

import time
from typing import Dict

import numpy as np


def _timeit(fn, iters=5, warmup=1):
    """Wall time per call; the returned value of ``fn`` is synchronized so
    async device dispatch cannot leak out of the timing window."""

    def _sync(v):
        if hasattr(v, "block_until_ready"):
            v.block_until_ready()
        return v

    for _ in range(warmup):
        _sync(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        _sync(fn())
    return (time.perf_counter() - t0) / iters


def config1_add3(n_rows: int = 1_000_000) -> Dict:
    """Scalar add-3 map_blocks (README example 1, scaled up)."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.capture import functions as F

    df = tft.TensorFrame.from_columns(
        {"x": np.arange(n_rows, dtype=np.float64)}
    )
    with tft.graph():
        x = tft.block(df, "x")
        g = tft.build_graph((x + 3.0).named("z"))

    def run():
        return tft.map_blocks(g, df).cache().column_block("z")

    dt = _timeit(run)
    assert float(run()[0]) == 3.0
    return {
        "metric": "config1_add3_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def config2_vector_reduce(n_rows: int = 1_000_000) -> Dict:
    """Vector reduce_sum + reduce_min on [?, 2] doubles (README example 2)."""
    import tensorframes_tpu as tft

    y = np.stack(
        [np.arange(n_rows, dtype=np.float64), -np.arange(n_rows, dtype=np.float64)],
        axis=1,
    )
    df = tft.TensorFrame.from_columns({"y": y, "z": y.copy()}).analyze()

    # one function object across passes (capture/compile memoized on it)
    def reduce_fn(y_input, z_input):
        return {"y": y_input.sum(axis=0), "z": z_input.min(axis=0)}

    def run():
        return tft.reduce_blocks(reduce_fn, df)

    dt = _timeit(run)
    s, m = run()
    np.testing.assert_allclose(np.asarray(m)[1], -(n_rows - 1))
    return {
        "metric": "config2_vector_reduce_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def config3_mnist_scoring(n_rows: int = 200_000) -> Dict:
    """MNIST-LR scoring via map_blocks on a frozen model (bench.py metric)."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.models import MLPClassifier

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, 784)).astype(np.float32)
    clf = MLPClassifier.init(0, [784, 10])
    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    def run():
        return clf.score_frame(df, "features").cache().column_block("prediction")

    dt = _timeit(run)
    return {
        "metric": "config3_mnist_scoring_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
    }


def config4_image_scoring(n_rows: int = 100_000) -> Dict:
    """Frozen multi-layer CNN embedding over binary image rows (the
    reference's VGG-over-binaryFiles workload, ``read_image.py:147-167``):
    host codec via ``decode_column``'s thread pool, then batched bf16 convs
    on device, one XLA program per partition block. 6 conv layers + dense
    head over 32x32x3 uint8 images."""
    import tensorframes_tpu as tft
    from tensorframes_tpu.models import CNNScorer

    rng = np.random.default_rng(0)
    scorer = CNNScorer.init(0, input_hw=(32, 32), channels=3, embed_dim=256)
    # one contiguous uint8 pool sliced into per-row byte cells: building
    # 100k bytes objects is frame-construction cost, not scoring cost
    pool = rng.integers(0, 256, size=(n_rows, 32 * 32 * 3), dtype=np.uint8)
    raws = [pool[i].tobytes() for i in range(n_rows)]
    df = tft.TensorFrame.from_columns({"image_data": raws}, num_partitions=16)

    # host codec stage, measured alone
    t0 = time.perf_counter()
    decoded = df.decode_column("image_data", scorer.decode).cache().analyze()
    dt_decode = time.perf_counter() - t0

    # chip scoring stage over the decoded frame: the first pass pays the
    # host->HBM transfer (memoized per column), later passes measure the
    # conv pipeline itself — the reference analog is repeated scoring of a
    # resident dataset, and it isolates chip rate from tunnel bandwidth
    def run():
        out = scorer.score_frame(decoded, "image_data")
        emb = out.cache().column_block("embedding")
        assert emb.shape == (n_rows, 256)
        return emb

    t0 = time.perf_counter()
    run()
    dt_first = time.perf_counter() - t0
    dt = _timeit(run, iters=2, warmup=0)
    return {
        "metric": "config4_image_scoring_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
        "decode_seconds_per_pass": round(dt_decode, 4),
        # first execution = XLA compile + host->HBM transfer + run; the
        # components are not separable without a second compile, so this is
        # reported as one labeled number rather than a fake decomposition
        "first_pass_seconds_incl_compile_and_transfer": round(dt_first, 4),
        "model": "cnn6-bf16-32x32x3-embed256",
    }


def config5_distributed_sgd(
    n_rows: int = 262_144, dim: int = 64, steps: int = 10
) -> Dict:
    """Distributed SGD composed from the dataframe ops: map_blocks computes
    per-block gradient partials, reduce_blocks sums them (the reference's
    composition, DebugRowOps.scala:290-526), parameters update on the host.
    Runs over the default mesh (all available devices)."""
    import tensorframes_tpu as tft
    import tensorframes_tpu.parallel as par

    rng = np.random.default_rng(0)
    w_true = rng.normal(size=dim).astype(np.float32)
    x = rng.normal(size=(n_rows, dim)).astype(np.float32)
    y = (x @ w_true + 0.01 * rng.normal(size=n_rows)).astype(np.float32)
    df = tft.TensorFrame.from_columns({"x": x, "y": y}).analyze()
    mesh = par.make_mesh()

    def grad_fn(x, y, w):
        err = x @ w - y
        return {"g": (x * err[:, None])[None].sum(axis=1)}

    w = np.zeros(dim, dtype=np.float32)
    lr = 0.1 / n_rows

    def sum_fn(g_input):
        return {"g": g_input.sum(axis=0)}

    def step(w):
        partials = par.map_blocks(
            grad_fn, df, mesh=mesh, trim=True, constants={"w": w}
        ).cache().analyze()
        g = par.reduce_blocks(sum_fn, partials, mesh=mesh)
        return w - lr * np.asarray(g)

    w = step(w)  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(steps):
        w = step(w)
    dt = (time.perf_counter() - t0) / steps
    err = float(np.linalg.norm(w - w_true) / np.linalg.norm(w_true))
    return {
        "metric": "config5_sgd_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_step": round(dt, 4),
        "rel_param_error": round(err, 4),
    }


def config6_grouped_aggregate(
    n_rows: int = 10_000_000, n_groups: int = 1024
) -> Dict:
    """Keyed aggregation at scale: 10M rows summed into 1024 groups through
    the segmented-scan aggregate (device sort + scan), against a
    multithreaded numpy host oracle (argsort + reduceat) — the reference
    ran this entirely in the JVM shuffle (``TensorFlowUDAF``,
    ``DebugRowOps.scala:601-695``)."""
    import tensorframes_tpu as tft

    rng = np.random.default_rng(0)
    x = rng.normal(size=n_rows).astype(np.float32)
    key = rng.integers(0, n_groups, size=n_rows).astype(np.int32)
    df = tft.TensorFrame.from_columns({"x": x, "key": key}).analyze()
    grouped = df.group_by("key")

    # one function object across passes: graph capture and its compiled
    # scan programs are memoized per function identity
    def agg_fn(x_input):
        return {"x": x_input.sum(axis=0)}

    def run():
        return tft.aggregate(agg_fn, grouped).cache().column_block("x")

    dt = _timeit(run, iters=3)

    def host_oracle():
        order = np.argsort(key, kind="stable")
        ks = key[order]
        xs = x[order]
        starts = np.flatnonzero(np.r_[True, ks[1:] != ks[:-1]])
        return ks[starts], np.add.reduceat(xs, starts)

    t0 = time.perf_counter()
    ok, osum = host_oracle()
    dt_host = time.perf_counter() - t0

    res = tft.aggregate(agg_fn, grouped).cache()
    got = {
        int(k): float(v)
        for k, v in zip(
            np.asarray(res.column_block("key")), np.asarray(res.column_block("x"))
        )
    }
    want = dict(zip(ok.tolist(), osum.tolist()))
    assert set(got) == set(want)
    worst = max(abs(got[k] - want[k]) / (abs(want[k]) + 1e-6) for k in want)
    assert worst < 1e-2, f"group sums diverge: {worst}"
    return {
        "metric": "config6_grouped_aggregate_rows_per_sec",
        "value": round(n_rows / dt, 1),
        "unit": "rows/s",
        "seconds_per_pass": round(dt, 4),
        "host_numpy_seconds": round(dt_host, 4),
        "vs_host_numpy": round(dt_host / dt, 3),
        "n_groups": n_groups,
    }


ALL_CONFIGS = {
    1: config1_add3,
    2: config2_vector_reduce,
    3: config3_mnist_scoring,
    4: config4_image_scoring,
    5: config5_distributed_sgd,
    6: config6_grouped_aggregate,
}
