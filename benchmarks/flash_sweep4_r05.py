"""Round-5 sweep, part 4: VMEM-safe dual-head D=64 tiles + final rows.

Part 3 found the dual-head forward exceeds the 16 MB scoped-VMEM budget
at 1024x1024 (two f32 score tiles live at once); it is now gated to
bq*bk <= 512k. This sweep measures the dual-head variant at its safe
tiles against the single-head incumbent, and records the final
train-step rows with the per-kernel backward tiles
(dq 1024x1024 + dkv 512x2048).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.attention_bench import bench_backward, bench_one
from benchmarks.flash_sweep_r05 import bwd_point, fwd_point


def main():
    rows = []

    def emit(r):
        rows.append(r)
        print(json.dumps(r), flush=True)

    L = 16384
    # dual-head D=64 at VMEM-safe tiles (bh=16, even -> dual engages)
    for bq, bk in [(512, 1024), (1024, 512), (512, 512), (256, 2048)]:
        emit(fwd_point(L, 64, bq, bk))
    # the single-head incumbent for reference (odd-head shapes use it)
    emit(fwd_point(L, 64, 1024, 1024, B=3, H=5))  # bh=15: single-head

    # final D=128 train-step with the mixed backward tiles
    emit(bench_backward(L, B=1, H=4, D=128))
    emit(bench_backward(32768, B=1, H=4, D=128))

    with open(
        os.path.join(os.path.dirname(__file__), "..",
                     "flash_sweep4_r05.json"),
        "w",
    ) as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
