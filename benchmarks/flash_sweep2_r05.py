"""Round-5 sweep, part 2: per-kernel backward tuning + D=64 fwd extras.

Part 1 (flash_sweep_r05.py) timed the backward PAIR with one shared tile
pair; here the dq kernel and the dk/dv kernel are timed separately so
each can pick its own tiles (they run different matmul mixes on
different grid orders), then the best combination is confirmed as a
pair. D=64 forward adds the smaller-tile candidates part 1 skipped.

Prints one JSON line per point; writes flash_sweep2_r05.json.
"""

import functools
import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.attention_bench import _diff_time, _make_qkv
from benchmarks.flash_sweep_r05 import bwd_point, fwd_point

_PEAK = 197e12


def _bwd_setup(L, D, B, H, causal):
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import _flash_forward

    q, k, v = _make_qkv(L, B, H, D, "bfloat16")
    bh = B * H
    qf, kf, vf = (a.reshape(bh, L, D) for a in (q, k, v))
    o, lse = _flash_forward(q, k, v, causal, 1024, 1024, False)
    dof = jnp.ones((bh, L, D), jnp.bfloat16)
    delta = (
        dof.astype(jnp.float32) * o.reshape(bh, L, D).astype(jnp.float32)
    ).sum(axis=-1, keepdims=True)
    return qf, kf, vf, dof, jax.lax.stop_gradient(lse), delta


def dq_kernel_point(L, D, bq, bk, B=1, H=4, causal=True):
    """Time ONLY the dq pallas call (3 matmuls/tile, k innermost)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tensorframes_tpu.ops.attention import (
        _dim_semantics,
        _flash_bwd_dq_kernel,
    )

    qf, kf, vf, dof, lse, delta = _bwd_setup(L, D, B, H, causal)
    bh = B * H
    scale = 1.0 / float(np.sqrt(D))

    q_spec = pl.BlockSpec(
        (1, bq, D), lambda bi, qi, ki: (bi, qi, 0), memory_space=pltpu.VMEM
    )
    k_spec = pl.BlockSpec(
        (1, bk, D), lambda bi, qi, ki: (bi, ki, 0), memory_space=pltpu.VMEM
    )
    row_spec = pl.BlockSpec(
        (1, bq, 1), lambda bi, qi, ki: (bi, qi, 0), memory_space=pltpu.VMEM
    )

    def one(qq):
        return pl.pallas_call(
            functools.partial(
                _flash_bwd_dq_kernel, block_q=bq, block_k=bk,
                causal=causal, offset=0, scale=scale,
            ),
            grid=(bh, L // bq, L // bk),
            in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
            out_specs=q_spec,
            out_shape=jax.ShapeDtypeStruct((bh, L, D), jnp.bfloat16),
            scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
            compiler_params=_dim_semantics(pltpu, False),
            interpret=False,
        )(qq, kf, vf, dof, lse, delta)

    def chain(n):
        def f(qq):
            def body(_, acc):
                return one(acc).astype(acc.dtype)

            return jax.lax.fori_loop(0, n, body, qq)

        return jax.jit(f)

    # dq kernel: 3 of the 7 real matmul passes -> 1.5x fwd volume
    flops = 1.5 * 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    try:
        per, chains = _diff_time(chain, (qf,), flops / (0.4 * _PEAK))
    except Exception as e:
        return {"metric": "flash_bwd_dq_kernel", "seq_len": L,
                "head_dim": D, "block_q": bq, "block_k": bk,
                "error": str(e)[:160]}
    tf = flops / per / 1e12
    return {
        "metric": "flash_bwd_dq_kernel", "seq_len": L, "head_dim": D,
        "batch": B, "heads": H, "block_q": bq, "block_k": bk,
        "ms": round(per * 1e3, 3), "tflops_model1p5x": round(tf, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tf * 1e12 / _PEAK, 1),
        "chain_lengths": chains,
    }


def dkv_kernel_point(L, D, bq, bk, B=1, H=4, causal=True):
    """Time ONLY the dk/dv pallas call (4 matmuls/tile, q innermost)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from tensorframes_tpu.ops.attention import (
        _dim_semantics,
        _flash_bwd_dkv_kernel,
    )

    qf, kf, vf, dof, lse, delta = _bwd_setup(L, D, B, H, causal)
    bh = B * H
    scale = 1.0 / float(np.sqrt(D))

    qk_q_spec = pl.BlockSpec(
        (1, bq, D), lambda bi, ki, qi: (bi, qi, 0), memory_space=pltpu.VMEM
    )
    qk_k_spec = pl.BlockSpec(
        (1, bk, D), lambda bi, ki, qi: (bi, ki, 0), memory_space=pltpu.VMEM
    )
    qk_row_spec = pl.BlockSpec(
        (1, bq, 1), lambda bi, ki, qi: (bi, qi, 0), memory_space=pltpu.VMEM
    )

    def one(kk):
        return pl.pallas_call(
            functools.partial(
                _flash_bwd_dkv_kernel, block_q=bq, block_k=bk,
                causal=causal, offset=0, scale=scale,
            ),
            grid=(bh, L // bk, L // bq),
            in_specs=[qk_q_spec, qk_k_spec, qk_k_spec, qk_q_spec,
                      qk_row_spec, qk_row_spec],
            out_specs=[qk_k_spec, qk_k_spec],
            out_shape=[
                jax.ShapeDtypeStruct((bh, L, D), jnp.bfloat16),
                jax.ShapeDtypeStruct((bh, L, D), jnp.bfloat16),
            ],
            scratch_shapes=[
                pltpu.VMEM((bk, D), jnp.float32),
                pltpu.VMEM((bk, D), jnp.float32),
            ],
            compiler_params=_dim_semantics(pltpu, False),
            interpret=False,
        )(qf, kk, vf, dof, lse, delta)

    def chain(n):
        def f(kk):
            def body(_, acc):
                dk, dv = one(acc)
                return (dk + dv).astype(acc.dtype)

            return jax.lax.fori_loop(0, n, body, kk)

        return jax.jit(f)

    # dkv kernel: 4 of the 7 real matmul passes -> 2x fwd volume
    flops = 2.0 * 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    try:
        per, chains = _diff_time(chain, (kf,), flops / (0.4 * _PEAK))
    except Exception as e:
        return {"metric": "flash_bwd_dkv_kernel", "seq_len": L,
                "head_dim": D, "block_q": bq, "block_k": bk,
                "error": str(e)[:160]}
    tf = flops / per / 1e12
    return {
        "metric": "flash_bwd_dkv_kernel", "seq_len": L, "head_dim": D,
        "batch": B, "heads": H, "block_q": bq, "block_k": bk,
        "ms": round(per * 1e3, 3), "tflops_model2x": round(tf, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tf * 1e12 / _PEAK, 1),
        "chain_lengths": chains,
    }


def main():
    rows = []

    def emit(r):
        rows.append(r)
        print(json.dumps(r), flush=True)

    L = 16384
    # fwd D=64 smaller-tile candidates
    for bq, bk in [(512, 1024), (1024, 512), (512, 512), (256, 1024)]:
        emit(fwd_point(L, 64, bq, bk))

    # dq kernel, D=128
    for bq, bk in [(1024, 1024), (512, 2048), (512, 4096), (1024, 2048),
                   (256, 2048)]:
        emit(dq_kernel_point(L, 128, bq, bk))

    # dkv kernel, D=128
    for bq, bk in [(1024, 1024), (2048, 512), (4096, 512), (2048, 1024),
                   (2048, 256), (1024, 512)]:
        emit(dkv_kernel_point(L, 128, bq, bk))

    with open(
        os.path.join(os.path.dirname(__file__), "..",
                     "flash_sweep2_r05.json"),
        "w",
    ) as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
