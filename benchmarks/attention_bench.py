"""Flash-attention microbench: Pallas kernel vs dense XLA attention.

The long-context stack's hot op (the reference has no attention at all —
SURVEY §5 "long context: absent"). Run on the attached backend:

    python benchmarks/attention_bench.py [seq_lens...]

Prints one JSON line per sequence length with ms/call and the achieved
fraction of the dense oracle's time (higher speedup = better; dense
attention materializes the [L, L] score matrix, flash streams K/V through
VMEM so its memory stays O(L))."""

import json
import sys
import time

import numpy as np


def bench_one(L, B=4, H=8, D=64, causal=True, iters=5):
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import attention_reference, flash_attention

    rng = np.random.default_rng(0)
    shape = (B, H, L, D)
    q = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    k = jnp.asarray(rng.normal(size=shape).astype(np.float32))
    v = jnp.asarray(rng.normal(size=shape).astype(np.float32))

    # chain the op inside ONE jitted program (output feeds the next query)
    # so per-dispatch link latency amortizes and the chip time dominates
    chain = 10

    def chained(attn):
        def f(a, b, c):
            def body(_, acc):
                return attn(acc, b, c)

            return jax.lax.fori_loop(0, chain, body, a)

        return jax.jit(f)

    flash1 = jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=causal))
    dense1 = jax.jit(lambda a, b, c: attention_reference(a, b, c, causal=causal))
    flash = chained(lambda a, b, c: flash_attention(a, b, c, causal=causal))

    out_f = jax.block_until_ready(flash1(q, k, v))
    err = None
    try:
        out_d = jax.block_until_ready(dense1(q, k, v))
        err = float(jnp.max(jnp.abs(out_f - out_d)))
        dense = chained(
            lambda a, b, c: attention_reference(a, b, c, causal=causal)
        )
        jax.block_until_ready(dense(q, k, v))
    except Exception:
        dense = None  # [L, L] score matrix no longer fits HBM

    def timeit(f):
        jax.block_until_ready(f(q, k, v))
        t0 = time.perf_counter()
        for _ in range(iters):
            jax.block_until_ready(f(q, k, v))
        return (time.perf_counter() - t0) / iters / chain

    tf_ = timeit(flash)
    td = timeit(dense) if dense is not None else None
    # attention FLOPs: 2 matmuls of [L,L]x[L,D] per head (causal ~half)
    flops = 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    return {
        "metric": "flash_attention_ms",
        "seq_len": L,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "causal": causal,
        "flash_ms": round(tf_ * 1e3, 3),
        "dense_ms": round(td * 1e3, 3) if td else None,
        "speedup_vs_dense": round(td / tf_, 3) if td else None,
        "flash_tflops": round(flops / tf_ / 1e12, 2),
        "max_abs_err_vs_dense": round(err, 6) if err is not None else None,
    }


def main():
    lens = [int(a) for a in sys.argv[1:]] or [1024, 2048, 4096, 8192]
    for L in lens:
        print(json.dumps(bench_one(L)))


if __name__ == "__main__":
    main()
