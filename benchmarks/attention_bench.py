"""Flash-attention microbench: Pallas kernel vs dense XLA attention.

The long-context stack's hot op (the reference has no attention at all —
SURVEY §5 "long context: absent"). Run on the attached backend:

    python benchmarks/attention_bench.py [seq_lens...]

Prints one JSON line per (sequence length, dtype) with ms/call, achieved
TFLOP/s, and MFU (% of the chip's matmul peak for that dtype). bf16 inputs
run the kernel's matmuls in the MXU's native bf16 mode (f32 accumulation);
dense attention materializes the [L, L] score matrix, flash streams K/V
through VMEM so its memory stays O(L).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: v5e (v5 lite) public matmul peaks per input dtype
_V5E_PEAK_FLOPS = {"bfloat16": 197e12, "float32": 49e12}


from benchmarks.configs import _sync  # readback barrier (advisory
# block_until_ready on relayed/tunneled PJRT devices — one shared recipe)


def _make_qkv(L, B, H, D, dtype):
    """Shared benchmark inputs: every row (forward, dense, train-step)
    measures the same distribution and dtype handling."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    shape = (B, H, L, D)
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    mk = lambda: jnp.asarray(
        rng.normal(size=shape).astype(np.float32)
    ).astype(dt)
    return mk(), mk(), mk()


def bench_one(L, B=4, H=8, D=64, causal=True, iters=5, dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import (
        attention_reference,
        flash_attention,
    )

    q, k, v = _make_qkv(L, B, H, D, dtype)

    # chain the op inside ONE jitted program (output feeds the next query)
    # so per-dispatch link latency amortizes and the chip time dominates
    chain = 10

    def chained(attn):
        def f(a, b, c):
            def body(_, acc):
                return attn(acc, b, c).astype(a.dtype)

            return jax.lax.fori_loop(0, chain, body, a)

        return jax.jit(f)

    flash1 = jax.jit(lambda a, b, c: flash_attention(a, b, c, causal=causal))
    dense1 = jax.jit(
        lambda a, b, c: attention_reference(a, b, c, causal=causal)
    )
    flash = chained(lambda a, b, c: flash_attention(a, b, c, causal=causal))

    out_f = _sync(flash1(q, k, v))
    err = None
    try:
        out_d = _sync(dense1(q, k, v))
        err = float(
            jnp.max(
                jnp.abs(
                    out_f.astype(jnp.float32) - out_d.astype(jnp.float32)
                )
            )
        )
        dense = chained(
            lambda a, b, c: attention_reference(a, b, c, causal=causal)
        )
        _sync(dense(q, k, v))
    except Exception:
        dense = None  # [L, L] score matrix no longer fits HBM

    def timeit(f):
        _sync(f(q, k, v))
        t0 = time.perf_counter()
        out = None
        for _ in range(iters):
            out = f(q, k, v)  # independent dispatches queue on device
        _sync(out)
        return (time.perf_counter() - t0) / iters / chain

    tf_ = timeit(flash)
    td = timeit(dense) if dense is not None else None
    # attention FLOPs: 2 matmuls of [L,L]x[L,D] per head (causal ~half)
    flops = 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    tflops = flops / tf_ / 1e12
    peak = _V5E_PEAK_FLOPS[dtype]
    note = None
    if tflops * 1e12 / peak < 0.10:
        # low MFU at short L means the measured time is mostly dispatch,
        # not kernel compute (one sync readback per iters x chain calls
        # still leaves a per-call dispatch share on this tunneled chip;
        # dense XLA pays the same) — the long-L rows reflect the kernel
        note = (
            "dispatch-dominated row (MFU < 10%): per-call overhead on "
            "this tunneled chip exceeds the kernel's compute at this "
            "size — the long-L rows reflect the kernel's streaming rate"
        )
    return {
        "metric": "flash_attention_ms",
        "seq_len": L,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "causal": causal,
        "dtype": dtype,
        "flash_ms": round(tf_ * 1e3, 3),
        "dense_ms": round(td * 1e3, 3) if td else None,
        "speedup_vs_dense": round(td / tf_, 3) if td else None,
        "flash_tflops": round(tflops, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tflops * 1e12 / peak, 1),
        "max_abs_err_vs_dense": round(err, 6) if err is not None else None,
        "note": note,
    }


def bench_backward(L, B=4, H=8, D=64, causal=True, iters=5, dtype="bfloat16"):
    """Train-step row: fwd + FlashAttention-2 backward (the custom VJP's
    two pallas kernels), the op long-context TRAINING actually runs.
    FLOP model: fwd 1x + bwd 2.5x (dq/dk/dv matmuls + softmax tile
    recompute) of the forward's 4*B*H*L^2*D."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import flash_attention

    q, k, v = _make_qkv(L, B, H, D, dtype)

    def loss(a, b, c):
        return flash_attention(a, b, c, causal=causal).astype(
            jnp.float32
        ).sum()

    # chain fwd+bwd steps inside ONE program (summing all three grads into
    # the next query keeps dq AND dk/dv live — nothing DCEs), so dispatch
    # latency amortizes like the forward rows
    chain = 5

    def f(a, b, c):
        def body(_, acc):
            dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(acc, b, c)
            return (dq + dk + dv).astype(a.dtype)

        return jax.lax.fori_loop(0, chain, body, a)

    g = jax.jit(f)
    _sync(g(q, k, v))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = g(q, k, v)
    _sync(out)
    dt_step = (time.perf_counter() - t0) / iters / chain
    flops = 3.5 * 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    return {
        "metric": "flash_attention_train_step_ms",
        "seq_len": L,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "causal": causal,
        "dtype": dtype,
        "fwd_bwd_ms": round(dt_step * 1e3, 3),
        "tflops": round(flops / dt_step / 1e12, 2),
        "mfu_pct_of_v5e_peak": round(
            100.0 * flops / dt_step / _V5E_PEAK_FLOPS[dtype], 1
        ),
    }


def main():
    lens = [int(a) for a in sys.argv[1:]] or [1024, 2048, 4096, 8192, 16384]
    for L in lens:
        for dtype in ("bfloat16", "float32"):
            print(json.dumps(bench_one(L, dtype=dtype)))
    for L in lens:
        if L >= 4096:
            print(json.dumps(bench_backward(L)))


def run_all():
    """All rows as dicts (for BENCH_ALL aggregation)."""
    out = []
    for L in (1024, 2048, 4096, 8192):
        for dtype in ("bfloat16", "float32"):
            out.append(bench_one(L, dtype=dtype))
    # long-context rows where compute dominates dispatch
    out.append(bench_one(16384, B=2, dtype="bfloat16"))
    out.append(bench_one(32768, B=1, dtype="bfloat16"))
    # D=128 rows: the MXU's full contraction width (D=64 caps the QK and
    # PV matmuls at half the systolic array)
    out.append(bench_one(8192, H=4, D=128, dtype="bfloat16"))
    out.append(bench_one(32768, B=1, H=4, D=128, dtype="bfloat16"))
    # training rows: the backward pass is pallas too
    out.append(bench_backward(8192))
    out.append(bench_backward(16384, B=2))
    out.append(bench_backward(16384, B=2, H=4, D=128))
    return out


if __name__ == "__main__":
    main()
