"""Flash-attention microbench: Pallas kernel vs dense XLA attention.

The long-context stack's hot op (the reference has no attention at all —
SURVEY §5 "long context: absent"). Run on the attached backend:

    python benchmarks/attention_bench.py [seq_lens...]

Prints one JSON line per (sequence length, dtype) with ms/call, achieved
TFLOP/s, and MFU — always as % of the 197 TF/s MXU pass rate: under TPU
default matmul precision f32 inputs ride the same bf16 pass the kernel
uses for bf16 (the 49 TF/s figure is the highest-precision mode this
kernel does not request); f32 rows carry a note saying so.

Methodology — CHAIN-LENGTH DIFFERENTIAL: on a tunnel-attached chip, any
single timed dispatch carries 0.1-0.2s of link RTT, and per-iteration
dispatch adds host-side overhead that does NOT run on the chip; dividing
by the iteration count leaks both into "per-call" numbers (round-3 rows
under-reported MFU by ~20 points this way). Here each row times TWO
single-dispatch programs that chain the op n1 and n2 times inside one
``lax.fori_loop`` and reports (T(n2) - T(n1)) / (n2 - n1): the constant
RTT/dispatch terms cancel exactly, leaving pure on-chip time. Chain
lengths are sized so the compute delta is ~1.5s — far above RTT variance
(reps take the min). bf16 inputs run the kernel's matmuls in the MXU's
native bf16 mode (f32 accumulation); dense attention materializes the
[L, L] score matrix, flash streams K/V through VMEM so its memory stays
O(L).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: v5e (v5 lite) public matmul peaks per input dtype
_V5E_PEAK_FLOPS = {"bfloat16": 197e12, "float32": 49e12}


from benchmarks.configs import _sync  # readback barrier (advisory
# block_until_ready on relayed/tunneled PJRT devices — one shared recipe)


def _make_qkv(L, B, H, D, dtype):
    """Shared benchmark inputs: every row (forward, dense, train-step)
    measures the same distribution and dtype handling."""
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    shape = (B, H, L, D)
    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    mk = lambda: jnp.asarray(
        rng.normal(size=shape).astype(np.float32)
    ).astype(dt)
    return mk(), mk(), mk()


def _diff_time(make_chain, args, est_per_call, target_delta_s=1.5, reps=3):
    """(T(n2) - T(n1)) / (n2 - n1) with chains sized so the compute delta
    dominates link noise; min over reps."""
    delta = max(20, int(target_delta_s / max(est_per_call, 1e-6)))
    n1 = max(5, delta // 5)
    n2 = n1 + delta
    f1, f2 = make_chain(n1), make_chain(n2)
    _sync(f1(*args))
    _sync(f2(*args))
    best = None
    for _ in range(reps):
        t0 = time.perf_counter()
        _sync(f1(*args))
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        _sync(f2(*args))
        t2 = time.perf_counter() - t0
        per = (t2 - t1) / (n2 - n1)
        best = per if best is None else min(best, per)
    return best, (n1, n2)


def bench_one(L, B=4, H=8, D=64, causal=True, dtype="bfloat16",
              block_q=None, block_k=None):
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import (
        _best_blocks,
        attention_reference,
        flash_attention,
    )

    q, k, v = _make_qkv(L, B, H, D, dtype)
    bq, bk = _best_blocks(
        jnp.bfloat16 if dtype == "bfloat16" else jnp.float32, D, L
    )
    if block_q:
        bq = block_q
    if block_k:
        bk = block_k

    def flash_chain(n):
        def f(a, b, c):
            def body(_, acc):
                return flash_attention(
                    acc, b, c, causal=causal, block_q=bq, block_k=bk
                ).astype(a.dtype)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    def dense_chain(n):
        # the carry MUST feed the op (as in flash_chain): a loop-invariant
        # body would be hoisted by XLA and the differential would measure
        # nothing
        def f(a, b, c):
            def body(_, acc):
                return attention_reference(acc, b, c, causal=causal).astype(
                    a.dtype
                )

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    flash1 = jax.jit(
        lambda a, b, c: flash_attention(
            a, b, c, causal=causal, block_q=bq, block_k=bk
        )
    )
    dense1 = jax.jit(
        lambda a, b, c: attention_reference(a, b, c, causal=causal)
    )

    out_f = _sync(flash1(q, k, v))
    err = None
    dense_ok = True
    try:
        out_d = _sync(dense1(q, k, v))
        err = float(
            jnp.max(
                jnp.abs(
                    out_f.astype(jnp.float32) - out_d.astype(jnp.float32)
                )
            )
        )
    except Exception:
        dense_ok = False  # [L, L] score matrix no longer fits HBM

    # attention FLOPs: 2 matmuls of [L,L]x[L,D] per head (causal ~half).
    # MFU denominator: on TPU default matmul precision, f32 inputs ride
    # the MXU's bf16 pass too, so the f32 "peak" is the same 197 TF/s
    # pass rate (the 49 TF/s figure is the HIGHEST-precision mode this
    # kernel does not request) — without this the f32 row reports >100%.
    flops = 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    peak = _V5E_PEAK_FLOPS["bfloat16"]
    est = flops / (0.5 * peak)
    tf_, chains = _diff_time(flash_chain, (q, k, v), est)
    td = None
    if dense_ok:
        try:
            # dense does 2x the causal FLOPs (no tile skipping) at lower
            # efficiency; size its chains from a conservative estimate
            td, _ = _diff_time(
                dense_chain, (q, k, v),
                (flops * (2.0 if causal else 1.0)) / (0.25 * peak),
                target_delta_s=1.0, reps=2,
            )
        except Exception:
            td = None
    tflops = flops / tf_ / 1e12
    row = {
        "metric": "flash_attention_ms",
        "seq_len": L,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "causal": causal,
        "dtype": dtype,
        "block_q": bq,
        "block_k": bk,
        "flash_ms": round(tf_ * 1e3, 3),
        "dense_ms": round(td * 1e3, 3) if td else None,
        "speedup_vs_dense": round(td / tf_, 3) if td else None,
        "flash_tflops": round(tflops, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tflops * 1e12 / peak, 1),
        "max_abs_err_vs_dense": round(err, 6) if err is not None else None,
        "chain_lengths": chains,
    }
    if dtype == "float32":
        row["note"] = (
            "f32 inputs ride the MXU's default-precision bf16 pass; MFU "
            "is vs the 197 TF/s pass rate, not the 49 TF/s "
            "highest-precision mode"
        )
    return row


def bench_backward(L, B=4, H=8, D=64, causal=True, dtype="bfloat16",
                   block_q=None, block_k=None):
    """Train-step row: fwd + FlashAttention-2 backward (the custom VJP's
    two pallas kernels), the op long-context TRAINING actually runs.
    FLOP model: fwd 1x + bwd 2.5x (dq/dk/dv matmuls + softmax tile
    recompute) of the forward's 4*B*H*L^2*D."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import _best_blocks, flash_attention

    q, k, v = _make_qkv(L, B, H, D, dtype)
    bq, bk = _best_blocks(
        jnp.bfloat16 if dtype == "bfloat16" else jnp.float32, D, L
    )
    if block_q:
        bq = block_q
    if block_k:
        bk = block_k
    # defaulted tiles let the VJP pick its own tuned backward tiles
    # (_BEST_BLOCKS_BWD); explicit overrides bind fwd AND bwd
    kw = (
        {}
        if (block_q is None and block_k is None)
        else {"block_q": bq, "block_k": bk}
    )

    def loss(a, b, c):
        return flash_attention(
            a, b, c, causal=causal, **kw
        ).astype(jnp.float32).sum()

    def chain(n):
        # summing all three grads into the next query keeps dq AND dk/dv
        # live — nothing DCEs
        def f(a, b, c):
            def body(_, acc):
                dq, dk, dv = jax.grad(loss, argnums=(0, 1, 2))(acc, b, c)
                return (dq + dk + dv).astype(a.dtype)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    flops = 3.5 * 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    peak = _V5E_PEAK_FLOPS["bfloat16"]  # see bench_one's MFU note
    dt_step, chains = _diff_time(chain, (q, k, v), flops / (0.4 * peak))
    return {
        "metric": "flash_attention_train_step_ms",
        "seq_len": L,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "causal": causal,
        "dtype": dtype,
        "block_q": bq,
        "block_k": bk,
        "fwd_bwd_ms": round(dt_step * 1e3, 3),
        "tflops": round(flops / dt_step / 1e12, 2),
        "mfu_pct_of_v5e_peak": round(
            100.0 * flops / dt_step / peak, 1
        ),
        "chain_lengths": chains,
    }


def bench_ring_hop(chunk=32768, hops=4, B=1, H=4, D=128, dtype="bfloat16"):
    """The blockwise ring-attention hop chain at a long-context chunk
    size, on one chip: fold ``hops`` visiting k/v chunks of ``chunk``
    tokens through the carry-mode flash kernel exactly as an
    ``hops``-chip ring runs per chip (hop 0 = causal diagonal, later
    hops = fully-visible past chunks), minus only the ppermute. The
    pre-blockwise implementation materialized a [chunk, chunk] f32 score
    matrix per (batch, head) per hop — at this size that is
    B*H*chunk^2*4 bytes (16 GiB at the defaults), beyond HBM; the
    blockwise path streams tiles, so this row EXISTING is the >HBM
    regression test. The figure of merit is the hop chain's TFLOP/s
    relative to the single-chip flash kernel at the same chunk
    (ring_vs_flash_pct) — the fraction of kernel throughput the ring
    path retains."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import (
        _NEG_BIG,
        _best_blocks,
        _finalize,
        flash_attention,
        flash_carry,
    )

    dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    rng = np.random.default_rng(0)
    mk = lambda: jnp.asarray(
        rng.normal(size=(B * H, chunk, D)).astype(np.float32)
    ).astype(dt)
    qf = mk()
    kcs = [mk() for _ in range(hops)]
    vcs = [mk() for _ in range(hops)]
    bq, bk = _best_blocks(dt, D, chunk)

    def hop_chain(n):
        def f(q, ks, vs):
            def body(_, q_in):
                m = jnp.full((B * H, chunk, 1), _NEG_BIG, jnp.float32)
                l = jnp.zeros((B * H, chunk, 1), jnp.float32)
                acc = jnp.zeros((B * H, chunk, D), jnp.float32)
                # hop 0: the causal diagonal; hops 1..n-1: past chunks
                m, l, acc = flash_carry(
                    q_in, ks[0], vs[0], m, l, acc,
                    causal=True, offset=0, block_q=bq, block_k=bk,
                    interpret=False,
                )
                for h in range(1, hops):
                    m, l, acc = flash_carry(
                        q_in, ks[h], vs[h], m, l, acc,
                        causal=False, offset=0, block_q=bq, block_k=bk,
                        interpret=False,
                    )
                return _finalize(l, acc).astype(q_in.dtype)

            return jax.lax.fori_loop(0, n, body, q)

        return jax.jit(f)

    # hop-chain FLOPs: diagonal is half-masked, the rest are full
    flops = 4.0 * B * H * chunk * chunk * D * (0.5 + (hops - 1))
    peak = _V5E_PEAK_FLOPS["bfloat16"]  # see bench_one's MFU note
    per, chains = _diff_time(
        hop_chain, (qf, kcs, vcs), flops / (0.5 * peak)
    )
    hop_tflops = flops / per / 1e12

    # single-chip flash reference at the same chunk + blocks
    q4 = qf.reshape(B, H, chunk, D)
    k4 = kcs[0].reshape(B, H, chunk, D)
    v4 = vcs[0].reshape(B, H, chunk, D)

    def flash_chain(n):
        def f(a, b, c):
            def body(_, acc):
                return flash_attention(
                    acc, b, c, causal=True, block_q=bq, block_k=bk
                ).astype(a.dtype)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    fl_flops = 4.0 * B * H * chunk * chunk * D * 0.5
    fl_per, _ = _diff_time(
        flash_chain, (q4, k4, v4), fl_flops / (0.5 * peak)
    )
    fl_tflops = fl_flops / fl_per / 1e12
    return {
        "metric": "ring_hop_chain_tflops",
        "chunk_per_chip": chunk,
        "hops": hops,
        "batch": B,
        "heads": H,
        "head_dim": D,
        "dtype": dtype,
        "block_q": bq,
        "block_k": bk,
        "hop_chain_ms": round(per * 1e3, 3),
        "hop_chain_tflops": round(hop_tflops, 2),
        "flash_single_chip_tflops": round(fl_tflops, 2),
        "ring_vs_flash_pct": round(100.0 * hop_tflops / fl_tflops, 1),
        "dense_path_score_bytes": int(B * H * chunk * chunk * 4),
        "chain_lengths": chains,
        "note": "old dense-score ring would allocate "
        f"{B * H * chunk * chunk * 4 / (1 << 30):.0f} GiB of scores per "
        "hop at this size (> HBM); the blockwise path runs it",
    }


def main():
    lens = [int(a) for a in sys.argv[1:]] or [8192, 16384, 32768]
    for L in lens:
        for dtype in ("bfloat16", "float32"):
            print(json.dumps(bench_one(L, dtype=dtype)))
    for L in lens:
        if L >= 8192:
            print(json.dumps(bench_backward(L)))
    print(json.dumps(bench_ring_hop()))


def run_all():
    """All rows as dicts (for BENCH_ALL aggregation)."""
    from benchmarks.flash_sweep_r05 import matmul_ceiling

    out = []
    # hardware ceilings for the attention matmul shapes, measured in the
    # SAME run (the weather control): narrow heads underfill the 128-wide
    # MXU, so D=64 rows are judged against THIS number, not 100%
    ceil64 = matmul_ceiling(64)
    ceil128 = matmul_ceiling(128)
    out.append(ceil64)
    out.append(ceil128)
    # D=128 rows: the MXU's full contraction width
    for L in (8192, 16384, 32768):
        out.append(bench_one(L, B=1, H=4, D=128, dtype="bfloat16"))
    out.append(bench_one(8192, B=1, H=4, D=128, dtype="float32"))
    r64 = bench_one(16384, B=2, D=64, dtype="bfloat16")
    r64["pct_of_measured_d64_ceiling"] = round(
        100.0 * r64["flash_tflops"] / ceil64["tflops"], 1
    )
    out.append(r64)
    # training rows: the backward pass is pallas too (per-kernel tiles,
    # transposed-score dkv — see _BEST_BLOCKS_BWD)
    out.append(bench_backward(16384, B=1, H=4, D=128))
    out.append(bench_backward(32768, B=1, H=4, D=128))
    # the blockwise ring hop chain at the >HBM chunk size
    out.append(bench_ring_hop())
    return out


if __name__ == "__main__":
    main()
