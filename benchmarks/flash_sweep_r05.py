"""Round-5 flash tile sweep: D=64 forward + the backward pair, plus the
hardware ceiling for D=64 attention matmuls.

Three questions, all chain-differential timed (see attention_bench.py):

1. What does the MXU actually deliver for the D=64 attention matmul
   shapes? A [bq,64]x[64,bk] contraction uses 64 of the 128 systolic
   rows and a [bq,bk]x[bk,64] product fills 64 of 128 output lanes —
   both cap at half the 197 TF/s pass rate REGARDLESS of kernel quality.
   The ceiling probe chains exactly those two matmuls (no softmax) and
   measures the cap on this chip; kernel rows then report % of that
   measured ceiling next to absolute MFU.
2. Which (block_q, block_k) wins the D=64 forward? Tiles are half the
   bytes of D=128, so 2048-wide tiles that blew VMEM at D=128 may fit.
3. Which tiles win the backward pair (dq + dkv kernels)? r04 only swept
   the forward; the backward runs a different matmul mix (5 products,
   2 grids) and need not share the forward's optimum. flash_bwd_pair is
   timed directly with fixed lse/delta so tile choice is isolated from
   the VJP plumbing.

Usage: python benchmarks/flash_sweep_r05.py [quick]
Prints one JSON line per point; run on the real chip.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.attention_bench import _diff_time, _make_qkv
from benchmarks.configs import _sync

_PEAK = 197e12


def matmul_ceiling(D, L=8192, bk=1024):
    """Measured TF/s for the attention matmul pair at head_dim D:
    s = q @ k^T ([L_tile,D]x[D,bk]) then o = s @ k ([L_tile,bk]x[bk,D]),
    chained so the carry feeds the next iteration. This is the kernel's
    roofline at this D on this chip — no softmax, no masking, no
    pipeline; pure MXU."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1024, D)).astype(np.float32)).astype(
        jnp.bfloat16
    )
    k = jnp.asarray(rng.normal(size=(bk, D)).astype(np.float32)).astype(
        jnp.bfloat16
    )

    def chain(n):
        def f(a, b):
            def body(_, acc):
                s = jax.lax.dot_general(
                    acc, b, (((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                o = jax.lax.dot_general(
                    s.astype(jnp.bfloat16), b, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                return o.astype(a.dtype)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    flops = 2.0 * 1024 * bk * D * 2  # two products per iteration
    per, chains = _diff_time(chain, (q, k), flops / (0.5 * _PEAK))
    tf = flops / per / 1e12
    return {
        "metric": "attention_matmul_ceiling",
        "head_dim": D,
        "bk": bk,
        "tflops": round(tf, 2),
        "pct_of_v5e_peak": round(100.0 * tf * 1e12 / _PEAK, 1),
        "chain_lengths": chains,
    }


def fwd_point(L, D, bq, bk, B=2, H=8, causal=True):
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import flash_attention

    q, k, v = _make_qkv(L, B, H, D, "bfloat16")

    def chain(n):
        def f(a, b, c):
            def body(_, acc):
                return flash_attention(
                    acc, b, c, causal=causal, block_q=bq, block_k=bk
                ).astype(a.dtype)

            return jax.lax.fori_loop(0, n, body, a)

        return jax.jit(f)

    flops = 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    try:
        per, chains = _diff_time(chain, (q, k, v), flops / (0.4 * _PEAK))
    except Exception as e:
        return {
            "metric": "flash_fwd_sweep", "seq_len": L, "head_dim": D,
            "block_q": bq, "block_k": bk, "error": str(e)[:200],
        }
    tf = flops / per / 1e12
    return {
        "metric": "flash_fwd_sweep",
        "seq_len": L, "batch": B, "heads": H, "head_dim": D,
        "causal": causal, "dtype": "bfloat16",
        "block_q": bq, "block_k": bk,
        "ms": round(per * 1e3, 3),
        "tflops": round(tf, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tf * 1e12 / _PEAK, 1),
        "chain_lengths": chains,
    }


def bwd_point(L, D, bq, bk, B=2, H=8, causal=True):
    """Time flash_bwd_pair alone (both kernels, one call each per
    iteration) with a fixed realistic lse/delta; the chain feeds
    dq+dk+dv back as q so nothing DCEs."""
    import jax
    import jax.numpy as jnp

    from tensorframes_tpu.ops.attention import (
        _flash_forward,
        flash_bwd_pair,
    )

    q, k, v = _make_qkv(L, B, H, D, "bfloat16")
    bh = B * H
    qf, kf, vf = (a.reshape(bh, L, D) for a in (q, k, v))
    # one real forward (at the default tiles) for a consistent lse
    o, lse = _flash_forward(q, k, v, causal, 1024, 1024, False)
    dof = jnp.ones((bh, L, D), jnp.bfloat16)
    delta = (
        dof.astype(jnp.float32) * o.reshape(bh, L, D).astype(jnp.float32)
    ).sum(axis=-1, keepdims=True)
    lse = jax.lax.stop_gradient(lse)

    def chain(n):
        def f(qq, kk, vv):
            def body(_, acc):
                dq, dk, dv = flash_bwd_pair(
                    acc, kk, vv, dof, lse, delta,
                    causal=causal, offset=0, block_q=bq, block_k=bk,
                    interpret=False,
                    out_dtypes=(jnp.bfloat16,) * 3,
                )
                return (dq + dk + dv).astype(acc.dtype)

            return jax.lax.fori_loop(0, n, body, qq)

        return jax.jit(f)

    # bwd pair: 2.5x the forward's matmul volume
    flops = 2.5 * 4.0 * B * H * L * L * D * (0.5 if causal else 1.0)
    try:
        per, chains = _diff_time(chain, (qf, kf, vf), flops / (0.35 * _PEAK))
    except Exception as e:
        return {
            "metric": "flash_bwd_sweep", "seq_len": L, "head_dim": D,
            "block_q": bq, "block_k": bk, "error": str(e)[:200],
        }
    tf = flops / per / 1e12
    return {
        "metric": "flash_bwd_sweep",
        "seq_len": L, "batch": B, "heads": H, "head_dim": D,
        "causal": causal, "dtype": "bfloat16",
        "block_q": bq, "block_k": bk,
        "ms": round(per * 1e3, 3),
        "tflops": round(tf, 2),
        "mfu_pct_of_v5e_peak": round(100.0 * tf * 1e12 / _PEAK, 1),
        "chain_lengths": chains,
    }


def main():
    quick = "quick" in sys.argv[1:]
    rows = []

    def emit(r):
        rows.append(r)
        print(json.dumps(r), flush=True)

    # hardware ceilings first: what the matmul shapes allow at all
    emit(matmul_ceiling(64))
    emit(matmul_ceiling(128))

    # D=64 forward sweep (L=16384 = the r04 28.7% row's regime)
    L64 = 16384
    combos64 = [
        (1024, 1024),  # r04 incumbent
        (1024, 2048),
        (2048, 1024),
        (2048, 2048),
        (512, 2048),
        (1024, 4096),
    ]
    if quick:
        combos64 = combos64[:3]
    for bq, bk in combos64:
        emit(fwd_point(L64, 64, bq, bk))

    # backward sweep at D=128 (the train-step rows' regime)
    L128 = 16384
    combos_bwd = [
        (1024, 1024),  # incumbent (shared with fwd)
        (512, 1024),
        (1024, 512),
        (512, 2048),
        (2048, 512),
        (512, 512),
    ]
    if quick:
        combos_bwd = combos_bwd[:3]
    for bq, bk in combos_bwd:
        emit(bwd_point(L128, 128, bq, bk, B=1, H=4))

    # backward at D=64 too (the D=64 train-step target)
    for bq, bk in ([(1024, 1024), (2048, 1024), (1024, 2048)] if not quick
                   else [(1024, 1024)]):
        emit(bwd_point(L64, 64, bq, bk))

    with open(
        os.path.join(os.path.dirname(__file__), "..", "flash_sweep_r05.json"),
        "w",
    ) as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
