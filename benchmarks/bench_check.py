"""Bench perf-regression gate (``make bench-check``).

The r01→r05 BENCH files record the bench *trajectory*, but nothing has
ever enforced it: a PR that quietly halved ``map_rows`` throughput
would sail through tier-1 (correctness) and only show up rounds later
when someone read the JSON. This gate makes the trajectory
enforceable: it runs a fresh ``bench.py map_rows`` and
``bench.py decode_serve`` under the PINNED environment recorded in
``BASELINE.json["bench_gate"]`` (same workload shape as the baseline
measurement — smoke-sized so the gate stays minutes, not tens of
minutes), compares each headline metric against its recorded baseline,
and exits non-zero when any falls more than ``tolerance_pct`` below
it.

Tolerance is deliberately generous (default 30%): these are wall-clock
benches on shared hosts, and the gate exists to catch *structural*
regressions (a lost fast path, an accidental sync, a double upload),
not scheduler noise. Precedence, loosest binding last:

1. ``TFT_BENCH_TOLERANCE_PCT`` (env — a one-run operator override for
   EVERY metric);
2. ``bench_gate.tolerances[<metric>]`` (per-metric override recorded
   in BASELINE.json — for metrics with measured machine-to-machine
   variance wider than the global band, e.g. ``map_rows`` throughput,
   which swings with filesystem cache state far more than the
   decode-bound serve bench); preserved across ``--update``;
3. ``bench_gate.tolerance_pct`` (the recorded global band);
4. the built-in 30% default.

Usage::

    python benchmarks/bench_check.py            # check against baseline
    python benchmarks/bench_check.py --update   # re-measure and record

``--update`` reruns both benches and rewrites the ``bench_gate`` block
(do this when a PR legitimately moves the numbers — the diff then
documents the move).

**Relative A/B mode** (``TFT_BENCH_GATE_RELATIVE=1``): recorded
absolute numbers go stale the moment the gate runs on a different
machine class than the one that recorded them (the PR 16 machine-drift
incident: ``map_rows_journaled`` read −55% at pristine HEAD). In
relative mode the gate ignores the recorded values entirely and runs
each config TWICE in the same invocation on the same box: leg A under
the pinned feature-off environment, leg B under the same pins except
any ``TFT_*`` variable the caller set explicitly (so
``TFT_BENCH_GATE_RELATIVE=1 TFT_BENCH_TIERS=1 make bench-check``
measures feature-off vs feature-on back to back). Leg B must land
within the same tolerance band of leg A. With no caller overrides the
two legs are identical and the run measures pure machine noise — a
cheap way to calibrate ``tolerance_pct`` for a new host class.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BASELINE = os.path.join(ROOT, "BASELINE.json")

#: the gated bench configs: bench.py argv -> the headline JSON "metric"
#: name recorded/compared (each bench prints exactly one JSON line)
CONFIGS = (
    ("map_rows", "map_rows_journaled_rows_per_sec"),
    ("decode_serve", "decode_serve_tokens_per_sec"),
)

#: the pinned workload shape: smoke-sized axes so the whole gate runs
#: in minutes; recorded alongside the numbers so check and baseline
#: always measure the same thing
GATE_ENV = {
    "TFT_BENCH_ROWS": "120000",
    "TFT_BENCH_JOB_WORKERS": "",  # skip the K-subprocess drain axis
    "TFT_BENCH_REPLICAS": "1",
    "TFT_BENCH_PROMPT_LENS": "32",
    # the tensor-parallel axis (TFT_BENCH_TP, ISSUE 14) pinned OFF:
    # mesh engines compile three extra shard_map programs per degree —
    # trajectory material for `make bench-serve`, not gate material
    "TFT_BENCH_TP": "",
    # the speculative-decoding axis (TFT_BENCH_SPEC, ISSUE 15) pinned
    # OFF for the same reason: the gated headline measures the
    # unchanged non-speculative (k=0) decode path; BASELINE.json notes
    # the pin
    "TFT_BENCH_SPEC": "",
    # the autotuner kill switch, pinned OFF: tuning trials (and a
    # winner that drifts between baseline recording and a later check)
    # must not pollute the regression baseline — the gate measures the
    # STATIC configuration, `make bench-autotune` measures tuning
    "TFT_TUNE": "0",
    # the multi-tenant QoS axis (TFT_BENCH_TENANTS, ISSUE 17) pinned
    # OFF: the gated headline measures the plane-off zero-cost default
    # (also the byte-identity baseline) — `make bench-serve` can opt in
    "TFT_BENCH_TENANTS": "",
    # fleet-telemetry export (ISSUE 16) pinned OFF: periodic snapshot
    # writes from an operator's ambient TFT_TELEMETRY_DIR must not
    # taint the gated numbers — `make bench-serve` measures the export
    # axis explicitly
    "TFT_TELEMETRY_DIR": "",
    # the disaggregated-tier axis (TFT_BENCH_TIERS, ISSUE 20) pinned
    # OFF: the gated headline measures the untiered single-engine
    # decode path; the tiered-vs-monolithic A/B is an explicit opt-in
    # (`make bench-serve` / TFT_BENCH_GATE_RELATIVE legs)
    "TFT_BENCH_TIERS": "",
    "JAX_PLATFORMS": os.environ.get("JAX_PLATFORMS", ""),
}

DEFAULT_TOLERANCE_PCT = 30.0


def _run_bench(config: str, env_overrides: dict) -> dict:
    """Run one bench config and return its (last) JSON line."""
    env = dict(os.environ)
    for k, v in env_overrides.items():
        if v == "" and not k.startswith("TFT_"):
            continue  # unset non-TFT passthroughs (JAX_PLATFORMS) stay unset
        # pinned-empty TFT_ vars are set to "" UNCONDITIONALLY: bench.py
        # treats empty as "axis off", and on a clean environment the
        # workers axis would otherwise run its 1/2/4-subprocess default
        # inside the smoke-sized gate
        env[k] = v
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "bench.py"), config],
        capture_output=True, text=True, env=env, cwd=ROOT,
    )
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout[-2000:] + proc.stderr[-4000:])
        raise SystemExit(
            f"bench.py {config} failed with rc={proc.returncode}"
        )
    last = None
    for line in proc.stdout.splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if "metric" in parsed:
                last = parsed
    if last is None:
        sys.stderr.write(proc.stdout[-2000:])
        raise SystemExit(f"bench.py {config} printed no JSON result line")
    return last


def _load_baseline() -> dict:
    with open(BASELINE) as f:
        return json.load(f)


def _tolerance_for(metric: str, gate: dict) -> float:
    """Resolve one metric's tolerance band (percent below baseline
    that still passes): env override > per-metric ``tolerances`` entry
    > global ``tolerance_pct`` > default."""
    env_tol = os.environ.get("TFT_BENCH_TOLERANCE_PCT", "")
    if env_tol:
        return float(env_tol)
    per_metric = gate.get("tolerances") or {}
    if metric in per_metric:
        return float(per_metric[metric])
    return float(gate.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))


def update() -> int:
    base = _load_baseline()
    prior = base.get("bench_gate") or {}
    gate = {
        "comment": (
            "perf-regression gate for `make bench-check`: headline bench "
            "values measured under `env`; a fresh run more than "
            "`tolerance_pct` below any baseline fails the gate. "
            "Re-record with `python benchmarks/bench_check.py --update`."
        ),
        "tolerance_pct": DEFAULT_TOLERANCE_PCT,
        "env": {k: v for k, v in GATE_ENV.items() if k != "JAX_PLATFORMS"},
        "metrics": {},
    }
    # per-metric bands survive a re-record: they encode each metric's
    # MEASURED variance on this class of host, not the baseline values
    if prior.get("tolerances"):
        gate["tolerances"] = dict(prior["tolerances"])
    for config, metric in CONFIGS:
        print(f"[bench-check] measuring {config} ...", flush=True)
        result = _run_bench(config, GATE_ENV)
        if result["metric"] != metric:
            raise SystemExit(
                f"bench.py {config} reported metric "
                f"{result['metric']!r}; expected {metric!r}"
            )
        gate["metrics"][metric] = {
            "value": result["value"],
            "unit": result.get("unit", ""),
            "config": config,
        }
        print(f"[bench-check]   {metric} = {result['value']}", flush=True)
    base["bench_gate"] = gate
    with open(BASELINE, "w") as f:
        json.dump(base, f, indent=2)
        f.write("\n")
    print(f"[bench-check] baseline recorded in {BASELINE}")
    return 0


def check_relative() -> int:
    """Same-run A/B gate (``TFT_BENCH_GATE_RELATIVE=1``): leg A under
    the pinned feature-off env, leg B with the caller's explicit
    ``TFT_*`` overrides layered on top, compared within the recorded
    tolerance band. No dependence on recorded absolute numbers — both
    legs run on this box, this invocation."""
    base = _load_baseline()
    gate = base.get("bench_gate") or {}
    env_a = dict(GATE_ENV)
    env_a.update(gate.get("env", {}))
    env_b = dict(env_a)
    overrides = {
        k: os.environ[k]
        for k in env_a
        if k.startswith("TFT_") and k in os.environ
    }
    env_b.update(overrides)
    print(
        "[bench-check] relative A/B mode: leg B overrides "
        f"{overrides or '(none — measuring machine noise)'}",
        flush=True,
    )
    failures = []
    for config, metric in CONFIGS:
        tol = _tolerance_for(metric, gate)
        print(f"[bench-check] running {config} (leg A, pinned) ...",
              flush=True)
        ref = _run_bench(config, env_a)
        print(f"[bench-check] running {config} (leg B, overrides) ...",
              flush=True)
        result = _run_bench(config, env_b)
        fresh, baseline = float(result["value"]), float(ref["value"])
        floor = baseline * (1.0 - tol / 100.0)
        delta_pct = (fresh - baseline) / baseline * 100.0
        verdict = "ok" if fresh >= floor else "REGRESSION"
        print(
            f"[bench-check]   {metric}: B={fresh:.1f} A={baseline:.1f} "
            f"({delta_pct:+.1f}%, floor {floor:.1f} at -{tol:.0f}%) "
            f"-> {verdict}",
            flush=True,
        )
        if fresh < floor:
            failures.append((metric, fresh, baseline, delta_pct))
    if failures:
        sys.stderr.write(
            "bench-check (relative) FAILED: "
            + "; ".join(
                f"{m} leg B {f:.1f} vs leg A {b:.1f} ({d:+.1f}%)"
                for m, f, b, d in failures
            )
            + "\n"
        )
        return 1
    print("[bench-check] relative A/B within tolerance")
    return 0


def check() -> int:
    if os.environ.get("TFT_BENCH_GATE_RELATIVE", "").strip() not in (
        "", "0",
    ):
        return check_relative()
    base = _load_baseline()
    gate = base.get("bench_gate")
    if not gate or not gate.get("metrics"):
        sys.stderr.write(
            "bench-check: no bench_gate block in BASELINE.json — record "
            "one with `python benchmarks/bench_check.py --update`\n"
        )
        return 2
    env = dict(GATE_ENV)
    env.update(gate.get("env", {}))
    failures = []
    for metric, entry in gate["metrics"].items():
        config = entry["config"]
        tol = _tolerance_for(metric, gate)
        print(f"[bench-check] running {config} ...", flush=True)
        result = _run_bench(config, env)
        fresh, baseline = float(result["value"]), float(entry["value"])
        floor = baseline * (1.0 - tol / 100.0)
        delta_pct = (fresh - baseline) / baseline * 100.0
        verdict = "ok" if fresh >= floor else "REGRESSION"
        print(
            f"[bench-check]   {metric}: fresh={fresh:.1f} "
            f"baseline={baseline:.1f} ({delta_pct:+.1f}%, floor "
            f"{floor:.1f} at -{tol:.0f}%) -> {verdict}",
            flush=True,
        )
        if fresh < floor:
            failures.append((metric, fresh, baseline, delta_pct))
    if failures:
        sys.stderr.write(
            "bench-check FAILED: "
            + "; ".join(
                f"{m} {f:.1f} vs baseline {b:.1f} ({d:+.1f}%)"
                for m, f, b, d in failures
            )
            + "\n"
        )
        return 1
    print("[bench-check] all metrics within tolerance")
    return 0


if __name__ == "__main__":
    sys.exit(update() if "--update" in sys.argv[1:] else check())
