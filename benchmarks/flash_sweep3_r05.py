"""Round-5 sweep, part 3: measure the rewritten kernels on chip.

After part 2's findings — the dkv kernel's axis-0 contractions cost
relayouts (73% of ceiling vs the dq kernel's 93%), and a third of the
D=64 forward's time is per-tile fixed cost — the dkv kernel was
rewritten in the transposed-score formulation and a dual-head D=64
forward landed. This sweep validates both on hardware and refreshes the
train-step rows.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.attention_bench import bench_backward, bench_one
from benchmarks.flash_sweep2_r05 import dkv_kernel_point
from benchmarks.flash_sweep_r05 import bwd_point, fwd_point


def main():
    rows = []

    def emit(r):
        rows.append(r)
        print(json.dumps(r), flush=True)

    L = 16384
    # dual-head D=64 forward (same tile candidates as the incumbent)
    emit(fwd_point(L, 64, 1024, 1024))
    emit(fwd_point(L, 64, 1024, 2048))
    emit(fwd_point(32768, 64, 1024, 1024, B=1, H=8))

    # transposed-score dkv kernel, D=128
    for bq, bk in [(1024, 1024), (512, 2048), (512, 1024), (1024, 2048)]:
        emit(dkv_kernel_point(L, 128, bq, bk))

    # backward pair + full train-step rows with the new kernels
    emit(bwd_point(L, 128, 1024, 1024, B=1, H=4))
    emit(bench_backward(L, B=1, H=4, D=128))
    emit(bench_backward(32768, B=1, H=4, D=128))
    emit(bench_backward(L, B=2, H=8, D=64))

    with open(
        os.path.join(os.path.dirname(__file__), "..",
                     "flash_sweep3_r05.json"),
        "w",
    ) as f:
        json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()
