# Developer entry points (the reference ships sbt + python/run-tests.sh,
# /root/reference/project/Build.scala:8-127, python/run-tests.sh:28-117).

# `verify` uses bash arrays/PIPESTATUS; make the whole file consistent
SHELL := /bin/bash

PY ?= python

.PHONY: test test-failfast test-fast test-attn test-chaos test-distjobs test-durability test-elastic test-fleet test-ha test-multihost test-obs test-obsfleet test-plan test-spec test-tenancy test-tiers test-tp test-tune soak verify bench bench-serve bench-attn bench-jobs bench-ingest bench-pipeline bench-autotune bench-check bench-check-update bench-all bench-attention dryrun install lint

install:
	$(PY) -m pip install -e . --no-build-isolation

# full suite on a virtual 8-device CPU mesh (conftest forces the backend).
# NO -x: merge CI must report EVERY failure, not stop at the first and
# hide the rest (use test-failfast for the edit loop)
test:
	$(PY) -m pytest tests/ -q

# stop at the first failure — the local edit-debug convenience
test-failfast:
	$(PY) -m pytest tests/ -x -q

# the edit-test loop tier: everything not marked slow, parallelized;
# target < 3 min (the slow marks carry the multi-process / training
# heavyweights — CI runs `test-fast` on PRs and `test` on merges).
# pytest-xdist is enabled by its -n flag alone (`-p xdist` is not how the
# plugin is selected and broke on installs that auto-load it).
test-fast:
	$(PY) -m pytest tests/ -q -m "not slow" -n 4

# the EXACT ROADMAP tier-1 command (what the driver measures after each
# PR) — run this before shipping so local numbers match CI's
verify:
	set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=$${PIPESTATUS[0]}; echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); exit $$rc

# the paged-attention suite (ops ragged kernel vs the gather oracle,
# prefix cache, chunked prefill) — fast, CPU interpret mode, part of
# tier-1; run alone when iterating on the kernel or the cache
test-attn:
	$(PY) -m pytest tests/ -q -m attn

# the seeded fault-injection suite (utils/chaos.py + the serving
# supervisor under chaos) — fast, CPU-only, deterministic; part of
# tier-1, runnable alone when iterating on failure handling
test-chaos:
	$(PY) -m pytest tests/ -q -m chaos

# the durable batch-job suite (engine/jobs.py: journal, crash-resume,
# quarantine) — fast, CPU-only, deterministic; part of tier-1
test-durability:
	$(PY) -m pytest tests/ -q -m durability

# the distributed-job suite (engine/dist_jobs.py: multi-worker block
# leasing, heartbeats, dead-worker reclamation, write fencing) — incl.
# the real 3-subprocess kill -9 soak; CPU-only, deterministic, tier-1
test-distjobs:
	$(PY) -m pytest tests/ -q -m distjobs

# the serving-fleet suite (serve/fleet.py: replicated engines behind the
# health-gated router, failover + request replay) — the fast tests are
# tier-1; the multi-replica chaos soak is marked slow and runs here too
test-fleet:
	$(PY) -m pytest tests/ -q -m fleet

# the observability suite (tensorframes_tpu/obs: metrics registry
# semantics, distributed tracing end-to-end, flight recorder + debug
# bundles, /statusz, the docs<->code drift gate) — CPU-only,
# deterministic, tier-1
test-obs:
	$(PY) -m pytest tests/ -q -m obs

# the fleet-telemetry suite (obs/export.py + obs/aggregate.py +
# obs/drift.py + obs/requests.py: cross-process snapshot federation
# incl. the 2-subprocess kill -9 staleness drill, merged-quantile
# oracles, drift shift/recovery, per-request cost attribution) —
# CPU-only, deterministic, tier-1
test-obsfleet:
	$(PY) -m pytest tests/ -q -m obsfleet

# the logical-plan suite (engine/plan.py: lazy op recording, map
# fusion, column pruning, reduction hoisting — incl. the per-pass
# byte-identity matrix and the journaled fused-pipeline kill+resume)
# — fast, CPU-only, deterministic; part of tier-1
test-plan:
	$(PY) -m pytest tests/ -q -m plan

# the self-tuning suite (tensorframes_tpu/tune: store durability incl.
# the 2-subprocess concurrent-write + kill -9 drills, learned-ranker
# pruning, per-surface byte-identity vs TFT_TUNE=0, persistence
# round-trip) — fast, CPU-only, deterministic; part of tier-1
test-tune:
	$(PY) -m pytest tests/ -q -m tune

# the speculative-decoding suite (serve/engine.py draft + verify step
# programs, the draft KV page group, exact-match acceptance): the
# byte-identity matrix vs solo decode — greedy/seeded, chunked
# prefill, prefix cache, preemption, restart, chaos at serve.verify,
# fleet failover across different k — plus the adaptive-k controller.
# Fast, CPU-only, deterministic; part of tier-1
test-spec:
	$(PY) -m pytest tests/ -q -m spec

# the multi-tenant QoS suite (serve/tenancy.py: quotas + token-bucket
# rate limits, priority admission/preemption/eviction, SLO-actuated
# shedding/deprioritization, 429 + /admin/tenants, the 2-replica
# fairness soak with byte-identity vs solo) — fast, CPU-only,
# deterministic; part of tier-1
test-tenancy:
	$(PY) -m pytest tests/ -q -m tenancy

# the tensor-parallel serving suite (serve/tp.py: mesh-sharded step
# programs + sharded KV PagePool — the TP=1/2/4 byte-identity matrix,
# capacity scaling, hetero-TP fleet failover). Part of tier-1 (conftest
# provisions the simulated mesh); this target also sets the
# host-device-count env itself so it works OUTSIDE pytest's conftest,
# e.g. under a bare `python -m pytest tests/test_serve_tp.py::...`
test-tp:
	env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" $(PY) -m pytest tests/ -q -m tp

# the elastic multi-host fleet suite (serve/membership.py: lease-based
# membership + epoch fencing, remote replicas over HTTP with failover
# byte-identity, /readyz + SIGTERM drain, rolling restart / hot weight
# swap with probe-gated re-admission) — the fast tests are tier-1; the
# 3-subprocess kill -9 + wedge acceptance soak is marked slow and runs
# here too
test-elastic:
	$(PY) -m pytest tests/ -q -m elastic

# the router high-availability suite (serve/router_ha.py: request WAL,
# resumable streams, fenced standby takeover, lease clock edges, local
# subprocess provisioner); the 2-router + 3-member kill -9 takeover
# acceptance soak is marked slow and runs here too
test-ha:
	$(PY) -m pytest tests/ -q -m ha

# the disaggregated-tier suite (serve/tiers.py + the fleet's tier-aware
# router: live KV-page migration byte-identity matrix — greedy/seeded ×
# TP degree × speculative × prefix-cache donors — first-token handoff,
# pool-pressure rebalance vs preemption, chaos at tier.handoff /
# fleet.migrate; incl. the slow-marked kill -9 mid-migration soak)
test-tiers:
	$(PY) -m pytest tests/ -q -m tiers

# every multi-process fault-tolerance soak in one command: the elastic
# membership, fleet failover, chaos, and router-HA suites INCLUDING
# their slow-marked subprocess drills — the pre-release confidence run
# (budget ~15 min; tier-1 stays the fast gate)
soak:
	$(PY) -m pytest tests/ -q -m "elastic or fleet or chaos or ha or tiers"

# just the real 2-process distributed suite
test-multihost:
	$(PY) -m pytest tests/test_multihost.py -q

# headline metric (one JSON line; targets the attached TPU)
bench:
	$(PY) bench.py

# serving trajectory: tokens/s + inter-token latency at 1/4/16 concurrency,
# the fleet's aggregate tokens/s at 1/2/4 replicas, the
# tensor-parallel axis — one replica spanning TP=1/2/4 simulated chips
# with tok/s + aggregate KV pages per degree — and the speculative-
# decoding axis (TFT_BENCH_SPEC, default 0,2,4: draft length k with
# tok/s, inter-token p50/p99 and acceptance rate on a repeated-suffix
# workload). (TFT_BENCH_REPLICAS=1,2, TFT_BENCH_TP=1,2 and
# TFT_BENCH_SPEC=0,4 shrink axes for smoke runs; an empty value
# disables that axis entirely)
bench-serve:
	$(PY) bench.py decode_serve

# decode paged-KV read microbench: gather vs the fused ragged
# paged-attention kernel — GB/s + tokens/s, one JSON line
# (TFT_BENCH_ATTN_SLOTS / _PAGES / _PAGE_SIZE shape the batch)
bench-attn:
	$(PY) bench.py paged_attn

# durable-job overhead: map_rows with the journal on vs off, plus the
# K-subprocess distributed-drain workers axis (TFT_BENCH_JOB_WORKERS,
# default 1,2,4; empty disables) — one JSON line
bench-jobs:
	$(PY) bench.py map_rows

# streaming ingest/egress: monolithic vs chunked-overlapped h2d/d2h GB/s
# on the 3.1 GB r05 scoring column, plus cold ingest→upload→score wall
# clock (one JSON line; TFT_BENCH_INGEST_ROWS shrinks it for smoke runs)
bench-ingest:
	$(PY) bench.py ingest

# logical-plan pipeline: a 3-op map chain + reduce, fused vs
# op-at-a-time — rows/s, framework overhead per logical op, and the
# h2d byte delta from column pruning (one JSON line;
# TFT_BENCH_PIPELINE_ROWS / _OPS shrink it for smoke runs)
bench-pipeline:
	$(PY) bench.py pipeline

# the self-tuning layer: cold-tune wall (trials included) vs
# cached-tune wall (persisted winners, zero trials), plus
# tuned-vs-static rows/s and tok/s on the map_rows / decode_serve
# smoke shapes (one JSON line; TFT_BENCH_ROWS and
# TFT_BENCH_TUNE_BUDGET_S shrink it)
bench-autotune:
	$(PY) bench.py autotune

# the perf-regression gate: fresh smoke-sized `bench.py map_rows` +
# `decode_serve` runs compared against BASELINE.json's bench_gate block
# within tolerance (default 30%; TFT_BENCH_TOLERANCE_PCT overrides) —
# non-zero exit on regression, so the bench trajectory is enforceable
# instead of advisory. Re-record after a legitimate perf change with
# bench-check-update (the diff then documents the move).
bench-check:
	$(PY) benchmarks/bench_check.py

bench-check-update:
	$(PY) benchmarks/bench_check.py --update

# all BASELINE configs + extras
bench-all:
	$(PY) benchmarks/run_all.py

bench-attention:
	$(PY) benchmarks/attention_bench.py

# the driver's multi-chip contract check (self-provisions 8 CPU devices)
dryrun:
	$(PY) -c "import __graft_entry__ as g; g.dryrun_multichip(8); print('DRYRUN OK')"

# compile-check every module (no external linter in this environment)
lint:
	$(PY) -m compileall -q tensorframes_tpu benchmarks examples tests bench.py __graft_entry__.py
