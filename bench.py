"""Benchmark: rows/sec/chip on ``map_blocks`` (BASELINE.json primary metric).

Workload: MNIST-logistic-regression scoring via ``map_blocks`` on a frozen
model — BASELINE config 3, the reference's flagship scoring path (variable
freezing + per-partition Session.run, reference ``core.py:41-55``). Here the
frozen model is a captured XLA program with parameters as constants.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the same scoring computed by numpy on the host CPU of
this machine — a stand-in for the reference's CPU execution path.

Prints exactly one JSON line.
"""

import json
import time

import numpy as np


def _numpy_baseline(x, w, b, iters=3):
    """CPU scoring throughput (argmax(x @ w + b))."""
    t0 = time.perf_counter()
    for _ in range(iters):
        np.argmax(x @ w + b, axis=-1)
    dt = (time.perf_counter() - t0) / iters
    return x.shape[0] / dt


def main():
    import jax

    import tensorframes_tpu as tft
    from tensorframes_tpu.models import MLPClassifier

    # 1M rows: the per-dispatch latency of the TPU link amortizes across a
    # large block, which is the intended usage pattern for block scoring
    n_rows, n_features, n_classes = 1_000_000, 784, 10
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, n_features)).astype(np.float32)

    clf = MLPClassifier.init(0, [n_features, n_classes])
    w, b = clf.params[0]["w"], clf.params[0]["b"]

    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    def run():
        scored = clf.score_frame(df, "features")
        # force full materialization (device compute + host transfer)
        return scored.column_block("prediction")

    preds = run()  # warmup: compile + execute
    ref = np.argmax(x @ w + b, axis=-1)
    # TPU MXU matmuls run bf16 by default, so near-tie argmaxes may flip vs
    # the f32 numpy oracle; 99% agreement is the sanity bar, not bit parity
    assert (np.asarray(preds) == ref).mean() > 0.99, "scoring mismatch"

    iters = 5
    t0 = time.perf_counter()
    for _ in range(iters):
        run()
    dt = (time.perf_counter() - t0) / iters
    rows_per_sec = n_rows / dt

    cpu_rows_per_sec = _numpy_baseline(x, w, b)

    print(
        json.dumps(
            {
                "metric": "map_blocks_scoring_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / cpu_rows_per_sec, 3),
                "detail": {
                    "workload": f"MNIST-LR scoring, {n_rows} x {n_features} f32 (BASELINE config 3)",
                    "device": str(jax.devices()[0]),
                    "cpu_numpy_rows_per_sec": round(cpu_rows_per_sec, 1),
                    "seconds_per_pass": round(dt, 4),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
