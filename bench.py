"""Benchmark: rows/sec/chip on ``map_blocks`` (BASELINE.json primary metric).

Workload: MNIST-logistic-regression scoring via ``map_blocks`` on a frozen
model — BASELINE config 3, the reference's flagship scoring path (variable
freezing + per-partition Session.run, reference ``core.py:41-55``). Here the
frozen model is a captured XLA program with parameters as constants.

Measurement modes (all through the full engine — capture, validation,
schema analysis, lazy frame, thunk, dispatch):

- **pipeline** (primary): N chained passes with device-resident outputs —
  each pass is exactly one engine dispatch, the way chained
  ``map_blocks``/``reduce_blocks`` pipelines actually run; every pass's
  result column stays in HBM and ONE final fold + host fetch forces the
  whole chain (per-pass check dispatches would charge harness overhead to
  the engine). Footprint: all N output columns stay live until the fold
  (~4 MB × 100 here); size iters to the output column, not just patience.
- **host_pipelined**: every pass's full output is fetched to the host, with
  ``copy_to_host_async`` overlapping transfers against compute.
- **host_sequential**: fetch each pass synchronously (the round-1 mode);
  on a tunneled dev TPU this is dominated by the ~100ms+ fetch RTT, which
  is environment latency, not framework or chip time.

``vs_baseline``: the reference publishes no numbers (BASELINE.md), so the
comparison point is the same scoring computed by numpy on the host CPU of
this machine — a stand-in for the reference's CPU execution path.

Prints exactly one JSON line.

``python bench.py decode_serve`` instead benchmarks the continuous-
batching generation engine (``tensorframes_tpu/serve``): tokens/sec,
p50/p99 INTER-TOKEN latency and p50/p99 TIME-TO-FIRST-TOKEN at 1, 4 and
16 concurrent requests, a prompt-length axis (``TFT_BENCH_PROMPT_LENS``),
the gather-vs-fused decode-read axis, and a shared-prefix workload with
the prefix cache off vs on (hit rate included) — the serving trajectory
the ROADMAP's heavy-traffic target is measured by. Also exactly one
JSON line.

``python bench.py paged_attn`` (``make bench-attn``) microbenches the
decode paged-KV read alone: gather ``paged_attention`` vs the fused
``ragged_paged_attention`` kernel on one ragged batch — GB/s and
tokens/s per impl, one JSON line.

``python bench.py ingest`` (``make bench-ingest``) benchmarks the
streaming transfer layer (``tensorframes_tpu/frame/transfer.py``):
monolithic vs chunked-overlapped h2d/d2h GB/s on the same 3.1 GB
column, plus the cold ingest→upload→score wall clock. Also exactly one
JSON line.

``python bench.py pipeline`` (``make bench-pipeline``) benchmarks the
lazy logical-plan layer (``tensorframes_tpu/engine/plan.py``): a 3-op
map chain + reduce fused vs op-at-a-time — rows/s, framework overhead
per logical op, and the h2d byte delta from column pruning (a decoy
column bound only by a dead op must never cross the link). Also
exactly one JSON line; ``TFT_BENCH_PIPELINE_ROWS`` / ``_OPS`` shrink
it for smoke runs.

``python bench.py autotune`` (``make bench-autotune``) benchmarks the
self-tuning layer (``tensorframes_tpu/tune``): cold-tune wall (first
online pass, micro-benchmark trials included) vs cached-tune wall (a
fresh process resolving the persisted winners with zero trials), plus
tuned-vs-static rows/s and tok/s on the map_rows and decode_serve
smoke shapes. Also exactly one JSON line.

``python bench.py map_rows`` (``make bench-jobs``) benchmarks the
durable batch-job layer and its distributed drain: journal on/off
overhead, plus a K-subprocess workers axis (``TFT_BENCH_JOB_WORKERS``,
default ``1,2,4``) draining one manifest through ``engine/dist_jobs.py``
block leasing — aggregate rows/s and scaling efficiency per K.
In detail, ``bench.py map_rows`` benchmarks the durable batch-job layer
(``tensorframes_tpu/engine/jobs.py``): the same ``map_rows`` job with
the journal **on** vs **off** (identical block loop; the delta is the
npz spooling + ledger appends on the background journal thread),
reporting rows/s for both and the journaling overhead percentage.
Also exactly one JSON line.
"""

import json
import time

import numpy as np

#: TPU v5e (v5 lite) public peaks, for the roofline estimate
_V5E_PEAK_BF16_FLOPS = 197e12
_V5E_HBM_BYTES_PER_S = 819e9


def _transfer_settings():
    """The active streaming-transfer knobs, for the bench JSON (a tuned
    chunk size / stream count must be readable off the trajectory)."""
    from tensorframes_tpu.utils import get_config

    cfg = get_config()
    return {
        "chunk_bytes": cfg.transfer_chunk_bytes,
        "streams": cfg.transfer_streams,
        "wire_dtype": cfg.transfer_dtype or "verbatim",
    }


def _numpy_baseline(x, w, b, iters=3):
    """CPU scoring throughput (argmax(x @ w + b))."""
    t0 = time.perf_counter()
    for _ in range(iters):
        np.argmax(x @ w + b, axis=-1)
    dt = (time.perf_counter() - t0) / iters
    return x.shape[0] / dt


def main():
    import glob
    import os

    import jax

    import tensorframes_tpu as tft

    # persistent-compile-cache state BEFORE any compilation: entries > 0
    # means this process warm-starts from executables earlier processes
    # compiled (the round-5 cold-start fix — see docs/perf.md)
    cache_dir = tft.enable_compilation_cache()
    cache_entries_before = (
        len(glob.glob(os.path.join(cache_dir, "*"))) if cache_dir else 0
    )
    from tensorframes_tpu.engine import map_blocks
    from tensorframes_tpu.models import MLPClassifier
    from tensorframes_tpu.utils.profiling import Timer

    # 1M rows: the per-dispatch latency of the TPU link amortizes across a
    # large block, which is the intended usage pattern for block scoring
    # (TFT_BENCH_ROWS shrinks it for smoke runs; published numbers use
    # the default)
    n_rows = int(os.environ.get("TFT_BENCH_ROWS", "1000000"))
    n_features, n_classes = 784, 10
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, n_features)).astype(np.float32)

    clf = MLPClassifier.init(0, [n_features, n_classes])
    w, b = clf.params[0]["w"], clf.params[0]["b"]

    timer = Timer()
    with timer.section("ingest+analyze"):
        df = tft.TensorFrame.from_columns({"features": x}).analyze()
    g = clf._scoring_graph(df, "features", "prediction", None)

    # the three cold-start costs, accounted separately because they have
    # different owners: UPLOAD is workload data movement over the tunnel
    # (the reference pays the same shuffle to feed its sessions — and it
    # recurs per process regardless of caching), PRECOMPILE is XLA
    # compilation (eliminated for warm processes by the persistent cache,
    # round-5 fix — compare this section cold vs warm), and
    # warmup+verify is the first real pass + correctness check.
    #
    # upload runs through the streaming transfer layer (chunked +
    # concurrent, frame/transfer.py — the round-6 fix for the 313.9 s /
    # 0.01 GB/s monolithic device_put of r05). The monolithic baseline is
    # sampled on a capped slice first (the full column at tunnel speeds
    # would add minutes; `make bench-ingest` runs the full-column
    # comparison): same link, same dtype, one blocking device_put.
    # untimed link warmup: the FIRST device transfer of a process absorbs
    # backend/allocator setup, and it must not land inside (and bias)
    # either timed mode
    jax.block_until_ready(jax.device_put(x[: min(n_rows, 1024)]))
    mono_rows = max(1, min(n_rows, (128 << 20) // (n_features * 4)))
    t0 = time.perf_counter()
    _mono = jax.device_put(x[:mono_rows])
    jax.block_until_ready(_mono)
    dt_mono_sample = time.perf_counter() - t0
    upload_mono_gb_per_s = x[:mono_rows].nbytes / 1e9 / dt_mono_sample
    try:
        _mono.delete()
    except Exception:
        pass
    with timer.section("upload"):
        feat_dev = df.column_data("features").device()
        jax.block_until_ready(feat_dev)
    with timer.section("precompile"):
        tft.precompile(g, df)
    with timer.section("warmup+verify"):
        scored = map_blocks(g, df)
        preds = np.asarray(scored.column_data("prediction").host())
        ref = np.argmax(x @ w + b, axis=-1)
        # TPU MXU matmuls run bf16 by default, so near-tie argmaxes may flip
        # vs the f32 numpy oracle; 99% agreement is the sanity bar
        assert (preds == ref).mean() > 0.99, "scoring mismatch"

    # -- primary: device-resident chained passes ---------------------------
    @jax.jit
    def _force_all(preds):
        # one fold over EVERY pass's output: consuming all of them in a
        # single final program guarantees completion of the whole chain
        # regardless of execution order
        return sum(p.sum() for p in preds)

    def _chained(iters, graph, frame):
        # shared forcing discipline for every pipeline mode: each pass is
        # exactly ONE dispatch (the engine program itself); outputs stay
        # device-resident and a single final fold + host fetch forces the
        # chain. Per-pass check dispatches (the r03 harness) cost one
        # host->tunnel round per pass and were charging harness overhead
        # to the engine. All iters outputs stay live in HBM until the
        # fold — ~400 MB at this workload's 4 MB i32 output column.
        outs = []
        for _ in range(iters):
            sf = map_blocks(graph, frame)
            outs.append(sf.column_data("prediction").device())
        np.asarray(_force_all(tuple(outs)))

    # flush: compile the final fold AT THE TIMED LENGTH (it re-traces per
    # tuple arity) and absorb the first-sync quantum
    iters = 100
    _chained(iters, g, df)
    with timer.section("pipeline"):
        t0 = time.perf_counter()
        _chained(iters, g, df)
        dt_pipeline = (time.perf_counter() - t0) / iters
    rows_per_sec = n_rows / dt_pipeline

    # -- bf16-input mode: half the HBM bytes per pass ----------------------
    # the workload is HBM-bound, so storing features bf16 halves the read
    # and roughly doubles rows/s; the cast runs ON DEVICE from the f32
    # column already resident (no extra tunnel transfer). Reported as a
    # detail row — `value` stays the f32 BASELINE-parity workload.
    import jax.numpy as jnp

    xb = df.column_data("features").device().astype(jnp.bfloat16)
    dfb = tft.TensorFrame.from_columns({"features": xb}).analyze()
    wb = jnp.asarray(w).astype(jnp.bfloat16)
    bb = jnp.asarray(b).astype(jnp.bfloat16)

    def score_bf16(features):
        return {"prediction": jnp.argmax(features @ wb + bb, axis=-1)}

    # correctness first, same contract as the f32 path: bf16 inputs lose
    # mantissa, so near-tie argmaxes flip a little more than the MXU's
    # bf16-pass default already does — 98% agreement is the sanity bar
    preds_b = np.asarray(
        map_blocks(score_bf16, dfb).column_data("prediction").host()
    )
    assert (preds_b == ref).mean() > 0.98, "bf16 scoring mismatch"

    _chained(iters, score_bf16, dfb)  # warmup at the timed arity
    with timer.section("bf16_pipeline"):
        t0 = time.perf_counter()
        _chained(iters, score_bf16, dfb)
        dt_bf16 = (time.perf_counter() - t0) / iters

    # -- host-fetch modes --------------------------------------------------
    # host_pipelined rides the streaming transfer layer's chunked
    # concurrent d2h. The old ``copy_to_host_async`` double-buffering was
    # measured ~2.2x SLOWER than host_sequential in BENCH_r05 (4.15 s vs
    # 1.86 s/pass): the async copies serialized behind each pass's compute
    # on the tunnel and ``np.asarray`` re-synchronized per array, so the
    # overlap cost more than it bought. ``d2h_async`` instead fans each
    # result out as transfer chunks on the pool the moment the pass is
    # dispatched, so fetch of pass i overlaps compute of pass i+1.
    from tensorframes_tpu.frame import transfer as _transfer

    h_iters = 8
    with timer.section("host_pipelined"):
        t0 = time.perf_counter()
        pending = []
        for _ in range(h_iters):
            sf = map_blocks(g, df)
            arr = sf.column_data("prediction").device()
            pending.append(_transfer.d2h_async(arr, what="bench"))
        outs = [p.result() for p in pending]
        dt_host_pipe = (time.perf_counter() - t0) / h_iters
    assert all(o.shape == (n_rows,) for o in outs)

    with timer.section("host_sequential"):
        t0 = time.perf_counter()
        for _ in range(3):
            sf = map_blocks(g, df)
            np.asarray(sf.column_data("prediction").host())
        dt_host_seq = (time.perf_counter() - t0) / 3

    # python-side framework overhead per pass (construct + validate +
    # analyze + thunk force + dispatch; no device dependency awaited)
    t0 = time.perf_counter()
    for _ in range(20):
        map_blocks(g, df).column_data("prediction")
    overhead_ms = (time.perf_counter() - t0) / 20 * 1e3

    cpu_rows_per_sec = _numpy_baseline(x, w, b)

    # roofline: the scoring pass reads the 1M x 784 f32 block from HBM
    bytes_moved = x.nbytes
    flops = 2.0 * n_rows * n_features * n_classes
    mbu = bytes_moved / dt_pipeline / _V5E_HBM_BYTES_PER_S
    mfu = flops / dt_pipeline / _V5E_PEAK_BF16_FLOPS

    print(
        json.dumps(
            {
                "metric": "map_blocks_scoring_rows_per_sec_per_chip",
                "value": round(rows_per_sec, 1),
                "unit": "rows/s",
                "vs_baseline": round(rows_per_sec / cpu_rows_per_sec, 3),
                "detail": {
                    "workload": f"MNIST-LR scoring, {n_rows} x {n_features} f32 (BASELINE config 3)",
                    "device": str(jax.devices()[0]),
                    "mode": "device-resident chained passes (pipeline)",
                    "seconds_per_pass": round(dt_pipeline, 6),
                    "bf16_input_rows_per_sec": round(n_rows / dt_bf16, 1),
                    "bf16_seconds_per_pass": round(dt_bf16, 6),
                    "bf16_hbm_bandwidth_util": round(
                        xb.nbytes / dt_bf16 / _V5E_HBM_BYTES_PER_S, 4
                    ),
                    # two-point decomposition from THIS run's f32/bf16
                    # pair: t = bytes/BW + c, where c is the
                    # dtype-independent fixed term (the [784,10]->[784,
                    # 128] lane-padded matmul, ~1ms of MXU time, which a
                    # pallas overlap attempt could not beat — see
                    # docs/perf.md). The raw bf16 utilization above is an
                    # amortization artifact of c over half the bytes;
                    # the STREAM itself runs at this fraction of peak in
                    # both modes:
                    "derived_stream_bandwidth_util": round(
                        (x.nbytes - xb.nbytes)
                        / (dt_pipeline - dt_bf16)
                        / _V5E_HBM_BYTES_PER_S,
                        4,
                    ),
                    "derived_fixed_mxu_ms": round(
                        (2 * dt_bf16 - dt_pipeline) * 1e3, 3
                    ),
                    "host_pipelined_rows_per_sec": round(n_rows / dt_host_pipe, 1),
                    "host_sequential_rows_per_sec": round(n_rows / dt_host_seq, 1),
                    "framework_overhead_ms_per_pass": round(overhead_ms, 3),
                    "cpu_numpy_rows_per_sec": round(cpu_rows_per_sec, 1),
                    "roofline": {
                        "hbm_bandwidth_util": round(mbu, 4),
                        "mfu_bf16": round(mfu, 6),
                        "note": (
                            f"workload is HBM-bound ({bytes_moved / 1e9:.1f}GB "
                            f"read, {flops / 1e9:.1f} GFLOP); peaks: v5e "
                            f"197 TF/s bf16, 819 GB/s"
                        ),
                    },
                    "sections": {
                        k: round(v, 4) for k, v in timer.totals.items()
                    },
                    # workload data movement — recurs per process, cache-
                    # INDEPENDENT (a real TPU host moves the same bytes
                    # over PCIe at ~10 GB/s; this is the tunnel). Chunked +
                    # overlapped through frame/transfer.py; the monolithic
                    # row is the old single-device_put path sampled on a
                    # capped slice of the same column (full-column
                    # comparison: `make bench-ingest`)
                    "upload_gb_per_s": round(
                        x.nbytes / 1e9 / timer.totals["upload"], 3
                    ),
                    "upload_monolithic_gb_per_s": round(
                        upload_mono_gb_per_s, 3
                    ),
                    "upload_speedup_vs_monolithic": round(
                        (x.nbytes / 1e9 / timer.totals["upload"])
                        / upload_mono_gb_per_s,
                        2,
                    ),
                    "transfer": _transfer_settings(),
                    "compilation_cache": {
                        "dir": cache_dir,
                        "entries_at_start": cache_entries_before,
                        "warm_start": cache_entries_before > 0,
                    },
                },
            }
        )
    )


def _pct(xs, p):
    return xs[min(len(xs) - 1, int(p * (len(xs) - 1)))] if xs else None


def _serve_one_concurrency(
    lm, n_requests, plen, max_new, seed, prompts=None, page_size=16,
    stats_out=None,
    **engine_kw,
):
    """One timed serving run: ``n_requests`` streams decoded through one
    shared continuous batch. Token timestamps are taken on the consumer
    side (per-stream iterators on their own threads), so the measured
    inter-token gaps AND time-to-first-token include the full engine
    path — scheduling, the compiled step(s), host sync, and handle
    delivery. ``prompts`` overrides the random per-request prompts (the
    shared-prefix workload passes near-identical ones); ``engine_kw``
    passes through to ``GenerationEngine`` (attention_impl,
    prefix_cache, prefill_chunk_tokens...)."""
    import threading

    from tensorframes_tpu.serve import GenerationEngine

    rng = np.random.default_rng(seed)
    if prompts is None:
        prompts = [
            rng.integers(1, 256, size=plen).astype(np.int32).tolist()
            for _ in range(n_requests)
        ]
    eng = GenerationEngine(
        lm,
        max_slots=n_requests,
        page_size=page_size,  # None = hint/tuned default (the autotune axis)
        max_seq_len=plen + max_new,
        queue_capacity=n_requests,
        **engine_kw,
    )
    # warmup: compile prefill + decode outside the timed window
    eng.generate([prompts[0]], 2)
    stamps = [[] for _ in range(n_requests)]

    def consume(i, handle):
        for _ in handle:
            stamps[i].append(time.perf_counter())

    with eng:
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new) for p in prompts]
        threads = [
            threading.Thread(target=consume, args=(i, h))
            for i, h in enumerate(handles)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
    total = n_requests * max_new
    gaps = sorted(
        b - a for s in stamps for a, b in zip(s, s[1:])
    )
    ttfts = sorted(s[0] - t0 for s in stamps if s)
    out = {
        "tokens_per_sec": round(total / dt, 1),
        "itl_p50_ms": round(_pct(gaps, 0.50) * 1e3, 3),
        "itl_p99_ms": round(_pct(gaps, 0.99) * 1e3, 3),
        "ttft_p50_ms": round(_pct(ttfts, 0.50) * 1e3, 3),
        "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
        "ttft_max_ms": round(max(ttfts) * 1e3, 3),
        "wall_s": round(dt, 3),
        "compiled_step_programs": eng.num_step_programs,
    }
    if eng.prefix_cache is not None:
        st = eng.prefix_cache.stats()
        out["prefix_cache_hit_rate"] = round(
            st["hits"] / max(1, st["lookups"]), 3
        )
        out["prefix_cache_tokens_saved"] = st["tokens_saved"]
    if stats_out is not None:
        # engine-side views an axis wants WITHOUT rebuilding the engine
        # (a second TP engine would re-run the collective estimate and
        # allocate a duplicate sharded pool): the health snapshot plus
        # raw pool byte counts
        stats_out["health"] = eng.health()
        stats_out["pool_num_pages"] = eng.pool.num_pages
        stats_out["pool_kv_nbytes"] = int(
            eng.pool.k.nbytes + eng.pool.v.nbytes
        )
    return out


def _serve_fleet_aggregate(lm, replicas, n_requests=16, plen=32, max_new=64,
                           seed=0):
    """Aggregate fleet throughput at one replica count: ``n_requests``
    requests placed by the router across ``replicas`` engines, timed
    end-to-end on the consumer side. Each replica's two step programs
    compile in an untimed warmup (the persistent compile cache makes
    replicas 2..N near-free)."""
    from tensorframes_tpu.serve import Fleet

    rng = np.random.default_rng(seed)
    prompts = [
        rng.integers(1, 256, size=plen).astype(np.int32).tolist()
        for _ in range(n_requests)
    ]
    fleet = Fleet(
        lm,
        replicas=replicas,
        max_slots=8,
        page_size=16,
        max_seq_len=plen + max_new,
        queue_capacity=n_requests,
    )
    with fleet:
        warm = [eng.submit([1, 2, 3], 2, block=False) for eng in fleet.engines]
        for h in warm:
            h.result(timeout=600)
        t0 = time.perf_counter()
        handles = [fleet.submit(p, max_new) for p in prompts]
        for h in handles:
            h.result(timeout=600)
        dt = time.perf_counter() - t0
        programs = fleet.program_counts()
    return {
        "tokens_per_sec": round(n_requests * max_new / dt, 1),
        "wall_s": round(dt, 3),
        "requests": n_requests,
        "compiled_step_programs": programs,
    }


def _serve_tenants_mix(lm, plen, max_new, seed, per_class=6):
    """The multi-tenant QoS axis (``TFT_BENCH_TENANTS``): one engine,
    ``per_class`` interactive-class and ``per_class`` batch-class
    requests submitted together under an enabled tenancy plane
    (serve/tenancy.py), reporting per-class tokens/s and TTFT — the
    number the priority-aware admission order exists to move (the
    interactive class should see better TTFT than batch under the same
    mixed load). Config is restored afterwards so later axes measure
    the plane-off default."""
    import threading

    import tensorframes_tpu as tft
    from tensorframes_tpu.serve import GenerationEngine

    rng = np.random.default_rng(seed)
    classes = ("interactive", "batch")
    prompts = {
        cls: [
            rng.integers(1, 256, size=plen).astype(np.int32).tolist()
            for _ in range(per_class)
        ]
        for cls in classes
    }
    tft.utils.set_config(tenants=(
        {"tenant": "fg", "priority": "interactive"},
        {"tenant": "bg", "priority": "batch"},
    ))
    tenant_of = {"interactive": "fg", "batch": "bg"}
    try:
        eng = GenerationEngine(
            lm,
            max_slots=per_class,  # half the load fits: admission ordering matters
            page_size=16,
            max_seq_len=plen + max_new,
            queue_capacity=2 * per_class,
        )
        eng.generate([prompts["interactive"][0]], 2)
        stamps = {cls: [[] for _ in range(per_class)] for cls in classes}

        def consume(cls, i, handle):
            for _ in handle:
                stamps[cls][i].append(time.perf_counter())

        with eng:
            t0 = time.perf_counter()
            handles = [
                (cls, i, eng.submit(p, max_new, tenant=tenant_of[cls]))
                for cls in classes
                for i, p in enumerate(prompts[cls])
            ]
            threads = [
                threading.Thread(target=consume, args=h) for h in handles
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            dt = time.perf_counter() - t0
        out = {"wall_s": round(dt, 3)}
        for cls in classes:
            ttfts = sorted(s[0] - t0 for s in stamps[cls] if s)
            ntok = sum(len(s) for s in stamps[cls])
            out[cls] = {
                "tokens_per_sec": round(ntok / dt, 1),
                "ttft_p50_ms": round(_pct(ttfts, 0.50) * 1e3, 3),
                "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
            }
        return out
    finally:
        tft.utils.set_config(tenants=())


def _serve_tiers_leg(lm, tiers, workload, seed, max_seq_len):
    """One leg of the ``TFT_BENCH_TIERS`` A/B: a two-replica fleet —
    monolithic (``tiers=None``: both replicas ``mixed``) or 1+1
    disaggregated (``("prefill", "decode")``: live KV-page handoff at
    first token, serve/tiers.py) — serving the same mixed
    prompt-heavy/decode-heavy workload. Consumer-side stamps give TTFT
    and inter-token percentiles; migration count and latency are read
    as metric deltas around the timed window."""
    import threading

    from tensorframes_tpu.obs import metrics as tft_metrics
    from tensorframes_tpu.serve import Fleet

    def _migration_counts():
        snap = tft_metrics.snapshot()
        mig = snap.get("serve.kv_migrations_total", {}).get("values", {})
        hist = (
            snap.get("serve.migration_seconds", {})
            .get("values", {})
            .get("", {})
        )
        return (
            sum(mig.values()),
            float(hist.get("sum", 0.0)),
            int(hist.get("count", 0)),
        )

    fleet = Fleet(
        lm,
        replicas=2,
        tiers=tiers,
        max_slots=len(workload),
        page_size=16,
        max_seq_len=max_seq_len,
        queue_capacity=len(workload),
    )
    stamps = [[] for _ in workload]

    def consume(i, handle):
        for _ in handle:
            stamps[i].append(time.perf_counter())

    with fleet:
        warm = [
            eng.submit([1, 2, 3], 2, block=False) for eng in fleet.engines
        ]
        for h in warm:
            h.result(timeout=600)
        mig0, mig_s0, mig_n0 = _migration_counts()
        t0 = time.perf_counter()
        handles = [
            fleet.submit(p, n, seed=seed + i)
            for i, (p, n) in enumerate(workload)
        ]
        threads = [
            threading.Thread(target=consume, args=(i, h))
            for i, h in enumerate(handles)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        dt = time.perf_counter() - t0
        mig1, mig_s1, mig_n1 = _migration_counts()
        programs = fleet.program_counts()
    total = sum(len(s) for s in stamps)
    gaps = sorted(b - a for s in stamps for a, b in zip(s, s[1:]))
    ttfts = sorted(s[0] - t0 for s in stamps if s)
    out = {
        "tokens_per_sec": round(total / dt, 1),
        "ttft_p50_ms": round(_pct(ttfts, 0.50) * 1e3, 3),
        "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3),
        "itl_p50_ms": round(_pct(gaps, 0.50) * 1e3, 3),
        "itl_p99_ms": round(_pct(gaps, 0.99) * 1e3, 3),
        "wall_s": round(dt, 3),
        "migrations": int(mig1 - mig0),
        "compiled_step_programs": programs,
    }
    if mig_n1 > mig_n0:
        out["migration_mean_ms"] = round(
            (mig_s1 - mig_s0) / (mig_n1 - mig_n0) * 1e3, 3
        )
    return out


def _serve_tiers_mix(lm, seed=20):
    """The disaggregated-tier axis (``TFT_BENCH_TIERS``, ISSUE 20): the
    SAME mixed load — prompt-heavy requests (long prefill, short
    decode) interleaved with decode-heavy ones (short prefill, long
    decode) — through a monolithic two-replica fleet vs a 1+1
    prefill/decode tiered one. The tiered leg prefills every request on
    the prefill replica and migrates its KV pages to the decode replica
    at first token, so prompt-heavy prefill bursts stop preempting the
    decode-heavy streams' step loop; the axis reports the numbers that
    move (TTFT p50/p99, aggregate tok/s) plus migration count and mean
    latency, monolithic first so regressions read as a pair."""
    rng = np.random.default_rng(seed)
    workload = []
    for i in range(4):  # prompt-heavy: 384-token prefill, 16 new
        workload.append(
            (rng.integers(1, 256, size=384).astype(np.int32).tolist(), 16)
        )
    for i in range(8):  # decode-heavy: 32-token prefill, 96 new
        workload.append(
            (rng.integers(1, 256, size=32).astype(np.int32).tolist(), 96)
        )
    out = {}
    for label, tiers in (
        ("monolithic", None),
        ("tiered_1p1d", ("prefill", "decode")),
    ):
        out[label] = _serve_tiers_leg(
            lm, tiers, workload, seed=seed, max_seq_len=448
        )
    return out


def _serve_tp_level(lm, degree, plen, max_new, seed, n_requests=16):
    """One tensor-parallel degree of the ``TFT_BENCH_TP`` axis: the
    concurrency-16 serving workload with ONE engine spanning ``degree``
    devices (``GenerationEngine(mesh=...)``, serve/tp.py), reporting
    tok/s plus the aggregate-KV-capacity view — total pool pages and
    per-chip KV bytes for a FIXED per-chip page budget (``num_pages``
    is per-chip under TP, so capacity scales ×N while bytes/chip stay
    flat). Degrees beyond the attached device count report a skip
    instead of failing the whole bench."""
    import jax

    from tensorframes_tpu.parallel import make_mesh
    from tensorframes_tpu.serve import pages_needed

    if degree > len(jax.devices()):
        return {
            "skipped": (
                f"needs {degree} devices; "
                f"{len(jax.devices())} attached"
            )
        }
    mesh = make_mesh({"tp": degree}) if degree > 1 else None
    page_size = 16
    per_chip_pages = n_requests * pages_needed(plen + max_new, page_size)
    stats = {}
    res = _serve_one_concurrency(
        lm, n_requests, plen=plen, max_new=max_new, seed=seed,
        page_size=page_size, num_pages=per_chip_pages, mesh=mesh,
        stats_out=stats,
    )
    tp_block = stats["health"]["tp"]
    res.update(
        tp_degree=degree,
        kv_pages_capacity=stats["pool_num_pages"],
        kv_bytes_per_chip=stats["pool_kv_nbytes"] // max(1, degree),
        collective_seconds_per_step_est=(
            tp_block["collective_seconds_per_step_est"] if tp_block
            else 0.0
        ),
    )
    return res


def main_decode_serve():
    import os
    import sys

    # the TP axis needs a multi-device mesh; on a CPU host that is the
    # simulated one. The flag only multiplies the HOST platform's
    # devices (a TPU run's device list is untouched), and it must land
    # before jax initializes its backends — harmless no-op when some
    # earlier import beat us to it (the axis then skips degrees that
    # don't fit and says so in the JSON).
    if os.environ.get("TFT_BENCH_TP", "1,2,4").strip() and (
        "jax" not in sys.modules
    ):
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()

    import jax

    import tensorframes_tpu as tft
    from tensorframes_tpu.models import TransformerLM

    tft.enable_compilation_cache()
    lm = TransformerLM.init(
        0, 256, d_model=128, n_heads=8, n_layers=4, max_len=512
    )
    plen, max_new = 32, 64
    levels = {}
    for c in (1, 4, 16):
        levels[str(c)] = _serve_one_concurrency(
            lm, c, plen=plen, max_new=max_new, seed=c
        )
    head = levels["16"]
    # prompt-length axis at concurrency 16: TTFT and tokens/s vs prompt
    # size (TFT_BENCH_PROMPT_LENS trims/extends; lens + max_new must fit
    # the model's 512-position table)
    lens_env = os.environ.get("TFT_BENCH_PROMPT_LENS", "32,128,384")
    prompt_lens = {}
    for pl in [int(x) for x in lens_env.split(",") if x.strip()]:
        prompt_lens[str(pl)] = _serve_one_concurrency(
            lm, 16, plen=pl, max_new=max_new, seed=1000 + pl
        )
    # decode-read implementation axis: the gather reference vs the fused
    # ragged paged-attention kernel (the fused win is a TPU bandwidth
    # property; on a CPU host the kernel runs in interpret mode — the
    # axis shrinks there so the smoke run stays minutes, and the number
    # only means something on real hardware)
    on_tpu = jax.devices()[0].platform == "tpu"
    attn_c, attn_new = (16, max_new) if on_tpu else (4, 16)
    attention = {}
    for impl in ("gather", "fused"):
        attention[impl] = _serve_one_concurrency(
            lm, attn_c, plen=plen, max_new=attn_new, seed=42,
            attention_impl=impl,
        )
    # shared-prefix workload: 16 requests sharing a 448-token system
    # prompt + 16 distinct user tokens, prefix cache off vs on (with
    # chunked prefill sized near the uncached suffix, so a hit prefills
    # one 32-wide chunk instead of the 464-token prompt) — the TTFT-
    # reduction acceptance axis. The warmup request inside
    # _serve_one_concurrency registers the prefix, so the timed window
    # measures the steady state (system prompt already resident).
    rng = np.random.default_rng(9)
    sys_prompt = rng.integers(1, 256, size=448).astype(np.int32).tolist()
    shared_prompts = [
        sys_prompt
        + rng.integers(1, 256, size=16).astype(np.int32).tolist()
        for _ in range(16)
    ]
    shared_prefix = {}
    for label, kw in (
        ("cache_off", {}),
        (
            "cache_on",
            {"prefix_cache": True, "prefill_chunk_tokens": 32},
        ),
    ):
        shared_prefix[label] = _serve_one_concurrency(
            lm, 16, plen=464, max_new=32, seed=9,
            prompts=shared_prompts, **kw
        )
    # the scale-out axis: aggregate tokens/s with the serving fleet at
    # 1/2/4 replicas, same per-request shape, 16 concurrent requests
    # routed least-loaded (TFT_BENCH_REPLICAS="1,2" shrinks smoke runs;
    # on a single-chip/CPU host the replicas share the device, so this
    # measures router + engine overhead there and true scale-out only
    # with one chip per replica)
    reps_env = os.environ.get("TFT_BENCH_REPLICAS", "1,2,4")
    rep_levels = {}
    for r in [int(x) for x in reps_env.split(",") if x.strip()]:
        rep_levels[str(r)] = _serve_fleet_aggregate(
            lm, r, plen=plen, max_new=max_new, seed=100 + r
        )
    # the tensor-parallel axis (ISSUE 14): one replica spanning 1/2/4
    # devices of the simulated mesh — tok/s + aggregate KV pages per
    # degree (TFT_BENCH_TP trims/extends; empty disables the axis, as
    # the bench-check gate pins it). On the CPU-sim mesh the "chips"
    # share one socket, so tok/s mostly measures collective/dispatch
    # overhead there and true FLOP/HBM scaling only on real chips; the
    # CAPACITY column (pages_capacity ×N for the same per-chip budget)
    # is exact everywhere.
    tp_env = os.environ.get("TFT_BENCH_TP", "1,2,4")
    tp_levels = {}
    for d in [int(x) for x in tp_env.split(",") if x.strip()]:
        tp_levels[str(d)] = _serve_tp_level(
            lm, d, plen=plen, max_new=max_new, seed=200 + d
        )
    # the speculative-decoding axis (ISSUE 15): draft-length k = 0
    # (plain decode) vs speculative k, tok/s + inter-token p50/p99 +
    # measured acceptance rate, on a repeated-suffix smoke workload
    # (prompts ending in a short repeating pattern — the regime
    # speculation exists for). The draft is the TARGET's own weights
    # (self-speculation), so acceptance ~1.0 and the numbers measure
    # the mechanism's dispatch-amortization ceiling: k+1 tokens per
    # draft+verify dispatch pair instead of 1 per decode dispatch; a
    # real deployment's gain scales with its draft's acceptance, which
    # this axis reports. TFT_BENCH_SPEC trims/extends the k list;
    # empty disables the axis (the bench-check gate pins it off so the
    # gated headline measures the unchanged k=0 path).
    spec_env = os.environ.get("TFT_BENCH_SPEC", "0,2,4")
    speculative = {}
    if spec_env.strip():
        rng_s = np.random.default_rng(15)
        base = rng_s.integers(1, 256, size=8).astype(np.int32).tolist()
        pattern = rng_s.integers(1, 256, size=4).astype(np.int32).tolist()
        rep_prompts = [
            (base + pattern * 6)[:plen] for _ in range(8)
        ]
        for k in [int(x) for x in spec_env.split(",") if x.strip()]:
            kw = (
                {}
                if k == 0
                else {"draft_params": lm.params, "draft_len": k}
            )
            stats = {}
            res = _serve_one_concurrency(
                lm, 8, plen=plen, max_new=48, seed=300 + k,
                prompts=rep_prompts, stats_out=stats, **kw
            )
            spec = (stats.get("health") or {}).get("speculative")
            res["acceptance_rate"] = (
                spec["acceptance_rate"] if spec else None
            )
            res["draft_len"] = k
            speculative[str(k)] = res
    # observability-cost axis (ISSUE 10): the same per-request shape
    # with tracing LIVE (JSONL sink attached — every span on the
    # prefill/decode path materializes and serializes) vs the TFT_OBS=0
    # kill switch, interleaved best-of. The trajectory tracks what the
    # layer costs; the budget is <= 1% (this tiny CPU model is the
    # WORST case for the pct — real-chip step times dwarf the ~µs span
    # cost)
    observability = _serve_obs_overhead(lm, plen=plen, max_new=16)
    # the multi-tenant QoS axis (ISSUE 17): a mixed interactive+batch
    # load under an enabled tenancy plane, per-class tok/s + TTFT.
    # TFT_BENCH_TENANTS opts IN (default off, and the bench-check gate
    # pins it off — the gated headline must measure the plane-off
    # zero-cost default, which is also the byte-identity baseline).
    tenants = {}
    if os.environ.get("TFT_BENCH_TENANTS", "").strip():
        tenants = _serve_tenants_mix(lm, plen=plen, max_new=32, seed=17)
    # the disaggregated-tier axis (ISSUE 20): mixed prompt-heavy/
    # decode-heavy load through a monolithic two-replica fleet vs a 1+1
    # prefill/decode tiered one with live KV-page handoff
    # (serve/tiers.py) — TTFT p50/p99 + tok/s + migration count/latency
    # per leg. TFT_BENCH_TIERS opts IN (default off, and the
    # bench-check gate pins it off: the gated headline measures the
    # untiered path, which is also the byte-identity baseline).
    tiers = {}
    if os.environ.get("TFT_BENCH_TIERS", "").strip():
        tiers = _serve_tiers_mix(lm, seed=20)
    from tensorframes_tpu.utils import chaos

    print(
        json.dumps(
            {
                "metric": "decode_serve_tokens_per_sec",
                "value": head["tokens_per_sec"],
                "unit": "tok/s",
                "detail": {
                    "workload": (
                        f"continuous-batching greedy decode, prompt {plen} "
                        f"+ {max_new} new tokens per request, paged KV "
                        f"(page_size 16)"
                    ),
                    "model": "d128 h8 L4 vocab256",
                    "device": str(jax.devices()[0]),
                    "concurrency": levels,
                    "prompt_lens": prompt_lens,
                    "attention_impl": attention,
                    "shared_prefix": shared_prefix,
                    "replicas": rep_levels,
                    "tensor_parallel": tp_levels,
                    "speculative": speculative,
                    "observability": observability,
                    "tenants": tenants,
                    "tiers": tiers,
                    # a chaos-tainted number must never be mistaken for a
                    # clean one (the injection sites sit on this path; the
                    # disabled check is the measured-as-free case)
                    "chaos": chaos.active_spec() or "off",
                },
            }
        )
    )


def _serve_obs_overhead(lm, plen, max_new, iters=3):
    """tokens/s with the tracing layer live vs killed — plus the
    time-series SAMPLER (ISSUE 12) running at a 0.25 s cadence vs
    parked, plus telemetry EXPORT (ISSUE 16: the periodic snapshot
    federation write, sampler live on both legs so the delta is the
    export path alone): best-of ``iters`` interleaved runs of the
    concurrency-4 workload. Each incremental delta carries a ≤ 1%
    budget; the export leg is a registry walk AND an atomic JSON write
    every 250 ms against a tiny CPU model — real-chip step times
    dwarf it."""
    import os
    import shutil
    import tempfile

    from tensorframes_tpu import obs
    from tensorframes_tpu.utils import get_config, set_config

    root = tempfile.mkdtemp(prefix="tft-bench-obs-")
    sink = os.path.join(root, "trace.jsonl")
    tdir = os.path.join(root, "telemetry")
    # the axis FORCES each leg's state; the operator's own setting
    # (e.g. an outer TFT_OBS=0 smoke run) is restored afterwards
    prev_obs = get_config().observability
    prev_interval = get_config().obs_sample_interval_s
    prev_tdir = get_config().telemetry_dir
    prev_export = get_config().obs_export_interval_s
    on = off = sampler_on = sampler_off = 0.0
    export_on = export_off = 0.0
    try:
        for i in range(iters):
            set_config(observability=True)
            obs.set_trace_sink(sink)
            try:
                on = max(
                    on,
                    _serve_one_concurrency(
                        lm, 4, plen=plen, max_new=max_new, seed=7000 + i
                    )["tokens_per_sec"],
                )
            finally:
                obs.set_trace_sink(None)
            set_config(observability=False)
            off = max(
                off,
                _serve_one_concurrency(
                    lm, 4, plen=plen, max_new=max_new, seed=8000 + i
                )["tokens_per_sec"],
            )
            # sampler pair: obs ON both legs, the background sampler the
            # only difference (what the observatory itself costs)
            set_config(
                observability=True, obs_sample_interval_s=0.25
            )
            obs.timeseries.acquire_sampler()
            try:
                sampler_on = max(
                    sampler_on,
                    _serve_one_concurrency(
                        lm, 4, plen=plen, max_new=max_new, seed=9000 + i
                    )["tokens_per_sec"],
                )
            finally:
                obs.timeseries.release_sampler()
            sampler_off = max(
                sampler_off,
                _serve_one_concurrency(
                    lm, 4, plen=plen, max_new=max_new, seed=9500 + i
                )["tokens_per_sec"],
            )
            # export pair (ISSUE 16): obs + sampler ON both legs, the
            # periodic snapshot federation write (every 250 ms) the only
            # difference — isolating what the telemetry plane itself
            # costs (the tracing/sampler rows above already price the
            # rest of the observatory, and that delta is NOT export's)
            set_config(
                observability=True, obs_sample_interval_s=0.25,
                telemetry_dir=tdir, obs_export_interval_s=0.25,
            )
            obs.timeseries.acquire_sampler()
            try:
                export_on = max(
                    export_on,
                    _serve_one_concurrency(
                        lm, 4, plen=plen, max_new=max_new, seed=9700 + i
                    )["tokens_per_sec"],
                )
            finally:
                obs.timeseries.release_sampler()
            set_config(telemetry_dir="")
            obs.timeseries.acquire_sampler()
            try:
                export_off = max(
                    export_off,
                    _serve_one_concurrency(
                        lm, 4, plen=plen, max_new=max_new, seed=9900 + i
                    )["tokens_per_sec"],
                )
            finally:
                obs.timeseries.release_sampler()
    finally:
        set_config(
            observability=prev_obs, obs_sample_interval_s=prev_interval,
            telemetry_dir=prev_tdir, obs_export_interval_s=prev_export,
        )
        shutil.rmtree(root, ignore_errors=True)
    return {
        "tracing_on_tokens_per_sec": round(on, 2),
        "obs_off_tokens_per_sec": round(off, 2),
        "overhead_pct": round((off - on) / off * 100.0, 2) if off else None,
        "sampler_on_tokens_per_sec": round(sampler_on, 2),
        "sampler_off_tokens_per_sec": round(sampler_off, 2),
        "sampler_overhead_pct": (
            round((sampler_off - sampler_on) / sampler_off * 100.0, 2)
            if sampler_off
            else None
        ),
        "export_on_tokens_per_sec": round(export_on, 2),
        "export_off_tokens_per_sec": round(export_off, 2),
        "export_overhead_pct": (
            round((export_off - export_on) / export_off * 100.0, 2)
            if export_off
            else None
        ),
    }


def main_paged_attn():
    """Decode paged-read microbench (``make bench-attn``): the gather
    ``paged_attention`` vs the fused ``ragged_paged_attention`` kernel on
    one ragged decode batch, outside the engine — isolating the read
    that PR-7 fuses. Reports per-impl step latency, decode tokens/s
    (slots / step), and two bandwidth views: ``gb_per_s_touched`` (bytes
    that impl actually reads: the gather touches ``max_pages *
    page_size`` positions per slot, the fused kernel only live pages)
    and ``gb_per_s_live`` (live-KV bytes / time — the apples-to-apples
    throughput number; higher is better). Exactly one JSON line.

    Knobs: ``TFT_BENCH_ATTN_SLOTS`` (default 16),
    ``TFT_BENCH_ATTN_PAGES`` (max pages/slot, default 32),
    ``TFT_BENCH_ATTN_PAGE_SIZE`` (default 16). Lengths are ragged:
    slot i holds ``(i + 1) / slots`` of the max length."""
    import os

    import jax
    import jax.numpy as jnp

    import tensorframes_tpu as tft
    from tensorframes_tpu.ops import paged_attention, ragged_paged_attention

    tft.enable_compilation_cache()
    slots = int(os.environ.get("TFT_BENCH_ATTN_SLOTS", "16"))
    mp = int(os.environ.get("TFT_BENCH_ATTN_PAGES", "32"))
    ps = int(os.environ.get("TFT_BENCH_ATTN_PAGE_SIZE", "16"))
    n_kv, group, hd = 8, 1, 128
    pool_pages = slots * mp
    rng = np.random.default_rng(0)
    q = jnp.asarray(
        rng.normal(size=(slots, n_kv, group, hd)).astype(np.float32)
    )
    kp = jnp.asarray(
        rng.normal(size=(pool_pages + 1, ps, n_kv, hd)).astype(np.float32)
    )
    vp = jnp.asarray(
        rng.normal(size=(pool_pages + 1, ps, n_kv, hd)).astype(np.float32)
    )
    ptab = (
        np.arange(slots * mp, dtype=np.int32).reshape(slots, mp) % pool_pages
    )
    lengths = np.maximum(
        1, ((np.arange(slots) + 1) * mp * ps) // slots
    ).astype(np.int32)
    live_pages = int(sum(-(-int(l) // ps) for l in lengths))
    bytes_per_page = ps * n_kv * hd * 4 * 2  # k and v
    live_bytes = live_pages * bytes_per_page
    touched = {
        "gather": slots * mp * bytes_per_page,
        "fused": live_bytes,
    }

    impls = {
        "gather": jax.jit(paged_attention),
        "fused": jax.jit(ragged_paged_attention),
    }
    # off-TPU the fused kernel runs in interpret mode (~1000x slower, a
    # correctness vehicle, not a measurement) — keep the smoke run short
    iters = 20 if jax.devices()[0].platform == "tpu" else 3
    out = {}
    for name, fn in impls.items():
        jax.block_until_ready(fn(q, kp, vp, ptab, lengths))  # compile
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn(q, kp, vp, ptab, lengths)
        jax.block_until_ready(r)
        dt = (time.perf_counter() - t0) / iters
        out[name] = {
            "step_ms": round(dt * 1e3, 4),
            "tokens_per_sec": round(slots / dt, 1),
            "gb_per_s_touched": round(touched[name] / dt / 1e9, 3),
            "gb_per_s_live": round(live_bytes / dt / 1e9, 3),
        }
    print(
        json.dumps(
            {
                "metric": "paged_attn_fused_tokens_per_sec",
                "value": out["fused"]["tokens_per_sec"],
                "unit": "tok/s",
                "vs_baseline": round(
                    out["fused"]["tokens_per_sec"]
                    / out["gather"]["tokens_per_sec"],
                    3,
                ),
                "detail": {
                    "workload": (
                        f"single decode-step paged KV read, {slots} slots, "
                        f"ragged lengths up to {mp * ps} positions, "
                        f"page_size {ps}, n_kv {n_kv}, head_dim {hd}, f32"
                    ),
                    "device": str(jax.devices()[0]),
                    "live_kv_gb": round(live_bytes / 1e9, 4),
                    "impl": out,
                    "note": (
                        "fused wins are a TPU bandwidth property; on a "
                        "CPU host the fused number measures pallas "
                        "interpret-mode overhead, not the kernel"
                    ),
                },
            }
        )
    )


def main_pipeline():
    """Logical-plan pipeline bench (``make bench-pipeline``): a 3-op
    map chain + ``reduce_blocks`` through the lazy plan layer
    (``engine/plan.py``), fused vs op-at-a-time — one JSON line with:

    - **rows/s** for the full pipeline in both modes (real compute:
      ``d×d`` matmul per op on ``TFT_BENCH_PIPELINE_ROWS`` rows);
    - **framework overhead per logical op** in both modes, measured on
      a deliberately tiny frame where compute is negligible (min over
      repetitions, divided by the number of logical ops) — the
      acceptance bar is fused ≤ ½ op-at-a-time;
    - the **h2d byte delta** from column pruning: the source carries a
      decoy column bound only by a dead op (its fetch is never
      demanded), so the fused run must upload exactly the live
      column's bytes while the op-at-a-time run uploads both.

    Knobs: ``TFT_BENCH_PIPELINE_ROWS`` (default 200000),
    ``TFT_BENCH_PIPELINE_OPS`` (chain length, default 3)."""
    import os

    import jax
    import jax.numpy as jnp

    import tensorframes_tpu as tft
    from tensorframes_tpu.obs import metrics as _metrics
    from tensorframes_tpu.utils import set_config

    tft.enable_compilation_cache()
    n_rows = int(os.environ.get("TFT_BENCH_PIPELINE_ROWS", "200000"))
    n_ops = max(2, int(os.environ.get("TFT_BENCH_PIPELINE_OPS", "3")))
    d = 64
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, d)).astype(np.float32)
    decoy = rng.normal(size=(n_rows, 32)).astype(np.float32)
    ws = [
        jnp.asarray(
            (rng.normal(size=(d, d)) / np.sqrt(d)).astype(np.float32)
        )
        for _ in range(n_ops)
    ]

    def _mk(i, w):
        # placeholder named per level via feed_dict; fetch h{i}, with
        # the chain head named "out" so the reduce's `out_input`
        # convention binds it directly (keeps the chain pure maps —
        # the hoisting pass needs no projection in between)
        name = "out" if i == n_ops - 1 else f"h{i}"
        return lambda inp: {name: jnp.dot(inp, w)}

    layers = [_mk(i, w) for i, w in enumerate(ws)]

    def dead_fn(decoy):
        return {"dead": decoy * 2.0}

    def build(df):
        cur = df
        for i, fn in enumerate(layers):
            src = "x" if i == 0 else f"h{i - 1}"
            cur = tft.map_blocks(fn, cur, feed_dict={"inp": src})
        # the decoy consumer: chained but never demanded downstream
        cur = tft.map_blocks(dead_fn, cur)
        return cur

    # defined ONCE: a lambda recreated per call is a fresh function
    # identity -> fresh capture -> fresh composite -> recompile per pass
    def reduce_fn(out_input):
        return {"out": out_input.sum(axis=0)}

    def run_pipeline(df):
        cur = build(df)
        # the reduce demands only "out": the decoy op is dead, its
        # column never uploads, and the pure-map chain hoists the
        # reduce into the fused program's per-block epilogue
        return tft.reduce_blocks(reduce_fn, cur)

    def one_mode(plan_on, frame):
        set_config(
            plan_lazy_ops=plan_on,
            plan_fuse_maps=plan_on,
            plan_prune_columns=plan_on,
            plan_hoist_reduce=plan_on,
        )
        # warmup compiles
        run_pipeline(frame)
        iters = 3
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run_pipeline(frame)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / iters
        return dt

    df = tft.TensorFrame.from_columns({"x": x, "decoy": decoy}).analyze()
    dt_fused = one_mode(True, df)
    dt_eager = one_mode(False, df)

    # framework overhead per logical op: a frame small enough that the
    # chain's compute is measured in microseconds, so the wall clock IS
    # the per-op framework cost (capture memo, validation, span,
    # dispatch, materialization) — the quantity fusion collapses
    tiny = tft.TensorFrame.from_columns(
        {"x": x[:64], "decoy": decoy[:64]}
    ).analyze()
    n_logical = n_ops + 2  # maps + dead map + reduce

    def overhead(plan_on, reps):
        set_config(
            plan_lazy_ops=plan_on,
            plan_fuse_maps=plan_on,
            plan_prune_columns=plan_on,
            plan_hoist_reduce=plan_on,
        )
        run_pipeline(tiny)  # warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            run_pipeline(tiny)
            best = min(best, time.perf_counter() - t0)
        return best / n_logical

    # alternate the two modes across rounds so scheduler/thermal noise
    # on a shared host cannot land entirely on one side of the ratio;
    # min-of-all is the overhead estimate
    ov_fused, ov_eager = float("inf"), float("inf")
    for _ in range(3):
        ov_fused = min(ov_fused, overhead(True, 25))
        ov_eager = min(ov_eager, overhead(False, 25))

    # h2d bytes: fresh frames so every upload actually crosses the link
    reg = _metrics.registry()

    def h2d_delta(plan_on):
        set_config(
            plan_lazy_ops=plan_on,
            plan_fuse_maps=plan_on,
            plan_prune_columns=plan_on,
            plan_hoist_reduce=plan_on,
        )
        fresh = tft.TensorFrame.from_columns(
            {"x": x, "decoy": decoy}
        ).analyze()
        h0 = reg.get("frame.h2d_bytes_total").value()
        run_pipeline(fresh)
        return int(reg.get("frame.h2d_bytes_total").value() - h0)

    h2d_fused = h2d_delta(True)
    h2d_eager = h2d_delta(False)
    set_config(
        plan_lazy_ops=True, plan_fuse_maps=True,
        plan_prune_columns=True, plan_hoist_reduce=True,
    )

    print(
        json.dumps(
            {
                "bench": "tensorframes_tpu.pipeline",
                "config": {
                    "workload": (
                        f"{n_ops}-op map chain (d={d} matmuls) + dead "
                        f"decoy op + hoisted reduce_blocks, "
                        f"{n_rows} rows"
                    ),
                    "device": str(jax.devices()[0]),
                    "rows": n_rows,
                    "chain_ops": n_ops,
                },
                "rows_per_s": {
                    "fused": round(n_rows / dt_fused, 1),
                    "op_at_a_time": round(n_rows / dt_eager, 1),
                    "speedup": round(dt_eager / dt_fused, 3),
                },
                "framework_overhead_ms_per_logical_op": {
                    "fused": round(ov_fused * 1e3, 4),
                    "op_at_a_time": round(ov_eager * 1e3, 4),
                    "reduction": round(ov_eager / ov_fused, 2),
                },
                "h2d_bytes_per_cold_run": {
                    "fused_pruned": h2d_fused,
                    "op_at_a_time": h2d_eager,
                    "live_column_bytes": int(x.nbytes),
                    "pruned_decoy_bytes": int(decoy.nbytes),
                },
                "transfer": _transfer_settings(),
            }
        )
    )
    # the pruning contract, asserted on the numbers just printed: the
    # fused run uploads exactly the live column; the decoy column's
    # bytes cross only in the op-at-a-time run
    assert h2d_fused == x.nbytes, (h2d_fused, x.nbytes)
    assert h2d_eager == x.nbytes + decoy.nbytes, (h2d_eager,)


def main_map_rows_journal():
    """Durable-job overhead: one ``map_rows`` workload through
    ``run_job`` with the journal off (in-memory ledger: the same
    deterministic block loop, zero disk I/O) and on (npz spool +
    buffered ledger append per block). The ratio isolates what
    journaling itself costs; the acceptance bar is ≤ 5%.

    The workload is a two-layer MLP scored per row — the reference's
    flagship pattern (frozen model, per-row scoring) at a realistic
    compute weight, journaled at 32k-row block granularity. Both knobs
    matter for what this bench claims: the journal costs ~1 ms per
    block flat (one npz spool + one buffered append; on a single-core
    host the background writer cannot truly overlap compute, so that
    cost is real), so the overhead *ratio* is a statement about jobs
    whose resume units carry real work. A job with sub-millisecond
    blocks finishes in milliseconds and has no business paying for
    durability; conversely, coarser blocks mean fewer resume points —
    the granularity knob is ``Config.max_rows_per_device_call``.

    A **workers axis** (``TFT_BENCH_JOB_WORKERS``, default ``1,2,4``;
    empty disables) then drains the same job with K real subprocess
    workers through ``engine/dist_jobs.py`` block leasing, reporting
    aggregate rows/s and scaling efficiency
    (``rps_K / (K * rps_1)``). The clock starts once every worker is
    warmed up (df built, jax imported) and stops when the journal is
    terminal, so the numbers measure the *drain*, not process startup;
    on one shared chip/CPU the workers contend and efficiency < 1 is
    expected — the axis exists to measure exactly that contention (and
    to verify on multi-chip hosts that the leasing layer itself is not
    the bottleneck)."""
    import shutil
    import tempfile

    import jax

    import tensorframes_tpu as tft
    from tensorframes_tpu.engine import run_job
    from tensorframes_tpu.utils import get_config, set_config

    tft.enable_compilation_cache()
    import os as _os_rows

    # TFT_BENCH_ROWS shrinks the workload for smoke runs and the
    # bench-check regression gate (recorded next to the gate baseline,
    # so the comparison replays the same size)
    n_rows = int(_os_rows.environ.get("TFT_BENCH_ROWS", "") or 500_000)
    width = 256
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, width)).astype(np.float32)
    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    import jax.numpy as jnp

    w1 = jnp.asarray(rng.normal(size=(width, width)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(width,)).astype(np.float32))

    def score(features):
        return {"s": jnp.tanh(features @ w1) @ w2}

    job_root = tempfile.mkdtemp(prefix="tft-bench-jobs-")
    iters = 8
    old_chunk = get_config().max_rows_per_device_call
    set_config(max_rows_per_device_call=32768)

    def one(journal: bool, i: int) -> float:
        t0 = time.perf_counter()
        res = run_job(
            "map_rows", score, df, journal=journal,
            job_dir=job_root, job_id=f"bench-{journal}-{i}",
        )
        dt = time.perf_counter() - t0
        assert res.completed.num_rows == n_rows
        one.blocks = res.blocks_total
        return dt

    # warmup both variants (compile + page cache), then INTERLEAVE the
    # timed runs so fs/scheduler drift hits both modes equally; best-of
    # is the noise-robust statistic for a fixed workload
    one(False, -1), one(True, -2)
    dt_off = dt_on = float("inf")
    for i in range(iters):
        dt_off = min(dt_off, one(False, i))
        dt_on = min(dt_on, one(True, i + iters))
    blocks = one.blocks
    # observability-cost axis (ISSUE 10): the in-memory workload with
    # tracing LIVE (JSONL sink attached — the engine.map_rows /
    # jobs.block spans all materialize) vs the TFT_OBS=0 kill switch,
    # interleaved best-of like the journal pair. Acceptance: <= 1%
    # overhead on this microbench.
    import os as _os

    from tensorframes_tpu import obs as _obs

    obs_sink = _os.path.join(job_root, "bench-trace.jsonl")
    # the axis FORCES each leg's state; the operator's own setting
    # (e.g. an outer TFT_OBS=0 smoke run) is restored afterwards
    prev_obs = get_config().observability
    prev_interval = get_config().obs_sample_interval_s
    prev_tdir = get_config().telemetry_dir
    prev_export = get_config().obs_export_interval_s
    bench_tdir = _os.path.join(job_root, "telemetry")
    dt_obs_on = dt_obs_off = float("inf")
    dt_smp_on = dt_smp_off = float("inf")
    dt_exp_on = dt_exp_off = float("inf")
    try:
        for i in range(iters):
            set_config(observability=True)
            _obs.set_trace_sink(obs_sink)
            try:
                dt_obs_on = min(dt_obs_on, one(False, 100 + i))
            finally:
                _obs.set_trace_sink(None)
            set_config(observability=False)
            dt_obs_off = min(dt_obs_off, one(False, 200 + i))
            # sampler pair (ISSUE 12): obs ON both legs; the background
            # time-series sampler at a 0.25 s cadence is the only
            # difference — what the observatory itself costs (<= 1% bar)
            set_config(observability=True, obs_sample_interval_s=0.25)
            _obs.timeseries.acquire_sampler()
            try:
                dt_smp_on = min(dt_smp_on, one(False, 300 + i))
            finally:
                _obs.timeseries.release_sampler()
            dt_smp_off = min(dt_smp_off, one(False, 400 + i))
            # export pair (ISSUE 16): obs + sampler ON both legs, the
            # periodic snapshot federation write the only difference —
            # the telemetry plane's own incremental cost (<= 1% bar)
            set_config(
                observability=True, obs_sample_interval_s=0.25,
                telemetry_dir=bench_tdir, obs_export_interval_s=0.25,
            )
            _obs.timeseries.acquire_sampler()
            try:
                dt_exp_on = min(dt_exp_on, one(False, 700 + i))
            finally:
                _obs.timeseries.release_sampler()
            set_config(telemetry_dir="")
            _obs.timeseries.acquire_sampler()
            try:
                dt_exp_off = min(dt_exp_off, one(False, 800 + i))
            finally:
                _obs.timeseries.release_sampler()
    finally:
        set_config(
            observability=prev_obs, obs_sample_interval_s=prev_interval,
            telemetry_dir=prev_tdir, obs_export_interval_s=prev_export,
        )
    obs_overhead_pct = (dt_obs_on - dt_obs_off) / dt_obs_off * 100.0
    sampler_overhead_pct = (dt_smp_on - dt_smp_off) / dt_smp_off * 100.0
    export_overhead_pct = (dt_exp_on - dt_exp_off) / dt_exp_off * 100.0
    # autotune axis (ISSUE 13): the same workload with the self-tuning
    # layer OFF vs ONLINE against a throwaway store — the first on-pass
    # pays the micro-benchmark trials (reported as its own wall), the
    # steady-state passes run with the installed winner
    from tensorframes_tpu import tune as _tune_mod

    tune_store = _os.path.join(job_root, "tune.jsonl")
    prev_tune = get_config()
    dt_tune_on = dt_tune_off = float("inf")
    try:
        set_config(autotune=False)
        for i in range(iters):
            dt_tune_off = min(dt_tune_off, one(False, 500 + i))
        set_config(
            autotune=True, tune_mode="online", tune_file=tune_store
        )
        _tune_mod.reset()
        t0 = time.perf_counter()
        one(False, 600)  # the tuning pass: trials + first real run
        tune_first_pass_s = time.perf_counter() - t0
        for i in range(iters):
            dt_tune_on = min(dt_tune_on, one(False, 601 + i))
        tuned_winners = _tune_mod.snapshot()
    finally:
        set_config(
            autotune=prev_tune.autotune, tune_mode=prev_tune.tune_mode,
            tune_file=prev_tune.tune_file,
        )
        _tune_mod.reset()
    autotune_axis = {
        "off_rows_per_sec": round(n_rows / dt_tune_off, 1),
        "on_rows_per_sec": round(n_rows / dt_tune_on, 1),
        "tuning_first_pass_seconds": round(tune_first_pass_s, 4),
        "winners": tuned_winners,
    }
    set_config(max_rows_per_device_call=old_chunk)
    workers_axis = _bench_job_workers(n_rows, width, job_root)
    shutil.rmtree(job_root, ignore_errors=True)
    overhead_pct = (dt_on - dt_off) / dt_off * 100.0

    print(
        json.dumps(
            {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": round(n_rows / dt_on, 1),
                "unit": "rows/s",
                "detail": {
                    "workload": (
                        f"map_rows MLP-score job ({width}x{width} tanh MLP), "
                        f"{n_rows} x {width} f32, {blocks} journal blocks"
                    ),
                    "device": str(jax.devices()[0]),
                    "journal_off_rows_per_sec": round(n_rows / dt_off, 1),
                    "journal_on_rows_per_sec": round(n_rows / dt_on, 1),
                    "journal_overhead_pct": round(overhead_pct, 2),
                    "observability": {
                        "tracing_on_rows_per_sec": round(
                            n_rows / dt_obs_on, 1
                        ),
                        "obs_off_rows_per_sec": round(
                            n_rows / dt_obs_off, 1
                        ),
                        "overhead_pct": round(obs_overhead_pct, 2),
                        "sampler_on_rows_per_sec": round(
                            n_rows / dt_smp_on, 1
                        ),
                        "sampler_off_rows_per_sec": round(
                            n_rows / dt_smp_off, 1
                        ),
                        "sampler_overhead_pct": round(
                            sampler_overhead_pct, 2
                        ),
                        "export_on_rows_per_sec": round(
                            n_rows / dt_exp_on, 1
                        ),
                        "export_off_rows_per_sec": round(
                            n_rows / dt_exp_off, 1
                        ),
                        "export_overhead_pct": round(
                            export_overhead_pct, 2
                        ),
                    },
                    "autotune": autotune_axis,
                    "seconds_per_job": {
                        "journal_off": round(dt_off, 4),
                        "journal_on": round(dt_on, 4),
                    },
                    "workers": workers_axis,
                },
            }
        )
    )


_DIST_WORKER_SCRIPT = r"""
import os, sys
import numpy as np
import tensorframes_tpu as tft
from tensorframes_tpu.utils import set_config

tft.enable_compilation_cache()
path, wid, ready, go = sys.argv[1:5]
n_rows, width = int(sys.argv[5]), int(sys.argv[6])
set_config(max_rows_per_device_call=32768)
rng = np.random.default_rng(0)
x = rng.normal(size=(n_rows, width)).astype(np.float32)
df = tft.TensorFrame.from_columns({"features": x}).analyze()
import jax.numpy as jnp
w1 = jnp.asarray(rng.normal(size=(width, width)).astype(np.float32))
w2 = jnp.asarray(rng.normal(size=(width,)).astype(np.float32))
def score(features):
    return {"s": jnp.tanh(features @ w1) @ w2}
# genuinely warm the compile path off the clock (an unjournaled run of
# the same workload traces + compiles the identical chunked programs),
# then rendezvous on the go file — otherwise the K=1 baseline would
# absorb the one-time compile while later axis points reuse the
# persistent cache it populated, inflating scaling efficiency
tft.run_job("map_rows", score, df, journal=False)
import time
open(ready, "w").close()
while not os.path.exists(go):
    time.sleep(0.05)
rep = tft.run_worker("map_rows", score, df, path=path, worker_id=wid,
                     poll_s=0.2)
print("WORKER_DONE", wid, rep.blocks_computed)
"""


def _bench_job_workers(n_rows: int, width: int, job_root: str):
    """K-subprocess drain of one manifest (``TFT_BENCH_JOB_WORKERS``):
    aggregate rows/s per K plus scaling efficiency vs K=1. Returns the
    detail dict for the ``map_rows`` JSON line, or ``None`` when the
    axis is disabled."""
    import os
    import subprocess
    import sys

    from tensorframes_tpu.engine.dist_jobs import journal_status

    spec = os.environ.get("TFT_BENCH_JOB_WORKERS", "1,2,4").strip()
    if not spec:
        return None
    ks = [int(s) for s in spec.split(",") if s.strip()]
    out = {"counts": ks, "rows_per_sec": {}, "scaling_efficiency": {}}
    base = None  # (k, rows/s) of the first axis point
    for k in ks:
        path = os.path.join(job_root, f"dist-{k}")
        marks = os.path.join(job_root, f"marks-{k}")
        os.makedirs(marks)
        go = os.path.join(marks, "go")
        procs = []
        for i in range(k):
            ready = os.path.join(marks, f"ready-{i}")
            procs.append(
                (
                    subprocess.Popen(
                        [
                            sys.executable, "-c", _DIST_WORKER_SCRIPT,
                            path, f"bench-w{i}", ready, go,
                            str(n_rows), str(width),
                        ],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    ),
                    ready,
                )
            )
        for _, ready in procs:
            while not os.path.exists(ready):
                time.sleep(0.05)
        t0 = time.perf_counter()
        open(go, "w").close()
        for p, _ in procs:
            rc = p.wait(timeout=1800)
            assert rc == 0, f"bench worker exited {rc}"
        dt = time.perf_counter() - t0
        status = journal_status(path)
        assert status["terminal"], status
        rps = n_rows / dt
        out["rows_per_sec"][str(k)] = round(rps, 1)
        base = base if base is not None else (k, rps)
        # per-worker throughput relative to the first axis point's
        out["scaling_efficiency"][str(k)] = round(
            (rps / k) / (base[1] / base[0]), 3
        )
    return out


def main_ingest():
    """Streaming-ingest bench (``make bench-ingest``): the round-5
    pathology head-on. One 1M×784 f32 column (3.1 GB — the exact r05
    scoring workload; shrink with ``TFT_BENCH_INGEST_ROWS`` for smoke
    runs) crosses the link twice each way:

    - **monolithic**: one blocking ``jax.device_put`` / ``np.asarray`` —
      the pre-round-6 path (313.9 s at 0.01 GB/s in BENCH_r05);
    - **chunked-overlapped**: the streaming transfer layer
      (``frame/transfer.py``) with the active ``transfer_chunk_bytes`` /
      ``transfer_streams`` knobs.

    Plus the cold end-to-end ingest→upload→score wall clock through the
    engine (frame build, chunked upload, one ``map_blocks`` scoring
    pass). Exactly one JSON line; ``value`` is the chunked h2d GB/s and
    ``vs_baseline`` the speedup over monolithic on the same workload."""
    import os

    import jax

    import tensorframes_tpu as tft
    from tensorframes_tpu.engine import map_blocks
    from tensorframes_tpu.frame import transfer
    from tensorframes_tpu.models import MLPClassifier

    tft.enable_compilation_cache()
    n_rows = int(os.environ.get("TFT_BENCH_INGEST_ROWS", "1000000"))
    n_features, n_classes = 784, 10
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    gb = x.nbytes / 1e9

    # untimed warmup: first-transfer backend/allocator setup must not
    # bias the monolithic-vs-chunked comparison (both run warm)
    warm = jax.device_put(x[: min(n_rows, 1024)])
    jax.block_until_ready(np.asarray(warm))
    del warm

    # -- h2d monolithic: ONE blocking device_put (the r05 upload path) ----
    t0 = time.perf_counter()
    mono = jax.device_put(x)
    jax.block_until_ready(mono)
    dt_h2d_mono = time.perf_counter() - t0

    # -- d2h monolithic: one blocking np.asarray --------------------------
    t0 = time.perf_counter()
    back_mono = np.asarray(mono)
    dt_d2h_mono = time.perf_counter() - t0
    del back_mono
    try:
        mono.delete()
    except Exception:
        pass
    del mono

    # -- chunked + overlapped, cold end-to-end through the engine ---------
    clf = MLPClassifier.init(0, [n_features, n_classes])
    t_cold = time.perf_counter()
    df = tft.TensorFrame.from_columns({"features": x}).analyze()
    t0 = time.perf_counter()
    feat = df.column_data("features").device()
    jax.block_until_ready(feat)
    dt_h2d_chunked = time.perf_counter() - t0
    g = clf._scoring_graph(df, "features", "prediction", None)
    pred = map_blocks(g, df).column_data("prediction").device()
    jax.block_until_ready(pred)
    dt_cold = time.perf_counter() - t_cold

    # -- d2h chunked (symmetric path), with byte-identity checked ---------
    t0 = time.perf_counter()
    back = transfer.d2h(feat)
    dt_d2h_chunked = time.perf_counter() - t0
    identical = bool(np.array_equal(back, x))
    del back

    n_chunks = len(transfer._chunk_bounds(n_rows, n_features * 4))

    print(
        json.dumps(
            {
                "metric": "ingest_upload_gb_per_s",
                "value": round(gb / dt_h2d_chunked, 3),
                "unit": "GB/s",
                "vs_baseline": round(dt_h2d_mono / dt_h2d_chunked, 2),
                "detail": {
                    "workload": (
                        f"{n_rows} x {n_features} f32 column "
                        f"({gb:.2f} GB), h2d + d2h, monolithic vs "
                        f"chunked-overlapped"
                    ),
                    "device": str(jax.devices()[0]),
                    "upload_gb_per_s": {
                        "monolithic": round(gb / dt_h2d_mono, 3),
                        "chunked_overlapped": round(gb / dt_h2d_chunked, 3),
                    },
                    "upload_seconds": {
                        "monolithic": round(dt_h2d_mono, 3),
                        "chunked_overlapped": round(dt_h2d_chunked, 3),
                    },
                    "fetch_gb_per_s": {
                        "monolithic": round(gb / dt_d2h_mono, 3),
                        "chunked": round(gb / dt_d2h_chunked, 3),
                    },
                    "cold_ingest_upload_score_seconds": round(dt_cold, 3),
                    "chunks": n_chunks,
                    "transfer": _transfer_settings(),
                    "byte_identity": identical,
                },
            }
        )
    )
    assert identical, "chunked transfer round-trip is not byte-identical"


def main_autotune():
    """The self-tuning layer's headline numbers (``make bench-autotune``,
    ISSUE 13): against a throwaway store,

    - **cold-tune wall**: the first ``map_rows`` pass in ``online``
      mode — micro-benchmark trials included — vs the **cached-tune
      wall**: the same pass after ``tune.reset()`` (a fresh process's
      memo) resolving every winner from the persisted store with ZERO
      trials. Cached ≪ cold is the persistence-round-trip acceptance
      criterion, asserted via the tuner's own counters;
    - **tuned-vs-static rows/s** on the map_rows smoke shape and
      **tuned-vs-static tok/s** on the decode_serve smoke shape (static
      = ``TFT_TUNE=0`` semantics; tuned = winners installed), plus the
      serving-knob search wall (``tune.tune_serve_knobs``).

    One JSON line. ``TFT_BENCH_ROWS`` shrinks the map_rows shape;
    ``TFT_BENCH_TUNE_BUDGET_S`` bounds each signature's search."""
    import os
    import shutil
    import tempfile

    import jax

    import tensorframes_tpu as tft
    from tensorframes_tpu import tune
    from tensorframes_tpu.engine import run_job
    from tensorframes_tpu.models import TransformerLM
    from tensorframes_tpu.obs import metrics as obs_metrics
    from tensorframes_tpu.utils import get_config, set_config

    tft.enable_compilation_cache()
    tmp = tempfile.mkdtemp(prefix="tft-bench-autotune-")
    store = os.path.join(tmp, "tune.jsonl")
    n_rows = int(os.environ.get("TFT_BENCH_ROWS", "") or 200_000)
    width = 256
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, width)).astype(np.float32)
    df = tft.TensorFrame.from_columns({"features": x}).analyze()

    import jax.numpy as jnp

    w1 = jnp.asarray(rng.normal(size=(width, width)).astype(np.float32))
    w2 = jnp.asarray(rng.normal(size=(width,)).astype(np.float32))

    def score(features):
        return {"s": jnp.tanh(features @ w1) @ w2}

    def one_map(i):
        t0 = time.perf_counter()
        res = run_job(
            "map_rows", score, df, journal=False, job_dir=tmp,
            job_id=f"bench-autotune-{i}",
        )
        assert res.completed.num_rows == n_rows
        return time.perf_counter() - t0

    def trials_total():
        snap = obs_metrics.snapshot().get("tune.trials_total", {})
        return float(sum((snap.get("values") or {}).values()))

    prev = get_config()
    budget = float(
        os.environ.get("TFT_BENCH_TUNE_BUDGET_S", "") or 5.0
    )
    iters = 3
    try:
        set_config(
            autotune=True, tune_mode="online", tune_file=store,
            tune_budget_s=budget, max_rows_per_device_call=32768,
        )
        tune.reset()
        # static leg (kill-switch semantics), warmed
        set_config(autotune=False)
        one_map(-1)
        dt_static = min(one_map(i) for i in range(iters))
        # cold tune: first online pass pays the trials
        set_config(autotune=True)
        t0 = time.perf_counter()
        one_map(100)
        cold_wall = time.perf_counter() - t0
        trials_cold = trials_total()
        # cached tune: a "fresh process" (memo dropped) resolves every
        # winner from the persisted store — zero trials
        tune.reset()
        t0 = time.perf_counter()
        one_map(101)
        cached_wall = time.perf_counter() - t0
        trials_cached = trials_total() - trials_cold
        dt_tuned = min(one_map(200 + i) for i in range(iters))
        map_winners = tune.snapshot()

        # -- decode_serve smoke shape -----------------------------------
        lm = TransformerLM.init(0, 256, d_model=32, n_heads=4, max_len=192)
        plen, max_new, slots = 64, 32, 4
        t0 = time.perf_counter()
        serve_winners = tune.tune_serve_knobs(
            lm, max_seq_len=plen + max_new, prompt_len=plen,
            max_new_tokens=8, max_slots=slots, repeats=1,
            budget_s=budget,
        )
        serve_tune_wall = time.perf_counter() - t0
        set_config(autotune=False)
        serve_static = _serve_one_concurrency(
            lm, slots, plen, max_new, 0, page_size=None
        )
        set_config(autotune=True, tune_mode="cached")
        tune.reset()
        serve_tuned = _serve_one_concurrency(
            lm, slots, plen, max_new, 0, page_size=None
        )
    finally:
        set_config(
            autotune=prev.autotune, tune_mode=prev.tune_mode,
            tune_file=prev.tune_file, tune_budget_s=prev.tune_budget_s,
            max_rows_per_device_call=prev.max_rows_per_device_call,
        )
        tune.reset()
        shutil.rmtree(tmp, ignore_errors=True)

    print(
        json.dumps(
            {
                "metric": "autotune_cached_tune_speedup",
                "value": round(cold_wall / max(cached_wall, 1e-9), 2),
                "unit": "x (cold-tune wall / cached-tune wall)",
                "detail": {
                    "device": str(jax.devices()[0]),
                    "tune_budget_s": budget,
                    "map_rows": {
                        "rows": n_rows,
                        "cold_tune_wall_s": round(cold_wall, 4),
                        "cached_tune_wall_s": round(cached_wall, 4),
                        "trials_cold": trials_cold,
                        "trials_cached": trials_cached,
                        "static_rows_per_sec": round(n_rows / dt_static, 1),
                        "tuned_rows_per_sec": round(n_rows / dt_tuned, 1),
                        "winners": map_winners,
                    },
                    "decode_serve": {
                        "serve_knob_search_wall_s": round(
                            serve_tune_wall, 3
                        ),
                        "static_tokens_per_sec": serve_static[
                            "tokens_per_sec"
                        ],
                        "tuned_tokens_per_sec": serve_tuned[
                            "tokens_per_sec"
                        ],
                        "winners": serve_winners,
                    },
                },
            }
        )
    )


if __name__ == "__main__":
    import sys

    if len(sys.argv) > 1 and sys.argv[1] == "decode_serve":
        main_decode_serve()
    elif len(sys.argv) > 1 and sys.argv[1] == "paged_attn":
        main_paged_attn()
    elif len(sys.argv) > 1 and sys.argv[1] == "map_rows":
        main_map_rows_journal()
    elif len(sys.argv) > 1 and sys.argv[1] == "ingest":
        main_ingest()
    elif len(sys.argv) > 1 and sys.argv[1] == "pipeline":
        main_pipeline()
    elif len(sys.argv) > 1 and sys.argv[1] == "autotune":
        main_autotune()
    else:
        main()
