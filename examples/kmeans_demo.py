"""Distributed k-means demo + micro-benchmark.

Port of the reference's
``/root/reference/src/main/python/tensorframes_snippets/kmeans_demo.py:198-255``
harness: synthetic blobs, framework k-means (in-graph pre-aggregation +
global reduce) vs a pure-numpy Lloyd baseline, with wall-clock timings.

Run: ``python examples/kmeans_demo.py [n_rows] [dim] [k]``
"""

import sys
import time

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.models import assign_clusters, kmeans


def numpy_kmeans(data, k, iters, seed):
    rng = np.random.default_rng(seed)
    c = data[rng.choice(len(data), k, replace=False)].copy()
    for _ in range(iters):
        d2 = ((data[:, None, :] - c[None, :, :]) ** 2).sum(-1)
        closest = d2.argmin(1)
        for j in range(k):
            m = closest == j
            if m.any():
                c[j] = data[m].mean(0)
    return c


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 100_000
    dim = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    k = int(sys.argv[3]) if len(sys.argv) > 3 else 10
    iters = 10
    rng = np.random.default_rng(42)
    centers = rng.normal(0, 10, (k, dim))
    data = (
        centers[rng.integers(0, k, n)] + rng.normal(0, 1, (n, dim))
    ).astype(np.float32)

    df = tft.TensorFrame.from_columns({"features": data}, num_partitions=4)
    df = tft.analyze(df)

    kmeans(df, "features", k=k, num_iters=1, seed=0)  # absorb XLA compile
    t0 = time.perf_counter()
    centroids, history = kmeans(df, "features", k=k, num_iters=iters, seed=0)
    t_tft = time.perf_counter() - t0
    print(f"tensorframes_tpu kmeans: {t_tft:.3f}s warm, final shift {history[-1]:.4f}")

    t0 = time.perf_counter()
    numpy_kmeans(data, k, iters, 0)
    t_np = time.perf_counter() - t0
    print(f"numpy kmeans:            {t_np:.3f}s  ({t_np / t_tft:.2f}x)")

    assigned = assign_clusters(df, "features", centroids)
    counts = np.bincount(
        np.asarray(assigned.column_block("closest_centroid")), minlength=k
    )
    print("cluster sizes:", counts.tolist())


if __name__ == "__main__":
    main()
