"""Score a published pre-trained model over a table of real encoded images.

The reference's flagship production story (``read_image.py``): download a
pre-trained frozen VGG-16, then score every image in a Spark DataFrame of
raw bytes through the frame ops. This example is that story TPU-native,
with the publisher side played by torch (the ecosystem most checkpoints
are published from):

1. a torch CNN's ``state_dict`` is saved to ``.safetensors`` — an
   externally-produced checkpoint, exactly what a model hub serves;
2. ``CNNScorer.from_pretrained`` imports it: NCHW/OIHW kernels are
   transposed to the NHWC/HWIO layout XLA tiles onto the MXU, and the
   post-flatten dense layer's input axis is re-ordered (torch flattens
   C*H*W, TPU flattens H*W*C — a plain transpose scores garbage);
3. a frame holds one PNG-encoded byte cell per row (``sc.binaryFiles``
   parity); ``map_blocks(decoders=)`` runs the REAL image codec on a
   host thread pool several partitions ahead of the chip;
4. tracing the scoring closure bakes the imported arrays into the XLA
   program — the freezing step (reference ``core.py:41-55``) — and the
   embeddings are checked against the torch model itself as the oracle.

Run: python examples/pretrained_scoring.py
"""

import os
import tempfile

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.data import encode_image
from tensorframes_tpu.models import CNNScorer

HW, C, EMBED = (32, 32), 3, 64


def publish_checkpoint(path: str):
    """The external publisher: a torch VGG-style net, saved the way model
    hubs publish weights. (Stands in for the reference's VGG-16 download,
    ``read_image.py:29-44`` — same flow, hub-scale weights drop in.)"""
    import torch

    torch.manual_seed(0)
    model = torch.nn.Sequential(
        torch.nn.Conv2d(C, 16, 3, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(16, 16, 3, padding=1), torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(16, 32, 3, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(32, 32, 3, padding=1), torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Flatten(),
        torch.nn.Linear(32 * (HW[0] // 4) * (HW[1] // 4), EMBED),
    )
    model.eval()
    from safetensors.torch import save_file

    save_file(model.state_dict(), path)
    return model


def main():
    n_rows = 256
    with tempfile.TemporaryDirectory() as tmp:
        ckpt = os.path.join(tmp, "published.safetensors")
        torch_model = publish_checkpoint(ckpt)
        print(f"published checkpoint: {os.path.getsize(ckpt) / 1e3:.0f} kB")

        # a table of REAL encoded images (PNG bytes per row)
        rng = np.random.default_rng(1)
        imgs = rng.integers(0, 256, size=(n_rows, *HW, C), dtype=np.uint8)
        df = tft.TensorFrame.from_columns(
            {"image_data": [encode_image(im) for im in imgs]},
            num_partitions=8,
        )

        # import + freeze + score (decode overlaps chip compute). The MXU
        # runs f32 matmuls as bf16 passes by default (~2e-3 rel); for the
        # oracle comparison trace the program at full f32 precision —
        # production scoring would keep the fast default (or bf16)
        import jax

        scorer = CNNScorer.from_pretrained(
            ckpt, input_hw=HW, channels=C, convs_per_block=2
        )
        with jax.default_matmul_precision("float32"):
            out = scorer.score_frame(df, "image_data", compute_dtype=None)
            emb = np.asarray(out.column_data("embedding").host())
        print(f"scored {emb.shape[0]} rows -> embeddings {emb.shape}")

        # oracle: the torch model itself on the same pixels
        import torch

        x = torch.from_numpy(
            imgs.astype(np.float32).transpose(0, 3, 1, 2) / 255.0
        )
        with torch.no_grad():
            oracle = torch_model(x).numpy()
        rel = np.abs(emb - oracle).max() / (np.abs(oracle).max() + 1e-12)
        print(f"max rel deviation vs torch oracle: {rel:.2e}")
        assert rel < 1e-3, "imported scoring diverged from the publisher model"
        print("imported-weight scoring matches the publisher model")


if __name__ == "__main__":
    main()
