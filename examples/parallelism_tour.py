"""Tour of the five mesh axes: dp / tp / sp / ep / pp on one machine.

Every strategy runs against its oracle. Works anywhere: if fewer than 8
devices are attached, the script provisions 8 virtual CPU devices (the
same mechanism the test suite and the driver's multichip dryrun use), so
the sharding semantics are identical to a real 8-chip slice.

Run: ``python examples/parallelism_tour.py``
"""

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    # size the CPU backend at 8 virtual devices BEFORE backends initialize
    # (harmless when 8 real chips exist — it only affects the CPU platform);
    # the same mechanism __graft_entry__.dryrun_multichip uses
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except Exception:
        pass
    devs = jax.devices()
    if len(devs) < 8:
        devs = jax.devices("cpu")
    if len(devs) < 8:
        print(f"needs 8 devices, found {len(devs)}")
        return
    devs = devs[:8]
    # pin single-device oracles to the same backend as the meshes —
    # otherwise a machine whose default device is a TPU computes oracles
    # in bf16 MXU precision while the mesh runs f32 on CPU, and the
    # "error" printed is just the precision gap
    ctx = jax.default_device(devs[0])
    ctx.__enter__()

    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par
    from tensorframes_tpu.models import TransformerLM
    from tensorframes_tpu.ops import (
        attention_reference,
        ring_attention,
        ulysses_attention,
    )

    rng = np.random.default_rng(0)

    # dp: rows sharded over chips — distributed dataframe ops
    df = tft.TensorFrame.from_columns(
        {"x": rng.normal(size=100_000).astype(np.float32)}, num_partitions=8
    )
    mesh_dp = par.make_mesh({"dp": 8}, devices=devs)
    total = par.reduce_blocks(
        lambda x_input: {"x": x_input.sum()}, df, mesh=mesh_dp
    )
    print(f"dp  reduce over 8 shards: {float(total):.2f}")

    # dp x tp: sharded SGD (batch over dp, Megatron weights over tp)
    trainer = par.ShardedSGDTrainer(
        [16, 32, 4], mesh=par.make_mesh({"dp": 4, "tp": 2}, devices=devs)
    )
    x = rng.normal(size=(64, 16)).astype(np.float32)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    _, losses = trainer.fit(x, y, steps=5)
    print(f"tp  dp4xtp2 SGD: loss {losses[0]:.3f} -> {losses[-1]:.3f}")

    # sp: ring and ulysses sequence parallelism vs the dense oracle
    mesh_sp = par.make_mesh({"sp": 8}, devices=devs)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 8, 64, 16)).astype(np.float32))
        for _ in range(3)
    )
    ref = attention_reference(q, k, v, causal=True)
    for name, fn in (("ring", ring_attention), ("ulysses", ulysses_attention)):
        out = fn(q, k, v, mesh=mesh_sp, causal=True)
        err = float(jnp.max(jnp.abs(out - ref)))
        print(f"sp  {name} attention over 8 chips: max err {err:.1e}")

    # ep: expert-parallel MoE, masked and all-to-all-routed
    mesh_ep = par.make_mesh({"ep": 8}, devices=devs)
    p = par.init_moe(0, d_model=16, d_ff=32, n_experts=16)
    toks = jnp.asarray(rng.normal(size=(4, 16, 16)).astype(np.float32))
    dense = par.moe_ffn(p, toks)
    masked = par.moe_apply(p, toks, mesh=mesh_ep)
    routed = par.moe_dispatch_apply(p, toks, mesh=mesh_ep, capacity_factor=8.0)
    print(
        f"ep  MoE 16 experts over 8 chips: masked err "
        f"{float(jnp.max(jnp.abs(masked - dense))):.1e}, routed err "
        f"{float(jnp.max(jnp.abs(routed - dense))):.1e}"
    )

    # pp: GPipe pipeline, one stage per chip
    mesh_pp = par.make_mesh({"pp": 8}, devices=devs)
    stages = {
        "w": rng.normal(0, 0.3, (8, 12, 12)).astype(np.float32),
        "b": rng.normal(0, 0.1, (8, 12)).astype(np.float32),
    }

    def stage_fn(sp, h):
        return jnp.tanh(h @ sp["w"] + sp["b"])

    xb = rng.normal(size=(16, 12)).astype(np.float32)
    got = par.pipeline_apply(stage_fn, stages, xb, n_micro=4, mesh=mesh_pp)
    want = par.pipeline_reference(stage_fn, stages, jnp.asarray(xb))
    print(
        f"pp  8-stage pipeline, 4 microbatches: max err "
        f"{float(jnp.max(jnp.abs(got - want))):.1e}"
    )

    # dp x sp composed in ONE train step (batch-sharded ring attention)
    lm = TransformerLM.init(0, vocab=32, d_model=16, n_heads=4, max_len=17)
    toks2 = rng.integers(0, 32, size=(8, 17)).astype(np.int32)
    l2 = lm.fit_sharded(toks2, par.make_mesh({"dp": 2, "sp": 4}, devices=devs), steps=4)
    print(f"dpxsp transformer step: loss {l2[0]:.3f} -> {l2[-1]:.3f}")

    # dp x tp: the TRANSFORMER itself Megatron-sharded (GSPMD annotations;
    # compare the dp x tp MLP trainer above) — same trajectory as 1 chip
    lm_tp = TransformerLM.init(0, vocab=32, d_model=16, n_heads=4, max_len=17)
    l3 = lm_tp.fit_tp(
        toks2, par.make_mesh({"dp": 2, "tp": 4}, devices=devs), steps=4
    )
    print(f"dpxtp transformer step: loss {l3[0]:.3f} -> {l3[-1]:.3f}")

    # pp x dp: 1F1B pipeline training with full-model grads
    lm_pp = TransformerLM.init(
        0, vocab=32, d_model=16, n_heads=4, n_layers=4, max_len=17
    )
    l4 = lm_pp.fit_pipelined(
        toks2,
        par.make_mesh({"pp": 4, "dp": 2}, devices=devs),
        steps=4,
        n_micro=2,
        schedule="1f1b",
    )
    print(f"ppxdp transformer step: loss {l4[0]:.3f} -> {l4[-1]:.3f}")


if __name__ == "__main__":
    main()
