"""Train and score a small transformer LM over frames.

The reference has no model training at all — its models are frozen graphs
scored through the dataframe ops (SURVEY §5: "no trainable-state
checkpointing at all"). This example shows the pieces this framework adds
on top of reference parity:

1. fit a causal LM on synthetic tokens (single jitted SGD step);
2. score a TensorFrame of token rows with the trained model through
   ``map_blocks`` (the frozen-graph path, reference ``core.py:41-55``);
3. run the same logits with ring attention — sequence parallelism over an
   ``sp`` mesh axis (needs >1 device; skipped on a single chip).

Run: ``python examples/train_lm.py``
"""

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.models import TransformerLM, transformer_logits


def main():
    import jax

    rng = np.random.default_rng(0)
    vocab, seq, batch = 64, 32, 16

    # synthetic corpus with learnable structure: next token = 2x+1 mod V
    start = rng.integers(0, vocab, size=(256, 1))
    mult = np.arange(seq)
    tokens = ((start * (2**mult)) + (2**mult - 1)) % vocab
    tokens = tokens.astype(np.int32)

    lm = TransformerLM.init(0, vocab, d_model=32, n_heads=4, n_layers=2, max_len=seq)
    losses = lm.fit(tokens[:batch], steps=30, lr=0.3)
    print(f"train nll: {losses[0]:.3f} -> {losses[-1]:.3f}")
    assert losses[-1] < losses[0]

    # frame scoring: per-row NLL as a new column
    df = tft.TensorFrame.from_columns({"tokens": tokens[batch : batch + 64]})
    scored = lm.score_frame(df, "tokens", loss_col="nll")
    nll = np.asarray(scored.cache().column_block("nll"))
    print(f"scored {len(nll)} rows, mean nll {nll.mean():.3f}")

    # KV-cached generation: the trained model continues the pattern. The
    # synthetic rule (next = 2x+1 mod V) is learnable, so greedy decode
    # should follow it much better than chance after training.
    prompt = tokens[:4, :4]
    gen = lm.generate(prompt, max_new_tokens=8)
    cont = gen[:, 4:]
    expect = ((prompt[:, -1:].astype(np.int64) + 1) * (2 ** np.arange(1, 9)) - 1) % vocab
    acc = float((cont == expect).mean())
    print(f"greedy decode follows the learned rule at {acc:.0%} (chance ~{1/vocab:.1%})")

    # sampled decode: temperature + top-k + nucleus, seeds swept through
    # ONE compiled program (seed/temperature/top_p are traced arguments)
    for seed in (0, 1):
        s = lm.generate(
            prompt[:1], max_new_tokens=8,
            temperature=0.8, seed=seed, top_k=8, top_p=0.95,
        )
        print(f"sampled decode (seed {seed}): {s[0, 4:].tolist()}")
    assert len(lm._generate_cache) <= 2  # greedy + ONE sampled program

    # ragged prompts: variable-length rows decode in ONE left-padded batch,
    # each exactly as it would alone
    from tensorframes_tpu.models import left_pad_prompts

    packed, lens = left_pad_prompts(
        [tokens[0, :2].tolist(), tokens[1, :5].tolist(), tokens[2, :3].tolist()]
    )
    ragged = lm.generate(packed, max_new_tokens=6, prompt_lengths=lens)
    solo = lm.generate(tokens[1:2, :5], max_new_tokens=6)
    np.testing.assert_array_equal(ragged[1, packed.shape[1]:], solo[0, 5:])
    print(f"ragged batch decode matches per-row decode for lengths {lens.tolist()}")

    # ring attention (sequence parallelism) when a mesh is available
    n = len(jax.devices())
    if n >= 2 and seq % n == 0:
        from tensorframes_tpu.parallel import make_mesh

        mesh = make_mesh({"sp": n})
        ring = transformer_logits(
            lm.params, tokens[:4], attn_impl="ring", mesh=mesh
        )
        dense = transformer_logits(lm.params, tokens[:4])
        err = float(np.max(np.abs(np.asarray(ring) - np.asarray(dense))))
        print(f"ring vs dense logits, max abs err {err:.2e} over sp={n}")
    else:
        print(f"ring attention skipped ({n} device(s))")


if __name__ == "__main__":
    main()
