"""Geometric & harmonic means over a frame column.

Port of the reference snippet
``/root/reference/src/main/python/tensorframes_snippets/geom_mean.py:26-49``:
log/invert in a map, sum via keyed aggregation, finish on the host.

Run: ``python examples/geom_mean.py`` (any backend; CPU is fine).
"""

import numpy as np

import tensorframes_tpu as tft


def geometric_mean(df, col: str) -> float:
    import jax.numpy as jnp

    df2 = tft.map_blocks(
        lambda x: {"logx": jnp.log(x), "cnt": jnp.ones_like(x)},
        df,
        feed_dict={"x": col},
    )
    logsum = tft.reduce_blocks(
        lambda logx_input: {"logx": logx_input.sum()}, df2
    )
    n = df.num_rows
    return float(np.exp(logsum / n))


def harmonic_mean(df, col: str) -> float:
    df2 = tft.map_blocks(
        lambda x: {"invx": 1.0 / x}, df, feed_dict={"x": col}
    )
    invsum = tft.reduce_blocks(
        lambda invx_input: {"invx": invx_input.sum()}, df2
    )
    return float(df.num_rows / invsum)


def main():
    data = np.array([1.0, 2.0, 4.0, 8.0])
    df = tft.TensorFrame.from_columns({"x": data})
    gm = geometric_mean(df, "x")
    hm = harmonic_mean(df, "x")
    print(f"geometric mean: {gm:.6f} (expect {data.prod() ** (1 / 4):.6f})")
    print(f"harmonic  mean: {hm:.6f} (expect {4 / (1 / data).sum():.6f})")


if __name__ == "__main__":
    main()
