"""Binary-column image scoring: frozen CNN over raw image bytes.

The reference's flagship binary workload scores a frozen VGG-16 over
``sc.binaryFiles`` with ``map_rows`` and a ``feed_dict``-bound string
tensor, decoding inside the TF graph
(``/root/reference/src/main/python/tensorframes_snippets/read_image.py:147-167``).

The TPU-native version splits that pipeline where the hardware wants it
split: the codec runs on the host (``decode_column``'s thread pool — TPUs
have no string type), and the conv net runs batched on device, one XLA
program per partition block instead of one Session.run per row. The model
is "frozen" the same way the reference freezes variables into the GraphDef
(``core.py:41-55``): parameters are closed over as constants in the
captured program.

Run: ``python examples/image_scoring.py``
"""

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.models import CNNScorer


def main():
    rng = np.random.default_rng(0)
    scorer = CNNScorer.init(0, input_hw=(32, 32), channels=3, embed_dim=256)

    # "images": raw packed uint8 HWC bytes (a real deployment points
    # decode_column at an actual codec instead)
    n = 2_000
    raws = [
        rng.integers(0, 256, size=(32, 32, 3), dtype=np.uint8).tobytes()
        for _ in range(n)
    ]
    df = tft.TensorFrame.from_columns({"image_data": raws}, num_partitions=4)

    scored = scorer.score_frame(df, "image_data")  # decode runs here;
    # device scoring stays lazy until the embedding column is accessed
    emb = np.asarray(scored.cache().column_block("embedding"))
    print(f"scored {n} images -> embeddings {emb.shape}, "
          f"norm[0]={np.linalg.norm(emb[0]):.3f}")
    assert emb.shape == (n, 256)

    # the same program scales over a device mesh unchanged:
    #   from tensorframes_tpu import parallel
    #   scorer.score_frame(df, "image_data", engine=parallel)


if __name__ == "__main__":
    main()
