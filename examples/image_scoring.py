"""Binary-column image scoring via ``map_rows``.

Port of the reference's VGG image-scoring snippet
(``/root/reference/src/main/python/tensorframes_snippets/read_image.py:147-167``):
a frame holds raw encoded bytes in a binary column; a row program decodes on
the host and scores with a captured model. Here the "decode" is a toy parser
(no image codecs in this environment) and the model is an MLP — the data
path (binary host decode -> device scoring) is the same.

Run: ``python examples/image_scoring.py``
"""

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.models import MLPClassifier


def main():
    rng = np.random.default_rng(0)
    clf = MLPClassifier.init(0, [64, 10])

    # "images": raw little-endian f32 bytes, 64 values each
    raws = [rng.normal(size=64).astype(np.float32).tobytes() for _ in range(20)]
    df = tft.TensorFrame.from_columns({"image_data": raws})

    def score(image_data):
        # host decode (binary rows run on the host path), device-free math
        x = np.frombuffer(image_data, dtype=np.float32)
        from tensorframes_tpu.models.mlp import mlp_logits

        logits = np.asarray(mlp_logits(clf.params, x[None]))[0]
        return {"label": np.int32(logits.argmax()), "score": logits.max()}

    scored = tft.map_rows(score, df)
    rows = scored.collect()
    print("first rows:", [(r.label, round(float(r.score), 3)) for r in rows[:5]])
    assert len(rows) == 20


if __name__ == "__main__":
    main()
