"""Serve a frozen model from the TPU host; remote workers stream Arrow.

The reference ran its engine inside every Spark executor (compute went
to the partitions because every executor had CPU TensorFlow). TPUs
invert that: executors have no chips, so partitions come to the
accelerator. This example runs the full inverted pattern in one process
tree:

1. the TPU host starts a :class:`ScoringServer` over a captured scoring
   program (weights frozen into the program at trace time);
2. "executors" — here worker threads, in production Spark tasks via
   ``remote_map_in_arrow(spark_df, addr, schema)`` — connect with ONLY
   socket + pyarrow and stream their partition as one Arrow IPC
   connection each;
3. results stream back; each connection's rows formed one logical
   block, so cross-row programs see partition semantics.

Run: python examples/remote_scoring.py
"""

import threading

import numpy as np
import pyarrow as pa

from tensorframes_tpu.interop import ScoringServer, remote_arrow_mapper


def main():
    rng = np.random.default_rng(0)
    n_features, n_parts, rows_per_part = 32, 4, 5000
    w = rng.normal(size=(n_features,)).astype(np.float32)

    def score(features):
        # frozen at trace time, exactly like the reference's
        # variable-freezing (core.py:41-55); also a cross-row stat to
        # prove partition semantics survive the wire
        s = features @ w
        return {"score": s, "rank_in_partition": s.argsort().argsort()}

    parts = [
        rng.normal(size=(rows_per_part, n_features)).astype(np.float32)
        for _ in range(n_parts)
    ]

    results = [None] * n_parts
    with ScoringServer(score, feed_dict={"features": "x"}) as addr:
        print(f"serving on {addr}")
        fn = remote_arrow_mapper(addr)  # what Spark would pickle to tasks

        def executor(i):
            table = pa.table({
                "x": pa.FixedSizeListArray.from_arrays(
                    pa.array(parts[i].ravel(), type=pa.float32()),
                    n_features,
                )
            })
            results[i] = pa.Table.from_batches(
                list(fn(table.to_batches(max_chunksize=512)))
            )

        threads = [
            threading.Thread(target=executor, args=(i,))
            for i in range(n_parts)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    total = 0
    for i, out in enumerate(results):
        scores = out.column("score").to_numpy()
        ranks = out.column("rank_in_partition").to_numpy()
        # rtol sized for the MXU's default bf16-pass f32 matmuls
        # (~2e-3 rel vs the numpy f64 oracle — docs/perf.md)
        np.testing.assert_allclose(scores, parts[i] @ w, rtol=5e-3, atol=1e-3)
        # the rank column proves the whole partition formed one block
        assert sorted(ranks) == list(range(rows_per_part))
        total += len(scores)
    print(f"scored {total} rows across {n_parts} remote partitions; "
          f"partition-block semantics verified")


if __name__ == "__main__":
    main()
