# Reproducible test/dev environment (CPU; the virtual 8-device mesh the
# test suite uses). The reference ships Nix envs (default.nix:1-16); this
# is the container equivalent. For TPU hosts, install the matching
# jax[tpu] wheel instead of the CPU jaxlib pin.
#
#   docker build -t tensorframes-tpu .
#   docker run --rm tensorframes-tpu                 # run the test suite
#   docker run --rm tensorframes-tpu python __graft_entry__.py 8
FROM python:3.12-slim

# g++ builds the native packer/executor (ctypes .so) on first use
RUN apt-get update && apt-get install -y --no-install-recommends \
    g++ make && rm -rf /var/lib/apt/lists/*

WORKDIR /opt/tensorframes-tpu
COPY requirements.lock ./
RUN pip install --no-cache-dir -r requirements.lock

COPY . .
RUN pip install --no-cache-dir -e .

ENV JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8

CMD ["python", "-m", "pytest", "tests/", "-q"]
