"""Version gates for known environment-dependent test failures.

The parallel layer calls the TOP-LEVEL ``jax.shard_map`` API; jax
releases before 0.5 expose only ``jax.experimental.shard_map``, so on
those every code path that crosses a mesh (ring/ulysses attention,
distributed engine ops, expert-parallel MoE, pipeline training) raises
``AttributeError: module 'jax' has no attribute 'shard_map'`` before any
real work happens. Rather than leave that as 36 red tier-1 entries on
such environments, the affected tests carry this EXPLICIT gate: the
failure mode is a known jax-version gap, not a regression, and the skip
reason says exactly that. On jax >= 0.5 the gate is inert and the tests
run.

(Kept out of ``conftest.py`` so the gate is imported by exactly the
modules that need it and greppable as one symbol.)
"""

import jax
import pytest

#: True when this jax exposes the top-level ``jax.shard_map`` the
#: parallel layer targets
HAS_SHARD_MAP = hasattr(jax, "shard_map")

requires_shard_map = pytest.mark.skipif(
    not HAS_SHARD_MAP,
    reason=(
        f"jax {jax.__version__} has no top-level jax.shard_map (added in "
        f"jax 0.5); the parallel layer targets that API, so every "
        f"mesh-crossing path fails with AttributeError on this version — "
        f"known version gap, not a regression"
    ),
)
