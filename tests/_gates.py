"""Version gates for known environment-dependent test failures.

The parallel layer builds every mesh-crossing program through
``tensorframes_tpu.parallel.compat.shard_map``, which resolves the
top-level ``jax.shard_map`` API (jax >= 0.5) and FALLS BACK to
``jax.experimental.shard_map.shard_map`` on older releases (translating
``check_vma`` to the old ``check_rep`` spelling) — so jax 0.4.x
environments run the full suite instead of skipping it (ISSUE 14
satellite; these used to be 36 version-skips). The gate below is now a
last resort: it fires only on a jax that offers NEITHER API, where
every mesh-crossing path genuinely cannot build.

(Kept out of ``conftest.py`` so the gate is imported by exactly the
modules that need it and greppable as one symbol.)
"""

import jax
import pytest

from tensorframes_tpu.parallel.compat import has_shard_map

#: True when this jax exposes ANY shard_map the compat layer can build
#: on (top-level, or the pre-0.5 experimental module)
HAS_SHARD_MAP = has_shard_map()

requires_shard_map = pytest.mark.skipif(
    not HAS_SHARD_MAP,
    reason=(
        f"jax {jax.__version__} has neither jax.shard_map (added in jax "
        f"0.5) nor jax.experimental.shard_map; the parallel layer's "
        f"compat shim has nothing to build mesh programs on — known "
        f"version gap, not a regression"
    ),
)
