"""Pretrained-weight import: published checkpoint -> frozen TPU scoring.

The reference's flagship binary workload downloads a REAL pre-trained
VGG-16, freezes it, and scores images through the frame ops
(``read_image.py:29-55,147-167``). These tests pin the TPU-native
equivalent end to end: a torch "publisher" model's ``state_dict`` saved to
``.safetensors``/``.npz`` is imported (NCHW/OIHW -> NHWC/HWIO, flatten
re-ordering), scored through ``map_blocks(decoders=)`` over REAL encoded
PNG rows, and matched against the torch model itself as the oracle.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.data import decode_image, encode_image, image_decoder
from tensorframes_tpu.interop import (
    cnn_params_from_torch_state,
    flatten_tree,
    load_weights,
    save_weights,
    unflatten_tree,
)
from tensorframes_tpu.models import CNNScorer
from tensorframes_tpu.models.cnn import cnn_embed

HW, C = (16, 16), 3


def _publisher_model(seed=0, embed_dim=32):
    """The external model: a standard torch Sequential VGG-ette (2 blocks
    of 2 convs + pool), the architecture convention
    ``cnn_params_from_torch_state`` documents. Tests that score against
    it importorskip torch INDIVIDUALLY — the format/codec/conversion
    tests have no torch dependency and must run even where torch is
    absent (CI installs only the [test] extra)."""
    torch = pytest.importorskip("torch")
    torch.manual_seed(seed)
    m = torch.nn.Sequential(
        torch.nn.Conv2d(C, 8, 3, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(8, 8, 3, padding=1), torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Conv2d(8, 16, 3, padding=1), torch.nn.ReLU(),
        torch.nn.Conv2d(16, 16, 3, padding=1), torch.nn.ReLU(),
        torch.nn.MaxPool2d(2),
        torch.nn.Flatten(),
        torch.nn.Linear(16 * (HW[0] // 4) * (HW[1] // 4), embed_dim),
    )
    m.eval()
    return m


def _torch_embed(model, images_u8):
    """Oracle: the publisher model scoring the same uint8 HWC images."""
    import torch  # callers built `model` via _publisher_model's skip

    x = torch.from_numpy(
        images_u8.astype(np.float32).transpose(0, 3, 1, 2) / 255.0
    )
    with torch.no_grad():
        return model(x).numpy()


def _images(n=12, seed=1):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *HW, C), dtype=np.uint8)


# --------------------------------------------------------------------------
# formats


def test_weight_formats_round_trip(tmp_path):
    tree = {
        "convs": [{"k": np.ones((3, 3, 3, 8), np.float32)}],
        "embed": {"w": np.arange(6, dtype=np.float32).reshape(2, 3)},
    }
    for ext in ("npz", "safetensors"):
        p = str(tmp_path / f"w.{ext}")
        save_weights(p, tree)
        back = unflatten_tree(load_weights(p))
        np.testing.assert_array_equal(
            back["convs"][0]["k"], tree["convs"][0]["k"]
        )
        np.testing.assert_array_equal(back["embed"]["w"], tree["embed"]["w"])


def test_flatten_unflatten_inverse():
    tree = {"a": {"b": [np.zeros(1), np.ones(2)]}, "c": np.full(3, 7.0)}
    flat = flatten_tree(tree)
    assert set(flat) == {"a.b.0", "a.b.1", "c"}
    back = unflatten_tree(flat)
    assert isinstance(back["a"]["b"], list)
    np.testing.assert_array_equal(back["a"]["b"][1], np.ones(2))


def test_load_rejects_unknown_extension(tmp_path):
    with pytest.raises(ValueError, match="unsupported weight format"):
        load_weights(str(tmp_path / "w.bin"))


# --------------------------------------------------------------------------
# torch layout conversion


def test_torch_import_matches_torch_oracle(tmp_path):
    """The crux: imported weights score IDENTICALLY (f32 tolerance) to the
    torch model — including the NCHW->NHWC flatten re-ordering, which a
    naive transpose gets silently wrong."""
    model = _publisher_model()
    p = str(tmp_path / "published.safetensors")
    from safetensors.torch import save_file

    save_file(model.state_dict(), p)

    params = cnn_params_from_torch_state(
        load_weights(p), input_hw=HW, channels=C, convs_per_block=2
    )
    imgs = _images()
    ours = np.asarray(cnn_embed(params, imgs))
    oracle = _torch_embed(model, imgs)
    np.testing.assert_allclose(ours, oracle, rtol=1e-4, atol=1e-4)


def test_torch_import_order_is_name_natural_not_dict_order(tmp_path):
    """safetensors sorts keys, so '10.weight' < '2.weight' in dict order;
    the importer must order by natural module index or deep stacks wire
    layers out of sequence."""
    model = _publisher_model()
    sd = model.state_dict()
    shuffled = dict(sorted(sd.items()))  # alphabetical: 10 before 2
    params = cnn_params_from_torch_state(
        {k: v.numpy() for k, v in shuffled.items()},
        input_hw=HW,
        channels=C,
        convs_per_block=2,
    )
    imgs = _images(4)
    np.testing.assert_allclose(
        np.asarray(cnn_embed(params, imgs)),
        _torch_embed(model, imgs),
        rtol=1e-4,
        atol=1e-4,
    )


def test_torch_import_validates_channel_chain():
    state = {
        "0.weight": np.zeros((8, 4, 3, 3), np.float32),  # expects 4 ch
        "0.bias": np.zeros(8, np.float32),
        "1.weight": np.zeros((8, 8, 3, 3), np.float32),
        "1.bias": np.zeros(8, np.float32),
        "2.weight": np.zeros((5, 8 * 8 * 8), np.float32),
        "2.bias": np.zeros(5, np.float32),
    }
    with pytest.raises(ValueError, match="input channels"):
        cnn_params_from_torch_state(state, (16, 16), channels=3)


# --------------------------------------------------------------------------
# real image codec


def test_png_codec_round_trip():
    img = _images(1)[0]
    assert decode_image(encode_image(img)).tolist() == img.tolist()


def test_image_decoder_resizes_and_converts():
    img = _images(1, seed=3)[0]
    dec = image_decoder(resize_hw=(8, 8), channels=1)
    out = dec(encode_image(img))
    assert out.shape == (8, 8, 1) and out.dtype == np.uint8


# --------------------------------------------------------------------------
# end to end: published weights + encoded images through the frame ops


def test_from_pretrained_scores_real_images_via_map_blocks(tmp_path):
    model = _publisher_model()
    p = str(tmp_path / "published.npz")
    np.savez(p, **{k: v.numpy() for k, v in model.state_dict().items()})

    scorer = CNNScorer.from_pretrained(
        p, input_hw=HW, channels=C, convs_per_block=2
    )
    imgs = _images(10)
    raws = [encode_image(im) for im in imgs]  # REAL PNG bytes rows
    df = tft.TensorFrame.from_columns({"image_data": raws}, num_partitions=3)

    out = scorer.score_frame(df, "image_data", compute_dtype=None)
    emb = np.asarray(out.column_data("embedding").host())
    oracle = _torch_embed(model, imgs)  # PNG is lossless: same pixels
    np.testing.assert_allclose(emb, oracle, rtol=1e-4, atol=1e-4)
