"""Host decode stage: ``TensorFrame.decode_column`` + ``map_rows(decoders=)``.

The TPU-native replacement for the reference's decode-inside-the-graph
binary scoring (``read_image.py:147-167``): decode bytes on the host,
batch the numeric program on device — instead of one Session.run per row
(``DebugRowOps.scala:819-857``).
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import parallel
from tensorframes_tpu.frame import TensorFrame

from _gates import requires_shard_map


def _bytes_frame(n=20, dim=8, parts=3, seed=0):
    rng = np.random.default_rng(seed)
    arrays = [rng.normal(size=dim).astype(np.float32) for _ in range(n)]
    raws = [a.tobytes() for a in arrays]
    df = TensorFrame.from_columns({"data": raws}, num_partitions=parts)
    return df, arrays


def _decode(b):
    return np.frombuffer(b, dtype=np.float32)


class TestDecodeColumn:
    def test_uniform_decode_is_dense(self):
        df, arrays = _bytes_frame()
        dec = df.decode_column("data", _decode)
        assert dec.is_lazy
        block = dec.cache().column_block("data")  # dense => MXU-ready
        np.testing.assert_array_equal(np.asarray(block), np.stack(arrays))
        assert dec.num_partitions == df.num_partitions

    def test_dense_decode_feeds_map_blocks(self):
        df, arrays = _bytes_frame()
        dec = df.decode_column("data", _decode)
        out = tft.map_blocks(lambda data: {"s": data.sum(axis=1)}, dec)
        np.testing.assert_allclose(
            np.asarray(out.cache().column_block("s")),
            np.stack(arrays).sum(axis=1),
            rtol=1e-6,
        )

    def test_varying_shapes_stay_ragged(self):
        raws = [np.arange(k, dtype=np.float64).tobytes() for k in (3, 5, 3)]
        df = TensorFrame.from_columns({"d": raws})
        dec = df.decode_column("d", lambda b: np.frombuffer(b, dtype=np.float64))
        out = tft.map_rows(lambda d: {"s": d.sum()}, dec).collect()
        assert [r.s for r in out] == [3.0, 10.0, 3.0]

    def test_dst_keeps_binary_column(self):
        df, arrays = _bytes_frame(n=5)
        dec = df.decode_column("data", _decode, dst="x").cache()
        assert set(dec.columns) == {"data", "x"}
        assert isinstance(dec.column_data("data").cell(0), bytes)
        np.testing.assert_array_equal(dec.column_data("x").cell(1), arrays[1])

    def test_dst_collision_rejected(self):
        df, _ = _bytes_frame(n=5)
        df = df.decode_column("data", _decode, dst="x").cache()
        with pytest.raises(ValueError, match="already exists"):
            df.decode_column("data", _decode, dst="x")

    def test_later_cells_cast_to_probe_dtype(self):
        # row 0 decodes f32; a decoder that returns f64 for later rows gets
        # cast so the declared schema holds
        df, _ = _bytes_frame(n=4)

        def promoting(b):
            a = np.frombuffer(b, dtype=np.float32)
            return a.astype(np.float64) if b != df.column_data("data").cell(0) else a

        dec = df.decode_column("data", promoting, num_threads=0).cache()
        assert dec.column_data("data").dense.dtype == np.float32

    def test_schema_declares_decoded_type(self):
        df, _ = _bytes_frame(n=5, dim=4)
        dec = df.decode_column("data", _decode)
        info = dec.schema["data"]
        assert info.scalar_type.name == "float32"
        assert info.nesting == 1

    def test_threaded_matches_serial(self):
        df, arrays = _bytes_frame(n=200)
        a = df.decode_column("data", _decode, num_threads=0).cache()
        b = df.decode_column("data", _decode, num_threads=4).cache()
        np.testing.assert_array_equal(
            np.asarray(a.column_block("data")), np.asarray(b.column_block("data"))
        )

    def test_missing_column(self):
        df, _ = _bytes_frame(n=5)
        with pytest.raises(KeyError):
            df.decode_column("nope", _decode)


class TestMapRowsDecoders:
    def test_matches_host_path(self):
        df, arrays = _bytes_frame(n=30, dim=6)
        w = np.arange(6, dtype=np.float32)

        # host per-row path (round-1 behavior)
        host = tft.map_rows(
            lambda data: {"y": np.frombuffer(data, dtype=np.float32) @ w}, df
        ).collect()
        # decoded + batched device path
        dev = tft.map_rows(
            lambda data: {"y": data @ w}, df, decoders={"data": _decode}
        ).collect()
        np.testing.assert_allclose(
            [r.y for r in dev], [r.y for r in host], rtol=1e-5
        )

    def test_feed_dict_placeholder_key(self):
        df, arrays = _bytes_frame(n=10, dim=4)
        out = tft.map_rows(
            lambda x: {"s": x.sum()},
            df,
            feed_dict={"x": "data"},
            decoders={"x": _decode},
        ).collect()
        np.testing.assert_allclose(
            [r.s for r in out], [a.sum() for a in arrays], rtol=1e-5
        )

    def test_feed_dict_wins_over_column_name_collision(self):
        # placeholder 'x' collides with an unrelated numeric column; the
        # explicit feed_dict routing must decode 'data', not column 'x'
        rng = np.random.default_rng(0)
        arrays = [rng.normal(size=4).astype(np.float32) for _ in range(6)]
        df = TensorFrame.from_columns(
            {"x": np.arange(6.0), "data": [a.tobytes() for a in arrays]}
        )
        out = tft.map_rows(
            lambda x: {"s": x.sum()},
            df,
            feed_dict={"x": "data"},
            decoders={"x": _decode},
        ).collect()
        np.testing.assert_allclose(
            [r.s for r in out], [a.sum() for a in arrays], rtol=1e-5
        )

    def test_unresolvable_decoder_key(self):
        df, _ = _bytes_frame(n=5)
        with pytest.raises(Exception, match="nope"):
            tft.map_rows(
                lambda data: {"s": data.sum()}, df, decoders={"nope": _decode}
            )

    @requires_shard_map
    def test_distributed_decoders(self):
        df, arrays = _bytes_frame(n=64, dim=8, parts=8)
        out = parallel.map_rows(
            lambda data: {"s": data.sum()}, df, decoders={"data": _decode}
        ).collect()
        np.testing.assert_allclose(
            [r.s for r in out], [a.sum() for a in arrays], rtol=1e-5
        )
