"""Fleet telemetry plane (ISSUE 16): cross-process metric federation
(``obs/export.py`` + ``obs/aggregate.py``), per-request cost
attribution (``obs/requests.py`` + the engine's finish hook), and
drift detection over the observatory (``obs/drift.py``).

The acceptance bar: ``GET /varz?scope=fleet`` merges metrics from at
least two REAL OS processes with bucket-exact histogram quantiles (==
a hand-combined oracle); a kill -9'd exporter stays visible but
flagged stale; a chaos-injected decode-latency shift flips
``obs.drift_active`` within one evaluation window and clears after
recovery; and every completed request carries tokens / KV pages /
estimated FLOPs / tenant in its cost record.

Everything here is CPU-only, seeded, and deterministic; the suite is
tier-1 (``make test-obsfleet``). Scratch metrics use ``t.``-prefixed
names, which the docs<->code drift gate ignores by convention.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

from tensorframes_tpu import obs
from tensorframes_tpu.obs import (
    aggregate,
    drift,
    export,
    flight,
    requests as obs_requests,
    timeseries,
)
from tensorframes_tpu.interop.serving import ScoringServer
from tensorframes_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    MetricsRegistry,
    quantile_from_counts,
)
from tensorframes_tpu.utils import get_config, set_config

pytestmark = pytest.mark.obsfleet

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    from tensorframes_tpu.models import TransformerLM

    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture(autouse=True)
def _isolated_plane():
    """Each test sees an empty store / drift state / request ring and a
    disabled telemetry dir, and leaves them that way."""
    prev_tdir = get_config().telemetry_dir
    timeseries.store().reset()
    drift.monitor().reset()
    obs_requests.reset()
    yield
    set_config(telemetry_dir=prev_tdir)
    obs_requests.reset()
    drift.monitor().reset()
    timeseries.store().reset()


def _http_get(host, port, path):
    c = socket.create_connection((host, port), timeout=60)
    try:
        c.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    finally:
        c.close()
    head, _, body = buf.partition(b"\r\n\r\n")
    return head.split(b"\r\n")[0].decode(), body


# ---------------------------------------------------------------------------
# export: per-process snapshots
# ---------------------------------------------------------------------------


class TestExportSnapshot:
    def test_disabled_without_dir(self, monkeypatch):
        monkeypatch.delenv("TFT_TELEMETRY_DIR", raising=False)
        set_config(telemetry_dir="")
        assert export.telemetry_dir() == ""
        assert export.export_snapshot() is None

    def test_kill_switch_parity(self, tmp_path):
        set_config(observability=False, telemetry_dir=str(tmp_path))
        try:
            assert export.export_snapshot() is None
            assert export.autoexport() is None
            assert list(tmp_path.iterdir()) == []
        finally:
            set_config(observability=True)

    def test_snapshot_roundtrip(self, tmp_path):
        c = obs.counter("t.exp_total", "scratch", labels=("k",))
        c.inc(4, k="x")
        timeseries.store().record("t.exp_series", 100.0, 2.5)
        set_config(telemetry_dir=str(tmp_path))
        path = export.export_snapshot(now=101.0)
        assert path is not None and os.path.exists(path)
        snap = json.loads(open(path).read())
        assert snap["schema"] == export.SCHEMA_VERSION
        assert snap["proc"] == export.proc_id()
        assert snap["pid"] == os.getpid()
        assert snap["identity"]["role"] in (
            "driver", "serve-replica", "job-worker"
        )
        assert snap["metrics"]["t.exp_total"]["values"]["k=x"] == 4.0
        assert snap["series"]["t.exp_series"] == [[100.0, 2.5]]

    def test_atomic_write_leaves_no_tmp(self, tmp_path):
        set_config(telemetry_dir=str(tmp_path))
        export.export_snapshot()
        leftovers = [p for p in os.listdir(tmp_path) if ".tmp-" in p]
        assert leftovers == []

    def test_autoexport_throttles(self, tmp_path):
        set_config(
            telemetry_dir=str(tmp_path), obs_export_interval_s=3600.0
        )
        first = export.autoexport()
        second = export.autoexport()
        # whichever call was inside the throttle window returns None;
        # at most one write per interval
        assert [first, second].count(None) >= 1

    def test_exports_counter_increments(self, tmp_path):
        set_config(telemetry_dir=str(tmp_path))
        before = (
            obs.registry()
            .snapshot()["obs.telemetry_exports_total"]["values"]
            .get("", 0.0)
        )
        assert export.export_snapshot() is not None
        after = obs.registry().snapshot()[
            "obs.telemetry_exports_total"
        ]["values"][""]
        assert after == before + 1


class TestIdentity:
    def test_set_identity_round_trip(self):
        try:
            ident = export.set_identity("job-worker")
            assert ident["role"] == "job-worker"
            assert ident["pid"] == os.getpid()
            snap = obs.registry().snapshot()["build.info"]
            assert snap["labels"] == ["proc", "pid", "role", "version",
                                      "device"]
            live = {
                ls: v for ls, v in snap["values"].items() if v == 1.0
            }
            assert len(live) == 1
            assert "role=job-worker" in next(iter(live))
        finally:
            export.set_identity("driver")

    def test_role_change_zeroes_former_series(self):
        try:
            export.set_identity("job-worker")
            export.set_identity("serve-replica")
            values = obs.registry().snapshot()["build.info"]["values"]
            for ls, v in values.items():
                if "role=job-worker" in ls:
                    assert v == 0.0
                if "role=serve-replica" in ls:
                    assert v == 1.0
        finally:
            export.set_identity("driver")

    def test_proc_id_env_override(self, monkeypatch):
        monkeypatch.setenv("TFT_PROC_ID", "replica-7")
        assert export.proc_id() == "replica-7"


# ---------------------------------------------------------------------------
# aggregate: read-side merge semantics
# ---------------------------------------------------------------------------


def _snap(proc, mtime, metrics=None, series=None, role="driver"):
    return {
        "schema": export.SCHEMA_VERSION,
        "proc": proc,
        "pid": 1,
        "ts_unix": mtime,
        "identity": {"role": role, "version": "0", "device": "cpu",
                     "host": "h"},
        "metrics": metrics or {},
        "series": series or {},
        "_mtime": mtime,
    }


def _hist_value(values):
    """Observe ``values`` into a scratch registry histogram and return
    its snapshot value dict — the per-process payload shape."""
    reg = MetricsRegistry()
    h = reg.histogram("t.h", "oracle")
    for v in values:
        h.observe(v)
    return reg.snapshot()["t.h"]["values"][""]


class TestAggregateMerge:
    def test_counters_sum_per_label(self):
        a = _snap("a", 100.0, metrics={
            "t.c": {"type": "counter", "help": "", "labels": ["k"],
                    "values": {"k=x": 3.0, "k=y": 1.0}},
        })
        b = _snap("b", 100.0, metrics={
            "t.c": {"type": "counter", "help": "", "labels": ["k"],
                    "values": {"k=x": 5.0}},
        })
        out = aggregate.merge([a, b], now=100.0, stale_after_s=60.0)
        assert out["metrics"]["t.c"]["values"] == {"k=x": 8.0, "k=y": 1.0}

    def test_gauges_keep_per_proc_sum_max(self):
        a = _snap("a", 100.0, metrics={
            "t.g": {"type": "gauge", "help": "", "labels": [],
                    "values": {"": 2.0}},
        })
        b = _snap("b", 100.0, metrics={
            "t.g": {"type": "gauge", "help": "", "labels": [],
                    "values": {"": 5.0}},
        })
        out = aggregate.merge([a, b], now=100.0, stale_after_s=60.0)
        merged = out["metrics"]["t.g"]["values"][""]
        assert merged["sum"] == 7.0
        assert merged["max"] == 5.0
        assert merged["procs"] == {"a": 2.0, "b": 5.0}

    def test_histogram_quantiles_bucket_exact_vs_oracle(self):
        obs_a = [1e-5, 3e-4, 0.002, 0.002, 0.4]
        obs_b = [0.008, 0.03, 0.03, 2.5]
        a = _snap("a", 100.0, metrics={
            "t.h": {"type": "histogram", "help": "", "labels": [],
                    "buckets": list(DEFAULT_BUCKETS),
                    "values": {"": _hist_value(obs_a)}},
        })
        b = _snap("b", 100.0, metrics={
            "t.h": {"type": "histogram", "help": "", "labels": [],
                    "buckets": list(DEFAULT_BUCKETS),
                    "values": {"": _hist_value(obs_b)}},
        })
        out = aggregate.merge([a, b], now=100.0, stale_after_s=60.0)
        merged = out["metrics"]["t.h"]["values"][""]
        # the oracle: one histogram that observed the UNION
        oracle = _hist_value(obs_a + obs_b)
        assert merged["counts"] == oracle["counts"]
        assert merged["count"] == len(obs_a) + len(obs_b)
        assert merged["sum"] == pytest.approx(sum(obs_a) + sum(obs_b))
        for suffix, q in (("p50", 0.5), ("p99", 0.99)):
            assert merged[suffix] == quantile_from_counts(
                list(DEFAULT_BUCKETS), oracle["counts"],
                oracle["count"], q,
            )

    def test_mismatched_buckets_flagged_not_merged(self):
        a = _snap("a", 100.0, metrics={
            "t.h": {"type": "histogram", "help": "", "labels": [],
                    "buckets": [1.0, 2.0],
                    "values": {"": {"counts": [1, 0, 0], "sum": 0.5,
                                     "count": 1}}},
        })
        b = _snap("b", 100.0, metrics={
            "t.h": {"type": "histogram", "help": "", "labels": [],
                    "buckets": [1.0, 4.0],
                    "values": {"": {"counts": [0, 1, 0], "sum": 3.0,
                                     "count": 1}}},
        })
        out = aggregate.merge([a, b], now=100.0, stale_after_s=60.0)
        entry = out["metrics"]["t.h"]
        assert entry.get("mixed_buckets") is True
        assert entry["values"][""]["count"] == 1  # first proc kept

    def test_stale_flagged_never_dropped(self):
        fresh = _snap("fresh", 100.0, metrics={
            "t.c": {"type": "counter", "help": "", "labels": [],
                    "values": {"": 1.0}},
        })
        dead = _snap("dead", 10.0, metrics={
            "t.c": {"type": "counter", "help": "", "labels": [],
                    "values": {"": 41.0}},
        })
        out = aggregate.merge([fresh, dead], now=101.0,
                              stale_after_s=15.0)
        by_proc = {p["proc"]: p for p in out["procs"]}
        assert by_proc["fresh"]["stale"] is False
        assert by_proc["dead"]["stale"] is True
        assert by_proc["dead"]["age_s"] == pytest.approx(91.0)
        # the dead process's counters still count
        assert out["metrics"]["t.c"]["values"][""] == 42.0

    def test_series_align_by_tick_rate_sums_level_means(self):
        a = _snap("a", 100.0, series={
            "t.q.rate": [[100.2, 3.0], [101.1, 5.0]],
            "t.depth": [[100.4, 10.0]],
        })
        b = _snap("b", 100.0, series={
            "t.q.rate": [[100.7, 4.0]],
            "t.depth": [[100.6, 30.0]],
        })
        out = aggregate.merge([a, b], now=101.0, stale_after_s=60.0)
        rate = out["series"]["t.q.rate"]
        assert rate["merge"] == "sum"
        assert rate["points"] == [[100.0, 7.0], [101.0, 5.0]]
        depth = out["series"]["t.depth"]
        assert depth["merge"] == "mean"
        assert depth["points"] == [[100.0, 20.0]]
        assert rate["procs"] == ["a", "b"]

    def test_read_snapshots_skips_foreign_files(self, tmp_path):
        (tmp_path / "good.json").write_text(json.dumps(
            {k: v for k, v in _snap("good", 1.0).items()
             if k != "_mtime"}
        ))
        (tmp_path / "bad-schema.json").write_text(json.dumps(
            {"schema": 999, "proc": "x"}
        ))
        (tmp_path / "torn.json").write_text('{"schema": 1, "proc": ')
        (tmp_path / "notes.txt").write_text("not telemetry")
        snaps = aggregate.read_snapshots(str(tmp_path))
        assert [s["proc"] for s in snaps] == ["good"]
        assert "_mtime" in snaps[0]

    def test_fleet_status_memoizes_parse_on_dir_stamp(self, tmp_path):
        set_config(telemetry_dir=str(tmp_path))
        export.export_snapshot()
        first = aggregate.fleet_status(str(tmp_path))
        assert first["dir"] == str(tmp_path)
        assert len(first["procs"]) == 1
        # unchanged directory -> the parsed snapshots are reused (the
        # merge still recomputes, so ages advance)
        again = aggregate.fleet_status(str(tmp_path))
        assert [p["proc"] for p in again["procs"]] == [
            p["proc"] for p in first["procs"]
        ]
        # a new export changes the stamp and is picked up
        obs.counter("t.memo_total", "scratch").inc()
        export.export_snapshot()
        updated = aggregate.fleet_status(str(tmp_path))
        assert "t.memo_total" in updated["metrics"]


# ---------------------------------------------------------------------------
# multi-process federation (the acceptance test)
# ---------------------------------------------------------------------------

_EXPORTER_SCRIPT = r"""
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from tensorframes_tpu import obs
from tensorframes_tpu.obs import export

mode = sys.argv[1]
c = obs.counter("t.fed_total", "federated scratch counter", labels=("k",))
h = obs.histogram("t.fed_seconds", "federated scratch histogram")
incs = int(sys.argv[2])
c.inc(incs, k="x")
for v in sys.argv[3].split(","):
    h.observe(float(v))
export.set_identity("job-worker")
p = export.export_snapshot()
assert p, "export failed"
print("READY", flush=True)
if mode == "loop":
    while True:
        time.sleep(0.1)
        export.export_snapshot()
else:  # park: stop refreshing, wait to be kill -9'd
    while True:
        time.sleep(60)
"""


@pytest.mark.slow
class TestMultiProcessFederation:
    def test_varz_fleet_merges_real_processes_and_flags_killed(
        self, tmp_path
    ):
        """Two real exporter subprocesses + this process: merged
        counters equal the per-process sum, merged histogram quantiles
        equal the hand-combined oracle, and the kill -9'd exporter is
        visible but stale while the live one stays fresh."""
        tdir = str(tmp_path / "telemetry")
        a_obs = [0.001, 0.004, 0.2]
        b_obs = [0.02, 0.3, 0.0005]
        my_obs = [0.08]

        def spawn(proc_id, mode, incs, values):
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["TFT_TELEMETRY_DIR"] = tdir
            env["TFT_PROC_ID"] = proc_id
            return subprocess.Popen(
                [sys.executable, "-c", _EXPORTER_SCRIPT, mode,
                 str(incs), ",".join(str(v) for v in values)],
                env=env, stdout=subprocess.PIPE, text=True,
            )

        live = spawn("fed-live", "loop", 3, a_obs)
        doomed = spawn("fed-doomed", "park", 5, b_obs)
        try:
            for p in (live, doomed):
                line = p.stdout.readline()
                assert "READY" in line, f"exporter failed: {line!r}"
            # kill -9 the parked exporter: its file stops refreshing
            doomed.send_signal(signal.SIGKILL)
            doomed.wait(timeout=30)
            # this process is the third member of the fleet
            c = obs.counter(
                "t.fed_total", "federated scratch counter", labels=("k",)
            )
            h = obs.histogram(
                "t.fed_seconds", "federated scratch histogram"
            )
            c.inc(2, k="x")
            for v in my_obs:
                h.observe(v)
            set_config(telemetry_dir=tdir)
            # age the corpse past the staleness bar while the live
            # exporter keeps refreshing its snapshot
            time.sleep(1.2)
            export.export_snapshot()

            srv = ScoringServer(lambda x: {"y": x * 2.0})
            with srv as addr:
                host, port_s = addr.rsplit(":", 1)
                status, body = _http_get(
                    host, int(port_s), "/varz?scope=fleet"
                )
            assert status.startswith("HTTP/1.1 200")
            view = json.loads(body)
            assert view["scope"] == "fleet"
            assert view["enabled"] is True

            by_proc = {p["proc"]: p for p in view["procs"]}
            assert {"fed-live", "fed-doomed"} <= set(by_proc)
            assert len(by_proc) == 3
            # counters merged across all three OS processes
            assert view["metrics"]["t.fed_total"]["values"][
                "k=x"
            ] == 10.0
            # histogram quantiles: bucket-exact == hand-combined oracle
            merged = view["metrics"]["t.fed_seconds"]["values"][""]
            oracle = _hist_value(a_obs + b_obs + my_obs)
            assert merged["counts"] == oracle["counts"]
            for suffix, q in (("p50", 0.5), ("p99", 0.99)):
                assert merged[suffix] == quantile_from_counts(
                    list(DEFAULT_BUCKETS), oracle["counts"],
                    oracle["count"], q,
                )
            # the kill -9'd worker: visible, counted, flagged stale
            stale_view = aggregate.fleet_status(
                tdir, stale_after_s=1.0
            )
            sp = {p["proc"]: p for p in stale_view["procs"]}
            assert sp["fed-doomed"]["stale"] is True
            assert sp["fed-live"]["stale"] is False
            assert sp["fed-doomed"]["role"] == "job-worker"
            assert stale_view["metrics"]["t.fed_total"]["values"][
                "k=x"
            ] == 10.0
        finally:
            for p in (live, doomed):
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
                p.stdout.close()

    def test_fleet_scope_without_dir_reports_disabled(self, monkeypatch):
        monkeypatch.delenv("TFT_TELEMETRY_DIR", raising=False)
        set_config(telemetry_dir="")
        srv = ScoringServer(lambda x: {"y": x})
        with srv as addr:
            host, port_s = addr.rsplit(":", 1)
            status, body = _http_get(
                host, int(port_s), "/varz?scope=fleet"
            )
        assert status.startswith("HTTP/1.1 200")
        view = json.loads(body)
        assert view["enabled"] is False
        assert "telemetry dir" in view["error"]


# ---------------------------------------------------------------------------
# drift detection
# ---------------------------------------------------------------------------


class TestDriftDetector:
    def _mon(self, **kw):
        mon = drift.DriftMonitor()
        kw.setdefault("name", "t_det")
        kw.setdefault("series", "t.lat.p99")
        kw.setdefault("tolerance", 0.3)
        kw.setdefault("min_samples", 3)
        kw.setdefault("trigger", 2)
        mon.add(drift.Detector(**kw))
        return mon

    def _drive(self, mon, store, values, start=100.0):
        for i, v in enumerate(values):
            store.record("t.lat.p99", start + i, v)
            mon.evaluate(store, now=start + i)
        return start + len(values)

    def test_stable_series_never_flags(self):
        mon, store = self._mon(), timeseries.TimeSeriesStore()
        self._drive(mon, store, [0.01] * 12)
        assert not mon.any_active()
        (row,) = mon.report()
        assert row["active"] is False
        assert row["baseline"] == pytest.approx(0.01)

    def test_shift_flags_within_trigger_and_report_names_delta(self):
        mon, store = self._mon(), timeseries.TimeSeriesStore()
        t = self._drive(mon, store, [0.01] * 6)
        # one out-of-band sample is NOT drift (trigger=2)...
        self._drive(mon, store, [0.05], start=t)
        assert not mon.any_active()
        # ...the second consecutive one is — within one more window
        self._drive(mon, store, [0.05], start=t + 1)
        (row,) = mon.report()
        assert row["active"] is True
        assert row["series"] == "t.lat.p99"
        assert row["detector"] == "t_det"
        assert row["delta"] == pytest.approx(0.04)
        assert row["since"] == t + 1

    def test_baseline_frozen_while_drifted_then_recovers(self):
        mon, store = self._mon(), timeseries.TimeSeriesStore()
        t = self._drive(mon, store, [0.01] * 6)
        t = self._drive(mon, store, [0.05] * 5, start=t)
        (row,) = mon.report()
        assert row["active"] is True
        # frozen: five shifted samples did not drag the baseline
        assert row["baseline"] == pytest.approx(0.01)
        # returning in-band for `trigger` samples clears the flag
        self._drive(mon, store, [0.01] * 2, start=t)
        (row,) = mon.report()
        assert row["active"] is False

    def test_adopting_drift_as_normal_never_reports_recovery(self):
        """The counterexample the frozen baseline exists for: if the
        shifted value simply persists, the detector stays active
        instead of quietly rebaselining."""
        mon, store = self._mon(), timeseries.TimeSeriesStore()
        t = self._drive(mon, store, [0.01] * 6)
        self._drive(mon, store, [0.05] * 30, start=t)
        assert mon.any_active()

    def test_min_band_floors_near_zero_series(self):
        mon = drift.DriftMonitor()
        mon.add(drift.Detector(
            name="p", series="t.preempt.rate", min_samples=3,
            trigger=2, min_band=0.5,
        ))
        store = timeseries.TimeSeriesStore()
        for i, v in enumerate([0.0] * 6 + [0.4, 0.3]):
            store.record("t.preempt.rate", 100.0 + i, v)
            mon.evaluate(store, now=100.0 + i)
        # without the floor a relative band around 0 flags everything
        assert not mon.any_active()

    def test_prefix_match_covers_labeled_series(self):
        mon = drift.DriftMonitor()
        mon.add(drift.Detector(
            name="acc", series="t.accept", match="prefix",
            min_samples=3, trigger=2,
        ))
        store = timeseries.TimeSeriesStore()
        for i in range(6):
            store.record("t.accept{engine=a}", 100.0 + i, 0.8)
            store.record("t.accept{engine=b}", 100.0 + i, 0.8)
            mon.evaluate(store, now=100.0 + i)
        for i in range(6, 9):
            store.record("t.accept{engine=a}", 100.0 + i, 0.2)
            store.record("t.accept{engine=b}", 100.0 + i, 0.8)
            mon.evaluate(store, now=100.0 + i)
        rows = {r["series"]: r for r in mon.report()}
        assert rows["t.accept{engine=a}"]["active"] is True
        assert rows["t.accept{engine=b}"]["active"] is False

    def test_shift_emits_gauge_counter_and_flight_event(self):
        flight.reset()
        try:
            mon, store = self._mon(), timeseries.TimeSeriesStore()
            t = self._drive(mon, store, [0.01] * 6)
            self._drive(mon, store, [0.05] * 3, start=t)
            snap = obs.registry().snapshot()
            assert snap["obs.drift_active"]["values"][
                "series=t.lat.p99"
            ] == 1.0
            assert snap["obs.drift_shifts_total"]["values"][
                "series=t.lat.p99"
            ] >= 1.0
            ring = flight.rings().get("drift", [])
            shifts = [e for e in ring if e["kind"] == "shift"]
            assert shifts and shifts[-1]["series"] == "t.lat.p99"
        finally:
            flight.reset()

    def test_detector_validation(self):
        with pytest.raises(ValueError):
            drift.Detector(name="x", series="s", match="regex")
        with pytest.raises(ValueError):
            drift.Detector(name="x", series="s", alpha=0.0)
        with pytest.raises(ValueError):
            drift.Detector(name="x", series="s", tolerance=-1.0)

    def test_canned_detectors_installed_on_default_monitor(self):
        names = {d.name for d in drift.monitor().detectors()}
        assert {"h2d_p50", "spec_acceptance", "inter_token_p99",
                "preemption_rate"} <= names


class TestDriftEndToEnd:
    def test_chaos_decode_latency_flags_and_clears(self, lm):
        """The acceptance drill: a chaos-injected decode-step latency
        shifts ``serve.inter_token_seconds.p99``; the sampler-tick
        evaluation flips ``obs.drift_active`` within one window of the
        trigger and clears it after the chaos stops."""
        from tensorframes_tpu.serve.engine import GenerationEngine

        mon = drift.monitor()
        # the canned inter-token detector uses a relative band; this
        # drill swaps in one with an absolute floor so CPU timing noise
        # in the baseline cannot flake the recovery phase
        mon.remove("inter_token_p99")
        det = drift.Detector(
            name="itl_e2e", series="serve.inter_token_seconds.p99",
            tolerance=0.5, min_band=0.03, min_samples=3, trigger=2,
        )
        mon.add(det)
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48
        )
        series = "serve.inter_token_seconds.p99"
        tick = [0]

        def one_round():
            h = eng.submit([1, 2, 3], 4)
            eng.run_until_idle()
            h.result(timeout=60)
            tick[0] += 1
            timeseries.sample_once(now=1000.0 + tick[0])

        try:
            for _ in range(5):  # warmup + baseline (sub-ms CPU steps)
                one_round()
            assert not any(
                r["active"] for r in drift.drift_report()
                if r["detector"] == "itl_e2e"
            )
            set_config(chaos="serve.decode_step=latency:ms=80")
            try:
                for _ in range(3):  # trigger=2 + one slack window
                    one_round()
            finally:
                set_config(chaos="")
            rows = [r for r in drift.drift_report()
                    if r["detector"] == "itl_e2e"]
            assert rows and rows[0]["active"] is True
            assert rows[0]["series"] == series
            assert rows[0]["delta"] > 0.03
            assert obs.registry().snapshot()["obs.drift_active"][
                "values"
            ][f"series={series}"] == 1.0
            # recovery: chaos off, in-band rounds clear the flag
            for _ in range(4):
                one_round()
            rows = [r for r in drift.drift_report()
                    if r["detector"] == "itl_e2e"]
            assert rows and rows[0]["active"] is False
            assert obs.registry().snapshot()["obs.drift_active"][
                "values"
            ][f"series={series}"] == 0.0
        finally:
            eng.stop()
            mon.remove("itl_e2e")
            mon.add(drift.inter_token_p99())


# ---------------------------------------------------------------------------
# sampler lag + /varz liveness
# ---------------------------------------------------------------------------


class TestSamplerLag:
    def test_lag_gauge_tracks_tick_gap(self):
        timeseries.sample_once(now=500.0)
        # a deliberately slow tick: 5 s after the previous one
        timeseries.sample_once(now=505.0)
        assert obs.registry().snapshot()[
            "obs.ts_sampler_lag_seconds"
        ]["values"][""] == 5.0
        assert timeseries.last_tick_ts() == 505.0
        # a healthy cadence shrinks the gauge back
        timeseries.sample_once(now=506.0)
        assert obs.registry().snapshot()[
            "obs.ts_sampler_lag_seconds"
        ]["values"][""] == 1.0

    def test_varz_reports_last_tick_and_lag(self):
        srv = ScoringServer(lambda x: {"y": x})
        with srv as addr:
            host, port_s = addr.rsplit(":", 1)
            timeseries.sample_once()
            status, body = _http_get(host, int(port_s), "/varz")
        assert status.startswith("HTTP/1.1 200")
        view = json.loads(body)
        assert view["last_tick_ts"] is not None
        assert view["sampler_lag_s"] is not None
        assert view["sampler_lag_s"] < 120.0


# ---------------------------------------------------------------------------
# per-request cost attribution
# ---------------------------------------------------------------------------


class TestCostAttribution:
    def test_completed_request_carries_costs(self, lm, tmp_path,
                                             monkeypatch):
        from tensorframes_tpu.serve.engine import GenerationEngine

        ledger = tmp_path / "requests.jsonl"
        monkeypatch.setenv("TFT_REQUESTS_FILE", str(ledger))
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48
        )
        try:
            h = eng.submit([1, 2, 3, 4], 6, tenant="acme")
            eng.run_until_idle()
            toks = h.result(timeout=60)
        finally:
            eng.stop()
        assert len(toks) >= 1
        t = h.timings
        assert t["tokens"] == len(toks)
        assert t["kv_pages"] >= 1
        assert t["tenant"] == "acme"
        assert t.get("est_flops", 0.0) > 0.0
        rows = [r for r in obs_requests.recent()
                if r.get("request_id") == h.request_id]
        assert len(rows) == 1
        row = rows[0]
        assert row["status"] == "completed"
        assert row["tenant"] == "acme"
        assert row["tokens"] == t["tokens"]
        assert row["kv_pages"] == t["kv_pages"]
        assert row["est_flops"] == pytest.approx(t["est_flops"])
        assert row["prefix_cached_tokens"] >= 0
        # the durable feed has the same record
        lines = [json.loads(ln) for ln in
                 ledger.read_text().splitlines()]
        match = [ln for ln in lines
                 if ln.get("request_id") == h.request_id]
        assert match and match[0]["tenant"] == "acme"

    def test_every_completed_request_gets_a_record(self, lm):
        from tensorframes_tpu.serve.engine import GenerationEngine

        obs_requests.reset()
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=48
        )
        try:
            handles = [
                eng.submit([1 + i, 2, 3], 4, tenant=f"team-{i % 2}")
                for i in range(4)
            ]
            eng.run_until_idle()
            for h in handles:
                h.result(timeout=60)
        finally:
            eng.stop()
        recorded = {r["request_id"] for r in obs_requests.recent()}
        assert {h.request_id for h in handles} <= recorded
        tenants = {r["tenant"] for r in obs_requests.recent()
                   if r["request_id"] in
                   {h.request_id for h in handles}}
        assert tenants == {"team-0", "team-1"}

    def test_top_by_cost_orders_by_flops_then_tokens(self):
        obs_requests.reset()
        obs_requests.record_request(request_id=1, est_flops=10.0,
                                    tokens=5)
        obs_requests.record_request(request_id=2, est_flops=99.0,
                                    tokens=1)
        obs_requests.record_request(request_id=3, est_flops=0.0,
                                    tokens=50)
        obs_requests.record_request(request_id=4, est_flops=0.0,
                                    tokens=2)
        top = obs_requests.top_by_cost(3)
        assert [r["request_id"] for r in top] == [2, 1, 3]

    def test_statusz_lists_top_costs_and_identity(self, lm):
        from tensorframes_tpu.serve.engine import GenerationEngine

        obs_requests.reset()
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48
        )
        srv = ScoringServer(engine=eng)
        with srv as addr:
            host, port_s = addr.rsplit(":", 1)
            h = eng.submit([1, 2, 3], 4, tenant="acme")
            h.result(timeout=60)
            status, body = _http_get(host, int(port_s), "/statusz")
        assert status.startswith("HTTP/1.1 200")
        page = json.loads(body)
        assert page["identity"]["role"] == "serve-replica"
        assert page["identity"]["proc"] == export.proc_id()
        costs = page["request_costs"]
        assert any(r.get("tenant") == "acme" for r in costs)

    def test_generate_endpoint_parses_tenant(self, lm):
        from tensorframes_tpu.serve.engine import GenerationEngine

        obs_requests.reset()
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48
        )
        srv = ScoringServer(engine=eng)
        with srv as addr:
            host, port_s = addr.rsplit(":", 1)
            spec = json.dumps({
                "prompt": [1, 2, 3], "max_new_tokens": 4,
                "tenant": "bill-me",
            }).encode()
            c = socket.create_connection((host, int(port_s)),
                                         timeout=60)
            try:
                c.sendall(
                    b"POST /generate HTTP/1.1\r\nHost: x\r\n"
                    + f"Content-Length: {len(spec)}\r\n\r\n".encode()
                    + spec
                )
                buf = b""
                while True:
                    chunk = c.recv(65536)
                    if not chunk:
                        break
                    buf += chunk
            finally:
                c.close()
            head, _, body = buf.partition(b"\r\n\r\n")
            assert head.split(b"\r\n")[0].endswith(b"200 OK")
            payload = json.loads(body)
        assert payload["timing"]["tenant"] == "bill-me"
        assert payload["timing"]["tokens"] >= 1
        assert payload["timing"]["est_flops"] >= 0.0
        rows = [r for r in obs_requests.recent()
                if r.get("tenant") == "bill-me"]
        assert rows and rows[-1]["status"] == "completed"


# ---------------------------------------------------------------------------
# debug bundles capture the triggering subsystem's series window
# ---------------------------------------------------------------------------


class TestBundleTimeseries:
    def test_dump_bundle_includes_prefixed_series_window(self, tmp_path):
        flight.reset()
        prev = get_config().debug_bundle_dir
        set_config(debug_bundle_dir=str(tmp_path / "bundles"))
        try:
            now = time.time()
            timeseries.store().record("serve.queue_depth", now, 7.0)
            timeseries.store().record("jobs.other", now, 1.0)
            path = flight.dump_bundle(
                "t_fatal", series_prefix="serve.",
                extra={"probe": True},
            )
            assert path is not None
            bundle = json.loads(open(path).read())
            ts = bundle["timeseries"]
            assert ts["prefix"] == "serve."
            assert "serve.queue_depth" in ts["series"]
            assert "jobs.other" not in ts["series"]
        finally:
            set_config(debug_bundle_dir=prev)
            flight.reset()

    def test_dump_bundle_without_prefix_has_no_series_block(
        self, tmp_path
    ):
        flight.reset()
        prev = get_config().debug_bundle_dir
        set_config(debug_bundle_dir=str(tmp_path / "bundles"))
        try:
            path = flight.dump_bundle("t_plain")
            assert path is not None
            bundle = json.loads(open(path).read())
            assert "timeseries" not in bundle
        finally:
            set_config(debug_bundle_dir=prev)
            flight.reset()
