"""Fused ragged paged-attention kernel, shared-prefix KV cache, chunked
prefill (PR 7).

Three correctness bars:

- the fused kernel (``ops.ragged_paged_attention``) matches the gather
  ``paged_attention`` oracle within float tolerance across a ragged
  length matrix — 1-token to max-pages sequences, MHA and GQA, f32 and
  bf16;
- engine decode streams stay BYTE-IDENTICAL to solo
  ``transformer_generate`` — greedy and seeded sampling — with the
  prefix cache and chunked prefill enabled, including under preemption,
  mid-run defragment, restart, and chaos;
- the compiled-program budget: <= 2 step programs with the new features
  off (the PR-2 invariant, untouched), <= 3 with them on (the one new
  program is the prefill chunk).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.ops import (
    paged_attention,
    paged_page_size_hint,
    ragged_paged_attention,
)
from tensorframes_tpu.serve import GenerationEngine, PagePool, SequencePages
from tensorframes_tpu.serve.kv_pages import PrefixCache
from tensorframes_tpu.utils import get_config, set_config

pytestmark = pytest.mark.attn

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=48)


@pytest.fixture(scope="module")
def lm_gqa():
    return TransformerLM.init(
        1, VOCAB, d_model=16, n_heads=4, n_kv_heads=2, max_len=48
    )


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _prompts(rng, lens):
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist()
        for n in lens
    ]


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


# ---------------------------------------------------------------------------


class TestRaggedKernelOracle:
    """ragged_paged_attention vs the gather paged_attention oracle."""

    def _case(self, rng, slots, n_kv, group, hd, ps, mp, pool, dtype):
        q = jnp.asarray(
            rng.normal(size=(slots, n_kv, group, hd)).astype(np.float32)
        ).astype(dtype)
        kp = jnp.asarray(
            rng.normal(size=(pool + 1, ps, n_kv, hd)).astype(np.float32)
        ).astype(dtype)
        vp = jnp.asarray(
            rng.normal(size=(pool + 1, ps, n_kv, hd)).astype(np.float32)
        ).astype(dtype)
        ptab = rng.integers(0, pool, size=(slots, mp)).astype(np.int32)
        return q, kp, vp, ptab

    @pytest.mark.parametrize(
        "n_kv,group", [(2, 1), (2, 2), (1, 4)],
        ids=["mha-ish", "gqa2", "mqa"],
    )
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_matches_gather_over_ragged_lengths(self, rng, n_kv, group,
                                                dtype):
        dt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
        ps, mp = 4, 4
        # every regime: single token, partial page, exact page boundary,
        # mid-sequence, and the full max_pages * page_size length
        lengths = np.asarray([1, 3, 4, 9, 16], np.int32)
        q, kp, vp, ptab = self._case(
            rng, len(lengths), n_kv, group, hd=8, ps=ps, mp=mp, pool=12,
            dtype=dt,
        )
        ref = paged_attention(q, kp, vp, ptab, lengths)
        got = ragged_paged_attention(q, kp, vp, ptab, lengths)
        assert got.dtype == q.dtype
        tol = 2e-2 if dtype == "bfloat16" else 2e-5
        np.testing.assert_allclose(
            np.asarray(got, np.float32),
            np.asarray(ref, np.float32),
            rtol=tol,
            atol=tol,
        )

    def test_under_jit_and_every_length(self, rng):
        # exhaustive 1..T sweep of one slot's length under jit — the
        # boundary-page mask has to be right at every offset
        ps, mp = 4, 3
        t = ps * mp
        fn = jax.jit(ragged_paged_attention)
        q, kp, vp, ptab = self._case(
            rng, 2, 2, 2, hd=8, ps=ps, mp=mp, pool=8, dtype=jnp.float32
        )
        for length in range(1, t + 1):
            lengths = np.asarray([length, t], np.int32)
            ref = paged_attention(q, kp, vp, ptab, lengths)
            got = fn(q, kp, vp, ptab, lengths)
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5,
                err_msg=f"length={length}",
            )

    def test_trash_paged_idle_slot_is_finite(self, rng):
        # an idle slot (all-trash table, length 1) must produce finite
        # output — the engine discards it, but NaN would poison the
        # whole decode batch through the shared program
        ps, mp, pool = 4, 2, 6
        q, kp, vp, _ = self._case(
            rng, 1, 2, 1, hd=8, ps=ps, mp=mp, pool=pool, dtype=jnp.float32
        )
        ptab = np.full((1, mp), pool, np.int32)  # trash page everywhere
        got = ragged_paged_attention(
            q, kp, vp, ptab, np.asarray([1], np.int32)
        )
        assert np.isfinite(np.asarray(got)).all()

    def test_page_size_hint_comes_from_tile_table(self):
        # the hint is the flash sweep's measured block_k — currently 1024
        # for every (dtype, head_dim) bucket
        assert paged_page_size_hint(jnp.bfloat16, 128) == 1024
        assert paged_page_size_hint(jnp.float32, 64) == 1024


class TestPagedInputValidation:
    """A wrong page_table/lengths dtype used to miscompute the mask
    silently; both reads must reject it loudly."""

    def _args(self, rng):
        q = jnp.zeros((2, 2, 1, 8), jnp.float32)
        kp = jnp.zeros((5, 4, 2, 8), jnp.float32)
        ptab = np.zeros((2, 3), np.int32)
        lengths = np.ones(2, np.int32)
        return q, kp, ptab, lengths

    @pytest.mark.parametrize("impl", [paged_attention, ragged_paged_attention])
    def test_bad_dtypes_rejected(self, rng, impl):
        q, kp, ptab, lengths = self._args(rng)
        with pytest.raises(ValueError, match="page_table must be int32"):
            impl(q, kp, kp, ptab.astype(np.int64), lengths)
        with pytest.raises(ValueError, match="lengths must be int32"):
            impl(q, kp, kp, ptab, lengths.astype(np.float32))

    @pytest.mark.parametrize("impl", [paged_attention, ragged_paged_attention])
    def test_bad_shapes_rejected(self, rng, impl):
        q, kp, ptab, lengths = self._args(rng)
        with pytest.raises(ValueError, match="lengths must be \\[slots"):
            impl(q, kp, kp, ptab, np.ones(3, np.int32))
        with pytest.raises(ValueError, match="page_table must be \\[slots"):
            impl(q, kp, kp, np.zeros((3, 3), np.int32), lengths)
        with pytest.raises(ValueError, match="n_kv"):
            impl(q, jnp.zeros((5, 4, 3, 8), jnp.float32),
                 jnp.zeros((5, 4, 3, 8), jnp.float32), ptab, lengths)
        with pytest.raises(ValueError, match="share a shape"):
            impl(q, kp, jnp.zeros((5, 4, 2, 4), jnp.float32), ptab, lengths)


# ---------------------------------------------------------------------------


class TestPrefixCacheUnit:
    def _pool(self, num_pages=12, page_size=4):
        return PagePool(
            n_layers=1, n_kv_heads=1, head_dim=4,
            num_pages=num_pages, page_size=page_size,
        )

    def test_refcount_share_and_release(self):
        pool = self._pool()
        pages = pool.alloc(3)
        pool.ref(pages[:2])
        assert pool.pages_shared == 2
        assert pool.free(pages) == 1  # two still referenced
        assert pool.pages_in_use == 2
        assert pool.free(pages[:2]) == 2
        assert pool.pages_in_use == 0 and pool.pages_shared == 0
        with pytest.raises(ValueError, match="double free"):
            pool.free([pages[0]])
        with pytest.raises(ValueError, match="ref free page"):
            pool.ref([pages[0]])

    def test_insert_acquire_exact_and_partial(self):
        pool = self._pool()
        cache = PrefixCache(pool)
        prompt = np.arange(100, 110, dtype=np.int32)  # 2 full pages + 2
        seq = SequencePages(pool)
        seq.ensure(len(prompt))
        assert cache.insert(prompt, seq.pages)
        assert not cache.insert(prompt, seq.pages)  # idempotent
        # exact prefix: both full pages, cow for the partial third page
        # is impossible (entry only holds full pages)
        shared, cow, cached = cache.acquire(prompt)
        assert shared == seq.pages[:2] and cached == 8 and cow is None
        pool.free(shared)
        # divergence INSIDE page 1 -> 1 shared page + cow of page 1
        p2 = prompt.copy()
        p2[6] = 7
        shared, cow, cached = cache.acquire(p2)
        assert shared == seq.pages[:1]
        assert cow == seq.pages[1] and cached == 6
        pool.free(shared)
        pool.free([cow])
        # total miss
        assert cache.acquire(np.asarray([9, 9, 9, 9, 9], np.int32)) == (
            [], None, 0
        )
        st = cache.stats()
        assert st["hits"] == 2 and st["lookups"] == 3

    def test_last_position_always_recomputed(self):
        # a prompt the cache covers ENTIRELY must still leave >= 1
        # position to prefill (the first sampled token needs its logits)
        pool = self._pool()
        cache = PrefixCache(pool)
        prompt = np.arange(8, dtype=np.int32)  # exactly 2 pages
        seq = SequencePages(pool)
        seq.ensure(8)
        cache.insert(prompt, seq.pages)
        shared, cow, cached = cache.acquire(prompt)
        assert cached == 7  # page 0 shared + 3 cow positions, not 8
        assert shared == seq.pages[:1] and cow == seq.pages[1]
        pool.free(shared)
        pool.free([cow])

    def test_eviction_frees_only_unshared(self):
        pool = self._pool(num_pages=6)
        cache = PrefixCache(pool)
        seq = SequencePages(pool)
        seq.ensure(8)
        prompt = np.arange(8, dtype=np.int32)
        cache.insert(prompt, seq.pages)
        seq.release()  # cache is now sole owner
        assert pool.pages_in_use == 2
        assert cache.evict_pages(1) == 2  # whole entry drops
        assert len(cache) == 0 and pool.pages_in_use == 0

    def test_lru_bound(self):
        pool = self._pool(num_pages=12)
        cache = PrefixCache(pool, max_entries=2)
        seqs = []
        for i in range(3):
            seq = SequencePages(pool)
            seq.ensure(4)
            cache.insert(np.arange(i * 10, i * 10 + 4, dtype=np.int32),
                         seq.pages)
            seqs.append(seq)
        assert len(cache) == 2  # oldest evicted
        assert cache.acquire(np.arange(0, 4, dtype=np.int32))[2] == 0

    def test_defragment_renumbers_cache_entries(self):
        pool = self._pool()
        cache = PrefixCache(pool)
        junk = SequencePages(pool)
        junk.ensure(8)  # occupy low pages, then free -> fragmentation
        seq = SequencePages(pool)
        seq.ensure(8)
        prompt = np.arange(8, dtype=np.int32)
        cache.insert(prompt, seq.pages)
        junk.release()
        remap = pool.defragment(
            [seq], page_lists=cache.entry_page_lists()
        )
        assert seq.pages == [0, 1]
        shared, _, cached = cache.acquire(prompt)
        assert shared == seq.pages[:1] or shared == seq.pages[:2]
        pool.free(shared)
        assert len(remap) == 2


# ---------------------------------------------------------------------------


class TestEngineFusedDecode:
    """The fused kernel wired into the decode step: stream parity."""

    def test_fused_streams_match_gather_and_solo(self, lm):
        rng = np.random.default_rng(3)
        prompts = _prompts(rng, [5, 9, 3, 17])
        outs = {}
        for impl in ("gather", "fused"):
            eng = GenerationEngine(
                lm, max_slots=4, page_size=4, max_seq_len=48,
                attention_impl=impl,
            )
            outs[impl] = eng.generate(prompts, 8)
            assert eng.num_step_programs <= 2
        for p, g, f in zip(prompts, outs["gather"], outs["fused"]):
            solo = _solo(lm, p, 8)
            assert np.array_equal(g, solo)
            assert np.array_equal(f, solo)

    def test_fused_gqa_streams_match_solo(self, lm_gqa):
        rng = np.random.default_rng(4)
        prompts = _prompts(rng, [6, 11])
        eng = GenerationEngine(
            lm_gqa, max_slots=2, page_size=4, max_seq_len=48,
            attention_impl="fused",
        )
        for p, o in zip(prompts, eng.generate(prompts, 8)):
            assert np.array_equal(o, _solo(lm_gqa, p, 8))

    def test_bad_impl_rejected(self, lm):
        with pytest.raises(ValueError, match="gather.*fused"):
            GenerationEngine(lm, attention_impl="magic")

    def test_config_default_applies(self, lm):
        old = get_config().serve_attention_impl
        set_config(serve_attention_impl="fused")
        try:
            eng = GenerationEngine(lm, max_slots=2, page_size=4,
                                   max_seq_len=48)
            assert eng.attention_impl == "fused"
        finally:
            set_config(serve_attention_impl=old)


class TestChunkedPrefill:
    def test_streams_identical_and_third_program(self, lm):
        rng = np.random.default_rng(5)
        prompts = _prompts(rng, [17, 5, 23, 9])  # mix: chunked and not
        before = _counter_value("serve.prefill_chunks_total")
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=48,
            prefill_chunk_tokens=8,
        )
        outs = eng.generate(prompts, 8)
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _solo(lm, p, 8))
        # prompts of 17 and 23 tokens chunk (3 chunks each); 5 and 9
        # run the one-pass program
        assert eng.num_step_programs <= 3
        assert _counter_value("serve.prefill_chunks_total") - before >= 6

    def test_seeded_sampling_identical(self, lm):
        rng = np.random.default_rng(6)
        prompts = _prompts(rng, [19, 21])
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefill_chunk_tokens=4,
        )
        kw = dict(temperature=0.8, seed=11, top_p=0.9)
        for p, o in zip(prompts, eng.generate(prompts, 8, **kw)):
            assert np.array_equal(o, _solo(lm, p, 8, **kw))

    def test_chunk_interleaves_with_decode(self, lm):
        # a long prompt admitted while another stream decodes must not
        # stall it: between the long prompt's chunks the short stream
        # keeps emitting (one decode step per engine step)
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefill_chunk_tokens=4,
        )
        rng = np.random.default_rng(7)
        short = _prompts(rng, [4])[0]
        long = _prompts(rng, [24])[0]
        h_short = eng.submit(short, 16)
        eng.step()  # short prefilled, emits token 1
        h_long = eng.submit(long, 4)
        emitted_before = len(h_short._tokens)
        # long needs 6 chunks; each step must also decode short
        for _ in range(6):
            eng.step()
        assert len(h_short._tokens) >= emitted_before + 6
        eng.run_until_idle()
        assert np.array_equal(h_short.result(5), _solo(lm, short, 16))
        assert np.array_equal(h_long.result(5), _solo(lm, long, 4))


class TestPrefixCacheEngine:
    def test_identical_prompts_hit_and_match(self, lm):
        rng = np.random.default_rng(8)
        shared = _prompts(rng, [17])[0]
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=48,
            prefix_cache=True,
        )
        hits0 = _counter_value("serve.prefix_cache_hits_total")
        first = eng.generate([shared], 8)[0]
        again = eng.generate([shared, shared], 8)
        solo = _solo(lm, shared, 8)
        assert np.array_equal(first, solo)
        assert np.array_equal(again[0], solo)
        assert np.array_equal(again[1], solo)
        assert _counter_value("serve.prefix_cache_hits_total") - hits0 >= 2
        assert eng.prefix_cache.stats()["hits"] >= 2
        assert eng.num_step_programs <= 3

    def test_divergent_prompt_cow_matches_solo(self, lm):
        rng = np.random.default_rng(9)
        base = _prompts(rng, [16])[0]
        diverged = list(base[:10]) + [1, 2, 3]  # splits inside page 2
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefix_cache=True,
        )
        eng.generate([base], 8)
        out = eng.generate([diverged], 8)[0]
        assert np.array_equal(out, _solo(lm, diverged, 8))
        assert eng.prefix_cache.stats()["hits"] >= 1

    def test_sampled_streams_with_cache_and_chunking(self, lm):
        rng = np.random.default_rng(10)
        shared = _prompts(rng, [20])[0]
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=48,
            prefix_cache=True, prefill_chunk_tokens=8,
        )
        kw = dict(temperature=0.7, seed=3, top_p=0.85)
        solo = _solo(lm, shared, 8, **kw)
        outs = eng.generate([shared, shared, shared], 8, **kw)
        for o in outs:
            assert np.array_equal(o, solo)

    def test_preemption_under_pressure_stays_identical(self, lm):
        # tight pool + cache refs: eviction must go before preemption,
        # and every stream must stay byte-identical through requeues
        rng = np.random.default_rng(11)
        sys_prompt = _prompts(rng, [12])[0]
        prompts = [
            sys_prompt + _prompts(rng, [4])[0] for _ in range(6)
        ]
        eng = GenerationEngine(
            lm, max_slots=3, page_size=4, max_seq_len=48, num_pages=18,
            prefix_cache=True, prefill_chunk_tokens=4, queue_capacity=8,
        )
        for p, o in zip(prompts, eng.generate(prompts, 8)):
            assert np.array_equal(o, _solo(lm, p, 8))
        assert eng.num_step_programs <= 3

    def test_defragment_mid_run_with_cache(self, lm):
        rng = np.random.default_rng(12)
        prompts = [_prompts(rng, [14])[0] for _ in range(2)]
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefix_cache=True,
        )
        handles = [eng.submit(p, 12) for p in prompts]
        for _ in range(3):
            eng.step()
        eng.defragment()
        eng.run_until_idle()
        for h, p in zip(handles, prompts):
            assert np.array_equal(h.result(5), _solo(lm, p, 12))
        # the cache survived compaction and still hits
        out = eng.generate([prompts[0]], 8)[0]
        assert np.array_equal(out, _solo(lm, prompts[0], 8))
        assert eng.prefix_cache.stats()["hits"] >= 1

    def test_defragment_remaps_pending_cow_donor(self, lm):
        # regression: a defragment landing between admission (which
        # pins a copy-on-write donor page by index) and the clone —
        # an earlier slot's prefill OOM does exactly this — must
        # renumber the pending donor, or the clone copies whatever page
        # took the old index and frees the wrong reference
        rng = np.random.default_rng(16)
        base = _prompts(rng, [14])[0]
        diverged = list(base[:10]) + [2, 4, 6]  # splits inside page 2
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefix_cache=True,
        )
        # fragment the pool so compaction actually moves pages
        junk = eng.scheduler.pool.alloc(5)
        eng.generate([base], 8)
        eng.pool.free(junk)
        h = eng.submit(diverged, 8)
        admitted = eng.scheduler.admit()
        (idx, act), = admitted
        assert act.cow_src is not None
        donor_before = act.cow_src
        eng._defragment_locked()
        assert act.cow_src is not None and act.cow_src != donor_before
        assert eng._try_prefill(idx, act, first=True) is None
        eng.run_until_idle()
        assert np.array_equal(h.result(5), _solo(lm, diverged, 8))

    def test_admit_eviction_covers_only_the_shortfall(self, lm):
        # regression: eviction on admission must free only the pages
        # the free list cannot cover — not the full prompt's worth —
        # so warm prefixes survive, and an admission the pool CAN
        # satisfy is not spuriously requeued
        rng = np.random.default_rng(17)
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48, num_pages=12,
            prefix_cache=True,
        )
        cold = _prompts(rng, [8])[0]
        warm = _prompts(rng, [8])[0]
        eng.generate([cold], 2)  # LRU-oldest entry: 2 pages
        eng.generate([warm], 2)  # newer entry: 2 pages
        assert len(eng.prefix_cache) == 2 and eng.pool.pages_in_use == 4
        big = _prompts(rng, [37])[0]  # 37 + 3 new = 10 pages > 8 free
        h = eng.submit(big, 3)
        eng.step()
        # admitted THIS step (not requeued — its own registration at
        # prefill completion proves it), and only the COLD entry paid:
        # the shortfall was 2 pages, so the warm entry survives
        assert eng.prefix_cache.acquire(np.asarray(cold, np.int32))[2] == 0
        got = eng.prefix_cache.acquire(np.asarray(warm, np.int32))
        assert got[2] > 0
        eng.pool.free(got[0])
        if got[1] is not None:
            eng.pool.free([got[1]])
        eng.run_until_idle()
        assert np.array_equal(h.result(5), _solo(lm, big, 3))

    def test_restart_clears_cache_and_recovers(self, lm):
        rng = np.random.default_rng(13)
        prompts = [_prompts(rng, [15])[0] for _ in range(2)]
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefix_cache=True, prefill_chunk_tokens=4,
        )
        handles = [eng.submit(p, 12) for p in prompts]
        for _ in range(4):
            eng.step()
        eng.restart()
        assert len(eng.prefix_cache) == 0  # device contents are gone
        eng.run_until_idle()
        for h, p in zip(handles, prompts):
            assert np.array_equal(h.result(5), _solo(lm, p, 12))
        assert eng.num_step_programs <= 3

    def test_kv_pages_shared_gauge_tracks_sharing(self, lm):
        rng = np.random.default_rng(14)
        shared = _prompts(rng, [16])[0]
        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=48,
            prefix_cache=True,
        )
        eng.generate([shared], 4)  # registers the prefix (cache-only ref)
        assert eng.pool.pages_shared == 0  # one ref each: not shared yet
        h = eng.submit(shared, 8)  # hit: sequence + cache share pages
        eng.step()
        assert eng.pool.pages_shared > 0
        g = obs_metrics.registry().get("serve.kv_pages_shared")
        assert g.value() > 0
        eng.run_until_idle()
        assert np.array_equal(h.result(5), _solo(lm, shared, 8))


@pytest.mark.chaos
class TestChaosWithPrefixAndChunks:
    def test_soak_transient_and_pool_faults(self, lm):
        # the PR-3 soak contract with the PR-7 features on: seeded
        # transient faults on every dispatch site (including the new
        # prefill-chunk site) + periodic pool exhaustion; streams stay
        # byte-identical and the program budget holds
        from tensorframes_tpu.utils import chaos
        old = (get_config().max_retries, get_config().retry_backoff_s)
        set_config(
            max_retries=3, retry_backoff_s=0.001,
            chaos=(
                "seed=7;serve.prefill=transient:p=0.1;"
                "serve.prefill_chunk=transient:p=0.1;"
                "serve.decode_step=transient:p=0.1;"
                "kv_pages.alloc=pool:every=13"
            ),
        )
        try:
            rng = np.random.default_rng(15)
            sys_prompt = _prompts(rng, [12])[0]
            prompts = [
                sys_prompt + _prompts(rng, [5])[0] for _ in range(5)
            ]
            eng = GenerationEngine(
                lm, max_slots=3, page_size=4, max_seq_len=48,
                num_pages=20, prefix_cache=True, prefill_chunk_tokens=4,
                queue_capacity=8,
            )
            outs = eng.generate(prompts, 8)
        finally:
            set_config(
                max_retries=old[0], retry_backoff_s=old[1], chaos=""
            )
        for p, o in zip(prompts, outs):
            assert np.array_equal(o, _solo(lm, p, 8))
        assert eng.num_step_programs <= 3
        assert chaos.active_spec() == ""
