"""TensorFrame columnar-table tests, incl. the analyze() semantics of the
reference (`ExtraOperationsSuite.scala:15-98`)."""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.frame import Row, TensorFrame
from tensorframes_tpu.schema import Shape, Unknown


def test_from_columns_dense_scalar():
    df = TensorFrame.from_columns({"x": np.arange(10.0)})
    assert df.num_rows == 10
    assert df.columns == ["x"]
    assert df.schema["x"].scalar_type.name == "float64"
    assert df.schema["x"].block_shape == Shape(Unknown)


def test_from_rows_and_collect():
    rows = [dict(x=float(i)) for i in range(5)]
    df = TensorFrame.from_rows(rows)
    out = df.collect()
    assert [r.x for r in out] == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert repr(out[0]) == "Row(x=0.0)"


def test_vector_column_dense():
    df = TensorFrame.from_columns({"y": [[1.0, -1.0], [2.0, -2.0]]})
    assert df.schema["y"].nesting == 1
    block = df.column_block("y")
    assert block.shape == (2, 2)


def test_ragged_column():
    df = TensorFrame.from_columns({"y": [[1.0], [2.0, 3.0]]})
    cd = df.column_data("y")
    assert cd.dense is None
    with pytest.raises(ValueError, match="ragged"):
        df.column_block("y")


def test_binary_column():
    df = TensorFrame.from_columns({"b": [b"ab", b"cde"]})
    assert df.schema["b"].scalar_type.name == "binary"
    with pytest.raises(ValueError, match="binary"):
        df.column_block("b")


def test_mixed_rank_rejected():
    with pytest.raises(ValueError, match="mixed rank"):
        TensorFrame.from_columns({"y": [1.0, [2.0, 3.0]]})


def test_partitions():
    df = TensorFrame.from_columns({"x": np.arange(10)}, num_partitions=3)
    bounds = df.partition_bounds()
    assert len(bounds) == 3
    assert bounds[0][0] == 0 and bounds[-1][1] == 10
    total = sum(hi - lo for lo, hi in bounds)
    assert total == 10
    p0 = df.column_block("x", 0)
    assert p0.tolist() == list(range(bounds[0][0], bounds[0][1]))


def test_partitions_capped_at_rows():
    df = TensorFrame.from_columns({"x": np.arange(2)}, num_partitions=5)
    assert df.num_partitions == 2


def test_select_and_alias():
    df = TensorFrame.from_columns({"y": [[1.0, 2.0]]})
    df2 = df.select("y", ("y", "z"))
    assert df2.columns == ["y", "z"]
    assert np.array_equal(df2.column_block("z"), df.column_block("y"))


def test_with_column():
    df = TensorFrame.from_columns({"x": np.arange(3.0)})
    df2 = df.with_column("z", np.arange(3.0) * 2)
    assert set(df2.columns) == {"x", "z"}
    with pytest.raises(ValueError, match="rows"):
        df.with_column("bad", np.arange(5.0))


def test_repartition():
    df = TensorFrame.from_columns({"x": np.arange(10)}).repartition(4)
    assert df.num_partitions == 4


def test_to_pandas_roundtrip():
    pd = pytest.importorskip("pandas")
    pdf = pd.DataFrame({"x": [1.0, 2.0], "y": [[1, 2], [3, 4]]})
    df = TensorFrame.from_pandas(pdf)
    back = df.to_pandas()
    assert list(back["x"]) == [1.0, 2.0]
    assert [list(v) for v in back["y"]] == [[1, 2], [3, 4]]


class TestAnalyze:
    # reference ExtraOperationsSuite.scala:15-98

    def test_scalar(self):
        df = TensorFrame.from_columns({"x": np.arange(4.0)}).analyze()
        # single partition of 4 rows -> lead dim known
        assert df.schema["x"].block_shape == Shape(4)

    def test_vector_uniform(self):
        df = TensorFrame.from_columns(
            {"y": [[float(i), float(-i)] for i in range(10)]}
        ).analyze()
        assert df.schema["y"].block_shape == Shape(10, 2)
        assert df.schema["y"].cell_shape == Shape(2)

    def test_vector_multi_partition_lead_unknown(self):
        # 3 partitions of differing sizes -> lead dim merges to Unknown
        df = TensorFrame.from_columns(
            {"y": [[float(i)] for i in range(10)]}, num_partitions=3
        ).analyze()
        assert df.schema["y"].block_shape == Shape(Unknown, 1)

    def test_ragged_merges_to_unknown(self):
        df = TensorFrame.from_columns({"y": [[1.0], [2.0, 3.0]]}).analyze()
        assert df.schema["y"].block_shape == Shape(2, Unknown)

    def test_print_schema_like_readme(self):
        # README.md:105-108
        df = TensorFrame.from_columns(
            {"y": [[float(i), float(-i)] for i in range(10)]}, num_partitions=2
        ).analyze()
        line = df.explain_tensors()
        assert "DoubleType[?,2]" in line or "DoubleType[5,2]" in line


def test_group_by_unknown_key():
    df = TensorFrame.from_columns({"x": np.arange(3)})
    with pytest.raises(KeyError):
        df.group_by("nope")


def test_filter_rows():
    df = TensorFrame.from_columns({"x": np.arange(5.0)})
    df2 = df.filter_rows(np.array([True, False, True, False, True]))
    assert [r.x for r in df2.collect()] == [0.0, 2.0, 4.0]


class TestMethodStyleOps:
    """Method-style op sugar (reference DFImplicits adds df.mapBlocks(...)
    etc. on DataFrames, ``dsl/Implicits.scala:25-116``)."""

    def test_map_blocks_method(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(5.0)})
        out = df.map_blocks(lambda x: {"z": x + 3.0})
        assert [r.z for r in out.collect()] == [3.0, 4.0, 5.0, 6.0, 7.0]

    def test_camelcase_aliases_and_trimmed(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(6.0)})
        assert df.mapBlocks(lambda x: {"z": x * 2.0}).collect()[2].z == 4.0
        tr = df.mapBlocksTrimmed(lambda x: {"u": x[:2]})
        assert len(tr.collect()) == 2
        assert df.mapRows(lambda x: {"r": x + 1.0}).collect()[0].r == 1.0

    def test_reduce_methods(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(4.0)})
        assert float(df.reduce_blocks(lambda x_input: {"x": x_input.sum()})) == 6.0
        assert float(df.reduceRows(lambda x_1, x_2: {"x": x_1 + x_2})) == 6.0

    def test_block_method_and_dsl(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(3.0)})
        with tft.graph():
            z = (df.block("x") * 2.0).named("z")
            out = df.map_blocks(z)
        assert [r.z for r in out.collect()] == [0.0, 2.0, 4.0]

    def test_grouped_aggregate_method(self):
        df = tft.TensorFrame.from_columns(
            {
                "k": np.array([0, 1, 0], dtype=np.int64),
                "x": np.array([1.0, 2.0, 4.0]),
            }
        )
        out = df.group_by("k").aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}
        )
        assert sorted((int(r.k), r.x) for r in out.collect()) == [
            (0, 5.0),
            (1, 2.0),
        ]


class TestFromArrowUnified:
    def test_fixed_size_list_round_trip_via_class_method(self):
        pa = pytest.importorskip("pyarrow")
        from tensorframes_tpu.interop.arrow import to_arrow

        df = tft.TensorFrame.from_columns(
            {"v": np.arange(8, dtype=np.float32).reshape(4, 2)}
        ).analyze()
        table = to_arrow(df)
        assert pa.types.is_fixed_size_list(table.column("v").type)
        back = tft.TensorFrame.from_arrow(table)
        # the fast path must land a dense [n, 2] f32 column, not object cells
        assert back.column_data("v").host().dtype == np.float32
        np.testing.assert_array_equal(
            back.column_data("v").host(), df.column_data("v").host()
        )

    def test_nulls_rejected_via_class_method(self):
        pa = pytest.importorskip("pyarrow")

        table = pa.table({"x": pa.array([1.0, None, 3.0])})
        with pytest.raises(ValueError, match="null"):
            tft.TensorFrame.from_arrow(table)
