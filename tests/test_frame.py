"""TensorFrame columnar-table tests, incl. the analyze() semantics of the
reference (`ExtraOperationsSuite.scala:15-98`)."""

import numpy as np
import pytest

from tensorframes_tpu.frame import Row, TensorFrame
from tensorframes_tpu.schema import Shape, Unknown


def test_from_columns_dense_scalar():
    df = TensorFrame.from_columns({"x": np.arange(10.0)})
    assert df.num_rows == 10
    assert df.columns == ["x"]
    assert df.schema["x"].scalar_type.name == "float64"
    assert df.schema["x"].block_shape == Shape(Unknown)


def test_from_rows_and_collect():
    rows = [dict(x=float(i)) for i in range(5)]
    df = TensorFrame.from_rows(rows)
    out = df.collect()
    assert [r.x for r in out] == [0.0, 1.0, 2.0, 3.0, 4.0]
    assert repr(out[0]) == "Row(x=0.0)"


def test_vector_column_dense():
    df = TensorFrame.from_columns({"y": [[1.0, -1.0], [2.0, -2.0]]})
    assert df.schema["y"].nesting == 1
    block = df.column_block("y")
    assert block.shape == (2, 2)


def test_ragged_column():
    df = TensorFrame.from_columns({"y": [[1.0], [2.0, 3.0]]})
    cd = df.column_data("y")
    assert cd.dense is None
    with pytest.raises(ValueError, match="ragged"):
        df.column_block("y")


def test_binary_column():
    df = TensorFrame.from_columns({"b": [b"ab", b"cde"]})
    assert df.schema["b"].scalar_type.name == "binary"
    with pytest.raises(ValueError, match="binary"):
        df.column_block("b")


def test_mixed_rank_rejected():
    with pytest.raises(ValueError, match="mixed rank"):
        TensorFrame.from_columns({"y": [1.0, [2.0, 3.0]]})


def test_partitions():
    df = TensorFrame.from_columns({"x": np.arange(10)}, num_partitions=3)
    bounds = df.partition_bounds()
    assert len(bounds) == 3
    assert bounds[0][0] == 0 and bounds[-1][1] == 10
    total = sum(hi - lo for lo, hi in bounds)
    assert total == 10
    p0 = df.column_block("x", 0)
    assert p0.tolist() == list(range(bounds[0][0], bounds[0][1]))


def test_partitions_capped_at_rows():
    df = TensorFrame.from_columns({"x": np.arange(2)}, num_partitions=5)
    assert df.num_partitions == 2


def test_select_and_alias():
    df = TensorFrame.from_columns({"y": [[1.0, 2.0]]})
    df2 = df.select("y", ("y", "z"))
    assert df2.columns == ["y", "z"]
    assert np.array_equal(df2.column_block("z"), df.column_block("y"))


def test_with_column():
    df = TensorFrame.from_columns({"x": np.arange(3.0)})
    df2 = df.with_column("z", np.arange(3.0) * 2)
    assert set(df2.columns) == {"x", "z"}
    with pytest.raises(ValueError, match="rows"):
        df.with_column("bad", np.arange(5.0))


def test_repartition():
    df = TensorFrame.from_columns({"x": np.arange(10)}).repartition(4)
    assert df.num_partitions == 4


def test_to_pandas_roundtrip():
    pd = pytest.importorskip("pandas")
    pdf = pd.DataFrame({"x": [1.0, 2.0], "y": [[1, 2], [3, 4]]})
    df = TensorFrame.from_pandas(pdf)
    back = df.to_pandas()
    assert list(back["x"]) == [1.0, 2.0]
    assert [list(v) for v in back["y"]] == [[1, 2], [3, 4]]


class TestAnalyze:
    # reference ExtraOperationsSuite.scala:15-98

    def test_scalar(self):
        df = TensorFrame.from_columns({"x": np.arange(4.0)}).analyze()
        # single partition of 4 rows -> lead dim known
        assert df.schema["x"].block_shape == Shape(4)

    def test_vector_uniform(self):
        df = TensorFrame.from_columns(
            {"y": [[float(i), float(-i)] for i in range(10)]}
        ).analyze()
        assert df.schema["y"].block_shape == Shape(10, 2)
        assert df.schema["y"].cell_shape == Shape(2)

    def test_vector_multi_partition_lead_unknown(self):
        # 3 partitions of differing sizes -> lead dim merges to Unknown
        df = TensorFrame.from_columns(
            {"y": [[float(i)] for i in range(10)]}, num_partitions=3
        ).analyze()
        assert df.schema["y"].block_shape == Shape(Unknown, 1)

    def test_ragged_merges_to_unknown(self):
        df = TensorFrame.from_columns({"y": [[1.0], [2.0, 3.0]]}).analyze()
        assert df.schema["y"].block_shape == Shape(2, Unknown)

    def test_print_schema_like_readme(self):
        # README.md:105-108
        df = TensorFrame.from_columns(
            {"y": [[float(i), float(-i)] for i in range(10)]}, num_partitions=2
        ).analyze()
        line = df.explain_tensors()
        assert "DoubleType[?,2]" in line or "DoubleType[5,2]" in line


def test_group_by_unknown_key():
    df = TensorFrame.from_columns({"x": np.arange(3)})
    with pytest.raises(KeyError):
        df.group_by("nope")


def test_filter_rows():
    df = TensorFrame.from_columns({"x": np.arange(5.0)})
    df2 = df.filter_rows(np.array([True, False, True, False, True]))
    assert [r.x for r in df2.collect()] == [0.0, 2.0, 4.0]
