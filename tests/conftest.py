"""Test configuration.

Tests run on CPU with a virtual 8-device mesh so multi-chip sharding logic
(`tensorframes_tpu.parallel`) is exercised without TPU hardware, mirroring
how the reference tests distribution semantics on a `local[1]` Spark master
with explicit multi-partition RDDs
(`/root/reference/src/test/scala/org/tensorframes/TensorFlossTestSparkContext.scala:10-43`).

Env vars must be set before jax initializes its backends, hence here.
"""

import os

# force CPU even when the environment points at a TPU tunnel: unit tests
# exercise sharding on 8 virtual devices, not the single real chip.
# The image's sitecustomize imports jax at interpreter start, so the env-var
# route alone is too late — flip the live jax config as well (backends are
# not initialized until the first jax.devices()/computation).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(autouse=True, scope="session")
def _debug_bundles_in_tmp(tmp_path_factory):
    """Flight-recorder debug bundles (engine fatals, quarantines the
    fault suites deliberately trigger) land in the test session's tmp
    dir, not the developer's ~/.cache. setdefault so an explicit
    operator/CI TFT_DEBUG_DIR still wins."""
    os.environ.setdefault(
        "TFT_DEBUG_DIR", str(tmp_path_factory.mktemp("debug-bundles"))
    )


@pytest.fixture(autouse=True, scope="session")
def _program_costs_in_tmp(tmp_path_factory):
    """The program-cost registry's JSONL autopersist (obs/programs.py,
    fed by the time-series sampler tick) writes to the test session's
    tmp dir, not the developer's journal root."""
    os.environ.setdefault(
        "TFT_PROGRAM_COSTS_FILE",
        str(tmp_path_factory.mktemp("program-costs") / "programs.jsonl"),
    )


@pytest.fixture(autouse=True, scope="session")
def _request_ledger_in_tmp(tmp_path_factory):
    """The per-request cost ledger (obs/requests.py, fed by engine
    request completion) appends to the test session's tmp dir, not the
    developer's journal root."""
    os.environ.setdefault(
        "TFT_REQUESTS_FILE",
        str(tmp_path_factory.mktemp("request-costs") / "requests.jsonl"),
    )


@pytest.fixture(autouse=True, scope="session")
def _tune_store_in_tmp(tmp_path_factory):
    """The self-tuning layer's persisted store (tensorframes_tpu/tune)
    reads/writes the test session's tmp dir: tests must neither pollute
    the developer's store nor inherit its stale winners (a tuned
    block-row budget from a bench run would silently change every
    map_rows plan under test). Unlike the debug/costs fixtures above
    this one FORCES the path — an inherited TFT_TUNE_FILE (e.g. the
    shared fleet store docs/tuning.md recommends exporting) would both
    leak winners INTO the tests and let the pin/clear/put drills wipe
    real fleet entries."""
    os.environ["TFT_TUNE_FILE"] = str(
        tmp_path_factory.mktemp("tune-store") / "tune.jsonl"
    )


@pytest.fixture
def rng():
    return np.random.default_rng(0)
