"""The performance observatory (ISSUE 12): the time-series store +
background sampler (``obs/timeseries.py``), the per-program
cost/roofline registry (``obs/programs.py``), the SLO burn-rate
monitors (``obs/slo.py``), and their serving surfaces (``GET /varz``,
the ``/statusz`` programs/slo tables, the degraded ``/healthz``
state).

The acceptance soak at the bottom drives the whole loop on one live
server: real generations populate the store, ``/varz`` serves
non-empty queue-depth/pages/TTFT-p99 series, ``/statusz`` lists every
compiled step program with flops/bytes/invocations/cumulative time,
and a chaos-injected decode latency burns the TTFT SLO until
``/healthz`` reports ``degraded`` with a flight-recorder event.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import obs
from tensorframes_tpu.obs import programs, slo, timeseries
from tensorframes_tpu.obs.timeseries import TimeSeriesStore, _Ring, _Series
from tensorframes_tpu.utils import get_config, set_config

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _isolated_observatory():
    """Each test sees an empty store/monitor/program registry and
    leaves them empty (the default store is process-global)."""
    timeseries.store().reset()
    slo.monitor().clear()
    yield
    slo.monitor().clear()
    timeseries.store().reset()


@pytest.fixture(scope="module")
def lm():
    from tensorframes_tpu.models import TransformerLM

    return TransformerLM.init(0, 64, d_model=16, n_heads=4, max_len=48)


def _http(host, port, path):
    c = socket.create_connection((host, port))
    try:
        c.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    finally:
        c.close()
    head, _, body = buf.partition(b"\r\n\r\n")
    status = head.split(b"\r\n")[0].decode()
    return status, body


# ---------------------------------------------------------------------------
# ring + retention tiers
# ---------------------------------------------------------------------------


class TestRing:
    def test_wraparound_keeps_newest(self):
        r = _Ring(4)
        for i in range(10):
            r.append(float(i), float(i * 10))
        pts = r.points()
        assert len(pts) == 4
        assert pts == [(6.0, 60.0), (7.0, 70.0), (8.0, 80.0), (9.0, 90.0)]
        # append after wrap keeps rolling
        r.append(10.0, 100.0)
        assert r.points()[0] == (7.0, 70.0)
        assert r.points()[-1] == (10.0, 100.0)

    def test_partial_fill_returns_in_order(self):
        r = _Ring(8)
        r.append(1.0, 1.0)
        r.append(2.0, 2.0)
        assert r.points() == [(1.0, 1.0), (2.0, 2.0)]

    def test_downsample_cascade_means_and_timestamps(self):
        """Every `factor` tier-0 appends produce one tier-1 point whose
        value is the MEAN of the collapsed span and whose timestamp is
        the span's last; tier 2 cascades the same way."""
        s = _Series("t", cap=16, factor=4, n_tiers=3)
        for i in range(16):
            s.append(float(i), float(i))
        t1 = s.tiers[1].points()
        assert len(t1) == 4
        # spans [0..3], [4..7], ... -> means 1.5, 5.5, 9.5, 13.5
        assert [v for _, v in t1] == [1.5, 5.5, 9.5, 13.5]
        assert [ts for ts, _ in t1] == [3.0, 7.0, 11.0, 15.0]
        t2 = s.tiers[2].points()
        assert len(t2) == 1
        assert t2[0] == (15.0, 7.5)  # mean of the four tier-1 means

    def test_tier_retention_outlives_raw_ring(self):
        """Once tier 0 wraps, tier 1 still covers the evicted span —
        the whole point of retention tiers."""
        store = TimeSeriesStore(samples_per_tier=8, downsample=4, tiers=2)
        for i in range(64):
            store.record("s", float(i), float(i))
        raw = store.points("s", 0)
        assert len(raw) == 8 and raw[0][0] == 56.0  # newest 8 only
        merged = store.window("s", seconds=60.0, now=63.0)
        # the window reaches back to t=3: tier 1 supplies the old span
        assert merged[0][0] < 56.0
        assert merged == sorted(merged)

    def test_window_merges_tiers_without_overlap(self):
        store = TimeSeriesStore(samples_per_tier=4, downsample=2, tiers=2)
        for i in range(12):
            store.record("s", float(i), float(i))
        pts = store.window("s", seconds=100.0, now=11.0)
        ts = [t for t, _ in pts]
        assert ts == sorted(ts)
        assert len(ts) == len(set(ts))  # no duplicated timestamps
        assert ts[-1] == 11.0  # the newest raw point is included


# ---------------------------------------------------------------------------
# store sampling semantics
# ---------------------------------------------------------------------------


class TestStoreSampling:
    def test_gauge_counter_histogram_series_shapes(self):
        store = TimeSeriesStore()
        obs.gauge("t.ob_g", "x").set(5.0)
        c = obs.counter("t.ob_total", "x")
        c.inc(10)
        h = obs.histogram("t.ob_seconds", "x")
        h.observe(0.01)
        store.sample(now=100.0)
        c.inc(20)
        h.observe(0.01)
        store.sample(now=102.0)
        assert store.latest("t.ob_g") == (102.0, 5.0)
        # counter rate: 20 increments over 2 seconds
        assert store.latest("t.ob_total.rate") == (102.0, 10.0)
        # histogram quantiles + observation rate
        assert store.latest("t.ob_seconds.p50")[1] == pytest.approx(
            h.quantile(0.5)
        )
        assert store.latest("t.ob_seconds.p99")[1] == pytest.approx(
            h.quantile(0.99)
        )
        assert store.latest("t.ob_seconds.rate") == (102.0, 0.5)

    def test_labeled_series_get_their_own_names(self):
        store = TimeSeriesStore()
        c = obs.counter("t.ob_lab_total", "x", labels=("op",))
        c.inc(3, op="a")
        store.sample(now=10.0)
        c.inc(3, op="a")
        c.inc(9, op="b")
        store.sample(now=11.0)
        store.sample(now=12.0)
        assert store.latest("t.ob_lab_total{op=a}.rate")[1] == 0.0
        # op=b first seen at t=11 (baseline), rate 0 by t=12
        assert store.latest("t.ob_lab_total{op=b}.rate")[1] == 0.0
        pts = store.points("t.ob_lab_total{op=a}.rate")
        assert pts[0] == (11.0, 3.0)

    def test_counter_reset_rebaselines_instead_of_negative_rate(self):
        store = TimeSeriesStore()
        c = obs.counter("t.ob_reset_total", "x")
        c.inc(100)
        store.sample(now=10.0)
        obs.registry().get("t.ob_reset_total")._reset()  # process restart
        c.inc(7)
        store.sample(now=11.0)  # cum went 100 -> 7: no point, re-baseline
        c.inc(5)
        store.sample(now=12.0)  # rate resumes from the new baseline
        pts = store.points("t.ob_reset_total.rate")
        assert all(v >= 0 for _, v in pts)
        assert pts == [(12.0, 5.0)]

    def test_histogram_quantiles_are_windowed_not_lifetime(self):
        """A latency spike must AGE OUT of the sampled p99: quantiles
        come from the bucket-count delta per tick, not the lifetime
        histogram — a cumulative p99 would pin any SLO over it breached
        for hours after a one-minute incident ended."""
        store = TimeSeriesStore()
        h = obs.histogram("t.ob_win_seconds", "x")
        h.observe(0.001)
        store.sample(now=10.0)  # baseline tick: no quantile point yet
        assert store.latest("t.ob_win_seconds.p99") is None
        h.observe(10.0)  # the spike
        store.sample(now=11.0)
        assert store.latest("t.ob_win_seconds.p99")[1] > 1.0
        h.observe(0.001)  # back to normal
        store.sample(now=12.0)
        assert store.latest("t.ob_win_seconds.p99")[1] < 1.0  # aged out
        store.sample(now=13.0)  # idle tick: no new observations
        assert store.latest("t.ob_win_seconds.p99")[0] == 12.0

    def test_kill_switch_parks_sampling(self):
        store = TimeSeriesStore()
        obs.gauge("t.ob_killed", "x").set(1.0)
        set_config(observability=False)
        try:
            assert store.sample(now=5.0) == 0
            assert store.names() == []
        finally:
            set_config(observability=True)

    def test_series_cap_drops_new_not_crashes(self):
        store = TimeSeriesStore()
        import tensorframes_tpu.obs.timeseries as ts_mod

        old = ts_mod._MAX_SERIES
        ts_mod._MAX_SERIES = 2
        try:
            store.record("a", 1.0, 1.0)
            store.record("b", 1.0, 1.0)
            store.record("c", 1.0, 1.0)  # dropped
            assert store.names() == ["a", "b"]
            store.record("a", 2.0, 2.0)  # existing still records
            assert len(store.points("a")) == 2
        finally:
            ts_mod._MAX_SERIES = old

    def test_background_sampler_refcount(self):
        set_config(obs_sample_interval_s=0.02)
        try:
            timeseries.acquire_sampler()
            timeseries.acquire_sampler()
            assert timeseries.sampler_running()
            timeseries.release_sampler()
            assert timeseries.sampler_running()  # still one holder
            obs.gauge("t.ob_bg", "x").set(3.0)
            deadline = time.monotonic() + 5.0
            while (
                timeseries.store().latest("t.ob_bg") is None
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            assert timeseries.store().latest("t.ob_bg") is not None
        finally:
            timeseries.release_sampler()
            set_config(obs_sample_interval_s=1.0)
        assert not timeseries.sampler_running()

    def test_sampler_release_acquire_bounce_leaves_one_thread(self):
        """A quick release->acquire (server bounce) must not leak the
        old sampler thread: each thread owns its OWN stop event, so the
        new acquire cannot un-set the event the old thread exits on."""
        set_config(obs_sample_interval_s=0.02)
        try:
            timeseries.acquire_sampler()
            timeseries.release_sampler()
            timeseries.acquire_sampler()  # immediate re-acquire
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                alive = [
                    t for t in threading.enumerate()
                    if t.name == "tft-obs-sampler" and t.is_alive()
                ]
                if len(alive) == 1:
                    break
                time.sleep(0.02)
            assert len(alive) == 1, f"{len(alive)} sampler threads alive"
            assert timeseries.sampler_running()
        finally:
            timeseries.release_sampler()
            set_config(obs_sample_interval_s=1.0)
        assert not timeseries.sampler_running()


# ---------------------------------------------------------------------------
# per-program cost registry
# ---------------------------------------------------------------------------


class TestPrograms:
    def test_matmul_costs_are_exact_2mnk(self):
        import jax

        programs.reset()
        try:
            m, k, n = 32, 48, 16
            a = np.ones((m, k), np.float32)
            b = np.ones((k, n), np.float32)
            wrapped = programs.instrument(
                jax.jit(lambda a, b: {"y": a @ b}),
                key="t:mm", name="t.matmul", kind="test",
            )
            wrapped(a, b)
            (rec,) = programs.programs()
            assert rec.flops == pytest.approx(2 * m * n * k)
            assert rec.cost_source in ("xla", "jaxpr")
            assert rec.compile_s is not None and rec.compile_s > 0
        finally:
            programs.reset()

    def test_jaxpr_fallback_matches_xla_for_matmul(self):
        import jax
        import jax.numpy as jnp

        f = lambda x: {"y": jnp.tanh(x) @ x}  # noqa: E731
        x = np.ones((8, 8), np.float32)
        flops, nbytes, _ = programs.estimate_costs(jax.jit(f), x)
        closed = jax.make_jaxpr(f)(x)
        j_flops, j_bytes = programs.jaxpr_costs(closed)
        # dot dominates and both agree on it exactly (2*8*8*8); the
        # elementwise tanh counts its outputs in both models
        assert j_flops == pytest.approx(2 * 8 * 8 * 8 + 8 * 8)
        assert flops >= 2 * 8 * 8 * 8
        assert nbytes > 0 and j_bytes == 8 * 8 * 4 * 2

    def test_dispatch_accounting_and_table_order(self):
        import jax

        programs.reset()
        try:
            w = programs.instrument(
                jax.jit(lambda x: {"y": x + 1}),
                key="t:a", name="t.a", kind="test",
            )
            x = np.ones((4,), np.float32)
            for _ in range(5):
                w(x)
            rec = w.record
            assert rec.invocations == 5
            assert rec.dispatches == 4  # first call was the compile
            assert rec.dispatch_s >= 0
            row = programs.table()[0]
            for field in (
                "compile_s", "flops", "bytes", "invocations",
                "dispatch_s", "achieved_flops_per_s",
                "intensity_flops_per_byte", "roofline_utilization",
            ):
                assert field in row
        finally:
            programs.reset()

    def test_recompile_books_into_compile_not_dispatch(self):
        """A later-signature call recompiles; its (potentially
        seconds-long) wall must land in compile_s, not corrupt the
        dispatch_s the roofline divides by. Detection: the jit's
        executable-cache depth grew."""
        import jax

        programs.reset()
        try:
            w = programs.instrument(
                jax.jit(lambda x: {"y": x * 2}),
                key="t:rc", name="t.recompile", kind="test",
            )
            w(np.ones((4,), np.float32))   # compile #1
            w(np.ones((4,), np.float32))   # dispatch
            compile_after_one = w.record.compile_s
            w(np.ones((9,), np.float32))   # NEW signature: compile #2
            w(np.ones((9,), np.float32))   # dispatch
            rec = w.record
            assert rec.invocations == 4
            assert rec.dispatches == 2
            assert rec.compile_s > compile_after_one  # accumulated
        finally:
            programs.reset()

    def test_kill_switch_is_a_pure_passthrough(self):
        """Under TFT_OBS=0 the wrapper must not even REGISTER: no
        record, nothing for /statusz to list, nothing for autopersist
        to write (registration is lazy on the first enabled call)."""
        import jax

        programs.reset()
        try:
            w = programs.instrument(
                jax.jit(lambda x: {"y": x * 2}),
                key="t:off", name="t.off", kind="test",
            )
            x = np.ones((4,), np.float32)
            set_config(observability=False)
            try:
                out = w(x)
                np.testing.assert_array_equal(np.asarray(out["y"]), x * 2)
                assert w.record is None
                assert programs.programs() == []
                assert programs.autopersist() == 0  # gated, no disk
            finally:
                set_config(observability=True)
            # flipping back on registers at the next call
            w(x)
            assert w.record is not None and w.record.invocations == 1
        finally:
            programs.reset()

    def test_engine_map_rows_registers_a_program(self):
        programs.reset()
        try:
            df = tft.TensorFrame.from_columns(
                {"x": np.ones((64, 4), np.float32)}
            ).analyze()
            tft.map_rows(lambda x: {"yy_obs": x * 2.0}, df).collect()
            names = [r.name for r in programs.programs()]
            assert any("yy_obs" in n for n in names), names
            rec = next(r for r in programs.programs() if "yy_obs" in r.name)
            assert rec.kind in ("engine.row", "engine.block")
            assert rec.flops is not None and rec.invocations >= 1
        finally:
            programs.reset()

    def test_fused_plan_composite_carries_its_label(self):
        programs.reset()
        try:
            df = tft.TensorFrame.from_columns(
                {"x": np.ones((64, 4), np.float32)}
            ).analyze()
            a = tft.map_rows(lambda x: {"m1_obs": x * 2.0}, df)
            b = tft.map_rows(lambda m1_obs: {"m2_obs": m1_obs + 1.0}, a)
            b.collect()
            names = [r.name for r in programs.programs()]
            assert any(n.startswith("plan.fused:") for n in names), names
        finally:
            programs.reset()

    def test_persist_jsonl_appends_only_dirty(self, tmp_path):
        import jax

        programs.reset()
        try:
            target = str(tmp_path / "programs.jsonl")
            w = programs.instrument(
                jax.jit(lambda x: {"y": x}),
                key="t:p", name="t.persist", kind="test",
            )
            w(np.ones((2,), np.float32))
            assert programs.persist(target) == 1
            assert programs.persist(target) == 0  # nothing moved
            w(np.ones((2,), np.float32))
            assert programs.persist(target) == 1
            lines = [
                json.loads(ln)
                for ln in open(target).read().splitlines()
            ]
            assert len(lines) == 2
            assert lines[0]["name"] == "t.persist"
            assert lines[1]["invocations"] == 2
            assert {"ts", "host", "pid", "flops", "dispatch_s"} <= set(
                lines[1]
            )
        finally:
            programs.reset()

    def test_peak_override_enables_roofline(self, monkeypatch):
        import jax

        programs.reset()
        try:
            monkeypatch.setenv("TFT_PEAK_FLOPS", "1e12")
            w = programs.instrument(
                jax.jit(lambda a, b: {"y": a @ b}),
                key="t:r", name="t.roof", kind="test",
            )
            a = np.ones((64, 64), np.float32)
            w(a, a)
            w(a, a)
            row = programs.table()[0]
            assert row["roofline_utilization"] is not None
            assert 0 < row["roofline_utilization"] < 1
        finally:
            programs.reset()

    def test_serve_engine_registers_named_step_programs(self, lm):
        from tensorframes_tpu.serve.engine import GenerationEngine

        programs.reset()
        try:
            eng = GenerationEngine(
                lm, max_slots=2, page_size=4, max_seq_len=32, name="rX"
            )
            h = eng.submit([1, 2, 3], 4)
            eng.run_until_idle()
            h.result(timeout=30)
            names = {r.name for r in programs.programs()}
            assert "serve.prefill[rX]" in names
            assert "serve.decode[rX]" in names
            decode = next(
                r for r in programs.programs()
                if r.name == "serve.decode[rX]"
            )
            assert decode.invocations >= 3
            assert decode.flops is not None and decode.dispatch_s > 0
        finally:
            programs.reset()

    def test_explain_analyze_appends_programs_table(self):
        programs.reset()
        try:
            df = tft.TensorFrame.from_columns(
                {"x": np.ones((16, 4), np.float32)}
            ).analyze()
            out = tft.map_rows(lambda x: {"ex_obs": x * 3.0}, df)
            out.collect()
            txt = tft.explain(out, analyze=True)
            assert "== Programs ==" in txt
            assert "ex_obs" in txt.split("== Programs ==")[1]
            # and without the flag, no table
            assert "== Programs ==" not in tft.explain(out)
        finally:
            programs.reset()


# ---------------------------------------------------------------------------
# SLO monitors
# ---------------------------------------------------------------------------


class TestSLO:
    def _ticks(self, store, series, values, start=1000.0, dt=1.0):
        for i, v in enumerate(values):
            store.record(series, start + i * dt, v)

    def test_breach_and_recovery_transitions(self):
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.Objective(
            name="t_lat", series="t.lat.p99", bound=1.0, kind="upper",
            fast_window_s=10.0, slow_window_s=20.0, min_samples=3,
        ))
        breaches = obs.registry().get("slo.breaches_total")
        base = breaches.value(slo="t_lat")
        self._ticks(store, obj.series, [5.0, 5.0, 5.0], start=1000.0)
        mon.evaluate(store, now=1002.0)
        assert mon.degraded()
        (st,) = mon.status()
        assert st["breached"] and st["fast_burn"] == 1.0
        assert breaches.value(slo="t_lat") == base + 1
        assert (
            obs.registry().get("slo.breached").value(slo="t_lat") == 1.0
        )
        # recovery: healthy samples displace the window
        self._ticks(store, obj.series, [0.1] * 12, start=1003.0)
        mon.evaluate(store, now=1014.0)
        assert not mon.degraded()
        assert (
            obs.registry().get("slo.breached").value(slo="t_lat") == 0.0
        )
        # exactly one breach counted for the whole episode
        assert breaches.value(slo="t_lat") == base + 1

    def test_flight_events_on_transition(self):
        obs.flight.reset()
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.Objective(
            name="t_ev", series="t.ev", bound=1.0,
            fast_window_s=5.0, slow_window_s=10.0, min_samples=2,
        ))
        self._ticks(store, obj.series, [9.0, 9.0], start=100.0)
        mon.evaluate(store, now=101.0)
        self._ticks(store, obj.series, [0.0] * 8, start=102.0)
        mon.evaluate(store, now=109.0)
        kinds = [
            (e["kind"], e.get("slo"))
            for e in obs.flight.rings().get("slo", [])
        ]
        assert ("breach", "t_ev") in kinds
        assert ("recovered", "t_ev") in kinds

    def test_fast_vs_sustained_severity(self):
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.Objective(
            name="t_sev", series="t.sev", bound=1.0,
            fast_window_s=4.0, slow_window_s=40.0, min_samples=2,
        ))
        # long healthy history, then a sharp recent burn: fast-only
        self._ticks(store, obj.series, [0.0] * 30, start=1000.0)
        self._ticks(store, obj.series, [5.0] * 4, start=1030.0)
        mon.evaluate(store, now=1033.0)
        (st,) = mon.status()
        assert st["breached"] and st["severity"] == "fast"
        # keep burning until the slow window crosses too
        self._ticks(store, obj.series, [5.0] * 30, start=1034.0)
        mon.evaluate(store, now=1063.0)
        (st,) = mon.status()
        assert st["severity"] == "sustained"

    def test_lower_bound_objective(self):
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.tokens_per_s_floor(
            100.0, fast_window_s=5.0, slow_window_s=10.0, min_samples=2,
        ))
        assert obj.series == "serve.tokens_total.rate"
        self._ticks(store, obj.series, [10.0, 10.0, 10.0], start=50.0)
        mon.evaluate(store, now=52.0)
        assert mon.degraded()

    def test_idle_zero_rate_does_not_breach_a_floor(self):
        """Counter rates record an explicit 0.0 every idle tick, so a
        throughput floor must not flip a healthy idle server to
        degraded: tokens_per_s_floor excludes exact-zero samples by
        default (ignore_zero=True)."""
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.tokens_per_s_floor(
            100.0, fast_window_s=5.0, slow_window_s=10.0, min_samples=2,
        ))
        self._ticks(store, obj.series, [0.0] * 5, start=50.0)  # idle
        mon.evaluate(store, now=54.0)
        assert not mon.degraded()
        # genuinely slow (nonzero but under the floor) still breaches
        self._ticks(store, obj.series, [5.0, 5.0, 5.0], start=60.0)
        mon.evaluate(store, now=62.0)
        assert mon.degraded()
        mon.clear()
        # opting out alerts on idleness itself
        mon.add(slo.tokens_per_s_floor(
            100.0, fast_window_s=5.0, slow_window_s=10.0,
            min_samples=2, ignore_zero=False,
        ))
        mon.evaluate(store, now=54.0)
        assert mon.degraded()

    def test_min_samples_gates_cold_series(self):
        store = TimeSeriesStore()
        mon = slo.SLOMonitor()
        obj = mon.add(slo.Objective(
            name="t_cold", series="t.cold", bound=1.0, min_samples=5,
            fast_window_s=10.0, slow_window_s=10.0,
        ))
        self._ticks(store, obj.series, [9.0] * 4, start=10.0)
        mon.evaluate(store, now=13.0)
        assert not mon.degraded()  # 4 < min_samples

    def test_objective_validation(self):
        with pytest.raises(ValueError):
            slo.Objective(name="x", series="s", bound=1.0, kind="sideways")
        with pytest.raises(ValueError):
            slo.Objective(name="x", series="s", bound=1.0, burn_threshold=0)
        with pytest.raises(ValueError):
            slo.Objective(
                name="x", series="s", bound=1.0,
                fast_window_s=60, slow_window_s=30,
            )


# ---------------------------------------------------------------------------
# serving surfaces
# ---------------------------------------------------------------------------


class TestEndpoints:
    def test_varz_statusz_healthz_shapes(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.serve.engine import GenerationEngine

        programs.reset()
        prev = get_config().obs_sample_interval_s
        set_config(obs_sample_interval_s=0.02)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        srv = ScoringServer(engine=eng)
        try:
            host, port = srv.start()
            assert timeseries.sampler_running()  # the server holds it
            h = eng.submit([1, 2, 3], 4)
            h.result(timeout=60)
            deadline = time.monotonic() + 5.0
            while (
                timeseries.store().latest("serve.queue_depth") is None
                and time.monotonic() < deadline
            ):
                time.sleep(0.02)
            status, body = _http(host, port, "/varz")
            assert status.endswith("200 OK")
            varz = json.loads(body)
            assert varz["sampler_running"]
            assert "serve.queue_depth" in varz["series"]
            assert varz["series"]["serve.queue_depth"]["points"]
            # prefix + window filtering
            status, body = _http(
                host, port, "/varz?prefix=serve.queue&window=60"
            )
            filtered = json.loads(body)["series"]
            assert set(filtered) == {"serve.queue_depth"}
            status, _ = _http(host, port, "/varz?window=bogus")
            assert status.endswith("400 Bad Request")
            # statusz: programs table + slo + timeseries summary
            status, body = _http(host, port, "/statusz")
            sz = json.loads(body)
            prog_names = {p["name"] for p in sz["programs"]}
            assert any(n.startswith("serve.prefill[") for n in prog_names)
            assert any(n.startswith("serve.decode[") for n in prog_names)
            for p in sz["programs"]:
                assert {
                    "flops", "bytes", "invocations", "dispatch_s",
                    "compile_s", "roofline_utilization",
                } <= set(p)
            assert sz["timeseries"]["sampler_running"]
            assert isinstance(sz["slo"], list)
            # healthz: ok status with no objectives declared
            status, body = _http(host, port, "/healthz")
            hz = json.loads(body)
            assert status.endswith("200 OK") and hz["status"] == "ok"
            assert hz["slo"] == []
            # 404 message names the varz endpoint
            status, body = _http(host, port, "/nope")
            assert status.endswith("404 Not Found")
            assert b"/varz" in body
        finally:
            srv.stop()
            set_config(obs_sample_interval_s=prev)
            programs.reset()
        assert not timeseries.sampler_running()  # released on stop

    def test_acceptance_soak_full_observatory_loop(self, lm):
        """The ISSUE-12 acceptance: one serving soak where (1) /varz
        returns non-empty queue-depth / pages / TTFT-p99 series, (2)
        /statusz lists every compiled step program with flops / bytes /
        invocations / cumulative time, and (3) a chaos-injected decode
        latency burns the TTFT p99 SLO until /healthz flips to the
        degraded state (still 200 — distinct from unhealthy) with a
        flight-recorder breach event."""
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.serve.engine import GenerationEngine

        programs.reset()
        obs.flight.reset()
        prev = get_config().obs_sample_interval_s
        set_config(obs_sample_interval_s=0.02)
        # quantile points land only on ticks with NEW TTFT observations
        # (windowed quantiles), so this low-traffic soak sizes the fast
        # window to a couple of request waves and accepts a single
        # violating sample — the tuning guidance docs/observability.md
        # gives for sparse series
        slo.monitor().add(slo.ttft_p99(
            0.5, fast_window_s=3.0, slow_window_s=12.0, min_samples=1,
        ))
        eng = GenerationEngine(lm, max_slots=4, page_size=4, max_seq_len=32)
        srv = ScoringServer(engine=eng)
        rng = np.random.default_rng(5)
        try:
            host, port = srv.start()

            def drive(n):
                handles = [
                    eng.submit(
                        list(rng.integers(1, 60, size=4)), 6, block=True
                    )
                    for _ in range(n)
                ]
                for h in handles:
                    h.result(timeout=60)

            # warmup pays the step-program compiles, then the registry
            # resets: ttft_seconds is a LIFETIME histogram, and a
            # compile-heavy first TTFT would otherwise pin its p99 over
            # the bound before any chaos fires (programs' compile_s is
            # recorded on the cost registry, which reset() leaves alone)
            drive(2)
            obs.registry().reset()
            timeseries.store().reset()

            # healthy traffic: one wave per drive (4 requests ≤
            # max_slots, so no queue wait inflates TTFT near the bound)
            drive(4)
            time.sleep(0.3)
            status, body = _http(host, port, "/healthz")
            assert json.loads(body)["status"] == "ok"

            # (3) chaos: a 1s latency on every prefill dispatch (the
            # TTFT path) burns the p99 through the 500ms bound while
            # the engine itself stays perfectly healthy
            set_config(chaos="serve.prefill=latency:ms=1000")
            try:
                deadline = time.monotonic() + 30.0
                degraded = False
                while time.monotonic() < deadline and not degraded:
                    drive(2)
                    time.sleep(0.1)
                    status, body = _http(host, port, "/healthz")
                    hz = json.loads(body)
                    degraded = hz["status"] == "degraded"
                assert degraded, "SLO breach never degraded /healthz"
                assert status.endswith("200 OK")  # degraded != unhealthy
                assert hz["healthy"] is True
                burning = [s for s in hz["slo"] if s["breached"]]
                assert burning and burning[0]["name"] == "ttft_p99"
            finally:
                set_config(chaos="")
            breach_events = [
                e for e in obs.flight.rings().get("slo", [])
                if e["kind"] == "breach" and e.get("slo") == "ttft_p99"
            ]
            assert breach_events, "breach left no flight-recorder event"

            # (1) /varz: the three acceptance series are non-empty
            status, body = _http(host, port, "/varz")
            series = json.loads(body)["series"]
            for name in (
                "serve.queue_depth",
                "serve.pages_in_use",
                "serve.ttft_seconds.p99",
            ):
                assert series.get(name, {}).get("points"), name
            # the injected latency is visible in the stored p99
            p99_values = [
                v for _, v in series["serve.ttft_seconds.p99"]["points"]
            ]
            assert max(p99_values) > 0.25

            # (2) /statusz: every compiled step program, with costs
            status, body = _http(host, port, "/statusz")
            sz = json.loads(body)
            by_name = {p["name"]: p for p in sz["programs"]}
            prefill = by_name[f"serve.prefill[{eng.name}]"]
            decode = by_name[f"serve.decode[{eng.name}]"]
            for p in (prefill, decode):
                assert p["flops"] and p["bytes"]
                assert p["invocations"] >= 1
                assert p["dispatch_s"] >= 0 and p["compile_s"] > 0
            assert decode["invocations"] > prefill["invocations"]
            slo_rows = {s["name"]: s for s in sz["slo"]}
            assert "ttft_p99" in slo_rows
        finally:
            srv.stop()
            set_config(obs_sample_interval_s=prev, chaos="")
            slo.monitor().clear()
            programs.reset()
            obs.flight.reset()


# ---------------------------------------------------------------------------
# bench-check gate logic
# ---------------------------------------------------------------------------


class TestBenchCheck:
    @staticmethod
    def _load_module():
        import importlib.util
        from pathlib import Path

        path = (
            Path(__file__).resolve().parent.parent
            / "benchmarks"
            / "bench_check.py"
        )
        spec = importlib.util.spec_from_file_location("bench_check", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def _gate(self, tmp_path, mod, baseline_value):
        base = {
            "bench_gate": {
                "tolerance_pct": 20.0,
                "env": {},
                "metrics": {
                    "map_rows_journaled_rows_per_sec": {
                        "value": baseline_value,
                        "unit": "rows/s",
                        "config": "map_rows",
                    }
                },
            }
        }
        target = tmp_path / "BASELINE.json"
        target.write_text(json.dumps(base))
        mod.BASELINE = str(target)
        return target

    def test_within_tolerance_passes(self, tmp_path, monkeypatch):
        mod = self._load_module()
        self._gate(tmp_path, mod, 1000.0)
        monkeypatch.setattr(
            mod, "_run_bench",
            lambda config, env: {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 850.0,  # -15% with 20% tolerance
            },
        )
        assert mod.check() == 0

    def test_regression_fails_nonzero(self, tmp_path, monkeypatch):
        mod = self._load_module()
        self._gate(tmp_path, mod, 1000.0)
        monkeypatch.setattr(
            mod, "_run_bench",
            lambda config, env: {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 700.0,  # -30% with 20% tolerance
            },
        )
        assert mod.check() == 1

    def test_tolerance_env_override(self, tmp_path, monkeypatch):
        mod = self._load_module()
        self._gate(tmp_path, mod, 1000.0)
        monkeypatch.setenv("TFT_BENCH_TOLERANCE_PCT", "50")
        monkeypatch.setattr(
            mod, "_run_bench",
            lambda config, env: {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 700.0,
            },
        )
        assert mod.check() == 0

    def test_per_metric_tolerance_overrides_global(
        self, tmp_path, monkeypatch
    ):
        """A `tolerances[<metric>]` entry widens (or narrows) just that
        metric's band — the fix for the false alarm where map_rows'
        machine-to-machine variance is wider than the global band that
        fits the decode bench."""
        mod = self._load_module()
        target = self._gate(tmp_path, mod, 1000.0)
        base = json.loads(target.read_text())
        base["bench_gate"]["tolerances"] = {
            "map_rows_journaled_rows_per_sec": 45.0
        }
        target.write_text(json.dumps(base))
        monkeypatch.setattr(
            mod, "_run_bench",
            lambda config, env: {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 600.0,  # -40%: fails at 20% global, ok at 45%
            },
        )
        assert mod.check() == 0
        # a metric WITHOUT an entry keeps the global band
        base["bench_gate"]["tolerances"] = {"some_other_metric": 45.0}
        target.write_text(json.dumps(base))
        assert mod.check() == 1

    def test_env_override_beats_per_metric_tolerance(
        self, tmp_path, monkeypatch
    ):
        mod = self._load_module()
        target = self._gate(tmp_path, mod, 1000.0)
        base = json.loads(target.read_text())
        base["bench_gate"]["tolerances"] = {
            "map_rows_journaled_rows_per_sec": 45.0
        }
        target.write_text(json.dumps(base))
        monkeypatch.setenv("TFT_BENCH_TOLERANCE_PCT", "10")
        monkeypatch.setattr(
            mod, "_run_bench",
            lambda config, env: {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 700.0,  # -30%: inside 45%, outside env's 10%
            },
        )
        assert mod.check() == 1

    def test_update_preserves_per_metric_tolerances(
        self, tmp_path, monkeypatch
    ):
        """--update re-measures values but must carry the `tolerances`
        block forward: the bands encode measured host variance, not the
        baseline numbers being replaced."""
        mod = self._load_module()
        target = self._gate(tmp_path, mod, 1000.0)
        base = json.loads(target.read_text())
        base["bench_gate"]["tolerances"] = {
            "map_rows_journaled_rows_per_sec": 45.0
        }
        target.write_text(json.dumps(base))
        results = {
            "map_rows": {
                "metric": "map_rows_journaled_rows_per_sec",
                "value": 1234.5,
                "unit": "rows/s",
            },
            "decode_serve": {
                "metric": "decode_serve_tokens_per_sec",
                "value": 99.0,
                "unit": "tok/s",
            },
        }
        monkeypatch.setattr(
            mod, "_run_bench", lambda config, env: results[config]
        )
        assert mod.update() == 0
        rewritten = json.loads(target.read_text())["bench_gate"]
        assert rewritten["tolerances"] == {
            "map_rows_journaled_rows_per_sec": 45.0
        }
        assert (
            rewritten["metrics"]["map_rows_journaled_rows_per_sec"]["value"]
            == 1234.5
        )

    def test_missing_gate_block_is_a_setup_error(self, tmp_path):
        mod = self._load_module()
        target = tmp_path / "BASELINE.json"
        target.write_text(json.dumps({"metric": "x"}))
        mod.BASELINE = str(target)
        assert mod.check() == 2

    def test_repo_baseline_has_a_recorded_gate(self):
        """The committed BASELINE.json must actually carry the gate the
        Makefile target reads (a fresh clone's `make bench-check` should
        compare, not error)."""
        from pathlib import Path

        base = json.loads(
            (Path(__file__).resolve().parent.parent / "BASELINE.json")
            .read_text()
        )
        gate = base.get("bench_gate")
        assert gate and gate["metrics"]
        assert set(gate["metrics"]) == {
            "map_rows_journaled_rows_per_sec",
            "decode_serve_tokens_per_sec",
        }
        for entry in gate["metrics"].values():
            assert entry["value"] > 0


# ---------------------------------------------------------------------------
# sampler overhead (the bench axis' assertable half)
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestSamplerOverhead:
    def test_sampler_overhead_within_budget(self):
        """The ISSUE-12 ≤1% budget, asserted on the map_rows microbench
        shape the bench measures (`detail.observability.sampler_*`):
        interleaved best-of passes with the background sampler at a
        0.25s cadence vs parked. The assert allows 5% — this shared
        single-core CI host jitters more than the budget itself, and the
        bench trajectory tracks the honest number every round; a wired
        per-dispatch cost (the failure this guards) shows up as tens of
        percent."""
        import time as _time

        rng = np.random.default_rng(0)
        x = rng.normal(size=(120_000, 64)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"features": x}).analyze()
        w = np.asarray(
            rng.normal(size=(64, 64)).astype(np.float32)
        )

        def score(features):
            import jax.numpy as jnp

            return {"s": jnp.tanh(features @ w).sum(axis=-1)}

        def one():
            t0 = _time.perf_counter()
            tft.map_rows(score, df).collect()
            return _time.perf_counter() - t0

        one()  # compile warmup
        prev = get_config().obs_sample_interval_s
        on = off = float("inf")
        try:
            set_config(obs_sample_interval_s=0.25)
            for _ in range(6):
                timeseries.acquire_sampler()
                try:
                    on = min(on, one())
                finally:
                    timeseries.release_sampler()
                off = min(off, one())
        finally:
            set_config(obs_sample_interval_s=prev)
        overhead = (on - off) / off * 100.0
        assert overhead <= 5.0, (
            f"sampler overhead {overhead:.2f}% exceeds budget "
            f"(on={on:.4f}s off={off:.4f}s)"
        )
