"""Expert parallelism (ep) and pipeline parallelism (pp) vs dense oracles.

Completes the mesh-axis set (dp/tp/sp/ep/pp); the reference has no model
parallelism at all (SURVEY §2.5)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorframes_tpu.parallel import make_mesh
from tensorframes_tpu.parallel.moe import init_moe, moe_apply, moe_ffn
from tensorframes_tpu.parallel.pipeline import (
    pipeline_apply,
    pipeline_reference,
)

#: full-model pipeline/MoE training sweeps: suite heavyweights (measured
#: r05 durations); `make test-fast` skips them
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def nprng():
    return np.random.default_rng(0)


class TestExpertParallel:
    def test_matches_dense_oracle(self, nprng):
        mesh = make_mesh({"ep": 4})
        params = init_moe(0, d_model=16, d_ff=32, n_experts=8)
        x = jnp.asarray(nprng.normal(size=(2, 12, 16)).astype(np.float32))
        out = moe_apply(params, x, mesh=mesh)
        ref = moe_ffn(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_eight_way_one_expert_each(self, nprng):
        mesh = make_mesh({"ep": 8})
        params = init_moe(1, d_model=8, d_ff=16, n_experts=8)
        x = jnp.asarray(nprng.normal(size=(1, 16, 8)).astype(np.float32))
        out = moe_apply(params, x, mesh=mesh)
        ref = moe_ffn(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_routing_actually_selects_experts(self, nprng):
        # different inputs must hit different experts (router is not
        # degenerate in this fixture)
        params = init_moe(2, d_model=8, d_ff=16, n_experts=4)
        x = jnp.asarray(nprng.normal(size=(1, 64, 8)).astype(np.float32))
        ids = np.asarray(
            jnp.argmax(jax.nn.softmax(x @ params["router"], axis=-1), -1)
        )
        assert len(np.unique(ids)) > 1

    def test_indivisible_experts_rejected(self, nprng):
        mesh = make_mesh({"ep": 4})
        params = init_moe(0, d_model=8, d_ff=16, n_experts=6)
        x = jnp.zeros((1, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="n_experts"):
            moe_apply(params, x, mesh=mesh)


def _stage_fn(p, h):
    return jnp.tanh(h @ p["w"] + p["b"])


def _stacked_params(rng, n_stages, d):
    return {
        "w": rng.normal(0, d**-0.5, (n_stages, d, d)).astype(np.float32),
        "b": rng.normal(0, 0.1, (n_stages, d)).astype(np.float32),
    }


class TestPipelineParallel:
    @pytest.mark.parametrize("n_micro", [2, 4, 8])
    def test_matches_sequential(self, nprng, n_micro):
        mesh = make_mesh({"pp": 4})
        params = _stacked_params(nprng, 4, 8)
        x = nprng.normal(size=(16, 8)).astype(np.float32)
        out = pipeline_apply(_stage_fn, params, x, n_micro=n_micro, mesh=mesh)
        ref = pipeline_reference(_stage_fn, params, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_eight_stages(self, nprng):
        mesh = make_mesh({"pp": 8})
        params = _stacked_params(nprng, 8, 4)
        x = nprng.normal(size=(8, 4)).astype(np.float32)
        out = pipeline_apply(_stage_fn, params, x, n_micro=4, mesh=mesh)
        ref = pipeline_reference(_stage_fn, params, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_stage_count_mismatch_rejected(self, nprng):
        mesh = make_mesh({"pp": 4})
        params = _stacked_params(nprng, 3, 8)
        with pytest.raises(ValueError, match="stages"):
            pipeline_apply(
                _stage_fn, params, np.zeros((8, 8), np.float32),
                n_micro=2, mesh=mesh,
            )

    def test_bad_microbatch_split_rejected(self, nprng):
        mesh = make_mesh({"pp": 4})
        params = _stacked_params(nprng, 4, 8)
        with pytest.raises(ValueError, match="n_micro"):
            pipeline_apply(
                _stage_fn, params, np.zeros((9, 8), np.float32),
                n_micro=2, mesh=mesh,
            )

    def test_rank3_activations(self, nprng):
        # transformer-shaped [B, L, D] activations through the pipe
        mesh = make_mesh({"pp": 4})
        params = _stacked_params(nprng, 4, 8)
        x = nprng.normal(size=(8, 5, 8)).astype(np.float32)
        out = pipeline_apply(_stage_fn, params, x, n_micro=2, mesh=mesh)
        ref = pipeline_reference(_stage_fn, params, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestMoEDispatch:
    """All-to-all (capacity) dispatch vs the masked oracle
    (moe.py::moe_dispatch_apply — the Switch-Transformer data path)."""

    def test_generous_capacity_matches_oracle(self, nprng):
        from tensorframes_tpu.parallel.moe import moe_dispatch_apply

        mesh = make_mesh({"ep": 4})
        params = init_moe(0, d_model=16, d_ff=32, n_experts=8)
        x = jnp.asarray(nprng.normal(size=(2, 16, 16)).astype(np.float32))
        # capacity_factor >= n guarantees no destination ever overflows
        out = moe_dispatch_apply(
            params, x, mesh=mesh, capacity_factor=4.0
        )
        ref = moe_ffn(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_dropped_tokens_are_zero(self, nprng):
        from tensorframes_tpu.parallel.moe import moe_dispatch_apply

        mesh = make_mesh({"ep": 4})
        # a router biased so every token picks expert 0 forces overflow
        params = init_moe(1, d_model=8, d_ff=16, n_experts=4)
        params = dict(params)
        params["router"] = np.zeros_like(params["router"])
        params["router"][:, 0] = 10.0
        x = jnp.asarray(nprng.normal(size=(1, 32, 8)).astype(np.float32))
        out = np.asarray(
            moe_dispatch_apply(params, x, mesh=mesh, capacity_factor=0.5)
        )
        ref = np.asarray(moe_ffn(params, x))
        # some rows match the oracle (processed), the rest are exactly zero
        zero_rows = np.all(out == 0.0, axis=-1)
        assert zero_rows.any(), "expected overflow drops"
        assert not zero_rows.all(), "expected some processed tokens"
        kept = ~zero_rows
        np.testing.assert_allclose(
            out[kept], ref[kept], rtol=2e-5, atol=2e-5
        )

    def test_eight_way(self, nprng):
        from tensorframes_tpu.parallel.moe import moe_dispatch_apply

        mesh = make_mesh({"ep": 8})
        params = init_moe(2, d_model=8, d_ff=16, n_experts=8)
        x = jnp.asarray(nprng.normal(size=(2, 32, 8)).astype(np.float32))
        out = moe_dispatch_apply(params, x, mesh=mesh, capacity_factor=8.0)
        ref = moe_ffn(params, x)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_bad_token_count_rejected(self, nprng):
        from tensorframes_tpu.parallel.moe import moe_dispatch_apply

        mesh = make_mesh({"ep": 4})
        params = init_moe(0, d_model=8, d_ff=16, n_experts=4)
        x = jnp.zeros((1, 6, 8), jnp.float32)  # 6 tokens on a 4-way axis
        with pytest.raises(ValueError, match="token count"):
            moe_dispatch_apply(params, x, mesh=mesh)


class TestLoadBalanceLoss:
    def test_uniform_routing_is_one(self, nprng):
        from tensorframes_tpu.parallel import init_moe, moe_load_balance_loss

        # router forced to route token i to expert i % E exactly
        params = init_moe(0, d_model=4, d_ff=8, n_experts=4)
        params = dict(params)
        x = np.eye(4, dtype=np.float32)[None].repeat(8, axis=0)  # [8,4,4]
        params["router"] = np.eye(4, dtype=np.float32) * 10.0
        loss = float(moe_load_balance_loss(params, jnp.asarray(x)))
        assert abs(loss - 1.0) < 0.35  # near-uniform -> near 1

    def test_collapsed_routing_is_large(self, nprng):
        from tensorframes_tpu.parallel import init_moe, moe_load_balance_loss

        params = init_moe(1, d_model=4, d_ff=8, n_experts=4)
        params = dict(params)
        params["router"] = np.zeros((4, 4), np.float32)
        params["router"][:, 0] = 10.0
        x = jnp.asarray(
            np.abs(nprng.normal(size=(2, 16, 4))).astype(np.float32)
        )
        loss = float(moe_load_balance_loss(params, x))
        assert loss > 2.0  # all mass on one expert -> ~E

    def test_differentiable(self, nprng):
        import jax
        from tensorframes_tpu.parallel import init_moe, moe_load_balance_loss

        params = init_moe(2, d_model=4, d_ff=8, n_experts=4)
        x = jnp.asarray(nprng.normal(size=(1, 8, 4)).astype(np.float32))

        g = jax.grad(
            lambda r: moe_load_balance_loss({**params, "router": r}, x)
        )(jnp.asarray(params["router"]))
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0


class TestPipelineDataParallel:
    """pp x dp in one program: microbatch rows sharded over dp while
    activations hop stages over pp."""

    def test_matches_sequential(self, nprng):
        mesh = make_mesh({"pp": 4, "dp": 2})
        params = _stacked_params(nprng, 4, 8)
        x = nprng.normal(size=(16, 8)).astype(np.float32)
        out = pipeline_apply(
            _stage_fn, params, x, n_micro=4, mesh=mesh, batch_axis="dp"
        )
        ref = pipeline_reference(_stage_fn, params, jnp.asarray(x))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_bad_batch_axis_rejected(self, nprng):
        mesh = make_mesh({"pp": 4})
        params = _stacked_params(nprng, 4, 8)
        with pytest.raises(ValueError, match="batch_axis"):
            pipeline_apply(
                _stage_fn, params, np.zeros((8, 8), np.float32),
                n_micro=2, mesh=mesh, batch_axis="dp",
            )

    def test_indivisible_microbatch_rejected(self, nprng):
        mesh = make_mesh({"pp": 4, "dp": 2})
        params = _stacked_params(nprng, 4, 8)
        with pytest.raises(ValueError, match="microbatch size"):
            pipeline_apply(
                _stage_fn, params, np.zeros((6, 8), np.float32),
                n_micro=2, mesh=mesh, batch_axis="dp",  # mb=3, dp=2
            )

    def test_batch_axis_equal_pipe_axis_rejected(self, nprng):
        mesh = make_mesh({"pp": 4, "dp": 2})
        params = _stacked_params(nprng, 4, 8)
        with pytest.raises(ValueError, match="must differ"):
            pipeline_apply(
                _stage_fn, params, np.zeros((8, 8), np.float32),
                n_micro=2, mesh=mesh, batch_axis="pp",
            )


class TestTopKRouting:
    def test_top2_matches_manual_oracle(self, nprng):
        import jax
        from tensorframes_tpu.parallel import init_moe, moe_ffn

        params = init_moe(0, d_model=8, d_ff=16, n_experts=4)
        x = jnp.asarray(nprng.normal(size=(2, 6, 8)).astype(np.float32))
        out = np.asarray(moe_ffn(params, x, k=2))

        # manual: renormalized top-2 gate-weighted expert outputs
        probs = np.asarray(jax.nn.softmax(x @ params["router"], axis=-1))
        want = np.zeros_like(np.asarray(x))
        order = np.argsort(-probs, axis=-1)
        for b in range(2):
            for t in range(6):
                ids = order[b, t, :2]
                g = probs[b, t, ids]
                g = g / g.sum()
                acc = np.zeros(8, np.float32)
                for gi, e in zip(g, ids):
                    h = np.asarray(jax.nn.gelu(
                        np.asarray(x)[b, t] @ params["w_up"][e] + params["b_up"][e]
                    ))
                    y = h @ params["w_down"][e] + params["b_down"][e]
                    acc += gi * y
                want[b, t] = acc
        np.testing.assert_allclose(out, want, rtol=2e-4, atol=2e-4)

    def test_top2_sharded_matches_oracle(self, nprng):
        from tensorframes_tpu.parallel import init_moe, moe_apply, moe_ffn

        mesh = make_mesh({"ep": 4})
        params = init_moe(1, d_model=8, d_ff=16, n_experts=8)
        x = jnp.asarray(nprng.normal(size=(2, 12, 8)).astype(np.float32))
        out = moe_apply(params, x, mesh=mesh, k=2)
        ref = moe_ffn(params, x, k=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_k1_unchanged(self, nprng):
        from tensorframes_tpu.parallel import init_moe, moe_ffn

        params = init_moe(2, d_model=8, d_ff=16, n_experts=4)
        x = jnp.asarray(nprng.normal(size=(1, 8, 8)).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(moe_ffn(params, x)),
            np.asarray(moe_ffn(params, x, k=1)),
        )

    def test_bad_k_rejected(self, nprng):
        from tensorframes_tpu.parallel import init_moe, moe_apply

        mesh = make_mesh({"ep": 4})
        params = init_moe(0, d_model=8, d_ff=16, n_experts=4)
        x = jnp.zeros((1, 4, 8), jnp.float32)
        with pytest.raises(ValueError, match="k="):
            moe_apply(params, x, mesh=mesh, k=5)


class TestPipelineTraining:
    """Backward through the pipeline: grads vs the sequential oracle, both
    schedules, pp alone and composed with dp (VERDICT r2 #3)."""

    @staticmethod
    def _setup(nprng, B):
        n, d = 4, 6
        stages = {
            "w": nprng.normal(0, 0.3, (n, d, d)).astype(np.float32),
            "b": nprng.normal(0, 0.1, (n, d)).astype(np.float32),
        }
        extra = {"wout": nprng.normal(0, 0.3, (d, 3)).astype(np.float32)}
        x = nprng.normal(size=(B, d)).astype(np.float32)
        tgt = nprng.normal(size=(B, 3)).astype(np.float32)

        def stage_fn(p, h):
            return jnp.tanh(h @ p["w"] + p["b"])

        def loss_fn(e, y, t):
            return (((y @ e["wout"]) - t) ** 2).mean()

        return stages, extra, x, tgt, stage_fn, loss_fn

    def _oracle(self, stages, extra, x, tgt, stage_fn, loss_fn, n_micro):
        from tensorframes_tpu.parallel.pipeline import pipeline_reference

        def total(stages, extra, x):
            d = x.shape[-1]
            mb = x.shape[0] // n_micro
            xm = x.reshape(n_micro, mb, d)
            tm = tgt.reshape(n_micro, mb, tgt.shape[-1])
            ls = [
                loss_fn(
                    extra, pipeline_reference(stage_fn, stages, xm[i]), tm[i]
                )
                for i in range(n_micro)
            ]
            return jnp.mean(jnp.asarray(ls))

        return jax.value_and_grad(total, argnums=(0, 1, 2))(
            stages, extra, x
        )

    def test_grad_through_pipeline_apply_matches_oracle(self, nprng):
        from tensorframes_tpu.parallel.pipeline import pipeline_apply

        stages, extra, x, tgt, stage_fn, loss_fn = self._setup(nprng, 8)
        mesh = make_mesh({"pp": 4})
        ol, og = self._oracle(
            stages, extra, x, tgt, stage_fn, loss_fn, n_micro=4
        )

        def papply_loss(stages, extra, x):
            y = pipeline_apply(stage_fn, stages, x, n_micro=4, mesh=mesh)
            return loss_fn(extra, y, tgt)

        gl, gg = jax.value_and_grad(papply_loss, argnums=(0, 1, 2))(
            stages, extra, x
        )
        np.testing.assert_allclose(float(gl), float(ol), rtol=1e-5)
        for a, b in zip(jax.tree.leaves(gg), jax.tree.leaves(og)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5
            )

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    @pytest.mark.parametrize("dp", [1, 2])
    def test_train_step_matches_oracle(self, nprng, schedule, dp):
        from tensorframes_tpu.parallel.pipeline import pipeline_train_step

        B = 8 * dp
        stages, extra, x, tgt, stage_fn, loss_fn = self._setup(nprng, B)
        mesh = (
            make_mesh({"pp": 4, "dp": 2}) if dp == 2 else make_mesh({"pp": 4})
        )
        ol, og = self._oracle(
            stages, extra, x, tgt, stage_fn, loss_fn, n_micro=4
        )
        loss, gs, ge, dx = pipeline_train_step(
            stage_fn,
            loss_fn,
            stages,
            extra,
            x,
            tgt,
            n_micro=4,
            mesh=mesh,
            batch_axis="dp" if dp == 2 else None,
            schedule=schedule,
        )
        np.testing.assert_allclose(float(loss), float(ol), rtol=1e-5)
        for a, b in zip(
            jax.tree.leaves((gs, ge)), jax.tree.leaves((og[0], og[1]))
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=3e-4, atol=3e-5
            )
        np.testing.assert_allclose(
            np.asarray(dx),
            np.asarray(og[2]).reshape(x.shape),
            rtol=3e-4,
            atol=3e-5,
        )

    def test_unknown_schedule_rejected(self, nprng):
        from tensorframes_tpu.parallel.pipeline import pipeline_train_step

        stages, extra, x, tgt, stage_fn, loss_fn = self._setup(nprng, 8)
        with pytest.raises(ValueError, match="schedule"):
            pipeline_train_step(
                stage_fn, loss_fn, stages, extra, x, tgt, n_micro=4,
                mesh=make_mesh({"pp": 4}), schedule="interleaved",
            )


class TestFitPipelined:
    """TransformerLM.fit_pipelined: full-model training (embedding outside
    the pipeline, loss head fused into the last stage) must walk the SAME
    trajectory as single-device fit."""

    @pytest.mark.parametrize("schedule", ["gpipe", "1f1b"])
    def test_losses_match_single_device_fit(self, nprng, schedule):
        from tensorframes_tpu.models import TransformerLM

        toks = nprng.integers(0, 50, size=(16, 17)).astype(np.int32)
        kw = dict(vocab=50, d_model=16, n_heads=2, n_layers=4, max_len=32)
        oracle = TransformerLM.init(3, **kw)
        o_losses = oracle.fit(toks, steps=3, lr=0.1)
        m = TransformerLM.init(3, **kw)
        losses = m.fit_pipelined(
            toks, make_mesh({"pp": 4, "dp": 2}), steps=3, lr=0.1,
            n_micro=4, schedule=schedule,
        )
        np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)
        assert losses[-1] < losses[0]

    def test_grad_accum_same_trajectory(self, nprng):
        from tensorframes_tpu.models import TransformerLM

        toks = nprng.integers(0, 50, size=(16, 17)).astype(np.int32)
        kw = dict(vocab=50, d_model=16, n_heads=2, n_layers=4, max_len=32)
        oracle = TransformerLM.init(3, **kw)
        o_losses = oracle.fit(toks, steps=3, lr=0.1)
        m = TransformerLM.init(3, **kw)
        losses = m.fit_pipelined(
            toks, make_mesh({"pp": 4}), steps=3, lr=0.1, n_micro=2,
            schedule="1f1b", grad_accum=2,
        )
        np.testing.assert_allclose(losses, o_losses, rtol=2e-4, atol=2e-5)

    def test_moe_blocks_rejected(self, nprng):
        from tensorframes_tpu.models import TransformerLM

        m = TransformerLM.init(
            0, vocab=20, d_model=8, n_heads=2, n_layers=4, moe_experts=4
        )
        toks = nprng.integers(0, 20, size=(8, 9)).astype(np.int32)
        with pytest.raises(ValueError, match="dense blocks"):
            m.fit_pipelined(toks, make_mesh({"pp": 4}), steps=1)

    def test_wrong_stage_count_rejected(self, nprng):
        from tensorframes_tpu.models import TransformerLM

        m = TransformerLM.init(0, vocab=20, d_model=8, n_heads=2, n_layers=2)
        toks = nprng.integers(0, 20, size=(8, 9)).astype(np.int32)
        with pytest.raises(ValueError, match="pp=4"):
            m.fit_pipelined(toks, make_mesh({"pp": 4}), steps=1)


class TestMoETraining:
    """Grads through BOTH expert data paths vs the dense oracle, and
    routed-LM training on the ep mesh (VERDICT r2 #4)."""

    def _grad_setup(self, nprng, n_experts=8):
        from tensorframes_tpu.parallel import init_moe

        params = init_moe(0, d_model=8, d_ff=16, n_experts=n_experts)
        x = jnp.asarray(nprng.normal(size=(2, 8, 8)).astype(np.float32))
        return params, x

    @pytest.mark.parametrize("k", [1, 2])
    def test_moe_apply_grads_match_dense_oracle(self, nprng, k):
        from tensorframes_tpu.parallel import init_moe, moe_apply, moe_ffn

        params, x = self._grad_setup(nprng)
        mesh = make_mesh({"ep": 4})

        def loss_sharded(p, x):
            return (moe_apply(p, x, mesh=mesh, k=k) ** 2).sum()

        def loss_dense(p, x):
            return (moe_ffn(p, x, k=k) ** 2).sum()

        gs = jax.grad(loss_sharded, argnums=(0, 1))(params, x)
        gd = jax.grad(loss_dense, argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree.leaves(gs), jax.tree.leaves(gd)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    @pytest.mark.parametrize("k", [1, 2])
    def test_dispatch_grads_match_dense_oracle(self, nprng, k):
        from tensorframes_tpu.parallel import (
            init_moe,
            moe_dispatch_apply,
            moe_ffn,
        )

        params, x = self._grad_setup(nprng)
        mesh = make_mesh({"ep": 4})

        # generous capacity: nothing drops, so grads must match exactly
        def loss_dispatch(p, x):
            return (
                moe_dispatch_apply(
                    p, x, mesh=mesh, capacity_factor=16.0, k=k
                )
                ** 2
            ).sum()

        def loss_dense(p, x):
            return (moe_ffn(p, x, k=k) ** 2).sum()

        gs = jax.grad(loss_dispatch, argnums=(0, 1))(params, x)
        gd = jax.grad(loss_dense, argnums=(0, 1))(params, x)
        for a, b in zip(jax.tree.leaves(gs), jax.tree.leaves(gd)):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
            )

    @pytest.mark.parametrize("k", [2, 3])
    def test_topk_dispatch_matches_oracle(self, nprng, k):
        from tensorframes_tpu.parallel import (
            init_moe,
            moe_dispatch_apply,
            moe_ffn,
        )

        params, x = self._grad_setup(nprng)
        mesh = make_mesh({"ep": 4})
        got = moe_dispatch_apply(
            params, x, mesh=mesh, capacity_factor=16.0, k=k
        )
        want = moe_ffn(params, x, k=k)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
        )

    def test_per_expert_capacity_isolates_experts(self, nprng):
        # Discriminating setup for PER-EXPERT capacity (the Switch
        # convention) vs a shared per-(src, dst-chip) buffer: from ONE
        # source chip, route token 0 -> expert 0 and token 1 -> expert 1
        # (different experts, SAME destination chip), with capacity 1 per
        # expert. Per-expert buffers keep both; a shared per-chip buffer
        # of 1 slot would evict token 1. Token 2 overflows expert 0's
        # buffer and must drop to zero.
        from tensorframes_tpu.parallel import init_moe, moe_dispatch_apply
        from tensorframes_tpu.parallel.moe import moe_ffn

        n_experts = 4
        params = init_moe(1, d_model=4, d_ff=8, n_experts=n_experts)
        mesh = make_mesh({"ep": 2})  # chip 0: experts {0,1}; chip 1: {2,3}
        # router: feature i -> expert i, deterministic
        params["router"] = (20.0 * np.eye(4)).astype(np.float32)
        x = np.zeros((1, 8, 4), dtype=np.float32)
        # source chip 0 holds tokens 0..3 (t_local = 4)
        x[0, 0, 0] = 1.0  # -> expert 0 (dst chip 0)
        x[0, 1, 1] = 1.0  # -> expert 1 (dst chip 0, own buffer: survives)
        x[0, 2, 0] = 1.0  # -> expert 0 again (overflows capacity 1)
        x[0, 3, 2] = 1.0  # -> expert 2 (dst chip 1)
        # source chip 1: all to expert 3; only the first fits
        for i in range(4, 8):
            x[0, i, 3] = 1.0
        # cf=1.0, t_local=4, E=4 -> capacity 1 per (source, expert)
        out = moe_dispatch_apply(
            params, jnp.asarray(x), mesh=mesh, capacity_factor=1.0, k=1
        )
        dense = moe_ffn(params, jnp.asarray(x), k=1)
        out, dense = np.asarray(out), np.asarray(dense)
        for kept in (0, 1, 3, 4):
            np.testing.assert_allclose(
                out[0, kept], dense[0, kept], rtol=1e-5,
                err_msg=f"token {kept} should have been processed",
            )
        for dropped in (2, 5, 6, 7):
            np.testing.assert_allclose(
                out[0, dropped], 0.0, atol=1e-7,
                err_msg=f"token {dropped} should have been dropped",
            )

    def test_aux_loss_reflects_topk_assignment(self, nprng):
        from tensorframes_tpu.parallel import init_moe
        from tensorframes_tpu.parallel.moe import moe_load_balance_loss

        # router that always picks experts {0, 1} as top-2
        n_experts = 4
        params = init_moe(0, d_model=4, d_ff=8, n_experts=n_experts)
        router = np.zeros((4, n_experts), dtype=np.float32)
        router[:, 0] = 5.0
        router[:, 1] = 4.0
        params["router"] = router
        x = jnp.asarray(nprng.normal(size=(1, 16, 4)).astype(np.float32))
        l1 = float(moe_load_balance_loss(params, x, k=1))
        l2 = float(moe_load_balance_loss(params, x, k=2))
        # top-1 sees all mass on expert 0 (f = [1,0,0,0]); top-2 splits
        # slots between experts 0 and 1 (f = [.5,.5,0,0]) — the aux loss
        # must see the difference
        assert l2 < l1

    @pytest.mark.parametrize("impl", ["masked", "dispatch"])
    def test_routed_lm_trains_on_ep_mesh(self, nprng, impl):
        from tensorframes_tpu.models import TransformerLM

        toks = nprng.integers(0, 30, size=(4, 9)).astype(np.int32)
        m = TransformerLM.init(
            0, vocab=30, d_model=8, n_heads=2, n_layers=2, max_len=16,
            moe_experts=8,
        )
        losses = m.fit(
            toks, steps=6, lr=0.3, mesh=make_mesh({"ep": 4}),
            moe_aux_weight=1e-2, moe_top_k=2, moe_impl=impl,
        )
        assert losses[-1] < losses[0], losses
        assert all(np.isfinite(losses))
