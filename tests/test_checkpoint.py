"""Checkpoint/resume subsystem (Orbax-backed).

The reference has no trainable-state checkpointing at all (SURVEY §5 —
model state ships as frozen graph constants); on TPU this is a first-class
subsystem, so it gets first-class tests: pytree round-trips, sharded-params
round-trips over the 8-device mesh with shardings preserved, manager
retention, and trainer resume.
"""

import numpy as np
import pytest

pytest.importorskip(
    "orbax.checkpoint", reason="checkpoint subsystem is an optional extra"
)

import tensorframes_tpu.parallel as par
from tensorframes_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)

from _gates import requires_shard_map


def test_pytree_round_trip(tmp_path):
    tree = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "meta": {"b": np.ones(4, dtype=np.float64)},
    }
    save_checkpoint(str(tmp_path / "ck"), tree)
    out = restore_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(out["w"], tree["w"])
    np.testing.assert_array_equal(out["meta"]["b"], tree["meta"]["b"])


def test_sharded_params_round_trip_preserves_sharding(tmp_path):
    import jax

    trainer = par.ShardedSGDTrainer([8, 4, 2])
    params = trainer.init_params(0)
    save_checkpoint(str(tmp_path / "ck"), params)
    restored = restore_checkpoint(str(tmp_path / "ck"), template=params)
    for orig, back in zip(
        jax.tree.leaves(params), jax.tree.leaves(restored)
    ):
        np.testing.assert_allclose(np.asarray(orig), np.asarray(back))
        assert back.sharding.is_equivalent_to(orig.sharding, orig.ndim), (
            orig.sharding,
            back.sharding,
        )


def test_manager_retention_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for step in (1, 2, 3):
        mgr.save(step, {"v": np.full(2, float(step))})
    assert mgr.latest_step() == 3
    step, tree = mgr.restore_latest()
    assert step == 3
    np.testing.assert_array_equal(tree["v"], [3.0, 3.0])
    mgr.close()
    # retention: only the last two steps remain on disk
    kept = sorted(
        int(p.name) for p in (tmp_path / "mgr").iterdir() if p.name.isdigit()
    )
    assert kept == [2, 3]


def test_trainer_fit_resume(tmp_path):
    import jax

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    ckdir = str(tmp_path / "train")

    trainer = par.ShardedSGDTrainer([8, 4, 2], lr=0.1)
    params_a, losses_a = trainer.fit(x, y, steps=4, resume=ckdir)
    assert len(losses_a) == 4

    # a fresh trainer resuming from the same dir starts at step 4: no new
    # steps to run, and it returns the checkpointed params
    trainer_b = par.ShardedSGDTrainer([8, 4, 2], lr=0.1)
    params_b, losses_b = trainer_b.fit(x, y, steps=4, resume=ckdir)
    assert losses_b == []
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # asking for more steps continues from the restored state
    params_c, losses_c = trainer_b.fit(x, y, steps=6, resume=ckdir)
    assert len(losses_c) == 2
    assert all(np.isfinite(l) for l in losses_c)


def _tiny_lm(seed):
    from tensorframes_tpu.models.transformer import TransformerLM

    return TransformerLM.init(
        seed, vocab=50, d_model=32, n_layers=2, n_heads=2, d_ff=64,
        max_len=32,
    )


def _toks():
    return (
        np.random.default_rng(2)
        .integers(0, 50, size=(8, 16))
        .astype(np.int32)
    )


def test_transformer_fit_resume_matches_uninterrupted(tmp_path):
    """Interrupted-then-resumed transformer SGD reproduces the
    uninterrupted loss trajectory exactly (same compiled step, restored
    params) — covers the resume path through ``_sgd_loop``."""
    toks = _toks()
    full = _tiny_lm(0).fit(toks, steps=6, lr=0.05)
    ckdir = str(tmp_path / "lm")
    first = _tiny_lm(0).fit(
        toks, steps=3, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    # a FRESH model object resuming = a restarted process
    rest = _tiny_lm(0).fit(
        toks, steps=6, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    np.testing.assert_allclose(first + rest, full, rtol=1e-5, atol=1e-6)


@requires_shard_map
def test_fit_pipelined_resume_matches_uninterrupted(tmp_path):
    """Resume through the PIPELINE layout: the restored stacked slab must
    be re-pinned to the pp axis (restored leaves come back committed to
    one device) and the trajectory must match the uninterrupted run."""
    from tensorframes_tpu.parallel import make_mesh

    toks = _toks()
    mesh = make_mesh({"pp": 2})
    full = _tiny_lm(1).fit_pipelined(toks, mesh, steps=4, lr=0.05, n_micro=2)
    ckdir = str(tmp_path / "pipe")
    first = _tiny_lm(1).fit_pipelined(
        toks, mesh, steps=2, lr=0.05, n_micro=2,
        resume=ckdir, checkpoint_every=1,
    )
    rest = _tiny_lm(1).fit_pipelined(
        toks, mesh, steps=4, lr=0.05, n_micro=2,
        resume=ckdir, checkpoint_every=1,
    )
    np.testing.assert_allclose(first + rest, full, rtol=1e-5, atol=1e-6)


def test_checkpoint_every_requires_resume_dir():
    from tensorframes_tpu.utils.checkpoint import run_checkpointed_loop

    with pytest.raises(ValueError, match="checkpoint_every"):
        run_checkpointed_loop(
            lambda s: (s, 0.0), {}, 2, checkpoint_every=1
        )


def test_fit_tp_resume_matches_uninterrupted(tmp_path):
    """Resume through the Megatron plan: restored committed leaves must be
    re-pinned to the dp x tp shardings before the jitted step."""
    from tensorframes_tpu.parallel import make_mesh

    toks = _toks()
    mesh = make_mesh({"dp": 4, "tp": 2})
    full = _tiny_lm(0).fit_tp(toks, mesh, steps=4, lr=0.05)
    ckdir = str(tmp_path / "tp")
    first = _tiny_lm(0).fit_tp(
        toks, mesh, steps=2, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    rest = _tiny_lm(0).fit_tp(
        toks, mesh, steps=4, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    np.testing.assert_allclose(first + rest, full, rtol=1e-5, atol=1e-6)


@requires_shard_map
def test_fit_sharded_resume_matches_uninterrupted(tmp_path):
    """Resume through the sequence-parallel (ring) plan."""
    from tensorframes_tpu.parallel import make_mesh

    toks = (
        np.random.default_rng(3)
        .integers(0, 50, size=(4, 17))
        .astype(np.int32)
    )
    mesh = make_mesh({"dp": 2, "sp": 4})
    full = _tiny_lm(1).fit_sharded(toks, mesh, steps=4, lr=0.05)
    ckdir = str(tmp_path / "sp")
    first = _tiny_lm(1).fit_sharded(
        toks, mesh, steps=2, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    rest = _tiny_lm(1).fit_sharded(
        toks, mesh, steps=4, lr=0.05, resume=ckdir, checkpoint_every=1
    )
    np.testing.assert_allclose(first + rest, full, rtol=1e-5, atol=1e-6)
