"""Checkpoint/resume subsystem (Orbax-backed).

The reference has no trainable-state checkpointing at all (SURVEY §5 —
model state ships as frozen graph constants); on TPU this is a first-class
subsystem, so it gets first-class tests: pytree round-trips, sharded-params
round-trips over the 8-device mesh with shardings preserved, manager
retention, and trainer resume.
"""

import numpy as np
import pytest

pytest.importorskip(
    "orbax.checkpoint", reason="checkpoint subsystem is an optional extra"
)

import tensorframes_tpu.parallel as par
from tensorframes_tpu.utils.checkpoint import (
    CheckpointManager,
    restore_checkpoint,
    save_checkpoint,
)


def test_pytree_round_trip(tmp_path):
    tree = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "meta": {"b": np.ones(4, dtype=np.float64)},
    }
    save_checkpoint(str(tmp_path / "ck"), tree)
    out = restore_checkpoint(str(tmp_path / "ck"))
    np.testing.assert_array_equal(out["w"], tree["w"])
    np.testing.assert_array_equal(out["meta"]["b"], tree["meta"]["b"])


def test_sharded_params_round_trip_preserves_sharding(tmp_path):
    import jax

    trainer = par.ShardedSGDTrainer([8, 4, 2])
    params = trainer.init_params(0)
    save_checkpoint(str(tmp_path / "ck"), params)
    restored = restore_checkpoint(str(tmp_path / "ck"), template=params)
    for orig, back in zip(
        jax.tree.leaves(params), jax.tree.leaves(restored)
    ):
        np.testing.assert_allclose(np.asarray(orig), np.asarray(back))
        assert back.sharding.is_equivalent_to(orig.sharding, orig.ndim), (
            orig.sharding,
            back.sharding,
        )


def test_manager_retention_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "mgr"), max_to_keep=2)
    for step in (1, 2, 3):
        mgr.save(step, {"v": np.full(2, float(step))})
    assert mgr.latest_step() == 3
    step, tree = mgr.restore_latest()
    assert step == 3
    np.testing.assert_array_equal(tree["v"], [3.0, 3.0])
    mgr.close()
    # retention: only the last two steps remain on disk
    kept = sorted(
        int(p.name) for p in (tmp_path / "mgr").iterdir() if p.name.isdigit()
    )
    assert kept == [2, 3]


def test_trainer_fit_resume(tmp_path):
    import jax

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 8)).astype(np.float32)
    y = rng.integers(0, 2, 32).astype(np.int32)
    ckdir = str(tmp_path / "train")

    trainer = par.ShardedSGDTrainer([8, 4, 2], lr=0.1)
    params_a, losses_a = trainer.fit(x, y, steps=4, resume=ckdir)
    assert len(losses_a) == 4

    # a fresh trainer resuming from the same dir starts at step 4: no new
    # steps to run, and it returns the checkpointed params
    trainer_b = par.ShardedSGDTrainer([8, 4, 2], lr=0.1)
    params_b, losses_b = trainer_b.fit(x, y, steps=4, resume=ckdir)
    assert losses_b == []
    for a, b in zip(jax.tree.leaves(params_a), jax.tree.leaves(params_b)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))

    # asking for more steps continues from the restored state
    params_c, losses_c = trainer_b.fit(x, y, steps=6, resume=ckdir)
    assert len(losses_c) == 2
    assert all(np.isfinite(l) for l in losses_c)
