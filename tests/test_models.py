"""Model-zoo tests: k-means (reference snippet parity) and MLP scoring."""

import numpy as np
import pytest

import tensorframes_tpu as tft
import tensorframes_tpu.parallel as par
from tensorframes_tpu.models import (
    MLPClassifier,
    assign_clusters,
    kmeans,
)

from _gates import requires_shard_map


def blob_data(n=300, d=5, k=3, seed=7):
    rng = np.random.default_rng(seed)
    centers = rng.normal(0, 20, (k, d))
    labels = rng.integers(0, k, n)
    data = centers[labels] + rng.normal(0, 0.5, (n, d))
    return data.astype(np.float32), centers, labels


class TestKMeans:
    def test_recovers_blobs(self):
        data, centers, _ = blob_data()
        df = tft.TensorFrame.from_columns(
            {"features": data}, num_partitions=3
        ).analyze()
        centroids, history = kmeans(df, "features", k=3, num_iters=8, seed=1)
        assert centroids.shape == (3, 5)
        # every true center has a recovered centroid nearby
        for c in centers:
            assert np.min(np.linalg.norm(centroids - c, axis=1)) < 1.0
        assert history[-1] <= history[0]

    def test_assign_clusters(self):
        data, _, _ = blob_data(n=50)
        df = tft.TensorFrame.from_columns({"features": data}).analyze()
        centroids, _ = kmeans(df, "features", k=3, num_iters=5, seed=1)
        out = assign_clusters(df, "features", centroids)
        rows = out.collect()
        assert set(out.columns) >= {"closest_centroid", "distance", "features"}
        assert all(0 <= r.closest_centroid < 3 for r in rows)
        assert all(r.distance >= 0 for r in rows)

    @requires_shard_map
    def test_distributed_matches_local(self):
        data, _, _ = blob_data(n=160)
        df = tft.TensorFrame.from_columns({"features": data}).analyze()
        local_c, _ = kmeans(df, "features", k=3, num_iters=4, seed=2)
        dist_c, _ = kmeans(
            df,
            "features",
            k=3,
            num_iters=4,
            seed=2,
            distributed=True,
            mesh=par.make_mesh(),
        )
        np.testing.assert_allclose(local_c, dist_c, rtol=1e-4, atol=1e-4)


class TestMLPScoring:
    def test_probabilities_column(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(10, 6)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"f": x}).analyze()
        clf = MLPClassifier.init(0, [6, 4, 3])
        out = clf.score_frame(df, "f", probabilities_col="probs")
        rows = out.collect()
        np.testing.assert_allclose(
            [float(np.sum(r.probs)) for r in rows], np.ones(10), rtol=1e-5
        )

    def test_scoring_reuses_graph(self):
        rng = np.random.default_rng(0)
        x = rng.normal(size=(4, 6)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"f": x}).analyze()
        clf = MLPClassifier.init(0, [6, 2])
        clf.score_frame(df, "f").cache()
        g1 = clf._graph_cache
        clf.score_frame(df, "f").cache()
        assert clf._graph_cache is g1 and len(g1) == 1
