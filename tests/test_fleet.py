"""Serving fleet: replicated engines, health-gated router, request replay.

The correctness bar is inherited from test_serve.py and raised one tier:
a stream decoded through the FLEET — placed on some replica, possibly
killed mid-stream and replayed on another — must stay BYTE-IDENTICAL to
the same request decoded alone through ``transformer_generate``, greedy
and seeded sampling alike, and failover must add zero compiled programs
(every replica stays at <= 2 for its lifetime).
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import (
    EngineUnhealthyError,
    Fleet,
    GenerationEngine,
    QueueFullError,
)
from tensorframes_tpu.utils import chaos, get_config, set_config
from tensorframes_tpu.utils.chaos import ChaosFault
from tensorframes_tpu.utils.failures import DeadlineExceededError

pytestmark = pytest.mark.fleet

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _prompts(rng, lens):
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist() for n in lens
    ]


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _fleet(lm, n=2, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("watchdog_interval_s", 0.02)
    return Fleet(lm, replicas=n, **kw)


def _wait_for(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


# ---------------------------------------------------------------------------


class TestPlacement:
    def test_least_loaded_prefers_free_pages_then_queue(self, lm):
        fleet = _fleet(lm, 2)
        r0, r1 = fleet._replicas
        # equal load: deterministic name tiebreak
        assert fleet._candidates()[0] is r0
        # r0 loses pages -> r1 leads
        held = r0.engine.pool.alloc(3)
        assert fleet._candidates()[0] is r1
        r0.engine.pool.free(held)
        # pages equal again, but r0's queue is deeper -> r1 leads
        r0.engine.submit([1, 2], 2)
        assert fleet._candidates()[0] is r1

    def test_session_affinity_sticks_until_fenced(self, lm):
        fleet = _fleet(lm, 2, auto_restart=False)
        h = fleet.submit([1, 2, 3], 2, session="chat-1")
        first = fleet._inflight[h.request_id].replica
        # the affine replica now carries MORE load, yet the session
        # sticks to it (KV locality beats balance while it is healthy)
        h2 = fleet.submit([1, 2, 3], 2, session="chat-1")
        assert fleet._inflight[h2.request_id].replica is first
        # a session-free request balances away from the loaded replica
        h3 = fleet.submit([1, 2, 3], 2)
        assert fleet._inflight[h3.request_id].replica is not first
        # fencing the affine replica remaps the session
        fleet._fence(first, ChaosFault("drill"))
        h4 = fleet.submit([1, 2, 3], 2, session="chat-1")
        assert fleet._inflight[h4.request_id].replica is not first

    def test_all_fenced_sheds_with_engine_unhealthy(self, lm):
        fleet = _fleet(lm, 2, auto_restart=False)
        for rep in fleet._replicas:
            fleet._fence(rep, ChaosFault("drill"))
        with pytest.raises(EngineUnhealthyError):
            fleet.submit([1, 2], 2)

    def test_all_queues_full_raises_queue_full(self, lm):
        fleet = _fleet(lm, 2, queue_capacity=0)
        with pytest.raises(QueueFullError):
            fleet.submit([1, 2], 2, block=False)
        t0 = time.monotonic()
        with pytest.raises(QueueFullError):
            fleet.submit([1, 2], 2, timeout=0.05)
        assert time.monotonic() - t0 < 5

    def test_infeasible_request_rejected_everywhere(self, lm):
        fleet = _fleet(lm, 2, max_seq_len=16)
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            fleet.submit([1] * 10, 10)

    def test_nonpositive_deadline_is_a_value_error(self, lm):
        """Same client-error classification as the single engine (HTTP
        400), not a 504-shaped DeadlineExceededError from placement."""
        fleet = _fleet(lm, 2)
        for bad in (0, -1):
            with pytest.raises(ValueError, match="deadline"):
                fleet.submit([1, 2], 2, deadline=bad)


# ---------------------------------------------------------------------------


class TestFleetServing:
    def test_streams_match_solo_greedy_and_sampled(self, lm):
        rng = np.random.default_rng(60)
        fleet = _fleet(lm, 2)
        prompts = _prompts(rng, (3, 5, 2, 7, 4, 6))
        with fleet:
            greedy = [fleet.submit(p, 6) for p in prompts[:3]]
            sampled = [
                fleet.submit(p, 6, temperature=0.8, top_p=0.9, seed=70 + i)
                for i, p in enumerate(prompts[3:])
            ]
            for p, h in zip(prompts[:3], greedy):
                np.testing.assert_array_equal(
                    h.result(timeout=60), _solo(lm, p, 6)
                )
            for i, (p, h) in enumerate(zip(prompts[3:], sampled)):
                np.testing.assert_array_equal(
                    h.result(timeout=60),
                    _solo(lm, p, 6, temperature=0.8, top_p=0.9, seed=70 + i),
                )
        assert all(n <= 2 for n in fleet.program_counts().values())

    def test_failover_mid_stream_is_byte_identical(self, lm, fast_retries):
        """The tentpole regression: kill the replica with active work
        mid-stream; every survivor replays on the other replica and the
        consumer streams stay byte-identical — greedy AND seeded
        sampling — with zero new compiled programs; the dead replica is
        restarted, probed, and re-admitted."""
        rng = np.random.default_rng(61)
        fleet = _fleet(lm, 2, max_seq_len=64)
        prompts = _prompts(rng, (3, 5, 2, 7))
        temps = [0.0, 0.8, 0.0, 0.9]
        seeds = [0, 81, 0, 83]
        replays0 = _counter_value("fleet.replays_total")
        failovers0 = _counter_value("fleet.failovers_total")
        with chaos.scoped("serve.decode_step=latency:ms=25"):
            with fleet:
                handles = [
                    fleet.submit(p, 20, temperature=t, top_p=0.9, seed=s)
                    for p, t, s in zip(prompts, temps, seeds)
                ]
                time.sleep(0.3)  # streams mid-flight (25 ms/step x 20)
                victim = next(
                    rep
                    for rep in fleet._replicas
                    if any(
                        s is not None for s in rep.engine.scheduler.slots
                    )
                )
                fleet._kill_replica(victim, ChaosFault("mid-stream kill"))
                outs = [h.result(timeout=120) for h in handles]
                for p, t, s, o in zip(prompts, temps, seeds, outs):
                    np.testing.assert_array_equal(
                        o,
                        _solo(
                            lm, p, 20, temperature=t, top_p=0.9, seed=s
                        ),
                    )
                _wait_for(
                    lambda: victim.state == "active",
                    what="restart + probe re-admission",
                )
        assert _counter_value("fleet.replays_total") > replays0
        assert _counter_value("fleet.failovers_total") > failovers0
        assert all(n <= 2 for n in fleet.program_counts().values())

    def test_chaos_site_kills_named_replica(self, lm, fast_retries):
        """``fleet.replica_fault.<name>`` kills exactly that replica on
        the watchdog's schedule; traffic continues on the survivor."""
        rng = np.random.default_rng(62)
        fleet = _fleet(lm, 2, auto_restart=False, max_seq_len=64)
        prompts = _prompts(rng, (4, 3, 5, 2))
        failovers0 = _counter_value("fleet.failovers_total")
        with chaos.scoped(
            "serve.decode_step=latency:ms=10;"
            "fleet.replica_fault.r1=fatal:every=5:times=1"
        ):
            with fleet:
                handles = [fleet.submit(p, 15) for p in prompts]
                _wait_for(
                    lambda: fleet.replica_state("r1") == "fenced",
                    what="chaos kill of r1",
                )
                assert fleet.replica_state("r0") == "active"
                for p, h in zip(prompts, handles):
                    np.testing.assert_array_equal(
                        h.result(timeout=120), _solo(lm, p, 15)
                    )
                # the fleet keeps serving on the survivor
                h = fleet.submit(prompts[0], 4)
                np.testing.assert_array_equal(
                    h.result(timeout=60), _solo(lm, prompts[0], 4)
                )
        assert _counter_value("fleet.failovers_total") > failovers0

    def test_deadline_is_terminal_not_replayed(self, lm):
        fleet = _fleet(lm, 2, max_seq_len=64)
        replays0 = _counter_value("fleet.replays_total")
        with chaos.scoped("serve.decode_step=latency:ms=30"):
            with fleet:
                h = fleet.submit([1, 2, 3], 40, deadline=0.15)
                with pytest.raises(DeadlineExceededError):
                    h.result(timeout=60)
        assert _counter_value("fleet.replays_total") == replays0

    def test_replay_cap_fails_instead_of_bouncing(self, lm):
        fleet = _fleet(lm, 2, max_replays=0, max_seq_len=64)
        with chaos.scoped("serve.decode_step=latency:ms=25"):
            with fleet:
                h = fleet.submit([1, 2, 3], 20)
                _wait_for(
                    lambda: fleet._inflight.get(h.request_id) is not None
                    and fleet._inflight[h.request_id].replica is not None,
                    what="placement",
                )
                time.sleep(0.1)
                rep = fleet._inflight[h.request_id].replica
                fleet._kill_replica(rep, ChaosFault("kill"))
                with pytest.raises(ChaosFault):
                    h.result(timeout=60)

    def test_replay_of_completed_stream_settles_success(self, lm):
        """A replica can die in the window between a stream's final
        emission and its clean close (the wedged drain path); replaying
        it would submit ``max_new_tokens=0`` (ValueError) or keep
        generating past EOS. The router must settle such records as
        SUCCESS — the client already has every byte."""
        fleet = _fleet(lm, 2)
        h = fleet.submit([1, 2, 3], 4)  # unstarted fleet: queued only
        rec = fleet._inflight[h.request_id]
        rec.handle._tokens.extend([5, 6, 7, 8])  # budget fully delivered
        assert fleet._replay(rec) is True
        assert h.done and h.error is None
        np.testing.assert_array_equal(h.result(timeout=1), [5, 6, 7, 8])
        assert h.request_id not in fleet._inflight
        # EOS variant: the engine-level default eos ended the stream
        fleet2 = _fleet(lm, 2, eos_id=9)
        h2 = fleet2.submit([1, 2], 6)
        rec2 = fleet2._inflight[h2.request_id]
        rec2.handle._tokens.extend([4, 9])
        assert fleet2._replay(rec2) is True
        assert h2.done and h2.error is None

    def test_all_fenced_forever_fails_fast_with_replica_error(self, lm):
        """The fail-fast rule, fleet edition: when no healthy replica
        appears within ``failover_timeout_s``, a parked survivor's
        handle fails with the replica's REAL error — a deadline-less
        consumer must never hang forever against a dead fleet."""
        fleet = _fleet(
            lm, 1, auto_restart=False, failover_timeout_s=0.2,
            max_seq_len=64,
        )
        with chaos.scoped("serve.decode_step=latency:ms=25"):
            with fleet:
                h = fleet.submit([1, 2, 3], 20)
                time.sleep(0.1)
                fleet._kill_replica(
                    fleet._replicas[0], ChaosFault("down for good")
                )
                t0 = time.monotonic()
                with pytest.raises(ChaosFault):
                    h.result(timeout=30)
                assert time.monotonic() - t0 < 10

    def test_stop_fails_inflight_handles(self, lm):
        fleet = _fleet(lm, 2, max_seq_len=64)
        with chaos.scoped("serve.decode_step=latency:ms=30"):
            fleet.start()
            h = fleet.submit([1, 2, 3], 40)
            time.sleep(0.1)
            fleet.stop()
        assert h.done and h.error is not None
        with pytest.raises(RuntimeError):
            h.result(timeout=1)


# ---------------------------------------------------------------------------


def _http(addr, req: bytes) -> bytes:
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=30) as c:
        c.sendall(req)
        out = b""
        while True:
            b = c.recv(65536)
            if not b:
                break
            out += b
    return out


def _post_generate(addr, spec) -> tuple:
    body = json.dumps(spec).encode()
    req = (
        b"POST /generate HTTP/1.1\r\nContent-Length: "
        + str(len(body)).encode()
        + b"\r\n\r\n"
        + body
    )
    resp = _http(addr, req)
    status = int(resp.split(b" ", 2)[1])
    payload = json.loads(resp.split(b"\r\n\r\n", 1)[1] or b"{}")
    return status, payload, resp


class TestFleetEndpoint:
    def test_generate_healthz_aggregate_and_fencing(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        rng = np.random.default_rng(63)
        fleet = _fleet(lm, 2, auto_restart=False)
        p = _prompts(rng, (4,))[0]
        with ScoringServer(engine=fleet) as addr:
            status, payload, _ = _post_generate(
                addr, {"prompt": p, "max_new_tokens": 6, "session": "u1"}
            )
            assert status == 200
            np.testing.assert_array_equal(payload["tokens"], _solo(lm, p, 6))
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 200
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["replicas_total"] == 2
            assert body["replicas_healthy"] == 2
            assert set(body["replicas"]) == {"r0", "r1"}
            assert body["replicas"]["r0"]["state"] == "active"

            # ONE replica fenced: healthz stays 200, generate keeps going
            fleet._fence(fleet._replicas[0], ChaosFault("drill"))
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 200
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["replicas_healthy"] == 1
            assert body["replicas"]["r0"]["state"] == "fenced"
            status, payload, _ = _post_generate(
                addr, {"prompt": p, "max_new_tokens": 6}
            )
            assert status == 200
            np.testing.assert_array_equal(payload["tokens"], _solo(lm, p, 6))

            # ALL replicas fenced: 503 + the adaptive Retry-After on both
            fleet._fence(fleet._replicas[1], ChaosFault("drill"))
            status, payload, resp = _post_generate(
                addr, {"prompt": p, "max_new_tokens": 6}
            )
            assert status == 503 and b"Retry-After:" in resp
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 503
            assert b"Retry-After:" in resp

    def test_malformed_sampling_params_are_400(self, lm):
        """REGRESSION: a non-numeric temperature/top_p/seed must answer
        400 like any other bad request — not crash the connection
        thread and drop the connection without a response."""
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with ScoringServer(engine=eng) as addr:
            for bad in (
                {"temperature": "hot"},
                {"top_p": []},
                {"seed": "x"},
                {"deadline_s": "soon"},
            ):
                status, payload, _ = _post_generate(
                    addr, {"prompt": [1, 2], "max_new_tokens": 2, **bad}
                )
                assert status == 400 and "error" in payload, bad

    def test_session_on_plain_engine_is_a_400(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with ScoringServer(engine=eng) as addr:
            status, payload, _ = _post_generate(
                addr,
                {"prompt": [1, 2], "max_new_tokens": 2, "session": "u1"},
            )
            assert status == 400


class TestHTTPRouting:
    """Satellite: unknown paths 404, wrong verbs 405 + Allow."""

    def test_unknown_path_is_404(self):
        from tensorframes_tpu.interop.serving import ScoringServer

        with ScoringServer(lambda x: {"y": x}) as addr:
            resp = _http(addr, b"GET /nope HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 404
            resp = _http(
                addr, b"POST /also/nope HTTP/1.1\r\nContent-Length: 0\r\n\r\n"
            )
            assert int(resp.split(b" ", 2)[1]) == 404

    def test_wrong_verb_is_405_with_allow(self):
        from tensorframes_tpu.interop.serving import ScoringServer

        with ScoringServer(lambda x: {"y": x}) as addr:
            resp = _http(addr, b"GET /generate HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 405
            assert b"Allow: POST" in resp
            resp = _http(
                addr, b"POST /metrics HTTP/1.1\r\nContent-Length: 0\r\n\r\n"
            )
            assert int(resp.split(b" ", 2)[1]) == 405
            assert b"Allow: GET" in resp
            resp = _http(
                addr, b"POST /healthz HTTP/1.1\r\nContent-Length: 0\r\n\r\n"
            )
            assert int(resp.split(b" ", 2)[1]) == 405
            assert b"Allow: GET" in resp
            # trailing slash normalizes to the same route
            resp = _http(addr, b"GET /metrics/ HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 200


class TestAdaptiveRetryAfter:
    """Satellite: Retry-After = queue depth x p50 inter-token latency,
    clamped to [1, 30]; 1 while no latency samples exist."""

    class _Stub:
        def __init__(self, depth):
            self._depth = depth

        def health(self):
            return {"queue_depth": self._depth}

    def _seed_itl(self, value, n=10):
        import tensorframes_tpu.serve.engine  # noqa: F401 — registers it

        hist = obs_metrics.registry().get("serve.inter_token_seconds")
        hist._reset()
        for _ in range(n):
            hist.observe(value)
        return hist

    def test_no_samples_falls_back_to_one(self):
        from tensorframes_tpu.interop.serving import _adaptive_retry_after

        hist = self._seed_itl(0.5, n=0)
        assert _adaptive_retry_after(self._Stub(50)) == "1"
        hist._reset()

    def test_scales_with_depth_and_latency_and_clamps(self):
        from tensorframes_tpu.interop.serving import _adaptive_retry_after

        hist = self._seed_itl(0.5)  # p50 bucket bound = 4^10 us = 1.048576 s
        try:
            assert _adaptive_retry_after(self._Stub(0)) == "1"  # floor
            assert _adaptive_retry_after(self._Stub(10)) == "11"
            assert _adaptive_retry_after(self._Stub(1000)) == "30"  # ceiling
            assert _adaptive_retry_after(None) == "1"
        finally:
            hist._reset()

    def test_fast_tokens_still_floor_at_one(self):
        from tensorframes_tpu.interop.serving import _adaptive_retry_after

        hist = self._seed_itl(1e-4)  # 100 us/token: depth 3 -> well under 1s
        try:
            assert _adaptive_retry_after(self._Stub(3)) == "1"
        finally:
            hist._reset()

    def test_histogram_quantile(self):
        hist = self._seed_itl(0.5)  # all samples in the 1.048576 s bucket
        try:
            assert hist.quantile(0.5) == pytest.approx(4.0 ** 10 * 1e-6)
            assert hist.quantile(1.0) == pytest.approx(4.0 ** 10 * 1e-6)
            hist.observe(1e9)  # +Inf tail reports the top bound
            assert hist.quantile(1.0) == hist.bounds[-1]
            with pytest.raises(ValueError):
                hist.quantile(1.5)
        finally:
            hist._reset()
        assert hist.quantile(0.5) is None  # no samples


# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestFleetSoak:
    def test_chaos_soak_replica_kill_under_staggered_traffic(
        self, lm, fast_retries
    ):
        """The acceptance soak: 16 staggered requests (greedy + seeded
        sampling) against 3 replicas while the chaos schedule kills one
        replica mid-stream and injects transient step faults (p=0.1).
        Every request completes within its deadline, every stream is
        byte-identical to its solo decode, ``fleet.failovers_total``
        advances, and no replica compiles more than its two step
        programs."""
        rng = np.random.default_rng(64)
        fleet = Fleet(
            lm,
            replicas=3,
            max_slots=4,
            page_size=4,
            max_seq_len=64,
            queue_capacity=32,
            watchdog_interval_s=0.02,
            probe_timeout_s=60,
        )
        plens = [int(rng.integers(1, 11)) for _ in range(16)]
        nnews = [int(rng.integers(4, 15)) for _ in range(16)]
        temps = [0.0 if i % 2 == 0 else 0.8 for i in range(16)]
        seeds = [90 + i for i in range(16)]
        prompts = _prompts(rng, plens)
        failovers0 = _counter_value("fleet.failovers_total")
        replays0 = _counter_value("fleet.replays_total")
        deadline = 120.0
        t0 = time.monotonic()
        handles = []
        with chaos.scoped(
            "seed=21;"
            "serve.decode_step=transient:p=0.1;"
            "serve.prefill=transient:p=0.1;"
            "serve.decode_step=latency:ms=10;"
            "fleet.replica_fault.r1=fatal:every=8:times=1"
        ):
            with fleet:
                waves = [
                    prompts[:5], prompts[5:9], prompts[9:13], prompts[13:]
                ]
                k = 0
                for wave in waves:
                    for p in wave:
                        handles.append(
                            fleet.submit(
                                p,
                                nnews[k],
                                temperature=temps[k],
                                top_p=0.9,
                                seed=seeds[k],
                                deadline=deadline,
                            )
                        )
                        k += 1
                    time.sleep(0.04)
                for i, h in enumerate(handles):
                    toks = h.result(timeout=deadline)
                    np.testing.assert_array_equal(
                        toks,
                        _solo(
                            lm,
                            prompts[i],
                            nnews[i],
                            temperature=temps[i],
                            top_p=0.9,
                            seed=seeds[i],
                        ),
                        err_msg=(
                            f"stream {i} diverged (plen={plens[i]}, "
                            f"n={nnews[i]}, temp={temps[i]})"
                        ),
                    )
        wall = time.monotonic() - t0
        assert wall < deadline  # nobody outlived the per-request budget
        assert _counter_value("fleet.failovers_total") > failovers0
        assert _counter_value("fleet.replays_total") > replays0
        counts = fleet.program_counts()
        assert all(n <= 2 for n in counts.values()), counts
