"""Device-resident result columns and the HBM-bound streaming fallback.

map_blocks results stay in HBM so chained ops never round-trip through the
host (the reference re-marshals rows through JNI on every Session.run,
``TFDataOps.scala:27-59``) — unless keeping them resident would blow the
``device_cache_bytes`` budget, in which case each partition's output is
pulled to host as it lands, keeping peak HBM at ~one block.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.frame.table import _is_device_array
from tensorframes_tpu.utils import get_config, set_config


@pytest.fixture
def small_budget():
    prev = get_config().device_cache_bytes
    set_config(device_cache_bytes=1024)
    yield
    set_config(device_cache_bytes=prev)


def test_map_blocks_output_is_device_resident():
    df = tft.TensorFrame.from_columns(
        {"x": np.arange(32, dtype=np.float32)}, num_partitions=4
    )
    out = tft.map_blocks(lambda x: {"z": x * 2.0}, df)
    cd = out.column_data("z")
    assert _is_device_array(cd.dense)
    # host access materializes once and memoizes
    h1 = cd.host()
    h2 = cd.host()
    assert h1 is h2
    np.testing.assert_allclose(h1, np.arange(32, dtype=np.float32) * 2.0)


def test_chained_maps_feed_device_resident_columns():
    df = tft.TensorFrame.from_columns({"x": np.arange(16, dtype=np.float32)})
    m1 = tft.map_blocks(lambda x: {"a": x + 1.0}, df)
    m2 = tft.map_blocks(lambda a: {"b": a * 3.0}, m1)
    cd = m2.column_data("b")
    assert _is_device_array(cd.dense)
    np.testing.assert_allclose(
        cd.host(), (np.arange(16, dtype=np.float32) + 1.0) * 3.0
    )


def test_streaming_budget_keeps_outputs_on_host(small_budget):
    # 64 f64 rows x 8 = 4KB > 1KB budget: inputs stream, outputs must land
    # on host per partition instead of accumulating in device memory
    x = np.arange(512, dtype=np.float64).reshape(64, 8)
    df = tft.TensorFrame.from_columns({"x": x}, num_partitions=4)
    out = tft.map_blocks(lambda x: {"z": x + 1.0}, df)
    cd = out.column_data("z")
    assert isinstance(cd.dense, np.ndarray)
    np.testing.assert_allclose(cd.dense, x + 1.0)


def test_large_output_small_input_streams(small_budget):
    # input fits the budget, but the output is bigger than it: the output
    # estimate must force host streaming too
    x = np.arange(64, dtype=np.float32)  # 256B < 1KB
    df = tft.TensorFrame.from_columns({"x": x}, num_partitions=2)
    out = tft.map_blocks(
        lambda x: {"z": np.ones((1, 16), np.float32) * x[:, None]}, df
    )  # 64*16*4 = 4KB > 1KB
    cd = out.column_data("z")
    assert isinstance(cd.dense, np.ndarray)
    np.testing.assert_allclose(cd.dense[3], np.full(16, 3.0))


def test_from_columns_accepts_device_arrays():
    import jax.numpy as jnp

    arr = jnp.arange(8, dtype=jnp.float32)
    df = tft.TensorFrame.from_columns({"x": arr})
    assert _is_device_array(df.column_data("x").dense)
    assert [r.x for r in df.collect()] == list(range(8))


def test_unpersist_preserves_device_resident_results():
    df = tft.TensorFrame.from_columns({"x": np.arange(8, dtype=np.float32)})
    out = tft.map_blocks(lambda x: {"z": x * 2.0}, df).cache()
    out.unpersist_device()
    cd = out.column_data("z")
    assert isinstance(cd.dense, np.ndarray)
    np.testing.assert_allclose(cd.dense, np.arange(8) * 2.0)


def test_trim_multi_fetch_row_count_mismatch_raises():
    df = tft.TensorFrame.from_columns({"x": np.arange(10, dtype=np.float32)})
    bad = tft.map_blocks(
        lambda x: {"u": x[:2], "v": x[:3]}, df, trim=True
    )
    with pytest.raises(ValueError, match="disagree on the output row count"):
        bad.cache()


def test_dense_map_rows_output_is_device_resident():
    # the all-dense single-bucket map_rows path keeps results in HBM like
    # map_blocks (no per-chunk host transfers), chunked by the per-call cap
    old = get_config().max_rows_per_device_call
    set_config(max_rows_per_device_call=7)  # forces multiple chunks
    try:
        x = np.arange(32, dtype=np.float32)
        df = tft.TensorFrame.from_columns({"x": x})
        out = tft.map_rows(lambda x: {"y": x * 2.0}, df)
        cd = out.column_data("y")
        assert _is_device_array(cd.dense)
        np.testing.assert_allclose(cd.host(), x * 2.0)
    finally:
        set_config(max_rows_per_device_call=old)


def test_dense_map_rows_streams_on_small_budget(small_budget):
    # over-budget columns keep the synchronous chunked path (host results)
    x = np.arange(512, dtype=np.float64)
    df = tft.TensorFrame.from_columns({"x": x})
    out = tft.map_rows(lambda x: {"y": x + 1.0}, df)
    cd = out.column_data("y")
    assert isinstance(cd.dense, np.ndarray)
    np.testing.assert_allclose(cd.dense, x + 1.0)
