"""Router high availability: durable request WAL, resumable client
streams, fenced standby takeover (serve/router_ha.py).

PR 18's correctness bar (byte-identical streams through member kill -9)
raised one tier again: now the ROUTER dies. The fast suite covers the
WAL's journal discipline (torn tails, cross-epoch merge, dedupe,
eviction), reconnect-resume byte-identity over real sockets, the
election/takeover state machine in-process (two RouterHA instances over
one shared directory), member-side zombie-epoch rejection, verbatim
Retry-After passthrough, lease clock edges, and the subprocess
provisioner; the slow soak spawns 2 router + 3 member subprocesses,
kill -9s the active router under 16 concurrent streams with chaos on,
and reconnects every client against the standby — byte-identical, zero
lost or duplicated tokens — then wakes a SIGSTOPped ex-active to prove
its late placement is epoch-rejected.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import GenerationEngine
from tensorframes_tpu.serve.fleet import Fleet
from tensorframes_tpu.serve.membership import (
    LocalProcessProvisioner,
    MemberAgent,
    MemberRegistry,
    RemoteEngine,
    connect_fleet,
)
from tensorframes_tpu.serve.router_ha import (
    ROUTER_LEASE_KEY,
    RequestWAL,
    RouterHA,
    attach_router_ha,
    router_epoch_from,
)
from tensorframes_tpu.interop.serving import ScoringServer
from tensorframes_tpu.utils.config import set_config
from tensorframes_tpu.utils.failures import (
    StaleLeaseError,
    StaleRouterEpochError,
    TenantThrottledError,
)
from tensorframes_tpu.utils.leases import LeaseStore

pytestmark = pytest.mark.ha

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture(autouse=True)
def _clean_config():
    yield
    set_config(router_wal=False, chaos="")


@pytest.fixture
def wal_on():
    set_config(router_wal=True)
    yield


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _wait_for(pred, timeout=20.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _engine(lm, name="m"):
    return GenerationEngine(
        lm, max_slots=4, page_size=4, num_pages=48, max_seq_len=64,
        name=name,
    )


def _http(addr, method, path, body=None, headers=None):
    """One raw HTTP exchange; returns (status, parsed body, headers)."""
    host, _, port = addr.rpartition(":")
    payload = b"" if body is None else json.dumps(body).encode()
    extra = "".join(
        f"{k}: {v}\r\n" for k, v in (headers or {}).items()
    )
    with socket.create_connection((host, int(port)), timeout=15) as c:
        c.sendall(
            (
                f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n{extra}"
                f"Connection: close\r\n\r\n"
            ).encode() + payload
        )
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, raw = buf.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    status = int(lines[0].split(b" ", 2)[1])
    hdrs = {}
    for hline in lines[1:]:
        k, _, v = hline.partition(b":")
        hdrs[k.strip().lower().decode()] = v.strip().decode()
    try:
        parsed = json.loads(raw.decode())
    except ValueError:
        parsed = {}
    return status, parsed, hdrs


def _stream_req(addr, body, stop_after=None, timeout=15.0):
    """Streaming POST /generate; returns ``(status, tokens, terminal)``.
    ``stop_after=k`` tears the connection after k token lines (the
    disconnecting-client drill; terminal comes back None). A connection
    that dies under us (the router was killed) returns what was read
    with terminal None instead of raising."""
    host, _, port = addr.rpartition(":")
    payload = json.dumps(dict(body, stream=True)).encode()
    c = socket.create_connection((host, int(port)), timeout=timeout)
    toks, terminal, status = [], None, 0
    try:
        c.sendall(
            (
                f"POST /generate HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode() + payload
        )
        f = c.makefile("rb")
        status = int(f.readline().split(b" ", 2)[1])
        while f.readline() not in (b"\r\n", b""):
            pass
        if status != 200:
            raw = f.read()
            try:
                terminal = json.loads(raw.decode())
            except ValueError:
                terminal = {}
            return status, toks, terminal
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line.decode())
            if "t" in d:
                toks.append(int(d["t"]))
                if stop_after is not None and len(toks) >= stop_after:
                    break
            else:
                terminal = d
                break
    except OSError:
        pass
    finally:
        c.close()
    return status, toks, terminal


# ---------------------------------------------------------------------------
# the WAL: journal discipline, tracker semantics
# ---------------------------------------------------------------------------


def _write_ledger(wal_dir, epoch, records):
    os.makedirs(wal_dir, exist_ok=True)
    path = os.path.join(wal_dir, f"wal.e{epoch:06d}.jsonl")
    with open(path, "ab") as f:
        for rec in records:
            if isinstance(rec, bytes):
                f.write(rec)  # raw bytes: the torn-tail drill
            else:
                f.write(json.dumps(rec).encode() + b"\n")
    return path


_REC = {"prompt": [1, 2, 3], "max_new": 8, "temperature": 0.0,
        "top_p": 1.0, "seed": 0, "eos_id": None, "session": None,
        "tenant": None, "deadline_s": None, "trace": None}


class TestRequestWAL:
    def test_recover_merges_epochs_and_skips_torn_tail(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        # epoch 0: admit + first 3 tokens, then a kill -9 torn tail
        _write_ledger(wal_dir, 0, [
            {"e": "admit", "rid": "r1", "rec": dict(_REC)},
            {"e": "tok", "rid": "r1", "off": 0, "t": [5]},
            {"e": "tok", "rid": "r1", "off": 1, "t": [6, 7]},
            b'{"e": "tok", "rid": "r1", "off": 3, "t"',  # torn
        ])
        # epoch 1: re-journaled snapshot (overlapping offsets) + more
        _write_ledger(wal_dir, 1, [
            {"e": "admit", "rid": "r1", "rec": dict(_REC)},
            {"e": "tok", "rid": "r1", "off": 0, "t": [5, 6, 7]},
            {"e": "tok", "rid": "r1", "off": 3, "t": [8]},
            {"e": "admit", "rid": "r2", "rec": dict(_REC)},
            {"e": "err", "rid": "r2", "kind": "ValueError", "msg": "bad"},
            # records for an admission never seen: ignored
            {"e": "tok", "rid": "ghost", "off": 0, "t": [1]},
        ])
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.epoch = 2  # recovering incarnation
        state = wal.recover()
        assert state["r1"]["tokens"] == [5, 6, 7, 8]
        assert state["r1"]["done"] is False
        assert state["r2"]["done"] is True
        assert state["r2"]["error"] == ("ValueError", "bad")
        assert "ghost" not in state

    def test_recover_trusts_only_contiguous_prefix_on_gap(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        _write_ledger(wal_dir, 0, [
            {"e": "admit", "rid": "g", "rec": dict(_REC)},
            {"e": "tok", "rid": "g", "off": 0, "t": [1, 2]},
            {"e": "tok", "rid": "g", "off": 5, "t": [9]},  # a gap
        ])
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.epoch = 1
        assert wal.recover()["g"]["tokens"] == [1, 2]

    def test_recover_readmission_after_error_resets(self, tmp_path):
        """A client retry of a refused id (forget() journaled the err
        and freed it) re-admits fresh — recovery must follow the
        RETRY's lifecycle, not merge into the refusal's."""
        wal_dir = str(tmp_path / "wal")
        _write_ledger(wal_dir, 0, [
            {"e": "admit", "rid": "x", "rec": dict(_REC)},
            {"e": "err", "rid": "x", "kind": "QueueFullError", "msg": "f"},
            {"e": "admit", "rid": "x", "rec": dict(_REC)},
            {"e": "tok", "rid": "x", "off": 0, "t": [4, 4]},
            {"e": "done", "rid": "x", "n": 2},
        ])
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.epoch = 1
        st = wal.recover()["x"]
        assert st == {"record": dict(_REC), "tokens": [4, 4],
                      "done": True, "error": None}

    def test_recover_ignores_own_and_future_epochs(self, tmp_path):
        wal_dir = str(tmp_path / "wal")
        _write_ledger(wal_dir, 3, [
            {"e": "admit", "rid": "mine", "rec": dict(_REC)},
        ])
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.epoch = 3
        assert wal.recover() == {}

    def test_admit_dedupes_and_forget_frees(self, tmp_path, wal_on):
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.open(0)
        try:
            e1, created1 = wal.admit("a", dict(_REC))
            e2, created2 = wal.admit("a", dict(_REC))
            assert created1 and not created2 and e1 is e2
            wal.forget("a", QueueFullErrorStub("full"))
            assert wal.lookup("a") is None
            e3, created3 = wal.admit("a", dict(_REC))
            assert created3 and e3 is not e1
        finally:
            wal.stop()

    def test_journal_flushes_fsynced_records_and_counts(
        self, tmp_path, wal_on
    ):
        before = {
            ev: _counter_value("fleet.wal_records_total", event=ev)
            for ev in ("admit", "done")
        }
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.open(0)
        try:
            entry, _ = wal.admit("j1", dict(_REC))
            wal._settle(entry, None)
            ledger = os.path.join(str(tmp_path), "wal", "wal.e000000.jsonl")
            _wait_for(
                lambda: os.path.exists(ledger)
                and len(open(ledger, "rb").read().splitlines()) >= 2,
                what="writer thread flushing both records",
            )
            lines = [
                json.loads(x)
                for x in open(ledger, "rb").read().splitlines()
            ]
            assert [x["e"] for x in lines] == ["admit", "done"]
            _wait_for(
                lambda: (
                    _counter_value("fleet.wal_records_total", event="admit")
                    > before["admit"]
                    and _counter_value(
                        "fleet.wal_records_total", event="done"
                    )
                    > before["done"]
                ),
                what="wal record counters",
            )
        finally:
            wal.stop()

    def test_chaos_transient_on_flush_is_absorbed(self, tmp_path, wal_on):
        set_config(chaos="fleet.router_wal=transient:p=1.0:times=2")
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.open(0)
        try:
            wal.admit("c1", dict(_REC))
            ledger = os.path.join(str(tmp_path), "wal", "wal.e000000.jsonl")
            _wait_for(
                lambda: os.path.exists(ledger)
                and b"admit" in open(ledger, "rb").read(),
                what="flush surviving transient chaos",
            )
        finally:
            wal.stop()
            set_config(chaos="")

    def test_eviction_drops_done_never_live(self, tmp_path, monkeypatch):
        import tensorframes_tpu.serve.router_ha as rh

        monkeypatch.setattr(rh, "_MAX_ENTRIES", 2)
        wal = RequestWAL(str(tmp_path), router_id="r-test")
        wal.open(0)
        try:
            live1, _ = wal.admit("live1", dict(_REC))
            done1, _ = wal.admit("done1", dict(_REC))
            wal._settle(done1, None)
            live2, _ = wal.admit("live2", dict(_REC))  # exceeds the bound
            assert wal.lookup("done1") is None  # evicted (oldest done)
            assert wal.lookup("live1") is live1
            assert wal.lookup("live2") is live2
            wal.admit("live3", dict(_REC))  # nothing evictable: all live
            assert wal.lookup("live1") and wal.lookup("live2")
        finally:
            wal.stop()


class QueueFullErrorStub(RuntimeError):
    pass


# ---------------------------------------------------------------------------
# gating: off by default, byte-identical off-path
# ---------------------------------------------------------------------------


class TestGating:
    def test_off_by_default_and_rid_still_echoed(self, lm):
        from tensorframes_tpu.utils.config import get_config

        assert get_config().router_wal is False
        fleet = Fleet(lm, replicas=1)
        try:
            assert getattr(fleet, "wal", None) is None
            with ScoringServer(engine=fleet) as addr:
                status, toks, term = _stream_req(
                    addr,
                    {"prompt": [3, 1, 2], "max_new_tokens": 6,
                     "request_id": "cli-1"},
                )
                assert status == 200 and term.get("done")
                # satellite: the client id is echoed even without a WAL
                assert term["request_id"] == "cli-1"
                np.testing.assert_array_equal(
                    np.asarray(toks), _solo(lm, [3, 1, 2], 6)
                )
                # no journal, no dedupe: a duplicate id generates again
                # (same bytes — determinism, not the tracker)
                status2, toks2, _ = _stream_req(
                    addr,
                    {"prompt": [3, 1, 2], "max_new_tokens": 6,
                     "request_id": "cli-1"},
                )
                assert status2 == 200 and toks2 == toks
        finally:
            fleet.stop()

    def test_attached_but_config_off_stays_cold(self, lm, tmp_path):
        fleet = Fleet(lm, replicas=1)
        ha = attach_router_ha(fleet, str(tmp_path))
        try:
            ha.tick()
            _wait_for(lambda: ha.active, what="first activation")
            with ScoringServer(engine=fleet) as addr:
                status, toks, term = _stream_req(
                    addr,
                    {"prompt": [2, 2], "max_new_tokens": 5,
                     "request_id": "cold-1"},
                )
                assert status == 200 and term.get("done")
                np.testing.assert_array_equal(
                    np.asarray(toks), _solo(lm, [2, 2], 5)
                )
            # config off → nothing tracked, nothing journaled
            assert fleet.wal.lookup("cold-1") is None
            assert fleet.wal.records_written == 0
        finally:
            ha.stop()
            fleet.stop()


# ---------------------------------------------------------------------------
# resumable streams (in-process: real sockets, local fleet)
# ---------------------------------------------------------------------------


@pytest.fixture
def ha_fleet(lm, tmp_path, wal_on):
    fleet = Fleet(lm, replicas=2)
    ha = attach_router_ha(fleet, str(tmp_path))
    ha.tick()
    _wait_for(lambda: ha.active, what="router activation")
    server = ScoringServer(engine=fleet)
    host, port = server.start()
    yield fleet, ha, f"{host}:{port}"
    server.stop()
    ha.stop()
    fleet.stop()


class TestResumableStreams:
    def test_fresh_stream_tracked_and_byte_identical(self, lm, ha_fleet):
        fleet, ha, addr = ha_fleet
        want = _solo(lm, [4, 5, 6], 8, temperature=0.7, seed=11)
        status, toks, term = _stream_req(
            addr,
            {"prompt": [4, 5, 6], "max_new_tokens": 8,
             "temperature": 0.7, "seed": 11, "request_id": "s-1"},
        )
        assert status == 200 and term.get("done")
        assert term["request_id"] == "s-1"
        np.testing.assert_array_equal(np.asarray(toks), want)
        entry = fleet.wal.lookup("s-1")
        assert entry is not None and entry.done
        assert entry.tokens == [int(t) for t in want]

    def test_duplicate_id_dedupes_nonstream(self, ha_fleet, lm):
        fleet, ha, addr = ha_fleet
        body = {"prompt": [1, 2, 3], "max_new_tokens": 6,
                "request_id": "dup-1"}
        before = _counter_value("serve.stream_resumes_total")
        s1, b1, _ = _http(addr, "POST", "/generate", body)
        s2, b2, _ = _http(addr, "POST", "/generate", body)
        assert s1 == 200 and s2 == 200
        assert b1["tokens"] == b2["tokens"]
        assert b1["request_id"] == b2["request_id"] == "dup-1"
        np.testing.assert_array_equal(
            np.asarray(b1["tokens"]), _solo(lm, [1, 2, 3], 6)
        )
        assert _counter_value("serve.stream_resumes_total") - before == 1.0

    def test_disconnect_reconnect_resumes_byte_identical(
        self, lm, ha_fleet
    ):
        fleet, ha, addr = ha_fleet
        want = _solo(lm, [7, 8], 10, temperature=0.5, seed=3)
        before = _counter_value("serve.stream_resumes_total")
        body = {"prompt": [7, 8], "max_new_tokens": 10,
                "temperature": 0.5, "seed": 3, "request_id": "rc-1"}
        # the client reads 4 tokens and its connection dies
        status, head, term = _stream_req(addr, body, stop_after=4)
        assert status == 200 and len(head) == 4 and term is None
        # reconnect with from=<what it already has>: the missed tail
        status, tail, term = _stream_req(
            addr, dict(body, **{"from": len(head)})
        )
        assert status == 200 and term.get("done")
        assert term["request_id"] == "rc-1"
        np.testing.assert_array_equal(np.asarray(head + tail), want)
        assert term["tokens_total"] == len(want)
        assert (
            _counter_value("serve.stream_resumes_total") - before == 1.0
        )

    def test_finished_stream_replays_fully_from_zero(self, lm, ha_fleet):
        fleet, ha, addr = ha_fleet
        body = {"prompt": [9, 1], "max_new_tokens": 7,
                "request_id": "rp-1"}
        status, first, term = _stream_req(addr, body)
        assert status == 200 and term.get("done")
        # long after completion: a replay of the whole stream
        status, again, term2 = _stream_req(addr, dict(body, **{"from": 0}))
        assert status == 200 and term2.get("done")
        assert again == first
        np.testing.assert_array_equal(
            np.asarray(again), _solo(lm, [9, 1], 7)
        )

    def test_negative_from_is_a_400(self, ha_fleet):
        fleet, ha, addr = ha_fleet
        status, body, _ = _http(
            addr, "POST", "/generate",
            {"prompt": [1], "max_new_tokens": 2, "request_id": "neg",
             "from": -1},
        )
        assert status == 400


# ---------------------------------------------------------------------------
# election, takeover, zombie fencing (in-process)
# ---------------------------------------------------------------------------


class TestElectionAndTakeover:
    def test_single_active_standby_waits_then_takes_over(
        self, lm, tmp_path, wal_on
    ):
        before = _counter_value("fleet.router_takeovers_total")
        fa = Fleet(lm, replicas=1)
        fb = Fleet(lm, replicas=1)
        ha_a = RouterHA(fa, str(tmp_path), name="ra", ttl_s=1.0)
        ha_b = RouterHA(fb, str(tmp_path), name="rb", ttl_s=1.0)
        try:
            ha_a.tick()
            _wait_for(lambda: ha_a.active, what="ra active")
            assert ha_a.epoch == 0 and fa.router_epoch == 0
            ha_b.tick()
            time.sleep(0.1)
            assert not ha_b.active  # the lease is live: no takeover
            # ra dies (no more renewals); rb campaigns past the TTL
            ha_a.store.stop(unlink_held=False)
            deadline = time.monotonic() + 20.0
            while not ha_b.active and time.monotonic() < deadline:
                ha_b._last_tick = -1e9  # defeat the tick rate limit
                ha_b.tick()
                time.sleep(0.05)
            assert ha_b.active and ha_b.epoch == 1
            assert fb.router_epoch == 1
            assert (
                _counter_value("fleet.router_takeovers_total") - before
                == 1.0
            )
        finally:
            ha_a.stop()
            ha_b.stop()
            fa.stop()
            fb.stop()

    def test_takeover_resumes_partial_request_byte_identical(
        self, lm, tmp_path, wal_on
    ):
        """The tentpole fold: a previous incarnation journaled an
        admission plus a delivered watermark and died; the new active
        resubmits with the watermark as the handle's prefix and the
        completed sequence is byte-identical to solo — greedy AND
        seeded sampling (per-step keys fold at absolute positions)."""
        # an expired epoch-0 lease so the takeover wins epoch 1
        old = LeaseStore(
            str(tmp_path), worker_id="dead-router", ttl_s=0.2
        )
        assert old.acquire(ROUTER_LEASE_KEY) == 0
        old._stop.set()  # kill its heartbeat; the lease lapses
        time.sleep(0.4)
        cases = {
            "greedy": ([5, 6, 7], 9, {}),
            "seeded": ([2, 4], 10,
                       {"temperature": 0.9, "top_p": 0.9, "seed": 21}),
        }
        wal_dir = str(tmp_path / "wal")
        wants = {}
        for rid, (prompt, n, kw) in cases.items():
            want = [int(t) for t in _solo(lm, prompt, n, **kw)]
            wants[rid] = want
            rec = dict(
                _REC, prompt=prompt, max_new=n,
                temperature=kw.get("temperature", 0.0),
                top_p=kw.get("top_p", 1.0), seed=kw.get("seed", 0),
            )
            _write_ledger(wal_dir, 0, [
                {"e": "admit", "rid": rid, "rec": rec},
                # 4 tokens delivered before the router died
                {"e": "tok", "rid": rid, "off": 0, "t": want[:4]},
            ])
        fleet = Fleet(lm, replicas=2)
        fleet.start()
        ha = attach_router_ha(fleet, str(tmp_path), ttl_s=1.0)
        try:
            deadline = time.monotonic() + 20.0
            while not ha.active and time.monotonic() < deadline:
                ha._last_tick = -1e9
                ha.tick()
                time.sleep(0.05)
            assert ha.active and ha.epoch == 1
            assert ha.resumed_requests == 2
            for rid, want in wants.items():
                entry = fleet.wal.lookup(rid)
                assert entry is not None
                _wait_for(
                    lambda e=entry: e.done, what=f"resumed {rid} settling"
                )
                assert entry.error is None
                assert entry.tokens == want, rid
        finally:
            ha.stop()
            fleet.stop()
            old.stop(unlink_held=False)

    def test_standby_router_serves_503(self, lm, tmp_path, wal_on):
        # someone else holds the lease: this router stays standby
        holder = LeaseStore(str(tmp_path), worker_id="other", ttl_s=30.0)
        assert holder.acquire(ROUTER_LEASE_KEY) == 0
        fleet = Fleet(lm, replicas=1)
        ha = attach_router_ha(fleet, str(tmp_path), ttl_s=30.0)
        try:
            ha.tick()
            time.sleep(0.1)
            assert not ha.active
            with ScoringServer(engine=fleet) as addr:
                status, body, hdrs = _http(
                    addr, "POST", "/generate",
                    {"prompt": [1], "max_new_tokens": 2,
                     "request_id": "sb"},
                )
                assert status == 503
                assert body["kind"] == "RouterStandby"
                assert body["request_id"] == "sb"
                assert hdrs.get("retry-after") == "1"
        finally:
            ha.stop()
            fleet.stop()
            holder.stop(unlink_held=False)

    def test_member_rejects_stale_router_epoch(self, lm, tmp_path):
        """Member-side fencing: a 409 for a placement whose
        x-router-epoch header is below the election lease's epoch, a
        pass for the current epoch, and no fencing without a header."""
        reg_dir = str(tmp_path)
        engine = _engine(lm, "m0")
        engine.start()
        registry = MemberRegistry(reg_dir, worker_id="proc-m0", ttl_s=30.0)
        agent = MemberAgent(engine, registry, "m0")
        host, port = agent.start()
        addr = f"{host}:{port}"
        # the election lease sits at epoch 1 (someone took over once)
        store = LeaseStore(reg_dir, worker_id="r-old", ttl_s=0.2)
        assert store.acquire(ROUTER_LEASE_KEY) == 0
        store._stop.set()
        time.sleep(0.4)
        store2 = LeaseStore(reg_dir, worker_id="r-new", ttl_s=30.0)
        assert store2.acquire(ROUTER_LEASE_KEY) == 1
        try:
            body = {"prompt": [1, 2], "max_new_tokens": 3}
            status, parsed, _ = _http(
                addr, "POST", "/generate", body,
                headers={"x-router-epoch": "0"},
            )
            assert status == 409
            assert parsed["kind"] == "StaleRouterEpochError"
            status, parsed, _ = _http(
                addr, "POST", "/generate", body,
                headers={"x-router-epoch": "1"},
            )
            assert status == 200 and len(parsed["tokens"]) == 3
            status, parsed, _ = _http(addr, "POST", "/generate", body)
            assert status == 200  # pre-HA clients are never fenced
        finally:
            agent.shutdown(timeout_s=5.0)
            store.stop(unlink_held=False)
            store2.stop(unlink_held=False)

    def test_remote_engine_stamps_epoch_and_reraises_409(
        self, lm, tmp_path
    ):
        """Router-side half: RemoteEngine sends the placing fleet's
        epoch and maps the member's 409 back to the exception class —
        which the fleet treats as non-replayable (no survivor retry of
        a zombie's placement)."""
        reg_dir = str(tmp_path)
        engine = _engine(lm, "m0")
        engine.start()
        registry = MemberRegistry(reg_dir, worker_id="proc-m0", ttl_s=30.0)
        agent = MemberAgent(engine, registry, "m0")
        host, port = agent.start()
        store = LeaseStore(reg_dir, worker_id="r-new", ttl_s=30.0)
        assert store.acquire(ROUTER_LEASE_KEY) == 0
        rem = RemoteEngine("m0", f"{host}:{port}")
        rem.router_epoch_fn = lambda: -1  # always below the lease epoch
        try:
            with pytest.raises(StaleRouterEpochError):
                rem.submit([1, 2], 3)
            rem.router_epoch_fn = lambda: 0  # current: placement lands
            h = rem.submit([1, 2], 3)
            assert len(h.result(timeout=30)) == 3
        finally:
            agent.shutdown(timeout_s=5.0)
            store.stop(unlink_held=False)

    def test_epoch_reader_caches_and_degrades_to_none(self, tmp_path):
        store = LeaseStore(str(tmp_path), worker_id="m", ttl_s=30.0)
        reader = router_epoch_from(store, cache_s=0.05)
        assert reader() is None  # no election lease yet
        holder = LeaseStore(str(tmp_path), worker_id="r", ttl_s=30.0)
        holder.acquire(ROUTER_LEASE_KEY)
        assert reader() is None  # cached miss
        time.sleep(0.08)
        assert reader() == 0  # cache expired: the lease is visible
        holder.stop(unlink_held=False)
        store.stop(unlink_held=False)


# ---------------------------------------------------------------------------
# error-mapping fidelity: Retry-After and reason ride through verbatim
# ---------------------------------------------------------------------------


class _RefusingEngine:
    """Duck-typed engine whose submit always refuses; _thread is
    non-None so ScoringServer never tries to start it."""

    def __init__(self, exc):
        self.exc = exc
        self._thread = threading.current_thread()

    def submit(self, *a, **kw):
        raise self.exc

    def health(self):
        return {"healthy": True}


class TestRetryAfterFidelity:
    def test_router_echoes_member_retry_after_verbatim_429(self):
        e = TenantThrottledError(
            "tenant t1 over quota", retry_after=7.0, reason="rate",
            tenant="t1",
        )
        e.retry_after_hint = "7"  # what the member's header said
        with ScoringServer(engine=_RefusingEngine(e)) as addr:
            status, body, hdrs = _http(
                addr, "POST", "/generate",
                {"prompt": [1], "max_new_tokens": 2,
                 "request_id": "q-1"},
            )
        assert status == 429
        assert hdrs["retry-after"] == "7"  # verbatim, not recomputed
        assert body["reason"] == "rate" and body["tenant"] == "t1"
        assert body["retry_after"] == 7.0
        assert body["request_id"] == "q-1"

    def test_router_echoes_member_retry_after_verbatim_503(self):
        from tensorframes_tpu.serve import EngineUnhealthyError

        e = EngineUnhealthyError("member shedding")
        e.retry_after_hint = "9"
        with ScoringServer(engine=_RefusingEngine(e)) as addr:
            status, body, hdrs = _http(
                addr, "POST", "/generate",
                {"prompt": [1], "max_new_tokens": 2},
            )
        assert status == 503 and hdrs["retry-after"] == "9"

    def test_remote_engine_attaches_member_hint(self):
        """End-to-end half: a member's 429 with Retry-After lands on
        the router's exception as retry_after_hint with the throttle
        reason and refill time intact."""
        member_exc = TenantThrottledError(
            "tenant t9 over quota", retry_after=13.0, reason="shed",
            tenant="t9",
        )
        with ScoringServer(engine=_RefusingEngine(member_exc)) as addr:
            rem = RemoteEngine("m0", addr)
            with pytest.raises(TenantThrottledError) as ei:
                rem.submit([1, 2], 3)
        caught = ei.value
        assert caught.retry_after_hint == "13"
        assert caught.reason == "shed" and caught.tenant == "t9"
        assert caught.retry_after == 13.0


# ---------------------------------------------------------------------------
# lease clock edges (utils/leases.py)
# ---------------------------------------------------------------------------


def _lease_file(tmp_path, key="k"):
    d = os.path.join(str(tmp_path), "leases")
    names = [n for n in os.listdir(d) if n.startswith(f"{key}.e")]
    assert len(names) == 1, names
    return os.path.join(d, names[0])


def _rewrite_deadline(path, deadline_unix):
    with open(path) as f:
        d = json.load(f)
    d["deadline_unix"] = deadline_unix
    with open(path, "w") as f:
        json.dump(d, f)


class TestLeaseClockEdges:
    def test_expiry_exactly_at_deadline_is_reclaimable(self, tmp_path):
        """deadline_unix <= now reads EXPIRED (the holder must renew
        BEFORE the deadline, not at it): a deadline pinned to 'now' is
        reclaimable, a hair in the future is not."""
        a = LeaseStore(str(tmp_path), worker_id="a", ttl_s=60.0)
        assert a.acquire("k") == 0
        a._stop.set()  # no renewals: the file's deadline is frozen
        b = LeaseStore(str(tmp_path), worker_id="b", ttl_s=60.0)
        _rewrite_deadline(_lease_file(tmp_path), time.time() + 30.0)
        assert b.acquire("k") is None  # live
        _rewrite_deadline(_lease_file(tmp_path), time.time())
        assert b.acquire("k") == 1  # the exact-deadline edge: expired
        a.stop(unlink_held=False)
        b.stop(unlink_held=False)

    def test_renewal_racing_expiry_loses_and_reports(self, tmp_path):
        """The holder's renewal sweeps AFTER a reclaimer won epoch+1:
        renew_all must drop the key (never resurrect the superseded
        epoch file) and fire on_lost with the stale epoch."""
        lost = []
        a = LeaseStore(
            str(tmp_path), worker_id="a", ttl_s=0.2, heartbeat_s=3600.0
        )
        a.on_lost = lambda key, epoch, cur: lost.append((key, epoch))
        assert a.acquire("k") == 0
        time.sleep(0.4)  # the lease lapses un-renewed
        b = LeaseStore(str(tmp_path), worker_id="b", ttl_s=60.0)
        assert b.acquire("k") == 1  # reclaimed
        assert a.renew_all() == 0  # the race: renewal after the steal
        assert lost == [("k", 0)]
        with pytest.raises(StaleLeaseError):
            a.publish("k", {"x": 1})
        # the loser's sweep must not have resurrected epoch 0
        assert b._scan("k").epoch == 1
        a.stop(unlink_held=False)
        b.stop(unlink_held=False)

    def test_renewal_before_deadline_retains_ownership(self, tmp_path):
        a = LeaseStore(
            str(tmp_path), worker_id="a", ttl_s=0.6, heartbeat_s=3600.0
        )
        assert a.acquire("k") == 0
        time.sleep(0.3)
        assert a.renew_all() == 1  # fresh deadline mid-ttl
        time.sleep(0.4)  # past the ORIGINAL deadline, not the renewed
        b = LeaseStore(str(tmp_path), worker_id="b", ttl_s=60.0)
        assert b.acquire("k") is None
        a.stop(unlink_held=False)
        b.stop(unlink_held=False)

    def test_wall_clock_drift_semantics(self, tmp_path):
        """Lease deadlines are WALL-clock (time.time()), shared via the
        filesystem: a holder whose clock runs behind writes deadlines
        that read as already-expired to a correct observer (reclaim —
        availability over the drifted holder), and a clock running
        ahead writes far-future deadlines that block reclaim until real
        time catches up (safety: observers must not fence a live
        holder on their own faster clock)."""
        a = LeaseStore(str(tmp_path), worker_id="a", ttl_s=5.0)
        assert a.acquire("k") == 0
        a._stop.set()
        b = LeaseStore(str(tmp_path), worker_id="b", ttl_s=5.0)
        # holder clock 60s behind: its freshly-written deadline already
        # reads expired here
        _rewrite_deadline(_lease_file(tmp_path), time.time() - 55.0)
        assert b.acquire("k") == 1
        # holder clock 60s ahead: reclaim refused though its ttl is 5s
        _rewrite_deadline(_lease_file(tmp_path), time.time() + 65.0)
        c = LeaseStore(str(tmp_path), worker_id="c", ttl_s=5.0)
        assert c.acquire("k") is None
        a.stop(unlink_held=False)
        b.stop(unlink_held=False)
        c.stop(unlink_held=False)


# ---------------------------------------------------------------------------
# the local subprocess provisioner (real autoscaler actuation)
# ---------------------------------------------------------------------------


_SLEEP_SCRIPT = "import sys, time\nwhile True: time.sleep(0.2)\n"


class TestLocalProcessProvisioner:
    def test_spawn_bound_retire_and_stop(self, tmp_path):
        prov = LocalProcessProvisioner(
            str(tmp_path), _SLEEP_SCRIPT, base_name="u", max_procs=2,
            term_grace_s=5.0,
        )
        try:
            assert prov.scale_up() == "u-1"
            assert prov.scale_up() == "u-2"
            assert prov.alive == 2
            assert prov.scale_up() is None  # the max_procs bound
            # newest-first retirement
            assert prov.scale_down() == "u-2"
            _wait_for(lambda: prov.alive == 1, what="u-2 exiting")
            assert prov.names() == ["u-1"]
        finally:
            prov.stop()
        assert prov.alive == 0
        assert prov.scale_down() is None  # nothing left to retire

    def test_autoscaler_convenience_binds_callbacks(self, tmp_path):
        prov = LocalProcessProvisioner(
            str(tmp_path), _SLEEP_SCRIPT, max_procs=3
        )

        class _F:
            replica_names = []
            _tick_hooks = []

        try:
            sc = prov.autoscaler(
                _F(), min_members=0, cooldown_s=0.0,
                signals_fn=lambda: {
                    "queue_depth": 99.0, "pages_frac": 0.0,
                    "itl_p99_s": 0.0, "members": 0.0,
                },
            )
            assert sc.max_members == 3
            assert sc.evaluate(now=100.0) == "up"
            assert prov.alive == 1
        finally:
            prov.stop()


_PROV_MEMBER_SCRIPT = r"""
import sys, time
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.serve import GenerationEngine
from tensorframes_tpu.serve.membership import MemberAgent, MemberRegistry

reg_dir, name = sys.argv[1], sys.argv[2]
lm = TransformerLM.init(0, 32, d_model=16, n_heads=4, max_len=64)
eng = GenerationEngine(
    lm, max_slots=4, page_size=4, num_pages=48, max_seq_len=64, name=name
)
eng.start()
agent = MemberAgent(
    eng, MemberRegistry(reg_dir, worker_id=f"proc-{name}", ttl_s=8.0), name
)
agent.start()
agent.install_sigterm()
while True:
    time.sleep(0.05)
"""


@pytest.mark.slow
class TestProvisionerScaleSoak:
    def test_scale_up_then_graceful_down_through_the_roster(
        self, lm, tmp_path
    ):
        """The ROADMAP item-3 remainder closed: the autoscaler's
        callbacks actuate REAL MemberAgent subprocesses — scale-up
        registers a serving member the router places work on; scale-down
        SIGTERMs it and the member drains + resigns (terminal lease),
        leaving the roster clean."""
        reg_dir = str(tmp_path / "reg")
        os.makedirs(reg_dir, exist_ok=True)
        prov = LocalProcessProvisioner(
            reg_dir, _PROV_MEMBER_SCRIPT, base_name="auto", max_procs=2,
            env={"JAX_PLATFORMS": "cpu"}, term_grace_s=60.0,
        )
        fleet = None
        try:
            fleet = connect_fleet(
                reg_dir, worker_id="router", ttl_s=8.0,
                sync_interval_s=0.1, watchdog_interval_s=0.05,
            )
            fleet.start()
            assert prov.scale_up() is not None
            _wait_for(
                lambda: len(fleet.replica_names) == 1, timeout=90,
                what="provisioned member joining the roster",
            )
            name = fleet.replica_names[0]
            got = np.asarray(
                fleet.submit([3, 1, 2], 6, temperature=0.3, seed=9)
                .result(timeout=120)
            )
            np.testing.assert_array_equal(
                got, _solo(lm, [3, 1, 2], 6, temperature=0.3, seed=9)
            )
            assert prov.scale_up() is not None
            _wait_for(
                lambda: len(fleet.replica_names) == 2, timeout=90,
                what="second member joining",
            )
            # scale down: SIGTERM → drain → resign → leave the roster
            retired = prov.scale_down()
            assert retired is not None
            _wait_for(
                lambda: len(fleet.replica_names) == 1, timeout=90,
                what="retired member leaving the roster",
            )
            _wait_for(lambda: prov.alive == 1, timeout=90,
                      what="retired process exiting")
            views = {v.key: v for v in fleet.registry.members()}
            assert views[retired].terminal  # resigned, not expired
            assert name in fleet.replica_names or retired != name
        finally:
            prov.stop()
            if fleet is not None:
                fleet.stop()
                fleet.registry.stop(unlink_held=False)


# ---------------------------------------------------------------------------
# statusz surfaces the router block
# ---------------------------------------------------------------------------


class TestStatusz:
    def test_router_block_present_when_attached(self, lm, ha_fleet):
        fleet, ha, addr = ha_fleet
        status, body, _ = _http(addr, "GET", "/statusz")
        assert status == 200
        router = body["router"]
        assert router["active"] is True and router["epoch"] == 0
        assert router["wal_enabled"] is True
        assert router["wal"]["epoch"] == 0

    def test_router_block_none_without_ha(self, lm):
        fleet = Fleet(lm, replicas=1)
        try:
            with ScoringServer(engine=fleet) as addr:
                status, body, _ = _http(addr, "GET", "/statusz")
            assert status == 200 and body["router"] is None
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# the acceptance soak: 2 router + 3 member subprocesses, kill -9 the
# active router mid-stream, SIGSTOP/CONT the successor for the zombie
# drill
# ---------------------------------------------------------------------------


_MEMBER_SCRIPT = r"""
import sys, time
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.serve import GenerationEngine
from tensorframes_tpu.serve.membership import MemberAgent, MemberRegistry

reg_dir, name, ttl = sys.argv[1], sys.argv[2], float(sys.argv[3])
lm = TransformerLM.init(0, 32, d_model=16, n_heads=4, max_len=64)
eng = GenerationEngine(
    lm, max_slots=8, page_size=4, num_pages=96, max_seq_len=64, name=name
)
eng.start()
agent = MemberAgent(
    eng, MemberRegistry(reg_dir, worker_id=f"proc-{name}", ttl_s=ttl), name
)
agent.start()
agent.install_sigterm()
while True:
    time.sleep(0.05)
"""

_ROUTER_SCRIPT = r"""
import json, os, sys, time
from tensorframes_tpu.interop.serving import ScoringServer
from tensorframes_tpu.serve.membership import connect_fleet
from tensorframes_tpu.serve.router_ha import attach_router_ha
from tensorframes_tpu.utils.config import set_config

reg_dir, name, report = sys.argv[1], sys.argv[2], sys.argv[3]
set_config(router_wal=True)
fleet = connect_fleet(
    reg_dir, worker_id=name, ttl_s=8.0,
    sync_interval_s=0.1, watchdog_interval_s=0.05,
)
ha = attach_router_ha(fleet, reg_dir, name=name, ttl_s=2.0)
fleet.start()
srv = ScoringServer(engine=fleet, max_connections=32)
host, port = srv.start()
with open(report + ".tmp", "w") as f:
    json.dump({"addr": f"{host}:{port}"}, f)
os.rename(report + ".tmp", report)
zreport = report + ".zombie"
reported = False
while True:
    time.sleep(0.05)
    if not reported and ha.fenced:
        out = {"fenced": True}
        try:
            h = fleet.submit([1, 2, 3], 3, block=False)
            h.result(timeout=15)
            err = h.error
            out["zombie_rejected"] = (
                type(err).__name__ == "StaleRouterEpochError"
            )
        except Exception as e:
            out["zombie_rejected"] = isinstance(
                e, Exception
            ) and "StaleRouterEpoch" in type(e).__name__
        with open(zreport + ".tmp", "w") as f:
            json.dump(out, f)
        os.rename(zreport + ".tmp", zreport)
        reported = True
"""


def _spawn(script, args, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-c", script, *args], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _read_report(path, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(0.1)
    pytest.fail(f"report {path} never appeared")


def _resilient_stream(addrs, body, rid, timeout=240.0):
    """Drive one stream to completion across router deaths: reconnect
    with request_id + from=<delivered> against whichever router
    answers. Returns (tokens, terminal)."""
    got = []
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        addr = addrs[i % len(addrs)]
        i += 1
        req = dict(body, request_id=rid, **{"from": len(got)})
        try:
            status, toks, term = _stream_req(addr, req, timeout=10.0)
        except OSError:
            time.sleep(0.25)
            continue
        if status in (503, 409) or status == 0:
            time.sleep(0.25)  # standby / fenced / no answer: rotate
            continue
        assert status == 200, (status, term)
        got.extend(toks)
        if term is not None:
            if term.get("done"):
                return got, term
            pytest.fail(f"stream {rid} errored: {term}")
        # torn mid-stream (the router died): loop reconnects
    pytest.fail(f"stream {rid} never finished")


@pytest.mark.slow
class TestRouterHASoak:
    def test_kill9_takeover_streams_resume_zombie_fenced(
        self, lm, tmp_path
    ):
        """The acceptance drill. Two routers (WAL on) + three members;
        16 concurrent client streams with transient chaos on members
        and the router WAL; kill -9 the ACTIVE router mid-stream — the
        standby takes over (epoch+1), resubmits the journaled requests
        recompute-style, and every client finishes byte-identical to
        solo by reconnecting with request_id + from (zero lost, zero
        duplicated tokens). Then a SIGSTOPped successor sleeps through
        its TTL, a fresh standby takes over, and the woken zombie's own
        late placement is rejected member-side (StaleRouterEpochError,
        reported from inside the zombie process)."""
        reg_dir = str(tmp_path / "reg")
        os.makedirs(reg_dir)
        decode_lag = "serve.decode_step=latency:ms=15"
        wal_chaos = "fleet.router_wal=transient:p=0.1"
        members = {
            name: _spawn(
                _MEMBER_SCRIPT, [reg_dir, name, "8.0"],
                extra_env={"TFT_CHAOS": f"seed={i + 1};{decode_lag}"},
            )
            for i, name in enumerate(["m0", "m1", "m2"])
        }
        r1_report = str(tmp_path / "r1.json")
        r2_report = str(tmp_path / "r2.json")
        routers = {
            "r1": _spawn(
                _ROUTER_SCRIPT, [reg_dir, "r1", r1_report],
                extra_env={"TFT_CHAOS": f"seed=7;{wal_chaos}"},
            ),
        }
        try:
            r1_addr = _read_report(r1_report)["addr"]

            # wait for the members to join and r1 to win the election
            def _ready():
                try:
                    status, body, _ = _http(r1_addr, "GET", "/statusz")
                except OSError:
                    return False
                router = body.get("router") or {}
                fleetv = body.get("serving") or {}
                return (
                    status == 200
                    and router.get("active") is True
                    and len(fleetv.get("replicas") or []) == 3
                )

            _wait_for(_ready, timeout=120, what="r1 active over 3 members")
            # the standby comes up AFTER r1 owns the lease
            routers["r2"] = _spawn(
                _ROUTER_SCRIPT, [reg_dir, "r2", r2_report],
                extra_env={"TFT_CHAOS": f"seed=8;{wal_chaos}"},
            )
            r2_addr = _read_report(r2_report)["addr"]
            addrs = [r1_addr, r2_addr]

            rng = np.random.default_rng(23)
            reqs = []
            for i in range(16):
                prompt = rng.integers(1, VOCAB, size=3 + i % 4).tolist()
                kw = (
                    {}
                    if i % 3 == 0
                    else {"temperature": 0.8, "seed": 50 + i}
                )
                reqs.append((prompt, 12, kw))
            want = [_solo(lm, p, n, **kw) for p, n, kw in reqs]

            results = [None] * 16
            errors = []

            def run_client(i):
                p, n, kw = reqs[i]
                body = {
                    "prompt": p, "max_new_tokens": n,
                    "session": f"s{i % 5}", **kw,
                }
                try:
                    toks, term = _resilient_stream(
                        addrs, body, rid=f"req-{i}"
                    )
                    results[i] = (toks, term)
                except BaseException as e:  # pytest.fail raises
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=run_client, args=(i,), daemon=True)
                for i in range(16)
            ]
            for i, t in enumerate(threads):
                t.start()
                time.sleep(0.1)
                if i == 7:
                    # kill -9 the ACTIVE router with streams in flight
                    routers["r1"].kill()
            for t in threads:
                t.join(timeout=300)
            assert not errors, errors
            assert all(r is not None for r in results)
            for i, ((toks, term), w) in enumerate(zip(results, want)):
                np.testing.assert_array_equal(
                    np.asarray(toks), np.asarray(w), err_msg=f"req-{i}"
                )
                assert term["request_id"] == f"req-{i}"
                assert term["tokens_total"] == len(w)

            # r2 must have taken over at epoch 1
            status, body, _ = _http(r2_addr, "GET", "/statusz")
            assert status == 200
            assert body["router"]["active"] is True
            assert body["router"]["epoch"] >= 1

            # --- the zombie drill: SIGSTOP r2 past its TTL, let a fresh
            # standby win, then wake r2 and watch its placement bounce
            r3_report = str(tmp_path / "r3.json")
            routers["r3"] = _spawn(
                _ROUTER_SCRIPT, [reg_dir, "r3", r3_report],
            )
            r3_addr = _read_report(r3_report)["addr"]
            routers["r2"].send_signal(signal.SIGSTOP)
            try:

                def _r3_active():
                    try:
                        s, b, _ = _http(r3_addr, "GET", "/statusz")
                    except OSError:
                        return False
                    return (
                        s == 200
                        and (b.get("router") or {}).get("active") is True
                    )

                _wait_for(
                    _r3_active, timeout=120,
                    what="r3 taking over from the stopped r2",
                )
            finally:
                routers["r2"].send_signal(signal.SIGCONT)
            zombie = _read_report(r2_report + ".zombie", timeout=120)
            assert zombie == {"fenced": True, "zombie_rejected": True}

            # the new active still serves byte-identically
            status, toks, term = _stream_req(
                r3_addr,
                {"prompt": [9, 9, 2], "max_new_tokens": 6,
                 "temperature": 0.4, "seed": 5, "request_id": "post"},
                timeout=60.0,
            )
            assert status == 200 and term.get("done")
            np.testing.assert_array_equal(
                np.asarray(toks),
                _solo(lm, [9, 9, 2], 6, temperature=0.4, seed=5),
            )
        finally:
            for proc in list(routers.values()) + list(members.values()):
                if proc.poll() is None:
                    try:
                        proc.send_signal(signal.SIGCONT)
                    except OSError:
                        pass
                    proc.kill()
                    proc.wait(timeout=30)
