"""Serving subsystem: paged KV cache, continuous batching, generate endpoint.

The correctness bar throughout: a request decoded through the shared
continuous batch must be BYTE-IDENTICAL to the same request decoded
alone through ``transformer_generate`` — the paged cache and slot
multiplexing are pure memory-layout concerns, invisible in the streams.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import (
    EngineUnhealthyError,
    GenerationEngine,
    GenRequest,
    GenerationHandle,
    PagePool,
    QueueFullError,
    Scheduler,
    SequencePages,
    pages_needed,
)
from tensorframes_tpu.utils import chaos, get_config, set_config
from tensorframes_tpu.utils.failures import (
    DeadlineExceededError,
    PagePoolExhausted,
)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=2, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])

pytestmark = pytest.mark.serve

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=48)


def _prompts(rng, lens):
    return [rng.integers(1, VOCAB, size=n).astype(np.int32).tolist() for n in lens]


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[0, len(prompt):]


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


# ---------------------------------------------------------------------------


class TestPagePool:
    def _pool(self, num_pages=6, page_size=4):
        return PagePool(
            n_layers=2, n_kv_heads=2, head_dim=4,
            num_pages=num_pages, page_size=page_size,
        )

    def test_static_shape_and_trash_row(self):
        pool = self._pool()
        assert pool.k.shape == (2, 7, 4, 2, 4)  # num_pages + 1 trash row
        assert pool.trash_page == 6

    def test_alloc_free_roundtrip(self):
        pool = self._pool()
        a = pool.alloc(2)
        b = pool.alloc(3)
        assert len(set(a) | set(b)) == 5 and pool.pages_in_use == 5
        pool.free(a)
        assert pool.pages_free == 3
        pool.free(b)
        assert pool.pages_in_use == 0

    def test_exhaustion_is_all_or_nothing(self):
        pool = self._pool(num_pages=4)
        pool.alloc(3)
        with pytest.raises(PagePoolExhausted):
            pool.alloc(2)  # only 1 free
        assert pool.pages_free == 1  # nothing leaked by the failed alloc

    def test_double_free_rejected(self):
        pool = self._pool()
        (p,) = pool.alloc(1)
        pool.free([p])
        with pytest.raises(ValueError, match="double free"):
            pool.free([p])

    def test_sequence_pages_growth_and_table(self):
        pool = self._pool(num_pages=6, page_size=4)
        seq = SequencePages(pool)
        seq.ensure(3)
        assert len(seq.pages) == 1 and seq.capacity == 4
        seq.ensure(4)  # fits the held page — no growth
        assert len(seq.pages) == 1
        seq.ensure(9)
        assert len(seq.pages) == 3
        tab = seq.table(5)
        assert tab.shape == (5,) and list(tab[:3]) == seq.pages
        assert all(tab[3:] == pool.trash_page)
        seq.release()
        assert pool.pages_in_use == 0
        seq.release()  # idempotent

    def test_pages_needed(self):
        assert pages_needed(1, 4) == 1
        assert pages_needed(4, 4) == 1
        assert pages_needed(5, 4) == 2

    def test_defragment_moves_contents_and_renumbers(self):
        pool = self._pool(num_pages=6, page_size=4)
        a, b = SequencePages(pool), SequencePages(pool)
        a.ensure(8)   # pages 0, 1
        b.ensure(8)   # pages 2, 3
        pool.free([a.pages[0]])  # punch a hole at page 0
        a.pages = a.pages[1:]
        # stamp each live page's contents with its page index
        for p in a.pages + b.pages:
            pool.k = pool.k.at[:, p].set(float(p))
        stamps = {p: float(p) for p in a.pages + b.pages}
        remap = pool.defragment([a, b])
        assert sorted(a.pages + b.pages) == [0, 1, 2]  # compacted prefix
        for old, new in remap.items():
            np.testing.assert_array_equal(
                np.asarray(pool.k[:, new]), stamps[old]
            )
        # freed tail is allocatable again
        assert pool.pages_free == 3
        pool.alloc(3)


# ---------------------------------------------------------------------------


def _mk_request(rid, plen, max_new, pool_unused=None):
    return GenRequest(
        request_id=rid,
        prompt=np.arange(1, plen + 1, dtype=np.int32),
        max_new_tokens=max_new,
        handle=GenerationHandle(rid),
    )


class TestScheduler:
    def _sched(self, num_pages=8, page_size=4, max_slots=2, cap=4):
        pool = PagePool(1, 1, 4, num_pages, page_size)
        return Scheduler(pool, max_slots, cap, max_seq_len=num_pages * page_size)

    def test_infeasible_request_rejected_at_submit(self):
        s = self._sched(num_pages=2, page_size=4)  # max 8 tokens ever
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            s.submit(_mk_request(1, plen=6, max_new=4))

    def test_bounded_queue_rejects_nonblocking(self):
        s = self._sched(cap=2)
        s.submit(_mk_request(1, 2, 2))
        s.submit(_mk_request(2, 2, 2))
        with pytest.raises(QueueFullError):
            s.submit(_mk_request(3, 2, 2), block=False)
        with pytest.raises(QueueFullError):
            s.submit(_mk_request(4, 2, 2), timeout=0.05)

    def test_admit_fills_slots_and_reserves_prompt_pages(self):
        s = self._sched(max_slots=2)
        for i in range(3):
            s.submit(_mk_request(i, plen=5, max_new=2))
        admitted = s.admit()
        assert [idx for idx, _ in admitted] == [0, 1]
        assert s.queue_depth == 1  # third waits for a slot
        # 5 tokens at page_size 4 -> 2 pages each
        assert s.pool.pages_in_use == 4

    def test_grow_preempts_youngest_and_requeues_front(self):
        s = self._sched(num_pages=4, page_size=4, max_slots=2)
        s.submit(_mk_request(1, plen=4, max_new=8))
        s.submit(_mk_request(2, plen=4, max_new=8))
        (i1, a1), (i2, a2) = s.admit()
        assert s.pool.pages_free == 2
        # the YOUNGER sequence grows to own the rest of the pool
        a2.generated.extend([9] * 5)
        assert s.grow(i2) is True
        assert s.pool.pages_free == 0
        # now the OLDER one must grow: the younger gets evicted
        a1.generated.extend([7] * 5)
        assert s.grow(i1) is True
        assert s.slots[i2] is None and s.slots[i1] is a1
        requeued = s._waiting[0]
        assert requeued.request_id == 2
        # recompute-style: progress folded into the prompt, budget reduced
        np.testing.assert_array_equal(requeued.prompt[-5:], [9] * 5)
        assert requeued.max_new_tokens == 3 and requeued.emitted == 5
        assert _counter_value("failures.preemptions_total", op="serve") >= 1

    def test_finish_releases_pages_and_closes_handle(self):
        s = self._sched()
        req = _mk_request(1, 3, 2)
        s.submit(req)
        ((idx, act),) = s.admit()
        act.req.handle._emit(5)
        s.finish(idx)
        assert s.pool.pages_in_use == 0 and s.slots[idx] is None
        assert req.handle.done
        np.testing.assert_array_equal(req.handle.result(timeout=1), [5])


# ---------------------------------------------------------------------------


class TestGenerationEngine:
    def test_greedy_streams_match_solo(self, lm):
        rng = np.random.default_rng(2)
        eng = GenerationEngine(lm, max_slots=4, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (3, 5, 2, 7))
        outs = eng.generate(prompts, max_new_tokens=6)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 6))
        assert eng.num_step_programs <= 2

    def test_sampled_streams_match_solo(self, lm):
        rng = np.random.default_rng(3)
        eng = GenerationEngine(lm, max_slots=3, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (4, 2, 6))
        handles = [
            eng.submit(p, 7, temperature=0.8, top_p=0.9, seed=50 + i)
            for i, p in enumerate(prompts)
        ]
        eng.run_until_idle()
        for i, (p, h) in enumerate(zip(prompts, handles)):
            np.testing.assert_array_equal(
                h.result(timeout=1),
                _solo(lm, p, 7, temperature=0.8, top_p=0.9, seed=50 + i),
            )
        assert eng.num_step_programs <= 2

    def test_eos_frees_slot_early(self, lm):
        rng = np.random.default_rng(4)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        # find a prompt whose greedy stream's third token is its first
        # occurrence, so eos_id cuts exactly there
        for _ in range(50):
            p = _prompts(rng, (4,))[0]
            solo = _solo(lm, p, 8)
            if solo[2] not in solo[:2]:
                break
        else:
            pytest.skip("no prompt with a fresh third token found")
        eos = int(solo[2])
        h = eng.submit(p, 8, eos_id=eos)
        eng.run_until_idle()
        np.testing.assert_array_equal(h.result(timeout=1), solo[:3])
        assert eng.pool.pages_in_use == 0

    def test_infeasible_submit_rejected(self, lm):
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=16)
        with pytest.raises(ValueError, match="exceeds max_seq_len"):
            eng.submit([1] * 10, max_new_tokens=10)
        with pytest.raises(ValueError):
            eng.submit([], max_new_tokens=2)

    def test_streaming_iteration_with_background_thread(self, lm):
        rng = np.random.default_rng(5)
        p = _prompts(rng, (3,))[0]
        with GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=32
        ) as eng:
            h = eng.submit(p, 5)
            got = list(h)  # streams as the background loop steps
        np.testing.assert_array_equal(got, _solo(lm, p, 5))

    def test_defragment_mid_generation_is_transparent(self, lm):
        rng = np.random.default_rng(6)
        eng = GenerationEngine(lm, max_slots=2, page_size=2, max_seq_len=32)
        prompts = _prompts(rng, (5, 3))
        handles = [eng.submit(p, 8) for p in prompts]
        for _ in range(3):
            eng.step()
        # punch holes: nothing guarantees compactness mid-run, so compact
        remap = eng.defragment()
        live = sorted(
            p for _, a in eng.scheduler.active for p in a.seq.pages
        )
        assert live == list(range(len(live)))  # contiguous prefix
        assert set(remap.values()) == set(live)
        eng.run_until_idle()
        for p, h in zip(prompts, handles):
            np.testing.assert_array_equal(h.result(timeout=1), _solo(lm, p, 8))


class TestPreemption:
    def test_starved_pool_preempts_requeues_and_stays_correct(self, lm):
        rng = np.random.default_rng(7)
        # 4 slots x up to 8 pages needed, but only 10 pages: sequences
        # evict each other and recompute; streams must not notice
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=32, num_pages=10
        )
        before = _counter_value("failures.preemptions_total", op="serve")
        prompts = _prompts(rng, (6, 9, 4, 8))
        outs = eng.generate(prompts, max_new_tokens=10)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 10))
        after = _counter_value("failures.preemptions_total", op="serve")
        assert after > before  # the pool really was contended
        assert eng.pool.pages_in_use == 0  # nothing leaked
        assert eng.num_step_programs <= 2  # preemption did not recompile


class TestSupervisor:
    def test_fatal_step_failure_fails_all_handles_fast(self, lm):
        """REGRESSION: a stepping-thread exception must fail every
        in-flight handle within a second — queued ones included — not
        strand them until the result timeout (the pre-fix behavior hung
        the full 300 s)."""
        from tensorframes_tpu.utils.chaos import ChaosFault

        rng = np.random.default_rng(20)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (3, 4, 2, 5))  # 2 active + 2 queued
        with chaos.scoped("serve.decode_step=fatal:times=1"):
            with eng:
                handles = [eng.submit(p, 6) for p in prompts]
                # wait out compile + the injected failure on the first one
                with pytest.raises(ChaosFault):
                    handles[0].result(timeout=30)
                # every other handle must already be (or instantly be) dead
                t0 = time.monotonic()
                for h in handles[1:]:
                    with pytest.raises(ChaosFault):
                        h.result(timeout=1)
                assert time.monotonic() - t0 < 1.0
                assert not eng.healthy
                # unhealthy engine sheds instead of queueing doomed work
                with pytest.raises(EngineUnhealthyError):
                    eng.submit(prompts[0], 4)
                assert _counter_value(
                    "serve.handles_failed_total", reason="fatal"
                ) >= 4

    def test_transient_step_failures_retry_invisibly(self, lm, fast_retries):
        rng = np.random.default_rng(21)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (4, 3))
        before = _counter_value(
            "chaos.injections_total", site="serve.decode_step",
            kind="transient",
        )
        with chaos.scoped("seed=5;serve.decode_step=transient:every=3"):
            outs = eng.generate(prompts, max_new_tokens=6)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 6))
        assert eng.healthy
        assert _counter_value(
            "chaos.injections_total", site="serve.decode_step",
            kind="transient",
        ) > before
        assert eng.num_step_programs <= 2

    def test_decode_oom_recovers_by_defrag_and_preempt(
        self, lm, fast_retries
    ):
        rng = np.random.default_rng(22)
        eng = GenerationEngine(lm, max_slots=3, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (4, 6, 3))
        before = _counter_value("failures.preemptions_total", op="serve")
        with chaos.scoped("serve.decode_step=oom:every=4:times=2"):
            outs = eng.generate(prompts, max_new_tokens=8)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 8))
        assert eng.healthy  # OOM was degraded through, not fatal
        assert _counter_value("failures.preemptions_total", op="serve") > before
        assert eng.num_step_programs <= 2

    def test_prefill_oom_requeues_recompute_style(self, lm, fast_retries):
        """A device OOM during prefill degrades like a decode OOM does —
        the request (nothing emitted yet) requeues for a retry — instead
        of escalating to a fail-everything terminal error."""
        rng = np.random.default_rng(25)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (4, 3))
        with chaos.scoped("serve.prefill=oom:every=1:times=1"):
            outs = eng.generate(prompts, max_new_tokens=6)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 6))
        assert eng.healthy
        assert eng.pool.pages_in_use == 0

    def test_empty_message_exception_does_not_kill_the_loop(self, lm):
        """str(e) == "" (bare asserts and friends) must not crash the
        supervisor's own logging: handles still fail with the real
        error and the loop thread survives."""
        rng = np.random.default_rng(26)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with eng:

            def boom(ready):
                raise RuntimeError()

            eng._decode_batch = boom
            h = eng.submit(_prompts(rng, (3,))[0], 4)
            with pytest.raises(RuntimeError):
                h.result(timeout=30)
            assert eng._thread.is_alive()  # the supervisor survived
            # the handle fails inside step(); the unhealthy flip happens
            # a beat later in the supervisor — give it that beat
            for _ in range(200):
                if not eng.healthy:
                    break
                time.sleep(0.01)
            assert not eng.healthy

    def test_restart_rebuilds_device_state_mid_run(self, lm):
        """Crash recovery: device KV state is corrupted mid-run; restart()
        preempts every live sequence (progress folded into prompts),
        re-zeroes the pool, and the streams stay byte-identical — with
        zero new compiled programs."""
        rng = np.random.default_rng(23)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (5, 3))
        handles = [eng.submit(p, 8) for p in prompts]
        for _ in range(3):
            eng.step()
        before = _counter_value("serve.engine_restarts_total")
        eng.pool.k = eng.pool.k * 0.0 + 7.25  # simulated device loss
        eng.pool.v = eng.pool.v * 0.0 - 3.5
        eng.restart()
        eng.run_until_idle()
        for p, h in zip(prompts, handles):
            np.testing.assert_array_equal(h.result(timeout=1), _solo(lm, p, 8))
        assert _counter_value("serve.engine_restarts_total") == before + 1
        assert eng.num_step_programs <= 2
        assert eng.pool.pages_in_use == 0

    def test_stop_join_failure_flips_unhealthy(self, lm):
        """stop() must not pretend a wedged stepping thread stopped: it
        flags the engine unhealthy and keeps the thread for a retry."""

        class _WedgedThread:
            def join(self, timeout=None):
                pass

            def is_alive(self):
                return True

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        eng.start()
        real = eng._thread
        eng._thread = _WedgedThread()
        eng.stop()
        assert eng._stop_wedged and not eng.healthy
        h = eng.health()
        assert h["healthy"] is False and h["stop_wedged"] is True
        # a wedged engine must refuse work AND refuse a restart that
        # could not actually step (the old thread still owns the loop)
        with pytest.raises(EngineUnhealthyError):
            eng.submit([1, 2], 2)
        with pytest.raises(RuntimeError, match="wedged"):
            eng.restart()
        # the retry path: the real thread exits on the stop event
        eng._thread = real
        eng.stop()
        assert eng._thread is None and not eng._stop_wedged
        eng.restart()
        assert eng.health()["healthy"] is True


class TestRestartSubmitRace:
    def test_submits_racing_restart_shed_or_complete_never_hang(self, lm):
        """restart() racing concurrent submit() on one engine: every
        submit must either shed fast with ``EngineUnhealthyError`` (or
        fail with the crash's own error, when a crash preceded the
        restart) or complete BYTE-IDENTICALLY — and no accepted handle
        may hang past its timeout. Phase 1 races restarts against a
        healthy engine (restart preempts-and-requeues, so nothing may
        shed or fail); phase 2 interleaves crashes, where shedding is
        the correct outcome for unlucky submits."""
        eng = GenerationEngine(lm, max_slots=3, page_size=4, max_seq_len=32)
        accepted = []  # (prompt, handle), under hlock
        sheds = []
        hlock = threading.Lock()
        stop = threading.Event()
        crash_allowed = threading.Event()

        def submitter(tid):
            trng = np.random.default_rng(300 + tid)
            while not stop.is_set():
                p = trng.integers(
                    1, VOCAB, size=int(trng.integers(2, 6))
                ).tolist()
                try:
                    h = eng.submit(p, 4)
                except EngineUnhealthyError:
                    with hlock:
                        sheds.append(tid)
                    assert crash_allowed.is_set(), (
                        "submit shed while only healthy restarts were "
                        "racing it"
                    )
                    time.sleep(0.002)
                    continue
                with hlock:
                    accepted.append((p, h))
                time.sleep(0.005)

        with eng:
            threads = [
                threading.Thread(target=submitter, args=(i,))
                for i in range(3)
            ]
            for t in threads:
                t.start()
            # phase 1: pure restarts — legal mid-run, streams must not
            # notice and submits must not shed
            for _ in range(5):
                time.sleep(0.03)
                eng.restart()
            # phase 2: crash + restart — submits may now shed, accepted
            # handles may fail with the injected crash
            crash_allowed.set()
            for _ in range(5):
                time.sleep(0.03)
                eng._fail_inflight(RuntimeError("injected crash"))
                time.sleep(0.005)
                eng.restart()
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not any(t.is_alive() for t in threads)
            # every accepted handle settles — byte-identical or failed
            # with the crash — well inside the timeout (TimeoutError
            # here would be the hang this test exists to catch)
            for p, h in accepted:
                try:
                    toks = h.result(timeout=60)
                except RuntimeError:
                    continue  # crashed mid-flight in phase 2 — legal
                np.testing.assert_array_equal(toks, _solo(lm, p, 4))
        assert accepted, "the race never accepted a submit"


class TestDeadlines:
    def test_queued_request_expires(self, lm):
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        before = _counter_value("serve.deadline_expired_total")
        h = eng.submit([1, 2, 3], 4, deadline=0.01)
        time.sleep(0.05)
        eng.step()
        assert h.done and isinstance(h.error, DeadlineExceededError)
        with pytest.raises(DeadlineExceededError):
            h.result(timeout=1)
        assert _counter_value("serve.deadline_expired_total") == before + 1
        assert _counter_value(
            "serve.handles_failed_total", reason="deadline"
        ) >= 1

    def test_mid_generation_deadline_releases_slot_and_pages(self, lm):
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=48)
        h = eng.submit([1, 2, 3, 4], 40, deadline=0.05)
        eng.step()  # admit + prefill + first decode
        assert not h.done
        time.sleep(0.06)
        eng.step()  # expiry sweep evicts the running sequence
        assert h.done and isinstance(h.error, DeadlineExceededError)
        assert eng.pool.pages_in_use == 0
        assert all(s is None for s in eng.scheduler.slots)

    def test_deadline_must_be_positive(self, lm):
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with pytest.raises(ValueError, match="deadline"):
            eng.submit([1, 2], 4, deadline=0.0)


class TestAdmissionPressure:
    def test_submit_timeout_races_queue_drain(self, lm):
        """A blocked submit(timeout=) must win the race when the stepping
        side drains the queue before the timeout — and lose it cleanly
        (QueueFullError, request not enqueued) when nothing drains."""
        rng = np.random.default_rng(24)
        eng = GenerationEngine(
            lm, max_slots=1, page_size=4, max_seq_len=32, queue_capacity=1
        )
        p1, p2 = _prompts(rng, (3, 4))
        h1 = eng.submit(p1, 5)  # fills the capacity-1 queue
        # no drain: the timed submit must give up on time
        t0 = time.monotonic()
        with pytest.raises(QueueFullError):
            eng.submit(p2, 5, timeout=0.05)
        assert time.monotonic() - t0 < 5
        # racing drain: stepping empties the queue while submit waits
        # (the admission pop notifies submitters immediately — the win
        # happens mid-step, before the drain thread's step returns)
        def drain():
            time.sleep(0.15)
            eng.step()  # admits h1 -> queue has room

        t = threading.Thread(target=drain)
        t.start()
        t1 = time.monotonic()
        h2 = eng.submit(p2, 5, timeout=30)  # parks, then wins the race
        waited = time.monotonic() - t1
        assert 0.14 <= waited < 30, waited  # parked until the drain ran
        t.join()
        eng.run_until_idle()
        np.testing.assert_array_equal(h1.result(timeout=1), _solo(lm, p1, 5))
        np.testing.assert_array_equal(h2.result(timeout=1), _solo(lm, p2, 5))


@pytest.mark.slow
class TestSoak:
    def test_sixteen_staggered_requests_byte_identical(self, lm):
        """The acceptance soak: N=16 requests, staggered arrivals, mixed
        prompt/output lengths, a pool small enough to force turnover —
        every stream byte-identical to its solo decode, with at most two
        compiled step programs for the whole run."""
        rng = np.random.default_rng(8)
        eng = GenerationEngine(
            lm, max_slots=6, page_size=4, max_seq_len=40, num_pages=24
        )
        plens = [int(rng.integers(1, 13)) for _ in range(16)]
        nnews = [int(rng.integers(3, 15)) for _ in range(16)]
        prompts = _prompts(rng, plens)
        handles = []
        # staggered arrivals: waves of submissions between live steps
        waves = [prompts[:5], prompts[5:9], prompts[9:13], prompts[13:]]
        k = 0
        for wave in waves:
            for p in wave:
                handles.append(eng.submit(p, nnews[k]))
                k += 1
            for _ in range(2):
                eng.step()
        eng.run_until_idle()
        for p, n, h in zip(prompts, nnews, handles):
            assert h.done and h.error is None
            np.testing.assert_array_equal(
                h.result(timeout=1), _solo(lm, p, n),
                err_msg=f"stream diverged (plen={len(p)}, n={n})",
            )
        assert eng.num_step_programs <= 2, eng.program_signatures
        assert eng.pool.pages_in_use == 0


# ---------------------------------------------------------------------------


def _http(addr, req: bytes) -> bytes:
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=30) as c:
        c.sendall(req)
        out = b""
        while True:
            b = c.recv(65536)
            if not b:
                break
            out += b
    return out


def _post_generate(addr, spec) -> tuple:
    body = json.dumps(spec).encode()
    req = (
        b"POST /generate HTTP/1.1\r\nContent-Length: "
        + str(len(body)).encode()
        + b"\r\n\r\n"
        + body
    )
    resp = _http(addr, req)
    status = int(resp.split(b" ", 2)[1])
    payload = json.loads(resp.split(b"\r\n\r\n", 1)[1] or b"{}")
    return status, payload


class TestGenerateEndpoint:
    def test_post_generate_matches_solo_and_scrape_shows_serve_metrics(
        self, lm
    ):
        from tensorframes_tpu.interop.serving import ScoringServer

        rng = np.random.default_rng(9)
        eng = GenerationEngine(lm, max_slots=4, page_size=4, max_seq_len=32)
        p = _prompts(rng, (4,))[0]
        with ScoringServer(engine=eng) as addr:
            status, payload = _post_generate(
                addr, {"prompt": p, "max_new_tokens": 6}
            )
            assert status == 200
            np.testing.assert_array_equal(payload["tokens"], _solo(lm, p, 6))
            scrape = _http(addr, b"GET /metrics HTTP/1.1\r\n\r\n").decode()
            for name in (
                "tft_serve_queue_depth",
                "tft_serve_active_slots",
                "tft_serve_pages_in_use",
                "tft_serve_ttft_seconds_count",
                "tft_serve_inter_token_seconds_count",
                'tft_serving_requests_total{kind="generate",status="ok"}',
            ):
                assert name in scrape, name
        assert eng._thread is None  # server stop also stopped its engine

    def test_concurrent_connections_share_the_batch(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        rng = np.random.default_rng(10)
        eng = GenerationEngine(lm, max_slots=4, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (3, 5, 2, 6))
        results = [None] * len(prompts)
        with ScoringServer(engine=eng) as addr:

            def worker(i):
                results[i] = _post_generate(
                    addr, {"prompt": prompts[i], "max_new_tokens": 5}
                )

            threads = [
                threading.Thread(target=worker, args=(i,))
                for i in range(len(prompts))
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
        for i, p in enumerate(prompts):
            status, payload = results[i]
            assert status == 200
            np.testing.assert_array_equal(
                payload["tokens"], _solo(lm, p, 5)
            )

    def test_bad_request_and_backpressure_status_codes(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=16, queue_capacity=0
        )
        with ScoringServer(engine=eng) as addr:
            status, payload = _post_generate(addr, {"prompt": [1, 2]})
            assert status == 400 and "error" in payload  # no max_new_tokens
            status, payload = _post_generate(
                addr, {"prompt": [1] * 12, "max_new_tokens": 10}
            )
            assert status == 400  # infeasible for max_seq_len=16
            # capacity-0 admission queue: instant 503 backpressure
            status, payload = _post_generate(
                addr, {"prompt": [1, 2], "max_new_tokens": 2}
            )
            assert status == 503

    def test_healthz_reports_engine_state(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with ScoringServer(engine=eng) as addr:
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            status = int(resp.split(b" ", 2)[1])
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert status == 200 and body["healthy"] is True
            for key in (
                "last_step_age_s",
                "queue_depth",
                "active_slots",
                "pages_in_use",
                "pages_capacity",
                "stepping_thread_alive",
                "stop_wedged",
            ):
                assert key in body, key
            assert body["stepping_thread_alive"] is True
            # the supervisor flipping unhealthy turns the probe red
            eng.healthy = False
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 503
            eng.healthy = True

    def test_healthz_without_engine_is_healthy(self):
        from tensorframes_tpu.interop.serving import ScoringServer

        with ScoringServer(lambda x: {"y": x}) as addr:
            resp = _http(addr, b"GET /healthz HTTP/1.1\r\n\r\n")
            assert int(resp.split(b" ", 2)[1]) == 200
            body = json.loads(resp.split(b"\r\n\r\n", 1)[1])
            assert body["healthy"] is True and body["engine"] is None
            # batch-job status rides along (engine/jobs.py)
            assert "runs_total" in body["jobs"]

    def test_shedding_answers_503_with_retry_after(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(
            lm, max_slots=2, page_size=4, max_seq_len=16, queue_capacity=0
        )
        with ScoringServer(engine=eng) as addr:
            # full admission queue: fast 503, caller told when to retry
            resp = _http(
                addr,
                b"POST /generate HTTP/1.1\r\nContent-Length: 40\r\n\r\n"
                b'{"prompt": [1, 2], "max_new_tokens": 2}\n',
            )
            assert int(resp.split(b" ", 2)[1]) == 503
            assert b"Retry-After: 1" in resp
            # unhealthy engine: same shedding, not a hang
            eng.healthy = False
            status, payload = _post_generate(
                addr, {"prompt": [1, 2], "max_new_tokens": 2}
            )
            assert status == 503 and "unhealthy" in payload["error"]
            eng.healthy = True

    def test_deadline_s_maps_to_504(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=48)
        # slow every decode step down so a 150 ms budget cannot fit the
        # requested 40 tokens — the sweep evicts mid-generation
        with chaos.scoped("serve.decode_step=latency:ms=60"):
            with ScoringServer(engine=eng) as addr:
                status, payload = _post_generate(
                    addr,
                    {
                        "prompt": [1, 2, 3],
                        "max_new_tokens": 40,
                        "deadline_s": 0.15,
                    },
                )
        assert status == 504
        assert "deadline" in payload["error"].lower()
        assert eng.pool.pages_in_use == 0

    def test_generate_only_server_refuses_arrow_scoring(self, lm):
        from tensorframes_tpu.interop.serving import (
            ScoringServer,
            remote_arrow_mapper,
        )

        pa = pytest.importorskip("pyarrow")
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=16)
        with ScoringServer(engine=eng) as addr:
            fn = remote_arrow_mapper(addr)
            batch = pa.record_batch({"x": pa.array([1.0, 2.0])})
            with pytest.raises(RuntimeError, match="no scoring program"):
                list(fn([batch]))

    def test_server_requires_program_or_engine(self):
        from tensorframes_tpu.interop.serving import ScoringServer

        with pytest.raises(ValueError, match="fetches"):
            ScoringServer()
