"""Transformer LM tests: forward parity across attention impls, training,
and frame scoring."""

import numpy as np
import pytest

import jax.numpy as jnp

import tensorframes_tpu as tft
from tensorframes_tpu.models import (
    TransformerLM,
    init_transformer,
    transformer_logits,
    transformer_loss,
)
from tensorframes_tpu.parallel import make_mesh

from _gates import requires_shard_map

VOCAB = 50


@pytest.fixture(scope="module")
def params():
    return init_transformer(
        0, VOCAB, d_model=32, n_heads=4, n_layers=2, max_len=64
    )


@pytest.fixture(scope="module")
def tokens():
    rng = np.random.default_rng(0)
    return rng.integers(0, VOCAB, (2, 32)).astype(np.int32)


def test_logits_shape_finite(params, tokens):
    out = np.asarray(transformer_logits(params, tokens))
    assert out.shape == (2, 32, VOCAB)
    assert np.isfinite(out).all()


def test_flash_matches_reference(params, tokens):
    ref = np.asarray(transformer_logits(params, tokens, attn_impl="reference"))
    fl = np.asarray(transformer_logits(params, tokens, attn_impl="flash"))
    np.testing.assert_allclose(fl, ref, rtol=2e-4, atol=2e-4)


@requires_shard_map
@pytest.mark.slow
def test_ring_matches_reference(params, tokens):
    mesh = make_mesh({"sp": 4})
    ref = np.asarray(transformer_logits(params, tokens, attn_impl="reference"))
    rg = np.asarray(
        transformer_logits(params, tokens, attn_impl="ring", mesh=mesh)
    )
    np.testing.assert_allclose(rg, ref, rtol=2e-4, atol=2e-4)


def test_causality(params, tokens):
    # changing future tokens must not affect earlier logits
    t2 = tokens.copy()
    t2[:, 20:] = (t2[:, 20:] + 7) % VOCAB
    a = np.asarray(transformer_logits(params, tokens))
    b = np.asarray(transformer_logits(params, t2))
    np.testing.assert_allclose(a[:, :20], b[:, :20], rtol=1e-5, atol=1e-5)
    assert not np.allclose(a[:, 20:], b[:, 20:])


def test_loss_and_fit(tokens):
    lm = TransformerLM.init(
        0, VOCAB, d_model=32, n_heads=4, n_layers=1, max_len=64
    )
    losses = lm.fit(tokens, steps=8, lr=0.5)
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_score_frame(params):
    rng = np.random.default_rng(1)
    toks = rng.integers(0, VOCAB, (6, 16)).astype(np.int32)
    df = tft.TensorFrame.from_columns({"tokens": toks}).analyze()
    lm = TransformerLM(params)
    out = lm.score_frame(df, "tokens")
    rows = out.collect()
    assert len(rows) == 6
    assert all(np.isfinite(r.nll) and r.nll > 0 for r in rows)


@pytest.mark.slow
class TestFitShardedDpSp:
    """dp x sp composition in ONE train step: batch-sharded ring attention
    plus GSPMD gradient all-reduce."""

    @requires_shard_map
    def test_losses_match_single_device_fit(self):
        from tensorframes_tpu.parallel import make_mesh

        rng = np.random.default_rng(5)
        vocab, L, B = 16, 17, 8  # L-1 = 16 divides sp=4; B divides dp=2
        toks = rng.integers(0, vocab, size=(B, L)).astype(np.int32)

        lm1 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_1 = lm1.fit(toks, steps=4, lr=0.2)

        mesh = make_mesh({"dp": 2, "sp": 4})
        lm2 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_2 = lm2.fit_sharded(toks, mesh, steps=4, lr=0.2)

        np.testing.assert_allclose(losses_2, losses_1, rtol=1e-4, atol=1e-5)

    def test_bad_shapes_rejected(self):
        from tensorframes_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": 2, "sp": 4})
        lm = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=20)
        toks = np.zeros((8, 20), np.int32)  # L-1 = 19 not divisible by 4
        with pytest.raises(ValueError, match="sp"):
            lm.fit_sharded(toks, mesh, steps=1)

    @requires_shard_map
    def test_ulysses_losses_match_single_device_fit(self):
        # ulysses trains through the flash kernel's custom VJP: the two
        # all_to_all transposes and the pallas backward compose under
        # jax.grad inside the dp x sp program
        from tensorframes_tpu.parallel import make_mesh

        rng = np.random.default_rng(6)
        vocab, L, B = 16, 17, 8  # L-1 = 16 divides sp=4; H=4 divides sp=4
        toks = rng.integers(0, vocab, size=(B, L)).astype(np.int32)

        lm1 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_1 = lm1.fit(toks, steps=4, lr=0.2)

        mesh = make_mesh({"dp": 2, "sp": 4})
        lm2 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_2 = lm2.fit_sharded(
            toks, mesh, steps=4, lr=0.2, attn_impl="ulysses"
        )

        np.testing.assert_allclose(losses_2, losses_1, rtol=1e-4, atol=1e-5)

    def test_fit_tp_matches_single_device_fit(self):
        # Megatron GSPMD sharding: qkv/up column-parallel, proj/down
        # row-parallel — same trajectory as the unsharded step
        from tensorframes_tpu.parallel import make_mesh

        rng = np.random.default_rng(3)
        toks = rng.integers(0, 16, size=(4, 12)).astype(np.int32)
        lm1 = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=12)
        ref = lm1.fit(toks, steps=4, lr=0.2)
        mesh = make_mesh({"dp": 2, "tp": 4})
        lm2 = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=12)
        got = lm2.fit_tp(toks, mesh, steps=4, lr=0.2)
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_fit_tp_guards(self):
        from tensorframes_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": 2, "tp": 4})
        toks = np.zeros((4, 12), np.int32)
        lm = TransformerLM.init(0, 16, d_model=18, n_heads=3, max_len=12)
        with pytest.raises(ValueError, match="head boundaries"):
            lm.fit_tp(toks, mesh, steps=1)
        lm2 = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=12)
        with pytest.raises(ValueError, match="batch"):
            lm2.fit_tp(np.zeros((3, 12), np.int32), mesh, steps=1)

    def test_single_chip_flash_fit_matches_reference_fit(self):
        # flash's custom VJP on one chip: same training trajectory as the
        # dense reference attention (L=128 divides the kernel's tiles)
        rng = np.random.default_rng(7)
        vocab, L, B = 16, 129, 2
        toks = rng.integers(0, vocab, size=(B, L)).astype(np.int32)

        lm1 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_ref = lm1.fit(toks, steps=3, lr=0.2)
        lm2 = TransformerLM.init(0, vocab, d_model=16, n_heads=4, max_len=L)
        losses_flash = lm2.fit(toks, steps=3, lr=0.2, attn_impl="flash")
        np.testing.assert_allclose(
            losses_flash, losses_ref, rtol=1e-4, atol=1e-5
        )

    def test_unsupported_impl_rejected(self):
        from tensorframes_tpu.parallel import make_mesh

        mesh = make_mesh({"dp": 2, "sp": 4})
        lm = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=17)
        toks = np.zeros((8, 17), np.int32)
        with pytest.raises(ValueError, match="ring.*ulysses"):
            lm.fit_sharded(toks, mesh, steps=1, attn_impl="reference")


class TestRemat:
    def test_remat_fit_matches_plain_fit(self):
        # jax.checkpoint must be semantics-preserving: identical losses,
        # only the backward's memory/FLOP trade differs
        rng = np.random.default_rng(0)
        toks = rng.integers(0, 16, size=(4, 12)).astype(np.int32)
        lm1 = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=12)
        plain = lm1.fit(toks, steps=4, lr=0.2)
        lm2 = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=12)
        remat = lm2.fit(toks, steps=4, lr=0.2, remat=True)
        np.testing.assert_allclose(remat, plain, rtol=1e-5, atol=1e-6)

    def test_remat_with_flash_and_moe(self):
        rng = np.random.default_rng(1)
        toks = rng.integers(0, 16, size=(2, 129)).astype(np.int32)
        lm = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=129)
        losses = lm.fit(toks, steps=2, lr=0.2, attn_impl="flash", remat=True)
        assert all(np.isfinite(losses))
        toks2 = rng.integers(0, 16, size=(2, 9)).astype(np.int32)
        lm2 = TransformerLM.init(
            0, 16, d_model=16, n_heads=4, max_len=12, moe_experts=4
        )
        l2 = lm2.fit(toks2, steps=2, lr=0.2, remat=True)
        assert all(np.isfinite(l2))


@pytest.mark.slow
class TestGenerate:
    """KV-cached scan decode vs the naive oracle: re-run the full forward
    on the growing sequence and argmax the last position."""

    def _naive_greedy(self, lm, prompt, n_new):
        import jax.numpy as jnp

        from tensorframes_tpu.models import transformer_logits

        toks = np.asarray(prompt, dtype=np.int32)
        for _ in range(n_new):
            logits = transformer_logits(lm.params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            toks = np.concatenate([toks, nxt[:, None].astype(np.int32)], 1)
        return toks

    def test_greedy_matches_naive_recompute(self):
        rng = np.random.default_rng(0)
        lm = TransformerLM.init(3, 32, d_model=16, n_heads=4, max_len=24)
        prompt = rng.integers(0, 32, size=(2, 5)).astype(np.int32)
        got = lm.generate(prompt, max_new_tokens=8)
        want = self._naive_greedy(lm, prompt, 8)
        np.testing.assert_array_equal(got, want)

    def test_greedy_after_training(self):
        # decode must read the TRAINED params (cache invalidates on fit)
        rng = np.random.default_rng(1)
        lm = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=20)
        prompt = rng.integers(0, 16, size=(1, 4)).astype(np.int32)
        before = lm.generate(prompt, max_new_tokens=6)
        toks = rng.integers(0, 16, size=(4, 12)).astype(np.int32)
        lm.fit(toks, steps=3, lr=0.3)
        after = lm.generate(prompt, max_new_tokens=6)
        want = self._naive_greedy(lm, prompt, 6)
        np.testing.assert_array_equal(after, want)
        assert before.shape == after.shape

    def test_sampled_decode_deterministic_per_seed(self):
        rng = np.random.default_rng(2)
        lm = TransformerLM.init(5, 32, d_model=16, n_heads=4, max_len=20)
        prompt = rng.integers(0, 32, size=(2, 4)).astype(np.int32)
        a = lm.generate(prompt, max_new_tokens=8, temperature=1.0, seed=7)
        b = lm.generate(prompt, max_new_tokens=8, temperature=1.0, seed=7)
        np.testing.assert_array_equal(a, b)
        c = lm.generate(prompt, max_new_tokens=8, temperature=1.0, seed=8)
        assert a.shape == c.shape == (2, 12)
        assert (a[:, :4] == prompt).all()

    def test_moe_model_greedy_matches_naive(self):
        rng = np.random.default_rng(3)
        lm = TransformerLM.init(
            1, 24, d_model=16, n_heads=4, max_len=20, moe_experts=4
        )
        prompt = rng.integers(0, 24, size=(2, 4)).astype(np.int32)
        got = lm.generate(prompt, max_new_tokens=6)
        want = self._naive_greedy(lm, prompt, 6)
        np.testing.assert_array_equal(got, want)

    def test_max_len_guard(self):
        lm = TransformerLM.init(0, 16, d_model=16, n_heads=4, max_len=10)
        with pytest.raises(ValueError, match="max_len"):
            lm.generate(np.zeros((1, 6), np.int32), max_new_tokens=8)
        with pytest.raises(ValueError, match="max_new_tokens"):
            lm.generate(np.zeros((1, 6), np.int32), max_new_tokens=0)

    def test_generate_composes_with_map_blocks(self):
        # decode over a FRAME of prompts: generation is just another
        # captured program through the dataframe plane
        import tensorframes_tpu as tft
        from tensorframes_tpu.models import transformer_generate

        rng = np.random.default_rng(5)
        lm = TransformerLM.init(1, 16, d_model=16, n_heads=4, max_len=16)
        prompts = rng.integers(0, 16, size=(6, 4)).astype(np.int32)
        df = tft.TensorFrame.from_columns({"prompt": prompts}).analyze()
        params = lm.params

        def gen_fn(prompt):
            return {"gen": transformer_generate(params, prompt, 5)}

        out = tft.map_blocks(gen_fn, df)
        got = np.asarray(out.cache().column_block("gen"))
        want = lm.generate(prompts, max_new_tokens=5)
        np.testing.assert_array_equal(got, want)

    def test_compiled_programs_reused_across_configs(self):
        # seeds and temperatures are TRACED arguments: a whole sweep runs
        # through one compiled program (the memo keys only structure), and
        # greedy decodes ignore seed entirely (it never enters the program)
        rng = np.random.default_rng(4)
        lm = TransformerLM.init(2, 16, d_model=16, n_heads=4, max_len=20)
        p = rng.integers(0, 16, size=(1, 4)).astype(np.int32)
        for seed in (1, 2, 3):
            lm.generate(p, 4, temperature=1.0, seed=seed)
        lm.generate(p, 4, temperature=0.7, seed=1)
        assert len(lm._generate_cache) == 1  # one program for the sweep
        a = lm.generate(p, 4, seed=1)
        b = lm.generate(p, 4, seed=9)
        np.testing.assert_array_equal(a, b)
        assert len(lm._generate_cache) == 2  # greedy adds ONE entry

    def test_generate_cache_is_bounded(self):
        rng = np.random.default_rng(6)
        lm = TransformerLM.init(2, 16, d_model=16, n_heads=4, max_len=64)
        for plen in range(2, 2 + lm._GENERATE_CACHE_MAX + 4):
            p = rng.integers(0, 16, size=(1, plen)).astype(np.int32)
            lm.generate(p, 2)
        assert len(lm._generate_cache) == lm._GENERATE_CACHE_MAX


@pytest.mark.slow
class TestSamplingFilters:
    """filter_logits (top-k / nucleus) and their wiring into generate."""

    def test_top_k_keeps_k_largest(self):
        from tensorframes_tpu.models import filter_logits

        logits = jnp.asarray([[0.0, 3.0, 1.0, 2.0, -1.0]])
        out = np.asarray(filter_logits(logits, top_k=2))
        kept = out > -1e30
        np.testing.assert_array_equal(kept, [[False, True, False, True, False]])
        np.testing.assert_allclose(out[0, 1], 3.0)

    def test_top_p_keeps_nucleus(self):
        from tensorframes_tpu.models import filter_logits

        # softmax of [2, 1, 0, -1] ~ [.64, .24, .09, .03]: top_p=0.7 keeps
        # the first two (mass before token 2 is .88 >= .7)
        logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]])
        out = np.asarray(filter_logits(logits, top_p=0.7))
        kept = out > -1e30
        np.testing.assert_array_equal(kept, [[True, True, False, False]])

    def test_tiny_top_p_keeps_argmax_only(self):
        from tensorframes_tpu.models import filter_logits

        logits = jnp.asarray([[0.5, 2.0, 1.0]])
        out = np.asarray(filter_logits(logits, top_p=1e-9))
        kept = out > -1e30
        np.testing.assert_array_equal(kept, [[False, True, False]])

    def test_top_k_1_sampling_equals_greedy(self):
        rng = np.random.default_rng(7)
        lm = TransformerLM.init(4, 24, d_model=16, n_heads=4, max_len=20)
        p = rng.integers(0, 24, size=(2, 4)).astype(np.int32)
        greedy = lm.generate(p, 6)
        k1 = lm.generate(p, 6, temperature=1.0, seed=3, top_k=1)
        np.testing.assert_array_equal(k1, greedy)

    def test_sampled_tokens_stay_within_top_k(self):
        # membership oracle via naive recompute: every sampled token must
        # be among the top-k of the step's true logits
        rng = np.random.default_rng(8)
        lm = TransformerLM.init(5, 24, d_model=16, n_heads=4, max_len=20)
        p = rng.integers(0, 24, size=(1, 3)).astype(np.int32)
        out = lm.generate(p, 5, temperature=1.3, seed=11, top_k=3)
        for t in range(3, out.shape[1]):
            logits = transformer_logits(
                lm.params, jnp.asarray(out[:, :t])
            )[:, -1]
            top3 = np.argsort(np.asarray(logits)[0])[-3:]
            assert out[0, t] in top3, (t, out[0, t], top3)

    def test_top_p_sweep_reuses_one_program(self):
        rng = np.random.default_rng(9)
        lm = TransformerLM.init(6, 16, d_model=16, n_heads=4, max_len=20)
        p = rng.integers(0, 16, size=(1, 4)).astype(np.int32)
        for tp in (0.5, 0.8, 0.95):
            lm.generate(p, 4, temperature=1.0, seed=1, top_p=tp)
        assert len(lm._generate_cache) == 1


class TestFilterLogitsEdgeCases:
    """filter_logits edge cases that matter to serving: deterministic
    top_k=1, tie-breaking exactly at the nucleus boundary, and the
    traced-scalar top_p contract under jit. Fast (pure functions + one
    tiny decode) so tier-1 keeps covering them."""

    def test_top_k_1_filter_keeps_argmax_only(self):
        from tensorframes_tpu.models import filter_logits

        logits = jnp.asarray([[0.5, 2.0, 1.0], [3.0, -1.0, 2.5]])
        out = np.asarray(filter_logits(logits, top_k=1))
        kept = out > -1e30
        np.testing.assert_array_equal(
            kept, [[False, True, False], [True, False, False]]
        )

    def test_top_k_1_sampling_equals_greedy_generate(self):
        # tiny end-to-end confirmation: with only the argmax surviving,
        # ANY temperature samples the greedy stream
        rng = np.random.default_rng(21)
        lm = TransformerLM.init(3, 16, d_model=8, n_heads=2, max_len=12)
        p = rng.integers(0, 16, size=(1, 3)).astype(np.int32)
        np.testing.assert_array_equal(
            lm.generate(p, 4, temperature=2.0, seed=5, top_k=1),
            lm.generate(p, 4),
        )

    def test_top_p_ties_at_nucleus_boundary_all_survive(self):
        from tensorframes_tpu.models import filter_logits

        # two EXACTLY tied logits, each with softmax mass 0.5 - eps: the
        # nucleus needs only the first, but masking is threshold-based
        # (logits < thresh), so its equal twin must survive too — a
        # sampled tie must never depend on sort order
        logits = jnp.asarray([[0.0, 0.0, -40.0]])
        out = np.asarray(filter_logits(logits, top_p=0.5))
        kept = out > -1e30
        np.testing.assert_array_equal(kept, [[True, True, False]])

    def test_top_p_boundary_mass_counts_strictly_before(self):
        from tensorframes_tpu.models import filter_logits

        # masses ~[.665, .245, .090]: top_p=0.7 keeps token 1 (mass
        # BEFORE it is .665 < .7) but drops token 2 (mass before .910)
        logits = jnp.asarray([[2.0, 1.0, 0.0]])
        out = np.asarray(filter_logits(logits, top_p=0.7))
        kept = out > -1e30
        np.testing.assert_array_equal(kept, [[True, True, False]])

    def test_traced_scalar_top_p_inside_jit(self):
        import jax

        from tensorframes_tpu.models import filter_logits

        calls = {"n": 0}

        def impl(logits, top_p):
            calls["n"] += 1
            return filter_logits(logits, top_p=top_p)

        f = jax.jit(impl)
        logits = jnp.asarray([[2.0, 1.0, 0.0, -1.0]])
        for tp, want_kept in ((0.7, 2), (0.95, 3), (1.0, 4)):
            out = np.asarray(f(logits, jnp.float32(tp)))
            assert (out > -1e30).sum() == want_kept, tp
            np.testing.assert_array_equal(
                out, np.asarray(filter_logits(logits, top_p=tp))
            )
        assert calls["n"] == 1  # one trace serves the whole sweep


class TestRaggedAgreementFast:
    """left_pad_prompts + prompt_lengths: a ragged batch must reproduce
    each row's solo decode token-for-token at temperature 0 (the fast
    tier-1 sibling of the slow TestRaggedPrompts suite)."""

    def test_left_pad_layout_agrees_with_lengths(self):
        from tensorframes_tpu.models import left_pad_prompts

        seqs = [[4], [1, 2, 3, 4], [9, 8]]
        packed, lens = left_pad_prompts(seqs, pad_id=7)
        np.testing.assert_array_equal(lens, [1, 4, 2])
        for row, s, n in zip(packed, seqs, lens):
            assert n == len(s)
            np.testing.assert_array_equal(row[len(row) - n :], s)
            assert all(row[: len(row) - n] == 7)

    def test_ragged_batch_matches_per_row_solo_decode(self):
        from tensorframes_tpu.models import left_pad_prompts

        rng = np.random.default_rng(22)
        lm = TransformerLM.init(9, 16, d_model=8, n_heads=2, max_len=16)
        seqs = [
            rng.integers(0, 16, size=n).astype(np.int32).tolist()
            for n in (1, 4, 2)
        ]
        packed, lens = left_pad_prompts(seqs)
        batch = lm.generate(packed, 4, prompt_lengths=lens)
        plen = packed.shape[1]
        for i, s in enumerate(seqs):
            solo = lm.generate(np.asarray([s], np.int32), 4)
            np.testing.assert_array_equal(
                batch[i, plen:], solo[0, len(s):],
                err_msg=f"row {i} (len {len(s)})",
            )


@pytest.mark.slow
class TestRaggedPrompts:
    """Left-padded variable-length prompt batches: each row must decode
    exactly as it would alone."""

    def test_left_pad_prompts_layout(self):
        from tensorframes_tpu.models import left_pad_prompts

        packed, lens = left_pad_prompts([[5], [1, 2, 3], [7, 8]], pad_id=0)
        np.testing.assert_array_equal(
            packed, [[0, 0, 5], [1, 2, 3], [0, 7, 8]]
        )
        np.testing.assert_array_equal(lens, [1, 3, 2])

    def test_ragged_greedy_matches_per_row_decode(self):
        from tensorframes_tpu.models import left_pad_prompts

        rng = np.random.default_rng(10)
        lm = TransformerLM.init(7, 24, d_model=16, n_heads=4, max_len=24)
        seqs = [
            rng.integers(0, 24, size=n).astype(np.int32).tolist()
            for n in (2, 4, 3)
        ]
        packed, lens = left_pad_prompts(seqs)
        batch = lm.generate(packed, 5, prompt_lengths=lens)
        p = packed.shape[1]
        for i, s in enumerate(seqs):
            alone = lm.generate(
                np.asarray([s], dtype=np.int32), 5
            )
            np.testing.assert_array_equal(
                batch[i, p:], alone[0, len(s):],
                err_msg=f"row {i} (len {len(s)})",
            )

    def test_ragged_equal_lengths_match_plain_path(self):
        rng = np.random.default_rng(11)
        lm = TransformerLM.init(8, 16, d_model=16, n_heads=4, max_len=20)
        p = rng.integers(0, 16, size=(3, 4)).astype(np.int32)
        plain = lm.generate(p, 5)
        ragged = lm.generate(
            p, 5, prompt_lengths=np.full(3, 4, np.int32)
        )
        np.testing.assert_array_equal(ragged, plain)


@pytest.mark.slow
class TestMoETransformer:
    """Transformer blocks with a routed MoE MLP (moe_experts=...)."""

    def test_moe_blocks_forward_and_fit(self):
        rng = np.random.default_rng(3)
        lm = TransformerLM.init(
            0, vocab=16, d_model=16, n_heads=4, max_len=16, moe_experts=4
        )
        toks = rng.integers(0, 16, size=(4, 16)).astype(np.int32)
        logits = np.asarray(transformer_logits(lm.params, toks))
        assert logits.shape == (4, 16, 16) and np.isfinite(logits).all()
        losses = lm.fit(toks, steps=6, lr=0.2)
        assert losses[-1] < losses[0]

    @requires_shard_map
    def test_ep_sharded_matches_local(self):
        from tensorframes_tpu.parallel import make_mesh

        rng = np.random.default_rng(4)
        params = TransformerLM.init(
            0, vocab=16, d_model=16, n_heads=4, max_len=16, moe_experts=8
        ).params
        toks = rng.integers(0, 16, size=(2, 16)).astype(np.int32)
        local = transformer_logits(params, toks)
        mesh = make_mesh({"ep": 8})
        sharded = transformer_logits(params, toks, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(sharded), np.asarray(local), rtol=2e-4, atol=2e-4
        )

    def test_moe_aux_loss_wired_into_training(self):
        rng = np.random.default_rng(6)
        lm = TransformerLM.init(
            0, vocab=16, d_model=16, n_heads=4, max_len=16, moe_experts=4
        )
        toks = rng.integers(0, 16, size=(4, 16)).astype(np.int32)
        losses = lm._sgd_loop(
            toks, steps=4, lr=0.2, loss_kwargs=dict(moe_aux_weight=1e-2)
        )
        assert np.isfinite(losses).all() and losses[-1] < losses[0]

    def test_aux_collection_returns_pair(self):
        from tensorframes_tpu.models.transformer import transformer_logits

        lm = TransformerLM.init(
            0, vocab=16, d_model=16, n_heads=4, max_len=16, moe_experts=4
        )
        toks = np.zeros((2, 16), np.int32)
        logits, aux = transformer_logits(
            lm.params, toks, collect_moe_aux=True
        )
        assert np.asarray(logits).shape == (2, 16, 16)
        assert float(aux) > 0


@pytest.mark.slow
class TestGQA:
    """Grouped-query attention: n_kv_heads k/v heads shared by
    n_heads/n_kv_heads query heads each. Exact oracle: an MHA model whose
    k/v projection columns are the GQA weights repeated per group
    computes identical attention."""

    def _mha_twin(self, params, n_heads, n_kv):
        import copy

        d = params["embed"].shape[1]
        hd = d // n_heads
        g = n_heads // n_kv
        twin = copy.deepcopy(params)
        for block in twin["blocks"]:
            w = np.asarray(block["qkv"])
            wq, wk, wv = w[:, :d], w[:, d:d + n_kv * hd], w[:, d + n_kv * hd:]
            rep = lambda m: np.repeat(
                m.reshape(d, n_kv, hd), g, axis=1
            ).reshape(d, d)
            block["qkv"] = np.concatenate([wq, rep(wk), rep(wv)], axis=1)
        return twin

    def test_logits_match_repeated_weight_mha(self):
        rng = np.random.default_rng(0)
        lm = TransformerLM.init(
            1, 32, d_model=32, n_heads=8, n_layers=2, max_len=16,
            n_kv_heads=2,
        )
        toks = rng.integers(0, 32, size=(3, 12)).astype(np.int32)
        got = transformer_logits(lm.params, toks)
        twin = self._mha_twin(lm.params, 8, 2)
        want = transformer_logits(twin, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_mqa_single_kv_head(self):
        rng = np.random.default_rng(1)
        lm = TransformerLM.init(
            2, 16, d_model=16, n_heads=4, max_len=16, n_kv_heads=1
        )
        toks = rng.integers(0, 16, size=(2, 8)).astype(np.int32)
        got = transformer_logits(lm.params, toks)
        want = transformer_logits(self._mha_twin(lm.params, 4, 1), toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )

    def test_generate_matches_naive_recompute(self):
        # the GQA decode path (n_kv-head cache, grouped einsums) must
        # agree with the full forward on the growing sequence
        rng = np.random.default_rng(2)
        lm = TransformerLM.init(
            3, 24, d_model=32, n_heads=8, n_layers=2, max_len=20,
            n_kv_heads=2,
        )
        prompt = rng.integers(0, 24, size=(2, 4)).astype(np.int32)
        got = lm.generate(prompt, max_new_tokens=8)
        toks = prompt
        for _ in range(8):
            logits = transformer_logits(lm.params, jnp.asarray(toks))
            nxt = np.asarray(jnp.argmax(logits[:, -1], axis=-1))
            toks = np.concatenate([toks, nxt[:, None].astype(np.int32)], 1)
        np.testing.assert_array_equal(got, toks)

    def test_gqa_trains(self):
        rng = np.random.default_rng(3)
        lm = TransformerLM.init(
            4, 16, d_model=16, n_heads=4, max_len=12, n_kv_heads=2
        )
        toks = rng.integers(0, 16, size=(4, 10)).astype(np.int32)
        losses = lm.fit(toks, steps=4, lr=0.3)
        assert all(np.isfinite(losses)) and losses[-1] < losses[0]

    def test_qkv_weight_shrinks(self):
        lm = TransformerLM.init(
            0, 16, d_model=32, n_heads=8, max_len=8, n_kv_heads=2
        )
        # d + 2 * n_kv * hd = 32 + 2*2*4 = 48, vs 96 for MHA
        assert lm.params["blocks"][0]["qkv"].shape == (32, 48)
        mha = TransformerLM.init(0, 16, d_model=32, n_heads=8, max_len=8)
        assert mha.params["blocks"][0]["qkv"].shape == (32, 96)

    def test_indivisible_kv_heads_rejected(self):
        with pytest.raises(ValueError, match="n_kv_heads"):
            TransformerLM.init(
                0, 16, d_model=32, n_heads=8, max_len=8, n_kv_heads=3
            )

    @requires_shard_map
    def test_gqa_through_ring_and_ulysses(self):
        rng = np.random.default_rng(5)
        lm = TransformerLM.init(
            6, 24, d_model=32, n_heads=8, n_layers=1, max_len=16,
            n_kv_heads=2,
        )
        toks = rng.integers(0, 24, size=(2, 16)).astype(np.int32)
        dense = transformer_logits(lm.params, toks)
        mesh = make_mesh({"sp": 4})
        for impl in ("ring", "ulysses"):
            got = transformer_logits(
                lm.params, toks, attn_impl=impl, mesh=mesh
            )
            np.testing.assert_allclose(
                np.asarray(got), np.asarray(dense), rtol=2e-4, atol=2e-4,
                err_msg=impl,
            )
