"""Real multi-process execution: 2 processes x 4 virtual CPU devices.

Everything else in the suite runs distribution semantics inside ONE
process over 8 virtual devices. These tests spawn two actual processes
joined by ``jax.distributed.initialize`` (cross-process collectives over
Gloo — the code path that rides DCN between TPU hosts), each feeding only
its local rows, and check the result against a single-process oracle. The
reference never tests across real executors (SURVEY §4: `local[1]`
masters only); this goes one step further than it did.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

#: real multi-process spawns: the suite's heavyweights (measured r05
#: durations); `make test-fast` skips them
pytestmark = pytest.mark.slow

_WORKER = r"""
import json, sys
import numpy as np
from tensorframes_tpu.parallel import multihost

pid, port = int(sys.argv[1]), sys.argv[2]
multihost.initialize(
    f"localhost:{port}", num_processes=2, process_id=pid, local_device_count=4
)
import jax
assert jax.process_count() == 2 and len(jax.devices()) == 8

from tensorframes_tpu.parallel import ShardedSGDTrainer, make_mesh

mesh = make_mesh({"dp": 4, "tp": 2})
trainer = ShardedSGDTrainer([8, 16, 4], mesh=mesh, lr=0.1)

rng = np.random.default_rng(7)
x = rng.normal(size=(32, 8)).astype(np.float32)
y = rng.integers(0, 4, size=(32,)).astype(np.int32)
rows = multihost.local_rows(32)

params, losses = trainer.fit(x[rows], y[rows], steps=5, seed=3)

# cross-process psum sanity: global sum assembled from local halves
local = np.arange(4.0) + 4 * pid
total = multihost.sync_global(
    jax.jit(lambda a: a.sum())(multihost.global_batch(local, mesh))
)

# uneven row split must be rejected under 2 processes
try:
    multihost.local_rows(33)
    uneven_rejected = False
except ValueError:
    uneven_rejected = True

# dataframe ops over the multi-process mesh: each process holds only its
# local rows; no process ever sees the whole table
import tensorframes_tpu as tft

data = np.arange(48, dtype=np.float32)  # the conceptual global column
rows = multihost.local_rows(48)
local_df = tft.TensorFrame.from_columns({"x": data[rows]})
dp_mesh = make_mesh({"dp": 8})
mapped = multihost.map_blocks(
    lambda x: {"z": x * 2.0 + 1.0}, local_df, dp_mesh
)
local_z = [float(r.z) for r in mapped.collect()]
reduced = multihost.reduce_blocks(
    lambda x_input: {"x": x_input.sum()}, local_df, dp_mesh
)

if pid == 0:
    print("RESULT " + json.dumps(
        {"losses": losses, "psum": float(total),
         "uneven_rejected": uneven_rejected,
         "local_z": local_z, "global_sum": float(reduced)}
    ), flush=True)
"""


def _free_port():
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]

def _run_workers(
    tmp_path_factory, name, source, num_procs, devices_per_proc,
    extra_args=(), worker_path=None,
):
    """Spawn ``num_procs`` worker processes joined by jax.distributed over
    Gloo, each with ``devices_per_proc`` virtual CPU devices; returns the
    (stdout, stderr) pairs after asserting every worker exited cleanly.
    A worker stuck in the distributed barrier (e.g. its peer died during
    initialize) must not outlive the fixture holding the port.
    ``worker_path`` reuses an already-written worker file (the drill's
    second phase); ``extra_args`` append to each worker's argv after the
    pid and port."""
    if worker_path is None:
        d = tmp_path_factory.mktemp(name)
        worker = d / "worker.py"
        worker.write_text(source)
    else:
        worker = worker_path
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(worker), str(i), str(port),
                *map(str, extra_args),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            env=env,
            text=True,
        )
        for i in range(num_procs)
    ]
    try:
        outs = [p.communicate(timeout=300) for p in procs]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for p, (out, err) in zip(procs, outs):
        assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
    return outs


def _per_pid_results(outs):
    results = {}
    for i, (out, _) in enumerate(outs):
        line = next(
            l for l in out.splitlines() if l.startswith(f"RESULT{i} ")
        )
        results[i] = json.loads(line[len(f"RESULT{i} "):])
    return results



_WORKER4 = r"""
import json, sys
import numpy as np
from tensorframes_tpu.parallel import multihost

pid, port = int(sys.argv[1]), sys.argv[2]
multihost.initialize(
    f"localhost:{port}", num_processes=4, process_id=pid, local_device_count=2
)
import jax
assert jax.process_count() == 4 and len(jax.devices()) == 8

import tensorframes_tpu as tft
from tensorframes_tpu.parallel import make_mesh

mesh = make_mesh({"dp": 8})
data = np.arange(48, dtype=np.float32)  # conceptual global column
rows = multihost.local_rows(48)
local_df = tft.TensorFrame.from_columns({"x": data[rows]})

# row map over the global mesh: each process feeds 12 rows, gets its 12 back
mapped = multihost.map_rows(lambda x: {"y": x * 3.0 + 1.0}, local_df, mesh)
# chained multihost op feeds the registered global result directly — the
# intermediate frame must stay lazy (its host rows never materialized)
chained = multihost.map_blocks(lambda y: {"z": y * 2.0}, mapped, mesh)
lazy_after_chain = bool(mapped.is_lazy)
local_z = [float(r.z) for r in chained.collect()]
local_y = [float(r.y) for r in mapped.collect()]

# pairwise row reduce: per-shard fold + all_gather + merge fold, replicated
total = multihost.reduce_rows(
    lambda x_1, x_2: {"x": x_1 + x_2}, local_df, mesh
)

# keyed aggregation with binary keys; group counts DIFFER per process
# (process p sees groups g0..g{p}) so the padded partial exchange is
# actually exercised
names = [b"g%d" % min(i // 3, pid) for i in range(12)]
kdf = tft.TensorFrame.from_columns(
    {"k": names, "v": np.arange(12, dtype=np.float32) + 100.0 * pid}
)
agg = multihost.aggregate(
    lambda v_input: {"v": v_input.sum(axis=0)}, kdf.group_by("k"), mesh
)
agg_rows = sorted((r.k.decode(), float(r.v)) for r in agg.collect())

# ragged rows run the partition-local path: still correct per process
rg = tft.TensorFrame.from_rows(
    [{"v": [1.0] * (1 + (pid + i) % 3)} for i in range(4)]
).analyze()
rr = multihost.map_rows(lambda v: {"s": v.sum()}, rg, mesh)
ragged_sums = [float(r.s) for r in rr.collect()]

print(f"RESULT{pid} " + json.dumps(
    {"local_y": local_y, "total": float(total), "agg": agg_rows,
     "ragged": ragged_sums, "local_z": local_z,
     "lazy_after_chain": lazy_after_chain}
), flush=True)
"""


@pytest.fixture(scope="module")
def four_process_result(tmp_path_factory):
    return _per_pid_results(
        _run_workers(tmp_path_factory, "mh4", _WORKER4, 4, 2)
    )


class TestFourProcess:
    """4 processes x 2 devices: all five frame ops distributed, vs oracle."""

    def test_map_rows_returns_local_slice_transformed(
        self, four_process_result
    ):
        data = np.arange(48, dtype=np.float32)
        for pid in range(4):
            np.testing.assert_allclose(
                four_process_result[pid]["local_y"],
                (data[pid * 12 : (pid + 1) * 12] * 3.0 + 1.0).tolist(),
            )

    def test_reduce_rows_replicated_global_fold(self, four_process_result):
        for pid in range(4):
            assert four_process_result[pid]["total"] == float(
                np.arange(48).sum()
            )

    def test_aggregate_uneven_groups_match_oracle(self, four_process_result):
        # single-process oracle over the union of all four local tables
        oracle = {}
        for pid in range(4):
            names = [f"g{min(i // 3, pid)}" for i in range(12)]
            vals = np.arange(12, dtype=np.float32) + 100.0 * pid
            for k, v in zip(names, vals):
                oracle[k] = oracle.get(k, 0.0) + float(v)
        expect = sorted((k, v) for k, v in oracle.items())
        for pid in range(4):
            got = [tuple(r) for r in four_process_result[pid]["agg"]]
            assert got == expect, (pid, got, expect)

    def test_ragged_map_rows_partition_local(self, four_process_result):
        for pid in range(4):
            expect = [float(1 + (pid + i) % 3) for i in range(4)]
            assert four_process_result[pid]["ragged"] == expect

    def test_chained_map_stays_device_resident(self, four_process_result):
        # the chained map_blocks fed map_rows's registered global array:
        # the intermediate frame stayed lazy across the chain, and the
        # chained values are the local slice through both programs
        data = np.arange(48, dtype=np.float32)
        for pid in range(4):
            assert four_process_result[pid]["lazy_after_chain"] is True
            np.testing.assert_allclose(
                four_process_result[pid]["local_z"],
                ((data[pid * 12 : (pid + 1) * 12] * 3.0 + 1.0) * 2.0).tolist(),
            )


_WORKER8 = r"""
import json, sys
import numpy as np
from tensorframes_tpu.parallel import multihost

pid, port = int(sys.argv[1]), sys.argv[2]
multihost.initialize(
    f"localhost:{port}", num_processes=8, process_id=pid, local_device_count=1
)
import jax
assert jax.process_count() == 8 and len(jax.devices()) == 8

import tensorframes_tpu as tft
from tensorframes_tpu.parallel import make_mesh

mesh = make_mesh({"dp": 8})
data = np.arange(64, dtype=np.float32)
rows = multihost.local_rows(64)
local_df = tft.TensorFrame.from_columns({"x": data[rows]})

# chained maps stay device-resident across 8 real processes
m1 = multihost.map_blocks(lambda x: {"y": x * 2.0}, local_df, mesh)
total = multihost.reduce_blocks(lambda y_input: {"y": y_input.sum()}, m1, mesh)
lazy = bool(m1.is_lazy)
local_y = [float(r.y) for r in m1.collect()]

print(f"RESULT{pid} " + json.dumps(
    {"local_y": local_y, "total": float(total), "lazy": lazy}
), flush=True)
"""


@pytest.fixture(scope="module")
def eight_process_result(tmp_path_factory):
    return _per_pid_results(
        _run_workers(tmp_path_factory, "mh8", _WORKER8, 8, 1)
    )


class TestEightProcess:
    """8 processes x 1 device each: one chip per host, the maximal
    process-to-device ratio — collectives cross a process boundary on
    EVERY hop."""

    def test_chained_map_reduce_with_device_residency(
        self, eight_process_result
    ):
        data = np.arange(64, dtype=np.float32)
        for pid in range(8):
            r = eight_process_result[pid]
            assert r["lazy"] is True
            assert r["total"] == float((data * 2.0).sum())
            np.testing.assert_allclose(
                r["local_y"],
                (data[pid * 8 : (pid + 1) * 8] * 2.0).tolist(),
            )


@pytest.fixture(scope="module")
def two_process_result(tmp_path_factory):
    outs = _run_workers(tmp_path_factory, "mh", _WORKER, 2, 4)
    line = next(
        l for l in outs[0][0].splitlines() if l.startswith("RESULT ")
    )
    return json.loads(line[len("RESULT "):])


class TestTwoProcess:
    def test_cross_process_collective(self, two_process_result):
        # sum over a dp-sharded array whose halves live in different
        # processes: 0+1+...+7
        assert two_process_result["psum"] == 28.0

    def test_sgd_matches_single_process_oracle(self, two_process_result):
        from tensorframes_tpu.parallel import ShardedSGDTrainer, make_mesh

        mesh = make_mesh({"dp": 4, "tp": 2})
        trainer = ShardedSGDTrainer([8, 16, 4], mesh=mesh, lr=0.1)
        rng = np.random.default_rng(7)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.integers(0, 4, size=(32,)).astype(np.int32)
        _, oracle = trainer.fit(x, y, steps=5, seed=3)
        np.testing.assert_allclose(
            two_process_result["losses"], oracle, rtol=1e-5, atol=1e-6
        )


class TestLocalRowsHelper:
    def test_single_process_full_range(self):
        from tensorframes_tpu.parallel import multihost

        assert multihost.local_rows(10) == slice(0, 10)

    def test_uneven_split_rejected_two_process(self, two_process_result):
        # exercised inside the 2-process worker, where 33 % 2 != 0
        assert two_process_result["uneven_rejected"] is True

    def test_dataframe_ops_over_processes(self, two_process_result):
        # process 0 held rows 0..23 of arange(48); its map result must be
        # exactly its local slice transformed, and the reduce must see the
        # GLOBAL table (both processes' rows)
        data = np.arange(48, dtype=np.float32)
        np.testing.assert_allclose(
            two_process_result["local_z"], (data[:24] * 2.0 + 1.0).tolist()
        )
        assert two_process_result["global_sum"] == float(data.sum())


class TestMultihostOpValidation:
    """Single-process checks of the multihost op pre-flight contract (the
    collective paths themselves run in the two-process fixture)."""

    def test_output_collision_rejected(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.engine.validation import OutputCollisionError
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns({"x": np.arange(8.0)})
        with pytest.raises(OutputCollisionError):
            multihost.map_blocks(
                lambda x: {"x": x * 2.0}, df, make_mesh({"dp": 8})
            )

    def test_scalar_output_rejected(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.engine.validation import InvalidDimensionError
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns({"x": np.arange(8.0)})
        with pytest.raises(InvalidDimensionError, match="reduce_blocks"):
            multihost.map_blocks(
                lambda x: {"z": x.sum()}, df, make_mesh({"dp": 8})
            )

    def test_chained_maps_reuse_global_arrays(self):
        # chained multihost ops feed the registered globally-sharded result
        # (no host round-trip): the intermediate frame stays lazy and the
        # second op's feed IS the first op's output array
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x * 2.0}, df, mesh)
        assert m1.is_lazy
        m2 = multihost.map_blocks(lambda y: {"z": y + 1.0}, m1, mesh)
        assert m1.is_lazy, "chaining must not materialize the parent"
        assert m2._mh_global["y"][1] is m1._mh_global["y"][1]
        rows = m2.collect()
        np.testing.assert_allclose(
            [r.z for r in rows], np.arange(16.0) * 2.0 + 1.0
        )
        np.testing.assert_allclose(
            [r.y for r in rows], np.arange(16.0) * 2.0
        )
        np.testing.assert_allclose([r.x for r in rows], np.arange(16.0))

    def test_reduce_after_map_keeps_map_lazy(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x + 1.0}, df, mesh)
        total = multihost.reduce_blocks(
            lambda y_input: {"y": y_input.sum()}, m1, mesh
        )
        assert m1.is_lazy, "reduce must feed the registered global array"
        assert float(total) == float((np.arange(16.0) + 1.0).sum())

    def test_chain_on_input_column_stays_lazy(self):
        # binding the parent's ORIGINAL input column (not a fetch) must
        # also avoid forcing the parent: the input feed is referenced in
        # the child registry when under the cache budget
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x * 2.0}, df, mesh)
        m2 = multihost.map_blocks(lambda x: {"w": x + 5.0}, m1, mesh)
        assert m1.is_lazy, "chaining on an input column forced the parent"
        np.testing.assert_allclose(
            [r.w for r in m2.collect()], np.arange(16.0) + 5.0
        )

    def test_over_budget_feed_is_transient(self):
        # columns above device_cache_bytes are assembled per call and not
        # pinned in any cache (HBM stays bounded, like distributed.py)
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost
        from tensorframes_tpu.utils import get_config, set_config

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=8)  # 16 f32 rows = 64 bytes > budget
        try:
            total = multihost.reduce_blocks(
                lambda x_input: {"x": x_input.sum()}, df, mesh
            )
            assert float(total) == float(np.arange(16.0).sum())
            cd = df.column_data("x")
            assert not cd._sharded_cache, "over-budget feed was pinned"
            m1 = multihost.map_blocks(lambda x: {"y": x + 1.0}, df, mesh)
            assert "x" not in (getattr(m1, "_mh_global", None) or {}), (
                "over-budget input feed pinned on the result frame"
            )
            np.testing.assert_allclose(
                [r.y for r in m1.collect()], np.arange(16.0) + 1.0
            )
        finally:
            set_config(device_cache_bytes=old)

    def test_reduce_rows_after_map_keeps_map_lazy(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x + 2.0}, df, mesh)
        total = multihost.reduce_rows(
            lambda y_1, y_2: {"y": y_1 + y_2}, m1, mesh
        )
        assert m1.is_lazy, "reduce_rows must feed the registered array"
        assert float(total) == float((np.arange(16.0) + 2.0).sum())

    def test_unpersist_device_releases_global_registry(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x * 2.0}, df, mesh)
        assert m1._mh_global
        m1.unpersist_device()
        assert getattr(m1, "_mh_global", None) is None
        # data survived the release as host rows; the next multihost op
        # just re-assembles its feed
        np.testing.assert_allclose(
            [r.y for r in m1.collect()], np.arange(16.0) * 2.0
        )
        total = multihost.reduce_blocks(
            lambda y_input: {"y": y_input.sum()}, m1, mesh
        )
        assert float(total) == float((np.arange(16.0) * 2.0).sum())

    def test_map_rows_chains_on_mapped_output(self):
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns(
            {"x": np.arange(16, dtype=np.float32)}
        )
        mesh = make_mesh({"dp": 8})
        m1 = multihost.map_blocks(lambda x: {"y": x * 3.0}, df, mesh)
        m2 = multihost.map_rows(lambda y: {"w": y - 1.0}, m1, mesh)
        assert m1.is_lazy, "row map must answer density from the registry"
        np.testing.assert_allclose(
            [r.w for r in m2.collect()], np.arange(16.0) * 3.0 - 1.0
        )

    def test_multi_axis_mesh_dedups_replica_shards(self):
        # P("dp") output on a dp x tp mesh is replicated over tp;
        # the local-row extraction must not duplicate rows
        import tensorframes_tpu as tft
        from tensorframes_tpu.parallel import make_mesh, multihost

        df = tft.TensorFrame.from_columns({"x": np.arange(8.0)})
        out = multihost.map_blocks(
            lambda x: {"z": x + 1.0}, df, make_mesh({"dp": 4, "tp": 2})
        )
        assert out.num_rows == 8
        np.testing.assert_allclose(
            [r.z for r in out.collect()], np.arange(8.0) + 1.0
        )


# ---------------------------------------------------------------------------
# process-death drill: SIGKILL one process mid-fit, resume from checkpoints
# ---------------------------------------------------------------------------

_WORKER_KILL = r"""
import json, os, signal, sys
import numpy as np
from tensorframes_tpu.parallel import multihost

pid, port = int(sys.argv[1]), sys.argv[2]
ckpt_dir, phase = sys.argv[3], sys.argv[4]
multihost.initialize(
    f"localhost:{port}", num_processes=2, process_id=pid, local_device_count=4
)
import jax
from tensorframes_tpu.parallel import ShardedSGDTrainer, make_mesh

mesh = make_mesh({"dp": 4, "tp": 2})
trainer = ShardedSGDTrainer([8, 16, 4], mesh=mesh, lr=0.1)
rng = np.random.default_rng(7)
x = rng.normal(size=(32, 8)).astype(np.float32)
y = rng.integers(0, 4, size=(32,)).astype(np.int32)
rows = multihost.local_rows(32)

def injected(step, loss):
    # hard process death AFTER the step-4 checkpoint committed: no atexit,
    # no orbax cleanup, exactly what a preempted/OOM-killed host looks like
    if phase == "kill" and pid == 1 and step == 5:
        os.kill(os.getpid(), signal.SIGKILL)

params, losses = trainer.fit(
    x[rows], y[rows], steps=8, seed=3,
    resume=ckpt_dir, checkpoint_every=2, on_step=injected,
)
digest = float(
    sum(float(np.abs(np.asarray(v)).sum()) for v in jax.tree.leaves(params))
)
if pid == 0:
    print("RESULT " + json.dumps(
        {"losses": losses, "digest": digest}
    ), flush=True)
"""


@pytest.mark.slow
class TestProcessDeathDrill:
    """The reference inherited mid-job task retry from Spark (SURVEY §5);
    here the equivalent contract is checkpoint+resume: a 2-process fit
    loses one process to SIGKILL mid-run, a fresh job over the same
    checkpoint directory completes it, and the combined loss trajectory
    matches an uninterrupted single-process oracle."""

    def test_sigkill_then_resume_matches_oracle(self, tmp_path_factory):
        import time

        d = tmp_path_factory.mktemp("mhkill")
        ckpt = str(d / "ckpts")
        worker = d / "worker.py"
        worker.write_text(_WORKER_KILL)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))

        # -- phase 1: run to step 5, process 1 dies by SIGKILL ------------
        port = _free_port()
        procs = [
            subprocess.Popen(
                [sys.executable, str(worker), str(i), str(port), ckpt, "kill"],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                env=env,
                text=True,
            )
            for i in range(2)
        ]
        try:
            deadline = time.monotonic() + 240
            while procs[1].poll() is None and time.monotonic() < deadline:
                time.sleep(0.5)
            assert procs[1].poll() is not None, "victim never died"
            # the victim must have died by the injected SIGKILL, not a bug
            assert procs[1].returncode == -9, procs[1].returncode
            # the survivor is stuck in (or erroring out of) a collective
            # whose peer is gone; give it a moment, then put it down —
            # its fate is not the contract, the checkpoint is
            try:
                procs[0].communicate(timeout=15)
            except subprocess.TimeoutExpired:
                pass
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.communicate()

        # the step-4 checkpoint (checkpoint_every=2; death at step 5) must
        # have committed before the death
        from tensorframes_tpu.utils.checkpoint import CheckpointManager

        mgr = CheckpointManager(ckpt)
        assert mgr.latest_step() == 4
        mgr.close()

        # -- phase 2: a FRESH 2-process job resumes and completes ---------
        outs = _run_workers(
            None, None, None, 2, 4,
            extra_args=(ckpt, "resume"), worker_path=worker,
        )
        line = next(
            l for l in outs[0][0].splitlines() if l.startswith("RESULT ")
        )
        resumed = json.loads(line[len("RESULT "):])
        assert len(resumed["losses"]) == 4  # steps 5..8 only

        # -- oracle: uninterrupted single-process run ---------------------
        from tensorframes_tpu.parallel import ShardedSGDTrainer, make_mesh

        mesh = make_mesh({"dp": 4, "tp": 2})
        trainer = ShardedSGDTrainer([8, 16, 4], mesh=mesh, lr=0.1)
        rng = np.random.default_rng(7)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = rng.integers(0, 4, size=(32,)).astype(np.int32)
        params, oracle = trainer.fit(x, y, steps=8, seed=3)
        np.testing.assert_allclose(
            resumed["losses"], oracle[4:], rtol=1e-5, atol=1e-6
        )
        import jax

        digest = float(
            sum(
                float(np.abs(np.asarray(v)).sum())
                for v in jax.tree.leaves(params)
            )
        )
        np.testing.assert_allclose(resumed["digest"], digest, rtol=1e-5)
