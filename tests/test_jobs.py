"""Durable batch jobs: journaling, crash-resume, poison-block quarantine.

The acceptance bar (ISSUE 4): a kill-and-resume soak whose resumed
output is byte-identical to a clean (unjournaled) run with only
unfinished blocks recomputed (asserted via ``jobs.blocks_total``), and a
poison block that quarantines with the real error instead of failing the
job. Everything here is CPU-only, seeded, and deterministic — the suite
is tier-1 (``make test-durability`` selects just it).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.engine import (
    load_quarantine,
    resume_job,
    run_job,
)
from tensorframes_tpu.engine.jobs import BlockLedger, jobs_status
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import (
    QuarantinedBlocksError,
    chaos,
    get_config,
    seed_backoff_jitter,
    set_config,
)
from tensorframes_tpu.utils.chaos import ChaosFault
from tensorframes_tpu.utils.failures import _backoff_delay, run_with_retries

pytestmark = pytest.mark.durability


@pytest.fixture
def small_chunks():
    old = get_config().max_rows_per_device_call
    set_config(max_rows_per_device_call=16)
    yield
    set_config(max_rows_per_device_call=old)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _counter(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _frame(n=96, width=4, parts=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, width)).astype(np.float32)
    return (
        tft.TensorFrame.from_columns({"x": x}).analyze().repartition(parts)
    )


def _fn(x):
    return {"y": x * 3.0 + 1.0}


def _col(frame, name="y"):
    return np.asarray(frame.column_data(name).host())


# ---------------------------------------------------------------------------


class TestJournalBasics:
    def test_journaled_map_rows_matches_plain(self, tmp_path, small_chunks):
        df = _frame()
        ref = _col(tft.map_rows(_fn, df))
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        assert res.blocks_total == 6  # 96 rows / 16-row chunks
        assert res.blocks_computed == 6 and res.blocks_restored == 0
        assert np.array_equal(_col(res.completed), ref)
        # journal layout on disk
        assert sorted(os.listdir(res.path))[:3] == [
            "blocks", "ledger.jsonl", "manifest.json",
        ]
        manifest = json.loads(
            (tmp_path / res.job_id / "manifest.json").read_text()
        )
        assert manifest["op"] == "map_rows"
        assert len(manifest["plan"]) == 6
        assert len(os.listdir(os.path.join(res.path, "blocks"))) == 6

    def test_resume_of_complete_job_recomputes_nothing(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        before = _counter("jobs.blocks_total", status="computed")
        res2 = resume_job(res.path, _fn, df)
        assert res2.resumed
        assert res2.blocks_computed == 0 and res2.blocks_restored == 6
        assert _counter("jobs.blocks_total", status="computed") == before
        assert np.array_equal(_col(res2.completed), _col(res.completed))

    def test_unjournaled_mode_writes_nothing(self, tmp_path, small_chunks):
        df = _frame()
        res = run_job(
            "map_rows", _fn, df, job_dir=str(tmp_path), journal=False
        )
        assert res.path is None
        assert os.listdir(tmp_path) == []
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    def test_map_blocks_and_reduce_and_aggregate_jobs(self, tmp_path):
        df = _frame()
        bres = run_job("map_blocks", _fn, df, job_dir=str(tmp_path))
        assert bres.blocks_total == 3  # one per partition
        assert np.array_equal(_col(bres.completed), _col(tft.map_blocks(_fn, df)))

        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        rres = run_job("reduce_blocks", red, df, job_dir=str(tmp_path))
        assert np.allclose(rres.completed, tft.reduce_blocks(red, df))
        rres2 = resume_job(rres.path, red, df)
        assert rres2.blocks_computed == 0 and rres2.blocks_restored == 3
        assert np.allclose(rres2.completed, rres.completed)

        keys = (np.arange(96) % 5).astype(np.int64)
        adf = tft.TensorFrame.from_columns(
            {"k": keys, "x": np.arange(96, dtype=np.float32)}
        ).analyze()
        agg = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        ares = run_job(
            "aggregate", agg, adf.group_by("k"), job_dir=str(tmp_path)
        )
        aref = tft.aggregate(agg, adf.group_by("k"))
        assert np.array_equal(
            _col(ares.completed, "x"), _col(aref, "x")
        )
        ares2 = resume_job(ares.path, agg, adf.group_by("k"))
        assert ares2.blocks_restored == 1 and ares2.blocks_computed == 0
        assert np.array_equal(_col(ares2.completed, "x"), _col(aref, "x"))

    def test_binary_key_aggregate_journal_round_trip(self, tmp_path):
        keys = [b"a", b"b", b"a", b"c", b"b", b"a"] * 4
        df = tft.TensorFrame.from_columns(
            {"k": keys, "x": np.arange(24, dtype=np.float32)}
        ).analyze()
        agg = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        aref = tft.aggregate(agg, df.group_by("k"))
        ares = run_job("aggregate", agg, df.group_by("k"), job_dir=str(tmp_path))
        ares2 = resume_job(ares.path, agg, df.group_by("k"))
        assert ares2.blocks_restored == 1
        for got in (ares.completed, ares2.completed):
            assert list(got.column_data("k").iter_cells()) == list(
                aref.column_data("k").iter_cells()
            )
            assert np.array_equal(_col(got, "x"), _col(aref, "x"))

    def test_ragged_bucketed_map_rows_journal(self, tmp_path, small_chunks):
        # ragged cells bucket by shape: the journaled plan must walk the
        # buckets in first-appearance order and resume byte-identically
        rng = np.random.default_rng(3)
        cells = [
            rng.normal(size=(3 + (i % 2),)).astype(np.float32)
            for i in range(48)
        ]
        df = tft.TensorFrame.from_columns({"v": cells}).analyze()
        fn = lambda v: {"s": v.sum()}  # noqa: E731
        ref = _col(tft.map_rows(fn, df), "s")
        res = run_job("map_rows", fn, df, job_dir=str(tmp_path))
        assert res.blocks_total == 4  # 2 buckets x 24 rows / 16-row chunks
        assert np.array_equal(_col(res.completed, "s"), ref)
        res2 = resume_job(res.path, fn, df)
        assert res2.blocks_computed == 0 and res2.blocks_restored == 4
        assert np.array_equal(_col(res2.completed, "s"), ref)

    @pytest.mark.chaos
    def test_ragged_quarantine_drops_the_bucket_chunk_rows(
        self, tmp_path, small_chunks
    ):
        rng = np.random.default_rng(3)
        cells = [
            rng.normal(size=(3 + (i % 2),)).astype(np.float32)
            for i in range(48)
        ]
        df = tft.TensorFrame.from_columns({"v": cells}).analyze()
        fn = lambda v: {"s": v.sum()}  # noqa: E731
        ref = _col(tft.map_rows(fn, df), "s")
        with chaos.scoped("jobs.block=fatal:every=2:times=1"):
            res = run_job("map_rows", fn, df, job_dir=str(tmp_path))
        assert [b.index for b in res.quarantined] == [1]
        # block 1 = rows 32..46 step 2 of bucket 0 (even rows, shape [3])
        dropped = set(range(32, 48, 2))
        keep = [i for i in range(48) if i not in dropped]
        assert np.array_equal(_col(res.completed, "s"), ref[keep])
        got_cells = list(res.completed.column_data("v").iter_cells())
        assert all(
            np.array_equal(a, cells[i]) for a, i in zip(got_cells, keep)
        )

    def test_resume_rejects_a_different_job(self, tmp_path, small_chunks):
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        other = _frame(n=80, parts=2, seed=1)
        with pytest.raises(ValueError, match="fingerprint|block plan"):
            resume_job(res.path, _fn, other)

    def test_aggregate_resume_rejects_a_different_program(self, tmp_path):
        keys = (np.arange(24) % 3).astype(np.int64)
        df = tft.TensorFrame.from_columns(
            {"k": keys, "x": np.arange(24, dtype=np.float32)}
        ).analyze()
        res = run_job(
            "aggregate",
            lambda x_input: {"x": x_input.sum()},
            df.group_by("k"),
            job_dir=str(tmp_path),
        )
        with pytest.raises(ValueError, match="fingerprint"):
            resume_job(
                res.path,
                lambda x_input: {"other": x_input.min()},
                df.group_by("k"),
            )

    def test_fetch_named_file_spools_fine(self, tmp_path, small_chunks):
        # "file" is an np.savez parameter name; the spool must not care
        df = _frame()
        fn = lambda x: {"file": x * 2.0}  # noqa: E731
        res = run_job("map_rows", fn, df, job_dir=str(tmp_path))
        assert not res.quarantined
        res2 = resume_job(res.path, fn, df)
        assert res2.blocks_restored == 6
        assert np.array_equal(
            _col(res2.completed, "file"), _col(tft.map_rows(fn, df), "file")
        )

    def test_fresh_job_refuses_an_occupied_directory(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        run_job("map_rows", _fn, df, job_dir=str(tmp_path), job_id="j1")
        with pytest.raises(ValueError, match="already holds"):
            run_job("map_rows", _fn, df, job_dir=str(tmp_path), job_id="j1")


# ---------------------------------------------------------------------------


class TestCrashResume:
    @pytest.mark.chaos
    def test_kill_and_resume_soak_byte_identical(
        self, tmp_path, small_chunks
    ):
        """The acceptance soak: a journaled map_rows job is killed (chaos
        ``fatal`` inside the journal-write path — after the block
        computed, before its record landed) after every k-th write,
        resumed, and killed again until it completes. The final output
        must be byte-identical to an unjournaled run, and each attempt
        must recompute only blocks without completion records."""
        df = _frame(n=128, parts=4)  # 8 blocks of 16
        ref = _col(tft.map_rows(_fn, df))
        path = str(tmp_path / "soak")
        k = 3
        res = None
        attempts = 0
        recorded_before = 0
        while res is None:
            attempts += 1
            assert attempts < 20, "soak failed to converge"
            c0 = _counter("jobs.blocks_total", status="computed")
            r0 = _counter("jobs.blocks_total", status="restored")
            try:
                with chaos.scoped(
                    f"seed=7;jobs.journal_write=fatal:every={k}:times=1"
                ):
                    if attempts == 1:
                        res = run_job(
                            "map_rows", _fn, df,
                            job_dir=str(tmp_path), job_id="soak",
                        )
                    else:
                        res = resume_job(path, _fn, df)
            except ChaosFault:
                res = None
            restored = _counter("jobs.blocks_total", status="restored") - r0
            computed = _counter("jobs.blocks_total", status="computed") - c0
            # every attempt restores exactly what previous attempts
            # recorded, and computes only the rest — never a redo of a
            # journaled block
            assert restored == recorded_before
            assert computed <= 8 - recorded_before
            recorded_before += computed
        assert res.blocks_total == 8
        assert attempts > 2, "the kill schedule never fired"
        assert np.array_equal(_col(res.completed), ref)
        # partition structure survives the journal round-trip
        assert res.completed.num_partitions == df.num_partitions

    @pytest.mark.chaos
    def test_transient_journal_write_failures_retry(
        self, tmp_path, small_chunks, fast_retries
    ):
        df = _frame()
        with chaos.scoped("jobs.journal_write=transient:every=2"):
            res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        assert res.blocks_computed == 6 and not res.quarantined
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    def test_cross_process_crash_then_resume(self, tmp_path):
        """A REAL process death: a child runs the journaled job with a
        chaos kill in the journal-write path and exits nonzero; this
        process then resumes from the on-disk journal alone."""
        job_dir = str(tmp_path)
        script = (
            "import numpy as np, tensorframes_tpu as tft\n"
            "from tensorframes_tpu.engine import run_job\n"
            "from tensorframes_tpu.utils import set_config\n"
            "set_config(max_rows_per_device_call=16)\n"
            "x = np.arange(384, dtype=np.float32).reshape(96, 4)\n"
            "df = tft.TensorFrame.from_columns({'x': x}).analyze()"
            ".repartition(3)\n"
            "run_job('map_rows', lambda x: {'y': x * 3.0 + 1.0}, df,\n"
            f"        job_dir={job_dir!r}, job_id='child')\n"
        )
        env = dict(
            os.environ,
            JAX_PLATFORMS="cpu",
            TFT_CHAOS="jobs.journal_write=fatal:every=4:times=1",
        )
        proc = subprocess.run(
            [sys.executable, "-c", script],
            env=env, capture_output=True, text=True, timeout=180,
        )
        assert proc.returncode != 0, proc.stdout + proc.stderr
        assert "ChaosFault" in proc.stderr
        path = os.path.join(job_dir, "child")
        assert os.path.exists(os.path.join(path, "manifest.json"))
        # resume in THIS process from disk state only
        old = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            x = np.arange(384, dtype=np.float32).reshape(96, 4)
            df = (
                tft.TensorFrame.from_columns({"x": x})
                .analyze().repartition(3)
            )
            res = resume_job(path, _fn, df)
            assert res.blocks_restored >= 1, "child recorded nothing"
            assert res.blocks_restored + res.blocks_computed == 6
            assert np.array_equal(
                _col(res.completed), _col(tft.map_rows(_fn, df))
            )
        finally:
            set_config(max_rows_per_device_call=old)

    def test_torn_ledger_tail_is_ignored(self, tmp_path, small_chunks):
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        ledger_path = os.path.join(res.path, "ledger.jsonl")
        with open(ledger_path, "ab") as f:
            f.write(b'{"block": 99, "status": "do')  # torn append
        led = BlockLedger.open_(res.path)
        assert led.num_blocks == 6
        res2 = resume_job(res.path, _fn, df)
        assert np.array_equal(_col(res2.completed), _col(res.completed))

    def test_missing_spool_recomputes_that_block(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        os.remove(os.path.join(res.path, "blocks", "block-00002.npz"))
        res2 = resume_job(res.path, _fn, df)
        assert res2.blocks_computed == 1 and res2.blocks_restored == 5
        assert np.array_equal(_col(res2.completed), _col(res.completed))

    @pytest.mark.chaos
    def test_resume_never_reuploads_completed_blocks(
        self, tmp_path, small_chunks
    ):
        """Block plans align with transfer chunks and feeds cross the
        link per block (``frame/transfer.py``), so a resume's
        ``frame.h2d_bytes_total`` delta is EXACTLY the unfinished
        blocks' input bytes — journaled blocks restore from their npz
        spools without touching the link."""
        df = _frame()  # 96 rows x 4 f32 -> 6 blocks of 16 at the cap
        block_bytes = 16 * 4 * 4
        path = str(tmp_path / "noreup")
        with chaos.scoped("jobs.journal_write=fatal:every=3:times=1"):
            with pytest.raises(ChaosFault):
                run_job(
                    "map_rows", _fn, df,
                    job_dir=str(tmp_path), job_id="noreup",
                )
        recorded = len(
            [
                ln
                for ln in open(os.path.join(path, "ledger.jsonl"))
                if '"done"' in ln
            ]
        )
        assert 0 < recorded < 6, "the kill left a partial journal"
        h0 = _counter("frame.h2d_bytes_total")
        res = resume_job(path, _fn, df)
        uploaded = _counter("frame.h2d_bytes_total") - h0
        assert res.blocks_restored == recorded
        assert res.blocks_computed == 6 - recorded
        assert uploaded == (6 - recorded) * block_bytes
        assert uploaded < df.num_rows * 4 * 4  # never the whole column
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    @pytest.mark.chaos
    def test_resume_survives_transfer_knob_retune(
        self, tmp_path, small_chunks
    ):
        """The dense block plan is rebuilt from the journal's manifest
        on resume, so retuning transfer_chunk_bytes (the knob
        docs/ingest.md tells operators to tune) between a crash and its
        resume must restore completed blocks, not reject the journal."""
        df = _frame()
        path = str(tmp_path / "retune")
        with chaos.scoped("jobs.journal_write=fatal:every=3:times=1"):
            with pytest.raises(ChaosFault):
                run_job(
                    "map_rows", _fn, df,
                    job_dir=str(tmp_path), job_id="retune",
                )
        old = get_config().transfer_chunk_bytes
        set_config(transfer_chunk_bytes=64)  # would re-plan 4-row blocks
        try:
            res = resume_job(path, _fn, df)
        finally:
            set_config(transfer_chunk_bytes=old)
        assert res.blocks_total == 6  # the journaled 16-row plan held
        assert res.blocks_restored > 0
        assert np.array_equal(_col(res.completed), _col(tft.map_rows(_fn, df)))

    def test_plan_aligns_with_transfer_chunks(self, tmp_path):
        """A journal block never spans transfer chunks: with a 128-byte
        chunk over 16-byte rows, the plan caps blocks at 8 rows even
        though the device-call cap allows far more."""
        old = (
            get_config().transfer_chunk_bytes,
            get_config().max_rows_per_device_call,
        )
        set_config(transfer_chunk_bytes=128, max_rows_per_device_call=8192)
        try:
            df = _frame()  # 96 rows x 4 f32 = 16 B/row
            res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
            assert res.blocks_total == 12  # 96 rows / 8-row chunks
            assert np.array_equal(
                _col(res.completed), _col(tft.map_rows(_fn, df))
            )
        finally:
            set_config(
                transfer_chunk_bytes=old[0],
                max_rows_per_device_call=old[1],
            )


# ---------------------------------------------------------------------------


class TestQuarantine:
    @pytest.mark.chaos
    def test_poison_block_quarantines_with_the_real_error(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        ref = _col(tft.map_rows(_fn, df))
        q0 = _counter("jobs.quarantined_total")
        with chaos.scoped("jobs.block=fatal:every=3:times=1"):
            res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        assert len(res.quarantined) == 1
        qb = res.quarantined[0]
        assert qb.index == 2 and qb.rows == 16
        assert qb.error_type == "ChaosFault"
        assert "chaos-injected fatal" in qb.error
        assert _counter("jobs.quarantined_total") == q0 + 1
        # partial result: the poisoned block's rows are gone, the rest
        # are byte-identical and stay aligned with the carried column
        assert res.completed.num_rows == 96 - 16
        keep = np.r_[0:32, 48:96]
        assert np.array_equal(_col(res.completed), ref[keep])
        assert np.array_equal(
            _col(res.completed, "x"),
            np.asarray(df.column_data("x").host())[keep],
        )

    @pytest.mark.chaos
    def test_quarantine_manifest_round_trip(self, tmp_path, small_chunks):
        df = _frame()
        with chaos.scoped("jobs.block=fatal:every=3:times=1"):
            res = run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        blocks = load_quarantine(res.path)
        assert [(b.index, b.error_type) for b in blocks] == [
            (2, "ChaosFault")
        ]
        assert "chaos-injected fatal" in blocks[0].error
        assert blocks[0].traceback  # the real traceback is preserved
        # resume without retry keeps the quarantine and recomputes nothing
        res2 = resume_job(res.path, _fn, df)
        assert len(res2.quarantined) == 1 and res2.blocks_computed == 0
        # retry_quarantined re-attempts the poisoned block (now healthy)
        res3 = resume_job(res.path, _fn, df, retry_quarantined=True)
        assert not res3.quarantined and res3.blocks_computed == 1
        assert np.array_equal(_col(res3.completed), _col(tft.map_rows(_fn, df)))
        assert load_quarantine(res.path) == []

    @pytest.mark.chaos
    def test_strict_mode_raises_quarantined_blocks_error(
        self, tmp_path, small_chunks
    ):
        df = _frame()
        with chaos.scoped("jobs.block=fatal:every=3:times=1"):
            with pytest.raises(QuarantinedBlocksError) as ei:
                run_job(
                    "map_rows", _fn, df, job_dir=str(tmp_path),
                    job_id="strict", strict=True,
                )
        assert [b.index for b in ei.value.blocks] == [2]
        # healthy blocks journaled before the raise: a retry resume
        # completes with ONE recompute (the poison, healthy now)
        res = resume_job(
            str(tmp_path / "strict"), _fn, df, retry_quarantined=True
        )
        assert res.blocks_computed == 1 and res.blocks_restored == 5

    def test_config_strict_default(self, tmp_path, small_chunks):
        old = get_config().quarantine_blocks
        set_config(quarantine_blocks=False)
        try:
            df = _frame()
            with chaos.scoped("jobs.block=fatal:every=3:times=1"):
                with pytest.raises(QuarantinedBlocksError):
                    run_job("map_rows", _fn, df, job_dir=str(tmp_path))
        finally:
            set_config(quarantine_blocks=old)

    @pytest.mark.chaos
    def test_map_blocks_quarantine_keeps_alignment(self, tmp_path):
        df = _frame()
        ref = _col(tft.map_blocks(_fn, df))
        with chaos.scoped("jobs.block=fatal:every=2:times=1"):
            res = run_job("map_blocks", _fn, df, job_dir=str(tmp_path))
        assert [b.index for b in res.quarantined] == [1]
        keep = np.r_[0:32, 64:96]  # partition 1 of 3 dropped
        assert np.array_equal(_col(res.completed), ref[keep])
        assert np.array_equal(
            _col(res.completed, "x"),
            np.asarray(df.column_data("x").host())[keep],
        )
        assert res.completed.num_partitions == 3  # structure kept, 0 rows

    @pytest.mark.chaos
    def test_reduce_blocks_quarantine_folds_survivors(self, tmp_path):
        x = np.arange(90, dtype=np.float64)
        df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(3)
        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        with chaos.scoped("jobs.block=fatal:every=2:times=1"):
            res = run_job("reduce_blocks", red, df, job_dir=str(tmp_path))
        assert [b.index for b in res.quarantined] == [1]
        # partitions 0 and 2 survive: rows 0..29 and 60..89
        assert np.allclose(
            res.completed, x[:30].sum() + x[60:].sum()
        )

    @pytest.mark.chaos
    def test_all_blocks_quarantined_yields_none(self, tmp_path):
        x = np.arange(30, dtype=np.float64)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        with chaos.scoped("jobs.block=fatal"):
            res = run_job("reduce_blocks", red, df, job_dir=str(tmp_path))
        assert res.completed is None
        assert len(res.quarantined) == 1

    @pytest.mark.chaos
    def test_transient_and_oom_failures_are_never_quarantined(
        self, tmp_path, small_chunks, fast_retries
    ):
        df = _frame()
        # a transient that outlives the retry budget fails the JOB
        # (resumable), it does not poison the block
        with chaos.scoped("jobs.block=transient"):
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                run_job(
                    "map_rows", _fn, df,
                    job_dir=str(tmp_path), job_id="transient-job",
                )
        assert load_quarantine(str(tmp_path / "transient-job")) == []


# ---------------------------------------------------------------------------


class TestReduceOomDegrade:
    @pytest.mark.chaos
    def test_streaming_partial_halves_on_oom(self, fast_retries):
        x = np.arange(64, dtype=np.float64)
        df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(2)
        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        clean = tft.reduce_blocks(red, df)
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=64)  # force the streaming path
        before = _counter("failures.oom_splits_total", op="reduce_blocks")
        try:
            with chaos.scoped("engine.dispatch=oom:times=1"):
                got = tft.reduce_blocks(red, df)
        finally:
            set_config(device_cache_bytes=old)
        assert np.allclose(got, clean)
        assert (
            _counter("failures.oom_splits_total", op="reduce_blocks")
            == before + 1
        )

    @pytest.mark.chaos
    def test_grouped_dispatch_oom_falls_back_per_partition(
        self, fast_retries
    ):
        x = np.arange(64, dtype=np.float64)
        df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(4)
        red = lambda x_input: {"x": x_input.sum()}  # noqa: E731
        clean = tft.reduce_blocks(red, df)
        with chaos.scoped("engine.dispatch=oom:times=1"):
            got = tft.reduce_blocks(red, df)
        assert np.allclose(got, clean)


# ---------------------------------------------------------------------------


class TestBackoffJitter:
    def test_full_jitter_bounded_and_seeded(self):
        seed_backoff_jitter(13)
        d1 = [_backoff_delay(a, base=0.5) for a in range(6)]
        seed_backoff_jitter(13)
        d2 = [_backoff_delay(a, base=0.5) for a in range(6)]
        assert d1 == d2  # seeded -> reproducible
        for a, d in enumerate(d1):
            cap = 0.5 * 2.0 ** a
            assert 0.0 < d <= cap
        # jitter actually jitters: the sequence is not the deterministic
        # lockstep schedule base * 2**n
        assert any(
            abs(d - 0.5 * 2.0 ** a) > 1e-9 for a, d in enumerate(d1)
        )
        seed_backoff_jitter(None)

    def test_retry_sleeps_use_jitter(self, fast_retries, monkeypatch):
        import tensorframes_tpu.utils.failures as failures

        slept = []
        monkeypatch.setattr(failures.time, "sleep", slept.append)
        seed_backoff_jitter(7)
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 4:
                raise RuntimeError("UNAVAILABLE: tunnel dropped")
            return 1

        assert run_with_retries(flaky) == 1
        assert len(slept) == 3
        for a, d in enumerate(slept):
            assert 0.0 < d <= 0.001 * 2.0 ** a
        seed_backoff_jitter(None)


# ---------------------------------------------------------------------------


class TestHealthzJobs:
    def test_healthz_reports_job_status(self):
        import urllib.request

        from tensorframes_tpu.interop.serving import ScoringServer

        df = _frame(n=16, parts=1)
        run_job("map_rows", _fn, df, journal=False)
        status = jobs_status()
        assert status["runs_total"] >= 1
        assert status["last"]["state"] == "complete"
        with ScoringServer(lambda x: {"y": x * 2.0}) as addr:
            with urllib.request.urlopen(
                f"http://{addr}/healthz", timeout=10
            ) as r:
                payload = json.loads(r.read())
        assert payload["healthy"] is True
        jobs = payload["jobs"]
        assert jobs["runs_total"] >= 1
        assert jobs["last"]["op"] == "map_rows"
        assert jobs["last"]["blocks_computed"] >= 1
