"""Self-tuning performance layer (``tensorframes_tpu/tune``, ISSUE 13).

Covers the three pieces and their wiring:

- **store** (``tune/store.py``): atomic-rename durability — concurrent
  two-process winner writes, ``kill -9`` mid-write → clean re-read,
  schema-version mismatch → ignore-and-retune, corrupt-line tolerance,
  cross-process mtime re-read;
- **model** (``tune/model.py``): the ridge fit recovers synthetic
  weights, thin data falls back to the analytic prior, ranking orders
  by predicted cost;
- **search** (``tune/search.py``): online tuning installs + persists a
  median-wall winner, the learned ranker prunes trials to ≤ half the
  grid, budgets degrade to the default, trials retry under chaos and
  skip on fatal faults, the ``tune.trial`` chaos site is a first-class
  dispatch site;
- **byte-identity** (the acceptance contract): for every tuned surface
  — flash tiles, transfer chunking, map-rows block rows, serve page
  size + prefill chunk — results with autotune on (pinned or online,
  incl. under chaos and a mid-trial process kill) are byte-identical
  to ``TFT_TUNE=0``;
- **persistence round-trip**: a winner tuned by a REAL subprocess is
  served in this process with zero trials (asserted on the tuner's own
  counters);
- satellites: the ``paged_page_size_hint`` serving default + /healthz
  report, the ``bench_check`` gate pinning ``TFT_TUNE=0``, /statusz +
  /varz export, ``explain(analyze=True)``.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import tune
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.tune.model import CostModel
from tensorframes_tpu.tune.store import SCHEMA_VERSION, TuneStore
from tensorframes_tpu.utils import get_config, set_config

pytestmark = pytest.mark.tune

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=48)


_TUNE_FIELDS = (
    "autotune", "tune_mode", "tune_budget_s", "tune_trials",
    "tune_top_k", "tune_file", "max_rows_per_device_call",
    "max_retries", "retry_backoff_s", "chaos",
)


@pytest.fixture
def tune_env(tmp_path, monkeypatch):
    """A per-test tuning world: private store file, fresh tuner, config
    restored afterwards. Yields the store path."""
    store = str(tmp_path / "tune.jsonl")
    monkeypatch.setenv("TFT_TUNE_FILE", store)
    monkeypatch.delenv("TFT_TUNE", raising=False)
    prev = {f: getattr(get_config(), f) for f in _TUNE_FIELDS}
    tune.reset()
    yield store
    set_config(**prev)
    tune.reset()


def _totals(name):
    snap = obs_metrics.snapshot().get(name, {})
    return float(sum((snap.get("values") or {}).values()))


def _err_hist_count():
    s = obs_metrics.registry().get("tune.predicted_error_ratio").series()
    return 0 if s is None else s["count"]


# ---------------------------------------------------------------------------
# store units
# ---------------------------------------------------------------------------


class TestStore:
    def test_put_get_roundtrip_atomic(self, tune_env):
        s = TuneStore(tune_env)
        rec = s.put(
            "surf|sig=1|dev", {"rows": 7}, wall_s=0.5, meta={"trials": 2}
        )
        assert rec["v"] == SCHEMA_VERSION
        got = s.get("surf|sig=1|dev")
        assert got["config"] == {"rows": 7}
        assert got["surface"] == "surf" and got["device"] == "dev"
        # atomic rename: the target parses, and no temp litter remains
        with open(tune_env) as f:
            for line in f:
                json.loads(line)
        litter = [
            n for n in os.listdir(os.path.dirname(tune_env))
            if n.endswith(".tmp")
        ]
        assert litter == []

    def test_last_write_wins_per_key(self, tune_env):
        s = TuneStore(tune_env)
        s.put("a|b|c", {"n": 1})
        s.put("a|b|c", {"n": 2})
        assert s.get("a|b|c")["config"] == {"n": 2}
        assert len(s.entries()) == 1

    def test_corrupt_lines_are_tolerated(self, tune_env):
        s = TuneStore(tune_env)
        s.put("good|sig|dev", {"n": 1})
        with open(tune_env, "a") as f:
            f.write("{torn json!!\n")
            f.write('"not a dict"\n')
        s2 = TuneStore(tune_env)
        assert s2.get("good|sig|dev")["config"] == {"n": 1}
        assert len(s2.entries()) == 1

    def test_schema_version_mismatch_is_ignored(self, tune_env):
        s = TuneStore(tune_env)
        with open(tune_env, "w") as f:
            f.write(
                json.dumps(
                    {
                        "v": SCHEMA_VERSION + 1,
                        "key": "old|sig|dev",
                        "config": {"n": 99},
                    }
                )
                + "\n"
            )
        # ignore-and-retune: the record is invisible, not an error
        assert s.get("old|sig|dev") is None
        # a put keeps the file valid JSONL AND carries the
        # foreign-version line through verbatim — a mixed-version fleet
        # sharing one store must never erase each other's winners
        s.put("new|sig|dev", {"n": 1})
        assert s.get("new|sig|dev")["config"] == {"n": 1}
        with open(tune_env) as f:
            recs = [json.loads(ln) for ln in f if ln.strip()]
        assert any(r.get("v") == SCHEMA_VERSION + 1 for r in recs)
        assert any(r.get("v") == SCHEMA_VERSION for r in recs)

    def test_cross_process_staleness_mtime_reread(self, tune_env):
        writer = TuneStore(tune_env)
        reader = TuneStore(tune_env)
        assert reader.get("k|s|d") is None
        writer.put("k|s|d", {"n": 1})
        # distinct instance, no shared state: the mtime re-read makes
        # process A's winner visible at B's next lookup
        assert reader.get("k|s|d")["config"] == {"n": 1}
        time.sleep(0.01)  # ensure the mtime moves even on coarse clocks
        writer.put("k|s|d", {"n": 2})
        assert reader.get("k|s|d")["config"] == {"n": 2}

    def test_clear_by_surface(self, tune_env):
        s = TuneStore(tune_env)
        s.put("a|s1|d", {"n": 1})
        s.put("b|s2|d", {"n": 2})
        assert s.clear("a") == 1
        assert s.get("a|s1|d") is None
        assert s.get("b|s2|d")["config"] == {"n": 2}
        assert s.clear() == 1
        assert s.entries() == {}


# ---------------------------------------------------------------------------
# store subprocess drills (patterns from tests/test_dist_jobs.py)
# ---------------------------------------------------------------------------

_WRITER_SCRIPT = r"""
import sys, time
from tensorframes_tpu.tune.store import TuneStore

path, tag = sys.argv[1:3]
s = TuneStore(path)
end = time.time() + 0.8
i = 0
while time.time() < end:
    for j in range(5):
        s.put(f"surf{tag}|k{j}|dev", {"writer": tag, "iter": i, "j": j})
    i += 1
print("W_DONE", tag, i, flush=True)
"""

_KILL_WRITER_SCRIPT = r"""
import sys
from tensorframes_tpu.tune.store import TuneStore

s = TuneStore(sys.argv[1])
print("WRITING", flush=True)
i = 0
while True:
    s.put("kill|sig|dev", {"n": i})
    i += 1
"""


def _env(**extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("TFT_CHAOS", None)
    env.update(extra)
    return env


class TestStoreProcesses:
    def test_concurrent_two_process_writes_no_torn_jsonl(self, tune_env):
        """Two real processes hammer the same store concurrently: the
        file must ALWAYS parse (atomic rename — no torn line can ever
        land), every surviving record must be something a writer
        actually wrote (last-complete-wins, never a splice), and
        neither writer may crash."""
        procs = [
            subprocess.Popen(
                [sys.executable, "-c", _WRITER_SCRIPT, tune_env, tag],
                env=_env(), stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
            )
            for tag in ("1", "2")
        ]
        for p in procs:
            out, err = p.communicate(timeout=120)
            assert p.returncode == 0, err
            assert "W_DONE" in out
        with open(tune_env) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
        assert lines, "both writers ran and nothing survived"
        entries = {}
        for ln in lines:
            rec = json.loads(ln)  # no torn JSONL, ever
            assert rec["v"] == SCHEMA_VERSION
            assert rec["surface"] in ("surf1", "surf2")
            cfg = rec["config"]
            assert cfg["writer"] in ("1", "2")
            assert rec["key"] == (
                f"surf{cfg['writer']}|k{cfg['j']}|dev"
            )
            entries[rec["key"]] = rec
        # the store reads it back cleanly too
        s = TuneStore(tune_env)
        assert set(s.entries()) == set(entries)

    def test_kill9_mid_write_clean_reread(self, tune_env):
        """A writer SIGKILLed while rewriting the store must leave a
        readable file: the rename either happened (previous complete
        state) or it did not (the one before) — never a torn tail."""
        p = subprocess.Popen(
            [sys.executable, "-c", _KILL_WRITER_SCRIPT, tune_env],
            env=_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True,
        )
        try:
            assert "WRITING" in p.stdout.readline()
            time.sleep(0.15)  # let some writes land, then murder it
            p.send_signal(signal.SIGKILL)
            assert p.wait(timeout=30) == -signal.SIGKILL
        finally:
            if p.poll() is None:
                p.kill()
        s = TuneStore(tune_env)
        entries = s.entries()  # parses — or the contract is broken
        rec = s.get("kill|sig|dev")
        if rec is not None:  # the kill may have landed before write 0
            assert isinstance(rec["config"]["n"], int)
        for r in entries.values():
            assert r["v"] == SCHEMA_VERSION


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------


class TestCostModel:
    def test_ridge_fit_recovers_synthetic_weights(self):
        rng = np.random.default_rng(0)
        w_f, w_b, w_0 = 2e-11, 5e-10, 1e-4
        records = []
        for _ in range(64):
            flops = float(rng.uniform(1e6, 1e9))
            nbytes = float(rng.uniform(1e5, 1e8))
            wall = w_f * flops + w_b * nbytes + w_0
            records.append(
                {
                    "flops": flops, "bytes": nbytes,
                    "dispatches": 10, "dispatch_s": wall * 10,
                }
            )
        m = CostModel.fit(records)
        assert m.source == "ridge"
        for flops, nbytes in ((5e8, 1e7), (1e7, 5e7)):
            truth = w_f * flops + w_b * nbytes + w_0
            assert abs(m.predict(flops, nbytes) - truth) / truth < 0.05

    def test_thin_data_falls_back_to_analytic_prior(self):
        m = CostModel.fit([{"flops": 1.0, "bytes": 1.0,
                            "dispatches": 1, "dispatch_s": 1.0}])
        assert m.source == "analytic"
        assert m.w_flops > 0 and m.w_bytes > 0 and m.w_overhead > 0

    def test_rank_orders_by_predicted_cost(self):
        m = CostModel(1e-12, 1e-10, 1e-4)
        cands = [{"n": n} for n in (1, 4, 2)]

        def feats(c):
            return 0.0, 0.0, float(c["n"])  # cost = overhead * n

        ranked = m.rank(cands, feats)
        assert [c["n"] for c, _ in ranked] == [1, 2, 4]
        # a candidate whose features raise ranks last, not fatally
        def bad_feats(c):
            if c["n"] == 1:
                raise RuntimeError("boom")
            return 0.0, 0.0, float(c["n"])

        ranked = m.rank(cands, bad_feats)
        assert ranked[-1][0]["n"] == 1


# ---------------------------------------------------------------------------
# search semantics
# ---------------------------------------------------------------------------


def _sleep_trial(ms_by_n):
    def trial(cand):
        time.sleep(ms_by_n[cand["n"]] / 1000.0)

    return trial


class TestSearch:
    def test_off_mode_and_kill_switch_return_default(
        self, tune_env, monkeypatch
    ):
        set_config(autotune=False, tune_mode="online")
        calls = []
        out = tune.lookup(
            "t.s", "sig", {"n": 1}, grid=[{"n": 2}],
            trial=lambda c: calls.append(c),
        )
        assert out == {"n": 1} and calls == []
        set_config(autotune=True)
        monkeypatch.setenv("TFT_TUNE", "0")
        out = tune.lookup(
            "t.s", "sig", {"n": 1}, grid=[{"n": 2}],
            trial=lambda c: calls.append(c),
        )
        assert out == {"n": 1} and calls == []
        assert tune.mode() == "off"

    def test_unknown_mode_warns_off(self, tune_env):
        set_config(autotune=True, tune_mode="turbo")
        assert tune.mode() == "off"

    def test_cached_miss_returns_default_without_trials(self, tune_env):
        set_config(autotune=True, tune_mode="cached")
        t0 = _totals("tune.trials_total")
        out = tune.lookup(
            "t.c", "sig", {"n": 1}, grid=[{"n": 2}],
            trial=lambda c: None,
        )
        assert out == {"n": 1}
        assert _totals("tune.trials_total") == t0
        assert not os.path.exists(tune_env) or TuneStore(
            tune_env
        ).entries() == {}

    def test_online_tunes_installs_persists_and_memoizes(self, tune_env):
        set_config(
            autotune=True, tune_mode="online", tune_trials=2,
            tune_budget_s=30.0,
        )
        t0 = _totals("tune.trials_total")
        h0 = _totals("tune.cache_hits_total")
        w0 = _totals("tune.winners_total")
        trial = _sleep_trial({1: 8, 2: 1, 3: 20})
        out = tune.lookup(
            "t.o", "sig", {"n": 1}, grid=[{"n": 2}, {"n": 3}],
            trial=trial,
        )
        assert out == {"n": 2}  # fastest by median wall
        assert _totals("tune.winners_total") == w0 + 1
        trials_used = _totals("tune.trials_total") - t0
        assert 1 <= trials_used <= 3
        # persisted, device-keyed
        rec = TuneStore(tune_env).get(
            f"t.o|sig|{tune.device_kind()}"
        )
        assert rec["config"] == {"n": 2}
        assert rec["meta"]["trials"] == trials_used
        # second lookup: memo hit, zero new trials
        out2 = tune.lookup(
            "t.o", "sig", {"n": 1}, grid=[{"n": 2}, {"n": 3}],
            trial=trial,
        )
        assert out2 == {"n": 2}
        assert _totals("tune.trials_total") - t0 == trials_used
        assert _totals("tune.cache_hits_total") > h0

    def test_learned_ranker_prunes_to_half_grid(self, tune_env):
        """The acceptance criterion: with the predictor, trials per
        signature ≤ half the full grid — and the predicted-vs-measured
        error histogram is populated."""
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_top_k=8, tune_budget_s=60.0,
        )
        grid = [{"n": n} for n in range(2, 9)]  # +default = 8 full

        def feats(c):
            return 0.0, 0.0, float(c["n"])

        t0 = _totals("tune.trials_total")
        e0 = _err_hist_count()
        out = tune.lookup(
            "t.rank", "sig", {"n": 1}, grid=grid, feats=feats,
            trial=lambda c: time.sleep(0.001 * c["n"]),
        )
        trials_used = _totals("tune.trials_total") - t0
        assert trials_used <= (len(grid) + 1) // 2
        assert trials_used >= 1
        assert out["n"] in (1, 2, 3, 4)  # a top-ranked candidate won
        assert _err_hist_count() > e0  # model honesty is a series

    def test_budget_exhaustion_degrades_to_default(self, tune_env):
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=0.0,
        )
        measured = []
        out = tune.lookup(
            "t.budget", "sig", {"n": 1},
            grid=[{"n": 2}, {"n": 3}],
            trial=lambda c: measured.append(c["n"]),
        )
        # only the default fit the (zero) budget; it still wins and is
        # persisted so the next process skips straight to cached
        assert out == {"n": 1}
        assert set(measured) == {1}
        rec = TuneStore(tune_env).get(
            f"t.budget|sig|{tune.device_kind()}"
        )
        assert rec["config"] == {"n": 1}

    def test_failing_candidate_is_skipped(self, tune_env):
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0, max_retries=0,
        )

        def trial(cand):
            if cand["n"] == 2:
                raise RuntimeError("candidate crashes")
            time.sleep(0.001)

        out = tune.lookup(
            "t.fail", "sig", {"n": 1}, grid=[{"n": 2}, {"n": 3}],
            trial=trial,
        )
        assert out["n"] in (1, 3)

    def test_failed_default_trial_never_installs_blind_winner(
        self, tune_env
    ):
        """If the DEFAULT's own trial fails, a candidate that was never
        compared against it must not win — 'degrades to keep the
        default, never a blind winner'."""
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0, max_retries=0,
        )

        def trial(cand):
            if cand["n"] == 1:  # the default
                raise RuntimeError("default trial dies")

        out = tune.lookup(
            "t.blind", "sig", {"n": 1}, grid=[{"n": 2}], trial=trial
        )
        assert out == {"n": 1}
        assert TuneStore(tune_env).get(
            f"t.blind|sig|{tune.device_kind()}"
        ) is None

    def test_all_candidates_failing_keeps_default_stores_nothing(
        self, tune_env
    ):
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0, max_retries=0,
        )

        def trial(cand):
            raise RuntimeError("device on fire")

        out = tune.lookup("t.dead", "sig", {"n": 1}, trial=trial)
        assert out == {"n": 1}
        assert TuneStore(tune_env).get(
            f"t.dead|sig|{tune.device_kind()}"
        ) is None

    def test_trials_retry_under_chaos_transients(self, tune_env):
        """The ``tune.trial`` site is a real dispatch site: transient
        chaos faults inside a trial retry inside the trial's own
        ``run_with_retries`` window and tuning still converges."""
        from tensorframes_tpu.utils import chaos

        set_config(
            autotune=True, tune_mode="online", tune_trials=2,
            tune_budget_s=30.0, max_retries=4, retry_backoff_s=0.001,
            chaos="seed=3;tune.trial=transient:p=0.4",
        )
        try:
            inj0 = _totals("chaos.injections_total")
            out = tune.lookup(
                "t.chaos", "sig", {"n": 1}, grid=[{"n": 2}],
                trial=_sleep_trial({1: 6, 2: 1}),
            )
            assert out == {"n": 2}
            assert _totals("chaos.injections_total") > inj0
        finally:
            set_config(chaos="")
        assert TuneStore(tune_env).get(
            f"t.chaos|sig|{tune.device_kind()}"
        )["config"] == {"n": 2}

    def test_lookup_inside_trial_is_read_only(self, tune_env):
        """A lookup made while a trial runs must never START a nested
        search — but it must still SEE installed winners, so trials
        measure the configuration steady state will run with."""
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0,
        )
        tune.pin("t.installed", "sig", {"n": 42})
        inner, installed = [], []

        def trial(cand):
            inner.append(
                tune.lookup("t.inner", "sig", {"n": 99},
                            grid=[{"n": 100}], trial=lambda c: None)
            )
            installed.append(
                tune.lookup("t.installed", "sig", {"n": 1})
            )

        tune.lookup("t.outer", "sig", {"n": 1}, grid=[{"n": 2}],
                    trial=trial)
        assert inner and all(v == {"n": 99} for v in inner)
        assert installed and all(v == {"n": 42} for v in installed)
        # and the inner surface was never tuned/persisted
        assert TuneStore(tune_env).get(
            f"t.inner|sig|{tune.device_kind()}"
        ) is None

    def test_empty_grid_skips_measurement_and_store(self, tune_env):
        set_config(autotune=True, tune_mode="online", tune_trials=3)
        calls = []
        out = tune.lookup(
            "t.lone", "sig", {"n": 1}, grid=[{"n": 1}],
            trial=lambda c: calls.append(c),
        )
        assert out == {"n": 1}
        assert calls == []  # nothing to choose between: no trials
        assert TuneStore(tune_env).get(
            f"t.lone|sig|{tune.device_kind()}"
        ) is None

    def test_pin_clear_snapshot_cookbook(self, tune_env):
        set_config(autotune=True, tune_mode="cached")
        tune.pin("t.pin", "sig", {"n": 5})
        out = tune.lookup("t.pin", "sig", {"n": 1})
        assert out == {"n": 5}
        snap = tune.snapshot()
        mine = [s for s in snap if s["surface"] == "t.pin"]
        assert mine and mine[0]["source"] == "pinned"
        assert "t.pin[sig]" in tune.render_table()
        assert tune.clear("t.pin") == 1
        assert tune.lookup("t.pin", "sig", {"n": 1}) == {"n": 1}


# ---------------------------------------------------------------------------
# byte-identity: every tuned surface vs TFT_TUNE=0
# ---------------------------------------------------------------------------


def _map_fn(x):
    return {"y": x * 2.0 + 1.0}


def _run_map(rows=100, width=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(rows, width)).astype(np.float32)
    df = tft.TensorFrame.from_columns({"x": x}).analyze()
    return tft.map_rows(_map_fn, df).cache().column_data("y").host()


class TestByteIdentity:
    def test_transfer_chunking(self, tune_env, monkeypatch):
        from tensorframes_tpu.frame import transfer

        rng = np.random.default_rng(1)
        arrs = [
            rng.normal(size=(999, 7)).astype(np.float32),
            rng.integers(0, 1000, size=(257, 3)).astype(np.int32),
        ]
        monkeypatch.setenv("TFT_TUNE", "0")
        baseline = [transfer.d2h(transfer.h2d(a)) for a in arrs]
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        tune.pin(
            "transfer.link", "link", {"chunk_bytes": 4096, "streams": 2}
        )
        cb, st = transfer._link_knobs()
        assert (cb, st) == (4096, 2)  # the tuned knobs actually apply
        for i, (a, base) in enumerate(zip(arrs, baseline)):
            up = transfer.StreamingUpload(a)
            if i == 0:  # the f32 column exceeds the tuned 4 KiB chunk
                assert up.num_chunks > 1  # genuinely chunked differently
            got = transfer.d2h(up.assembled())
            np.testing.assert_array_equal(got, base)

    def test_flash_tiles(self, tune_env, monkeypatch):
        from tensorframes_tpu.ops.attention import flash_attention

        rng = np.random.default_rng(2)
        L, D = 256, 64
        q, k, v = (
            rng.normal(size=(1, 1, L, D)).astype(np.float32)
            for _ in range(3)
        )
        monkeypatch.setenv("TFT_TUNE", "0")
        base = np.asarray(flash_attention(q, k, v, causal=True))
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        # a winner differing in block_q ONLY — the shipped grids vary
        # nothing else, exactly because that preserves bit-identity
        tune.pin(
            "flash.tiles", f"lowp=0|d=64|L={L}",
            {"block_q": 128, "block_k": 1024},
        )
        from tensorframes_tpu.ops import attention as attn_mod

        assert attn_mod._best_blocks(np.float32, D, L) == (128, 1024)
        tuned = np.asarray(flash_attention(q, k, v, causal=True))
        np.testing.assert_array_equal(tuned, base)

    def test_map_rows_block_rows(self, tune_env, monkeypatch):
        monkeypatch.setenv("TFT_TUNE", "0")
        base = _run_map()
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        # width 4 f32 -> 16 bytes/row, 100 rows -> n bucket 128: the
        # signature the consumer computes; an odd 7-row budget
        # exercises ragged tails
        tune.pin(
            "map_rows.block_rows", "row_bytes=16|cols=1|n=128",
            {"rows": 7},
        )
        tuned = _run_map()
        np.testing.assert_array_equal(tuned, base)

    def test_map_rows_online_tuning_under_chaos(self, tune_env,
                                                monkeypatch):
        """Online trials — real row programs, chaos-injected at
        ``tune.trial`` — must leave results byte-identical to the kill
        switch."""
        monkeypatch.setenv("TFT_TUNE", "0")
        base = _run_map(rows=128)
        monkeypatch.delenv("TFT_TUNE")
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0, max_rows_per_device_call=32,
            max_retries=4, retry_backoff_s=0.001,
            chaos="seed=5;tune.trial=transient:p=0.3",
        )
        try:
            t0 = _totals("tune.trials_total")
            tuned = _run_map(rows=128)
            assert _totals("tune.trials_total") > t0  # it DID tune
        finally:
            set_config(chaos="")
        np.testing.assert_array_equal(tuned, base)
        # and the winner is a real persisted record
        assert any(
            r["surface"] == "map_rows.block_rows"
            for r in TuneStore(tune_env).entries().values()
        )

    def test_serve_page_size_and_prefill_chunk(self, tune_env, lm,
                                               monkeypatch):
        from tensorframes_tpu.serve import GenerationEngine

        prompt = list(np.random.default_rng(3).integers(1, VOCAB, size=12))
        monkeypatch.setenv("TFT_TUNE", "0")
        eng = GenerationEngine(lm, max_slots=2, max_seq_len=48)
        assert eng.page_size == 48  # hint clamped to max_seq_len
        base_greedy = eng.generate([prompt], 8)[0]
        base_sampled = eng.generate(
            [prompt], 8, temperature=0.8, seed=7
        )[0]
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        sig = tune.serve_signature(np.float32, 4, 48)
        tune.pin("serve.page_size", sig, {"page_size": 8})
        tune.pin("serve.prefill_chunk", sig, {"tokens": 8})
        eng2 = GenerationEngine(lm, max_slots=2, max_seq_len=48)
        assert eng2.page_size == 8
        assert eng2.prefill_chunk_tokens == 8
        np.testing.assert_array_equal(
            eng2.generate([prompt], 8)[0], base_greedy
        )
        np.testing.assert_array_equal(
            eng2.generate([prompt], 8, temperature=0.8, seed=7)[0],
            base_sampled,
        )

    def test_serve_page_slots_geometry(self, tune_env, lm, monkeypatch):
        """The ISSUE 14 pool-geometry surface: a stored winner steers
        the DEFAULT max_slots and num_pages (clamped to feasibility),
        explicit arguments always win, and the streams stay
        byte-identical — geometry moves scheduling, never bytes."""
        from tensorframes_tpu.serve import GenerationEngine

        prompt = list(np.random.default_rng(5).integers(1, VOCAB, size=10))
        monkeypatch.setenv("TFT_TUNE", "0")
        base_eng = GenerationEngine(lm, max_seq_len=48, page_size=8)
        assert base_eng.max_slots == 8  # the untuned default
        base = base_eng.generate([prompt], 8)[0]
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        sig = tune.serve_signature(np.float32, 4, 48)
        tune.pin(
            "serve.page_slots", sig, {"slots": 3, "pages_per_slot": 2}
        )
        eng = GenerationEngine(lm, max_seq_len=48, page_size=8)
        assert eng.max_slots == 3
        # pool = max(one full-length request, slots × pages_per_slot)
        assert eng.pool.num_pages == max(eng._max_pages, 3 * 2)
        np.testing.assert_array_equal(eng.generate([prompt], 8)[0], base)
        # explicit arguments beat the winner
        eng2 = GenerationEngine(
            lm, max_seq_len=48, page_size=8, max_slots=5, num_pages=40
        )
        assert eng2.max_slots == 5 and eng2.pool.num_pages == 40
        np.testing.assert_array_equal(eng2.generate([prompt], 8)[0], base)

    def test_jobs_lease_ttl_surface(self, tune_env, tmp_path,
                                    monkeypatch):
        """The ISSUE 14 lease-TTL surface: cache/pin-only resolution on
        the drain path, explicit ttl untouched, and a real one-worker
        drain under the tuned TTL produces byte-identical block results
        (TTL moves reclamation timing, never results)."""
        from tensorframes_tpu.engine.dist_jobs import (
            _tuned_lease_ttl,
            run_worker,
            wait_job,
        )

        rng = np.random.default_rng(0)
        x = rng.normal(size=(48, 4)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(2)

        def fn(x):
            return {"y": x * 3.0 + 1.0}

        monkeypatch.setenv("TFT_TUNE", "0")
        assert _tuned_lease_ttl(6.0) == 6.0
        ref = np.asarray(tft.map_rows(fn, df).column_data("y").host())
        monkeypatch.delenv("TFT_TUNE")
        set_config(autotune=True, tune_mode="cached")
        tune.pin("jobs.lease_ttl", tune.jobs_signature(), {"ttl_s": 2.0})
        assert _tuned_lease_ttl(6.0) == 2.0
        # garbage in the store degrades to the default, never a crash
        tune.pin("jobs.lease_ttl", tune.jobs_signature(), {"ttl_s": -1})
        assert _tuned_lease_ttl(6.0) == 6.0
        tune.pin("jobs.lease_ttl", tune.jobs_signature(), {"ttl_s": 2.0})
        path = str(tmp_path / "drain")
        report = run_worker(
            "map_rows", fn, df, path=path, worker_id="w0", poll_s=0.05
        )
        assert report.complete
        out = wait_job(path, fn, df)
        np.testing.assert_array_equal(
            np.asarray(out.completed.column_data("y").host()), ref
        )

    def test_rank_tp_layouts_ranks_and_persists(self, tune_env, lm):
        """The ISSUE 14 sharding-ranker surface: cost-model ranking over
        TP degrees (programs.jsonl-fitted when records exist, analytic
        prior otherwise), non-dividing degrees rank last with an
        infinite prediction, winner persisted under serve.tp_layout."""
        set_config(autotune=True, tune_mode="cached")
        ranked = tune.rank_tp_layouts(
            lm, max_seq_len=48, degrees=(1, 2, 4, 3)
        )
        assert [r["tp"] for r in ranked[:3]] != []
        finite = [r for r in ranked if np.isfinite(r["predicted_step_s"])]
        assert {r["tp"] for r in finite} == {1, 2, 4}
        # n_heads=4 does not divide by 3 — ranked last, prediction inf
        assert ranked[-1]["tp"] == 3
        assert not np.isfinite(ranked[-1]["predicted_step_s"])
        # predictions are monotone with the ranking order
        preds = [r["predicted_step_s"] for r in ranked]
        assert preds == sorted(preds)
        stored = {
            r["surface"]: r["config"] for r in tune.snapshot()
        }
        assert stored.get("serve.tp_layout", {}).get("tp") == finite[0]["tp"]
        # higher degrees shrink the per-chip attention-read bytes the
        # model sees (the 1/N KV sharding is IN the features)
        by_tp = {r["tp"]: r for r in finite}
        assert by_tp[4]["bytes"] < by_tp[2]["bytes"] < by_tp[1]["bytes"]


# ---------------------------------------------------------------------------
# persistence round-trip + mid-trial kill (real subprocesses)
# ---------------------------------------------------------------------------

_TUNER_SCRIPT = r"""
import sys
import numpy as np
import tensorframes_tpu as tft
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import set_config

set_config(autotune=True, tune_mode="online", tune_budget_s=30.0,
           tune_trials=1, max_rows_per_device_call=32)
rng = np.random.default_rng(0)
x = rng.normal(size=(128, 4)).astype(np.float32)
df = tft.TensorFrame.from_columns({"x": x}).analyze()
out = tft.map_rows(
    lambda x: {"y": x * 2.0 + 1.0}, df
).cache().column_data("y").host()
snap = obs_metrics.snapshot().get("tune.trials_total", {})
trials = sum((snap.get("values") or {}).values())
np.save(sys.argv[1], out)
print("A_TRIALS", trials, flush=True)
print("A_DONE", flush=True)
"""

_KILL_TUNER_SCRIPT = r"""
import numpy as np
import tensorframes_tpu as tft
from tensorframes_tpu.utils import set_config

# latency chaos on every trial + many repeats = a tuning pass long
# enough for the parent to SIGKILL us mid-trial, deterministically
set_config(autotune=True, tune_mode="online", tune_budget_s=600.0,
           tune_trials=50, max_rows_per_device_call=32,
           chaos="tune.trial=latency:ms=100")
rng = np.random.default_rng(0)
x = rng.normal(size=(128, 4)).astype(np.float32)
df = tft.TensorFrame.from_columns({"x": x}).analyze()
print("TUNING", flush=True)
tft.map_rows(lambda x: {"y": x * 2.0 + 1.0}, df).cache()
print("NEVER_REACHED", flush=True)
"""


class TestPersistenceRoundTrip:
    def test_winner_tuned_in_process_a_serves_b_with_zero_trials(
        self, tune_env, monkeypatch
    ):
        """The acceptance criterion end-to-end: process A (a REAL
        subprocess) tunes online and persists; this process (B) resolves
        the same signature from the store with ZERO trials — asserted
        via ``tune.trials_total`` / ``tune.cache_hits_total`` — and
        produces byte-identical results."""
        out_npy = tune_env + ".a.npy"
        p = subprocess.run(
            [sys.executable, "-c", _TUNER_SCRIPT, out_npy],
            env=_env(TFT_TUNE_FILE=tune_env), capture_output=True,
            text=True, timeout=300,
        )
        assert p.returncode == 0, p.stderr
        assert "A_DONE" in p.stdout
        a_trials = float(p.stdout.split("A_TRIALS")[1].split()[0])
        assert a_trials > 0, "process A never actually tuned"
        winners = {
            r["surface"]: r
            for r in TuneStore(tune_env).entries().values()
        }
        assert "map_rows.block_rows" in winners

        # process B: same signature, online mode — but the store wins
        set_config(
            autotune=True, tune_mode="online", tune_budget_s=30.0,
            tune_trials=1, max_rows_per_device_call=32,
        )
        t0 = _totals("tune.trials_total")
        h0 = _totals("tune.cache_hits_total")
        b_out = _run_map(rows=128)
        assert _totals("tune.trials_total") == t0, (
            "process B ran trials for a signature the store already has"
        )
        assert _totals("tune.cache_hits_total") > h0
        np.testing.assert_array_equal(b_out, np.load(out_npy))

    def test_mid_trial_kill9_store_clean_and_identity_holds(
        self, tune_env, monkeypatch
    ):
        """kill -9 in the middle of a tuning pass: the store re-reads
        cleanly (possibly empty, never torn) and results afterwards —
        cached mode vs kill switch — stay byte-identical."""
        p = subprocess.Popen(
            [sys.executable, "-c", _KILL_TUNER_SCRIPT],
            env=_env(TFT_TUNE_FILE=tune_env), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True,
        )
        try:
            line = ""
            deadline = time.monotonic() + 240
            while "TUNING" not in line:
                assert time.monotonic() < deadline
                line = p.stdout.readline()
                assert line, p.stderr.read()
            time.sleep(0.25)  # mid-trial (each trial sleeps 100ms)
            p.send_signal(signal.SIGKILL)
            assert p.wait(timeout=30) == -signal.SIGKILL
        finally:
            if p.poll() is None:
                p.kill()
        for rec in TuneStore(tune_env).entries().values():
            assert rec["v"] == SCHEMA_VERSION  # clean re-read
        monkeypatch.setenv("TFT_TUNE", "0")
        base = _run_map(rows=128)
        monkeypatch.delenv("TFT_TUNE")
        set_config(
            autotune=True, tune_mode="cached",
            max_rows_per_device_call=32,
        )
        tune.reset()
        np.testing.assert_array_equal(_run_map(rows=128), base)


# ---------------------------------------------------------------------------
# serving satellites + the measured serve-knob search
# ---------------------------------------------------------------------------


class TestServeSatellites:
    def test_page_size_hint_is_the_default_and_healthz_reports(
        self, tune_env, lm
    ):
        from tensorframes_tpu.ops.attention import paged_page_size_hint
        from tensorframes_tpu.serve import GenerationEngine

        hint = paged_page_size_hint(np.float32, 4)
        eng = GenerationEngine(lm, max_slots=2, max_seq_len=48)
        assert eng.page_size == min(hint, 48)
        h = eng.health()
        assert h["page_size"] == eng.page_size
        assert h["prefill_chunk_tokens"] == 0
        # the explicit argument still wins
        eng16 = GenerationEngine(
            lm, max_slots=2, max_seq_len=48, page_size=16
        )
        assert eng16.page_size == 16
        assert eng16.health()["page_size"] == 16

    def test_tune_serve_knobs_persists_and_engines_inherit(
        self, tune_env, lm
    ):
        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=120.0,
        )
        winners = tune.tune_serve_knobs(
            lm, max_seq_len=48, prompt_len=12, max_new_tokens=4,
            max_slots=2, page_sizes=[8], prefill_chunks=[0, 8],
            repeats=1,
        )
        assert set(winners) == {
            "serve.page_size", "serve.prefill_chunk", "serve.page_slots",
        }
        stored = {
            r["surface"] for r in TuneStore(tune_env).entries().values()
        }
        assert {"serve.page_size", "serve.prefill_chunk"} <= stored
        # a later engine resolves the persisted winner (fresh memo =
        # fresh process)
        tune.reset()
        set_config(tune_mode="cached")
        from tensorframes_tpu.serve import GenerationEngine

        eng = GenerationEngine(lm, max_slots=2, max_seq_len=48)
        assert eng.page_size == winners["serve.page_size"]["page_size"]

    def test_tune_serve_knobs_reuses_engines_across_shared_grids(
        self, tune_env, lm, monkeypatch
    ):
        """ISSUE 15 satellite fix: the measured serve search memoizes
        throwaway engines per distinct engine-level config — candidates
        sharing a config (and repeat trials of one candidate) must not
        rebuild, or construction wall eats ``tune_budget_s`` on the
        larger spec-enabled grid."""
        from tensorframes_tpu import serve as serve_pkg

        set_config(
            autotune=True, tune_mode="online", tune_trials=2,
            tune_budget_s=120.0,
        )
        real = serve_pkg.GenerationEngine
        builds = []

        class Counting(real):
            def __init__(self, *a, **kw):
                builds.append(1)
                super().__init__(*a, **kw)

        monkeypatch.setattr(serve_pkg, "GenerationEngine", Counting)
        winners = tune.tune_serve_knobs(
            lm, max_seq_len=48, prompt_len=12, max_new_tokens=4,
            max_slots=2, page_sizes=[8, 16], prefill_chunks=[0, 8],
            draft_params=lm.params, draft_lens=(2, 3),
            repeats=2,
        )
        assert "serve.draft_len" in winners
        assert winners["serve.draft_len"]["k"] in (2, 3, 4)
        # distinct engine configs per grid (the memo is scoped to one
        # surface so only one grid's device pools stay resident): <= 3
        # page sizes (hint default + 2 candidates) + <= 2 chunk configs
        # + <= 3 geometries + <= 3 draft lengths = <= 11 builds. Every
        # measured candidate runs warmup + 2 repeats (~3x that in
        # run_engine calls), so an un-memoized search would build ~30
        # engines — the bound is what separates reuse from
        # rebuild-per-trial.
        trials = _totals("tune.trials_total")
        assert trials >= 8
        assert len(builds) <= 11, (
            f"{len(builds)} engine builds for {trials} measured trials "
            f"— the per-config memo is not reusing engines"
        )
        stored = {
            r["surface"] for r in TuneStore(tune_env).entries().values()
        }
        assert "serve.draft_len" in stored

    def test_draft_len_candidates_stream_byte_identical(
        self, tune_env, lm
    ):
        """The serve-suite invariant extended to the new surface: every
        draft-length candidate (and k=0, speculation off) emits the
        same bytes — draft length changes scheduling, never streams."""
        from tensorframes_tpu.serve import GenerationEngine

        prompts = [[1, 5, 9, 2, 7], [3, 3, 8]]
        outs = []
        for k in (0, 2, 4):
            kw = (
                {}
                if k == 0
                else dict(draft_params=lm.params, draft_len=k)
            )
            eng = GenerationEngine(
                lm, max_slots=2, page_size=8, max_seq_len=48, **kw
            )
            outs.append(
                eng.generate(prompts, 8, temperature=0.7, seed=13)
            )
        for other in outs[1:]:
            for a, b in zip(outs[0], other):
                np.testing.assert_array_equal(a, b)


class TestPerChipRecords:
    """ISSUE 15 satellite: multi-device ``programs.jsonl`` records
    (per-replica TP-named programs, ``meta.tp_degree``) feed the
    layout ranker's cost-model fit, normalized to per-chip features."""

    @staticmethod
    def _mixed_records(w_f=2e-11, w_b=1e-10, w_0=5e-5, n_per=8):
        """Synthetic mixed-degree history obeying a PER-CHIP linear
        law: a degree-N record carries GLOBAL features (N x the
        per-chip work) while its wall is the per-chip wall."""
        rng = np.random.default_rng(0)
        recs = []
        for tp in (1, 2, 4):
            for _ in range(n_per):
                f_chip = float(rng.uniform(1e8, 5e9))
                b_chip = float(rng.uniform(1e6, 5e8))
                wall = w_f * f_chip + w_b * b_chip + w_0
                recs.append(
                    {
                        "flops": f_chip * tp,
                        "bytes": b_chip * tp,
                        "dispatches": 10,
                        "dispatch_s": wall * 10,
                        "meta": {"tp_degree": tp},
                    }
                )
        return recs

    def test_normalization_and_passthrough(self):
        recs = [
            {"flops": 8.0, "bytes": 4.0, "meta": {"tp_degree": 4}},
            {"flops": 8.0, "bytes": 4.0, "meta": {}},
            {"flops": None, "bytes": 4.0, "meta": {"tp_degree": 2}},
        ]
        out = tune.per_chip_records(recs)
        assert out[0]["flops"] == 2.0 and out[0]["bytes"] == 1.0
        assert out[1]["flops"] == 8.0  # single-device: unchanged
        assert out[2]["flops"] is None and out[2]["bytes"] == 2.0
        # the input rows are never mutated
        assert recs[0]["flops"] == 8.0

    def test_mixed_degree_fit_recovers_the_per_chip_law(self):
        recs = self._mixed_records()
        fit_norm = CostModel.fit(tune.per_chip_records(recs))
        fit_raw = CostModel.fit(recs)
        # probe on per-chip features (what rank_tp_layouts predicts
        # with): the normalized fit tracks the generating law; the raw
        # fit is skewed by the global-feature rows
        probe_f, probe_b = 2e9, 2e8
        truth = 2e-11 * probe_f + 1e-10 * probe_b + 5e-5
        err_norm = abs(fit_norm.predict(probe_f, probe_b, 1) - truth)
        err_raw = abs(fit_raw.predict(probe_f, probe_b, 1) - truth)
        assert err_norm < truth * 0.05
        assert err_norm < err_raw

    def test_rank_tp_layouts_fits_over_multi_device_records(
        self, tune_env, lm, tmp_path, monkeypatch
    ):
        """End-to-end: a programs.jsonl holding ONLY multi-device rows
        still yields a usable ranking (finite predictions, monotone
        order, winner pinned) — the fit no longer depends on
        single-device-only records."""
        import json as _json

        costs = tmp_path / "programs.jsonl"
        with open(costs, "w") as f:
            for rec in self._mixed_records():
                if rec["meta"]["tp_degree"] == 1:
                    continue
                f.write(_json.dumps(rec) + "\n")
        monkeypatch.setenv("TFT_PROGRAM_COSTS_FILE", str(costs))
        set_config(autotune=True, tune_mode="cached")
        model = tune.default_model(per_chip=True)
        assert model.source.startswith("ridge")
        ranked = tune.rank_tp_layouts(
            lm, max_seq_len=48, degrees=(1, 2, 4)
        )
        preds = [r["predicted_step_s"] for r in ranked]
        assert all(np.isfinite(p) for p in preds)
        assert preds == sorted(preds)
        stored = {
            r["surface"]: r["config"] for r in tune.snapshot()
        }
        assert stored.get("serve.tp_layout", {}).get("tp") == (
            ranked[0]["tp"]
        )


# ---------------------------------------------------------------------------
# export + gate satellites
# ---------------------------------------------------------------------------


def _http(host, port, path):
    c = socket.create_connection((host, port))
    try:
        c.sendall(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    finally:
        c.close()
    head, _, body = buf.partition(b"\r\n\r\n")
    return head.split(b"\r\n")[0].decode(), body


class TestExportSurfaces:
    def test_bench_check_gate_pins_tune_kill_switch(self):
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "benchmarks",
        ))
        try:
            import bench_check

            assert bench_check.GATE_ENV["TFT_TUNE"] == "0"
        finally:
            sys.path.pop(0)

    def test_explain_analyze_appends_tuned_table(self, tune_env):
        set_config(autotune=True, tune_mode="cached")
        tune.pin("t.explain", "sig", {"n": 3})
        x = np.arange(8, dtype=np.float32).reshape(4, 2)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        txt = tft.explain(df, analyze=True)
        assert "== Tuned configs ==" in txt
        assert "t.explain[sig]" in txt

    def test_statusz_and_varz_export(self, tune_env, lm):
        """/statusz carries the tuned-winner view; the
        predicted-vs-measured error histogram is sampled onto /varz."""
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.obs import timeseries
        from tensorframes_tpu.serve import GenerationEngine

        set_config(
            autotune=True, tune_mode="online", tune_trials=1,
            tune_budget_s=30.0,
        )
        timeseries.sample_once()  # baseline tick
        tune.lookup(
            "t.varz", "sig", {"n": 1},
            grid=[{"n": 2}, {"n": 3}, {"n": 4}],
            feats=lambda c: (0.0, 0.0, float(c["n"])),
            trial=lambda c: time.sleep(0.001),
        )
        timeseries.sample_once()
        names = timeseries.store().names()
        assert any(
            n.startswith("tune.predicted_error_ratio.") for n in names
        ), names
        srv = ScoringServer(
            engine=GenerationEngine(
                lm, max_slots=2, page_size=4, max_seq_len=32
            )
        )
        try:
            host, port = srv.start()
            status, body = _http(host, port, "/statusz")
            assert status.endswith("200 OK")
            tz = json.loads(body)["tune"]
            assert tz["mode"] == "online"
            assert any(
                w["surface"] == "t.varz" for w in tz["winners"]
            )
            status, body = _http(
                host, port, "/varz?prefix=tune.predicted_error_ratio"
            )
            assert status.endswith("200 OK")
            series = json.loads(body)["series"]
            assert any(
                k.startswith("tune.predicted_error_ratio.")
                and v.get("points")
                for k, v in series.items()
            ), series
        finally:
            srv.stop()
