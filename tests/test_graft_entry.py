"""Driver-contract tests for ``__graft_entry__``.

The driver compile-checks ``entry()`` single-chip and runs
``dryrun_multichip(N)`` on a box that may have fewer than N real devices
(MULTICHIP_r01 failed exactly because the round-1 entry assumed N real
chips).  These tests pin the self-provisioning contract.
"""

import os
import pytest
import subprocess
import sys

import jax
import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import __graft_entry__ as graft  # noqa: E402

#: multi-process spawns / full-model training sweeps: the suite's
#: heavyweights (measured r05 durations); `make test-fast` skips them
pytestmark = pytest.mark.slow


def test_entry_is_jittable():
    fn, args = graft.entry()
    out = jax.jit(fn)(*args)
    assert out.shape == (128, 10)
    assert np.isfinite(np.asarray(out)).all()


def test_devices_for_provisions_virtual_devices():
    devs = graft._devices_for(8)
    assert devs is not None and len(devs) == 8


def test_devices_for_provisions_in_process():
    """The non-trivial branch: jax preimported (as this image's
    sitecustomize does), backends NOT yet initialized, no env help — the
    jax_num_cpu_devices config route must provision without a subprocess."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "_TFT_DRYRUN_CHILD")
    }
    env["JAX_PLATFORMS"] = "cpu"
    code = (
        "import jax\n"  # preimport without initializing backends
        "import __graft_entry__ as g\n"
        "devs = g._devices_for(8)\n"
        "assert devs is not None and len(devs) == 8, devs\n"
        "print('in-process OK')\n"
    )
    res = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "in-process OK" in res.stdout


def test_dryrun_multichip_in_process():
    # conftest provisions 8 virtual CPU devices; exercise the full path.
    graft.dryrun_multichip(4)


def test_dryrun_multichip_subprocess_single_device():
    """The driver's actual invocation shape: fresh interpreter, no env help,
    possibly only one device visible — must still exit 0."""
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    # pin the interpreter to one CPU device so provisioning must do the work
    env["JAX_PLATFORMS"] = "cpu"
    code = "import __graft_entry__ as g; g.dryrun_multichip(8)"
    res = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert res.returncode == 0, res.stderr[-2000:]
