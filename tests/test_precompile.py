"""Persistent-compile-cache config + ahead-of-time ``precompile``.

The reference pays zero compile cost (TF 1.x sessions execute GraphDefs
directly, ``TensorFlowOps.scala:76-95``); this framework's equivalent is
XLA's persistent executable cache plus an AOT warm-up API. These tests pin
the contract: the cache is configured at import, ``precompile`` builds one
program per distinct block shape without touching data, and the programs it
builds are the ones ``map_blocks`` then runs.
"""

import os

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.utils.config import enable_compilation_cache


def _frame(n=100, parts=4):
    x = np.arange(n * 8, dtype=np.float32).reshape(n, 8)
    return (
        tft.TensorFrame.from_columns(
            {"features": x}, num_partitions=parts
        ).analyze(),
        x,
    )


def _score(features):
    return {"out": features * 2.0 + 1.0}


def test_cache_dir_configured_at_import():
    # conftest leaves TFT_NO_COMPILE_CACHE unset, so the package import
    # configured the persistent cache; jax must agree on the directory
    import jax

    d = enable_compilation_cache()  # idempotent: returns the active dir
    assert d is not None
    assert jax.config.jax_compilation_cache_dir == d
    assert os.path.isdir(d)
    # engine thunks compile in well under jax's 1.0s default floor; the
    # floor must be lowered or short-job warmup caches nothing
    assert jax.config.jax_persistent_cache_min_compile_time_secs <= 0.1
    assert jax.config.jax_persistent_cache_min_entry_size_bytes == -1


def test_precompile_frame_counts_distinct_block_shapes():
    df, _ = _frame(n=100, parts=4)  # 4 equal partitions of 25
    assert tft.precompile(_score, df) == 1
    # uneven partitioning: 3 parts of 33/33/34 -> two distinct sizes
    df2, _ = _frame(n=100, parts=3)
    assert tft.precompile(_score, df2) == 2


def test_precompile_then_map_blocks_matches():
    df, x = _frame()
    tft.precompile(_score, df)
    out = tft.map_blocks(_score, df)
    np.testing.assert_allclose(
        np.asarray(out.column_data("out").host()), x * 2.0 + 1.0
    )


def test_precompile_schema_path_requires_block_rows():
    df, _ = _frame()
    with pytest.raises(ValueError, match="block_rows"):
        tft.precompile(_score, df.schema)
    assert tft.precompile(_score, df.schema, block_rows=[25, 50]) == 2


def test_precompile_rejects_unknown_dims():
    x = np.arange(80, dtype=np.float32).reshape(10, 8)
    df = tft.TensorFrame.from_columns({"features": x})  # NOT analyzed
    # from_columns on a dense ndarray knows the cell dims, so force an
    # Unknown via a serialized-graph-style schema with an Unknown tail
    from tensorframes_tpu.schema import (
        ColumnInfo,
        FrameInfo,
        Shape,
        Unknown,
        for_numpy_dtype,
    )

    info = FrameInfo(
        [
            ColumnInfo(
                "features",
                for_numpy_dtype(np.dtype(np.float32)),
                analyzed_shape=Shape([Unknown, Unknown]),
                nesting=1,
            )
        ]
    )
    with pytest.raises(ValueError, match="unknown cell dims"):
        tft.precompile(_score, info, block_rows=[10])


def test_precompile_with_constants_and_feed_dict():
    df, x = _frame()
    w = np.full((8,), 3.0, dtype=np.float32)

    def affine(v, w):
        return {"out": v * w}

    assert (
        tft.precompile(
            affine, df, feed_dict={"v": "features"}, constants={"w": w}
        )
        == 1
    )
    out = tft.map_blocks(
        affine, df, feed_dict={"v": "features"}, constants={"w": w}
    )
    np.testing.assert_allclose(
        np.asarray(out.column_data("out").host()), x * 3.0
    )


def test_precompile_graph_from_artifact(tmp_path):
    # serving-process story: load a serialized graph in a process with no
    # data, precompile for the block sizes it will serve
    df, x = _frame()
    from tensorframes_tpu.schema import FLOAT32, Shape, Unknown

    g = tft.CapturedGraph.from_callable(
        _score, {"features": (FLOAT32, Shape([Unknown, 8]))}
    )
    path = tmp_path / "scoring.tfg"
    tft.save_graph(g, str(path))
    g2 = tft.load_graph(str(path))
    assert tft.precompile(g2, df.schema, block_rows=[25]) == 1
    out = tft.map_blocks(g2, df)
    np.testing.assert_allclose(
        np.asarray(out.column_data("out").host()), x * 2.0 + 1.0
    )
