"""Distributed tracing + crash flight recorder (ISSUE 10).

The acceptance bar: ONE ``trace_id`` submitted via a W3C ``traceparent``
header on ``POST /generate`` is reconstructible from the JSONL span sink
across a chaos-injected mid-stream replica kill and failover replay;
a kill -9'd dist-jobs worker's block shows claim → reclaim → record as
one trace across two processes and epochs; a fatal engine step and a
quarantined block each dump a debug bundle listed by ``GET /statusz``;
and ``TFT_OBS=0`` disables the whole layer.

Everything here is CPU-only, seeded, and deterministic; the suite is
tier-1 (``make test-obs`` selects the observability marker).
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import obs
from tensorframes_tpu.obs import flight
from tensorframes_tpu.obs.metrics import MetricsRegistry
from tensorframes_tpu.obs.tracing import TraceContext
from tensorframes_tpu.utils import chaos, get_config, set_config

pytestmark = pytest.mark.obs

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    from tensorframes_tpu.models import TransformerLM

    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture
def sink(tmp_path):
    """A JSONL trace sink for the test, detached afterwards."""
    path = tmp_path / "trace.jsonl"
    obs.set_trace_sink(str(path))
    yield path
    obs.set_trace_sink(None)


@pytest.fixture
def bundle_dir(tmp_path):
    """Debug bundles land in the test's tmp dir, recorder state reset."""
    flight.reset()
    old = get_config().debug_bundle_dir
    set_config(debug_bundle_dir=str(tmp_path / "bundles"))
    yield tmp_path / "bundles"
    set_config(debug_bundle_dir=old)
    flight.reset()


def _events(path):
    return [json.loads(ln) for ln in path.read_text().splitlines()]


def _http(addr, req: bytes, timeout=120) -> bytes:
    host, port_s = addr.rsplit(":", 1)
    s = socket.create_connection((host, int(port_s)), timeout=timeout)
    try:
        s.sendall(req)
        data = b""
        while True:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    return data


def _post_generate(addr, spec, headers=None, timeout=120):
    body = json.dumps(spec).encode()
    head = f"POST /generate HTTP/1.1\r\nContent-Length: {len(body)}\r\n"
    for k, v in (headers or {}).items():
        head += f"{k}: {v}\r\n"
    resp = _http(addr, head.encode() + b"\r\n" + body, timeout=timeout)
    status = int(resp.split(b" ", 2)[1])
    raw_head, raw_body = resp.split(b"\r\n\r\n", 1)
    resp_headers = {}
    for line in raw_head.split(b"\r\n")[1:]:
        name, _, val = line.partition(b":")
        resp_headers[name.strip().lower().decode()] = val.strip().decode()
    return status, json.loads(raw_body or b"{}"), resp_headers


def _get_json(addr, path):
    resp = _http(addr, f"GET {path} HTTP/1.1\r\n\r\n".encode())
    status = int(resp.split(b" ", 2)[1])
    return status, json.loads(resp.split(b"\r\n\r\n", 1)[1])


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------


class TestTraceContext:
    def test_traceparent_round_trip(self):
        ctx = obs.new_trace()
        assert re.fullmatch(r"[0-9a-f]{32}", ctx.trace_id)
        assert re.fullmatch(r"[0-9a-f]{16}", ctx.span_id)
        hdr = ctx.traceparent()
        assert hdr == f"00-{ctx.trace_id}-{ctx.span_id}-01"
        back = TraceContext.from_traceparent(hdr)
        assert back == ctx

    def test_child_keeps_trace_changes_span(self):
        ctx = obs.new_trace()
        kid = ctx.child()
        assert kid.trace_id == ctx.trace_id
        assert kid.span_id != ctx.span_id

    @pytest.mark.parametrize(
        "bad",
        [
            None,
            "",
            "garbage",
            "00-short-abcdefabcdefabcd-01",
            "00-" + "0" * 32 + "-abcdefabcdefabcd-01",  # all-zero trace
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span
            "ff-" + "ab" * 16 + "-abcdefabcdefabcd-01",  # forbidden version
            "00-" + "zz" * 16 + "-abcdefabcdefabcd-01",  # non-hex
            "00-" + "ab" * 16 + "-abcdefabcdefabc-01",  # 15-char span
        ],
    )
    def test_malformed_traceparent_degrades_to_none(self, bad):
        assert TraceContext.from_traceparent(bad) is None

    def test_case_and_whitespace_are_tolerated(self):
        hdr = "  00-" + "AB" * 16 + "-ABCDEFABCDEFABCD-01  "
        ctx = TraceContext.from_traceparent(hdr)
        assert ctx is not None and ctx.trace_id == "ab" * 16


class TestPropagation:
    def test_spans_adopt_the_ambient_trace(self, sink):
        ctx = obs.new_trace()
        with obs.use_trace(ctx):
            with obs.span("t.outer") as sp:
                assert sp.trace_id == ctx.trace_id
                assert sp.parent_id == ctx.span_id
                with obs.span("t.inner") as inner:
                    assert inner.trace_id == ctx.trace_id
                    assert inner.parent_id == sp.span_id
        # outside the block the ambient context is gone
        assert obs.current_trace() is None
        by = {e["name"]: e for e in _events(sink)}
        assert by["t.inner"]["trace_id"] == ctx.trace_id
        assert by["t.inner"]["parent_id"] == by["t.outer"]["span_id"]

    def test_span_with_no_context_roots_a_fresh_trace(self, sink):
        with obs.span("t.root") as sp:
            assert re.fullmatch(r"[0-9a-f]{32}", sp.trace_id)
            assert sp.parent_id is None

    def test_current_trace_crosses_threads(self, sink):
        handoff = {}
        with obs.span("t.parent") as sp:
            handoff["ctx"] = obs.current_trace()
        assert handoff["ctx"].span_id == sp.span_id

        def worker():
            with obs.use_trace(handoff["ctx"]):
                with obs.span("t.child"):
                    pass

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        by = {e["name"]: e for e in _events(sink)}
        assert by["t.child"]["trace_id"] == by["t.parent"]["trace_id"]
        assert by["t.child"]["parent_id"] == by["t.parent"]["span_id"]

    def test_event_is_written_immediately(self, sink):
        with obs.span("t.enclosing") as sp:
            ectx = obs.event("t.point", k="v")
            # the span is still OPEN, but the point event is on disk
            events = _events(sink)
            assert [e["name"] for e in events] == ["t.point"]
            assert events[0]["kind"] == "event"
            assert events[0]["dur_s"] == 0.0
            assert events[0]["parent_id"] == sp.span_id
            assert events[0]["attrs"] == {"k": "v"}
            assert ectx.trace_id == sp.trace_id

    def test_span_ids_are_unique(self, sink):
        with obs.span("t.a") as a:
            pass
        with obs.span("t.b") as b:
            pass
        assert a.span_id != b.span_id


# ---------------------------------------------------------------------------
# JSONL sink rotation
# ---------------------------------------------------------------------------


class TestSinkRotation:
    def test_size_rotation_keeps_last_bytes(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        obs.set_trace_sink(str(path), max_bytes=2048)
        try:
            for i in range(100):
                with obs.span("t.rot", i=i, pad="x" * 80):
                    pass
        finally:
            obs.set_trace_sink(None)
        rolled = tmp_path / "trace.jsonl.1"
        assert rolled.exists(), "sink never rotated"
        assert path.stat().st_size <= 2048
        assert rolled.stat().st_size <= 2048 + 200
        # both files are whole-line valid JSONL and the newest span is
        # in the live file (rotation is between-writes, never mid-line)
        live = _events(path)
        for e in live + _events(rolled):
            assert e["name"] == "t.rot"
        assert live[-1]["attrs"]["i"] == 99

    def test_env_default_used_when_unspecified(self, tmp_path, monkeypatch):
        monkeypatch.setenv("TFT_TRACE_FILE_MAX_BYTES", "1024")
        path = tmp_path / "trace.jsonl"
        obs.set_trace_sink(str(path))
        try:
            for i in range(50):
                with obs.span("t.envrot", pad="y" * 80):
                    pass
        finally:
            obs.set_trace_sink(None)
        assert (tmp_path / "trace.jsonl.1").exists()

    def test_zero_disables_rotation(self, tmp_path):
        path = tmp_path / "trace.jsonl"
        obs.set_trace_sink(str(path), max_bytes=0)
        try:
            for i in range(50):
                with obs.span("t.norot", pad="z" * 80):
                    pass
        finally:
            obs.set_trace_sink(None)
        assert not (tmp_path / "trace.jsonl.1").exists()
        assert len(_events(path)) == 50


# ---------------------------------------------------------------------------
# Prometheus exposition-format escaping (the audit's regression)
# ---------------------------------------------------------------------------


class TestPromEscaping:
    def test_help_newline_and_backslash_escape(self):
        """REGRESSION: an embedded newline in HELP text split the line
        and corrupted every series after it in the scrape; backslashes
        went through raw. The exposition format (0.0.4) escapes both."""
        reg = MetricsRegistry()
        reg.counter("t.helpesc_total", "line1\nline2 C:\\dir done").inc()
        text = reg.render_prometheus()
        lines = text.splitlines()
        help_lines = [l for l in lines if l.startswith("# HELP")]
        assert help_lines == [
            "# HELP tft_t_helpesc_total line1\\nline2 C:\\\\dir done"
        ]
        # nothing leaked onto its own line
        assert not any(l.startswith("line2") for l in lines)

    def test_label_values_round_trip_a_scrape_parse(self):
        """Exception text in a label value (the `status` reasons on
        failure counters) must survive render → parse: backslash first,
        then quote, then newline, per the exposition format."""
        reg = MetricsRegistry()
        nasty = 'RuntimeError: "quoted"\npath C:\\x \\n literal'
        reg.counter("t.esc2_total", "x", labels=("status",)).inc(
            status=nasty
        )
        text = reg.render_prometheus()
        (line,) = [
            l for l in text.splitlines() if l.startswith("tft_t_esc2_total{")
        ]
        assert "\n" not in line  # the rendered series is ONE line
        m = re.fullmatch(r'tft_t_esc2_total\{status="(.*)"\} 1', line)
        assert m, line
        unescaped = (
            m.group(1)
            .replace("\\\\", "\x00")
            .replace('\\"', '"')
            .replace("\\n", "\n")
            .replace("\x00", "\\")
        )
        assert unescaped == nasty

    def test_every_rendered_line_is_valid_exposition(self):
        reg = MetricsRegistry()
        reg.counter("t.v_total", "a\nb", labels=("s",)).inc(s='x"\\\n')
        reg.gauge("t.v", "g").set(1.5)
        reg.histogram("t.v_seconds", "h").observe(0.1)
        ok = re.compile(
            r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .*"
            r"|[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? -?[0-9e+.infNa]+)$"
        )
        for line in reg.render_prometheus().splitlines():
            assert ok.match(line), f"bad exposition line: {line!r}"


# ---------------------------------------------------------------------------
# multi-thread hammer on the registry while a scrape loop runs
# ---------------------------------------------------------------------------


class TestRegistryHammer:
    def test_no_lost_increments_under_concurrent_scrapes(self):
        reg = MetricsRegistry()
        c = reg.counter("t.hammer_total", "c", labels=("op",))
        g = reg.gauge("t.hammer_inflight", "g")
        h = reg.histogram("t.hammer_seconds", "h")
        per_thread, n_threads = 2000, 8
        stop = threading.Event()
        scrapes, scrape_errors = [], []

        def scrape_loop():
            while not stop.is_set():
                try:
                    text = reg.render_prometheus()
                    reg.snapshot()
                    scrapes.append(text)
                except Exception as e:  # pragma: no cover
                    scrape_errors.append(e)
                    return

        def hammer(i):
            for k in range(per_thread):
                c.inc(op=f"op{i % 2}")
                g.adjust(1.0)
                h.observe(1e-4 * (k % 7 + 1))
                g.adjust(-1.0)

        scraper = threading.Thread(target=scrape_loop)
        scraper.start()
        ts = [
            threading.Thread(target=hammer, args=(i,))
            for i in range(n_threads)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        stop.set()
        scraper.join()
        assert not scrape_errors, scrape_errors
        assert scrapes, "the scrape loop never completed a pass"
        total = per_thread * n_threads
        assert c.value(op="op0") == total / 2
        assert c.value(op="op1") == total / 2
        assert g.value() == 0.0
        assert h.series()["count"] == total
        # the final scrape is valid and carries the exact totals
        final = reg.render_prometheus()
        assert f'tft_t_hammer_total{{op="op0"}} {int(total / 2)}' in final
        assert f"tft_t_hammer_seconds_count {total}" in final


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------


class TestFlightRecorder:
    def test_record_and_rings(self, bundle_dir):
        flight.record("testring", "boom", a=1, b="x")
        rings = flight.rings()
        (evt,) = rings["testring"]
        assert evt["kind"] == "boom" and evt["a"] == 1 and evt["b"] == "x"
        assert evt["ts"] > 0

    def test_ring_is_bounded(self, bundle_dir):
        for i in range(600):
            flight.record("bounded", "e", i=i)
        evts = flight.rings()["bounded"]
        assert len(evts) == 512  # TFT_FLIGHT_EVENTS default
        assert evts[-1]["i"] == 599 and evts[0]["i"] == 88  # oldest evicted

    def test_capture_spans_mirrors_spans_into_the_trace_ring(
        self, bundle_dir
    ):
        # no sink, no annotations: with capture ON the span must still
        # go live and land in the ring
        flight.capture_spans(True)
        try:
            with obs.span("t.flightspan", k=1) as sp:
                assert sp is not None
            obs.event("t.flightevent")
        finally:
            flight.capture_spans(False)
        names = [e["name"] for e in flight.rings()["trace"]]
        assert "t.flightspan" in names and "t.flightevent" in names
        # capture off again: spans short-circuit
        with obs.span("t.dead") as sp:
            assert sp is None

    def test_dump_bundle_contents_and_registry(self, bundle_dir):
        flight.record("testring", "precrash", n=7)
        path = flight.dump_bundle(
            "test_reason", health={"healthy": False}, extra={"why": "test"}
        )
        assert path is not None and os.path.exists(path)
        assert os.path.dirname(path) == str(bundle_dir)
        bundle = json.load(open(path))
        assert bundle["reason"] == "test_reason"
        assert bundle["version"] == 1
        assert bundle["pid"] == os.getpid()
        assert bundle["rings"]["testring"][0]["kind"] == "precrash"
        assert "obs.debug_bundles_total" in bundle["metrics"]
        assert bundle["health"] == {"healthy": False}
        assert bundle["config"]["debug_bundle_dir"] == str(bundle_dir)
        assert bundle["chaos_spec"] == ""
        assert bundle["extra"] == {"why": "test"}
        (rec,) = [
            b
            for b in flight.recent_bundles()
            if b["reason"] == "test_reason"
        ]
        assert rec["path"] == path
        assert flight.last_bundle()["path"] == path

    def test_dump_bundle_debounces_crash_loops(self, bundle_dir):
        p1 = flight.dump_bundle("loop_reason")
        p2 = flight.dump_bundle("loop_reason")  # within the 1 s window
        p3 = flight.dump_bundle("other_reason")  # different reason: dumps
        assert p1 is not None and p2 is None and p3 is not None

    def test_debounce_key_separates_distinct_failures(self, bundle_dir):
        """Sibling failures of ONE reason milliseconds apart (several
        blocks quarantining in a row) each get their bundle; only a
        true repeat of the same unit is suppressed."""
        p1 = flight.dump_bundle("q_reason", debounce_key="job/1")
        p2 = flight.dump_bundle("q_reason", debounce_key="job/2")
        p3 = flight.dump_bundle("q_reason", debounce_key="job/1")
        assert p1 is not None and p2 is not None and p3 is None

    def test_chaos_injections_land_in_the_ring(self, bundle_dir):
        with chaos.scoped("jobs.block=latency:ms=1:times=1"):
            chaos.site("jobs.block")
        evts = flight.rings()["chaos"]
        assert any(
            e["kind"] == "latency" and e["site"] == "jobs.block"
            for e in evts
        )

    def test_kill_switch_parity(self, bundle_dir):
        set_config(observability=False)
        try:
            flight.record("offring", "e")
            assert flight.dump_bundle("off_reason") is None
            assert obs.event("t.off") is None
            flight.capture_spans(True)
            with obs.span("t.off2") as sp:
                assert sp is None
        finally:
            flight.capture_spans(False)
            set_config(observability=True)
        assert "offring" not in flight.rings()
        assert not any(
            b["reason"] == "off_reason" for b in flight.recent_bundles()
        )


# ---------------------------------------------------------------------------
# POST /generate tracing + /statusz (solo engine)
# ---------------------------------------------------------------------------


@pytest.mark.serve
class TestGenerateTracing:
    def test_traceparent_echo_timing_and_sink(self, lm, sink, bundle_dir):
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.serve import GenerationEngine

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=48)
        client = obs.new_trace()
        # the server starts (and stops) the engine itself
        with ScoringServer(engine=eng) as addr:
            status, body, headers = _post_generate(
                addr,
                {"prompt": [1, 2, 3], "max_new_tokens": 6},
                headers={"traceparent": client.traceparent()},
            )
            assert status == 200
            # the response adopts the CLIENT's trace and echoes it
            assert body["trace_id"] == client.trace_id
            echoed = TraceContext.from_traceparent(headers["traceparent"])
            assert echoed.trace_id == client.trace_id
            assert echoed.span_id != client.span_id
            timing = body["timing"]
            assert timing["total_s"] > 0
            assert timing["queue_wait_s"] >= 0
            assert timing["prefill_s"] > 0
            assert timing["decode_s"] >= 0
            assert timing["prefill_chunks"] == 0
            assert timing["replays"] == 0
            # a malformed header degrades to a FRESH trace, not a 4xx
            status, body2, _ = _post_generate(
                addr,
                {"prompt": [1, 2, 3], "max_new_tokens": 2},
                headers={"traceparent": "00-garbage-zz-01"},
            )
            assert status == 200
            assert re.fullmatch(r"[0-9a-f]{32}", body2["trace_id"])
            assert body2["trace_id"] != client.trace_id

            # /statusz: the request log carries the trace ids
            status, sz = _get_json(addr, "/statusz")
            assert status == 200
            gens = [r for r in sz["requests"] if r["kind"] == "generate"]
            assert {g["trace_id"] for g in gens} == {
                body["trace_id"],
                body2["trace_id"],
            }
            assert sz["slowest_requests"][0]["dur_s"] >= 0
            assert sz["chaos"] == ""
            assert sz["trace_sink"] is True
            assert "serving" in sz["flight"]
        # the whole request is ONE trace in the sink with correct
        # parentage: serving.generate under the client's trace, the
        # engine's prefill (another thread) under serving.generate
        events = _events(sink)
        (gen,) = [
            e
            for e in events
            if e["name"] == "serving.generate"
            and e["trace_id"] == client.trace_id
        ]
        assert gen["parent_id"] == echoed.span_id
        prefills = [
            e
            for e in events
            if e["name"] == "serve.prefill"
            and e["trace_id"] == client.trace_id
        ]
        assert prefills, "engine prefill did not join the request trace"
        assert all(p["parent_id"] == gen["span_id"] for p in prefills)

    def test_statusz_and_healthz_list_bundles(self, lm, sink, bundle_dir):
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.serve import GenerationEngine

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=48)
        with ScoringServer(engine=eng) as addr:
            path = flight.dump_bundle("statusz_test")
            assert path is not None
            status, sz = _get_json(addr, "/statusz")
            assert status == 200
            assert any(
                b["reason"] == "statusz_test" and b["path"] == path
                for b in sz["debug_bundles"]
            )
            status, hz = _get_json(addr, "/healthz")
            assert status == 200
            assert any(
                b["reason"] == "statusz_test"
                for b in hz["debug_bundles"]
            )
        # unknown paths advertise the new endpoint
        # (routing itself is covered in test_fleet)


# ---------------------------------------------------------------------------
# engine fatal -> debug bundle
# ---------------------------------------------------------------------------


@pytest.mark.serve
@pytest.mark.chaos
class TestEngineFatalBundle:
    def test_fatal_step_dumps_a_bundle(self, lm, bundle_dir):
        from tensorframes_tpu.serve import GenerationEngine
        from tensorframes_tpu.utils.chaos import ChaosFault

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with chaos.scoped("serve.decode_step=fatal:times=1"):
            with eng:
                h = eng.submit([1, 2, 3], 6)
                with pytest.raises(ChaosFault):
                    h.result(timeout=60)
                # the handle fails from inside the step; the supervisor
                # (unhealthy flip + bundle dump) lands a beat later
                deadline = time.monotonic() + 10
                bundles = []
                while not bundles and time.monotonic() < deadline:
                    bundles = [
                        b
                        for b in flight.recent_bundles()
                        if b["reason"] == "engine_fatal"
                    ]
                    time.sleep(0.01)
                assert not eng.healthy
        assert bundles, "no engine_fatal bundle dumped"
        bundle = json.load(open(bundles[0]["path"]))
        assert bundle["extra"]["error_type"] == "ChaosFault"
        assert bundle["health"]["healthy"] is False
        # the serve ring captured the fatal, the chaos ring the injection
        assert any(
            e["kind"] == "engine_fatal" for e in bundle["rings"]["serve"]
        )
        assert any(
            e["site"] == "serve.decode_step"
            for e in bundle["rings"]["chaos"]
        )
        assert "serve.requests_total" in bundle["metrics"]


# ---------------------------------------------------------------------------
# fleet failover: ONE trace across a mid-stream replica kill (acceptance)
# ---------------------------------------------------------------------------


@pytest.mark.fleet
@pytest.mark.chaos
class TestFleetFailoverTrace:
    def test_one_trace_across_replica_kill_and_replay(
        self, lm, sink, bundle_dir
    ):
        from tensorframes_tpu.interop.serving import ScoringServer
        from tensorframes_tpu.serve import Fleet
        from tensorframes_tpu.utils.chaos import ChaosFault

        fleet = Fleet(
            lm, replicas=2, max_slots=4, page_size=4, max_seq_len=64,
            watchdog_interval_s=0.02,
        )
        client = obs.new_trace()
        result = {}

        def call(addr):
            result["resp"] = _post_generate(
                addr,
                {"prompt": [1, 2, 3], "max_new_tokens": 20},
                headers={"traceparent": client.traceparent()},
            )

        with chaos.scoped("serve.decode_step=latency:ms=25"):
            # the server starts (and stops) the fleet itself
            with ScoringServer(engine=fleet) as addr:
                t = threading.Thread(target=call, args=(addr,))
                t.start()
                # wait until SOME replica is streaming it, then kill it
                deadline = time.monotonic() + 60
                victim = None
                while victim is None:
                    assert time.monotonic() < deadline
                    victim = next(
                        (
                            rep
                            for rep in fleet._replicas
                            if any(
                                s is not None
                                for s in rep.engine.scheduler.slots
                            )
                        ),
                        None,
                    )
                    time.sleep(0.01)
                fleet._kill_replica(victim, ChaosFault("mid-stream kill"))
                t.join(timeout=120)
                assert not t.is_alive()
        status, body, _ = result["resp"]
        assert status == 200
        assert body["trace_id"] == client.trace_id
        assert body["timing"]["replays"] >= 1
        events = _events(sink)
        ours = [e for e in events if e["trace_id"] == client.trace_id]
        # the failover point is marked IN the same trace...
        replays = [e for e in ours if e["name"] == "fleet.replay"]
        assert replays and replays[0]["attrs"]["replay"] == 1
        assert replays[0]["kind"] == "event"
        # ...and the work spans exist on BOTH sides of the kill: one
        # prefill dispatch per replica that served the stream, all in
        # the client's trace, all parented under serving.generate
        prefills = [e for e in ours if e["name"] == "serve.prefill"]
        assert len(prefills) >= 2, (
            "expected prefill spans from both replicas in one trace"
        )
        (gen,) = [e for e in ours if e["name"] == "serving.generate"]
        assert all(p["parent_id"] == gen["span_id"] for p in prefills)
        # the fence landed in the flight recorder's fleet ring
        assert any(
            e["kind"] == "fence" for e in flight.rings().get("fleet", [])
        )


# ---------------------------------------------------------------------------
# batch jobs: journal-carried traces + quarantine bundles
# ---------------------------------------------------------------------------


@pytest.mark.durability
class TestJobsTracing:
    def test_manifest_and_ledger_carry_the_trace(
        self, tmp_path, sink, bundle_dir
    ):
        from tensorframes_tpu.engine import run_job

        old = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            rng = np.random.default_rng(0)
            x = rng.normal(size=(96, 4)).astype(np.float32)
            df = tft.TensorFrame.from_columns({"x": x}).analyze()
            res = run_job(
                "map_rows", lambda x: {"y": x * 2.0}, df,
                job_dir=str(tmp_path / "job"),
            )
        finally:
            set_config(max_rows_per_device_call=old)
        manifest = json.load(open(os.path.join(res.path, "manifest.json")))
        tid = manifest["trace_id"]
        assert re.fullmatch(r"[0-9a-f]{32}", tid)
        assert re.fullmatch(r"[0-9a-f]{16}", manifest["trace_span_id"])
        # every done-record in the ledger carries the job trace + its
        # block span id — the journal alone reconstructs the story
        recs = [
            json.loads(ln)
            for ln in open(os.path.join(res.path, "ledger.jsonl"))
            if '"done"' in ln
        ]
        assert recs and all(r["trace_id"] == tid for r in recs)
        span_ids = {r["span_id"] for r in recs}
        assert len(span_ids) == len(recs)  # one block span each
        # and those span ids are REAL spans in the sink, under jobs.run
        events = _events(sink)
        by_id = {e["span_id"]: e for e in events}
        (run_span,) = [
            e
            for e in events
            if e["name"] == "jobs.run" and e["trace_id"] == tid
        ]
        for sid in span_ids:
            assert by_id[sid]["name"] == "jobs.block"
            assert by_id[sid]["trace_id"] == tid

    def test_resume_continues_the_same_trace(self, tmp_path, sink):
        from tensorframes_tpu.engine import resume_job, run_job

        old = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            rng = np.random.default_rng(1)
            x = rng.normal(size=(64, 4)).astype(np.float32)
            df = tft.TensorFrame.from_columns({"x": x}).analyze()
            fn = lambda x: {"y": x + 1.0}  # noqa: E731
            res = run_job("map_rows", fn, df, job_dir=str(tmp_path / "j"))
            tid = json.load(
                open(os.path.join(res.path, "manifest.json"))
            )["trace_id"]
            res2 = resume_job(res.path, fn, df)
        finally:
            set_config(max_rows_per_device_call=old)
        assert res2.blocks_restored > 0
        tid2 = json.load(
            open(os.path.join(res2.path, "manifest.json"))
        )["trace_id"]
        assert tid2 == tid
        # the resume's jobs.run span is in the ORIGINAL trace
        runs = [
            e
            for e in _events(sink)
            if e["name"] == "jobs.run" and e["trace_id"] == tid
        ]
        assert len(runs) == 2

    def test_quarantine_dumps_a_linked_bundle(
        self, tmp_path, sink, bundle_dir
    ):
        from tensorframes_tpu.engine import load_quarantine, run_job

        old = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            rng = np.random.default_rng(2)
            x = rng.normal(size=(96, 4)).astype(np.float32)
            df = tft.TensorFrame.from_columns({"x": x}).analyze()
            with chaos.scoped("jobs.block=fatal:every=2:times=1"):
                res = run_job(
                    "map_rows", lambda x: {"y": x * 3.0}, df,
                    job_dir=str(tmp_path / "q"),
                )
        finally:
            set_config(max_rows_per_device_call=old)
        (qb,) = res.quarantined
        assert qb.debug_bundle and os.path.exists(qb.debug_bundle)
        # quarantine.json links the bundle — the post-mortem starts from
        # load_quarantine alone
        (qb2,) = load_quarantine(res.path)
        assert qb2.debug_bundle == qb.debug_bundle
        bundle = json.load(open(qb.debug_bundle))
        assert bundle["reason"] == "block_quarantine"
        assert bundle["extra"]["block"] == qb.index
        assert bundle["extra"]["error_type"] == "ChaosFault"
        tid = json.load(
            open(os.path.join(res.path, "manifest.json"))
        )["trace_id"]
        assert bundle["extra"]["trace_id"] == tid
        assert any(
            e["kind"] == "quarantine" for e in bundle["rings"]["jobs"]
        )
        # the quarantine record in the ledger carries the trace too
        recs = [
            json.loads(ln)
            for ln in open(os.path.join(res.path, "ledger.jsonl"))
            if '"quarantined"' in ln
        ]
        assert recs and recs[0]["trace_id"] == tid


# ---------------------------------------------------------------------------
# dist jobs: claim -> kill -9 -> reclaim -> record as ONE trace across
# two processes and epochs (acceptance)
# ---------------------------------------------------------------------------

_TRACED_WORKER = r"""
import sys
import numpy as np
import tensorframes_tpu as tft

path, wid, ttl = sys.argv[1:4]
tft.utils.set_config(max_rows_per_device_call=16)
x = np.arange(256, dtype=np.float32).reshape(64, 4)
df = tft.TensorFrame.from_columns({"x": x}).analyze().repartition(2)
rep = tft.run_worker(
    "map_rows", lambda x: {"y": x * 3.0 + 1.0}, df, path=path,
    worker_id=wid, lease_ttl_s=float(ttl), poll_s=0.2,
)
print("WORKER_EXIT", wid)
"""


def _spawn_traced_worker(path, wid, ttl, trace_file, chaos_spec=""):
    env = dict(os.environ, JAX_PLATFORMS="cpu", TFT_TRACE_FILE=trace_file)
    env.pop("TFT_CHAOS", None)
    if chaos_spec:
        env["TFT_CHAOS"] = chaos_spec
    return subprocess.Popen(
        [sys.executable, "-c", _TRACED_WORKER, path, wid, str(ttl)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )


def _live_lease(path, worker_id):
    lease_dir = os.path.join(path, "leases")
    try:
        names = os.listdir(lease_dir)
    except FileNotFoundError:
        return None
    for n in sorted(names):
        if not (n.startswith("block-") and n.endswith(".lease")):
            continue
        try:
            with open(os.path.join(lease_dir, n)) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        if d.get("worker") == worker_id and d.get("state") != "done":
            return int(n.split(".e")[0][len("block-"):])
    return None


@pytest.mark.distjobs
@pytest.mark.chaos
class TestDistKillTrace:
    def test_claim_reclaim_record_is_one_trace_across_processes(
        self, tmp_path
    ):
        """The acceptance post-mortem: worker A claims a block and is
        kill -9'd mid-compute; worker B (a different process) reclaims
        it at epoch 1 and records it. ``manifest.json`` +
        ``ledger.jsonl`` + the JSONL trace sink — written by TWO
        processes, read by a THIRD that computed nothing — reconstruct
        claim → reclaim → record as one ``trace_id``."""
        path = str(tmp_path / "job")
        trace_file = str(tmp_path / "trace.jsonl")
        # A stalls forever inside its first block (chaos latency) while
        # heartbeating, so its lease is live until the SIGKILL
        victim = _spawn_traced_worker(
            path, "w-a", 1.5, trace_file,
            chaos_spec="jobs.block=latency:ms=120000",
        )
        drainer = None
        try:
            deadline = time.monotonic() + 120
            victim_block = None
            while victim_block is None:
                assert time.monotonic() < deadline, (
                    "victim never claimed a lease: "
                    + victim.stderr.read()
                    if victim.poll() is not None
                    else "victim never claimed a lease"
                )
                assert victim.poll() is None, victim.stderr.read()
                victim_block = _live_lease(path, "w-a")
                if victim_block is None:
                    time.sleep(0.1)
            # the claim's point event lands microseconds after the lease
            # file — but on a loaded one-core host the worker can be
            # descheduled in between. Waiting for it does not weaken the
            # kill: the chaos stall pins the worker INSIDE the block for
            # 120 s, so this is still a genuine mid-compute death.
            def claim_on_disk():
                try:
                    return any(
                        '"jobs.lease.claim"' in ln
                        for ln in open(trace_file)
                    )
                except OSError:
                    return False

            while not claim_on_disk():
                assert time.monotonic() < deadline, (
                    "victim's claim event never reached the sink"
                )
                time.sleep(0.05)
            victim.send_signal(signal.SIGKILL)
            assert victim.wait(timeout=30) == -signal.SIGKILL
            # B drains the journal after A's lease expires
            drainer = _spawn_traced_worker(path, "w-b", 20.0, trace_file)
            out_b = drainer.communicate(timeout=240)
            assert drainer.returncode == 0, out_b[1][-4000:]
        finally:
            for p in (victim, drainer):
                if p is not None and p.poll() is None:
                    p.kill()
        manifest = json.load(open(os.path.join(path, "manifest.json")))
        tid = manifest["trace_id"]
        assert re.fullmatch(r"[0-9a-f]{32}", tid)
        # the ledger: the victim's block was recorded ONCE, at epoch 1,
        # by the reclaiming worker, in the job's trace
        recs = [
            json.loads(ln)
            for ln in open(os.path.join(path, "ledger.jsonl"))
            if '"done"' in ln
        ]
        assert len(recs) == 4  # 64 rows / 16-chunks
        (vrec,) = [r for r in recs if r["block"] == victim_block]
        assert vrec["epoch"] == 1 and vrec["worker"] == "w-b"
        assert all(r["trace_id"] == tid for r in recs)
        # the trace sink (shared by both PROCESSES): the dead worker's
        # claim survived as a point event, and the reclaim is the same
        # trace one epoch later
        events = [
            json.loads(ln) for ln in open(trace_file)
        ]
        claims = [
            e
            for e in events
            if e["name"] == "jobs.lease.claim"
            and e["attrs"]["block"] == victim_block
        ]
        assert {c["trace_id"] for c in claims} == {tid}
        by_worker = {c["attrs"]["worker"]: c for c in claims}
        assert by_worker["w-a"]["attrs"]["epoch"] == 0
        assert by_worker["w-a"]["attrs"]["reclaim"] is False
        assert by_worker["w-b"]["attrs"]["epoch"] == 1
        assert by_worker["w-b"]["attrs"]["reclaim"] is True
        # the reclaimed block's compute span is in the same trace, and
        # the ledger's span_id points at a real span in the sink
        by_id = {e["span_id"]: e for e in events}
        assert by_id[vrec["span_id"]]["name"] == "jobs.block"
        assert by_id[vrec["span_id"]]["trace_id"] == tid
        # two distinct processes minted ids in one trace: the span-id
        # process prefixes differ between A's claim and B's record
        assert (
            by_worker["w-a"]["span_id"][:8]
            != by_worker["w-b"]["span_id"][:8]
        )


# ---------------------------------------------------------------------------
# docs <-> code drift (mirror of the chaos-site drift test)
# ---------------------------------------------------------------------------


class TestDocsDrift:
    @staticmethod
    def _doc_tables():
        """(metric_names, span_names) documented in the first column of
        docs/observability.md's `| name | ... |` / `| span | ... |`
        tables."""
        from pathlib import Path

        doc = (
            Path(__file__).resolve().parent.parent
            / "docs"
            / "observability.md"
        ).read_text()
        metric_names, span_names = set(), set()
        current = None
        for line in doc.splitlines():
            if not line.startswith("|"):
                current = None
                continue
            cells = [c.strip() for c in line.strip("|").split("|")]
            if not cells:
                continue
            if cells[0] == "name":
                current = metric_names
                continue
            if cells[0] == "span":
                current = span_names
                continue
            if current is None or set(cells[0]) <= {"-", " "}:
                continue
            m = re.match(r"`([^`]+)`", cells[0])
            if m:
                current.add(m.group(1))
        return metric_names, span_names

    @staticmethod
    def _package_spans():
        """Span/event names referenced as literals in package source."""
        from pathlib import Path

        import tensorframes_tpu

        root = Path(tensorframes_tpu.__file__).parent
        pat = re.compile(
            r"""(?<![A-Za-z0-9_])(?:_span|span|_trace_event|event)"""
            r"""\(\s*["']([^"']+)["']""",
        )
        found = set()
        for p in sorted(root.rglob("*.py")):
            for m in pat.finditer(p.read_text()):
                if "." in m.group(1):
                    found.add(m.group(1))
        return found

    @staticmethod
    def _registered_metrics():
        # import every module that registers series so the registry is
        # fully populated (the scrape of a live server sees the same)
        import tensorframes_tpu.data.packer  # noqa: F401
        import tensorframes_tpu.engine.dist_jobs  # noqa: F401
        import tensorframes_tpu.engine.jobs  # noqa: F401
        import tensorframes_tpu.frame.transfer  # noqa: F401
        import tensorframes_tpu.interop.serving  # noqa: F401
        import tensorframes_tpu.obs.flight  # noqa: F401
        import tensorframes_tpu.serve.engine  # noqa: F401
        import tensorframes_tpu.serve.fleet  # noqa: F401
        import tensorframes_tpu.serve.membership  # noqa: F401
        import tensorframes_tpu.tune  # noqa: F401
        import tensorframes_tpu.utils.chaos  # noqa: F401
        import tensorframes_tpu.utils.failures  # noqa: F401
        import tensorframes_tpu.utils.profiling  # noqa: F401

        return {
            n
            for n in obs.registry().names()
            if not n.startswith("t.")  # test-local scratch series
        }

    def test_every_documented_name_exists_in_the_package(self):
        """A docs table naming a series/span the package no longer emits
        lies to the operator reading a dashboard. Lazily-registered
        series (``profiling.timer_seconds``) fall back to a source-text
        mention, like the chaos drift test's composed-name escape."""
        from pathlib import Path

        import tensorframes_tpu

        metric_names, span_names = self._doc_tables()
        assert metric_names and span_names, "doc tables failed to parse"
        registered = self._registered_metrics()
        sources = "\n".join(
            p.read_text()
            for p in sorted(
                Path(tensorframes_tpu.__file__).parent.rglob("*.py")
            )
        )
        ghosts = [
            n
            for n in metric_names
            if n not in registered and f'"{n}"' not in sources
        ]
        assert not ghosts, f"documented metrics missing from package: {ghosts}"
        pkg_spans = self._package_spans()
        ghost_spans = [n for n in span_names if n not in pkg_spans]
        assert not ghost_spans, (
            f"documented spans missing from package: {ghost_spans}"
        )

    def test_every_registered_series_is_documented(self):
        metric_names, _ = self._doc_tables()
        undocumented = sorted(self._registered_metrics() - metric_names)
        assert not undocumented, (
            f"registered series missing from docs/observability.md "
            f"tables: {undocumented} — document them so operators can "
            f"find what a dashboard shows"
        )

    def test_every_package_span_is_documented(self):
        _, span_names = self._doc_tables()
        undocumented = sorted(self._package_spans() - span_names)
        assert not undocumented, (
            f"package spans missing from the docs span catalog: "
            f"{undocumented}"
        )
