"""Frame persistence round-trips (Parquet + tensor-schema sidecar).

``save_frame``/``load_frame`` must preserve what the Parquet schema alone
cannot: analyzed tensor shapes, scalar dtypes, ragged and binary columns,
and the partition count.
"""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import tensorframes_tpu as tft
from tensorframes_tpu.interop.parquet import (
    load_frame,
    map_parquet,
    save_frame,
    scan_parquet,
)


def _write_grouped(path, n=100, row_group_size=16):
    import pyarrow.parquet as pq

    t = pa.table(
        {
            "x": pa.array(np.arange(n, dtype=np.float32)),
            "v": pa.array(
                np.stack([np.arange(3.0) + i for i in range(n)]).tolist()
            ),
        }
    )
    pq.write_table(t, path, row_group_size=row_group_size)


class TestStreaming:
    def test_scan_yields_row_group_blocks(self, tmp_path):
        src = str(tmp_path / "src.parquet")
        _write_grouped(src, n=100, row_group_size=16)
        frames = list(scan_parquet(src))
        assert [f.num_rows for f in frames] == [16] * 6 + [4]
        np.testing.assert_allclose(
            np.concatenate([f.column_block("x") for f in frames]),
            np.arange(100.0),
        )

    def test_scan_grouped_blocks(self, tmp_path):
        src = str(tmp_path / "src.parquet")
        _write_grouped(src, n=100, row_group_size=16)
        frames = list(scan_parquet(src, row_groups_per_block=3))
        assert [f.num_rows for f in frames] == [48, 48, 4]

    def test_map_parquet_streams_and_round_trips(self, tmp_path):
        src = str(tmp_path / "src.parquet")
        dst = str(tmp_path / "dst.parquet")
        _write_grouped(src, n=100, row_group_size=16)
        stats = map_parquet(
            lambda x, v: {"y": x * 2.0 + v.sum(axis=-1)}, src, dst
        )
        assert stats == {"rows": 100, "blocks": 7}
        out = load_frame(dst)
        assert out.columns[0] == "y"
        expect = np.arange(100.0) * 2.0 + (
            np.arange(3.0).sum() + 3 * np.arange(100.0)
        )
        np.testing.assert_allclose(out.column_block("y"), expect)
        # inputs carried through, vector schema restored from the sidecar
        assert out.schema["v"].nesting == 1
        np.testing.assert_allclose(out.column_block("x"), np.arange(100.0))

    def test_map_parquet_cross_block_ragged_lists(self, tmp_path):
        # cells uniform WITHIN a row group but differing across groups:
        # list columns emit as variable lists so the stream survives
        import pyarrow.parquet as pq

        src = str(tmp_path / "src.parquet")
        dst = str(tmp_path / "dst.parquet")
        t = pa.table(
            {"v": pa.array([[1.0, 2.0]] * 4 + [[1.0, 2.0, 3.0]] * 4)}
        )
        pq.write_table(t, src, row_group_size=4)
        stats = map_parquet(
            lambda v: {"s": v.sum(axis=-1, keepdims=True)}, src, dst
        )
        assert stats["blocks"] == 2
        out = load_frame(dst)
        np.testing.assert_allclose(
            np.asarray(out.column_block("s")).ravel(),
            [3.0] * 4 + [6.0] * 4,
        )

    def test_map_parquet_zero_row_source(self, tmp_path):
        # a 0-row source still has one (empty) row group: it streams
        # through and produces a valid empty output with the schema
        import pyarrow.parquet as pq

        src = str(tmp_path / "empty.parquet")
        dst = str(tmp_path / "dst.parquet")
        pq.write_table(pa.table({"x": pa.array([], pa.float32())}), src)
        stats = map_parquet(lambda x: {"y": x + 1.0}, src, dst)
        assert stats == {"rows": 0, "blocks": 1}
        import os

        assert os.path.exists(dst)
        assert pq.read_table(dst).num_rows == 0

    def test_map_parquet_no_row_groups_raises(self, tmp_path):
        # a file with literally zero row groups has no block to derive
        # the output schema from
        import pyarrow.parquet as pq

        src = str(tmp_path / "norg.parquet")
        dst = str(tmp_path / "dst.parquet")
        w = pq.ParquetWriter(src, pa.schema([("x", pa.float32())]))
        w.close()
        with pytest.raises(ValueError, match="no row groups"):
            map_parquet(lambda x: {"y": x + 1.0}, src, dst)
        import os

        assert not os.path.exists(dst)
        assert not os.path.exists(dst + ".inprogress")

    def test_map_parquet_failure_leaves_no_partial_output(self, tmp_path):
        import os

        src = str(tmp_path / "src.parquet")
        dst = str(tmp_path / "dst.parquet")
        _write_grouped(src, n=32, row_group_size=16)

        def bad(x):
            raise RuntimeError("boom mid-stream")

        with pytest.raises(Exception):
            map_parquet(bad, src, dst)
        assert not os.path.exists(dst), "partial output must not land"
        assert not os.path.exists(dst + ".inprogress")

    def test_map_parquet_trim_and_block_semantics(self, tmp_path):
        # trim drops inputs; a cross-row block op sees ONE block per
        # row-group span (the partition), like the Spark mapper
        src = str(tmp_path / "src.parquet")
        dst = str(tmp_path / "dst.parquet")
        _write_grouped(src, n=32, row_group_size=16)
        map_parquet(
            lambda x: {"c": x - x.mean()}, src, dst, trim=True
        )
        out = load_frame(dst)
        assert out.columns == ["c"]
        got = np.asarray(out.column_block("c"))
        x = np.arange(32.0)
        expect = np.concatenate(
            [x[:16] - x[:16].mean(), x[16:] - x[16:].mean()]
        )
        np.testing.assert_allclose(got, expect, atol=1e-5)


def test_dense_round_trip_with_schema(tmp_path):
    p = str(tmp_path / "f.parquet")
    df = tft.TensorFrame.from_columns(
        {
            "x": np.arange(10, dtype=np.float32),
            "v": np.arange(20, dtype=np.float64).reshape(10, 2),
        },
        num_partitions=3,
    ).analyze()
    save_frame(df, p)
    back = load_frame(p)
    assert back.num_partitions == 3
    assert back.num_rows == 10
    np.testing.assert_array_equal(
        np.asarray(back.column_data("x").host()), df.column_data("x").host()
    )
    np.testing.assert_array_equal(
        np.asarray(back.column_data("v").host()), df.column_data("v").host()
    )
    for name in ("x", "v"):
        assert back.schema[name].scalar_type == df.schema[name].scalar_type
        assert (
            back.schema[name].analyzed_shape == df.schema[name].analyzed_shape
        ), name


def test_ragged_round_trip(tmp_path):
    p = str(tmp_path / "r.parquet")
    cells = [[1.0], [2.0, 3.0], [4.0, 5.0, 6.0]]
    df = tft.TensorFrame.from_rows([{"v": c} for c in cells]).analyze()
    save_frame(df, p)
    back = load_frame(p)
    got = [np.asarray(r.v).tolist() for r in back.collect()]
    assert got == cells
    assert back.schema["v"].scalar_type == df.schema["v"].scalar_type


def test_binary_round_trip(tmp_path):
    p = str(tmp_path / "b.parquet")
    blobs = [b"ab", b"", b"\x00\xff", b"xyz"]
    df = tft.TensorFrame.from_rows(
        [{"blob": b, "i": np.int64(i)} for i, b in enumerate(blobs)]
    )
    save_frame(df, p)
    back = load_frame(p)
    assert [r.blob for r in back.collect()] == blobs
    assert back.schema["blob"].scalar_type.name == "binary"


def test_device_resident_result_saves(tmp_path):
    # a lazy map result (device-resident column) must persist cleanly
    p = str(tmp_path / "d.parquet")
    df = tft.TensorFrame.from_columns({"x": np.arange(6, dtype=np.float32)})
    out = tft.map_blocks(lambda x: {"z": x * 2.0}, df)
    save_frame(out, p)
    back = load_frame(p)
    np.testing.assert_allclose(
        np.asarray(back.column_data("z").host()), np.arange(6) * 2.0
    )
