"""Frame persistence round-trips (Parquet + tensor-schema sidecar).

``save_frame``/``load_frame`` must preserve what the Parquet schema alone
cannot: analyzed tensor shapes, scalar dtypes, ragged and binary columns,
and the partition count.
"""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

import tensorframes_tpu as tft
from tensorframes_tpu.interop.parquet import load_frame, save_frame


def test_dense_round_trip_with_schema(tmp_path):
    p = str(tmp_path / "f.parquet")
    df = tft.TensorFrame.from_columns(
        {
            "x": np.arange(10, dtype=np.float32),
            "v": np.arange(20, dtype=np.float64).reshape(10, 2),
        },
        num_partitions=3,
    ).analyze()
    save_frame(df, p)
    back = load_frame(p)
    assert back.num_partitions == 3
    assert back.num_rows == 10
    np.testing.assert_array_equal(
        np.asarray(back.column_data("x").host()), df.column_data("x").host()
    )
    np.testing.assert_array_equal(
        np.asarray(back.column_data("v").host()), df.column_data("v").host()
    )
    for name in ("x", "v"):
        assert back.schema[name].scalar_type == df.schema[name].scalar_type
        assert (
            back.schema[name].analyzed_shape == df.schema[name].analyzed_shape
        ), name


def test_ragged_round_trip(tmp_path):
    p = str(tmp_path / "r.parquet")
    cells = [[1.0], [2.0, 3.0], [4.0, 5.0, 6.0]]
    df = tft.TensorFrame.from_rows([{"v": c} for c in cells]).analyze()
    save_frame(df, p)
    back = load_frame(p)
    got = [np.asarray(r.v).tolist() for r in back.collect()]
    assert got == cells
    assert back.schema["v"].scalar_type == df.schema["v"].scalar_type


def test_binary_round_trip(tmp_path):
    p = str(tmp_path / "b.parquet")
    blobs = [b"ab", b"", b"\x00\xff", b"xyz"]
    df = tft.TensorFrame.from_rows(
        [{"blob": b, "i": np.int64(i)} for i, b in enumerate(blobs)]
    )
    save_frame(df, p)
    back = load_frame(p)
    assert [r.blob for r in back.collect()] == blobs
    assert back.schema["blob"].scalar_type.name == "binary"


def test_device_resident_result_saves(tmp_path):
    # a lazy map result (device-resident column) must persist cleanly
    p = str(tmp_path / "d.parquet")
    df = tft.TensorFrame.from_columns({"x": np.arange(6, dtype=np.float32)})
    out = tft.map_blocks(lambda x: {"z": x * 2.0}, df)
    save_frame(out, p)
    back = load_frame(p)
    np.testing.assert_allclose(
        np.asarray(back.column_data("z").host()), np.arange(6) * 2.0
    )
