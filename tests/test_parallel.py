"""Distributed-engine tests over the virtual 8-device CPU mesh (conftest
forces xla_force_host_platform_device_count=8), mirroring how the reference
exercises distribution through partitioning on a local master (SURVEY §4)."""

import numpy as np
import pytest

import tensorframes_tpu as tft
import tensorframes_tpu.parallel as par

from _gates import requires_shard_map


@pytest.fixture(scope="module")
def mesh():
    import jax

    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    return par.make_mesh()


def test_mesh_shapes():
    m = par.make_mesh({"dp": 4, "tp": 2})
    assert m.shape["dp"] == 4 and m.shape["tp"] == 2
    with pytest.raises(ValueError, match="devices"):
        par.make_mesh({"dp": 64})


class TestDistributedMapBlocks:
    @requires_shard_map
    def test_divisible(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(16.0)})
        df2 = par.map_blocks(lambda x: {"z": x * 2.0}, df, mesh=mesh)
        assert [r.z for r in df2.collect()] == [2.0 * i for i in range(16)]

    @requires_shard_map
    def test_remainder_tail(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(19.0)})
        df2 = par.map_blocks(lambda x: {"z": x + 1.0}, df, mesh=mesh)
        assert [r.z for r in df2.collect()] == [float(i + 1) for i in range(19)]

    def test_small_frame_all_tail(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(3.0)})
        df2 = par.map_blocks(lambda x: {"z": -x}, df, mesh=mesh)
        assert [r.z for r in df2.collect()] == [0.0, -1.0, -2.0]

    @requires_shard_map
    def test_trim(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(16.0)})
        df2 = par.map_blocks(
            lambda x: {"z": x[:1]}, df, mesh=mesh, trim=True
        )
        rows = df2.collect()
        # one row per shard
        assert len(rows) == 8

    @requires_shard_map
    def test_vector_columns(self, mesh):
        df = tft.TensorFrame.from_columns(
            {"y": [[float(i), float(-i)] for i in range(8)]}
        ).analyze()
        df2 = par.map_blocks(lambda y: {"s": y.sum(axis=1)}, df, mesh=mesh)
        assert [r.s for r in df2.collect()] == [0.0] * 8


class TestDistributedReduce:
    @requires_shard_map
    def test_reduce_blocks_sum(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(16.0)})
        out = par.reduce_blocks(
            lambda x_input: {"x": x_input.sum()}, df, mesh=mesh
        )
        assert float(out) == sum(range(16))

    @requires_shard_map
    def test_reduce_blocks_vector_with_tail(self, mesh):
        df = tft.TensorFrame.from_columns(
            {"y": [[float(i), 1.0] for i in range(21)]}
        ).analyze()
        out = par.reduce_blocks(
            lambda y_input: {"y": y_input.sum(axis=0)}, df, mesh=mesh
        )
        np.testing.assert_allclose(out, [sum(range(21)), 21.0])

    @requires_shard_map
    def test_reduce_blocks_min(self, mesh):
        df = tft.TensorFrame.from_columns(
            {"x": np.array([5.0, -2.0, 9.0, 0.5] * 4)}
        )
        out = par.reduce_blocks(
            lambda x_input: {"x": x_input.min()}, df, mesh=mesh
        )
        assert float(out) == -2.0

    @requires_shard_map
    def test_reduce_rows(self, mesh):
        df = tft.TensorFrame.from_columns({"x": np.arange(17.0)})
        out = par.reduce_rows(
            lambda x_1, x_2: {"x": x_1 + x_2}, df, mesh=mesh
        )
        assert float(out) == sum(range(17))

    @requires_shard_map
    def test_matches_local_engine(self, mesh):
        rng = np.random.default_rng(3)
        data = rng.normal(size=(40, 3))
        df = tft.TensorFrame.from_columns({"y": data}).analyze()
        local = tft.reduce_blocks(
            lambda y_input: {"y": y_input.sum(axis=0)}, df
        )
        dist = par.reduce_blocks(
            lambda y_input: {"y": y_input.sum(axis=0)}, df, mesh=mesh
        )
        np.testing.assert_allclose(local, dist, rtol=1e-12)


def test_distributed_scalar_output_guard(mesh):
    df = tft.TensorFrame.from_columns({"x": np.arange(16.0)})
    with pytest.raises(tft.InvalidDimensionError, match="scalar"):
        par.map_blocks(lambda x: {"s": x.sum()}, df, mesh=mesh)


def test_mlp_params_update_invalidates_scoring_cache():
    from tensorframes_tpu.models import MLPClassifier, init_mlp

    df = tft.TensorFrame.from_columns(
        {"f": np.eye(4, dtype=np.float32)}
    ).analyze()
    clf = MLPClassifier.init(0, [4, 2])
    first = [r.prediction for r in clf.score_frame(df, "f").collect()]
    # swap in weights that force class 1 everywhere
    new = init_mlp(0, [4, 2])
    new[0]["w"][:] = 0.0
    new[0]["b"][:] = np.array([0.0, 100.0], dtype=np.float32)
    clf.params = new
    second = [r.prediction for r in clf.score_frame(df, "f").collect()]
    assert second == [1, 1, 1, 1]
    assert first != second or first == [1, 1, 1, 1]


class TestDistributedAggregate:
    @requires_shard_map
    def test_two_phase_matches_local(self, mesh):
        rng = np.random.default_rng(0)
        n = 50
        df = tft.TensorFrame.from_columns(
            {
                "k": rng.integers(0, 7, n).astype(np.int64),
                "v": rng.normal(size=n),
            }
        )
        local = tft.aggregate(
            lambda v_input: {"v": v_input.sum(axis=0)}, df.group_by("k")
        )
        dist = par.aggregate(
            lambda v_input: {"v": v_input.sum(axis=0)},
            df.group_by("k"),
            mesh=mesh,
        )
        lrows = {r.k: r.v for r in local.collect()}
        drows = {r.k: r.v for r in dist.collect()}
        assert set(lrows) == set(drows)
        for k in lrows:
            np.testing.assert_allclose(lrows[k], drows[k], rtol=1e-12)


class TestShardedTraining:
    def test_sgd_loss_decreases(self):
        m = par.make_mesh({"dp": 4, "tp": 2})
        trainer = par.ShardedSGDTrainer([8, 16, 3], mesh=m, lr=0.5)
        rng = np.random.default_rng(0)
        x = rng.normal(size=(32, 8)).astype(np.float32)
        y = (rng.integers(0, 3, 32)).astype(np.int32)
        params, losses = trainer.fit(x, y, steps=20)
        assert losses[-1] < losses[0]

    def test_param_shardings_alternate(self):
        m = par.make_mesh({"dp": 4, "tp": 2})
        trainer = par.ShardedSGDTrainer([8, 16, 3], mesh=m)
        sh = trainer.param_shardings()
        specs = [s["w"].spec for s in sh]
        assert specs[0] == (None, "tp")
        assert specs[1] == ("tp", None)

    def test_trained_model_scores_frame(self):
        m = par.make_mesh({"dp": 4, "tp": 2})
        trainer = par.ShardedSGDTrainer([4, 3], mesh=m, lr=0.3)
        rng = np.random.default_rng(1)
        x = rng.normal(size=(16, 4)).astype(np.float32)
        y = (x[:, 0] > 0).astype(np.int32)
        params, _ = trainer.fit(x, y, steps=30)
        from tensorframes_tpu.models import MLPClassifier
        import jax

        clf = MLPClassifier(jax.device_get(params))
        df = tft.TensorFrame.from_columns({"features": x}).analyze()
        scored = clf.score_frame(df, "features")
        preds = [r.prediction for r in scored.collect()]
        assert len(preds) == 16
        assert set(preds) <= {0, 1, 2}


class TestDistributedMapRows:
    """Distributed row ops (VERDICT r01 gap: the reference runs every op
    through its distributed plane, ``DebugRowOps.scala:396-477``)."""

    @requires_shard_map
    def test_dense_matches_local(self, mesh):
        x = np.random.default_rng(0).normal(size=(37, 3))
        df = tft.TensorFrame.from_columns({"v": x}).analyze()
        dist = par.map_rows(lambda v: {"s": v.sum()}, df, mesh=mesh)
        local = tft.map_rows(lambda v: {"s": v.sum()}, df)
        np.testing.assert_allclose(
            [r.s for r in dist.collect()], [r.s for r in local.collect()]
        )

    @requires_shard_map
    def test_scalar_cells_with_tail(self, mesh):
        # 19 rows over 8 devices: main=16 sharded, tail=3 local
        df = tft.TensorFrame.from_columns({"x": np.arange(19.0)})
        out = par.map_rows(lambda x: {"y": x * 10.0}, df, mesh=mesh)
        assert [r.y for r in out.collect()] == [10.0 * i for i in range(19)]

    def test_ragged_column(self, mesh):
        cells = [[1.0], [2.0, 3.0], [4.0, 5.0, 6.0]] * 6  # 18 rows, 3 buckets
        df = tft.TensorFrame.from_rows([{"v": c} for c in cells]).analyze()
        out = par.map_rows(lambda v: {"s": v.sum()}, df, mesh=mesh)
        expect = [float(np.sum(c)) for c in cells]
        assert [r.s for r in out.collect()] == expect

    @requires_shard_map
    def test_multi_fetch_and_passthrough(self, mesh):
        df = tft.TensorFrame.from_columns(
            {"a": np.arange(16.0), "b": np.arange(16.0) * 2}
        )
        out = par.map_rows(
            lambda a, b: {"lo": a - b, "hi": a + b}, df, mesh=mesh
        )
        rows = out.collect()
        assert set(out.columns) == {"lo", "hi", "a", "b"}
        assert rows[3].lo == -3.0 and rows[3].hi == 9.0

    @requires_shard_map
    def test_feed_dict_binding(self, mesh):
        df = tft.TensorFrame.from_columns({"col": np.arange(16.0)})
        out = par.map_rows(
            lambda x: {"y": x + 1.0}, df, mesh=mesh, feed_dict={"x": "col"}
        )
        assert out.collect()[5].y == 6.0

    def test_binary_delegates_to_host_path(self, mesh):
        df = tft.TensorFrame.from_rows(
            [{"blob": bytes([i] * (i + 1))} for i in range(10)]
        )
        out = par.map_rows(
            lambda blob: {"n": np.float64(len(blob))}, df, mesh=mesh
        )
        assert [r.n for r in out.collect()] == [float(i + 1) for i in range(10)]


class TestDistributedAggregateGeneralKeys:
    @requires_shard_map
    def test_binary_key_matches_local(self, mesh):
        rng = np.random.default_rng(3)
        names = [b"a", b"bb", b"ccc", b"dddd"]
        rows = [
            {"name": names[int(i)], "x": float(v)}
            for i, v in zip(rng.integers(0, 4, 50), rng.normal(size=50))
        ]
        df = tft.TensorFrame.from_rows(rows)
        dist = par.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)},
            df.group_by("name"),
            mesh=mesh,
        )
        local = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("name")
        )
        d = sorted((r.name, round(r.x, 6)) for r in dist.collect())
        l = sorted((r.name, round(r.x, 6)) for r in local.collect())
        assert d == l

    @requires_shard_map
    def test_mixed_multi_key(self, mesh):
        rows = [
            {"s": [b"x", b"y"][i % 2], "k": np.int64(i % 3), "v": float(i)}
            for i in range(40)
        ]
        df = tft.TensorFrame.from_rows(rows)
        dist = par.aggregate(
            lambda v_input: {"v": v_input.sum(axis=0)},
            df.group_by("s", "k"),
            mesh=mesh,
        )
        local = tft.aggregate(
            lambda v_input: {"v": v_input.sum(axis=0)}, df.group_by("s", "k")
        )
        assert sorted((r.s, int(r.k), r.v) for r in dist.collect()) == sorted(
            (r.s, int(r.k), r.v) for r in local.collect()
        )
