"""Disaggregated prefill/decode tiers: live KV-page slot migration.

The correctness bar is the same byte-identity contract every serve
feature carries, applied to a stream that MOVES between engines
mid-generation: a request prefilled on one replica and handed off to
another at first token — or rebalanced away from a pressured pool mid
decode — must stay BYTE-IDENTICAL to the same request decoded alone
through ``transformer_generate``, greedy and seeded alike, across
tensor-parallel degree changes, speculative-decoding asymmetry, and
prefix-cache/COW donors. Migration must add ZERO compiled step
programs (the snapshot restore writes pages with the same eager
indexing as COW materialization), and every failure at either chaos
site (``tier.handoff``, ``fleet.migrate``) must degrade to the
pre-tier behavior: keep decoding where the request already is, or
fall back to recompute-style preemption/replay — never a broken
stream.
"""

import json
import os
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.parallel import make_mesh
from tensorframes_tpu.serve import Fleet, GenerationEngine, QueueFullError
from tensorframes_tpu.serve.tiers import TierMigrationError
from tensorframes_tpu.utils import chaos, get_config, set_config

pytestmark = [pytest.mark.serve, pytest.mark.tiers]

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture(scope="module")
def lm_tp():
    # 8 MHA heads so tp=2 slices whole KV heads (same shape as the
    # test_serve_tp module model)
    return TransformerLM.init(0, VOCAB, d_model=32, n_heads=8, max_len=64)


@pytest.fixture
def tier_knobs():
    old = (get_config().tier_handoff, get_config().tier_rebalance)
    yield
    set_config(tier_handoff=old[0], tier_rebalance=old[1])


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _fleet(lm, n=2, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("page_size", 4)
    kw.setdefault("max_seq_len", 48)
    kw.setdefault("watchdog_interval_s", 0.02)
    return Fleet(lm, replicas=n, **kw)


def _wait_for(pred, timeout=15.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


def _mixed_requests(seed, count, n_new=10):
    """(prompt, n, kwargs) triples alternating greedy / seeded."""
    rng = np.random.default_rng(seed)
    reqs = []
    for i in range(count):
        prompt = rng.integers(1, VOCAB, size=3 + i % 5).tolist()
        kw = {} if i % 2 == 0 else {"temperature": 0.7, "seed": 40 + i}
        reqs.append((prompt, n_new, kw))
    return reqs


def _run_and_check(fleet, lm, reqs):
    """Submit every request concurrently, then assert byte-identity.
    Starts the fleet when needed — the supervisor thread is what
    drains the migration queues."""
    if fleet._thread is None:
        fleet.start()
    handles = [
        fleet.submit(p, n, **kw) for p, n, kw in reqs
    ]
    for h, (p, n, kw) in zip(handles, reqs):
        got = np.asarray(h.result(timeout=120))
        np.testing.assert_array_equal(
            got, _solo(lm, p, n, **kw),
            err_msg=f"prompt={p} kw={kw}",
        )


# ---------------------------------------------------------------------------
# engine-level export / restore (no fleet in the loop)
# ---------------------------------------------------------------------------


class TestExportRestore:
    def _engine(self, lm, **kw):
        kw.setdefault("max_slots", 4)
        kw.setdefault("page_size", 4)
        kw.setdefault("num_pages", 64)
        kw.setdefault("max_seq_len", 48)
        eng = GenerationEngine(lm, **kw)
        eng.start()
        return eng

    def test_unknown_request_returns_none(self, lm):
        eng = self._engine(lm)
        try:
            assert eng.detach_slot(999_999) is None
        finally:
            eng.stop()

    def test_engine_to_engine_byte_identity(self, lm):
        src = self._engine(lm)
        dst = self._engine(lm)
        try:
            # warm the destination's ordinary programs so the assertion
            # below isolates the attach itself (a cold engine would
            # compile its decode program on the first continued step
            # regardless of how the slot arrived)
            dst.submit([1, 2], 2).result(timeout=60)
            for kw in ({}, {"temperature": 0.6, "seed": 11}):
                prompt, n = [5, 3, 7, 1], 10
                # slow the source's decode so the request is still
                # mid-stream when the export lands (the tiny model
                # would otherwise finish all n tokens in milliseconds)
                with chaos.scoped("serve.decode_step=latency:ms=25"):
                    h = src.submit(prompt, n, **kw)
                    _wait_for(
                        lambda: len(h._tokens) >= 2,
                        what="tokens before export",
                    )
                    snap = src.detach_slot(h.request_id)
                assert snap is not None
                assert snap.n_pages >= 1 and snap.nbytes > 0
                before = dst.num_step_programs
                h2 = dst.attach_slot(snap)
                rest = h2.result(timeout=60)
                got = np.asarray(list(snap.generated) + list(rest))
                np.testing.assert_array_equal(
                    got, _solo(lm, prompt, n, **kw), err_msg=f"kw={kw}"
                )
                # restore writes pages eagerly — no new step programs
                assert dst.num_step_programs == before
        finally:
            src.stop()
            dst.stop()

    def test_still_prefilling_is_not_migratable(self, lm):
        eng = self._engine(lm, prefill_chunk_tokens=4)
        try:
            h = eng.submit(list(range(1, 25)), 4)
            # before the first generated token the slot must not export
            snap = eng.detach_slot(h.request_id)
            if snap is not None:
                # raced past prefill: the export is then legal and the
                # invariant is byte-identity, checked elsewhere
                assert snap.generated
            else:
                assert np.asarray(h.result(timeout=60)).shape == (4,)
        finally:
            eng.stop()

    def test_geometry_mismatch_raises_and_leaves_dst_clean(self, lm):
        src = self._engine(lm, page_size=4)
        dst = self._engine(lm, page_size=8)
        try:
            with chaos.scoped("serve.decode_step=latency:ms=25"):
                h = src.submit([2, 4, 6], 8)
                _wait_for(lambda: len(h._tokens) >= 1, what="first token")
                snap = src.detach_slot(h.request_id)
            assert snap is not None
            free_before = dst.pool.pages_free
            with pytest.raises(TierMigrationError):
                dst.attach_slot(snap)
            assert dst.pool.pages_free == free_before
        finally:
            src.stop()
            dst.stop()

    def test_too_long_for_destination_raises(self, lm):
        src = self._engine(lm, max_seq_len=48)
        dst = self._engine(lm, max_seq_len=16)
        try:
            with chaos.scoped("serve.decode_step=latency:ms=25"):
                h = src.submit(list(range(1, 13)), 20)
                _wait_for(lambda: len(h._tokens) >= 1, what="first token")
                snap = src.detach_slot(h.request_id)
            assert snap is not None
            with pytest.raises(TierMigrationError):
                dst.attach_slot(snap)
        finally:
            src.stop()
            dst.stop()

    def test_no_free_slot_raises_queue_full(self, lm):
        src = self._engine(lm)
        dst = self._engine(lm, max_slots=1)
        occupant = None
        try:
            occupant = dst.submit([1, 2], 40)
            _wait_for(
                lambda: any(s is not None for s in dst.scheduler.slots),
                what="occupant seated",
            )
            with chaos.scoped("serve.decode_step=latency:ms=25"):
                h = src.submit([3, 3, 3], 8)
                _wait_for(lambda: len(h._tokens) >= 1, what="first token")
                snap = src.detach_slot(h.request_id)
            assert snap is not None
            free_before = dst.pool.pages_free
            with pytest.raises(QueueFullError):
                dst.attach_slot(snap)
            assert dst.pool.pages_free == free_before
        finally:
            src.stop()
            dst.stop()


# ---------------------------------------------------------------------------
# the byte-identity matrix through a tiered fleet
# ---------------------------------------------------------------------------


class TestHandoffByteIdentity:
    def test_greedy_and_seeded_streams_survive_handoff(self, lm):
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            before = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            _run_and_check(fleet, lm, _mixed_requests(3, 6, n_new=12))
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                > before
            )
            # handoff restores compile nothing: both replicas stay at
            # the fleet's usual program budget
            assert all(n <= 2 for n in fleet.program_counts().values())
        finally:
            fleet.stop()

    @pytest.mark.parametrize("direction", ["tp1_to_tp2", "tp2_to_tp1"])
    def test_hetero_tp_handoff(self, lm_tp, direction):
        meshes = [None, make_mesh({"tp": 2})]
        if direction == "tp2_to_tp1":
            meshes.reverse()
        fleet = Fleet(
            lm_tp,
            replicas=2,
            tiers=("prefill", "decode"),
            replica_kwargs=[{"mesh": m} for m in meshes],
            max_slots=4,
            page_size=4,
            max_seq_len=48,
            watchdog_interval_s=0.02,
        )
        try:
            before = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            _run_and_check(fleet, lm_tp, _mixed_requests(7, 4, n_new=10))
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                > before
            )
        finally:
            fleet.stop()

    @pytest.mark.parametrize("spec_on", ["prefill", "decode"])
    def test_speculative_asymmetry_handoff(self, lm, spec_on):
        """The draft KV page group exists on one side only: exported
        and dropped (prefill-side spec), or absent and re-derived from
        scratch (decode-side spec). Exact-match acceptance keeps the
        bytes pinned either way."""
        spec = {"draft_params": lm.params, "draft_len": 3}
        rk = [spec, {}] if spec_on == "prefill" else [{}, spec]
        fleet = Fleet(
            lm,
            replicas=2,
            tiers=("prefill", "decode"),
            replica_kwargs=rk,
            max_slots=4,
            page_size=4,
            max_seq_len=48,
            watchdog_interval_s=0.02,
        )
        try:
            before = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            _run_and_check(fleet, lm, _mixed_requests(11, 4, n_new=12))
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                > before
            )
        finally:
            fleet.stop()

    def test_prefix_cache_donor_handoff(self, lm):
        """A request seated on cached prefix pages (COW donor path)
        still migrates byte-identically once its first token lands —
        and a request still COW-materializing simply keeps decoding
        where it is (export refuses, nothing breaks)."""
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            fleet.start()
            prompt = [4, 4, 8, 8, 2, 2, 6, 6]
            warm = fleet.submit(prompt, 6)
            np.testing.assert_array_equal(
                np.asarray(warm.result(timeout=60)), _solo(lm, prompt, 6)
            )
            before = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            reqs = [
                (prompt, 10, {}),
                (prompt, 10, {"temperature": 0.5, "seed": 21}),
            ]
            _run_and_check(fleet, lm, reqs)
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                > before
            )
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# tier-aware routing
# ---------------------------------------------------------------------------


class TestTierRouting:
    def test_new_requests_prefer_the_prefill_tier(self, lm, tier_knobs):
        set_config(tier_handoff=False)  # freeze placement for inspection
        fleet = _fleet(lm, 2, tiers=("decode", "prefill"))
        try:
            fleet.start()
            h = fleet.submit([1, 2, 3], 4)
            assert fleet._inflight[h.request_id].replica.tier == "prefill"
            np.testing.assert_array_equal(
                np.asarray(h.result(timeout=60)), _solo(lm, [1, 2, 3], 4)
            )
        finally:
            fleet.stop()

    def test_untiered_fleet_never_migrates(self, lm):
        fleet = _fleet(lm, 2)
        try:
            before = obs_metrics.snapshot().get(
                "serve.kv_migrations_total", {}
            )
            _run_and_check(fleet, lm, _mixed_requests(5, 4))
            assert obs_metrics.snapshot().get(
                "serve.kv_migrations_total", {}
            ) == before
            assert all(rep.tier == "mixed" for rep in fleet._replicas)
        finally:
            fleet.stop()

    def test_handoff_config_off_stays_put(self, lm, tier_knobs):
        set_config(tier_handoff=False)
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            before = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            _run_and_check(fleet, lm, _mixed_requests(9, 3))
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                == before
            )
        finally:
            fleet.stop()

    def test_no_decode_capacity_keeps_decoding_on_prefill(self, lm):
        # every replica is prefill: the handoff finds no destination
        # and the stream finishes where it prefilled — tiering can
        # never strand a request
        fleet = _fleet(lm, 2, tiers=("prefill", "prefill"))
        try:
            _run_and_check(fleet, lm, _mixed_requests(13, 3))
        finally:
            fleet.stop()

    def test_set_replica_tier_health_and_gauge(self, lm):
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            fleet.start()  # the supervisor publishes the per-tier gauge
            tiers = {
                n: h["tier"]
                for n, h in fleet.health()["replicas"].items()
            }
            assert sorted(tiers.values()) == ["decode", "prefill"]

            def _gauge(tier):
                return _counter_value("fleet.tier_replicas_active", tier=tier)

            _wait_for(
                lambda: _gauge("prefill") == 1.0 and _gauge("decode") == 1.0,
                what="per-tier gauge",
            )
            name = next(n for n, t in tiers.items() if t == "prefill")
            fleet.set_replica_tier(name, "mixed")
            assert fleet.health()["replicas"][name]["tier"] == "mixed"
            _wait_for(
                lambda: _gauge("prefill") == 0.0 and _gauge("mixed") == 1.0,
                what="gauge after re-tiering",
            )
            with pytest.raises(ValueError):
                fleet.set_replica_tier(name, "warp")
            with pytest.raises(KeyError):
                fleet.set_replica_tier("no-such-replica", "decode")
        finally:
            fleet.stop()

    def test_statusz_tiers_block(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            _run_and_check(fleet, lm, _mixed_requests(17, 2))
            with ScoringServer(engine=fleet) as addr:
                status, body, _ = _http(addr, "GET", "/statusz")
            assert status == 200
            block = body["tiers"]
            assert sorted(block["replicas"].values()) == [
                "decode", "prefill",
            ]
            assert isinstance(block["migrations"], dict)
        finally:
            fleet.stop()

    def test_member_advertised_tier_reaches_the_roster(self, lm, tmp_path):
        """The multi-process wiring: a MemberAgent(tier=...) carries
        its role in the lease metadata, the router's sync applies it
        on join, and a later metadata change re-roles the replica."""
        from tensorframes_tpu.serve import GenerationEngine
        from tensorframes_tpu.serve.membership import (
            MemberAgent,
            MemberRegistry,
            connect_fleet,
        )

        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, num_pages=64, max_seq_len=48,
            name="m0",
        )
        eng.start()
        agent = MemberAgent(
            eng,
            MemberRegistry(str(tmp_path), worker_id="proc-m0", ttl_s=5.0),
            "m0",
            tier="decode",
        )
        agent.start()
        fleet = None
        try:
            fleet = connect_fleet(
                str(tmp_path), worker_id="router", ttl_s=5.0,
                sync_interval_s=0.05, watchdog_interval_s=0.05,
            )
            fleet.start()
            _wait_for(
                lambda: "m0" in fleet.replica_names, what="member joining"
            )
            assert fleet.health()["replicas"]["m0"]["tier"] == "decode"
            with pytest.raises(ValueError):
                MemberAgent(eng, None, "bad", tier="warp")
        finally:
            if fleet is not None:
                fleet.stop()
                fleet.registry.stop(unlink_held=False)
            agent.shutdown(timeout_s=5.0)

    def test_statusz_tiers_none_when_all_mixed(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        fleet = _fleet(lm, 2)
        try:
            with ScoringServer(engine=fleet) as addr:
                status, body, _ = _http(addr, "GET", "/statusz")
            assert status == 200 and body["tiers"] is None
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# pool-pressure rebalancing: migrate instead of preempt
# ---------------------------------------------------------------------------


def _pressure_fleet(lm):
    # sized so the pinned replica overflows mid-decode but ONE
    # migration fully relieves it: 3 streams x 5 pages at full length
    # = 15 > 12 per-replica pages, while any 2 = 10 fit — fleet-wide
    # capacity (24) covers the whole workload, so zero preemptions is
    # actually achievable when rebalance works
    return Fleet(
        lm,
        replicas=2,
        max_slots=4,
        page_size=4,
        num_pages=12,
        max_seq_len=48,
        watchdog_interval_s=0.02,
    )


def _pressure_reqs():
    rng = np.random.default_rng(29)
    return [
        (rng.integers(1, VOCAB, size=8).tolist(), 12,
         {"temperature": 0.6, "seed": 60 + i})
        for i in range(3)
    ]


class TestRebalance:
    def test_pressure_migrates_instead_of_preempting(self, lm, tier_knobs):
        fleet = _pressure_fleet(lm)
        try:
            fleet.start()
            mig0 = _counter_value(
                "serve.kv_migrations_total", reason="rebalance"
            )
            pre0 = _counter_value("failures.preemptions_total", op="serve")
            reqs = _pressure_reqs()
            handles = [
                fleet.submit(p, n, session="hot", **kw) for p, n, kw in reqs
            ]
            for h, (p, n, kw) in zip(handles, reqs):
                np.testing.assert_array_equal(
                    np.asarray(h.result(timeout=120)),
                    _solo(lm, p, n, **kw),
                )
            assert (
                _counter_value(
                    "serve.kv_migrations_total", reason="rebalance"
                )
                > mig0
            )
            # migration absorbed the pressure: no preemption was paid
            assert (
                _counter_value("failures.preemptions_total", op="serve")
                == pre0
            )
        finally:
            fleet.stop()

    def test_rebalance_config_off_falls_back_to_preemption(
        self, lm, tier_knobs
    ):
        set_config(tier_rebalance=False)
        fleet = _pressure_fleet(lm)
        try:
            fleet.start()
            mig0 = _counter_value(
                "serve.kv_migrations_total", reason="rebalance"
            )
            pre0 = _counter_value("failures.preemptions_total", op="serve")
            reqs = _pressure_reqs()
            handles = [
                fleet.submit(p, n, session="hot", **kw) for p, n, kw in reqs
            ]
            for h, (p, n, kw) in zip(handles, reqs):
                np.testing.assert_array_equal(
                    np.asarray(h.result(timeout=120)),
                    _solo(lm, p, n, **kw),
                )
            assert (
                _counter_value(
                    "serve.kv_migrations_total", reason="rebalance"
                )
                == mig0
            )
            assert (
                _counter_value("failures.preemptions_total", op="serve")
                > pre0
            )
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# chaos at the migration sites
# ---------------------------------------------------------------------------


class TestMigrationChaos:
    def test_fatal_export_aborts_and_stream_continues(self, lm):
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            ab0 = _counter_value(
                "serve.kv_migrations_total", reason="aborted"
            )
            ok0 = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            with chaos.scoped("tier.handoff=fatal"):
                _run_and_check(fleet, lm, _mixed_requests(19, 4))
            assert (
                _counter_value("serve.kv_migrations_total", reason="aborted")
                > ab0
            )
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                == ok0
            )
        finally:
            fleet.stop()

    def test_transient_migrate_fault_retries_through(
        self, lm, fast_retries
    ):
        fleet = _fleet(lm, 2, tiers=("prefill", "decode"))
        try:
            ok0 = _counter_value(
                "serve.kv_migrations_total", reason="handoff"
            )
            with chaos.scoped("fleet.migrate=transient:every=2"):
                _run_and_check(fleet, lm, _mixed_requests(23, 4, n_new=12))
            assert (
                _counter_value("serve.kv_migrations_total", reason="handoff")
                > ok0
            )
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# HTTP plumbing for the statusz checks and the soak
# ---------------------------------------------------------------------------


def _http(addr, method, path, body=None):
    host, _, port = addr.rpartition(":")
    payload = b"" if body is None else json.dumps(body).encode()
    with socket.create_connection((host, int(port)), timeout=15) as c:
        c.sendall(
            (
                f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode() + payload
        )
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, raw = buf.partition(b"\r\n\r\n")
    status = int(head.split(b"\r\n")[0].split(b" ", 2)[1])
    try:
        parsed = json.loads(raw.decode())
    except ValueError:
        parsed = {}
    return status, parsed, {}


def _stream_req(addr, body, timeout=15.0):
    """Streaming POST /generate; (status, tokens, terminal). A torn
    connection (the router died under us) returns what was read with
    terminal None instead of raising."""
    host, _, port = addr.rpartition(":")
    payload = json.dumps(dict(body, stream=True)).encode()
    c = socket.create_connection((host, int(port)), timeout=timeout)
    toks, terminal, status = [], None, 0
    try:
        c.sendall(
            (
                f"POST /generate HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode() + payload
        )
        f = c.makefile("rb")
        status = int(f.readline().split(b" ", 2)[1])
        while f.readline() not in (b"\r\n", b""):
            pass
        if status != 200:
            try:
                terminal = json.loads(f.read().decode())
            except ValueError:
                terminal = {}
            return status, toks, terminal
        for line in f:
            line = line.strip()
            if not line:
                continue
            d = json.loads(line.decode())
            if "t" in d:
                toks.append(int(d["t"]))
            else:
                terminal = d
                break
    except OSError:
        pass
    finally:
        c.close()
    return status, toks, terminal


def _resilient_stream(addrs, body, rid, timeout=240.0):
    """Drive one stream to completion across router deaths: reconnect
    with request_id + from=<delivered> against whichever router
    answers."""
    got = []
    deadline = time.monotonic() + timeout
    i = 0
    while time.monotonic() < deadline:
        addr = addrs[i % len(addrs)]
        i += 1
        req = dict(body, request_id=rid, **{"from": len(got)})
        try:
            status, toks, term = _stream_req(addr, req, timeout=10.0)
        except OSError:
            time.sleep(0.25)
            continue
        if status in (503, 409) or status == 0:
            time.sleep(0.25)  # standby / fenced / no answer: rotate
            continue
        assert status == 200, (status, term)
        got.extend(toks)
        if term is not None:
            if term.get("done"):
                return got, term
            pytest.fail(f"stream {rid} errored: {term}")
    pytest.fail(f"stream {rid} never finished")


def _read_report(path, timeout=90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        time.sleep(0.1)
    pytest.fail(f"report {path} never appeared")


# each router subprocess hosts its OWN local tiered fleet (KV pages can
# only migrate between engines in one process) behind the shared
# router-HA lease + WAL dir: kill the active one and the standby's
# fleet replays the journal — prefill, handoff, resume — byte-identical
_TIER_ROUTER_SCRIPT = r"""
import json, os, sys, time
from tensorframes_tpu.interop.serving import ScoringServer
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.serve import Fleet
from tensorframes_tpu.serve.router_ha import attach_router_ha
from tensorframes_tpu.utils.config import set_config

ha_dir, name, report = sys.argv[1], sys.argv[2], sys.argv[3]
set_config(router_wal=True)
lm = TransformerLM.init(0, 32, d_model=16, n_heads=4, max_len=64)
fleet = Fleet(
    lm, replicas=2, tiers=("prefill", "decode"), max_slots=8,
    page_size=4, num_pages=96, max_seq_len=64,
    watchdog_interval_s=0.05,
)
ha = attach_router_ha(fleet, ha_dir, name=name, ttl_s=2.0)
fleet.start()
srv = ScoringServer(engine=fleet, max_connections=32)
host, port = srv.start()
with open(report + ".tmp", "w") as f:
    json.dump({"addr": f"{host}:{port}"}, f)
os.rename(report + ".tmp", report)
while True:
    time.sleep(0.05)
"""


def _spawn(script, args, extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-c", script, *args], env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


@pytest.mark.slow
class TestKillSoak:
    def test_kill9_mid_migration_streams_resume_byte_identical(
        self, lm, tmp_path
    ):
        """The acceptance drill: two routers, each fronting a local
        prefill/decode fleet over the shared WAL dir; 12 client
        streams with chaos LATENCY injected at ``fleet.migrate`` on
        the active router so handoffs are reliably in flight when it
        takes kill -9. The standby seizes the lease, replays the
        journal recompute-style through its own tiered fleet (prefill
        -> handoff -> decode again), and every client finishes
        byte-identical to solo with zero lost or duplicated tokens."""
        ha_dir = str(tmp_path / "ha")
        os.makedirs(ha_dir)
        r1_report = str(tmp_path / "r1.json")
        r2_report = str(tmp_path / "r2.json")
        routers = {
            # stretch the export->restore window so the kill lands
            # mid-migration for some streams
            "r1": _spawn(
                _TIER_ROUTER_SCRIPT, [ha_dir, "r1", r1_report],
                extra_env={"TFT_CHAOS": "seed=3;fleet.migrate=latency:ms=40"},
            ),
        }
        try:
            r1_addr = _read_report(r1_report)["addr"]

            def _active(addr):
                try:
                    status, body, _ = _http(addr, "GET", "/statusz")
                except OSError:
                    return False
                return status == 200 and (
                    (body.get("router") or {}).get("active") is True
                )

            _wait_for(
                lambda: _active(r1_addr), timeout=120,
                what="r1 active with its tiered fleet",
            )
            routers["r2"] = _spawn(
                _TIER_ROUTER_SCRIPT, [ha_dir, "r2", r2_report],
            )
            r2_addr = _read_report(r2_report)["addr"]
            addrs = [r1_addr, r2_addr]

            rng = np.random.default_rng(31)
            reqs = []
            for i in range(12):
                prompt = rng.integers(1, VOCAB, size=3 + i % 4).tolist()
                kw = (
                    {} if i % 3 == 0
                    else {"temperature": 0.8, "seed": 70 + i}
                )
                reqs.append((prompt, 12, kw))
            want = [_solo(lm, p, n, **kw) for p, n, kw in reqs]

            results = [None] * len(reqs)
            errors = []

            def run_client(i):
                p, n, kw = reqs[i]
                body = {"prompt": p, "max_new_tokens": n, **kw}
                try:
                    results[i] = _resilient_stream(
                        addrs, body, rid=f"mig-{i}"
                    )
                except BaseException as e:  # pytest.fail raises
                    errors.append((i, repr(e)))

            threads = [
                threading.Thread(target=run_client, args=(i,), daemon=True)
                for i in range(len(reqs))
            ]
            for i, t in enumerate(threads):
                t.start()
                time.sleep(0.1)
                if i == 5:
                    # kill -9 the ACTIVE router with handoffs in flight
                    routers["r1"].kill()
            for t in threads:
                t.join(timeout=300)
            assert not errors, errors
            assert all(r is not None for r in results)
            for i, ((toks, term), w) in enumerate(zip(results, want)):
                np.testing.assert_array_equal(
                    np.asarray(toks), np.asarray(w), err_msg=f"mig-{i}"
                )
                assert term["tokens_total"] == len(w)

            # the standby owns the lease now, and its own tiered fleet
            # performed real handoffs while absorbing the replay
            status, body, _ = _http(r2_addr, "GET", "/statusz")
            assert status == 200
            assert body["router"]["active"] is True
            assert body["router"]["epoch"] >= 1
            tiers = body["tiers"]
            assert sorted(tiers["replicas"].values()) == [
                "decode", "prefill",
            ]
            assert any(
                "handoff" in str(k) and v > 0
                for k, v in tiers["migrations"].items()
            ), tiers["migrations"]
        finally:
            for proc in routers.values():
                if proc.poll() is None:
                    try:
                        proc.kill()
                    except OSError:
                        pass
            for proc in routers.values():
                proc.wait(timeout=30)
