"""Dtype-matrix replication suite.

Analog of the reference's abstract ``CommonOperationsSuite[T]`` instantiated
per dtype (`/root/reference/src/test/scala/org/tensorframes/type_suites.scala:8-213`):
identity/monoid operations across Int/Double/Float/Long, here parametrized
over the same four scalar types for every op family."""

import numpy as np
import pytest

import tensorframes_tpu as tft

# the reference's four types, plus the TPU-first extras the registry
# advertises (bfloat16 is the MXU-native dtype; test values stay small so
# every sum is exactly representable at any precision)
DTYPES = [np.float64, np.float32, np.int32, np.int64, np.float16]
try:
    import ml_dtypes

    DTYPES.append(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


def ids(dt):
    return np.dtype(dt).name


@pytest.fixture(params=DTYPES, ids=ids)
def dtype(request):
    return request.param


def make_df(dtype, n=6, parts=2):
    return tft.TensorFrame.from_columns(
        {"x": np.arange(1, n + 1, dtype=dtype)}, num_partitions=parts
    )


class TestIdentity:
    # reference BasicIdentityTests (type_suites.scala:8-95)

    def test_scalar_identity(self, dtype):
        df = make_df(dtype)
        out = tft.map_blocks(lambda x: {"z": x}, df).collect()
        assert [r.z for r in out] == [r.x for r in out]
        assert out[0].z == dtype(1)

    def test_vector_identity(self, dtype):
        df = tft.TensorFrame.from_columns(
            {"y": np.arange(8, dtype=dtype).reshape(4, 2)}
        ).analyze()
        out = tft.map_blocks(lambda y: {"z": y}, df).collect()
        assert out[1].z.tolist() == out[1].y.tolist()

    def test_dtype_preserved(self, dtype):
        df = make_df(dtype)
        df2 = tft.map_blocks(lambda x: {"z": x + x}, df)
        assert df2.schema["z"].scalar_type.name == np.dtype(dtype).name
        block = df2.cache().column_block("z")
        assert block.dtype == np.dtype(dtype)


class TestMonoid:
    # reference BasicMonoidTests (type_suites.scala:97-187)

    def test_reduce_blocks_sum(self, dtype):
        df = make_df(dtype)
        out = tft.reduce_blocks(
            lambda x_input: {"x": x_input.sum(axis=0)}, df
        )
        assert out == dtype(21)

    def test_reduce_rows_sum(self, dtype):
        df = make_df(dtype)
        out = tft.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df)
        assert out == dtype(21)

    def test_reduce_blocks_min(self, dtype):
        df = make_df(dtype)
        out = tft.reduce_blocks(
            lambda x_input: {"x": x_input.min(axis=0)}, df
        )
        assert out == dtype(1)

    def test_aggregate_sum(self, dtype):
        df = tft.TensorFrame.from_columns(
            {
                "k": np.array([0, 0, 1, 1], dtype=np.int64),
                "x": np.array([1, 2, 3, 4], dtype=dtype),
            }
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        rows = sorted(out.collect(), key=lambda r: r.k)
        assert [r.x for r in rows] == [dtype(3), dtype(7)]

    def test_map_rows_identity(self, dtype):
        df = make_df(dtype, parts=1)
        out = tft.map_rows(lambda x: {"z": x * dtype(2)}, df).collect()
        assert [r.z for r in out] == [dtype(2 * i) for i in range(1, 7)]
