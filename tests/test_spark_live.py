"""Live-SparkSession interop tests: the partition-streaming path executed
by a REAL local-mode Spark (``DataFrame.mapInArrow``), not just the
iterator contract. The reference is a Spark package whose whole test suite
runs inside a SparkContext (core_test.py:18, DebugRowOps.scala:377-391);
this file is the equivalent end-to-end check for the interop edge.

Requires pyspark (the dedicated CI job installs it); skipped otherwise.
"""

import numpy as np
import pytest

# the ONE expected tier-1 skip: pyspark is not in the base image (it is
# an optional extra — pyproject `[project.optional-dependencies] spark`)
# and this environment cannot pip-install it. The dedicated CI job that
# installs the extra runs this file for real; everywhere else the suite
# reports exactly "1 skipped" here, and ROADMAP.md tracks it so a second
# skip appearing is a regression, not noise.
pyspark = pytest.importorskip(
    "pyspark", reason="pyspark not installed (optional `spark` extra)"
)

import tensorframes_tpu as tft
from tensorframes_tpu.interop.spark import (
    from_spark,
    map_in_arrow,
    to_spark,
)


@pytest.fixture(scope="module")
def spark():
    try:
        from pyspark.sql import SparkSession

        s = (
            SparkSession.builder.master("local[2]")
            .appName("tensorframes-tpu-live")
            .config("spark.sql.execution.arrow.pyspark.enabled", "true")
            .config("spark.ui.enabled", "false")
            .config("spark.driver.memory", "1g")
            .getOrCreate()
        )
    except Exception as e:  # no JVM on this host
        pytest.skip(f"cannot start local SparkSession: {e}")
    yield s
    s.stop()


class TestMapInArrowLive:
    def _df(self, spark, n=40, parts=3):
        rows = [(float(i),) for i in range(n)]
        return spark.createDataFrame(rows, "x double").repartition(parts)

    def test_row_local_program(self, spark):
        sdf = self._df(spark)
        out = map_in_arrow(sdf, lambda x: {"y": x * 2.0 + 1.0}, "x double, y double")
        got = {r.x: r.y for r in out.collect()}
        assert len(got) == 40
        for x, y in got.items():
            assert y == x * 2.0 + 1.0

    def test_trim_drops_inputs(self, spark):
        sdf = self._df(spark, n=12, parts=2)
        out = map_in_arrow(sdf, lambda x: {"y": x + 1.0}, "y double", trim=True)
        assert out.columns == ["y"]
        assert sorted(r.y for r in out.collect()) == [
            float(i) + 1.0 for i in range(12)
        ]

    def test_block_semantics_cover_whole_partition(self, spark):
        # block = partition: a cross-row op (partition mean) must see every
        # row of the partition regardless of Spark's Arrow chunk size
        spark.conf.set("spark.sql.execution.arrow.maxRecordsPerBatch", "3")
        try:
            sdf = self._df(spark, n=20, parts=1).coalesce(1)
            out = map_in_arrow(
                sdf,
                lambda x: {"centered": x - x.mean()},
                "x double, centered double",
            )
            rows = out.collect()
            xs = np.array([r.x for r in rows])
            centered = np.array([r.centered for r in rows])
            np.testing.assert_allclose(centered, xs - xs.mean(), rtol=1e-12)
        finally:
            spark.conf.unset("spark.sql.execution.arrow.maxRecordsPerBatch")

    def test_streaming_mode(self, spark):
        sdf = self._df(spark, n=24, parts=2)
        out = map_in_arrow(
            sdf, lambda x: {"y": x * 3.0}, "x double, y double",
            streaming=True,
        )
        got = {r.x: r.y for r in out.collect()}
        assert len(got) == 24
        for x, y in got.items():
            assert y == x * 3.0

    def test_string_columns_carry_as_binary(self, spark):
        sdf = spark.createDataFrame(
            [("a", 1.0), ("bb", 2.0)], "k string, x double"
        )
        out = map_in_arrow(
            sdf, lambda x: {"y": x + 0.5}, "k binary, x double, y double"
        )
        rows = sorted(out.collect(), key=lambda r: r.x)
        assert [bytes(r.k) for r in rows] == [b"a", b"bb"]
        assert [r.y for r in rows] == [1.5, 2.5]


class TestFrameRoundTrip:
    def test_from_spark_engine_to_spark(self, spark):
        sdf = spark.createDataFrame(
            [(float(i),) for i in range(10)], "x double"
        ).repartition(2)
        df = from_spark(sdf)
        assert df.num_partitions == 2
        mapped = tft.map_blocks(lambda x: {"y": x * x}, df)
        back = to_spark(mapped, spark)
        got = sorted((r.x, r.y) for r in back.collect())
        assert got == [(float(i), float(i * i)) for i in range(10)]

    def test_reduce_over_spark_source(self, spark):
        sdf = spark.createDataFrame(
            [(float(i),) for i in range(7)], "x double"
        )
        df = from_spark(sdf)
        total = tft.reduce_blocks(lambda x_input: {"x": x_input.sum()}, df)
        assert float(total) == float(sum(range(7)))


class TestRemoteScoringService:
    """Executors stream partitions to a ScoringServer on the chip's host
    (the inverted compute-goes-to-partitions pattern): a REAL local-mode
    Spark job maps through the remote service end to end."""

    def test_remote_map_in_arrow(self, spark):
        from tensorframes_tpu.interop import (
            ScoringServer,
            remote_map_in_arrow,
        )

        sdf = spark.createDataFrame(
            [(float(i),) for i in range(200)], "x double"
        ).repartition(4)
        with ScoringServer(lambda x: {"y": x * 2.0 + 1.0}) as addr:
            out = remote_map_in_arrow(
                sdf, addr, "y double, x double"
            ).collect()
        got = sorted((r.x, r.y) for r in out)
        assert got == [(float(i), float(i) * 2.0 + 1.0) for i in range(200)]

    def test_cross_row_block_sees_the_partition(self, spark):
        from tensorframes_tpu.interop import (
            ScoringServer,
            remote_map_in_arrow,
        )

        # one partition -> the block mean covers all 50 rows even though
        # Arrow chunks the wire transfer
        spark.conf.set("spark.sql.execution.arrow.maxRecordsPerBatch", "8")
        try:
            sdf = spark.createDataFrame(
                [(float(i),) for i in range(50)], "x double"
            ).coalesce(1)
            with ScoringServer(lambda x: {"d": x - x.mean()}) as addr:
                out = remote_map_in_arrow(sdf, addr, "d double, x double")
                rows = sorted((r.x, r.d) for r in out.collect())
            mean = np.mean(np.arange(50.0))
            for x, d in rows:
                np.testing.assert_allclose(d, x - mean, rtol=1e-6)
        finally:
            spark.conf.unset("spark.sql.execution.arrow.maxRecordsPerBatch")
