"""Profiling hooks (the reference ships none — SURVEY §5)."""

import numpy as np
import pytest

from tensorframes_tpu.utils.profiling import Timer, block_until_ready, trace


class TestTimer:
    def test_sections_accumulate(self):
        t = Timer()
        with t.section("a"):
            pass
        with t.section("a"):
            pass
        with t.section("b"):
            pass
        assert t.counts == {"a": 2, "b": 1}
        assert set(t.totals) == {"a", "b"}
        assert all(v >= 0.0 for v in t.totals.values())

    def test_section_sync_waits_on_device_work(self):
        import jax.numpy as jnp

        t = Timer()
        x = jnp.arange(1024.0)
        with t.section("matmul", sync=x):
            y = x * 2.0
        block_until_ready(y)
        assert t.counts["matmul"] == 1

    def test_report_format(self):
        t = Timer()
        with t.section("s"):
            pass
        rep = t.report()
        assert "s:" in rep and "ms/call" in rep

    def test_exception_still_recorded(self):
        t = Timer()
        with pytest.raises(ValueError):
            with t.section("boom"):
                raise ValueError("x")
        assert t.counts["boom"] == 1


class TestTrace:
    def test_trace_writes_artifacts(self, tmp_path):
        import jax.numpy as jnp

        with trace(str(tmp_path)):
            block_until_ready(jnp.arange(16.0).sum())
        # jax writes a plugins/profile tree under the log dir
        produced = list(tmp_path.rglob("*"))
        assert produced, "profiler produced no artifacts"
