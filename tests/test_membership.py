"""Elastic multi-host serving fleet: lease membership, host-death
failover, rolling restarts / weight swaps (serve/membership.py).

The correctness bar is test_fleet.py's, raised one tier again: a stream
decoded through a fleet of SEPARATE serving processes — placed over
HTTP on some member, possibly killed mid-stream (the member, not the
request) and replayed on a survivor — must stay BYTE-IDENTICAL to the
same request decoded alone, greedy and seeded sampling alike. The fast
suite runs the whole topology in-process (real ScoringServer sockets,
real NDJSON relays, real lease files; only the process boundary is
elided); the slow soak spawns three real serving subprocesses and
kill -9s one mid-stream.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import EngineUnhealthyError, GenerationEngine
from tensorframes_tpu.serve.membership import (
    Autoscaler,
    MemberAgent,
    MemberRegistry,
    RemoteEngine,
    connect_fleet,
    load_params,
    rolling_restart,
    rolling_weight_swap,
    save_params,
)
from tensorframes_tpu.utils import chaos
from tensorframes_tpu.utils.failures import (
    StaleLeaseError,
    TenantThrottledError,
)

pytestmark = pytest.mark.elastic

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _wait_for(pred, timeout=20.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    pytest.fail(f"timed out waiting for {what}")


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _engine(lm, name="m"):
    return GenerationEngine(
        lm, max_slots=4, page_size=4, num_pages=48, max_seq_len=64,
        name=name,
    )


def _http(addr, method, path, body=None):
    """One raw HTTP exchange against a member's ingress; returns
    ``(status_code, parsed_body)``."""
    host, _, port = addr.rpartition(":")
    payload = b"" if body is None else json.dumps(body).encode()
    with socket.create_connection((host, int(port)), timeout=10) as c:
        c.sendall(
            (
                f"{method} {path} HTTP/1.1\r\nHost: x\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n"
            ).encode() + payload
        )
        buf = b""
        while True:
            chunk = c.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, raw = buf.partition(b"\r\n\r\n")
    status = int(head.split(b" ", 2)[1])
    try:
        parsed = json.loads(raw.decode())
    except ValueError:
        parsed = {}
    return status, parsed


class _Member:
    """One in-process fleet member: engine + registry + agent (real
    ScoringServer socket, real lease files)."""

    def __init__(self, lm, reg_dir, name, ttl_s=5.0):
        self.engine = _engine(lm, name)
        self.engine.start()
        self.registry = MemberRegistry(
            reg_dir, worker_id=f"proc-{name}", ttl_s=ttl_s
        )
        self.agent = MemberAgent(self.engine, self.registry, name)
        self.host, self.port = self.agent.start()
        self.addr = f"{self.host}:{self.port}"


@pytest.fixture
def trio(lm, tmp_path):
    """Three members + a connected router, torn down afterwards."""
    members = [
        _Member(lm, str(tmp_path), f"m{i}", ttl_s=5.0) for i in range(3)
    ]
    fleet = connect_fleet(
        str(tmp_path), worker_id="router", ttl_s=5.0,
        sync_interval_s=0.05, watchdog_interval_s=0.05,
    )
    fleet.start()
    _wait_for(
        lambda: len(fleet.replica_names) == 3, what="3 members in roster"
    )
    yield members, fleet
    fleet.stop()
    fleet.registry.stop(unlink_held=False)
    for m in members:
        m.agent.shutdown(timeout_s=5.0)


# ---------------------------------------------------------------------------
# checkpoint round-trip
# ---------------------------------------------------------------------------


class TestCheckpoint:
    def test_params_round_trip_bytes_and_structure(self, lm, tmp_path):
        path = save_params(str(tmp_path / "w.npz"), lm)
        back = load_params(path)
        assert isinstance(back["n_heads"], int)
        assert back["n_heads"] == lm.params["n_heads"]
        assert isinstance(back["blocks"], list)
        assert len(back["blocks"]) == len(lm.params["blocks"])
        import jax

        for a, b in zip(
            jax.tree_util.tree_leaves(lm.params),
            jax.tree_util.tree_leaves(back),
        ):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_swap_rejects_mismatched_checkpoint(self, lm, tmp_path):
        other = TransformerLM.init(
            1, VOCAB, d_model=32, n_heads=4, max_len=64
        )
        path = save_params(str(tmp_path / "bad.npz"), other)
        eng = _engine(lm)
        try:
            with pytest.raises(ValueError):
                eng.swap_weights(load_params(path))
        finally:
            eng.stop()


# ---------------------------------------------------------------------------
# the registry: leases, fencing, zombie rejection
# ---------------------------------------------------------------------------


class TestMemberRegistry:
    def test_register_and_members_view(self, tmp_path):
        reg = MemberRegistry(str(tmp_path), worker_id="w0", ttl_s=5.0)
        try:
            epoch = reg.register("a", {"url": "h:1", "state": "ready"})
            assert epoch == 0
            views = reg.members()
            assert [v.key for v in views] == ["a"]
            assert views[0].meta["url"] == "h:1"
            assert not views[0].expired and not views[0].terminal
        finally:
            reg.stop()

    def test_fence_is_exactly_once_and_counted(self, tmp_path):
        reg = MemberRegistry(str(tmp_path), worker_id="w0", ttl_s=0.2)
        r1 = MemberRegistry(str(tmp_path), worker_id="router1", ttl_s=0.2)
        r2 = MemberRegistry(str(tmp_path), worker_id="router2", ttl_s=0.2)
        try:
            reg.register("a", {"url": "h:1"})
            reg.stop(unlink_held=False)  # heartbeat dies; lease lapses
            _wait_for(
                lambda: r1.members()[0].expired, what="lease expiry"
            )
            before = _counter_value("fleet.member_fences_total")
            got = [r1.fence("a"), r2.fence("a")]
            assert sorted(x is None for x in got) == [False, True]
            assert (
                _counter_value("fleet.member_fences_total") - before == 1.0
            )
            view = r1.members()[0]
            assert view.terminal and view.state == "fenced"
        finally:
            r1.stop()
            r2.stop()

    def test_zombie_publish_is_rejected_after_fence(self, tmp_path):
        member = MemberRegistry(str(tmp_path), worker_id="w0", ttl_s=60.0)
        router = MemberRegistry(str(tmp_path), worker_id="r0", ttl_s=60.0)
        try:
            member.register("a", {"url": "h:1", "state": "ready"})
            assert router.steal("a", state="fenced") is not None
            with pytest.raises(StaleLeaseError):
                member.publish_state("a", state="ready")
        finally:
            member.stop(unlink_held=False)
            router.stop()

    def test_reregister_after_tombstone_bumps_epoch(self, tmp_path):
        member = MemberRegistry(str(tmp_path), worker_id="w0", ttl_s=60.0)
        router = MemberRegistry(str(tmp_path), worker_id="r0", ttl_s=60.0)
        fresh = MemberRegistry(str(tmp_path), worker_id="w0b", ttl_s=60.0)
        try:
            e0 = member.register("a", {"url": "h:1"})
            router.steal("a", state="fenced")
            e1 = fresh.register("a", {"url": "h:2"})
            assert e1 > e0
            # the ORIGINAL incarnation stays fenced at its old epoch
            with pytest.raises(StaleLeaseError):
                member.publish_state("a", state="ready")
        finally:
            member.stop(unlink_held=False)
            fresh.stop(unlink_held=False)
            router.stop()

    def test_resign_is_terminal_without_fence_metric(self, tmp_path):
        reg = MemberRegistry(str(tmp_path), worker_id="w0", ttl_s=60.0)
        router = MemberRegistry(str(tmp_path), worker_id="r0", ttl_s=60.0)
        try:
            reg.register("a", {"url": "h:1"})
            before = _counter_value("fleet.member_fences_total")
            reg.resign("a")
            view = router.members()[0]
            assert view.terminal and view.state == "resigned"
            assert _counter_value("fleet.member_fences_total") == before
        finally:
            reg.stop(unlink_held=False)
            router.stop()

    def test_heartbeat_chaos_latency_is_the_presumed_death_drill(
        self, tmp_path
    ):
        """``latency`` on ``fleet.member_heartbeat`` past the TTL stalls
        the sweep until the lease has lapsed — any router may then
        fence, and the stalled member discovers the loss when its sweep
        finally lands (``on_lost``)."""
        lost = []
        with chaos.scoped(
            "seed=1;fleet.member_heartbeat=latency:ms=600"
        ):
            member = MemberRegistry(
                str(tmp_path), worker_id="w0", ttl_s=0.25,
                heartbeat_s=0.05,
            )
            router = MemberRegistry(
                str(tmp_path), worker_id="r0", ttl_s=0.25
            )
            try:
                member.on_lost = lambda key, epoch, cur: lost.append(key)
                member.register("a", {"url": "h:1"})
                _wait_for(
                    lambda: router.members()[0].expired,
                    what="stalled heartbeat to lapse the lease",
                )
                assert router.fence("a") is not None
                _wait_for(
                    lambda: lost == ["a"],
                    what="member discovering the stolen lease",
                )
            finally:
                member.stop(unlink_held=False)
                router.stop()


# ---------------------------------------------------------------------------
# the remote-engine adapter (unit level)
# ---------------------------------------------------------------------------


class TestRemoteEngine:
    def test_refusal_kinds_reraise_the_router_exceptions(self):
        eng = RemoteEngine("x", "127.0.0.1:1")
        cases = [
            (503, {"kind": "QueueFullError", "error": "full"}, None),
            (503, {"kind": "EngineUnhealthyError", "error": "sick"}, None),
            (503, {"kind": "Draining", "error": "draining"}, None),
            (400, {"kind": "ValueError", "error": "bad"}, None),
            (504, {"kind": "DeadlineExceededError", "error": "late"}, None),
        ]
        from tensorframes_tpu.serve.membership import (  # noqa: F401
            QueueFullError,
        )
        from tensorframes_tpu.utils.failures import DeadlineExceededError

        expect = [
            QueueFullError, EngineUnhealthyError, EngineUnhealthyError,
            ValueError, DeadlineExceededError,
        ]
        for (status, body, _), exc in zip(cases, expect):
            with pytest.raises(exc):
                eng._raise_refusal(status, json.dumps(body).encode())

    def test_tenant_throttle_reconstructs_retry_fields(self):
        eng = RemoteEngine("x", "127.0.0.1:1")
        body = {
            "kind": "TenantThrottledError", "error": "over quota",
            "retry_after": 2.5, "reason": "rate", "tenant": "t9",
        }
        with pytest.raises(TenantThrottledError) as ei:
            eng._raise_refusal(429, json.dumps(body).encode())
        assert ei.value.retry_after == 2.5
        assert ei.value.reason == "rate"
        assert ei.value.tenant == "t9"

    def test_unreachable_member_reads_unhealthy(self):
        with socket.socket() as s:  # a port nothing listens on
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        eng = RemoteEngine("x", f"127.0.0.1:{port}")
        h = eng.health()
        assert h["healthy"] is False and h["reachable"] is False
        assert not eng.healthy
        for key in (
            "queue_depth", "active_slots", "pages_in_use",
            "pages_capacity", "stepping_thread_alive",
        ):
            assert key in h


# ---------------------------------------------------------------------------
# the elastic fleet (in-process topology, real sockets + leases)
# ---------------------------------------------------------------------------


class TestElasticFleet:
    def test_byte_identity_greedy_and_seeded_over_http(self, lm, trio):
        members, fleet = trio
        rng = np.random.default_rng(3)
        reqs = []
        for i in range(8):
            prompt = rng.integers(1, VOCAB, size=4 + i % 3).tolist()
            kw = (
                {}
                if i % 2
                else {"temperature": 0.7, "seed": 100 + i, "top_p": 0.9}
            )
            reqs.append((prompt, 8, kw))
        handles = [
            fleet.submit(p, n, session=f"s{i % 4}", **kw)
            for i, (p, n, kw) in enumerate(reqs)
        ]
        for h, (p, n, kw) in zip(handles, reqs):
            got = np.asarray(h.result(timeout=60))
            np.testing.assert_array_equal(got, _solo(lm, p, n, **kw))

    def test_readyz_and_ingress_gate_through_drain_admit(self, trio):
        members, fleet = trio
        m = members[0]
        assert _http(m.addr, "GET", "/readyz")[0] == 200
        status, body = _http(
            m.addr, "POST", "/admin/lifecycle", {"action": "drain"}
        )
        assert status == 200
        status, body = _http(m.addr, "GET", "/readyz")
        assert status == 503 and body["state"] == "draining"
        # liveness is NOT affected: a balancer must not recycle it
        assert _http(m.addr, "GET", "/healthz")[0] == 200
        # the ingress sheds new work while in-flight streams finish
        status, body = _http(
            m.addr, "POST", "/generate",
            {"prompt": [1, 2], "max_new_tokens": 2},
        )
        assert status == 503 and body["kind"] == "Draining"
        # the router mirrors the drain from the registry metadata
        _wait_for(
            lambda: fleet.replica_state("m0") == "draining",
            what="router seeing the drain",
        )
        status, _ = _http(
            m.addr, "POST", "/admin/lifecycle", {"action": "admit"}
        )
        assert status == 200
        assert _http(m.addr, "GET", "/readyz")[0] == 200
        _wait_for(
            lambda: fleet.replica_state("m0") == "active",
            what="router re-admitting after probe",
        )

    def test_member_fault_replays_to_survivor_byte_identical(
        self, lm, trio
    ):
        members, fleet = trio
        prompt, n = [3, 1, 4, 1], 16
        want = _solo(lm, prompt, n, temperature=0.6, seed=11)
        with chaos.scoped("seed=1;serve.decode_step=latency:ms=15"):
            h = fleet.submit(
                prompt, n, temperature=0.6, seed=11, session="die"
            )
            _wait_for(lambda: len(h._tokens) >= 2, what="stream underway")
            victim = fleet._sessions["die"][0].name
            owner = next(m for m in members if m.agent.name == victim)
            owner.engine.inject_fault(RuntimeError("member blew up"))
        got = np.asarray(h.result(timeout=60))
        np.testing.assert_array_equal(got, want)

    def test_dead_member_is_fenced_once_and_leaves_roster(
        self, lm, trio
    ):
        members, fleet = trio
        m0 = members[0]
        before = _counter_value("fleet.member_fences_total")
        # the process "dies": ingress gone, heartbeat gone
        m0.agent.server.stop()
        m0.registry.stop(unlink_held=False)
        _wait_for(
            lambda: "m0" not in fleet.replica_names,
            timeout=30,
            what="dead member leaving the roster",
        )
        assert _counter_value("fleet.member_fences_total") - before == 1.0
        view = next(
            v for v in fleet.registry.members() if v.key == "m0"
        )
        assert view.state == "fenced"
        # the fenced member's own late write is rejected (zombie)
        with pytest.raises(StaleLeaseError):
            m0.registry.publish_state("m0", state="ready")
        # survivors still serve, byte-identically
        got = np.asarray(fleet.submit([5, 6], 6).result(timeout=60))
        np.testing.assert_array_equal(got, _solo(lm, [5, 6], 6))

    def test_rolling_restart_zero_failed_requests(self, lm, trio):
        members, fleet = trio
        rng = np.random.default_rng(5)
        stop = threading.Event()
        failures, checked = [], [0]

        def traffic():
            i = 0
            while not stop.is_set():
                prompt = rng.integers(1, VOCAB, size=3).tolist()
                try:
                    got = np.asarray(
                        fleet.submit(prompt, 4).result(timeout=60)
                    )
                    np.testing.assert_array_equal(
                        got, _solo(lm, prompt, 4)
                    )
                    checked[0] += 1
                except Exception as e:  # noqa: BLE001
                    failures.append(e)
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            out = rolling_restart(fleet, drain_timeout_s=15.0)
        finally:
            stop.set()
            t.join(timeout=30)
        assert out["outcome"] == "ok"
        assert sorted(out["restarted"]) == ["m0", "m1", "m2"]
        assert not failures, failures
        assert checked[0] > 0
        assert all(
            fleet.replica_state(n) == "active" for n in fleet.replica_names
        )

    def test_rolling_weight_swap_commits_and_stays_byte_identical(
        self, lm, trio, tmp_path
    ):
        members, fleet = trio
        before = _counter_value("fleet.rollouts_total", outcome="ok")
        ckpt = save_params(str(tmp_path / "same.npz"), lm)
        out = rolling_weight_swap(fleet, ckpt, drain_timeout_s=15.0)
        assert out["outcome"] == "ok"
        assert (
            _counter_value("fleet.rollouts_total", outcome="ok") - before
            == 1.0
        )
        got = np.asarray(
            fleet.submit([7, 8, 9], 8, temperature=0.5, seed=2).result(
                timeout=60
            )
        )
        np.testing.assert_array_equal(
            got, _solo(lm, [7, 8, 9], 8, temperature=0.5, seed=2)
        )

    def test_swap_probe_failure_rolls_back_and_halts(
        self, lm, trio, tmp_path, monkeypatch
    ):
        """A checkpoint that passes load/shape validation on every
        member but fails the probe on one: the rollout rolls EVERY
        swapped member back (mixed weights would break failover
        byte-identity) and halts."""
        members, fleet = trio
        other = TransformerLM.init(
            9, VOCAB, d_model=16, n_heads=4, max_len=64
        )
        ckpt = save_params(str(tmp_path / "new.npz"), other)
        order = list(fleet.replica_names)
        real_probe = fleet.probe_replica
        calls = []

        def failing_probe(name):
            calls.append(name)
            if len(calls) == 2:  # second member's probe "fails"
                return False
            return real_probe(name)

        monkeypatch.setattr(fleet, "probe_replica", failing_probe)
        before = _counter_value(
            "fleet.rollouts_total", outcome="rolled_back"
        )
        out = rolling_weight_swap(fleet, ckpt, drain_timeout_s=15.0)
        assert out["outcome"] == "rolled_back"
        assert out["failed"] == order[1]
        assert (
            _counter_value("fleet.rollouts_total", outcome="rolled_back")
            - before
            == 1.0
        )
        _wait_for(
            lambda: all(
                fleet.replica_state(n) == "active"
                for n in fleet.replica_names
            ),
            what="all members re-admitted on old weights",
        )
        # the OLD weights serve on EVERY member — including the ones
        # re-admitted BEFORE the failure (their rollback stash must
        # survive the per-member admit) — byte-identical to solo
        ref = _solo(lm, [2, 3], 8)
        for name in fleet.replica_names:
            rep = fleet._replica(name)
            got = np.asarray(
                rep.engine.submit([2, 3], max_new_tokens=8).result(
                    timeout=60
                )
            )
            np.testing.assert_array_equal(
                got, ref, err_msg=f"member {name} not on old weights"
            )


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


class _FakeFleet:
    def __init__(self, n):
        self.replica_names = [f"m{i}" for i in range(n)]
        self._tick_hooks = []


class TestAutoscaler:
    def _scaler(self, n=2, **kw):
        fleet = _FakeFleet(n)
        ups, downs = [], []
        sig = {"queue_depth": 0.0, "pages_frac": 0.0, "itl_p99_s": 0.0,
               "members": float(n)}
        kw.setdefault("cooldown_s", 10.0)
        sc = Autoscaler(
            fleet,
            scale_up=lambda: ups.append(1),
            scale_down=lambda: downs.append(1),
            min_members=1, max_members=4,
            queue_high=8, pages_frac_high=0.85, itl_p99_high_s=1.0,
            signals_fn=lambda: sig,
            **kw,
        )
        return sc, sig, ups, downs

    def test_scale_up_on_any_pressure_signal(self):
        for key, value in (
            ("queue_depth", 20.0),
            ("pages_frac", 0.95),
            ("itl_p99_s", 3.0),
        ):
            sc, sig, ups, downs = self._scaler()
            sig[key] = value
            assert sc.evaluate(now=100.0) == "up"
            assert ups and not downs

    def test_scale_down_only_when_everything_is_quiet(self):
        sc, sig, ups, downs = self._scaler()
        assert sc.evaluate(now=100.0) == "down"
        assert downs and not ups
        sc, sig, ups, downs = self._scaler()
        sig["queue_depth"] = 3.0  # above queue_low: not quiet
        assert sc.evaluate(now=100.0) is None

    def test_bounds_and_cooldown(self):
        sc, sig, ups, downs = self._scaler(n=4)
        sig["queue_depth"] = 50.0
        assert sc.evaluate(now=100.0) is None  # at max_members
        sc, sig, ups, downs = self._scaler(n=1)
        assert sc.evaluate(now=100.0) is None  # at min_members
        sc, sig, ups, downs = self._scaler()
        sig["queue_depth"] = 50.0
        before = _counter_value(
            "fleet.scale_decisions_total", direction="up"
        )
        assert sc.evaluate(now=100.0) == "up"
        assert sc.evaluate(now=105.0) is None  # inside cooldown
        assert sc.evaluate(now=111.0) == "up"  # past it
        assert (
            _counter_value("fleet.scale_decisions_total", direction="up")
            - before
            == 2.0
        )


# ---------------------------------------------------------------------------
# docs drift: the ingress surface must stay documented
# ---------------------------------------------------------------------------


class TestEndpointDocsDrift:
    def test_every_route_is_documented(self):
        """Every route the ingress answers must appear in the docs —
        in particular the liveness/readiness SPLIT (`/healthz` vs
        `/readyz`) and the lifecycle actuator, which operators and
        balancer configs are built against."""
        from pathlib import Path

        from tensorframes_tpu.interop.serving import ScoringServer

        docs_root = Path(__file__).resolve().parent.parent / "docs"
        corpus = "".join(
            p.read_text()
            for p in (
                docs_root / "observability.md",
                docs_root / "serving_llm.md",
                docs_root / "fault_tolerance.md",
            )
        )
        missing = [r for r in ScoringServer._ROUTES if r not in corpus]
        assert not missing, (
            f"ingress routes missing from the docs: {missing}"
        )


# ---------------------------------------------------------------------------
# SIGTERM: the graceful exit (real subprocess)
# ---------------------------------------------------------------------------


_MEMBER_SCRIPT = r"""
import json, os, sys, time
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.serve import GenerationEngine
from tensorframes_tpu.serve.membership import MemberAgent, MemberRegistry

reg_dir, name, ttl = sys.argv[1], sys.argv[2], float(sys.argv[3])
lm = TransformerLM.init(0, 32, d_model=16, n_heads=4, max_len=64)
eng = GenerationEngine(
    lm, max_slots=4, page_size=4, num_pages=48, max_seq_len=64, name=name
)
eng.start()
agent = MemberAgent(
    eng, MemberRegistry(reg_dir, worker_id=f"proc-{name}", ttl_s=ttl), name
)
agent.start()
agent.install_sigterm()
report = sys.argv[4] if len(sys.argv) > 4 else ""
while True:
    time.sleep(0.05)
    if report and agent.state == "fenced":
        out = {"fenced": True}
        try:
            agent.registry.publish_state(name, state="ready")
            out["zombie_rejected"] = False
        except Exception as e:
            out["zombie_rejected"] = type(e).__name__ == "StaleLeaseError"
        with open(report + ".tmp", "w") as f:
            json.dump(out, f)
        os.rename(report + ".tmp", report)
        report = ""
"""


def _spawn_member(reg_dir, name, ttl, report="", extra_env=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(extra_env or {})
    args = [sys.executable, "-c", _MEMBER_SCRIPT, reg_dir, name, str(ttl)]
    if report:
        args.append(report)
    return subprocess.Popen(
        args, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


class TestSigtermDrain:
    def test_sigterm_drains_resigns_and_exports(self, tmp_path):
        reg_dir = str(tmp_path / "reg")
        tele_dir = str(tmp_path / "tele")
        os.makedirs(tele_dir)
        proc = _spawn_member(
            reg_dir, "w0", 30.0,
            extra_env={"TFT_TELEMETRY_DIR": tele_dir},
        )
        router = None
        try:
            router = MemberRegistry(
                reg_dir, worker_id="router", ttl_s=30.0
            )
            _wait_for(
                lambda: any(
                    not v.terminal for v in router.members()
                ),
                timeout=60,
                what="member registration",
            )
            url = router.members()[0].meta["url"]
            status, body = _http(
                url, "POST", "/generate",
                {"prompt": [1, 2, 3], "max_new_tokens": 4},
            )
            assert status == 200 and len(body["tokens"]) == 4
            proc.send_signal(signal.SIGTERM)
            assert proc.wait(timeout=60) == 0
            # the lease is resigned or gone — never left to lapse
            views = router.members()
            assert all(v.terminal for v in views), [
                (v.key, v.state) for v in views
            ]
            # the final telemetry snapshot made it out
            assert any(
                f.endswith(".json") for f in os.listdir(tele_dir)
            ), os.listdir(tele_dir)
        finally:
            if router is not None:
                router.stop()
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)


# ---------------------------------------------------------------------------
# the acceptance soak: three real serving processes, one router
# ---------------------------------------------------------------------------


@pytest.mark.slow
class TestMultiProcessSoak:
    def test_kill_wedge_swap_soak(self, lm, tmp_path):
        """The ISSUE's acceptance drill: three serving subprocesses
        behind one router; 16 staggered streams (greedy and seeded);
        a rolling weight swap (identical weights) mid-traffic with
        zero failed requests; one member kill -9'd mid-stream; one
        member wedged past its lease TTL by chaos latency on its
        heartbeat. Every stream byte-identical to solo; the victims
        fenced exactly once each; the wedged zombie's late registry
        write epoch-rejected.

        Timeline: the swap races phase-1 traffic while all three
        leases are fresh (the wedged member's longer TTL keeps it
        registered through the swap); the kill -9 lands mid-phase-2;
        the wedge fences at its TTL and the zombie discovers it when
        the stalled sweep finally returns."""
        reg_dir = str(tmp_path / "reg")
        decode_lag = "serve.decode_step=latency:ms=12"
        ttl = 8.0
        procs = {
            # m0 survives; m1 gets kill -9; m2 wedges (its heartbeat
            # stalls 20s — longer than the TTL — on its first sweep, so
            # it is fenced while wedged and learns on the late sweep)
            "m0": _spawn_member(
                reg_dir, "m0", ttl,
                extra_env={"TFT_CHAOS": f"seed=1;{decode_lag}"},
            ),
            "m1": _spawn_member(
                reg_dir, "m1", ttl,
                extra_env={"TFT_CHAOS": f"seed=2;{decode_lag}"},
            ),
            # m2 gets a longer TTL (20s) so the rolling swap finishes
            # before its wedge lapses the lease; the 45s stall is still
            # far past it, so the presumed-death fence fires while the
            # member is genuinely unresponsive
            "m2": _spawn_member(
                reg_dir, "m2", 20.0,
                report=str(tmp_path / "m2.report.json"),
                extra_env={
                    "TFT_CHAOS": (
                        f"seed=3;{decode_lag};"
                        "fleet.member_heartbeat=latency:p=1:ms=45000"
                    )
                },
            ),
        }
        fleet = None
        try:
            fleet = connect_fleet(
                reg_dir, worker_id="router", ttl_s=ttl,
                sync_interval_s=0.1, watchdog_interval_s=0.05,
                failover_timeout_s=120.0,
            )
            fleet.start()
            _wait_for(
                lambda: len(fleet.replica_names) == 3,
                timeout=90,
                what="3 subprocess members joining",
            )
            fences_before = _counter_value("fleet.member_fences_total")

            rng = np.random.default_rng(17)
            reqs = []
            for i in range(16):
                prompt = rng.integers(1, VOCAB, size=3 + i % 4).tolist()
                kw = (
                    {}
                    if i % 3 == 0
                    else {"temperature": 0.8, "seed": 40 + i}
                )
                reqs.append((prompt, 12, kw))
            want = [_solo(lm, p, n, **kw) for p, n, kw in reqs]

            handles = []
            swap_result = {}

            def run_swap():
                ckpt = save_params(str(tmp_path / "same.npz"), lm)
                swap_result.update(
                    rolling_weight_swap(fleet, ckpt, drain_timeout_s=20.0)
                )

            # phase 1: staggered streams with the rolling swap racing
            # them (all three members healthy: m2's lease stays fresh
            # until its stalled sweep lapses it at ~TTL)
            swapper = None
            for i in range(8):
                p, n, kw = reqs[i]
                handles.append(
                    fleet.submit(p, n, session=f"s{i % 5}", **kw)
                )
                time.sleep(0.12)
                if i == 3:
                    swapper = threading.Thread(
                        target=run_swap, daemon=True
                    )
                    swapper.start()
            swapper.join(timeout=180)
            assert not swapper.is_alive(), "rolling swap never finished"
            # zero failed requests through the swap, and it committed
            assert swap_result.get("outcome") == "ok", swap_result

            # phase 2: more staggered streams; kill -9 one member with
            # its streams in flight
            for i in range(8, 16):
                p, n, kw = reqs[i]
                handles.append(
                    fleet.submit(p, n, session=f"s{i % 5}", **kw)
                )
                time.sleep(0.12)
                if i == 9:
                    procs["m1"].kill()  # SIGKILL mid-stream

            for h, w in zip(handles, want):
                got = np.asarray(h.result(timeout=180))
                np.testing.assert_array_equal(got, np.asarray(w))

            # both victims fenced, each exactly once
            _wait_for(
                lambda: _counter_value("fleet.member_fences_total")
                - fences_before
                >= 2.0,
                timeout=90,
                what="both victims fenced",
            )
            _wait_for(
                lambda: set(fleet.replica_names) == {"m0"},
                timeout=90,
                what="victims leaving the roster",
            )
            assert (
                _counter_value("fleet.member_fences_total")
                - fences_before
                == 2.0
            )
            states = {
                v.key: v.state for v in fleet.registry.members()
            }
            assert states["m1"] == "fenced"
            assert states["m2"] == "fenced"

            # the wedged zombie discovered the fence and its late
            # write was epoch-rejected
            report_path = str(tmp_path / "m2.report.json")
            _wait_for(
                lambda: os.path.exists(report_path),
                timeout=90,
                what="the wedged member's zombie report",
            )
            with open(report_path) as f:
                report = json.load(f)
            assert report == {"fenced": True, "zombie_rejected": True}

            # the survivor still serves byte-identically
            got = np.asarray(
                fleet.submit([9, 9, 2], 6, temperature=0.4, seed=5)
                .result(timeout=120)
            )
            np.testing.assert_array_equal(
                got, _solo(lm, [9, 9, 2], 6, temperature=0.4, seed=5)
            )
        finally:
            if fleet is not None:
                fleet.stop()
                fleet.registry.stop(unlink_held=False)
            for proc in procs.values():
                if proc.poll() is None:
                    proc.kill()
                proc.wait(timeout=30)
