"""CNN image scoring: the reference's frozen-VGG-over-binary-rows workload
(``read_image.py:147-167``) done TPU-first (host decode -> batched device
convs)."""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.frame import TensorFrame
from tensorframes_tpu.models import CNNScorer, cnn_embed, cnn_logits, init_cnn
from tensorframes_tpu.utils import get_config, set_config

from _gates import requires_shard_map


def _image_frame(scorer, n=12, parts=3, seed=0):
    rng = np.random.default_rng(seed)
    h, w = scorer.input_hw
    imgs = rng.integers(0, 256, size=(n, h, w, scorer.channels), dtype=np.uint8)
    raws = [im.tobytes() for im in imgs]
    df = TensorFrame.from_columns({"image_data": raws}, num_partitions=parts)
    return df, imgs


class TestCNN:
    def test_embed_shapes(self):
        p = init_cnn(0, input_hw=(16, 16), block_widths=(8, 16), embed_dim=32)
        x = np.zeros((4, 16, 16, 3), dtype=np.uint8)
        emb = np.asarray(cnn_embed(p, x))
        assert emb.shape == (4, 32)
        assert emb.dtype == np.float32

    def test_logits_head(self):
        p = init_cnn(
            0, input_hw=(16, 16), block_widths=(8,), embed_dim=16, num_classes=5
        )
        x = np.random.default_rng(0).normal(size=(3, 16, 16, 3)).astype(np.float32)
        assert np.asarray(cnn_logits(p, x)).shape == (3, 5)
        with pytest.raises(ValueError, match="num_classes"):
            cnn_logits(init_cnn(0, input_hw=(16, 16), block_widths=(8,)), x)

    def test_uint8_normalized_on_device(self):
        p = init_cnn(0, input_hw=(8, 8), block_widths=(4,), embed_dim=8)
        img = np.random.default_rng(1).integers(
            0, 256, size=(2, 8, 8, 3), dtype=np.uint8
        )
        a = np.asarray(cnn_embed(p, img))
        b = np.asarray(cnn_embed(p, img.astype(np.float32) / 255.0))
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)

    def test_score_frame_matches_direct(self):
        scorer = CNNScorer.init(
            0, input_hw=(16, 16), block_widths=(8, 16), embed_dim=32
        )
        df, imgs = _image_frame(scorer)
        out = scorer.score_frame(df, "image_data", compute_dtype=None)
        got = np.asarray(out.cache().column_block("embedding"))
        want = np.asarray(cnn_embed(scorer.params, imgs))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    @requires_shard_map
    def test_score_frame_distributed(self):
        from tensorframes_tpu import parallel

        scorer = CNNScorer.init(
            0, input_hw=(16, 16), block_widths=(8,), embed_dim=16
        )
        df, imgs = _image_frame(scorer, n=32, parts=8)
        out = scorer.score_frame(
            df, "image_data", engine=parallel, compute_dtype=None
        )
        got = np.asarray(out.cache().column_block("embedding"))
        want = np.asarray(cnn_embed(scorer.params, imgs))
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_bfloat16_close_to_f32(self):
        scorer = CNNScorer.init(
            0, input_hw=(16, 16), block_widths=(8,), embed_dim=16
        )
        df, imgs = _image_frame(scorer, n=6, parts=1)
        bf = np.asarray(
            scorer.score_frame(df, "image_data").cache().column_block("embedding")
        )
        f32 = np.asarray(cnn_embed(scorer.params, imgs))
        # bf16 matmul precision: loose tolerance, but must correlate tightly
        assert np.corrcoef(bf.ravel(), f32.ravel())[0, 1] > 0.999


class TestMapRowsChunking:
    def test_large_bucket_chunks_match_unchunked(self):
        old = get_config().max_rows_per_device_call
        try:
            df = TensorFrame.from_columns(
                {"x": np.arange(100, dtype=np.float64)}
            )
            fn = lambda x: {"y": x * 2.0}
            set_config(max_rows_per_device_call=7)  # forces 15 chunks
            chunked = [r.y for r in tft.map_rows(fn, df).collect()]
            set_config(max_rows_per_device_call=old)
            whole = [r.y for r in tft.map_rows(fn, df).collect()]
            assert chunked == whole
        finally:
            set_config(max_rows_per_device_call=old)
