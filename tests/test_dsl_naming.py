"""Golden tests pinning DSL naming semantics.

The reference differential-tests its DSL's emitted NodeDefs node-by-node
against real TF (ExtractNodes.scala:13-74); numerics tests alone would let
scope/auto-number behavior drift silently. These goldens pin the exact
name strings the DSL produces — a drifted name fails the suite.
"""

import threading

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.capture import dsl
from tensorframes_tpu.capture.dsl import build_graph, graph, scope


@pytest.fixture
def df():
    return tft.TensorFrame.from_columns(
        {"x": np.arange(4, dtype=np.float64)}
    )


class TestAutoNumbering:
    def test_first_use_is_bare_then_suffixed(self, df):
        with graph():
            x = dsl.block(df, "x")
            a = x + x
            b = x + a
            c = a * b
            d = a * c
        assert x.name == "x"
        assert a.name == "add"
        assert b.name == "add_1"
        assert c.name == "mul"
        assert d.name == "mul_1"

    def test_counters_are_per_op_name(self, df):
        with graph():
            x = dsl.block(df, "x")
            nodes = [x + 1.0, x - 1.0, x * 2.0, x + 2.0, x - 2.0]
        assert [n.name for n in nodes] == ["add", "sub", "mul", "add_1", "sub_1"]

    def test_graph_resets_counters(self, df):
        with graph():
            first = dsl.block(df, "x") + 1.0
        with graph():
            second = dsl.block(df, "x") + 1.0
        assert first.name == "add"
        assert second.name == "add"

    def test_apply_op_default_and_custom_op_name(self, df):
        with graph():
            x = dsl.block(df, "x")
            o1 = dsl.apply_op(lambda a: a * 3.0, x)
            o2 = dsl.apply_op(lambda a: a * 3.0, x)
            s = dsl.apply_op(lambda a: a.sum(), x, op_name="reduce_sum")
        assert o1.name == "op"
        assert o2.name == "op_1"
        assert s.name == "reduce_sum"


class TestScopes:
    def test_scope_prefixes_with_slash(self, df):
        with graph():
            x = dsl.block(df, "x")
            with scope("layer"):
                a = x + 1.0
        assert a.name == "layer/add"

    def test_nested_scopes_join(self, df):
        with graph():
            x = dsl.block(df, "x")
            with scope("outer"):
                with scope("inner"):
                    a = x * 2.0
                b = x * 2.0
            c = x * 2.0
        assert a.name == "outer/inner/mul"
        assert b.name == "outer/mul"
        assert c.name == "mul"

    def test_counters_are_per_scoped_path(self, df):
        # the same op name in different scopes does NOT share a counter
        # (reference Paths.scala keys the counter by the full path)
        with graph():
            x = dsl.block(df, "x")
            with scope("s"):
                a1 = x + 1.0
                a2 = x + 1.0
            b1 = x + 1.0
        assert a1.name == "s/add"
        assert a2.name == "s/add_1"
        assert b1.name == "add"

    def test_named_override_respects_scope(self, df):
        with graph():
            x = dsl.block(df, "x")
            with scope("s"):
                a = (x + 1.0).named("result")
        assert a.name == "s/result"

    def test_explicit_name_at_construction(self, df):
        with graph():
            x = dsl.block(df, "x")
            with scope("s"):
                a = dsl.apply_op(lambda v: v + 1.0, x, name="out")
        assert a.name == "s/out"


class TestPlaceholderNaming:
    def test_block_uses_column_name(self, df):
        with graph():
            x = dsl.block(df, "x")
        assert x.name == "x"
        assert dsl.bound_column(x) == "x"

    def test_renamed_placeholder_keeps_column_binding(self, df):
        with graph():
            x = dsl.block(df, "x").named("input")
            g = build_graph(x + 1.0)
        assert "input" in g.placeholders
        assert g.inputs_map["input"] == "x"

    def test_constant_auto_name(self):
        with graph():
            c1 = dsl.constant(3.0)
            c2 = dsl.constant(4.0)
        assert c1.name == "constant"
        assert c2.name == "constant_1"


class TestNodeSummariesGolden:
    """Textual pin of the analyzeGraphTF-analog output (the reference pins
    NodeDef text; here the (name, kind, dtype, shape) tuples)."""

    def _render(self, summaries):
        return [
            f"{'in' if s.is_input else 'out'} {s.name}: "
            f"{s.scalar_type.name}{list(s.shape.dims)}"
            for s in summaries
        ]

    def test_simple_map_graph(self, df):
        with graph():
            x = dsl.block(df, "x")
            y = (x * 2.0).named("y")
            g = build_graph(y)
        assert self._render(g.node_summaries()) == [
            "in x: float64[-1]",
            "out y: float64[-1]",
        ]

    def test_scoped_two_fetch_graph(self, df):
        with graph():
            x = dsl.block(df, "x")
            with scope("stats"):
                lo = (x - 1.0).named("lo")
                hi = (x + 1.0).named("hi")
            g = build_graph([lo, hi])
        assert self._render(g.node_summaries()) == [
            "in x: float64[-1]",
            "out stats/lo: float64[-1]",
            "out stats/hi: float64[-1]",
        ]


class TestThreadLocality:
    def test_counters_do_not_leak_across_threads(self, df):
        # the reference's Paths object is explicitly NOT thread-safe
        # (Paths.scala:10-12); this DSL's state is thread-local by design
        results = {}

        def worker(tag):
            with graph():
                x = dsl.placeholder(np.float64, [None], name=f"x{tag}")
                a = x + 1.0
                b = x + 2.0
                results[tag] = (a.name, b.name)

        threads = [
            threading.Thread(target=worker, args=(i,)) for i in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        for tag in range(4):
            assert results[tag] == ("add", "add_1")
