"""Capture-layer tests: DSL naming/wiring (analog of the reference's DSL
suites + ExtractNodes oracle tests), analysis (analog of
TFInitializationSuite's analyzeGraphTF round-trips), serialization."""

import numpy as np
import pytest

import tensorframes_tpu.capture as cap
from tensorframes_tpu.capture import functions as F
from tensorframes_tpu.frame import TensorFrame
from tensorframes_tpu.schema import FLOAT64, INT32, Shape, Unknown


def make_df():
    return TensorFrame.from_columns({"x": np.arange(10.0)})


def make_vec_df():
    return TensorFrame.from_columns(
        {"y": [[float(i), float(-i)] for i in range(10)]}
    ).analyze()


class TestNaming:
    def test_auto_numbering(self):
        with cap.graph():
            a = cap.constant(1.0)
            b = cap.constant(2.0)
            c = a + b
            d = a + b
            assert a.name == "constant"
            assert b.name == "constant_1"
            assert c.name == "add"
            assert d.name == "add_1"

    def test_named(self):
        with cap.graph():
            z = (cap.constant(1.0) + 3).named("z")
            assert z.name == "z"

    def test_scope(self):
        with cap.graph():
            with cap.scope("outer"):
                a = cap.constant(1.0)
                z = F.identity(a, name="z")
            assert a.name == "outer/constant"
            assert z.name == "outer/z"

    def test_graph_isolation(self):
        with cap.graph():
            a = cap.constant(1.0)
        with cap.graph():
            b = cap.constant(1.0)
        assert a.name == b.name == "constant"


class TestCapture:
    def test_block_placeholder_shape(self):
        df = make_vec_df()
        with cap.graph():
            y = cap.block(df, "y")
            assert y.ph_spec.shape == Shape(Unknown, 2)
            r = cap.row(df, "y")
            assert r.ph_spec.shape == Shape(2)

    def test_capture_simple(self):
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            z = (x + 3.0).named("z")
            g = cap.build_graph(z)
        assert list(g.placeholders) == ["x"]
        assert g.fetch_names == ["z"]
        assert g.inputs_map == {"x": "x"}

    def test_renamed_placeholder_keeps_binding(self):
        # reference README.md:116-117: tfs.block(df3, 'y', tf_name='y_input')
        df = make_vec_df()
        with cap.graph():
            y_in = cap.block(df, "y", tft_name="y_input")
            s = F.reduce_sum(y_in, axis=[0], name="y")
            g = cap.build_graph(s)
        assert g.inputs_map == {"y_input": "y"}

    def test_duplicate_fetches_rejected(self):
        # reference core.py:105-107
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            a = (x + 1).named("z")
            b = (x + 2).named("z")
            with pytest.raises(ValueError, match="unique names"):
                cap.build_graph([a, b])

    def test_fn_evaluates(self):
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            z = (x * 2.0 + 1.0).named("z")
            g = cap.build_graph(z)
        out = g.fn({"x": np.arange(4.0)})
        np.testing.assert_allclose(np.asarray(out["z"]), [1, 3, 5, 7])

    def test_constant_only_graph(self):
        with cap.graph():
            c = (cap.constant(np.array([1.0, 2.0])) * 2).named("c")
            g = cap.build_graph(c)
        assert list(g.placeholders) == []
        np.testing.assert_allclose(np.asarray(g.fn({})["c"]), [2.0, 4.0])


class TestAnalysis:
    def test_analyze_block_add(self):
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            z = (x + 3.0).named("z")
            g = cap.build_graph(z)
        out = g.analyze()
        assert out["z"].scalar_type is FLOAT64
        assert out["z"].shape == Shape(Unknown)

    def test_analyze_reduce_shape(self):
        df = make_vec_df()
        with cap.graph():
            y_in = cap.block(df, "y", tft_name="y_input")
            s = F.reduce_sum(y_in, axis=[0], name="y")
            g = cap.build_graph(s)
        out = g.analyze(input_shapes={"y_input": Shape(Unknown, 2)})
        assert out["y"].shape == Shape(2)

    def test_analyze_preserves_symbolic_lead(self):
        df = make_vec_df()
        with cap.graph():
            y = cap.block(df, "y")
            z = F.reduce_sum(y, axis=[1], name="z")
            g = cap.build_graph(z)
        out = g.analyze()
        # lead dim rides through the op: stays Unknown (symbolic)
        assert out["z"].shape == Shape(Unknown)

    def test_analyze_int_dtype(self):
        df = TensorFrame.from_columns({"k": np.arange(5, dtype=np.int32)})
        with cap.graph():
            k = cap.block(df, "k")
            z = (k * 2).named("z")
            g = cap.build_graph(z)
        out = g.analyze()
        assert out["z"].scalar_type is INT32

    def test_shape_hint_overrides(self):
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            z = F.identity(x, name="z")
            g = cap.build_graph(z).with_hints({"z": Shape(10)})
        out = g.analyze()
        assert out["z"].shape == Shape(10)

    def test_missing_fetch_detected(self):
        g = cap.CapturedGraph.from_callable(
            lambda x: {"a": x},
            {"x": (FLOAT64, Shape(Unknown))},
            fetch_names=["zz"],
        )
        with pytest.raises(KeyError, match="zz"):
            g.analyze()

    def test_node_summaries(self):
        df = make_df()
        with cap.graph():
            x = cap.block(df, "x")
            z = (x + 1.0).named("z")
            g = cap.build_graph(z)
        summaries = g.node_summaries()
        by_name = {s.name: s for s in summaries}
        assert by_name["x"].is_input and not by_name["x"].is_output
        assert by_name["z"].is_output


class TestCallableFrontend:
    def test_from_callable_infers_fetches(self):
        g = cap.CapturedGraph.from_callable(
            lambda x: {"z": x + 3.0, "w": x * 2.0},
            {"x": (FLOAT64, Shape(Unknown))},
        )
        assert set(g.fetch_names) == {"z", "w"}
        out = g.analyze()
        assert out["z"].shape == Shape(Unknown)

    def test_single_fetch_array_return(self):
        g = cap.CapturedGraph.from_callable(
            lambda x: x + 1.0,
            {"x": (FLOAT64, Shape(Unknown))},
            fetch_names=["z"],
        )
        out = g.fn({"x": np.arange(3.0)})
        np.testing.assert_allclose(np.asarray(out["z"]), [1, 2, 3])

    def test_feed_dict_merge(self):
        g = cap.CapturedGraph.from_callable(
            lambda inp: {"z": inp * 2},
            {"inp": (FLOAT64, Shape(Unknown))},
        ).with_inputs({"inp": "some_col"})
        assert g.inputs_map == {"inp": "some_col"}
        with pytest.raises(KeyError, match="unknown placeholder"):
            g.with_inputs({"nope": "c"})


class TestSerialize:
    def test_roundtrip(self, tmp_path):
        df = make_vec_df()
        with cap.graph():
            y = cap.block(df, "y")
            z = F.reduce_sum(y, axis=[1], name="z")
            g = cap.build_graph(z)
        path = str(tmp_path / "g.tfs")
        cap.save_graph(g, path)
        g2 = cap.load_graph(path)
        assert g2.fetch_names == ["z"]
        assert list(g2.placeholders) == ["y"]
        data = np.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
        out = g2.fn({"y": data})
        np.testing.assert_allclose(np.asarray(out["z"]), [3.0, 7.0, 11.0])

    def test_bad_magic(self):
        with pytest.raises(ValueError, match="serialized graph"):
            cap.deserialize_graph(b"garbage")


def test_analyze_cache_keys_on_x64_state():
    """The analyze memo must not serve a pre-x64 spec after ensure_x64
    flips result dtypes (x64 is one-way in-process, so this needs a fresh
    interpreter)."""
    import os
    import subprocess
    import sys

    code = (
        "import numpy as np\n"
        "from tensorframes_tpu.capture import CapturedGraph\n"
        "from tensorframes_tpu.schema import for_numpy_dtype, Shape, Unknown\n"
        "def fn(x):\n"
        "    return {'z': x.astype('float64') + 1}\n"
        "g = CapturedGraph.from_callable(\n"
        "    fn, {'x': (for_numpy_dtype(np.dtype('float32')), Shape([Unknown]))})\n"
        "s1 = g.analyze({'x': Shape([Unknown])})\n"
        "assert s1['z'].scalar_type.name == 'float32', s1  # x64 off: demoted\n"
        "from tensorframes_tpu.utils import ensure_x64\n"
        "ensure_x64()\n"
        "s2 = g.analyze({'x': Shape([Unknown])})\n"
        "assert s2['z'].scalar_type.name == 'float64', s2  # not the stale memo\n"
        "print('x64-keyed OK')\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run(
        [sys.executable, "-c", code],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert res.returncode == 0, res.stderr[-2000:]
    assert "x64-keyed OK" in res.stdout


class TestConcreteProbe:
    """When symbolic tracing fails, analyze falls back to a double concrete
    probe; output dims that track the fill size are Unknown, genuinely fixed
    dims are kept — even when they collide with a plausible fill value."""

    def _graph(self, fixed):
        import jax.numpy as jnp

        # int() on a symbolic dim raises, forcing the concrete-probe path
        def fn(x):
            return {"z": jnp.zeros((int(x.shape[0]), fixed), x.dtype)}

        return cap.CapturedGraph.from_callable(
            fn, {"x": (FLOAT64, Shape(Unknown))}, fetch_names=["z"]
        )

    def test_inherited_dim_marked_unknown(self):
        out = self._graph(13).analyze()
        assert out["z"].shape == Shape(Unknown, 13)

    def test_fixed_dim_equal_to_fill_value_kept(self):
        # 1013 is one of the probe fills; a constant 1013 must survive
        out = self._graph(1013).analyze()
        assert out["z"].shape == Shape(Unknown, 1013)
