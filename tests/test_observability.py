"""The observability subsystem (``tensorframes_tpu.obs``): metrics
registry semantics, span tracing, engine/serving wiring, and the
Prometheus scrape off a live :class:`ScoringServer`.

The reference had nothing to test here — runtime visibility was Spark's
UI (SURVEY §5). These tests pin the contracts every later perf/robustness
PR reads its regression signal through.
"""

import json
import socket
import threading

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import obs
from tensorframes_tpu.obs.metrics import DEFAULT_BUCKETS, MetricsRegistry

pytestmark = pytest.mark.obs


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


class TestRegistry:
    def test_counter_thread_safety(self):
        reg = MetricsRegistry()
        c = reg.counter("t.hits_total", "x", labels=("op",))
        per_thread, n_threads = 5000, 8

        def work():
            for _ in range(per_thread):
                c.inc(op="a")

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value(op="a") == per_thread * n_threads

    def test_histogram_thread_safety(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.lat_seconds", "x")
        per_thread, n_threads = 3000, 6

        def work():
            for _ in range(per_thread):
                h.observe(1e-3)

        ts = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert h.series()["count"] == per_thread * n_threads

    def test_histogram_bucket_edges_are_le_inclusive(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.edge_seconds", "x")
        assert h.bounds == DEFAULT_BUCKETS
        edge = h.bounds[3]
        h.observe(edge)            # exactly on a bound -> that bucket
        h.observe(edge * 1.0001)   # just above -> next bucket
        h.observe(0.0)             # below the first bound -> bucket 0
        h.observe(1e12)            # beyond the last bound -> +Inf bucket
        s = h.series()
        assert s["counts"][3] == 1
        assert s["counts"][4] == 1
        assert s["counts"][0] == 1
        assert s["counts"][-1] == 1
        assert s["count"] == 4

    def test_counter_rejects_negative_and_bad_labels(self):
        reg = MetricsRegistry()
        c = reg.counter("t.neg_total", "x", labels=("op",))
        with pytest.raises(ValueError):
            c.inc(-1.0, op="a")
        with pytest.raises(ValueError):
            c.inc(typo="a")
        with pytest.raises(ValueError):
            c.inc()  # missing declared label
        with pytest.raises(ValueError):
            c.bind(op="a").inc(-1.0)  # bound handles stay monotonic too

    def test_gauge_adjust_bypasses_kill_switch_for_paired_updates(self):
        reg = MetricsRegistry()
        g = reg.gauge("t.inflight", "x")
        g.adjust(1.0)  # request started while observability was on
        tft.utils.set_config(observability=False)
        try:
            g.adjust(-1.0)  # kill switch flipped mid-request: stays paired
        finally:
            tft.utils.set_config(observability=True)
        assert g.value() == 0.0

    def test_get_or_create_idempotent_and_type_checked(self):
        reg = MetricsRegistry()
        a = reg.counter("t.same_total", "x")
        assert reg.counter("t.same_total") is a
        with pytest.raises(ValueError):
            reg.gauge("t.same_total")
        with pytest.raises(ValueError):
            reg.counter("t.same_total", labels=("op",))

    def test_snapshot_is_plain_json_dict(self):
        reg = MetricsRegistry()
        reg.counter("t.c_total", "c", labels=("k",)).inc(k="v")
        reg.gauge("t.g", "g").set(3.5)
        reg.histogram("t.h_seconds", "h").observe(0.5)
        snap = reg.snapshot()
        json.dumps(snap)  # must be JSON-serializable as-is
        assert snap["t.c_total"]["values"]["k=v"] == 1.0
        assert snap["t.g"]["values"][""] == 3.5
        assert snap["t.h_seconds"]["values"][""]["count"] == 1

    def test_prometheus_rendering_and_escapes(self):
        reg = MetricsRegistry()
        c = reg.counter("t.esc_total", "escape test", labels=("v",))
        c.inc(v='a"b\\c\nd')
        g = reg.gauge("t.active", "gauge")
        g.set(2)
        h = reg.histogram("t.lat_seconds", "hist")
        h.observe(2e-6)
        text = reg.render_prometheus()
        # names are prefixed + dot-mapped
        assert "# TYPE tft_t_esc_total counter" in text
        assert 'tft_t_esc_total{v="a\\"b\\\\c\\nd"} 1' in text
        assert "tft_t_active 2" in text
        # histogram: cumulative buckets, +Inf, sum, count
        assert 'tft_t_lat_seconds_bucket{le="+Inf"} 1' in text
        assert "tft_t_lat_seconds_count 1" in text
        assert "tft_t_lat_seconds_sum" in text
        # series for a bound above the observation include it (cumulative)
        assert f'le="{DEFAULT_BUCKETS[2]!r}"' in text


# ---------------------------------------------------------------------------
# histogram quantiles (the Retry-After + time-series sampler dependency)
# ---------------------------------------------------------------------------


class TestHistogramQuantile:
    def test_empty_histogram_returns_none(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.q_empty_seconds", "x")
        assert h.quantile(0.5) is None
        assert h.quantile(0.99) is None
        # labeled series that never observed: also None
        hl = reg.histogram("t.q_lab_seconds", "x", labels=("op",))
        assert hl.quantile(0.5, op="a") is None

    def test_bad_q_raises_even_on_empty_series(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.q_bad_seconds", "x")
        with pytest.raises(ValueError):
            h.quantile(1.5)
        with pytest.raises(ValueError):
            h.quantile(-0.1)
        h.observe(1.0)
        with pytest.raises(ValueError):
            h.quantile(2.0)

    def test_single_bucket_mass_every_q_reports_that_bound(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.q_single_seconds", "x")
        edge = h.bounds[5]
        for _ in range(10):
            h.observe(edge * 0.9)  # all land in bucket 5
        for q in (0.0, 0.01, 0.5, 0.99, 1.0):
            assert h.quantile(q) == edge

    def test_all_mass_in_inf_tail_reports_top_bound(self):
        """Observations beyond every finite bound land in +Inf; the
        quantile reports the top FINITE bound — the documented
        (conservative) underestimate, never None/inf."""
        reg = MetricsRegistry()
        h = reg.histogram("t.q_inf_seconds", "x")
        for _ in range(4):
            h.observe(1e15)
        assert h.series()["counts"][-1] == 4  # really in the tail
        for q in (0.5, 0.99, 1.0):
            assert h.quantile(q) == h.bounds[-1]

    def test_exact_bound_observation_is_le_inclusive(self):
        """An observation exactly on a bound belongs to that bound's
        bucket (Prometheus `le` semantics), so the quantile of a series
        holding only exact-bound observations is that bound itself."""
        reg = MetricsRegistry()
        h = reg.histogram("t.q_exact_seconds", "x")
        edge = h.bounds[7]
        h.observe(edge)
        assert h.quantile(0.5) == edge
        assert h.quantile(1.0) == edge
        # one just above tips the p100 into the NEXT bucket
        h.observe(edge * 1.000001)
        assert h.quantile(1.0) == h.bounds[8]
        assert h.quantile(0.25) == edge

    def test_q_zero_reports_smallest_occupied_bucket(self):
        """q=0 must not report the registry's first bound when nothing
        was ever observed there — it reports the smallest bucket that
        HOLDS an observation (the max(target, 1) rule)."""
        reg = MetricsRegistry()
        h = reg.histogram("t.q_zero_seconds", "x")
        edge = h.bounds[9]
        h.observe(edge * 0.99)
        assert h.quantile(0.0) == edge

    def test_split_mass_interpolates_across_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.q_split_seconds", "x")
        lo, hi = h.bounds[3], h.bounds[10]
        for _ in range(9):
            h.observe(lo * 0.5)
        h.observe(hi * 0.5)
        assert h.quantile(0.5) == lo
        assert h.quantile(0.95) == hi  # 0.95*10 = 9.5 -> needs the 10th


# ---------------------------------------------------------------------------
# Prometheus histogram exposition round-trip
# ---------------------------------------------------------------------------


def _parse_histogram_exposition(text, pname):
    """Parse one histogram's series out of exposition text:
    {label_str: {"buckets": [(le, cum)...], "sum": s, "count": n}}."""
    import re

    out = {}
    pat = re.compile(
        rf"^{re.escape(pname)}(_bucket|_sum|_count)(?:{{(.*)}})? (.+)$"
    )
    for line in text.splitlines():
        m = pat.match(line)
        if not m:
            continue
        suffix, labels, value = m.group(1), m.group(2) or "", m.group(3)
        le = None
        rest = []
        for part in re.findall(r'(\w+)="((?:[^"\\]|\\.)*)"', labels):
            if part[0] == "le":
                le = part[1]
            else:
                rest.append(f"{part[0]}={part[1]}")
        key = ",".join(rest)
        series = out.setdefault(
            key, {"buckets": [], "sum": None, "count": None}
        )
        if suffix == "_bucket":
            series["buckets"].append(
                (float("inf") if le == "+Inf" else float(le), int(value))
            )
        elif suffix == "_sum":
            series["sum"] = float(value)
        else:
            series["count"] = int(value)
    return out


class TestPrometheusHistogramRoundTrip:
    """The standard cumulative `_bucket`/`_sum`/`_count` exposition must
    be parseable by a real Prometheus: le-labeled, float-parseable
    bounds, monotone cumulative counts, an explicit +Inf bucket equal to
    `_count`, and per-bucket counts reconstructible by differencing."""

    def test_unlabeled_round_trip(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.rt_seconds", "x")
        observations = [2e-6, 1e-3, 1e-3, 0.5, 1e9]  # incl. +Inf tail
        for v in observations:
            h.observe(v)
        parsed = _parse_histogram_exposition(
            reg.render_prometheus(), "tft_t_rt_seconds"
        )
        s = parsed[""]
        # every finite bound + the explicit +Inf bucket, in order
        les = [le for le, _ in s["buckets"]]
        assert les == sorted(les)
        assert les[:-1] == [float(b) for b in h.bounds]
        assert les[-1] == float("inf")
        # cumulative counts are monotone; +Inf == _count == observations
        cums = [c for _, c in s["buckets"]]
        assert cums == sorted(cums)
        assert cums[-1] == s["count"] == len(observations)
        assert s["sum"] == pytest.approx(sum(observations))
        # differencing reconstructs the internal per-bucket counts
        per_bucket = [cums[0]] + [
            b - a for a, b in zip(cums, cums[1:])
        ]
        assert per_bucket == h.series()["counts"]

    def test_labeled_series_round_trip(self):
        reg = MetricsRegistry()
        h = reg.histogram("t.rt_lab_seconds", "x", labels=("op",))
        h.observe(1e-3, op="a")
        h.observe(2.0, op="a")
        h.observe(5e-5, op="b")
        parsed = _parse_histogram_exposition(
            reg.render_prometheus(), "tft_t_rt_lab_seconds"
        )
        assert set(parsed) == {"op=a", "op=b"}
        assert parsed["op=a"]["count"] == 2
        assert parsed["op=b"]["count"] == 1
        for s in parsed.values():
            assert s["buckets"][-1][1] == s["count"]
            assert s["buckets"][-1][0] == float("inf")

    def test_scrape_quantile_matches_registry_quantile(self):
        """A Grafana `histogram_quantile` built from the scraped buckets
        must see the same bucket data `Histogram.quantile` uses: the
        smallest le whose cumulative reaches q*count agrees with the
        in-process answer."""
        reg = MetricsRegistry()
        h = reg.histogram("t.rt_q_seconds", "x")
        for v in (1e-4, 2e-4, 5e-2, 1.0, 3.0):
            h.observe(v)
        parsed = _parse_histogram_exposition(
            reg.render_prometheus(), "tft_t_rt_q_seconds"
        )[""]
        for q in (0.5, 0.99):
            target = max(q * parsed["count"], 1)
            from_scrape = next(
                le for le, cum in parsed["buckets"] if cum >= target
            )
            assert from_scrape == pytest.approx(h.quantile(q))


# ---------------------------------------------------------------------------
# kill switch
# ---------------------------------------------------------------------------


class TestKillSwitch:
    def test_config_disables_collection_and_spans(self, tmp_path):
        reg = MetricsRegistry()
        c = reg.counter("t.off_total", "x")
        sink = tmp_path / "spans.jsonl"
        tft.utils.set_config(observability=False)
        try:
            assert not obs.enabled()
            c.inc()
            assert c.value() == 0.0
            obs.set_trace_sink(str(sink))
            with obs.span("disabled") as sp:
                assert sp is None
        finally:
            tft.utils.set_config(observability=True)
            obs.set_trace_sink(None)
        assert sink.read_text() == ""
        c.inc()
        assert c.value() == 1.0


# ---------------------------------------------------------------------------
# span tracing
# ---------------------------------------------------------------------------


class TestSpans:
    def test_nesting_and_jsonl_schema(self, tmp_path):
        sink = tmp_path / "spans.jsonl"
        obs.set_trace_sink(str(sink))
        try:
            assert obs.current_span() is None
            with obs.span("outer", a=1) as s1:
                assert obs.current_span() is s1
                with obs.span("inner") as s2:
                    assert s2.depth == s1.depth + 1
                    assert s2.parent_id == s1.span_id
                    s2.attrs["extra"] = "v"
            assert obs.current_span() is None
        finally:
            obs.set_trace_sink(None)
        events = [
            json.loads(line) for line in sink.read_text().splitlines()
        ]
        assert [e["name"] for e in events] == ["inner", "outer"]  # close order
        by = {e["name"]: e for e in events}
        for e in events:
            assert {
                "name", "span_id", "parent_id", "depth", "ts", "dur_s",
                "thread", "attrs",
            } <= set(e)
            assert e["dur_s"] >= 0.0
        assert by["inner"]["parent_id"] == by["outer"]["span_id"]
        assert by["inner"]["depth"] == by["outer"]["depth"] + 1
        assert by["outer"]["attrs"] == {"a": 1}
        assert by["inner"]["attrs"] == {"extra": "v"}

    def test_sync_records_device_duration(self, tmp_path):
        import jax.numpy as jnp

        sink = tmp_path / "spans.jsonl"
        obs.set_trace_sink(str(sink))
        try:
            with obs.span("synced") as sp:
                sp.sync = jnp.arange(128.0).sum()
        finally:
            obs.set_trace_sink(None)
        (event,) = [
            json.loads(line) for line in sink.read_text().splitlines()
        ]
        assert event["dur_synced_s"] >= event["dur_s"]

    def test_span_survives_exceptions(self, tmp_path):
        sink = tmp_path / "spans.jsonl"
        obs.set_trace_sink(str(sink))
        try:
            with pytest.raises(ValueError):
                with obs.span("boom"):
                    raise ValueError("x")
            assert obs.current_span() is None
        finally:
            obs.set_trace_sink(None)
        (event,) = [
            json.loads(line) for line in sink.read_text().splitlines()
        ]
        assert event["name"] == "boom"


# ---------------------------------------------------------------------------
# engine wiring
# ---------------------------------------------------------------------------


class TestEngineWiring:
    def test_recapture_counts_every_time_but_warns_once(self, caplog):
        df = tft.TensorFrame.from_columns({"x": np.arange(8.0)})
        c = obs.registry().get("engine.callable_recapture_total")

        def make():
            return lambda x: {"y_obs_churn": x + 41.5}

        base = c.value()
        with caplog.at_level("WARNING", logger="tensorframes_tpu.engine"):
            for _ in range(4):
                tft.map_blocks(make(), df)
        # first capture seeds the signature; the three later recaptures
        # each count, while the log line fires exactly once
        assert c.value() - base == 3
        churn_warnings = [
            r for r in caplog.records if "capturing" in r.getMessage()
        ]
        assert len(churn_warnings) == 1

    def test_memo_and_jit_and_rows_counters(self):
        reg = obs.registry()
        hits = reg.get("engine.graph_memo_hits_total")
        misses = reg.get("engine.graph_memo_misses_total")
        reuse = reg.get("engine.jit_cache_reuse_total")
        rows = reg.get("engine.rows_processed_total")
        df = tft.TensorFrame.from_columns({"x": np.arange(10.0)})
        h0, m0, r0 = hits.value(), misses.value(), reuse.value()
        rows0 = rows.value(op="map_blocks")

        def fn(x):
            return {"y_obs_memo": x * 2.0}

        tft.map_blocks(fn, df).cache()
        tft.map_blocks(fn, df).cache()
        assert misses.value() - m0 == 1  # first capture traces
        assert hits.value() - h0 == 1    # second resolves from the memo
        assert reuse.value() - r0 >= 1   # second call reuses the jit wrapper
        assert rows.value(op="map_blocks") - rows0 == 20

    def test_transfer_byte_counters(self):
        reg = obs.registry()
        h2d = reg.get("frame.h2d_bytes_total")
        before = h2d.value()
        df = tft.TensorFrame.from_columns(
            {"x": np.arange(256.0)}  # 2 KiB of f64
        )
        tft.map_blocks(lambda x: {"y_obs_h2d": x + 1.0}, df).cache()
        assert h2d.value() - before >= 256 * 8

    def test_retry_counter_increments_per_attempt(self):
        from tensorframes_tpu.utils import run_with_retries, set_config

        c = obs.registry().get("failures.retries_total")
        base = c.value(op="obs-retry-test", reason="UNAVAILABLE")
        attempts = []
        set_config(retry_backoff_s=0.0)
        try:
            def flaky():
                attempts.append(1)
                if len(attempts) < 3:
                    raise RuntimeError("UNAVAILABLE: fake link drop")
                return "ok"

            assert run_with_retries(flaky, what="obs-retry-test run") == "ok"
        finally:
            set_config(retry_backoff_s=0.5)
        assert (
            c.value(op="obs-retry-test", reason="UNAVAILABLE") - base == 2
        )

    def test_oom_split_counter(self):
        from tensorframes_tpu.utils.failures import record_oom_split

        c = obs.registry().get("failures.oom_splits_total")
        base = c.value(op="map_rows")
        record_oom_split("map_rows")
        assert c.value(op="map_rows") - base == 1


# ---------------------------------------------------------------------------
# Timer integration
# ---------------------------------------------------------------------------


class TestTimerIntegration:
    def test_as_dict(self):
        from tensorframes_tpu.utils.profiling import Timer

        t = Timer()
        for _ in range(3):
            with t.section("s"):
                pass
        d = t.as_dict()
        assert d["s"]["count"] == 3
        assert d["s"]["min_s"] <= d["s"]["mean_s"] <= d["s"]["max_s"]
        assert d["s"]["total_s"] >= 0.0
        json.dumps(d)

    def test_publish_into_registry(self):
        from tensorframes_tpu.utils.profiling import Timer

        t = Timer(publish=True)
        with t.section("obs_pub"):
            pass
        h = obs.registry().get("profiling.timer_seconds")
        assert h.series(section="obs_pub")["count"] >= 1


# ---------------------------------------------------------------------------
# end-to-end: live ScoringServer scrape
# ---------------------------------------------------------------------------


def _http_get(addr: str, path: str) -> str:
    host, port_s = addr.rsplit(":", 1)
    s = socket.create_connection((host, int(port_s)), timeout=30)
    try:
        s.sendall(
            f"GET {path} HTTP/1.1\r\nHost: scrape\r\n\r\n".encode("latin-1")
        )
        data = b""
        while True:
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    return data.decode("utf-8", "replace")


class TestServingEndToEnd:
    def test_scrape_after_round_trip(self, tmp_path):
        pa = pytest.importorskip("pyarrow")
        from tensorframes_tpu.interop import (
            ScoringServer,
            remote_arrow_mapper,
        )

        sink = tmp_path / "spans.jsonl"
        obs.set_trace_sink(str(sink))

        def score(x):
            return {"y_obs_e2e": x * 2.0 + 1.0}

        xs = np.arange(64.0, dtype=np.float32)
        t = pa.table({"x": pa.array(xs, type=pa.float32())})
        try:
            with ScoringServer(score) as addr:
                fn = remote_arrow_mapper(addr)
                for _ in range(2):  # second round-trip hits the graph memo
                    out = pa.Table.from_batches(list(fn(t.to_batches())))
                np.testing.assert_allclose(
                    out.column("y_obs_e2e").to_numpy(), xs * 2.0 + 1.0
                )
                # the latency observation lands in the handler's
                # finally, AFTER the client has its response bytes — on
                # a loaded one-core box the second handler thread can
                # still be parked when an immediate scrape is served,
                # so re-scrape until both observations landed
                import time as _t

                deadline = _t.monotonic() + 10.0
                while True:
                    text = _http_get(addr, "/metrics")
                    landed = [
                        ln
                        for ln in text.splitlines()
                        if ln.startswith(
                            "tft_serving_request_seconds_count "
                        )
                    ]
                    if (
                        landed
                        and float(landed[0].rsplit(" ", 1)[1]) >= 2
                    ) or _t.monotonic() > deadline:
                        break
                    _t.sleep(0.05)
                assert _http_get(addr, "/nope").startswith(
                    "HTTP/1.1 404"
                )
                # a slow HTTP client whose "GET " dribbles in byte by
                # byte must still route to the scrape, not the Arrow
                # parser
                host, port_s = addr.rsplit(":", 1)
                s = socket.create_connection((host, int(port_s)), timeout=30)
                try:
                    s.sendall(b"GE")
                    import time as _time

                    _time.sleep(0.2)
                    s.sendall(b"T /metrics HTTP/1.1\r\nHost: slow\r\n\r\n")
                    data = b""
                    while True:
                        chunk = s.recv(1 << 16)
                        if not chunk:
                            break
                        data += chunk
                finally:
                    s.close()
                assert data.decode("utf-8", "replace").startswith(
                    "HTTP/1.1 200"
                )
        finally:
            obs.set_trace_sink(None)

        assert text.startswith("HTTP/1.1 200")
        assert "text/plain; version=0.0.4" in text

        def metric_value(name: str) -> float:
            for line in text.splitlines():
                if line.startswith(name + " ") or line.startswith(name + "{"):
                    tail = line.rsplit(" ", 1)[1]
                    return float(tail)
            raise AssertionError(f"{name} not in scrape")

        # request count, latency histogram, engine cache counters: nonzero
        assert (
            'tft_serving_requests_total{kind="score",status="ok"}' in text
        )
        assert metric_value("tft_serving_request_seconds_count") >= 2
        assert 'tft_serving_request_seconds_bucket{le="+Inf"}' in text
        assert metric_value("tft_serving_bytes_in_total") > 0
        assert metric_value("tft_serving_bytes_out_total") > 0
        assert metric_value("tft_engine_graph_memo_hits_total") >= 1
        assert metric_value("tft_engine_graph_memo_misses_total") >= 1
        assert metric_value("tft_engine_rows_processed_total{op=\"map_blocks\"}") >= 128

        # span events landed in the JSONL sink with correct nesting:
        # engine.map_blocks runs inside the serving.request span tree
        events = [
            json.loads(line) for line in sink.read_text().splitlines()
        ]
        by_id = {e["span_id"]: e for e in events}
        serving_ids = {
            e["span_id"] for e in events if e["name"] == "serving.request"
        }
        assert serving_ids, "no serving.request span emitted"
        engine_events = [
            e for e in events if e["name"] == "engine.map_blocks"
        ]
        assert engine_events, "no engine.map_blocks span emitted"

        def has_serving_ancestor(e):
            seen = set()
            while e["parent_id"] is not None and e["parent_id"] not in seen:
                seen.add(e["parent_id"])
                parent = by_id.get(e["parent_id"])
                if parent is None:
                    return False
                if parent["span_id"] in serving_ids:
                    return True
                e = parent
            return False

        assert any(has_serving_ancestor(e) for e in engine_events)
