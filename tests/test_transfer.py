"""Streaming host↔device transfers (``frame/transfer.py``).

The acceptance bar (ISSUE 5): chunked h2d/d2h must be **byte-identical**
to the monolithic paths — dense f32 / bf16 / byte-payload columns, odd
remainder chunks, 0-row and 1-row frames — including under injected
transient transfer faults, and the engine's streaming feeds (map_blocks
prefetch, map_rows device-resident pass) must not change any result.
CPU-only, seeded, deterministic.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.engine import map_blocks, map_rows, reduce_blocks
from tensorframes_tpu.frame import transfer
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.utils import chaos, get_config, set_config


def _counter(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _hist_count(name):
    try:
        s = obs_metrics.registry().get(name).series()
    except KeyError:
        return 0
    return 0 if s is None else s["count"]


@pytest.fixture
def tiny_chunks():
    """128-byte chunks, 3 streams: any column beyond a few rows splits
    into many odd-remainder chunks."""
    old = get_config()
    set_config(transfer_chunk_bytes=128, transfer_streams=3)
    yield
    set_config(
        transfer_chunk_bytes=old.transfer_chunk_bytes,
        transfer_streams=old.transfer_streams,
    )


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _roundtrip_bytes(x):
    """h2d then d2h through the streaming layer; returns host bytes."""
    dev = transfer.h2d(x)
    assert tuple(dev.shape) == x.shape and dev.dtype == x.dtype
    return transfer.d2h(dev).tobytes()


class TestH2DIdentity:
    """Chunked upload == monolithic device_put, byte for byte."""

    def test_f32_odd_remainder(self, tiny_chunks, rng):
        # 128-byte chunks over 28-byte rows -> 4 rows/chunk, 41 rows ->
        # 10 full chunks + a 1-row remainder
        x = rng.normal(size=(41, 7)).astype(np.float32)
        assert _roundtrip_bytes(x) == x.tobytes()

    def test_int32_and_uint8(self, tiny_chunks, rng):
        xi = rng.integers(-(2**31), 2**31 - 1, size=(57, 5), dtype=np.int32)
        assert _roundtrip_bytes(xi) == xi.tobytes()
        # byte payloads (the binary-adjacent dense form: u8 feature bytes)
        xb = rng.integers(0, 256, size=(300, 3), dtype=np.uint8)
        assert _roundtrip_bytes(xb) == xb.tobytes()

    def test_bf16_column(self, tiny_chunks, rng):
        import ml_dtypes

        x = rng.normal(size=(33, 9)).astype(np.float32).astype(
            ml_dtypes.bfloat16
        )
        assert _roundtrip_bytes(x) == x.tobytes()

    def test_zero_and_one_row(self, tiny_chunks):
        for n in (0, 1):
            x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
            assert _roundtrip_bytes(x) == x.tobytes()

    def test_scalar_roundtrip(self, tiny_chunks):
        # 0-d arrays cross whole in both directions (h2d/d2h symmetry)
        x = np.array(3.25, dtype=np.float32)
        assert _roundtrip_bytes(x) == x.tobytes()

    def test_single_chunk_when_it_fits(self, rng):
        # default 64 MiB chunk: small columns pay nothing for chunking
        x = rng.normal(size=(16, 4)).astype(np.float32)
        su = transfer.StreamingUpload(x)
        assert su.num_chunks == 1
        assert np.asarray(su.assembled()).tobytes() == x.tobytes()

    def test_chunk_count_is_capped(self):
        old = get_config().transfer_chunk_bytes
        set_config(transfer_chunk_bytes=1)
        try:
            bounds = transfer._chunk_bounds(100_000, 4)
            assert len(bounds) <= transfer._MAX_CHUNKS
            assert bounds[0][0] == 0 and bounds[-1][1] == 100_000
        finally:
            set_config(transfer_chunk_bytes=old)

    def test_chunking_disabled_is_monolithic(self, rng):
        old = get_config().transfer_chunk_bytes
        set_config(transfer_chunk_bytes=0)
        try:
            x = rng.normal(size=(1000, 8)).astype(np.float32)
            su = transfer.StreamingUpload(x)
            assert su.num_chunks == 1
            assert np.asarray(su.assembled()).tobytes() == x.tobytes()
        finally:
            set_config(transfer_chunk_bytes=old)


class TestStreamSlices:
    def test_slices_across_chunk_boundaries(self, tiny_chunks, rng):
        x = rng.normal(size=(50, 7)).astype(np.float32)
        cd = tft.TensorFrame.from_columns({"x": x}).column_data("x")
        su = cd.device_stream()
        assert su.num_chunks > 3
        for lo, hi in [(0, 3), (2, 9), (4, 8), (0, 50), (49, 50), (7, 43)]:
            got = np.asarray(su.slice(lo, hi))
            assert got.tobytes() == x[lo:hi].tobytes(), (lo, hi)

    def test_device_memoizes_assembled(self, tiny_chunks, rng):
        x = rng.normal(size=(40, 4)).astype(np.float32)
        cd = tft.TensorFrame.from_columns({"x": x}).column_data("x")
        before = _counter("frame.h2d_bytes_total")
        d1 = cd.device()
        assert _counter("frame.h2d_bytes_total") - before == x.nbytes
        d2 = cd.device()
        assert d2 is d1  # memoized: the column crossed once
        assert _counter("frame.h2d_bytes_total") - before == x.nbytes
        assert cd._stream is None

    def test_unpersist_releases_the_stream(self, tiny_chunks, rng):
        x = rng.normal(size=(40, 4)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x})
        df.column_data("x").device_stream()
        df.unpersist_device()
        assert df.column_data("x")._stream is None


class TestD2HIdentity:
    def test_chunked_fetch_matches_monolithic(self, tiny_chunks, rng):
        import jax

        x = rng.normal(size=(61, 5)).astype(np.float32)
        dev = jax.device_put(x)
        got = transfer.d2h(dev)
        assert got.tobytes() == np.asarray(dev).tobytes() == x.tobytes()

    def test_column_host_roundtrip(self, tiny_chunks, rng):
        import jax

        x = rng.normal(size=(45, 6)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": jax.device_put(x)})
        assert df.column_data("x").host().tobytes() == x.tobytes()

    def test_d2h_async_overlaps(self, tiny_chunks, rng):
        import jax

        xs = [
            jax.device_put(rng.normal(size=(40, 4)).astype(np.float32))
            for _ in range(3)
        ]
        pending = [transfer.d2h_async(d) for d in xs]
        outs = [p.result() for p in pending]
        for d, o in zip(xs, outs):
            assert o.tobytes() == np.asarray(d).tobytes()


class TestWireCast:
    def test_bf16_wire_rounds_values_keeps_dtype(self, tiny_chunks, rng):
        import ml_dtypes

        x = rng.normal(size=(37, 5)).astype(np.float32)
        old = get_config().transfer_dtype
        set_config(transfer_dtype="bf16")
        try:
            before = _counter("frame.h2d_bytes_total")
            cd = tft.TensorFrame.from_columns({"x": x}).column_data("x")
            dev = cd.device()
            assert np.dtype(dev.dtype) == np.float32  # device dtype intact
            exp = x.astype(ml_dtypes.bfloat16).astype(np.float32)
            assert np.array_equal(np.asarray(dev), exp)
            # half the bytes ever crossed the wire
            assert _counter("frame.h2d_bytes_total") - before == x.nbytes // 2
        finally:
            set_config(transfer_dtype=old)

    def test_non_f32_payloads_are_untouched(self, tiny_chunks, rng):
        xi = rng.integers(0, 100, size=(29, 3), dtype=np.int32)
        old = get_config().transfer_dtype
        set_config(transfer_dtype="bf16")
        try:
            assert _roundtrip_bytes(xi) == xi.tobytes()
        finally:
            set_config(transfer_dtype=old)

    def test_unknown_wire_dtype_fails_loudly(self):
        old = get_config().transfer_dtype
        set_config(transfer_dtype="fp8")
        try:
            with pytest.raises(ValueError, match="transfer_dtype"):
                transfer.h2d(np.zeros((4, 4), np.float32))
        finally:
            set_config(transfer_dtype=old)


@pytest.mark.chaos
class TestTransferChaos:
    """Transient tunnel faults during chunked transfers retry per chunk
    and the landed bytes stay identical — the no-retry ingest kill of
    the monolithic era is gone."""

    def test_h2d_transient_faults_retry_byte_identical(
        self, tiny_chunks, fast_retries, rng
    ):
        x = rng.normal(size=(53, 7)).astype(np.float32)
        i0 = _counter("chaos.injections_total", site="frame.h2d",
                      kind="transient")
        r0 = _counter("failures.retries_total", op="frame.h2d",
                      reason="UNAVAILABLE")
        with chaos.scoped("seed=3;frame.h2d=transient:every=3"):
            dev = transfer.h2d(x)
        assert np.asarray(dev).tobytes() == x.tobytes()
        assert _counter("chaos.injections_total", site="frame.h2d",
                        kind="transient") > i0
        assert _counter("failures.retries_total", op="frame.h2d",
                        reason="UNAVAILABLE") > r0

    def test_d2h_transient_faults_retry_byte_identical(
        self, tiny_chunks, fast_retries, rng
    ):
        import jax

        x = rng.normal(size=(53, 7)).astype(np.float32)
        dev = jax.device_put(x)
        with chaos.scoped("seed=5;frame.d2h=transient:every=3"):
            got = transfer.d2h(dev)
        assert got.tobytes() == x.tobytes()

    def test_exhausted_retries_surface_the_error(
        self, tiny_chunks, fast_retries, rng
    ):
        x = rng.normal(size=(40, 4)).astype(np.float32)
        with chaos.scoped("frame.h2d=transient"):  # fires on EVERY call
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                transfer.h2d(x)

    def test_engine_pass_survives_transfer_faults(
        self, tiny_chunks, fast_retries, rng
    ):
        x = rng.normal(size=(64, 6)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x}, num_partitions=3)
        df = df.analyze()
        with chaos.scoped("seed=11;frame.h2d=transient:every=4"):
            out = map_blocks(lambda x: {"y": x * 2.0}, df)
            got = out.column_data("y").host()
        assert np.array_equal(got, x * 2.0)


class TestEngineStreaming:
    """The engine's block loops consume chunks as they land; results
    must be identical to the monolithic-upload era."""

    def test_map_blocks_chunked_feed_identity(self, tiny_chunks, rng):
        x = rng.normal(size=(101, 7)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x}, num_partitions=4)
        df = df.analyze()
        got = map_blocks(lambda x: {"y": x + 1.0}, df).column_data("y")
        assert np.array_equal(got.host(), x + 1.0)

    def test_map_blocks_overbudget_upload_prefetch(self, tiny_chunks, rng):
        """Over-budget columns stream host blocks through the prefetching
        uploader (block i+1 crosses while i computes)."""
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=256)  # force host streaming
        try:
            x = rng.normal(size=(90, 5)).astype(np.float32)
            df = tft.TensorFrame.from_columns(
                {"x": x}, num_partitions=6
            ).analyze()
            before = _counter("frame.h2d_bytes_total")
            got = map_blocks(lambda x: {"y": x * 3.0}, df).column_data("y")
            assert np.array_equal(got.host(), x * 3.0)
            # every streamed block crossed through the transfer layer
            assert _counter("frame.h2d_bytes_total") - before >= x.nbytes
        finally:
            set_config(device_cache_bytes=old)

    def test_map_rows_chunked_identity(self, tiny_chunks, rng):
        x = rng.normal(size=(77, 4)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        got = map_rows(lambda x: {"y": x * 2.0 + 1.0}, df).column_data("y")
        assert np.array_equal(got.host(), x * 2.0 + 1.0)

    def test_map_rows_sync_path_counts_feed_uploads(self, tiny_chunks, rng):
        """The synchronous chunked path (device-residency off) uploads
        its feeds explicitly: counted, retried, chaos-injectable."""
        old = get_config().max_rows_per_device_call
        set_config(max_rows_per_device_call=16)
        try:
            x = rng.normal(size=(64, 4)).astype(np.float32)
            # ragged second column forces the bucketed (non-fast) path
            cells = [
                rng.normal(size=(2 + (i % 2),)).astype(np.float32)
                for i in range(64)
            ]
            df = tft.TensorFrame.from_columns(
                {"x": x, "r": cells}
            ).analyze()
            before = _counter("frame.h2d_bytes_total")
            got = map_rows(
                lambda x: {"y": x.sum()}, df, feed_dict={"x": "x"}
            ).column_data("y")
            assert np.allclose(got.host(), x.sum(axis=1), rtol=1e-6)
            assert _counter("frame.h2d_bytes_total") - before >= x.nbytes
        finally:
            set_config(max_rows_per_device_call=old)

    def test_reduce_blocks_chunked_identity(self, tiny_chunks, rng):
        x = rng.normal(size=(66, 3)).astype(np.float32)
        df = tft.TensorFrame.from_columns(
            {"x": x}, num_partitions=3
        ).analyze()
        got = reduce_blocks(
            lambda x_input: {"x": x_input.sum(axis=0)}, df
        )
        assert np.allclose(np.asarray(got), x.sum(axis=0), rtol=1e-5)

    def test_unanalyzed_map_rows_uploads_bound_columns_once(self, rng):
        """The ROADMAP item-2 double-upload regression (fixed in ISSUE
        12): ``map_rows`` on an UN-analyzed frame has unknown out-spec
        dims, so the device-resident fast path must bail — and it must
        bail BEFORE probing ``_block_feeder``, which starts the
        column's chunked upload. The old order started that upload,
        bailed, and then the ``run_chunk`` fallback re-uploaded every
        chunk via explicit h2d: the column crossed the link TWICE. The
        exact-equality assert pins single-crossing."""
        x = rng.normal(size=(50_000, 8)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x})  # NOT analyzed
        before = _counter("frame.h2d_bytes_total")
        got = map_rows(lambda x: {"y": x + 1.0}, df).column_data("y")
        assert np.array_equal(got.host(), x + 1.0)
        assert _counter("frame.h2d_bytes_total") - before == x.nbytes

    def test_analyzed_map_rows_also_uploads_once(self, rng):
        """The fast path itself (analyzed frame, known out specs) has
        always uploaded once via the streaming feeder; pin it so the
        bail-out reorder cannot regress the happy path either."""
        x = rng.normal(size=(50_000, 8)).astype(np.float32)
        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        before = _counter("frame.h2d_bytes_total")
        got = map_rows(lambda x: {"y": x + 1.0}, df).column_data("y")
        assert np.array_equal(got.host(), x + 1.0)
        assert _counter("frame.h2d_bytes_total") - before == x.nbytes


class TestTelemetry:
    def test_histograms_and_gauge(self, tiny_chunks, rng):
        import jax

        x = rng.normal(size=(40, 4)).astype(np.float32)
        h0, d0 = _hist_count("frame.h2d_seconds"), _hist_count(
            "frame.d2h_seconds"
        )
        dev = transfer.h2d(x)
        transfer.d2h(jax.device_put(x))
        assert _hist_count("frame.h2d_seconds") > h0
        assert _hist_count("frame.d2h_seconds") > d0
        # gauge is back to zero once nothing is in flight
        assert _counter("ingest.inflight_chunks") == 0
        del dev
