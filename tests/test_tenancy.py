"""Multi-tenant QoS plane (serve/tenancy.py): quotas, token-bucket rate
limits, priority-aware scheduling/preemption/eviction, and SLO-actuated
shedding.

Two bars hold throughout:

- **off is identical**: with no policies configured (the default) every
  hook is one boolean check and the engine behaves byte-for-byte like
  the pre-tenancy build — admission stays FIFO, preemption stays
  preempt-youngest, eviction stays LRU;
- **on never changes bytes**: QoS reorders *which* request runs *when*
  and *where*; any admitted stream is still byte-identical to the same
  request decoded alone, greedy and seeded, under preemption and
  fleet placement.
"""

import json
import socket
import threading
import time

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu import obs
from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.obs import requests as obs_requests
from tensorframes_tpu.obs import slo, timeseries
from tensorframes_tpu.serve import (
    Fleet,
    GenerationEngine,
    GenRequest,
    PagePool,
    Scheduler,
    tenancy,
)
from tensorframes_tpu.serve.kv_pages import PrefixCache
from tensorframes_tpu.serve.scheduler import GenerationHandle
from tensorframes_tpu.utils import get_config, set_config
from tensorframes_tpu.utils.failures import TenantThrottledError, is_transient

pytestmark = pytest.mark.tenancy

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture(autouse=True)
def _clean_plane():
    """Every test starts and ends with the plane OFF and no runtime
    state (buckets, shed flag, deprioritization holds, fleet ref)."""
    set_config(tenants=())
    tenancy._reset_for_tests()
    yield
    set_config(tenants=(), chaos="")
    tenancy._reset_for_tests()


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _prompts(rng, lens):
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist() for n in lens
    ]


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


def _mk_request(rid, plen=4, max_new=2, priority=1, tenant=""):
    return GenRequest(
        request_id=rid,
        prompt=np.arange(1, plen + 1, dtype=np.int32),
        max_new_tokens=max_new,
        handle=GenerationHandle(rid),
        tenant=tenant,
        priority=priority,
    )


def _enable(*policies):
    """Turn the plane on with the given policy dicts."""
    set_config(tenants=tuple(policies))


#: any policy flips _ON; this one constrains nothing (class only)
_JUST_ON = {"tenant": "qos-on", "priority": "standard"}


# ---------------------------------------------------------------------------
# policy registry / config plumbing
# ---------------------------------------------------------------------------


class TestPolicyRegistry:
    def test_plane_off_by_default_and_admit_is_a_noop(self):
        assert not tenancy.enabled()
        assert tenancy.priority_of("anyone") == 1
        # no policies -> admit never raises, whatever the footprint
        tenancy.admit_request("anyone", 10_000, active=99, queued=99)

    def test_set_config_enables_and_empty_disables(self):
        _enable({"tenant": "a", "priority": "interactive"})
        assert tenancy.enabled()
        assert tenancy.priority_of("a") == 2
        assert tenancy.priority_of("unknown") == 1
        set_config(tenants=())
        assert not tenancy.enabled()

    @pytest.mark.parametrize(
        "bad",
        [
            {"priority": "interactive"},  # no tenant name
            {"tenant": "x", "priority": "urgent"},  # unknown class
            {"tenant": "x", "max_active": -1},
            {"tenant": "x", "tokens_per_s": -5.0},
            {"tenant": "x", "burst": 3},  # unknown field
        ],
    )
    def test_invalid_policy_rejected(self, bad):
        with pytest.raises(ValueError):
            tenancy._parse_policy(bad)

    def test_bucket_state_survives_unrelated_config_change(self):
        _enable({"tenant": "a", "requests_per_s": 1.0})
        tenancy.admit_request("a", 1, 0, 0)  # drains the burst
        with pytest.raises(TenantThrottledError):
            tenancy.admit_request("a", 1, 0, 0)
        # same policy re-set (e.g. an unrelated set_config): still dry
        _enable({"tenant": "a", "requests_per_s": 1.0})
        with pytest.raises(TenantThrottledError):
            tenancy.admit_request("a", 1, 0, 0)
        # a RETUNED rate starts from a fresh bucket
        _enable({"tenant": "a", "requests_per_s": 2.0})
        tenancy.admit_request("a", 1, 0, 0)

    def test_apply_admin_upsert_delete_replace(self):
        view = tenancy.apply_admin(
            {"tenant": "a", "priority": "interactive", "max_active": 2}
        )
        assert [p["tenant"] for p in view] == ["a"]
        assert tenancy.enabled()
        view = tenancy.apply_admin({"tenant": "b", "priority": "batch"})
        assert [p["tenant"] for p in view] == ["a", "b"]
        view = tenancy.apply_admin({"tenant": "a", "delete": True})
        assert [p["tenant"] for p in view] == ["b"]
        # replace-all with [] turns the plane off; bad specs never land
        with pytest.raises(ValueError):
            tenancy.apply_admin({"tenants": [{"tenant": ""}]})
        assert tenancy.enabled()
        assert tenancy.apply_admin({"tenants": []}) == []
        assert not tenancy.enabled()


# ---------------------------------------------------------------------------
# token bucket
# ---------------------------------------------------------------------------


class TestBucket:
    def test_zero_rate_is_unlimited(self):
        b = tenancy._Bucket(0.0)
        for _ in range(100):
            assert b.try_take(1e9, now=0.0) == 0.0

    def test_burst_then_refusal_with_refill_hint(self):
        b = tenancy._Bucket(2.0)  # burst = 2
        b.t = 0.0  # anchor the reference clock for the explicit nows
        assert b.try_take(1.0, now=100.0) == 0.0
        assert b.try_take(1.0, now=100.0) == 0.0
        wait = b.try_take(1.0, now=100.0)
        assert wait == pytest.approx(0.5)  # 1 unit at 2/s
        # after the advertised wait the take succeeds
        assert b.try_take(1.0, now=100.0 + wait) == 0.0

    def test_oversized_cost_admits_on_burst_then_charges_debt(self):
        # a single request larger than the burst must not deadlock:
        # it is admitted against a full bucket and driven into debt,
        # enforcing the SUSTAINED rate
        b = tenancy._Bucket(10.0)  # burst = 10
        b.t = 0.0  # anchor the reference clock for the explicit nows
        assert b.try_take(35.0, now=0.0) == 0.0
        assert b.level == pytest.approx(-25.0)
        # the next request waits for the debt plus its own need
        wait = b.try_take(10.0, now=0.0)
        assert wait == pytest.approx(3.5)
        assert b.try_take(10.0, now=3.5) == 0.0


# ---------------------------------------------------------------------------
# the admission gate
# ---------------------------------------------------------------------------


class TestAdmission:
    def test_quota_bounds_total_footprint(self):
        _enable({"tenant": "a", "max_active": 2, "max_queued": 1})
        tenancy.admit_request("a", 4, active=1, queued=1)  # 2 < 3
        with pytest.raises(TenantThrottledError) as ei:
            tenancy.admit_request("a", 4, active=2, queued=1)
        assert ei.value.reason == "quota"
        assert ei.value.tenant == "a"

    def test_rate_reason_carries_refill_retry_after(self):
        _enable({"tenant": "a", "requests_per_s": 0.5})
        tenancy.admit_request("a", 4, 0, 0)
        with pytest.raises(TenantThrottledError) as ei:
            tenancy.admit_request("a", 4, 0, 0)
        assert ei.value.reason == "rate"
        assert 0.0 < ei.value.retry_after <= 2.1

    def test_token_rate_charges_requested_tokens(self):
        _enable({"tenant": "a", "tokens_per_s": 8.0})
        tenancy.admit_request("a", 100, 0, 0)  # burst admit, deep debt
        with pytest.raises(TenantThrottledError) as ei:
            tenancy.admit_request("a", 1, 0, 0)
        assert ei.value.reason == "rate"
        assert ei.value.retry_after > 5.0  # ~92 tokens of debt at 8/s

    def test_unknown_tenant_unlimited_but_counted(self):
        _enable({"tenant": "other", "requests_per_s": 1.0})
        # no policy for "b": quota/rate never refuse it
        for _ in range(20):
            tenancy.admit_request("b", 1000, 5, 5)

    def test_shed_refuses_batch_class_only(self):
        _enable(
            {"tenant": "bg", "priority": "batch"},
            {"tenant": "fg", "priority": "interactive"},
        )
        tenancy._shed_active = True
        try:
            with pytest.raises(TenantThrottledError) as ei:
                tenancy.admit_request("bg", 4, 0, 0)
            assert ei.value.reason == "shed"
            assert ei.value.retry_after == pytest.approx(5.0)
            tenancy.admit_request("fg", 4, 0, 0)  # interactive sails
            tenancy.admit_request("std", 4, 0, 0)  # unknown = standard
        finally:
            tenancy._shed_active = False

    def test_throttle_increments_counter_and_flight_ring(self):
        _enable({"tenant": "a", "max_active": 1})
        base = _counter_value(
            "serve.tenant_throttled_total", tenant="a", reason="quota"
        )
        with pytest.raises(TenantThrottledError):
            tenancy.admit_request("a", 4, active=1, queued=0)
        assert _counter_value(
            "serve.tenant_throttled_total", tenant="a", reason="quota"
        ) == base + 1
        events = [
            e for e in obs.flight.rings().get("tenancy", [])
            if e.get("kind") == "throttle" and e.get("tenant") == "a"
        ]
        assert events and events[-1]["reason"] == "quota"

    def test_throttled_error_is_not_transient_and_not_replayable(self):
        err = TenantThrottledError("no", retry_after=2.0, reason="rate")
        assert not is_transient(err)
        # the fleet must never replay a throttled admission elsewhere —
        # that would launder the refusal through a second replica
        assert not Fleet._replayable(err)

    def test_chaos_site_covers_the_admission_path(self):
        set_config(chaos="tenancy.admit=transient:p=1.0")
        try:
            with pytest.raises(RuntimeError, match="UNAVAILABLE"):
                tenancy.admit_request("a", 1, 0, 0)
        finally:
            set_config(chaos="")


# ---------------------------------------------------------------------------
# priority-aware scheduler: admission order + victim choice
# ---------------------------------------------------------------------------


class TestPriorityScheduling:
    def _sched(self, num_pages=8, page_size=4, max_slots=2, cap=8):
        pool = PagePool(1, 1, 4, num_pages, page_size)
        return Scheduler(
            pool, max_slots, cap, max_seq_len=num_pages * page_size
        )

    def test_admit_prefers_priority_then_arrival(self):
        _enable(_JUST_ON)
        s = self._sched(max_slots=1)
        s.submit(_mk_request(1, priority=0))
        s.submit(_mk_request(2, priority=2))
        s.submit(_mk_request(3, priority=2))
        s.submit(_mk_request(4, priority=1))
        order = []
        while s.queue_depth or any(s.slots):
            for idx, act in s.admit():
                order.append(act.req.request_id)
                s.finish(idx)
        # interactive first (in arrival order), then standard, then batch
        assert order == [2, 3, 4, 1]

    def test_plane_off_is_strict_fifo_even_with_priorities_set(self):
        s = self._sched(max_slots=1)
        s.submit(_mk_request(1, priority=0))
        s.submit(_mk_request(2, priority=2))
        ((idx, act),) = s.admit()
        assert act.req.request_id == 1  # FIFO: the QoS-off contract
        s.finish(idx)

    def test_victim_is_lowest_priority_then_youngest(self):
        _enable(_JUST_ON)
        s = self._sched(num_pages=3, page_size=4, max_slots=3)
        s.submit(_mk_request(1, plen=4, max_new=8, priority=0))
        s.submit(_mk_request(2, plen=4, max_new=8, priority=2))
        s.submit(_mk_request(3, plen=4, max_new=8, priority=2))
        admitted = s.admit()
        assert len(admitted) == 3 and s.pool.pages_free == 0
        by_rid = {a.req.request_id: i for i, a in admitted}
        # pool pressure: request 2 needs a second page; the BATCH slot
        # pays, not the younger interactive one (QoS-off evicts rid 3)
        base = _counter_value("serve.preemptions_total", priority="batch")
        a2 = s.slots[by_rid[2]]
        a2.generated.extend([9] * 4)
        assert s.grow(by_rid[2]) is True
        assert s.slots[by_rid[1]] is None  # the batch victim
        assert s.slots[by_rid[3]] is not None  # interactive survived
        assert s._waiting[0].request_id == 1
        assert s._waiting[0].priority == 0  # class survives the requeue
        assert _counter_value(
            "serve.preemptions_total", priority="batch"
        ) == base + 1

    def test_tenant_counts_folds_slots_and_queue(self):
        s = self._sched(max_slots=1, cap=8)
        s.submit(_mk_request(1, tenant="a"))
        s.submit(_mk_request(2, tenant="a"))
        s.submit(_mk_request(3, tenant="b"))
        s.admit()
        active, queued = s.tenant_counts()
        assert active == {"a": 1}
        assert queued == {"a": 1, "b": 1}


# ---------------------------------------------------------------------------
# priority-weighted prefix-cache eviction + speculative clamp
# ---------------------------------------------------------------------------


class TestPriorityEviction:
    def _cache(self, num_pages=8, page_size=4):
        pool = PagePool(1, 1, 4, num_pages, page_size)
        return pool, PrefixCache(pool)

    @staticmethod
    def _insert(pool, cache, tokens, priority):
        pages = pool.alloc(1)
        cache.insert(tokens, pages, priority=priority)
        pool.free(pages)  # the cache's reference is now the only one

    def test_low_priority_prefixes_evict_first_when_on(self):
        _enable(_JUST_ON)
        pool, cache = self._cache()
        hi = np.arange(1, 5, dtype=np.int32)
        lo = np.arange(10, 14, dtype=np.int32)
        self._insert(pool, cache, hi, priority=2)
        self._insert(pool, cache, lo, priority=0)  # newer, lower rank
        assert cache.evict_pages(1) == 1
        # the interactive prefix survived; plain LRU would have evicted
        # it (it is the OLDER entry) and kept the batch one
        assert len(cache) == 1
        assert next(iter(cache._entries.values())).priority == 2

    def test_off_keeps_plain_lru(self):
        pool, cache = self._cache()
        older = np.arange(1, 5, dtype=np.int32)
        newer = np.arange(10, 14, dtype=np.int32)
        self._insert(pool, cache, older, priority=2)
        self._insert(pool, cache, newer, priority=0)
        assert cache.evict_pages(1) == 1
        # LRU: the OLDER entry went, priority ignored with the plane off
        assert len(cache) == 1
        assert next(iter(cache._entries.values())).priority == 0

    def test_shared_prefix_keeps_highest_registrant_rank(self):
        _enable(_JUST_ON)
        pool, cache = self._cache()
        shared = np.arange(1, 5, dtype=np.int32)
        pages = pool.alloc(1)
        cache.insert(shared, pages, priority=2)
        cache.insert(shared, pages, priority=0)  # batch re-registers
        ent = next(iter(cache._entries.values()))
        assert ent.priority == 2  # the interactive share still protects it

    def test_spec_k_clamps_by_rank_only_under_pressure(self):
        _enable(_JUST_ON)
        # plenty free -> untouched at any rank
        assert tenancy.clamp_spec_k(4, 0, pages_free=50, pages_total=100) == 4
        # tight pool -> batch 1, standard 2, interactive keeps k
        assert tenancy.clamp_spec_k(4, 0, pages_free=10, pages_total=100) == 1
        assert tenancy.clamp_spec_k(4, 1, pages_free=10, pages_total=100) == 2
        assert tenancy.clamp_spec_k(4, 2, pages_free=10, pages_total=100) == 4
        set_config(tenants=())
        assert tenancy.clamp_spec_k(4, 0, pages_free=10, pages_total=100) == 4


# ---------------------------------------------------------------------------
# engine integration: QoS off is byte-identical, QoS on never changes bytes
# ---------------------------------------------------------------------------


class TestEngineIntegration:
    def test_off_streams_match_solo_under_contention(self, lm):
        rng = np.random.default_rng(21)
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=32, num_pages=10
        )
        prompts = _prompts(rng, (6, 9, 4, 8))
        outs = eng.generate(prompts, max_new_tokens=10)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 10))
        assert eng.pool.pages_in_use == 0

    def test_on_streams_match_solo_under_priority_preemption(self, lm):
        _enable(
            {"tenant": "fg", "priority": "interactive"},
            {"tenant": "bg", "priority": "batch"},
        )
        rng = np.random.default_rng(22)
        # the starved-pool workload from test_serve, now with mixed
        # classes: preemption picks batch victims, streams stay exact
        eng = GenerationEngine(
            lm, max_slots=4, page_size=4, max_seq_len=32, num_pages=10
        )
        base = _counter_value("serve.preemptions_total", priority="batch")
        prompts = _prompts(rng, (6, 9, 4, 8))
        tenants = ("bg", "bg", "fg", "fg")
        with eng:
            handles = [
                eng.submit(p, 10, tenant=t) for p, t in zip(prompts, tenants)
            ]
            for p, h in zip(prompts, handles):
                np.testing.assert_array_equal(
                    h.result(timeout=60), _solo(lm, p, 10)
                )
        assert eng.pool.pages_in_use == 0
        # the pool was contended and every victim was batch-class
        assert _counter_value(
            "serve.preemptions_total", priority="batch"
        ) > base

    def test_engine_front_door_throttles_and_books_rejection(self, lm):
        _enable({"tenant": "t", "requests_per_s": 0.01})
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with eng:
            h = eng.submit([1, 2, 3], 2, tenant="t")
            with pytest.raises(TenantThrottledError):
                eng.submit([1, 2, 3], 2, tenant="t")
            # other tenants are not collateral damage
            h2 = eng.submit([1, 2, 3], 2, tenant="other")
            h.result(timeout=60)
            h2.result(timeout=60)

    def test_active_slots_gauge_tracks_tenants(self, lm):
        _enable({"tenant": "g", "priority": "interactive"})
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=48)
        with eng:
            h = eng.submit([1, 2, 3, 4], 24, tenant="g")
            deadline = time.monotonic() + 30
            while (
                _counter_value("serve.tenant_active_slots", tenant="g") < 1
                and time.monotonic() < deadline
            ):
                time.sleep(0.01)
            assert (
                _counter_value("serve.tenant_active_slots", tenant="g") == 1
            )
            h.result(timeout=60)


# ---------------------------------------------------------------------------
# HTTP: 429 + Retry-After, /admin/tenants, /statusz tenants block
# ---------------------------------------------------------------------------


def _http(addr, req: bytes) -> bytes:
    host, port = addr.rsplit(":", 1)
    with socket.create_connection((host, int(port)), timeout=60) as c:
        c.sendall(req)
        out = b""
        while True:
            b = c.recv(65536)
            if not b:
                break
            out += b
    return out


def _req(addr, verb, path, payload=None):
    body = b"" if payload is None else json.dumps(payload).encode()
    head = (
        f"{verb} {path} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(body)}\r\n\r\n"
    ).encode()
    resp = _http(addr, head + body)
    status = int(resp.split(b" ", 2)[1])
    raw_head, _, raw_body = resp.partition(b"\r\n\r\n")
    headers = {}
    for line in raw_head.split(b"\r\n")[1:]:
        k, _, v = line.partition(b":")
        headers[k.strip().lower().decode()] = v.strip().decode()
    return status, headers, json.loads(raw_body or b"{}")


class TestHTTP:
    def test_429_retry_after_and_admin_lifecycle(self, lm):
        from tensorframes_tpu.interop.serving import ScoringServer

        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        with ScoringServer(engine=eng) as addr:
            # plane off: admin view says so, statusz has no tenants block
            status, _, out = _req(addr, "GET", "/admin/tenants")
            assert status == 200 and out == {
                "enabled": False, "shedding": False, "tenants": [],
            }
            st, _, page = _req(addr, "GET", "/statusz")
            assert st == 200 and page["tenants"] is None

            # upsert a tight policy at runtime
            status, _, out = _req(
                addr, "POST", "/admin/tenants",
                {"tenant": "flood", "requests_per_s": 0.01,
                 "priority": "batch"},
            )
            assert status == 200 and out["enabled"]
            assert out["tenants"][0]["tenant"] == "flood"

            # first request spends the burst, second answers 429
            spec = {"prompt": [1, 2, 3], "max_new_tokens": 2,
                    "tenant": "flood"}
            status, _, out = _req(addr, "POST", "/generate", spec)
            assert status == 200
            np.testing.assert_array_equal(
                out["tokens"], _solo(lm, [1, 2, 3], 2)
            )
            status, headers, out = _req(addr, "POST", "/generate", spec)
            assert status == 429
            assert out["reason"] == "rate" and out["tenant"] == "flood"
            assert 1 <= int(headers["retry-after"]) <= 30

            # /statusz shows the tenant row with the booked throttle
            st, _, page = _req(addr, "GET", "/statusz")
            rows = {
                r["tenant"]: r for r in page["tenants"]["tenants"]
            }
            assert rows["flood"]["throttles"].get("rate", 0) >= 1
            assert rows["flood"]["priority"] == "batch"

            # malformed admin bodies answer 400, registry untouched
            status, _, out = _req(
                addr, "POST", "/admin/tenants",
                {"tenant": "x", "priority": "urgent"},
            )
            assert status == 400 and "error" in out

            # delete turns the plane back off
            status, _, out = _req(
                addr, "POST", "/admin/tenants",
                {"tenant": "flood", "delete": True},
            )
            assert status == 200 and not out["enabled"]
            status, _, out = _req(addr, "POST", "/generate", spec)
            assert status == 200


# ---------------------------------------------------------------------------
# the SLO actuator
# ---------------------------------------------------------------------------


@pytest.fixture
def _observatory():
    timeseries.store().reset()
    slo.monitor().clear()
    obs_requests.reset()
    yield
    slo.monitor().clear()
    timeseries.store().reset()
    obs_requests.reset()


def _breach_series(name="t.qos.lat", values=(5.0, 5.0, 5.0), start=1000.0):
    for i, v in enumerate(values):
        timeseries.store().record(name, start + i, v)


def _objective(fast=10.0, slow=20.0):
    return slo.Objective(
        name="t_qos", series="t.qos.lat", bound=1.0, kind="upper",
        fast_window_s=fast, slow_window_s=slow, min_samples=3,
    )


class TestSLOActuator:
    def test_fast_burn_sheds_batch_then_recovers(self, _observatory):
        _enable(
            {"tenant": "bg", "priority": "batch"},
            {"tenant": "fg", "priority": "interactive"},
        )
        slo.monitor().add(_objective())
        _breach_series(values=[5.0, 5.0, 5.0], start=1000.0)
        base = _counter_value("slo.actions_total", action="shed_batch")
        # the real integration: the sampler tick evaluates the monitor
        # and then runs the actuator (obs/timeseries.sample_once)
        timeseries.sample_once(now=1002.0)
        assert tenancy.shedding()
        assert _counter_value(
            "slo.actions_total", action="shed_batch"
        ) == base + 1
        with pytest.raises(TenantThrottledError) as ei:
            tenancy.admit_request("bg", 4, 0, 0)
        assert ei.value.reason == "shed"
        tenancy.admit_request("fg", 4, 0, 0)  # interactive unaffected
        # recovery: healthy samples displace the window
        _breach_series(values=[0.1] * 25, start=1003.0)
        rec = _counter_value("slo.actions_total", action="recover")
        timeseries.sample_once(now=1027.0)
        assert not tenancy.shedding()
        assert _counter_value("slo.actions_total", action="recover") == rec + 1
        tenancy.admit_request("bg", 4, 0, 0)

    def test_sustained_burn_deprioritizes_top_cost_tenant(self, _observatory):
        _enable(
            {"tenant": "whale", "priority": "interactive"},
            {"tenant": "minnow", "priority": "standard"},
        )
        # the cost ledger names the offender
        for _ in range(3):
            obs_requests.record_request(
                tenant="whale", est_flops=5e9, tokens=400, status="completed"
            )
        obs_requests.record_request(
            tenant="minnow", est_flops=1e6, tokens=10, status="completed"
        )
        slo.monitor().add(_objective(fast=10.0, slow=20.0))
        # breach across the SLOW window too -> severity "sustained"
        _breach_series(values=[5.0] * 22, start=1000.0)
        base = _counter_value("slo.actions_total", action="deprioritize")
        timeseries.sample_once(now=1021.0)
        assert _counter_value(
            "slo.actions_total", action="deprioritize"
        ) == base + 1
        # the interactive whale now schedules (and sheds) as batch
        assert tenancy.priority_of("whale") == 0
        assert tenancy.priority_of("minnow") == 1
        with pytest.raises(TenantThrottledError) as ei:
            tenancy.admit_request("whale", 4, 0, 0)  # shedding is on too
        assert ei.value.reason == "shed"
        view = tenancy.statusz_view()
        rows = {r["tenant"]: r for r in view["tenants"]}
        assert rows["whale"]["deprioritized"]
        assert not rows["minnow"]["deprioritized"]
        # one deprioritization per hold: a second sustained tick is a
        # no-op until the hold expires
        timeseries.sample_once(now=1022.0)
        assert _counter_value(
            "slo.actions_total", action="deprioritize"
        ) == base + 1

    def test_deprioritized_tenant_fleet_sessions_are_replaced(
        self, lm, _observatory
    ):
        _enable({"tenant": "whale", "priority": "interactive"})
        fleet = Fleet(
            lm, replicas=2, max_slots=4, page_size=4, max_seq_len=48,
            watchdog_interval_s=0.02,
        )
        with fleet:  # start() registers the fleet with the actuator
            h = fleet.submit([1, 2, 3], 2, session="s1", tenant="whale")
            h.result(timeout=60)
            assert "s1" in fleet._sessions
            obs_requests.record_request(
                tenant="whale", est_flops=1e9, tokens=100, status="completed"
            )
            slo.monitor().add(_objective())
            _breach_series(values=[5.0] * 22, start=1000.0)
            base = _counter_value(
                "slo.actions_total", action="replace_sessions"
            )
            timeseries.sample_once(now=1021.0)
            # the pin is gone: the next request for s1 re-places fresh
            assert "s1" not in fleet._sessions
            assert _counter_value(
                "slo.actions_total", action="replace_sessions"
            ) == base + 1


# ---------------------------------------------------------------------------
# e2e: chaos-slowed decode burns a TTFT SLO until the actuator sheds
# ---------------------------------------------------------------------------


class TestSLOActionEndToEnd:
    def test_decode_latency_burn_sheds_batch_admissions(
        self, lm, _observatory
    ):
        _enable(
            {"tenant": "bg", "priority": "batch"},
            {"tenant": "fg", "priority": "interactive"},
        )
        # any real TTFT breaches the bound; quantile points land only on
        # ticks with NEW observations, so min_samples=1 (the sparse-
        # series tuning from docs/observability.md)
        slo.monitor().add(slo.ttft_p99(
            0.0001, fast_window_s=5.0, slow_window_s=20.0, min_samples=1,
        ))
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        base = _counter_value("slo.actions_total", action="shed_batch")
        set_config(chaos="serve.decode_step=latency:ms=5:p=1.0")
        try:
            with eng:
                # first tick baselines the histograms (windowed
                # quantiles record points only for NEW observations);
                # then real chaos-slowed requests land TTFT samples
                # between ticks until the monitor breaches and the
                # actuator flips shedding
                timeseries.sample_once()
                deadline = time.monotonic() + 60
                while (
                    not tenancy.shedding()
                    and time.monotonic() < deadline
                ):
                    eng.generate([[1, 2, 3]], 2)
                    timeseries.sample_once()
                assert tenancy.shedding(), slo.monitor().status()
                assert _counter_value(
                    "slo.actions_total", action="shed_batch"
                ) == base + 1
                with pytest.raises(TenantThrottledError) as ei:
                    eng.submit([1, 2, 3], 2, tenant="bg")
                assert ei.value.reason == "shed"
                # interactive work still lands while batch sheds
                h = eng.submit([1, 2, 3], 2, tenant="fg")
                np.testing.assert_array_equal(
                    h.result(timeout=60), _solo(lm, [1, 2, 3], 2)
                )
        finally:
            set_config(chaos="")
        # objective gone -> next tick recovers
        slo.monitor().clear()
        timeseries.sample_once()
        assert not tenancy.shedding()


# ---------------------------------------------------------------------------
# the fairness soak (the PR's acceptance workload)
# ---------------------------------------------------------------------------


class TestFairnessSoak:
    def test_flooding_batch_tenant_is_bounded_not_starved(self, lm):
        """2 replicas, 3 tenants. A batch tenant floods past its quota;
        an interactive tenant and a standard tenant submit normally.
        The QoS plane must (a) throttle the flooder's excess with 429s,
        (b) still complete the flooder's admitted share (bounded, not
        starved), (c) keep every admitted stream byte-identical to a
        solo decode, and (d) keep interactive TTFT sane."""
        _enable(
            {"tenant": "fg", "priority": "interactive", "ttft_slo_s": 20.0},
            {"tenant": "std", "priority": "standard"},
            {"tenant": "bg", "priority": "batch",
             "max_active": 2, "max_queued": 2},
        )
        rng = np.random.default_rng(31)
        fleet = Fleet(
            lm, replicas=2, max_slots=4, page_size=4, max_seq_len=48,
            queue_capacity=16, watchdog_interval_s=0.02,
        )
        thr_base = _counter_value(
            "serve.tenant_throttled_total", tenant="bg", reason="quota"
        )
        ttfts = {}
        lock = threading.Lock()

        def consume(key, prompt, handle, t0):
            toks = []
            first = None
            for t in handle:
                if first is None:
                    first = time.perf_counter() - t0
                toks.append(t)
            with lock:
                ttfts[key] = first
            np.testing.assert_array_equal(
                toks, _solo(lm, prompt, len(toks))
            )

        admitted_bg = 0
        threads = []
        with fleet:
            # compile both replicas' step programs outside the timed
            # window (the TTFT assertion measures scheduling, not XLA)
            warm = [
                eng.submit([1, 2, 3], 2, block=False)
                for eng in fleet.engines
            ]
            for h in warm:
                h.result(timeout=120)
            # the flood: 12 batch submissions against a footprint of 4
            bg_prompts = _prompts(rng, (4,) * 12)
            t0 = time.perf_counter()
            for i, p in enumerate(bg_prompts):
                try:
                    h = fleet.submit(p, 6, tenant="bg")
                except TenantThrottledError as e:
                    assert e.reason == "quota"
                    continue
                admitted_bg += 1
                th = threading.Thread(
                    target=consume, args=(f"bg{i}", p, h, t0)
                )
                th.start()
                threads.append(th)
            # normal traffic rides alongside the flood
            fg_prompts = _prompts(rng, (5, 7, 4))
            std_prompts = _prompts(rng, (6, 5))
            for i, p in enumerate(fg_prompts):
                h = fleet.submit(p, 6, tenant="fg")
                th = threading.Thread(
                    target=consume, args=(f"fg{i}", p, h, t0)
                )
                th.start()
                threads.append(th)
            for i, p in enumerate(std_prompts):
                h = fleet.submit(p, 6, tenant="std")
                th = threading.Thread(
                    target=consume, args=(f"std{i}", p, h, t0)
                )
                th.start()
                threads.append(th)
            for th in threads:
                th.join(timeout=120)
                assert not th.is_alive()

        # (a) the flooder's excess was throttled, with the right label
        throttled = _counter_value(
            "serve.tenant_throttled_total", tenant="bg", reason="quota"
        ) - thr_base
        assert throttled >= 1
        assert admitted_bg + throttled == 12
        # (b) bounded, not starved: the admitted share completed
        assert admitted_bg >= 1
        assert all(k in ttfts for k in (f"fg{i}" for i in range(3)))
        # (d) interactive TTFT stayed sane while the flood ran (the
        # bound is generous — CPU CI boxes — but a starved interactive
        # class would blow far past it)
        fg_ttfts = sorted(ttfts[f"fg{i}"] for i in range(3))
        assert fg_ttfts[-1] < 20.0
        # fleet-wide per-tenant accounting saw the mix
        view = tenancy.statusz_view(None)
        assert view is not None and not view["shedding"]
