"""OpBuilder facade + Arrow interop tests (analog of the reference's
PythonInterface wire-protocol behavior + its data ingestion edge)."""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.capture import functions as F
from tensorframes_tpu.interop import from_arrow, to_arrow, spark_available


def saved_graph(tmp_path, df):
    with tft.graph():
        x = tft.block(df, "x")
        g = tft.build_graph((x * 2.0).named("z"))
    p = str(tmp_path / "g.tfs")
    tft.save_graph(g, p)
    return p


class TestOpBuilder:
    def test_map_blocks_from_file(self, tmp_path):
        df = tft.TensorFrame.from_columns({"x": np.arange(4.0)})
        p = saved_graph(tmp_path, df)
        out = tft.OpBuilder.map_blocks(df).graph_from_file(p).build_df()
        assert [r.z for r in out.collect()] == [0.0, 2.0, 4.0, 6.0]

    def test_graph_bytes_and_inputs(self, tmp_path):
        df = tft.TensorFrame.from_columns({"other": np.arange(3.0)})
        df_x = tft.TensorFrame.from_columns({"x": np.arange(3.0)})
        with tft.graph():
            x = tft.block(df_x, "x")
            g = tft.build_graph((x + 1.0).named("z"))
        data = tft.serialize_graph(g)
        out = (
            tft.OpBuilder.map_blocks(df)
            .graph(data)
            .inputs({"x": "other"})
            .build_df()
        )
        assert [r.z for r in out.collect()] == [1.0, 2.0, 3.0]

    def test_reduce_build_row(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(5.0)}).select(
            ("x", "x")
        )
        with tft.graph():
            xin = tft.block(df, "x", tft_name="x_input")
            g = tft.build_graph(F.reduce_sum(xin, axis=[0], name="x"))
        out = tft.OpBuilder.reduce_blocks(df).graph(g).build_row()
        assert float(out) == 10.0

    def test_fetch_subset(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(3.0)})
        g = tft.CapturedGraph.from_callable(
            lambda x: {"a": x + 1, "b": x + 2},
            {"x": (tft.schema.FLOAT64, tft.Shape(-1))},
        )
        out = (
            tft.OpBuilder.map_blocks(df).graph(g).fetches(["b"]).build_df()
        )
        assert set(out.columns) == {"b", "x"}

    def test_wire_name_aliases(self, tmp_path):
        df = tft.TensorFrame.from_columns({"x": np.arange(2.0)})
        p = saved_graph(tmp_path, df)
        b = tft.OpBuilder.map_blocks(df)
        out = b.graphFromFile(p).buildDF()
        assert [r.z for r in out.collect()] == [0.0, 2.0]

    def test_errors(self):
        df = tft.TensorFrame.from_columns({"x": np.arange(2.0)})
        with pytest.raises(ValueError, match="no graph"):
            tft.OpBuilder.map_blocks(df).build_df()
        with pytest.raises(ValueError, match="unknown op kind"):
            tft.OpBuilder("nope", df)


class TestArrowInterop:
    def test_roundtrip_scalar_and_vector(self):
        pa = pytest.importorskip("pyarrow")
        t = pa.table(
            {
                "x": pa.array([1.0, 2.0, 3.0]),
                "v": pa.array([[1, 2], [3, 4], [5, 6]]),
            }
        )
        df = from_arrow(t)
        assert df.num_rows == 3
        assert df.schema["v"].nesting == 1
        back = to_arrow(df.analyze())
        assert back.column("x").to_pylist() == [1.0, 2.0, 3.0]
        assert back.column("v").to_pylist()[2] == [5, 6]

    def test_binary_column(self):
        pa = pytest.importorskip("pyarrow")
        t = pa.table({"b": pa.array([b"ab", b"c"], type=pa.binary())})
        df = from_arrow(t)
        assert df.schema["b"].scalar_type.name == "binary"
        back = to_arrow(df)
        assert back.column("b").to_pylist() == [b"ab", b"c"]

    def test_engine_over_arrow_frame(self):
        pa = pytest.importorskip("pyarrow")
        t = pa.table({"x": pa.array(np.arange(6.0))})
        df = from_arrow(t, num_partitions=2)
        out = tft.reduce_blocks(lambda x_input: {"x": x_input.sum()}, df)
        assert float(out) == 15.0


def test_spark_gated():
    if not spark_available():
        from tensorframes_tpu.interop import from_spark

        with pytest.raises(ImportError, match="pyspark"):
            from_spark(None)


class TestArrowBatchMapper:
    """Partition streaming (mapInArrow contract): the executor-side
    function consumes an iterator of RecordBatches and yields result
    batches — tested against that exact contract (what Spark executes),
    no cluster needed. Reference anchor: compute goes to the partitions
    (DebugRowOps.scala:377-391)."""

    def _batches(self, n=10, per=4):
        pa = pytest.importorskip("pyarrow")

        out = []
        for lo in range(0, n, per):
            rows = min(per, n - lo)
            out.append(
                pa.RecordBatch.from_pydict(
                    {"x": [float(lo + i) for i in range(rows)]}
                )
            )
        return out

    def test_streams_partition_batches(self):
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        fn = arrow_batch_mapper(lambda x: {"y": x * 2.0 + 1.0})
        got = list(fn(iter(self._batches())))
        assert all(isinstance(b, pa.RecordBatch) for b in got)
        table = pa.Table.from_batches(got)
        ys = table.column("y").to_pylist()
        xs = table.column("x").to_pylist()
        assert ys == [x * 2.0 + 1.0 for x in xs]
        assert xs == [float(i) for i in range(10)]

    def test_trim_drops_inputs(self):
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        fn = arrow_batch_mapper(lambda x: {"y": x + 1.0}, trim=True)
        table = pa.Table.from_batches(list(fn(iter(self._batches()))))
        assert table.column_names == ["y"]

    def test_batch_rechunking(self):
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        fn = arrow_batch_mapper(lambda x: {"y": x + 1.0}, batch_rows=2)
        got = list(fn(iter(self._batches(n=8, per=8))))
        assert all(b.num_rows <= 2 for b in got)
        assert sum(b.num_rows for b in got) == 8

    def test_streaming_mode_per_batch(self):
        # streaming=True: row-local programs run per incoming batch with
        # bounded memory; results identical to the buffered mode
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        prog = lambda x: {"y": x * 3.0}
        buffered = pa.Table.from_batches(
            list(arrow_batch_mapper(prog)(iter(self._batches())))
        )
        streamed = pa.Table.from_batches(
            list(arrow_batch_mapper(prog, streaming=True)(iter(self._batches())))
        )
        assert streamed.column("y").to_pylist() == buffered.column(
            "y"
        ).to_pylist()

    def test_streaming_mode_skips_empty_batches(self):
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        empty = pa.RecordBatch.from_pydict({"x": pa.array([], pa.float64())})
        batches = [empty] + self._batches(n=4, per=2) + [empty]
        fn = arrow_batch_mapper(lambda x: {"y": x + 1.0}, streaming=True)
        table = pa.Table.from_batches(list(fn(iter(batches))))
        assert table.num_rows == 4

    def test_no_driver_materialization(self):
        # feeding a generator (not a list) works — the exact iterator
        # contract Spark executes
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        def gen():
            for b in self._batches(n=6, per=3):
                yield b

        fn = arrow_batch_mapper(lambda x: {"y": x - 1.0})
        table = pa.Table.from_batches(list(fn(gen())))
        assert table.num_rows == 6

    def test_block_semantics_independent_of_arrow_chunking(self):
        # the iterator covers one partition: a cross-row block op must see
        # the whole partition, not Spark's arbitrary Arrow batch size
        # (maxRecordsPerBatch must not leak into results)
        pa = pytest.importorskip("pyarrow")

        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        fn = arrow_batch_mapper(lambda x: {"y": x - x.mean()})
        chunked = pa.Table.from_batches(
            list(fn(iter(self._batches(n=8, per=3))))
        )
        whole = pa.Table.from_batches(
            list(fn(iter(self._batches(n=8, per=8))))
        )
        assert chunked.column("y").to_pylist() == whole.column("y").to_pylist()

    def test_empty_partition_yields_nothing(self):
        pytest.importorskip("pyarrow")
        from tensorframes_tpu.interop.spark import arrow_batch_mapper

        fn = arrow_batch_mapper(lambda x: {"y": x + 1.0})
        assert list(fn(iter([]))) == []
