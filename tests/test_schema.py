"""Schema-core tests (analog of the reference's shape/metadata unit tests)."""

import numpy as np
import pytest

from tensorframes_tpu.schema import (
    BINARY,
    FLOAT32,
    FLOAT64,
    INT32,
    INT64,
    ColumnInfo,
    FrameInfo,
    Shape,
    Unknown,
    for_any,
    for_name,
    for_numpy_dtype,
    has_ops,
)


class TestShape:
    def test_basic(self):
        s = Shape(2, 3)
        assert s.num_dims == 2
        assert s.dims == (2, 3)
        assert s.num_elements == 6
        assert not s.has_unknown

    def test_unknown(self):
        s = Shape(Unknown, 3)
        assert s.has_unknown
        assert s.num_elements is None
        assert repr(s) == "[?,3]"

    def test_empty_scalar(self):
        s = Shape.empty()
        assert s.num_dims == 0
        assert s.num_elements == 1

    def test_prepend_tail_drop(self):
        s = Shape(3)
        assert s.prepend(5) == Shape(5, 3)
        assert Shape(5, 3).tail() == Shape(3)
        assert Shape(5, 3).drop_inner() == Shape(5)

    def test_from_iterable(self):
        assert Shape([2, 3]) == Shape(2, 3)
        assert Shape((2,)) == Shape(2)

    # reference Shape.scala:54-59
    def test_more_precise(self):
        assert Shape(5, 3).check_more_precise_than(Shape(Unknown, 3))
        assert Shape(5, 3).check_more_precise_than(Shape(5, 3))
        assert not Shape(5, 3).check_more_precise_than(Shape(5, 4))
        assert not Shape(5, 3).check_more_precise_than(Shape(3))
        assert Shape(Unknown).check_more_precise_than(Shape(Unknown))

    # reference ExperimentalOperations.scala:147-157
    def test_merge(self):
        assert Shape(2, 3).merge(Shape(2, 3)) == Shape(2, 3)
        assert Shape(2, 3).merge(Shape(2, 4)) == Shape(2, Unknown)
        assert Shape(2, 3).merge(Shape(3)) is None

    def test_invalid_dim(self):
        with pytest.raises(ValueError):
            Shape(-2)

    def test_jax_roundtrip(self):
        s = Shape(Unknown, 4)
        assert s.to_jax() == (None, 4)
        assert Shape.from_jax((None, 4)) == s
        assert s.to_concrete(fill=7) == (7, 4)

    def test_hash_eq(self):
        assert Shape(1, 2) == (1, 2)
        assert hash(Shape(1, 2)) == hash(Shape(1, 2))
        d = {Shape(1, 2): "a"}
        assert d[Shape(1, 2)] == "a"


class TestDtypes:
    def test_registry_lookup(self):
        assert for_numpy_dtype(np.float64) is FLOAT64
        assert for_numpy_dtype("int32") is INT32
        assert for_name("float32") is FLOAT32

    def test_for_any(self):
        assert for_any(3.0) is FLOAT64
        assert for_any(3) is INT64
        assert for_any(b"abc") is BINARY
        assert for_any(np.zeros(3, np.int32)) is INT32
        assert for_any("int64") is INT64
        assert for_any(INT32) is INT32

    def test_has_ops(self):
        assert has_ops(1.5)
        assert has_ops(np.int32(2))
        assert not has_ops(object())

    def test_binary_no_blocks(self):
        assert not BINARY.supports_blocks
        assert FLOAT64.supports_blocks

    def test_unsupported(self):
        with pytest.raises(KeyError):
            for_numpy_dtype(np.complex128)


class TestColumnInfo:
    def test_minimal_shape_from_nesting(self):
        c = ColumnInfo("x", FLOAT64, nesting=0)
        assert c.block_shape == Shape(Unknown)
        assert c.cell_shape == Shape.empty()
        c2 = ColumnInfo("y", FLOAT64, nesting=1)
        assert c2.block_shape == Shape(Unknown, Unknown)

    def test_analyzed_overrides(self):
        c = ColumnInfo("y", FLOAT64, nesting=1).with_analyzed(Shape(Unknown, 2))
        assert c.block_shape == Shape(Unknown, 2)
        assert c.cell_shape == Shape(2)

    def test_metadata_roundtrip(self):
        c = ColumnInfo("y", INT64, analyzed_shape=Shape(Unknown, 2), nesting=1)
        md = c.to_metadata()
        c2 = ColumnInfo.from_metadata("y", md)
        assert c2 == c

    def test_explain_line_format(self):
        # matches the reference README's print_schema sample (README.md:105-108)
        c = ColumnInfo("y", FLOAT64, analyzed_shape=Shape(Unknown, 2), nesting=1)
        assert c.explain_line() == " |-- y: array (nullable = false) DoubleType[?,2]"


class TestFrameInfo:
    def test_explain(self):
        fi = FrameInfo(
            [
                ColumnInfo("x", FLOAT64, nesting=0),
                ColumnInfo("y", INT32, analyzed_shape=Shape(10, 2), nesting=1),
            ]
        )
        out = fi.explain()
        assert out.startswith("root\n")
        assert "|-- x:" in out and "IntegerType[10,2]" in out

    def test_duplicate_names_rejected(self):
        with pytest.raises(ValueError):
            FrameInfo([ColumnInfo("x", FLOAT64), ColumnInfo("x", FLOAT32)])

    def test_lookup(self):
        fi = FrameInfo([ColumnInfo("x", FLOAT64)])
        assert fi["x"].scalar_type is FLOAT64
        assert "x" in fi and "z" not in fi
        with pytest.raises(KeyError):
            fi["z"]
