"""Failure handling: transient retry + OOM degradation.

The reference delegates all of this to Spark task retry (SURVEY §5); here
the engine owns it. Device failures are injected by patching the jit
wrappers — the classification layer only sees exception text, same as it
would from a real PJRT client.
"""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.engine import ops as engine_ops
from tensorframes_tpu.frame import TensorFrame
from tensorframes_tpu.utils import (
    DeviceOOMError,
    is_oom,
    is_transient,
    run_with_retries,
    set_config,
    get_config,
)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=2, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


class TestClassification:
    def test_oom(self):
        assert is_oom(RuntimeError("RESOURCE_EXHAUSTED: while allocating"))
        assert is_oom(RuntimeError("Out of memory allocating 16G"))
        assert not is_oom(RuntimeError("UNAVAILABLE: socket closed"))

    def test_transient(self):
        assert is_transient(RuntimeError("UNAVAILABLE: connection reset"))
        assert is_transient(RuntimeError("DEADLINE_EXCEEDED: 30s"))
        assert not is_transient(ValueError("shapes do not match"))
        # OOM is NOT transient: identical retry cannot help
        assert not is_transient(RuntimeError("RESOURCE_EXHAUSTED"))

    def test_markers_match_case_insensitively(self):
        # PJRT renders UNAVAILABLE, grpc-python unavailable, wrappers
        # anything between — the casing must not decide retryability
        assert is_transient(RuntimeError("unavailable: tunnel dropped"))
        assert is_transient(RuntimeError("Deadline_Exceeded: rpc wait"))
        assert is_transient(RuntimeError("Connection Reset by peer"))
        assert is_transient(RuntimeError("SOCKET CLOSED mid-write"))
        assert is_oom(RuntimeError("resource_exhausted: hbm"))
        assert is_oom(RuntimeError("OUT OF MEMORY while allocating"))
        assert is_oom(RuntimeError("oom during reduction"))

    def test_chained_cause_text_is_seen(self):
        # a wrapped PJRT status (`raise X from Y`) keeps its class
        def build(inner_msg, outer_msg="dispatch failed"):
            try:
                try:
                    raise RuntimeError(inner_msg)
                except RuntimeError as inner:
                    raise RuntimeError(outer_msg) from inner
            except RuntimeError as outer:
                return outer

        assert is_transient(build("UNAVAILABLE: preempted tunnel"))
        assert is_oom(build("RESOURCE_EXHAUSTED: hbm"))
        assert not is_transient(build("RESOURCE_EXHAUSTED: hbm"))
        assert not is_transient(build("just a bug"))
        # implicit __context__ (no `from`) must NOT leak retryability:
        # an unrelated error raised while HANDLING a transient one is
        # its own failure
        try:
            try:
                raise RuntimeError("UNAVAILABLE: flaky")
            except RuntimeError:
                raise ValueError("bug in the handler")
        except ValueError as e:
            assert not is_transient(e)

    def test_typed_oom_anywhere_in_chain(self):
        try:
            try:
                raise DeviceOOMError("pool dry")
            except DeviceOOMError as inner:
                raise RuntimeError("step failed") from inner
        except RuntimeError as e:
            assert is_oom(e) and not is_transient(e)

    def test_near_miss_strings_do_not_match(self):
        # "oom" must match as a word, not as a substring of zoom/room —
        # the old any-substring matching would break here once markers
        # went case-insensitive
        assert not is_oom(RuntimeError("zoom level 3 unsupported"))
        assert not is_oom(RuntimeError("the room is full"))
        assert not is_oom(RuntimeError("Bloom filter saturated"))
        assert is_oom(RuntimeError("OOM: killed"))
        assert is_oom(RuntimeError("device oom (16G requested)"))
        # "not available" is not "unavailable"
        assert not is_transient(RuntimeError("backend not available"))
        # a deadline that was merely mentioned is not the status marker
        assert not is_transient(RuntimeError("the deadline exceeded plan"))

    def test_deadline_exceeded_error_is_terminal(self):
        from tensorframes_tpu.utils import DeadlineExceededError

        e = DeadlineExceededError("request 7 exceeded its deadline")
        # a missed REQUEST deadline is caller-facing and final — unlike
        # a PJRT DEADLINE_EXCEEDED dispatch status, which retries
        assert not is_transient(e)
        assert not is_oom(e)
        assert isinstance(e, TimeoutError)


class TestRunWithRetries:
    def test_retries_then_succeeds(self, fast_retries):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise RuntimeError("UNAVAILABLE: tunnel dropped")
            return 42

        assert run_with_retries(flaky) == 42
        assert len(calls) == 3  # initial + 2 retries

    def test_exhausts_and_raises(self, fast_retries):
        def always():
            raise RuntimeError("UNAVAILABLE: down")

        with pytest.raises(RuntimeError, match="UNAVAILABLE"):
            run_with_retries(always)

    def test_nontransient_raises_immediately(self, fast_retries):
        calls = []

        def bad():
            calls.append(1)
            raise ValueError("bug")

        with pytest.raises(ValueError):
            run_with_retries(bad)
        assert len(calls) == 1


class TestEngineIntegration:
    def test_map_blocks_transient_retried(self, fast_retries, monkeypatch):
        real = engine_ops._jitted
        state = {"failed": False}

        def flaky_jitted(g):
            fn = real(g)

            def wrapper(feed):
                if not state["failed"]:
                    state["failed"] = True
                    raise RuntimeError("UNAVAILABLE: injected")
                return fn(feed)

            return wrapper

        monkeypatch.setattr(engine_ops, "_jitted", flaky_jitted)
        df = TensorFrame.from_columns({"x": np.arange(6.0)})
        out = tft.map_blocks(lambda x: {"z": x + 1.0}, df).collect()
        assert [r.z for r in out] == [float(i + 1) for i in range(6)]
        assert state["failed"]

    def test_map_blocks_oom_says_repartition(self, fast_retries, monkeypatch):
        def oom_jitted(g):
            def wrapper(feed):
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")

            return wrapper

        monkeypatch.setattr(engine_ops, "_jitted", oom_jitted)
        df = TensorFrame.from_columns({"x": np.arange(6.0)})
        with pytest.raises(DeviceOOMError, match="repartition"):
            tft.map_blocks(lambda x: {"z": x + 1.0}, df).cache()

    def test_distributed_program_dispatch_retries(self, fast_retries):
        from tensorframes_tpu.parallel import distributed as D

        class G:
            pass

        calls = []

        def build():
            def prog(x):
                calls.append(1)
                if len(calls) < 2:
                    raise RuntimeError("UNAVAILABLE: injected")
                return x + 1

            return prog

        p = D._cached_program(G(), "k", build)
        assert p(1) == 2
        assert len(calls) == 2

    def test_map_rows_oom_halves_chunks(self, fast_retries, monkeypatch):
        real = engine_ops._jitted_vmap
        big_calls = []

        def limited_vmap(g):
            fn = real(g)

            def wrapper(feed):
                m = next(iter(feed.values())).shape[0]
                if m > 4:
                    big_calls.append(m)
                    raise RuntimeError("RESOURCE_EXHAUSTED: injected")
                return fn(feed)

            return wrapper

        monkeypatch.setattr(engine_ops, "_jitted_vmap", limited_vmap)
        df = TensorFrame.from_columns({"x": np.arange(20.0)})
        out = tft.map_rows(lambda x: {"y": x * 2.0}, df).collect()
        assert [r.y for r in out] == [float(2 * i) for i in range(20)]
        assert big_calls  # the halving path actually fired

    def test_reduce_blocks_streaming_path_correct(self, fast_retries):
        # force the host-streaming feeder (column over the cache budget):
        # reduce must take the per-partition sync path and stay correct
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=64)
        try:
            y = np.arange(40, dtype=np.float64).reshape(20, 2)
            df = TensorFrame.from_columns({"y": y}, num_partitions=4).analyze()
            s = tft.reduce_blocks(
                lambda y_input: {"y": y_input.sum(axis=0)}, df
            )
            np.testing.assert_allclose(np.asarray(s), y.sum(axis=0))
        finally:
            set_config(device_cache_bytes=old)

    def test_map_rows_single_row_oom_is_typed(self, fast_retries, monkeypatch):
        def always_oom(g):
            def wrapper(feed):
                raise RuntimeError("RESOURCE_EXHAUSTED: injected")

            return wrapper

        monkeypatch.setattr(engine_ops, "_jitted_vmap", always_oom)
        df = TensorFrame.from_columns({"x": np.arange(4.0)})
        with pytest.raises(DeviceOOMError, match="one row per call"):
            tft.map_rows(lambda x: {"y": x * 2.0}, df).cache()


class _PoisonedResult:
    """Mimics a jax array whose async computation failed: shape metadata is
    readable (the dispatch-time checks pass), but any materialization —
    block_until_ready or conversion to numpy — raises the stored error."""

    def __init__(self, real):
        self._real = np.asarray(real)
        self.shape = self._real.shape
        self.nbytes = self._real.nbytes

    def block_until_ready(self):
        raise RuntimeError("UNAVAILABLE: injected mid-chain async failure")

    def __array__(self, *a, **k):
        raise RuntimeError("UNAVAILABLE: injected mid-chain async failure")


class TestMidChainRecovery:
    """A transient failure during ASYNC execution surfaces at
    materialization; the engine must re-run only the partitions whose
    outputs were lost — never the completed ones."""

    def _flaky_backend(self, fail_call_idx):
        real = engine_ops._jitted
        calls = []

        def jitted(g):
            fn = real(g)

            def wrapper(feed):
                idx = len(calls)
                calls.append(idx)
                res = fn(feed)
                if idx == fail_call_idx:
                    return {k: _PoisonedResult(v) for k, v in res.items()}
                return res

            return wrapper

        return jitted, calls

    def test_device_resident_chain_recovers_lost_partition(
        self, fast_retries, monkeypatch
    ):
        jitted, calls = self._flaky_backend(fail_call_idx=2)
        monkeypatch.setattr(engine_ops, "_jitted", jitted)
        df = TensorFrame.from_columns(
            {"x": np.arange(8.0)}, num_partitions=4
        )
        out = tft.map_blocks(lambda x: {"z": x * 10.0}, df).collect()
        assert [r.z for r in out] == [float(10 * i) for i in range(8)]
        # 4 partitions + exactly ONE recovery re-run: completed partitions
        # were not recomputed
        assert len(calls) == 5

    def test_streaming_mode_recovers_lost_partition(
        self, fast_retries, monkeypatch
    ):
        from tensorframes_tpu.utils import get_config, set_config

        jitted, calls = self._flaky_backend(fail_call_idx=1)
        monkeypatch.setattr(engine_ops, "_jitted", jitted)
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=64)  # force host-streaming drains
        try:
            df = TensorFrame.from_columns(
                {"x": np.arange(12.0)}, num_partitions=4
            )
            out = tft.map_blocks(lambda x: {"z": x + 5.0}, df).collect()
            assert [r.z for r in out] == [float(i + 5) for i in range(12)]
            assert len(calls) == 5
        finally:
            set_config(device_cache_bytes=old)

    def test_deterministic_failure_still_raises(
        self, fast_retries, monkeypatch
    ):
        # every run of partition 2 is poisoned: recovery must re-raise, not
        # loop
        real = engine_ops._jitted
        calls = []

        def jitted(g):
            fn = real(g)

            def wrapper(feed):
                idx = len(calls)
                calls.append(idx)
                res = fn(feed)
                if float(np.asarray(next(iter(res.values())))[0]) == 40.0:
                    return {k: _PoisonedResult(v) for k, v in res.items()}
                return res

            return wrapper

        monkeypatch.setattr(engine_ops, "_jitted", jitted)
        df = TensorFrame.from_columns(
            {"x": np.arange(8.0)}, num_partitions=4
        )
        with pytest.raises(RuntimeError, match="injected mid-chain"):
            tft.map_blocks(lambda x: {"z": x * 10.0}, df).collect()

    def test_demote_to_streaming_recovers_lost_partition(
        self, fast_retries, monkeypatch
    ):
        # trim maps have no static output-size estimate, so they start
        # device-resident and DEMOTE to host streaming when accumulated
        # bytes cross the budget mid-run — the demotion's host pulls must
        # recover lost results too
        from tensorframes_tpu.utils import get_config, set_config

        jitted, calls = self._flaky_backend(fail_call_idx=0)
        monkeypatch.setattr(engine_ops, "_jitted", jitted)
        old = get_config().device_cache_bytes
        set_config(device_cache_bytes=20)  # crosses after two partitions
        try:
            df = TensorFrame.from_columns(
                {"x": np.arange(8.0)}, num_partitions=4
            )
            out = tft.map_blocks(
                lambda x: {"z": x * 2.0}, df, trim=True
            ).collect()
            assert [r.z for r in out] == [float(2 * i) for i in range(8)]
            assert len(calls) == 5  # 4 partitions + 1 recovery
        finally:
            set_config(device_cache_bytes=old)
