"""Speculative decoding (ISSUE 15): draft-model step programs, the
batched multi-token verify, and acceptance-tuned draft length.

The correctness bar, inherited from every serve feature: speculative
streams must be BYTE-IDENTICAL to solo non-speculative decode — greedy
AND seeded — because acceptance is exact-match against the target's own
sampled token (per-step keys folded at absolute positions). The matrix
here drives that through chunked prefill, prefix-cache hits,
preemption, defragment, restart, chaos at ``serve.verify``, and fleet
failover across replicas with DIFFERENT draft lengths. Program budget:
<= 5 compiled step programs with speculation on (draft + verify added,
plain decode retired), <= 3 off.
"""

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM, init_draft_transformer
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import Fleet, GenerationEngine, PagePool
from tensorframes_tpu.utils import chaos, get_config, set_config

pytestmark = [pytest.mark.serve, pytest.mark.spec]

VOCAB = 32


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=2, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=64)


@pytest.fixture(scope="module")
def draft(lm):
    # a real (mismatched) draft: half the layers, its own seed — wrong
    # often enough to exercise rejection + rollback on every run
    return init_draft_transformer(lm.params, seed=99, n_layers=1)


def _prompts(seed, lens):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist()
        for n in lens
    ]


def _counter_total(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


# ---------------------------------------------------------------------------
# the byte-identity matrix
# ---------------------------------------------------------------------------


class TestByteIdentity:
    def test_self_draft_and_cold_draft_match_solo(self, lm, draft):
        """Streams match solo decode bit-for-bit whether the draft is
        perfect (the target's own weights — acceptance 1.0) or cold (a
        fresh 1-layer model — heavy rejection), greedy and seeded."""
        prompts = _prompts(0, (5, 12, 23, 9))
        solo = GenerationEngine(lm, max_slots=4, page_size=8,
                                max_seq_len=64)
        base_g = solo.generate(prompts, 12)
        base_s = solo.generate(prompts, 12, temperature=0.8, seed=11,
                               top_p=0.9)
        for dp, label in ((lm.params, "self"), (draft, "cold")):
            eng = GenerationEngine(
                lm, max_slots=4, page_size=8, max_seq_len=64,
                draft_params=dp, draft_len=3,
            )
            got_g = eng.generate(prompts, 12)
            got_s = eng.generate(prompts, 12, temperature=0.8, seed=11,
                                 top_p=0.9)
            for a, b in zip(base_g, got_g):
                np.testing.assert_array_equal(a, b, err_msg=label)
            for a, b in zip(base_s, got_s):
                np.testing.assert_array_equal(a, b, err_msg=label)
            assert eng.num_step_programs <= 5
            spec = eng.health()["speculative"]
            assert spec["proposed"] > 0
            if label == "self":
                # a perfect draft accepts everything
                assert spec["acceptance_rate"] == 1.0
            else:
                assert spec["accepted"] < spec["proposed"]

    def test_every_k_matches_and_matches_the_models_oracle(self, lm,
                                                           draft):
        prompt = _prompts(3, (14,))[0]
        oracle = lm.generate(np.asarray([prompt], np.int32), 10)[0, 14:]
        for k in (1, 2, 4, 8):
            eng = GenerationEngine(
                lm, max_slots=2, page_size=8, max_seq_len=64,
                draft_params=draft, draft_len=k,
            )
            np.testing.assert_array_equal(
                eng.generate([prompt], 10)[0], oracle
            )

    def test_chunked_prefill_and_prefix_cache_combo(self, lm, draft):
        """Speculation composes with chunked prefill + shared-prefix
        hits (the draft KV rides the shared pages): second pass hits
        the cache, both passes byte-identical to solo."""
        kw = dict(
            max_slots=4, page_size=8, max_seq_len=64,
            prefill_chunk_tokens=8, prefix_cache=True,
        )
        prompts = _prompts(5, (21, 17))
        solo = GenerationEngine(lm, **kw)
        base = solo.generate(prompts, 10, temperature=0.6, seed=7)
        eng = GenerationEngine(lm, draft_params=draft, draft_len=3, **kw)
        first = eng.generate(prompts, 10, temperature=0.6, seed=7)
        cached = eng.generate(prompts, 10, temperature=0.6, seed=7)
        assert eng.prefix_cache.stats()["hits"] > 0
        for a, b in zip(base, first):
            np.testing.assert_array_equal(a, b)
        for a, b in zip(base, cached):
            np.testing.assert_array_equal(a, b)
        assert eng.num_step_programs <= 5

    def test_preempt_defrag_restart_stay_identical(self, lm, draft):
        """A pool tight enough to force preemption, an explicit
        defragment, and a restart — speculative streams still match
        solo (speculative lookahead degrades k, never evicts live
        work)."""
        prompts = _prompts(9, (16, 16, 16, 16))
        solo = GenerationEngine(lm, max_slots=4, page_size=8,
                                max_seq_len=64)
        base = solo.generate(prompts, 16)
        before = _counter_total("failures.preemptions_total", op="serve")
        eng = GenerationEngine(
            lm, max_slots=4, page_size=8, num_pages=12, max_seq_len=64,
            draft_params=draft, draft_len=2,
        )
        out = eng.generate(prompts, 16)
        assert (
            _counter_total("failures.preemptions_total", op="serve")
            > before
        ), "workload was meant to exhaust the pool"
        for a, b in zip(base, out):
            np.testing.assert_array_equal(a, b)
        eng.defragment()
        for a, b in zip(base, eng.generate(prompts, 16)):
            np.testing.assert_array_equal(a, b)
        eng.restart()
        for a, b in zip(base, eng.generate(prompts, 16)):
            np.testing.assert_array_equal(a, b)

    def test_eos_mid_burst_truncates_identically(self, lm):
        """An EOS accepted mid-burst finishes the stream at the same
        byte solo would — nothing past the EOS is emitted."""
        prompt = _prompts(13, (9,))[0]
        solo = GenerationEngine(lm, max_slots=2, page_size=8,
                                max_seq_len=64)
        ref = solo.generate([prompt], 12)[0]
        eos = int(ref[3])  # force an early stop on a token we know lands
        base = solo.generate([prompt], 12, eos_id=eos)
        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64,
            draft_params=lm.params, draft_len=4,
        )
        got = eng.generate([prompt], 12, eos_id=eos)
        np.testing.assert_array_equal(base[0], got[0])
        assert len(got[0]) <= 4


# ---------------------------------------------------------------------------
# mechanism: multi-token steps, adaptive k, timings, page group
# ---------------------------------------------------------------------------


class TestMechanism:
    def test_self_draft_advances_multiple_tokens_per_step(self, lm):
        """With a perfect draft, each engine step emits up to k+1
        tokens: far fewer steps than tokens."""
        prompt = _prompts(1, (6,))[0]
        eng = GenerationEngine(
            lm, max_slots=1, page_size=8, max_seq_len=64,
            draft_params=lm.params, draft_len=4,
        )
        h = eng.submit(prompt, 20)
        steps = 0
        while eng.step():
            steps += 1
        toks = h.result(timeout=60)
        assert len(toks) == 20
        # prefill step + ceil(19 / 5) verify steps ~= 5; decode would
        # need 20
        assert steps <= 8
        spec = eng.health()["speculative"]
        assert spec["acceptance_rate"] == 1.0
        t = h.timings
        assert t["draft_s"] > 0 and t["verify_s"] > 0
        assert t["spec_accepted"] == t["spec_proposed"] > 0
        assert t["spec_rolled_back"] == 0

    def test_adaptive_k_shrinks_on_cold_slots(self, lm, draft):
        """A cold draft's per-slot k walks down toward the floor (1);
        rolled-back proposals land in the timings breakdown."""
        prompt = _prompts(2, (8,))[0]
        eng = GenerationEngine(
            lm, max_slots=1, page_size=8, max_seq_len=64,
            draft_params=draft, draft_len=6,
        )
        h = eng.submit(prompt, 24)
        seen_k = []
        while eng.step():
            act = eng.scheduler.slots[0]
            if act is not None and act.spec_k >= 0:
                seen_k.append(act.spec_k)
        h.result(timeout=60)
        assert seen_k and min(seen_k) < 6, (
            f"cold draft never shrank k: {seen_k}"
        )
        assert h.timings.get("spec_rolled_back", 0) > 0
        assert h.timings.get("rollback_s", 0.0) >= 0.0

    def test_metrics_and_health_surface(self, lm, draft):
        before_p = _counter_total("serve.spec_proposed_total")
        before_a = _counter_total("serve.spec_accepted_total")
        eng = GenerationEngine(
            lm, max_slots=2, page_size=8, max_seq_len=64,
            draft_params=draft, draft_len=2,
        )
        eng.generate(_prompts(4, (7, 11)), 8)
        assert _counter_total("serve.spec_proposed_total") > before_p
        assert _counter_total("serve.spec_accepted_total") >= before_a
        hist = obs_metrics.registry().get("serve.verify_seconds")
        assert hist.series()["count"] > 0
        spec = eng.health()["speculative"]
        assert spec["draft_len"] == 2
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        plain = GenerationEngine(lm, max_slots=1, page_size=8,
                                 max_seq_len=64)
        assert plain.health()["speculative"] is None

    def test_page_group_defrag_and_reset(self):
        """kv_pages satellite: a group's rows move with the pool's
        defragment permutation and re-zero on reset."""
        import jax.numpy as jnp

        from tensorframes_tpu.serve import SequencePages

        pool = PagePool(
            n_layers=1, n_kv_heads=1, head_dim=2, num_pages=6,
            page_size=4,
        )
        g = pool.add_group("draft", n_layers=2, n_kv_heads=1, head_dim=3)
        assert g.k.shape == (2, 7, 4, 1, 3)
        with pytest.raises(ValueError, match="already exists"):
            pool.add_group("draft", 1, 1, 1)
        seq = SequencePages(pool)
        seq.ensure(12)  # pages 0..2
        other = SequencePages(pool)
        other.ensure(4)
        # color the group rows by page index, then free the first seq
        # so defragment must move the survivor's page
        g.k = g.k.at[:].set(
            jnp.arange(7, dtype=jnp.float32)[None, :, None, None, None]
            * jnp.ones_like(g.k)
        )
        held = other.pages[0]
        seq.release()
        remap = pool.defragment([other])
        assert other.pages[0] == remap[held]
        # the group row followed its page: contents still the ORIGINAL
        # page's color
        np.testing.assert_allclose(
            np.asarray(g.k[:, other.pages[0]]), float(held)
        )
        pool.reset()
        np.testing.assert_allclose(np.asarray(g.k), 0.0)

    def test_draft_model_validation(self, lm):
        wrong_vocab = TransformerLM.init(0, VOCAB + 1, d_model=16,
                                         n_heads=4, max_len=64)
        with pytest.raises(ValueError, match="vocab"):
            GenerationEngine(lm, max_seq_len=64,
                             draft_params=wrong_vocab)
        short_pos = TransformerLM.init(0, VOCAB, d_model=16, n_heads=4,
                                       max_len=16)
        with pytest.raises(ValueError, match="positional"):
            GenerationEngine(lm, max_seq_len=64, draft_params=short_pos)
        with pytest.raises(ValueError, match="draft_len"):
            GenerationEngine(lm, max_seq_len=64, draft_params=lm.params,
                             draft_len=0)


# ---------------------------------------------------------------------------
# chaos at serve.verify + fleet failover across different k
# ---------------------------------------------------------------------------


class TestFaults:
    def test_transient_verify_chaos_retries_invisibly(self, lm, draft,
                                                      fast_retries):
        solo = GenerationEngine(lm, max_slots=2, page_size=8,
                                max_seq_len=64)
        prompts = _prompts(6, (9, 13))
        base = solo.generate(prompts, 10, temperature=0.5, seed=3)
        before = _counter_total(
            "chaos.injections_total", site="serve.verify",
            kind="transient",
        )
        with chaos.scoped("seed=7;serve.verify=transient:every=3"):
            eng = GenerationEngine(
                lm, max_slots=2, page_size=8, max_seq_len=64,
                draft_params=draft, draft_len=2,
            )
            got = eng.generate(prompts, 10, temperature=0.5, seed=3)
        assert (
            _counter_total(
                "chaos.injections_total", site="serve.verify",
                kind="transient",
            )
            > before
        ), "the schedule never fired"
        for a, b in zip(base, got):
            np.testing.assert_array_equal(a, b)

    def test_failover_across_different_k_mid_stream(self, lm, draft):
        """A speculative replica dies mid-stream; the survivor replays
        onto a replica with a DIFFERENT k (and a different draft) and
        the client stream stays byte-identical to solo."""
        import time

        prompt = _prompts(13, (9,))[0]
        solo = GenerationEngine(lm, max_slots=4, page_size=8,
                                max_seq_len=64)
        base = solo.generate([prompt], 24, temperature=0.6, seed=5)[0]
        fleet = Fleet(
            lm, replicas=2, max_slots=4, page_size=8, max_seq_len=64,
            watchdog_interval_s=0.01,
            replica_kwargs=[
                {"draft_params": lm.params, "draft_len": 4},
                {"draft_params": draft, "draft_len": 2},
            ],
        )
        with fleet:
            h = fleet.submit(prompt, 24, temperature=0.6, seed=5,
                             session="s")
            got = []
            it = iter(h)
            for _ in range(4):
                got.append(next(it))
            fleet._kill_replica(
                fleet._replica("r0"), RuntimeError("chaos kill")
            )
            deadline = time.monotonic() + 60
            for tok in it:
                got.append(tok)
                assert time.monotonic() < deadline
            assert all(
                n <= 5 for n in fleet.program_counts().values()
            )
        np.testing.assert_array_equal(np.asarray(got, np.int32), base)


# ---------------------------------------------------------------------------
# tuned draft length
# ---------------------------------------------------------------------------


class TestTunedDraftLen:
    def test_engine_picks_up_stored_draft_len(self, lm, tmp_path,
                                              monkeypatch):
        from tensorframes_tpu import tune
        from tensorframes_tpu.utils import get_config, set_config

        monkeypatch.setenv("TFT_TUNE_FILE", str(tmp_path / "t.jsonl"))
        monkeypatch.delenv("TFT_TUNE", raising=False)
        prev = (get_config().autotune, get_config().tune_mode)
        tune.reset()
        try:
            set_config(autotune=True, tune_mode="cached")
            sig = tune.serve_signature(np.float32, 4, 64)
            tune.pin("serve.draft_len", sig, {"k": 2})
            eng = GenerationEngine(
                lm, max_seq_len=64, page_size=8,
                draft_params=lm.params,
            )
            assert eng.draft_len == 2
            # an explicit argument always wins
            eng2 = GenerationEngine(
                lm, max_seq_len=64, page_size=8,
                draft_params=lm.params, draft_len=5,
            )
            assert eng2.draft_len == 5
        finally:
            set_config(autotune=prev[0], tune_mode=prev[1])
            tune.reset()
