"""Data-plane tests: native packer vs numpy fallback equivalence (analog of
the reference's conversion perf/correctness suites,
`perf/ConvertPerformanceSuite.scala`, `DebugRowOpsSuite.scala`)."""

import numpy as np
import pytest

from tensorframes_tpu.data import (
    RaggedBuffer,
    gather_ragged_pad,
    gather_rows,
    native_available,
    pad_ragged,
    scatter_rows,
    unpad_ragged,
)
from tensorframes_tpu.data import packer as packer_mod


def _np_pad(flat, offsets, max_len, pad_value):
    n = len(offsets) - 1
    out = np.full((n, max_len), pad_value, dtype=flat.dtype)
    for i in range(n):
        row = flat[offsets[i] : offsets[i + 1]]
        out[i, : len(row)] = row
    return out


@pytest.fixture(params=["float64", "float32", "int32", "int64", "uint8"])
def dtype(request):
    return np.dtype(request.param)


def make_ragged(rng, dtype, n=50, max_len=17):
    lens = rng.integers(0, max_len + 1, n)
    offsets = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(lens, out=offsets[1:])
    flat = (rng.normal(size=offsets[-1]) * 10).astype(dtype)
    return flat, offsets


def test_native_builds():
    # the toolchain is present in this image; the native path must be live
    assert native_available()


def test_pad_matches_fallback(rng, dtype):
    flat, offsets = make_ragged(rng, dtype)
    got = pad_ragged(flat, offsets, pad_value=3)
    want = _np_pad(flat, offsets, int(np.diff(offsets).max()), 3)
    np.testing.assert_array_equal(got, want)


def test_pad_explicit_maxlen(rng):
    flat, offsets = make_ragged(rng, np.dtype("float32"))
    got = pad_ragged(flat, offsets, max_len=40, pad_value=-1)
    assert got.shape[1] == 40
    with pytest.raises(ValueError, match="max_len"):
        pad_ragged(flat, offsets, max_len=1)


def test_unpad_roundtrip(rng, dtype):
    flat, offsets = make_ragged(rng, dtype)
    padded = pad_ragged(flat, offsets)
    back = unpad_ragged(padded, np.diff(offsets))
    np.testing.assert_array_equal(back, flat)


def test_gather_rows(rng, dtype):
    src = (rng.normal(size=(30, 4)) * 10).astype(dtype)
    idx = rng.permutation(30)[:12]
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_gather_rows_3d(rng):
    src = rng.normal(size=(10, 3, 2))
    idx = np.array([4, 1, 9])
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])


def test_scatter_rows_inverts_gather(rng):
    src = rng.normal(size=(20, 5)).astype(np.float32)
    perm = rng.permutation(20)
    gathered = gather_rows(src, perm)
    restored = scatter_rows(gathered, perm, 20)
    np.testing.assert_array_equal(restored, src)


def test_gather_ragged_pad(rng, dtype):
    flat, offsets = make_ragged(rng, dtype)
    idx = np.array([3, 0, 7, 7], dtype=np.int64)
    lens = np.diff(offsets)
    ml = int(lens[idx].max())
    got = gather_ragged_pad(flat, offsets, idx, ml, pad_value=0)
    want = _np_pad(flat, offsets, int(lens.max()), 0)[idx][:, :ml]
    np.testing.assert_array_equal(got, want)


class TestRaggedBuffer:
    def test_from_cells_roundtrip(self, rng):
        cells = [rng.normal(size=rng.integers(0, 6)) for _ in range(20)]
        rb = RaggedBuffer.from_cells(cells)
        assert rb.num_rows == 20
        for i, c in enumerate(cells):
            np.testing.assert_array_equal(rb.cell(i), c)

    def test_pad_and_back(self, rng):
        cells = [rng.normal(size=k) for k in (3, 1, 4, 1)]
        rb = RaggedBuffer.from_cells(cells)
        padded = rb.pad()
        assert padded.shape == (4, 4)
        rb2 = RaggedBuffer.from_padded(padded, rb.lengths)
        np.testing.assert_array_equal(rb2.flat, rb.flat)

    def test_gather_pad_equal_bucket(self, rng):
        cells = [rng.normal(size=3) for _ in range(5)] + [rng.normal(size=7)]
        rb = RaggedBuffer.from_cells(cells)
        idx = np.array([0, 2, 4])
        got = rb.gather_pad(idx)
        assert got.shape == (3, 3)
        np.testing.assert_array_equal(got[1], cells[2])

    def test_invalid_offsets(self):
        with pytest.raises(ValueError):
            RaggedBuffer(np.arange(3.0), np.array([1, 3], dtype=np.int64))


class TestBoundsChecks:
    """The native path must never memcpy out of bounds (these inputs
    previously corrupted the heap)."""

    def test_gather_pad_maxlen_too_small(self):
        rb = RaggedBuffer.from_cells([np.arange(8.0), np.arange(2.0)])
        with pytest.raises(ValueError, match="max_len"):
            rb.gather_pad(np.array([1, 0]), max_len=3)

    def test_unpad_lengths_too_large(self, rng):
        padded = rng.normal(size=(1, 2))
        with pytest.raises(ValueError, match="lengths"):
            unpad_ragged(np.ascontiguousarray(padded), np.array([5]))

    def test_unpad_negative_length(self, rng):
        padded = np.ascontiguousarray(rng.normal(size=(2, 3)))
        with pytest.raises(ValueError, match="lengths"):
            unpad_ragged(padded, np.array([1, -1]))

    def test_gather_rows_oob(self, rng):
        src = rng.normal(size=(4, 2))
        with pytest.raises(IndexError):
            gather_rows(src, np.array([0, 7]))
        with pytest.raises(IndexError):
            gather_rows(src, np.array([-1]))

    def test_scatter_rows_oob(self, rng):
        src = rng.normal(size=(2, 2))
        with pytest.raises(IndexError):
            scatter_rows(src, np.array([0, 9]), 4)

    def test_gather_ragged_oob_index(self, rng):
        flat, offsets = make_ragged(rng, np.dtype("float64"), n=5)
        with pytest.raises(IndexError):
            gather_ragged_pad(flat, offsets, np.array([9]), 4)


def test_frame_copies_on_ingest():
    """Mutating the caller's array after frame construction must not change
    engine results (columns own their storage)."""
    import tensorframes_tpu as tft

    x = np.arange(4.0)
    df = tft.TensorFrame.from_columns({"x": x})
    first = [r.z for r in tft.map_blocks(lambda x: {"z": x * 1.0}, df).collect()]
    x[:] = 100.0
    second = [r.z for r in tft.map_blocks(lambda x: {"z": x * 1.0}, df).collect()]
    assert first == second == [0.0, 1.0, 2.0, 3.0]


def test_fallback_matches_native(rng, monkeypatch):
    """Force the numpy fallback and check it agrees with the native path."""
    flat, offsets = make_ragged(rng, np.dtype("float64"))
    native = pad_ragged(flat, offsets, pad_value=9)
    monkeypatch.setattr(packer_mod, "_load", lambda: None)
    fallback = pad_ragged(flat, offsets, pad_value=9)
    np.testing.assert_array_equal(native, fallback)


class TestOffsetsValidation:
    """Offsets feed memcpy lengths in the native path (`native/packer.cpp`);
    malformed arrays must be rejected before the pointer crosses the ABI."""

    def test_offsets_must_start_at_zero(self):
        with pytest.raises(ValueError, match="start at 0"):
            pad_ragged(np.arange(4.0), np.array([1, 2, 4], dtype=np.int64))

    def test_offsets_must_be_non_decreasing(self):
        with pytest.raises(ValueError, match="non-decreasing"):
            pad_ragged(np.arange(4.0), np.array([0, 3, 1], dtype=np.int64))

    def test_offsets_must_stay_in_bounds(self):
        with pytest.raises(ValueError, match="beyond flat length"):
            pad_ragged(np.arange(4.0), np.array([0, 2, 9], dtype=np.int64))

    def test_offsets_must_be_contiguous(self):
        off = np.array([0, 7, 1, 9, 2, 11], dtype=np.int64)[::2]
        with pytest.raises(ValueError, match="contiguous"):
            pad_ragged(np.arange(4.0), off)

    def test_gather_checks_too(self):
        with pytest.raises(ValueError, match="non-decreasing"):
            gather_ragged_pad(
                np.arange(4.0),
                np.array([0, 3, 2], dtype=np.int64),
                np.array([0]),
                4,
            )


class TestNativeExecutor:
    """Thread-pool variants of the packer kernels (native/executor.cpp).
    Row ranges have disjoint outputs, so pooled results must be
    bit-identical to the serial kernels at any thread count."""

    def test_pooled_matches_serial(self):
        from tensorframes_tpu.data import packer as P

        if not P.native_available():
            pytest.skip("no native toolchain")
        rng = np.random.default_rng(0)
        old_thresh = P._PAR_THRESHOLD_BYTES
        P._PAR_THRESHOLD_BYTES = 1  # force the pooled path
        P.set_native_threads(4)
        try:
            assert P.native_threads() == 4
            src = rng.normal(size=(500, 8)).astype(np.float32)
            idx = rng.permutation(500).astype(np.int64)
            np.testing.assert_array_equal(P.gather_rows(src, idx), src[idx])
            back = P.scatter_rows(src[idx], idx, 500)
            np.testing.assert_array_equal(back, src)

            lens = rng.integers(0, 9, size=300)
            offsets = np.concatenate([[0], np.cumsum(lens)]).astype(np.int64)
            flat = rng.normal(size=int(offsets[-1])).astype(np.float64)
            padded = P.pad_ragged(flat, offsets, pad_value=-1.0)
            for i in range(300):
                row = flat[offsets[i]:offsets[i + 1]]
                np.testing.assert_array_equal(padded[i, :len(row)], row)
                assert (padded[i, len(row):] == -1.0).all()
            sel = rng.integers(0, 300, size=64).astype(np.int64)
            g = P.gather_ragged_pad(flat, offsets, sel, int(lens.max()))
            for k, i in enumerate(sel):
                row = flat[offsets[i]:offsets[i + 1]]
                np.testing.assert_array_equal(g[k, :len(row)], row)
        finally:
            P._PAR_THRESHOLD_BYTES = old_thresh
            P.set_native_threads(0)

    def test_set_threads_roundtrip(self):
        from tensorframes_tpu.data import packer as P

        if not P.native_available():
            pytest.skip("no native toolchain")
        P.set_native_threads(2)
        assert P.native_threads() == 2
        P.set_native_threads(0)
        assert P.native_threads() >= 1


class TestCodeKeys:
    """The native group-key coder (both paths: list-direct via the
    CPython API, and the buffer path on the packer pool) must agree with
    pandas.factorize's first-appearance contract exactly — the aggregate
    path's group ordering depends on it."""

    def test_first_appearance_parity_with_pandas(self):
        pd = pytest.importorskip("pandas")
        from tensorframes_tpu.data.packer import code_keys

        rng = np.random.default_rng(1)
        for n, g in [(1000, 7), (20_000, 997), (5_000, 5_000)]:
            keys = [b"key_%d" % rng.integers(0, g) for _ in range(n)]
            got = code_keys(keys)
            if got is None:  # no toolchain: fallback paths cover it
                pytest.skip("native coder unavailable")
            arr = np.empty(n, dtype=object)
            arr[:] = keys
            np.testing.assert_array_equal(got, pd.factorize(arr)[0])

    def test_edge_cases(self):
        from tensorframes_tpu.data.packer import code_keys

        if code_keys([b"x"]) is None:
            pytest.skip("native coder unavailable")
        assert code_keys([]).shape == (0,)
        assert code_keys([b""]).tolist() == [0]
        assert code_keys([b"", b"a", b""]).tolist() == [0, 1, 0]
        # byte-likes that are not bytes take the buffer path
        got = code_keys([memoryview(b"xy"), b"xy", bytearray(b"z")])
        if got is not None:
            assert got.tolist() == [0, 0, 1]
        # non-bytes-like falls back to None (callers use pandas)
        assert code_keys([b"a", 3]) is None

    def test_aggregate_string_keys_with_narrow_codes(self):
        """End to end through aggregate: group count under 256 exercises
        the uint8 upload narrowing; results must match a host oracle."""
        import tensorframes_tpu as tft

        rng = np.random.default_rng(2)
        n, g = 5000, 100
        gid = rng.integers(0, g, size=n)
        keys = [b"grp_%03d" % i for i in gid]
        vals = rng.normal(size=n).astype(np.float32)
        df = tft.TensorFrame.from_columns({"k": keys, "x": vals}).analyze()
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        got = {r.k: float(r.x) for r in out.collect()}
        oracle = {}
        for kk, v in zip(keys, vals):
            oracle[kk] = oracle.get(kk, 0.0) + float(v)
        assert set(got) == set(oracle)
        for kk in oracle:
            np.testing.assert_allclose(got[kk], oracle[kk], rtol=1e-4)
