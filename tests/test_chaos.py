"""Chaos harness: deterministic fault injection + the serving soak under
fault.

Everything here is CPU-only, seed-deterministic, and fast — the suite is
tier-1 (`make test-chaos` selects just it). The correctness bar for the
serving soak is unchanged from `test_serve.py`: every stream
byte-identical to its solo decode, ≤ 2 compiled step programs — now with
transient step failures, page-pool exhaustion, and a mid-run engine
crash + restart() injected underneath it.
"""

import time

import numpy as np
import pytest

from tensorframes_tpu.models import TransformerLM
from tensorframes_tpu.obs import metrics as obs_metrics
from tensorframes_tpu.serve import GenerationEngine
from tensorframes_tpu.utils import chaos, get_config, set_config
from tensorframes_tpu.utils.chaos import ChaosFault
from tensorframes_tpu.utils.failures import (
    DeviceOOMError,
    PagePoolExhausted,
    is_oom,
    is_transient,
)

pytestmark = pytest.mark.chaos

VOCAB = 32


@pytest.fixture(scope="module")
def lm():
    return TransformerLM.init(0, VOCAB, d_model=16, n_heads=4, max_len=48)


@pytest.fixture
def fast_retries():
    old = (get_config().max_retries, get_config().retry_backoff_s)
    set_config(max_retries=3, retry_backoff_s=0.001)
    yield
    set_config(max_retries=old[0], retry_backoff_s=old[1])


def _counter_value(name, **labels):
    try:
        return obs_metrics.registry().get(name).value(**labels)
    except KeyError:
        return 0.0


def _prompts(rng, lens):
    return [
        rng.integers(1, VOCAB, size=n).astype(np.int32).tolist() for n in lens
    ]


def _solo(lm, prompt, n, **kw):
    return lm.generate(np.asarray([prompt], np.int32), n, **kw)[
        0, len(prompt):
    ]


# ---------------------------------------------------------------------------


class TestHarness:
    def test_disabled_is_a_noop(self):
        assert not chaos.enabled()
        chaos.site("serve.decode_step")  # any name, nothing happens
        chaos.site("no.such.site")

    def test_unknown_site_in_spec_never_fires_elsewhere(self):
        with chaos.scoped("other.site=fatal"):
            chaos.site("serve.decode_step")  # different site: no fire

    def test_every_nth_schedule(self):
        with chaos.scoped("s=transient:every=3"):
            fired = []
            for i in range(9):
                try:
                    chaos.site("s")
                    fired.append(False)
                except RuntimeError:
                    fired.append(True)
        assert fired == [False, False, True] * 3

    def test_times_caps_injections(self):
        with chaos.scoped("s=transient:times=2"):
            raised = 0
            for _ in range(10):
                try:
                    chaos.site("s")
                except RuntimeError:
                    raised += 1
        assert raised == 2

    def test_probability_schedule_is_seed_deterministic(self):
        def pattern():
            out = []
            with chaos.scoped("seed=9;s=transient:p=0.3"):
                for _ in range(50):
                    try:
                        chaos.site("s")
                        out.append(0)
                    except RuntimeError:
                        out.append(1)
            return out

        a, b = pattern(), pattern()
        assert a == b
        assert 0 < sum(a) < 50  # actually probabilistic, not all/nothing

    def test_kinds_match_the_failure_taxonomy(self):
        with chaos.scoped(
            "t=transient;o=oom;p=pool;f=fatal;l=latency:ms=30"
        ):
            with pytest.raises(RuntimeError) as ei:
                chaos.site("t")
            assert is_transient(ei.value) and not is_oom(ei.value)
            with pytest.raises(DeviceOOMError) as ei:
                chaos.site("o")
            assert is_oom(ei.value)
            with pytest.raises(PagePoolExhausted):
                chaos.site("p")
            with pytest.raises(ChaosFault) as ei:
                chaos.site("f")
            # the fatal kind must dodge BOTH classifiers — it exists to
            # exercise the fail-fast path
            assert not is_transient(ei.value) and not is_oom(ei.value)
            t0 = time.monotonic()
            chaos.site("l")  # latency injects, never raises
            assert time.monotonic() - t0 >= 0.03

    def test_injections_are_counted_by_site_and_kind(self):
        before = _counter_value(
            "chaos.injections_total", site="counted", kind="transient"
        )
        with chaos.scoped("counted=transient:every=2"):
            for _ in range(6):
                try:
                    chaos.site("counted")
                except RuntimeError:
                    pass
        assert (
            _counter_value(
                "chaos.injections_total", site="counted", kind="transient"
            )
            == before + 3
        )

    def test_malformed_specs_fail_loudly(self):
        # a typo'd schedule silently doing nothing would defeat the
        # harness; every malformed entry must raise at configure time
        for bad in (
            "s=notakind",
            "justaname",
            "s=transient:bogus=1",
            "s=transient:p",
        ):
            with pytest.raises(ValueError):
                set_config(chaos=bad)
            set_config(chaos="")

    def test_unrelated_set_config_keeps_schedule_state(self):
        with chaos.scoped("s=transient:every=2"):
            try:
                chaos.site("s")  # call 1 of 2
            except RuntimeError:
                pytest.fail("fired early")
            old = get_config().max_retries
            set_config(max_retries=old)  # unrelated touch mid-schedule
            with pytest.raises(RuntimeError):
                chaos.site("s")  # still call 2 -> fires

    def test_env_spec_drives_the_harness(self):
        import os
        import subprocess
        import sys

        code = (
            "from tensorframes_tpu.utils import chaos\n"
            "assert chaos.enabled(), chaos.active_spec()\n"
            "try:\n"
            "    chaos.site('x'); raise SystemExit('no injection')\n"
            "except RuntimeError as e:\n"
            "    assert 'UNAVAILABLE' in str(e)\n"
            "print('ENV_OK')\n"
        )
        env = dict(os.environ, TFT_CHAOS="x=transient", JAX_PLATFORMS="cpu")
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=env, capture_output=True, text=True, timeout=120,
        )
        assert "ENV_OK" in out.stdout, out.stderr


class TestSiteDrift:
    def test_every_package_call_site_is_declared_in_SITES(self):
        """Drift regression: a chaos site added to the package without a
        SITES entry would silently miss the configure-time unknown-site
        warning — a typo'd schedule for it would never fire and nobody
        would be told. Grep the package for literal
        ``chaos.site("...")`` call sites and assert both directions:
        every referenced name is declared, and every declared name has a
        call site (dead entries lie about coverage). Dynamically
        composed names (``"site." + suffix``, e.g. the fleet's
        per-replica kills) are out of grep scope by design — they ride
        a declared site's family."""
        import re
        from pathlib import Path

        import tensorframes_tpu

        root = Path(tensorframes_tpu.__file__).parent
        # every call form in the package: chaos.site("..."),
        # _chaos.site("..."), and the `site as _chaos_site` import alias
        pat = re.compile(
            r"""(?:_chaos\.site|chaos\.site|_chaos_site)"""
            r"""\(\s*["']([^"']+)["']\s*\)"""
        )
        referenced = {}
        sources = {}
        for path in sorted(root.rglob("*.py")):
            text = path.read_text()
            sources[path.name] = text
            for m in pat.finditer(text):
                referenced.setdefault(m.group(1), set()).add(path.name)
        assert referenced, "grep found no chaos.site call sites at all"
        unknown = {
            name: sorted(files)
            for name, files in referenced.items()
            if name not in chaos.SITES
        }
        assert not unknown, (
            f"chaos.site() call sites missing from chaos.SITES: {unknown} "
            f"— add them so a typo'd schedule warns at configure time"
        )
        # converse, softer (composed names like `"frame." + direction`
        # defeat the call-site grep): every declared site must at least
        # be MENTIONED in package source — a SITES entry nothing
        # references is a lie about coverage
        dead = [
            s
            for s in chaos.SITES
            if s not in referenced
            and not any(s in text for text in sources.values())
        ]
        assert not dead, (
            f"chaos.SITES entries never referenced in the package: {dead}"
        )

    def test_site_family_suffix_skips_unknown_site_warning(self, caplog):
        """``fleet.replica_fault.r1``-style names are a FAMILY site's
        runtime-composed children (``SITE_FAMILIES``): configuring one
        must not warn. A suffix on a NON-family site and a genuinely
        unknown name must both still warn — they are typos that would
        silently never fire."""
        import logging

        with caplog.at_level(logging.WARNING, logger="tensorframes_tpu.chaos"):
            with chaos.scoped("fleet.replica_fault.r9=fatal"):
                pass
        assert not any(
            "not one of the wired" in r.getMessage() for r in caplog.records
        )
        for typo in ("totally.bogus=fatal", "serve.decode_step.typo=fatal"):
            caplog.clear()
            with caplog.at_level(
                logging.WARNING, logger="tensorframes_tpu.chaos"
            ):
                with chaos.scoped(typo):
                    pass
            assert any(
                "not one of the wired" in r.getMessage()
                for r in caplog.records
            ), typo


class TestEngineDispatchSite:
    def test_batch_engine_retries_injected_transients(self, fast_retries):
        import tensorframes_tpu as tft
        from tensorframes_tpu.frame import TensorFrame

        before = _counter_value(
            "chaos.injections_total", site="engine.dispatch",
            kind="transient",
        )
        # times=1 — the first dispatch fails once (the device-resident
        # pass degrades to the synchronous chunked engine, whose retry
        # window runs the rows to completion)
        with chaos.scoped("engine.dispatch=transient:every=1:times=1"):
            df = TensorFrame.from_columns({"x": np.arange(8.0)})
            out = tft.map_rows(lambda x: {"y": x * 3.0}, df).collect()
        assert [r.y for r in out] == [3.0 * i for i in range(8)]
        assert (
            _counter_value(
                "chaos.injections_total", site="engine.dispatch",
                kind="transient",
            )
            > before
        )


class TestServingUnderChaos:
    def test_pool_exhaustion_injection_preempts_not_crashes(
        self, lm, fast_retries
    ):
        rng = np.random.default_rng(30)
        eng = GenerationEngine(lm, max_slots=3, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (5, 3, 6))
        before = _counter_value("failures.preemptions_total", op="serve")
        with chaos.scoped("seed=4;kv_pages.alloc=pool:every=6"):
            outs = eng.generate(prompts, max_new_tokens=8)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 8))
        assert _counter_value("failures.preemptions_total", op="serve") > before
        assert eng.pool.pages_in_use == 0
        assert eng.num_step_programs <= 2

    def test_chaos_soak_sixteen_requests_with_crash_and_restart(
        self, lm, fast_retries
    ):
        """The acceptance soak: the 16-request staggered run from
        test_serve.py, now under a seeded chaos schedule injecting
        transient step failures and page-pool exhaustion, plus one
        mid-run device-state crash + restart(). Every stream must stay
        byte-identical to its solo decode, every handle must finish
        inside its deadline, and recovery must add zero compiled
        programs."""
        rng = np.random.default_rng(8)
        eng = GenerationEngine(
            lm, max_slots=6, page_size=4, max_seq_len=40, num_pages=24
        )
        plens = [int(rng.integers(1, 13)) for _ in range(16)]
        nnews = [int(rng.integers(3, 15)) for _ in range(16)]
        prompts = _prompts(rng, plens)
        restarts_before = _counter_value("serve.engine_restarts_total")
        deadline = 120.0
        t0 = time.monotonic()
        handles = []
        with chaos.scoped(
            "seed=13;"
            "serve.decode_step=transient:p=0.15;"
            "serve.prefill=transient:p=0.05;"
            "kv_pages.alloc=pool:every=11"
        ):
            waves = [prompts[:5], prompts[5:9], prompts[9:13], prompts[13:]]
            k = 0
            for w, wave in enumerate(waves):
                for p in wave:
                    handles.append(eng.submit(p, nnews[k], deadline=deadline))
                    k += 1
                for _ in range(2):
                    eng.step()
                if w == 1:
                    # mid-run crash: device KV state is lost outright;
                    # restart() rebuilds it from host-side progress
                    eng.pool.k = eng.pool.k * 0.0 + 99.0
                    eng.pool.v = eng.pool.v * 0.0 - 99.0
                    eng.restart()
            eng.run_until_idle()
        wall = time.monotonic() - t0
        assert wall < deadline  # no handle outlived its deadline budget
        for p, n, h in zip(prompts, nnews, handles):
            assert h.done and h.error is None
            np.testing.assert_array_equal(
                h.result(timeout=1), _solo(lm, p, n),
                err_msg=f"stream diverged (plen={len(p)}, n={n})",
            )
        assert eng.num_step_programs <= 2, eng.program_signatures
        assert eng.pool.pages_in_use == 0
        assert eng.healthy
        assert (
            _counter_value("serve.engine_restarts_total")
            == restarts_before + 1
        )
        # the schedule really did bite: both fault kinds fired
        assert (
            _counter_value(
                "chaos.injections_total", site="serve.decode_step",
                kind="transient",
            )
            > 0
        )
        assert (
            _counter_value(
                "chaos.injections_total", site="kv_pages.alloc", kind="pool"
            )
            > 0
        )

    def test_disabled_chaos_adds_no_programs(self, lm):
        """The overhead half of the acceptance bar that is assertable in
        a unit test: with no schedule installed the sites are inert and
        the engine still compiles exactly two step programs (the bench
        half — decode_serve within noise — is measured by `make
        bench-serve`, which reports the active chaos spec)."""
        assert not chaos.enabled()
        rng = np.random.default_rng(31)
        eng = GenerationEngine(lm, max_slots=2, page_size=4, max_seq_len=32)
        prompts = _prompts(rng, (3, 4))
        outs = eng.generate(prompts, max_new_tokens=5)
        for p, o in zip(prompts, outs):
            np.testing.assert_array_equal(o, _solo(lm, p, 5))
        assert eng.num_step_programs <= 2
