"""Engine tests: the reference's BasicOperationsSuite + core_test.py
equivalents (`/root/reference/src/test/scala/org/tensorframes/BasicOperationsSuite.scala`,
`src/main/python/tensorframes/tests/core_test.py`), including both README
examples end-to-end (README.md:60-128)."""

import numpy as np
import pytest

import tensorframes_tpu as tft
from tensorframes_tpu.capture import functions as F


def scalar_df(n=10, dtype=np.float64, parts=1):
    return tft.TensorFrame.from_columns(
        {"x": np.arange(n, dtype=dtype)}, num_partitions=parts
    )


class TestReadmeExamples:
    def test_readme_add3(self):
        # README.md:60-90: add 3 to column x, z appears next to x
        df = tft.TensorFrame.from_rows([dict(x=float(x)) for x in range(10)])
        with tft.graph():
            x = tft.block(df, "x")
            z = (x + 3).named("z")
            df2 = tft.map_blocks(z, df)
        assert df2.is_lazy  # lazy until collected
        rows = df2.collect()
        assert rows[0] == {"z": 3.0, "x": 0.0}
        assert [r.z for r in rows] == [float(z + 3) for z in range(10)]
        assert [r.x for r in rows] == [float(x) for x in range(10)]

    def test_readme_vector_reduce(self):
        # README.md:93-128: analyze, select+alias, reduce_sum + reduce_min
        df = tft.TensorFrame.from_rows(
            [dict(y=[float(y), float(-y)]) for y in range(10)]
        )
        df2 = tft.analyze(df)
        assert "DoubleType[10,2]" in tft.explain(df2)
        df3 = df2.select("y", ("y", "z"))
        with tft.graph():
            y_input = tft.block(df3, "y", tft_name="y_input")
            z_input = tft.block(df3, "z", tft_name="z_input")
            y = F.reduce_sum(y_input, axis=[0], name="y")
            z = F.reduce_min(z_input, axis=[0], name="z")
            data_sum, data_min = tft.reduce_blocks([y, z], df3)
        np.testing.assert_allclose(data_sum, [45.0, -45.0])
        np.testing.assert_allclose(data_min, [0.0, -9.0])

    def test_readme_vector_reduce_multipartition(self):
        df = tft.TensorFrame.from_rows(
            [dict(y=[float(y), float(-y)]) for y in range(10)],
            num_partitions=3,
        )
        df2 = tft.analyze(df)
        with tft.graph():
            y_input = tft.block(df2, "y", tft_name="y_input")
            y = F.reduce_sum(y_input, axis=[0], name="y")
            out = tft.reduce_blocks(y, df2)
        np.testing.assert_allclose(out, [45.0, -45.0])


class TestMapBlocks:
    def test_identity(self):
        df = scalar_df()
        with tft.graph():
            x = tft.block(df, "x")
            out = tft.map_blocks(F.identity(x, name="z"), df).collect()
        assert [r.z for r in out] == [r.x for r in out]

    def test_multi_partition(self):
        df = scalar_df(10, parts=3)
        with tft.graph():
            x = tft.block(df, "x")
            df2 = tft.map_blocks((x * 2.0).named("z"), df)
        assert [r.z for r in df2.collect()] == [2.0 * i for i in range(10)]
        assert df2.num_partitions == 3

    def test_callable_frontend(self):
        df = scalar_df(5)
        df2 = tft.map_blocks(lambda x: {"z": x + 1.0, "w": x * x}, df)
        rows = df2.collect()
        assert rows[2].z == 3.0 and rows[2].w == 4.0

    def test_trim_changes_row_count(self):
        # reference TrimmingOperationsSuite.scala:25-39
        df = scalar_df(6)
        df2 = tft.map_blocks(
            lambda x: {"z": x[:2]}, df, trim=True
        )
        rows = df2.collect()
        assert len(rows) == 2
        assert list(rows[0].keys()) == ["z"]

    def test_nontrim_rowcount_change_rejected(self):
        df = scalar_df(6)
        df2 = tft.map_blocks(lambda x: {"z": x[:2]}, df)
        with pytest.raises(ValueError, match="row count"):
            df2.collect()

    def test_output_collision(self):
        df = scalar_df()
        with pytest.raises(tft.OutputCollisionError):
            tft.map_blocks(lambda x: {"x": x}, df)

    def test_missing_input(self):
        df = scalar_df()
        with pytest.raises(tft.InputNotFoundError, match="not provided"):
            tft.map_blocks(lambda nope: {"z": nope}, df)

    def test_no_implicit_casting(self):
        df = scalar_df(dtype=np.float32)
        with tft.graph():
            ph = tft.placeholder("float64", [-1], name="x")
            with pytest.raises(tft.InvalidTypeError, match="float64"):
                tft.map_blocks(tft.build_graph((ph + 1).named("z")), df)

    def test_shape_mismatch(self):
        df = tft.TensorFrame.from_columns({"y": [[1.0, 2.0], [3.0, 4.0]]}).analyze()
        with tft.graph():
            ph = tft.placeholder("float64", [-1, 3], name="y")
            with pytest.raises(tft.InvalidDimensionError, match="incompatible"):
                tft.map_blocks(tft.build_graph((ph + 1).named("z")), df)

    def test_vector_output(self):
        df = scalar_df(4)
        df2 = tft.map_blocks(lambda x: {"z": np.ones((1, 2)) * x[:, None]}, df)
        rows = df2.collect()
        assert rows[3].z.tolist() == [3.0, 3.0]

    def test_int_types(self):
        for dt, st in [(np.int32, "int32"), (np.int64, "int64")]:
            df = scalar_df(5, dtype=dt)
            df2 = tft.map_blocks(lambda x: {"z": x * 2}, df)
            assert df2.schema["z"].scalar_type.name == st
            assert [r.z for r in df2.collect()] == [0, 2, 4, 6, 8]

    def test_feed_dict(self):
        df = tft.TensorFrame.from_columns({"col": np.arange(4.0)})
        df2 = tft.map_blocks(
            lambda inp: {"z": inp + 1.0}, df, feed_dict={"inp": "col"}
        )
        assert [r.z for r in df2.collect()] == [1.0, 2.0, 3.0, 4.0]

    def test_constants_feed(self):
        # constants are row-independent parameters (e.g. centroids/weights)
        df = scalar_df(4)
        w = np.array([10.0, 100.0])
        df2 = tft.map_blocks(
            lambda x, w: {"z": x[:, None] * w[None, :]}, df, constants={"w": w}
        )
        rows = df2.collect()
        assert rows[2].z.tolist() == [20.0, 200.0]

    def test_constants_reuse_one_graph(self):
        # same fn object + same shapes -> one CapturedGraph across calls
        from tensorframes_tpu.engine.ops import _callable_graphs

        df = scalar_df(4)

        def fn(x, c):
            return {"z": x * c}

        tft.map_blocks(fn, df, constants={"c": np.array(2.0)}).cache()
        g1 = _callable_graphs[fn]
        tft.map_blocks(fn, df, constants={"c": np.array(5.0)}).cache()
        assert _callable_graphs[fn] is g1 and len(g1) == 1

    def test_lazy_chaining(self):
        df = scalar_df(4)
        df2 = tft.map_blocks(lambda x: {"z": x + 1.0}, df)
        df3 = tft.map_blocks(lambda z: {"w": z * 10.0}, df2)
        assert df3.is_lazy
        rows = df3.collect()
        assert rows[1].w == 20.0 and rows[1].z == 2.0 and rows[1].x == 1.0


class TestMapRows:
    def test_simple(self):
        df = scalar_df(5)
        with tft.graph():
            x = tft.row(df, "x")
            df2 = tft.map_rows((x * 2.0).named("z"), df)
        assert [r.z for r in df2.collect()] == [0.0, 2.0, 4.0, 6.0, 8.0]

    def test_ragged(self):
        df = tft.TensorFrame.from_columns(
            {"y": [[1.0], [2.0, 3.0], [4.0]]}
        ).analyze()
        df2 = tft.map_rows(lambda y: {"s": y.sum()}, df)
        assert [r.s for r in df2.collect()] == [1.0, 5.0, 4.0]

    def test_dense_byte_capped_chunking(self):
        # tiny rows raise the chunk above the row cap (one dispatch for
        # the whole column); a tiny byte cap pins it back at the row cap —
        # results identical either way
        from tensorframes_tpu.utils import get_config, set_config

        n = 50_000
        x = np.arange(n, dtype=np.float32)

        def fn(x):
            return {"y": x * 3.0 + 1.0}

        df = tft.TensorFrame.from_columns({"x": x}).analyze()
        got = tft.map_rows(fn, df).cache().column_data("y").host()
        np.testing.assert_allclose(got, x * 3.0 + 1.0)

        old = get_config().max_bytes_per_device_call
        set_config(max_bytes_per_device_call=1)
        try:
            df2 = tft.TensorFrame.from_columns({"x": x}).analyze()
            got2 = tft.map_rows(fn, df2).cache().column_data("y").host()
            np.testing.assert_allclose(got2, x * 3.0 + 1.0)
        finally:
            set_config(max_bytes_per_device_call=old)

    def test_ragged_vector_output(self):
        df = tft.TensorFrame.from_columns({"y": [[1.0], [2.0, 3.0]]}).analyze()
        df2 = tft.map_rows(lambda y: {"d": y * 2}, df)
        cells = [r.d for r in df2.collect()]
        assert cells[0].tolist() == [2.0]
        assert cells[1].tolist() == [4.0, 6.0]

    def test_feed_dict(self):
        # reference core_test.py:107-118
        df = scalar_df(3)
        df2 = tft.map_rows(
            lambda inp: {"z": inp + 1.0}, df, feed_dict={"inp": "x"}
        )
        assert [r.z for r in df2.collect()] == [1.0, 2.0, 3.0]

    def test_binary_host_path(self):
        df = tft.TensorFrame.from_columns({"b": [b"ab", b"abc", b""]})
        df2 = tft.map_rows(
            lambda b: {"length": np.int64(len(b))}, df
        )
        assert [r.length for r in df2.collect()] == [2, 3, 0]


class TestReduce:
    def test_reduce_blocks_scalar(self):
        df = scalar_df(10, parts=2)
        out = tft.reduce_blocks(lambda x_input: {"x": x_input.sum()}, df)
        assert float(out) == 45.0

    def test_reduce_blocks_missing_convention(self):
        df = scalar_df()
        with pytest.raises(tft.InvalidDimensionError, match="x_input"):
            tft.reduce_blocks(lambda x: {"x": x.sum()}, df)

    def test_reduce_rows(self):
        # reference: fetch x needs placeholders x_1, x_2
        df = scalar_df(10, parts=3)
        out = tft.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df)
        assert float(out) == 45.0

    def test_reduce_rows_vector(self):
        df = tft.TensorFrame.from_columns(
            {"y": [[float(i), 1.0] for i in range(5)]}, num_partitions=2
        ).analyze()
        out = tft.reduce_rows(lambda y_1, y_2: {"y": y_1 + y_2}, df)
        np.testing.assert_allclose(out, [10.0, 5.0])

    def test_reduce_rows_single_row(self):
        df = scalar_df(1)
        out = tft.reduce_rows(lambda x_1, x_2: {"x": x_1 + x_2}, df)
        assert float(out) == 0.0

    def test_reduce_empty_frame(self):
        df = scalar_df(3).filter_rows(np.array([False] * 3))
        with pytest.raises(ValueError, match="empty"):
            tft.reduce_blocks(lambda x_input: {"x": x_input.sum()}, df)

    def test_reduce_multiple_fetches_order(self):
        # each fetch needs its own <fetch>_input; duplicate the column via
        # select+alias as the README does (README.md:112-121)
        df = scalar_df(4).select("x", ("x", "m"))
        m, x = tft.reduce_blocks(
            lambda x_input, m_input: {"x": x_input.sum(), "m": m_input.max()},
            df,
        )
        # callable-frontend fetches come back in sorted-name order
        assert (float(m), float(x)) == (3.0, 6.0)


class TestAggregate:
    def test_sum_by_key(self):
        # reference core_test.py:213-222
        df = tft.TensorFrame.from_columns(
            {
                "key": np.array([1, 1, 2, 2, 2], dtype=np.int64),
                "x": np.array([1.0, 2.0, 10.0, 20.0, 30.0]),
            }
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)},
            df.group_by("key"),
        )
        rows = sorted(out.collect(), key=lambda r: r.key)
        assert [(r.key, r.x) for r in rows] == [(1, 3.0), (2, 60.0)]

    def test_min_by_key_unsorted_input(self):
        df = tft.TensorFrame.from_columns(
            {
                "k": np.array([3, 1, 3, 1, 2], dtype=np.int32),
                "v": np.array([5.0, 7.0, 2.0, 1.0, 9.0]),
            }
        )
        out = tft.aggregate(
            lambda v_input: {"v": v_input.min(axis=0)}, df.group_by("k")
        )
        rows = sorted(out.collect(), key=lambda r: r.k)
        assert [(r.k, r.v) for r in rows] == [(1, 1.0), (2, 9.0), (3, 2.0)]

    def test_vector_aggregate(self):
        df = tft.TensorFrame.from_columns(
            {
                "k": np.array([0, 0, 1], dtype=np.int64),
                "y": [[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]],
            }
        ).analyze()
        out = tft.aggregate(
            lambda y_input: {"y": y_input.sum(axis=0)}, df.group_by("k")
        )
        rows = sorted(out.collect(), key=lambda r: r.k)
        np.testing.assert_allclose(rows[0].y, [4.0, 6.0])
        np.testing.assert_allclose(rows[1].y, [5.0, 6.0])

    def test_single_group(self):
        df = tft.TensorFrame.from_columns(
            {"k": np.zeros(4, dtype=np.int64), "x": np.arange(4.0)}
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        ).collect()
        assert len(out) == 1 and out[0].x == 6.0

    def test_many_groups(self):
        n = 101
        df = tft.TensorFrame.from_columns(
            {
                "k": np.arange(n, dtype=np.int64) % 13,
                "x": np.ones(n),
            }
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        total = sum(r.x for r in out.collect())
        assert total == n

    def test_key_cannot_be_input(self):
        df = tft.TensorFrame.from_columns(
            {"k": np.arange(3, dtype=np.int64)}
        )
        with pytest.raises(ValueError, match="key and input"):
            tft.aggregate(
                lambda k_input: {"k": k_input.sum(axis=0)}, df.group_by("k")
            )


class TestGraphSerializationPath:
    def test_map_from_loaded_graph(self, tmp_path):
        # analog of loading a frozen GraphDef (PythonInterface.scala:115-118)
        df = scalar_df(4)
        with tft.graph():
            x = tft.block(df, "x")
            g = tft.build_graph((x * 3.0).named("z"))
        p = str(tmp_path / "g.bin")
        tft.save_graph(g, p)
        g2 = tft.load_graph(p)
        out = tft.map_blocks(g2, df).collect()
        assert [r.z for r in out] == [0.0, 3.0, 6.0, 9.0]


class TestAggregateGeneralKeys:
    """String/binary and multi-column group keys (reference aggregates under
    any Spark groupBy key incl. strings, ``DebugRowOps.scala:547-592``,
    ``core_test.py:213-222``)."""

    def test_binary_key(self):
        df = tft.TensorFrame.from_rows(
            [
                {"name": b"apple", "x": 1.0},
                {"name": b"pear", "x": 10.0},
                {"name": b"apple", "x": 2.0},
                {"name": b"pear", "x": 20.0},
                {"name": b"fig", "x": 5.0},
            ]
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("name")
        )
        got = sorted((r.name, r.x) for r in out.collect())
        assert got == [(b"apple", 3.0), (b"fig", 5.0), (b"pear", 30.0)]

    def test_mixed_multi_key(self):
        df = tft.TensorFrame.from_rows(
            [
                {"s": b"a", "k": 0, "x": 1.0},
                {"s": b"a", "k": 1, "x": 2.0},
                {"s": b"b", "k": 0, "x": 4.0},
                {"s": b"a", "k": 0, "x": 8.0},
            ]
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)},
            df.group_by("s", "k"),
        )
        got = sorted((r.s, r.k, r.x) for r in out.collect())
        assert got == [(b"a", 0, 9.0), (b"a", 1, 2.0), (b"b", 0, 4.0)]

    def test_numeric_multi_key(self):
        df = tft.TensorFrame.from_columns(
            {
                "a": np.array([1, 1, 2, 2, 1], dtype=np.int64),
                "b": np.array([0, 1, 0, 0, 0], dtype=np.int64),
                "x": np.array([1.0, 2.0, 4.0, 8.0, 16.0]),
            }
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("a", "b")
        )
        got = sorted((r.a, r.b, r.x) for r in out.collect())
        assert got == [(1, 0, 17.0), (1, 1, 2.0), (2, 0, 12.0)]

    def test_nan_float_key_rows_stay_separate_groups(self):
        # NaN != NaN: the old per-row dict coding and the pure-numeric
        # device path both give every NaN row its own group; the
        # vectorized mixed-key coding must match (np.unique alone would
        # collapse NaNs into one group)
        df = tft.TensorFrame.from_rows(
            [
                {"s": b"a", "f": np.nan, "x": 1.0},
                {"s": b"a", "f": np.nan, "x": 2.0},
                {"s": b"a", "f": 1.0, "x": 4.0},
                {"s": b"b", "f": 1.0, "x": 8.0},
            ]
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)},
            df.group_by("s", "f"),
        )
        got = sorted(r.x for r in out.collect())
        assert got == [1.0, 2.0, 4.0, 8.0]

    def test_trailing_nul_keys_stay_distinct(self):
        df = tft.TensorFrame.from_rows(
            [
                {"k": b"a", "x": 1.0},
                {"k": b"a\x00", "x": 2.0},
                {"k": b"a\x00\x00", "x": 4.0},
                {"k": b"a", "x": 8.0},
            ]
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        got = sorted(r.x for r in out.collect())
        assert got == [2.0, 4.0, 9.0]

    def test_outlier_long_key_uses_bounded_memory_path(self):
        # one huge key forces the O(total bytes) dict fallback instead of
        # an n x max_len fixed-width buffer; semantics are identical
        rows = [{"k": b"k%d" % (i % 3), "x": 1.0} for i in range(64)]
        rows.append({"k": b"z" * (1 << 21), "x": 100.0})
        df = tft.TensorFrame.from_rows(rows)
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        got = sorted(r.x for r in out.collect())
        assert got == [21.0, 21.0, 22.0, 100.0]

    def test_ragged_key_rejected(self):
        df = tft.TensorFrame.from_rows(
            [{"k": [1.0]}, {"k": [1.0, 2.0]}]
        ).analyze()
        df = df.with_column("x", np.ones(2))
        with pytest.raises(ValueError, match="ragged"):
            tft.aggregate(
                lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
            )


class TestAggregateChunked:
    """Large frames route through the fixed-depth chunked scan + recursive
    boundary merge; results must match the small-frame path exactly."""

    def test_chunked_matches_oracle(self):
        from tensorframes_tpu.engine.ops import _AGG_CHUNK

        n = _AGG_CHUNK * 2 + 137  # 3 chunks, ragged tail
        rng = np.random.default_rng(1)
        k = rng.integers(0, 53, n).astype(np.int32)
        x = rng.normal(size=n).astype(np.float32)
        df = tft.TensorFrame.from_columns({"k": k, "x": x})
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        got = {int(r.k): r.x for r in out.collect()}
        expect = np.zeros(53, np.float64)
        np.add.at(expect, k, x.astype(np.float64))
        assert len(got) == 53
        for kk, v in got.items():
            np.testing.assert_allclose(v, expect[kk], rtol=2e-4)

    def test_chunked_min_nonsum_merge(self):
        from tensorframes_tpu.engine.ops import _AGG_CHUNK

        n = _AGG_CHUNK + 11
        rng = np.random.default_rng(2)
        k = rng.integers(0, 7, n).astype(np.int64)
        x = rng.normal(size=n).astype(np.float32)
        df = tft.TensorFrame.from_columns({"k": k, "x": x})
        out = tft.aggregate(
            lambda x_input: {"x": x_input.min(axis=0)}, df.group_by("k")
        )
        got = {int(r.k): r.x for r in out.collect()}
        for kk in range(7):
            np.testing.assert_allclose(got[kk], x[k == kk].min())

    def test_unique_keys_exceeding_chunk_terminates(self):
        # regression: >_AGG_CHUNK distinct groups used to recurse forever
        # (the partial table can never shrink below the group count)
        from tensorframes_tpu.engine.ops import _AGG_CHUNK

        n = _AGG_CHUNK + 5
        df = tft.TensorFrame.from_columns(
            {
                "k": np.arange(n, dtype=np.int64),
                "x": np.ones(n, dtype=np.float32),
            }
        )
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)}, df.group_by("k")
        )
        assert out.num_rows == n
        assert float(np.asarray(out.column_data("x").host()).sum()) == n
